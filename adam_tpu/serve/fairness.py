"""Per-tenant weighted window interleaving (``adam_tpu/serve``).

The streamed pipeline calls its ``pacer`` hook once per window at the
pass-A and pass-C boundaries; when N jobs share one device pool, those
calls all land here and the interleaver decides whose window goes next.
The discipline is classic **virtual-time weighted fair queuing** over
*tenants* (not jobs): each tenant owns a virtual clock that advances by
``1 / weight`` per granted window, and the waiting tenant with the
smallest clock wins — so a tenant with weight 2 streams two windows for
every one a weight-1 tenant streams, whenever both are actually
waiting, and two jobs of one tenant share that tenant's allocation
instead of doubling it.

Work-conserving by construction: a tenant that is busy computing (not
blocked in :meth:`turn`) never stalls anyone, and its clock catches up
to the global virtual time when it returns, so an idle spell earns no
burst of back-to-back grants.  A solo job is granted immediately every
time — pacing a one-job pool costs one lock acquisition per window.

The interleaver is also the graceful-drain trigger: :meth:`cancel`
makes every blocked (and future) :meth:`turn` raise
:class:`~adam_tpu.pipelines.streamed.RunCancelled`, which the streamed
pipeline honors at the window boundary — in-flight parts publish, the
journal stays resumable (docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from adam_tpu.pipelines.streamed import RunCancelled
from adam_tpu.utils import faults

#: Recheck period for blocked turns: grants notify the condition, so
#: this only bounds recovery from a theoretical missed wakeup.
_WAIT_S = 0.1


class _Tenant:
    __slots__ = ("weight", "vt")

    def __init__(self, weight: float, vt: float):
        self.weight = weight
        self.vt = vt


class _Lane:
    __slots__ = ("job", "tenant", "cancelled", "waiting_seq")

    def __init__(self, job: str, tenant: str):
        self.job = job
        self.tenant = tenant
        self.cancelled = False
        self.waiting_seq: Optional[int] = None


class WeightedInterleaver:
    """Thread-safe tenant-weighted window interleaver (module doc)."""

    #: Grant-history ring depth (the fairness audit window; a
    #: service-lifetime list would grow one entry per window forever).
    HISTORY = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._tenants: dict[str, _Tenant] = {}
        self._lanes: dict[str, _Lane] = {}
        self._grants: deque = deque(maxlen=self.HISTORY)
        self._vtime = 0.0
        self._arrivals = 0
        self._cancel_all = False

    # ---- lane lifecycle (scheduler-side) -------------------------------
    def register(self, job: str, tenant: str = "default",
                 weight: float = 1.0) -> None:
        """Add a job lane under its tenant's clock.  The tenant's clock
        catches up to the global virtual time, so joining late earns no
        retroactive share."""
        with self._lock:
            t = self._tenants.get(tenant)
            if t is None:
                self._tenants[tenant] = _Tenant(
                    max(weight, 1e-9), self._vtime
                )
            else:
                t.weight = max(weight, 1e-9)
                t.vt = max(t.vt, self._vtime)
            self._lanes[job] = _Lane(job, tenant)
            self._cond.notify_all()

    def deregister(self, job: str) -> None:
        """Drop a job lane (idempotent); the tenant's clock survives so
        a follow-up job of the same tenant keeps its fair position."""
        with self._lock:
            lane = self._lanes.pop(job, None)
            if lane is not None and not any(
                ln.tenant == lane.tenant for ln in self._lanes.values()
            ):
                # last lane of the tenant: drop the clock — a future
                # re-register catches up to the global time anyway
                self._tenants.pop(lane.tenant, None)
            self._cond.notify_all()

    def cancel(self, job: Optional[str] = None) -> None:
        """Make ``turn`` raise ``RunCancelled`` for one job (or, with
        ``None``, for every job — the graceful-drain trigger).  Blocked
        turns wake immediately."""
        with self._lock:
            if job is None:
                self._cancel_all = True
            else:
                lane = self._lanes.get(job)
                if lane is not None:
                    lane.cancelled = True
            self._cond.notify_all()

    def cancelled(self, job: str) -> bool:
        """Whether ``turn`` would raise for this job right now (drain
        or per-job cancel) — the quota throttle's stop probe, so a
        deferral never outlives the drain that should interrupt it."""
        with self._lock:
            if self._cancel_all:
                return True
            lane = self._lanes.get(job)
            return lane is not None and lane.cancelled

    def grant_history(self) -> list:
        """Recent grants as job ids, oldest first (bounded ring)."""
        with self._lock:
            return [job for job, _, _ in self._grants]

    def grant_times(self, last: Optional[int] = None) -> list:
        """Monotonic timestamps of recent grants, oldest first (the
        newest ``last`` when given).  The gateway derives its
        ``Retry-After`` hint from the inter-grant cadence here: when
        windows are flowing at one grant every t seconds, "come back
        after a batch of windows has drained" is the honest estimate
        of when a slot could free (docs/SERVING.md back-pressure)."""
        with self._lock:
            times = [t for _, t, _ in self._grants]
        return times if last is None else times[-last:]

    def grant_records(self, last: Optional[int] = None) -> list:
        """Recent grants as ``(monotonic time, size)`` pairs, oldest
        first.  ``size`` is the granted window's byte payload (the
        streamed pipeline passes it through the pacer seam; 0 when the
        caller predates sizes) — the quota leg's Retry-After derives
        from bytes-per-grant here instead of grant cadence alone
        (serve/quota.rate_retry_hint)."""
        with self._lock:
            recs = [(t, s) for _, t, s in self._grants]
        return recs if last is None else recs[-last:]

    def tenant_clock(self, tenant: str) -> Optional[float]:
        """The tenant's WFQ virtual clock (None when unknown) — the
        cross-job coalescer orders the row blocks of a fused dispatch
        by it, so the most underserved tenant's windows lead the grid
        exactly as they would have led the solo grant order."""
        with self._lock:
            t = self._tenants.get(tenant)
            return t.vt if t is not None else None

    # ---- the pacing hot path -------------------------------------------
    def pacer(self, job: str):
        """The per-job ``pacer(phase, index, size)`` hook the scheduler
        hands to ``transform_streamed`` — one fault point + one turn
        per window boundary.  ``size`` is the window's byte payload
        (0 from callers that predate sizes); it lands in the grant
        ring so the quota leg can reason in bytes-per-grant."""

        def pace(phase: str, index: int, size: int = 0,
                 _job=job) -> None:
            faults.point("sched.dispatch", device=_job)
            self.turn(_job, size=size)

        return pace

    def _next_waiter_locked(self) -> Optional[_Lane]:
        """The lane to grant next: smallest (clock, tenant-name) among
        tenants with a waiter; FIFO within the tenant.  Caller holds
        the lock."""
        best_lane = None
        best_key = None
        for lane in self._lanes.values():
            if lane.waiting_seq is None:
                continue
            t = self._tenants[lane.tenant]
            key = (t.vt, lane.tenant, lane.waiting_seq)
            if best_key is None or key < best_key:
                best_key = key
                best_lane = lane
        return best_lane

    def turn(self, job: str, size: int = 0) -> None:
        """Block until this job's tenant is granted the next window.

        Unregistered jobs free-run (a pacer outliving its lane must not
        deadlock teardown).  Raises ``RunCancelled`` once the job — or
        the whole pool — is cancelled.  ``size`` (bytes this grant
        covers) is recorded in the grant ring beside the timestamp."""
        with self._lock:
            lane = self._lanes.get(job)
            if lane is None:
                return
            t = self._tenants[lane.tenant]
            # idle catch-up: a tenant that computed for a while resumes
            # at the current virtual time, never with a grant burst
            t.vt = max(t.vt, self._vtime)
            self._arrivals += 1
            lane.waiting_seq = self._arrivals
            try:
                while True:
                    if self._cancel_all or lane.cancelled:
                        raise RunCancelled(
                            f"job {job} cancelled by the scheduler "
                            "(drain or quarantine)"
                        )
                    if self._next_waiter_locked() is lane:
                        self._vtime = t.vt
                        t.vt += 1.0 / t.weight
                        self._grants.append(
                            (job, time.monotonic(), int(size))
                        )
                        self._cond.notify_all()
                        return
                    self._cond.wait(_WAIT_S)
            finally:
                lane.waiting_seq = None
