"""Per-tenant quota enforcement for the multi-job transform service
(docs/SERVING.md "Continuous batching & quotas").

The PR 7 device ledger already attributes every h2d/d2h byte and every
compile second; this module turns that accounting into an *admission*
contract: each tenant owns a rolling-window budget of device-link
bytes and compute seconds, consumption is charged from the fairness
interleaver's grant sizes (serve/fairness.py records bytes-per-grant)
and the cross-job coalescer's per-dispatch attribution
(serve/batching.py), and a submission from an over-budget tenant is
refused with a typed ``Busy(kind="quota")`` carrying a
**budget-derived** Retry-After — the gateway's 429 quota leg, distinct
from the capacity leg (which signals "slots full", not "you spent your
share").

Grammar (``--quota`` / ``ADAM_TPU_QUOTA``)::

    tenantA:bytes=512M,compute=10s;tenantB:bytes=2G;*:bytes=1G

``bytes`` is the rolling-window device-byte budget (h2d + d2h charged
to the tenant; suffixes K/M/G/T are binary), ``compute`` the
device-compute-seconds budget (optional ``s`` suffix).  ``*`` names
the default budget for tenants without their own clause; tenants with
neither clause are unlimited.  The window is
``ADAM_TPU_QUOTA_WINDOW_S`` (default 60 s): charges age out of the
budget exactly ``window_s`` after they were incurred, so a refused
tenant is admissible again once enough of its recent spend expires —
which is precisely what its Retry-After advertises.  Malformed clauses
warn and are ignored (the tuning-var contract every ``ADAM_TPU_*``
knob keeps): a quota typo must never take down admission for everyone.

Enforcement has two rungs.  **Admission** refuses fresh submissions
from an over-budget tenant (the 429 leg above).  **Mid-run
throttling** (:meth:`QuotaManager.throttle`, on by default with
``ADAM_TPU_QUOTA_THROTTLE``; ``ADAM_TPU_QUOTA_MAX_DEFER_S`` bounds a
single deferral) smooths the edge for long jobs: when a tenant goes
over budget mid-run, its next window grants DEFER at the pacer seam —
short bounded sleeps until enough spend ages out of the rolling
window — instead of streaming at full rate until the next admission
check.  Deferred grants count ``sched.quota.deferred``; a drain (or
job cancel) interrupts a deferral immediately, and a job is never
killed mid-flight for quota (killing a paid-for run wastes the spend
that triggered the kill).  Other tenants' throughput is untouched —
the WFQ interleaver still owns intra-run fairness.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Optional

from adam_tpu.utils import telemetry as tele

log = logging.getLogger(__name__)

#: Default rolling budget window (seconds) — ``ADAM_TPU_QUOTA_WINDOW_S``.
DEFAULT_WINDOW_S = 60.0

#: Retry-After bounds for the quota leg (seconds).  Wider than the
#: capacity leg's [1, 30]: a spent byte budget frees on the quota
#: window's schedule, not at job-slot turnover speed.
QUOTA_RETRY_MIN_S = 1
QUOTA_RETRY_MAX_S = 3600

#: Mid-run throttle poll step (seconds): short enough that a drain or
#: an expiring charge is honored promptly, long enough not to spin.
THROTTLE_POLL_S = 0.05


def throttle_enabled() -> bool:
    """``ADAM_TPU_QUOTA_THROTTLE`` (default on): whether over-budget
    tenants get pacer-level grant deferral mid-run."""
    from adam_tpu.utils.retry import env_toggle

    return env_toggle("ADAM_TPU_QUOTA_THROTTLE", True)


def max_defer_s() -> float:
    """``ADAM_TPU_QUOTA_MAX_DEFER_S``: the bound on ONE grant's
    deferral; 0/unset means "derive from the rolling window" (the
    window plus a poll's slack — by then every charge that was in the
    window when the deferral began has aged out, so a longer wait can
    never be needed)."""
    from adam_tpu.utils.retry import env_float

    v = env_float("ADAM_TPU_QUOTA_MAX_DEFER_S", 0.0)
    return v if v > 0 else 0.0

_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def parse_size(text: str) -> int:
    """``512M`` -> bytes (binary suffixes K/M/G/T, bare ints pass)."""
    t = text.strip().lower()
    mult = 1
    if t and t[-1] in _SUFFIX:
        mult = _SUFFIX[t[-1]]
        t = t[:-1]
    return int(float(t) * mult)


def quota_window_s() -> float:
    """The rolling budget window (``ADAM_TPU_QUOTA_WINDOW_S``; a
    malformed or nonpositive value warns and keeps the default —
    ``utils/retry.env_float``, the shared tuning-var parser)."""
    from adam_tpu.utils.retry import env_float

    v = env_float("ADAM_TPU_QUOTA_WINDOW_S", DEFAULT_WINDOW_S)
    if v <= 0:
        log.warning(
            "ADAM_TPU_QUOTA_WINDOW_S=%s is not positive; using default "
            "%.0fs", v, DEFAULT_WINDOW_S,
        )
        return DEFAULT_WINDOW_S
    return v


@dataclass(frozen=True)
class Budget:
    """One tenant's rolling-window budget (None = unlimited)."""

    bytes: Optional[int] = None
    compute_s: Optional[float] = None

    @property
    def limited(self) -> bool:
        return self.bytes is not None or self.compute_s is not None


@dataclass(frozen=True)
class QuotaExceeded:
    """Typed refusal: which budget the tenant exhausted, by how much,
    and when the rolling window frees enough spend to admit again."""

    tenant: str
    resource: str  # "bytes" | "compute_s"
    used: float
    budget: float
    retry_after_s: int

    @property
    def reason(self) -> str:
        if self.resource == "bytes":
            return (
                f"tenant {self.tenant!r} is over its device-byte quota "
                f"({int(self.used)} of {int(self.budget)} bytes in the "
                "rolling window); retry after the window frees budget"
            )
        return (
            f"tenant {self.tenant!r} is over its compute quota "
            f"({self.used:.3f} of {self.budget:.3f} s in the rolling "
            "window); retry after the window frees budget"
        )


def parse_quota_spec(spec: str) -> dict:
    """Grammar (module docstring) -> ``{tenant: Budget}``.  Malformed
    clauses warn and are skipped — never raise (tuning-var contract)."""
    budgets: dict = {}
    for clause in (spec or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        tenant, sep, body = clause.partition(":")
        tenant = tenant.strip()
        if not sep or not tenant or not body.strip():
            log.warning(
                "quota clause %r is not tenant:key=value[,...]; ignoring",
                clause,
            )
            continue
        nbytes = compute = None
        ok = True
        for item in body.split(","):
            key, s2, val = item.partition("=")
            key = key.strip().lower()
            val = val.strip().lower()
            try:
                if not s2:
                    raise ValueError("missing '='")
                if key == "bytes":
                    nbytes = parse_size(val)
                elif key in ("compute", "compute_s"):
                    compute = float(val[:-1] if val.endswith("s") else val)
                else:
                    raise ValueError(f"unknown key {key!r}")
            except ValueError as e:
                log.warning(
                    "quota clause %r: bad item %r (%s); ignoring the "
                    "whole clause", clause, item, e,
                )
                ok = False
                break
        if ok:
            budgets[tenant] = Budget(bytes=nbytes, compute_s=compute)
    return budgets


def rate_retry_hint(deficit_bytes: float, grant_records: list,
                    now: Optional[float] = None) -> Optional[int]:
    """Bytes-per-grant Retry-After estimate: given the fairness ring's
    recent ``(monotonic time, size)`` grant records, the tenant's byte
    deficit divided by the observed service byte rate is roughly how
    long the rolling window needs to drain that much spend.  ``None``
    when the ring carries no sized grants yet (cold service)."""
    recs = [(t, s) for t, s in (grant_records or []) if s > 0]
    if deficit_bytes <= 0 or len(recs) < 2:
        return None
    t0 = recs[0][0]
    t1 = recs[-1][0] if now is None else max(now, recs[-1][0])
    span = t1 - t0
    if span <= 0:
        return None
    rate = sum(s for _, s in recs) / span  # bytes/second
    if rate <= 0:
        return None
    return int(min(QUOTA_RETRY_MAX_S,
                   max(QUOTA_RETRY_MIN_S, round(deficit_bytes / rate))))


class QuotaManager:
    """Rolling-window per-tenant byte/compute accounting + the typed
    admission check (module docstring).  Thread-safe: jobs charge from
    their own threads, the coalescer from its dispatcher thread, and
    admission reads from the scheduler's."""

    def __init__(self, spec: str = "", window_s: Optional[float] = None,
                 clock=time.monotonic, tracer=None):
        self.budgets = parse_quota_spec(spec)
        self.window_s = (
            float(window_s) if window_s is not None else quota_window_s()
        )
        self._clock = clock
        self._tracer = tracer if tracer is not None else tele.TRACE
        self._lock = threading.Lock()
        # tenant -> deque[(t, bytes, compute_s)], oldest first
        self._charges: dict = {}

    def budget_for(self, tenant: str) -> Budget:
        b = self.budgets.get(tenant)
        if b is None:
            b = self.budgets.get("*")
        return b if b is not None else Budget()

    @property
    def enforcing(self) -> bool:
        return any(b.limited for b in self.budgets.values())

    # ---- charging -------------------------------------------------------
    def charge(self, tenant: str, nbytes: int = 0,
               compute_s: float = 0.0) -> None:
        """Account one charge against a tenant's rolling window (and
        mirror it into the telemetry quota ledger, so `adam-tpu
        analyze` renders per-tenant consumption)."""
        if nbytes <= 0 and compute_s <= 0:
            return
        now = self._clock()
        with self._lock:
            dq = self._charges.get(tenant)
            if dq is None:
                dq = self._charges[tenant] = deque()
            dq.append((now, int(nbytes), float(compute_s)))
            self._prune_locked(tenant, now)
        b = self.budget_for(tenant)
        self._tracer.record_quota(
            tenant, nbytes=nbytes, compute_s=compute_s,
            budget_bytes=b.bytes, budget_compute_s=b.compute_s,
        )

    def _prune_locked(self, tenant: str, now: float) -> None:
        dq = self._charges.get(tenant)
        if not dq:
            return
        horizon = now - self.window_s
        while dq and dq[0][0] < horizon:
            dq.popleft()

    def consumed(self, tenant: str) -> tuple:
        """(bytes, compute_s) spent inside the current window."""
        now = self._clock()
        with self._lock:
            self._prune_locked(tenant, now)
            dq = self._charges.get(tenant) or ()
            return (
                sum(c[1] for c in dq),
                sum(c[2] for c in dq),
            )

    # ---- the admission check -------------------------------------------
    def check(self, tenant: str) -> Optional[QuotaExceeded]:
        """None when the tenant may be admitted; a typed
        :class:`QuotaExceeded` (with a budget-derived Retry-After)
        when its rolling-window spend exceeds a budget."""
        b = self.budget_for(tenant)
        if not b.limited:
            return None
        now = self._clock()
        with self._lock:
            self._prune_locked(tenant, now)
            dq = list(self._charges.get(tenant) or ())
        used_b = sum(c[1] for c in dq)
        used_c = sum(c[2] for c in dq)
        if b.bytes is not None and used_b > b.bytes:
            return QuotaExceeded(
                tenant, "bytes", used_b, b.bytes,
                self._expiry_hint(dq, now, used_b - b.bytes, idx=1),
            )
        if b.compute_s is not None and used_c > b.compute_s:
            return QuotaExceeded(
                tenant, "compute_s", used_c, b.compute_s,
                self._expiry_hint(dq, now, used_c - b.compute_s, idx=2),
            )
        return None

    def _expiry_hint(self, dq: list, now: float, deficit: float,
                     idx: int) -> int:
        """Seconds until enough of the oldest charges age out of the
        window to cover ``deficit`` — the honest Retry-After: the
        rolling window IS the refill schedule."""
        freed = 0.0
        for charge in dq:
            freed += charge[idx]
            if freed >= deficit:
                eta = charge[0] + self.window_s - now
                return int(min(QUOTA_RETRY_MAX_S,
                               max(QUOTA_RETRY_MIN_S, round(eta))))
        return int(min(QUOTA_RETRY_MAX_S,
                       max(QUOTA_RETRY_MIN_S, round(self.window_s))))

    # ---- the mid-run throttle ------------------------------------------
    def throttle(self, tenant: str, should_stop=None,
                 max_wait_s: Optional[float] = None,
                 sleep=None, tracer=None) -> float:
        """Defer one grant while ``tenant`` is over budget (the pacer
        seam calls this before taking the WFQ turn).  Returns the
        seconds actually deferred (0.0 on the in-budget fast path —
        one ``check`` call).

        The wait polls in :data:`THROTTLE_POLL_S` steps so (a) charges
        aging out of the rolling window free the grant promptly and
        (b) ``should_stop()`` — the scheduler's drain/cancel probe —
        interrupts a deferral immediately (the caller's own pacer turn
        then raises ``RunCancelled``).  Bounded by ``max_wait_s``
        (default :func:`max_defer_s`): a stuck budget degrades to a
        bounded delay, never a wedged job.  Counts
        ``sched.quota.deferred`` once per deferral episode."""
        if self.check(tenant) is None:
            return 0.0
        if should_stop is not None and should_stop():
            # draining/cancelled: the caller's own pacer turn raises
            # next — a deferral that would end before it began is not
            # an episode (no count, no warning)
            return 0.0
        tr = tracer if tracer is not None else self._tracer
        tr.count(tele.C_QUOTA_DEFERRED)
        if max_wait_s is not None:
            bound = max_wait_s
        else:
            bound = max_defer_s() or (self.window_s + 1.0)
        do_sleep = sleep if sleep is not None else time.sleep
        t0 = self._clock()
        exceeded = self.check(tenant)
        log.warning(
            "tenant %r over budget mid-run (%s); deferring grants up "
            "to %.1fs", tenant,
            exceeded.reason if exceeded else "rechecking", bound,
        )
        while True:
            if should_stop is not None and should_stop():
                break
            if self.check(tenant) is None:
                break
            if self._clock() - t0 >= bound:
                break
            do_sleep(THROTTLE_POLL_S)
        return max(0.0, self._clock() - t0)

    # ---- status ---------------------------------------------------------
    def status(self) -> dict:
        """Point-in-time per-tenant view (scheduler/gateway status)."""
        with self._lock:
            tenants = sorted(
                set(self._charges) | set(self.budgets) - {"*"}
            )
        out = {}
        for t in tenants:
            used_b, used_c = self.consumed(t)
            b = self.budget_for(t)
            out[t] = {
                "bytes_used": used_b,
                "compute_s_used": round(used_c, 6),
                "budget_bytes": b.bytes,
                "budget_compute_s": b.compute_s,
            }
        return {"window_s": self.window_s, "tenants": out}


def quota_from_env() -> Optional[QuotaManager]:
    """Build a manager from ``ADAM_TPU_QUOTA`` (None when unset/empty
    — the zero-overhead default)."""
    spec = os.environ.get("ADAM_TPU_QUOTA", "").strip()
    if not spec:
        return None
    return QuotaManager(spec)
