"""Fault-isolated multi-job scheduler for the streamed transform.

The ROADMAP's "always-on transform service" jump: one process, one
shared :class:`~adam_tpu.parallel.device_pool.DevicePool`, N concurrent
streamed jobs — each an ordinary ``transform_streamed`` run wearing
three service-grade harnesses (docs/ROBUSTNESS.md "Fault-isolated
multi-job scheduling"):

* **Admission control** — ``max_jobs`` bounded slots; a full or
  draining scheduler returns a typed :class:`~adam_tpu.serve.job.Busy`
  instead of queueing unboundedly.  Admitted jobs interleave their
  windows on the shared pool under per-tenant weighted fair queuing
  (serve/fairness.py).
* **Fault isolation / quarantine** — a job whose run keeps failing is
  resumed from its own :class:`~adam_tpu.pipelines.checkpoint.RunJournal`
  up to ``job_retries`` times (``ADAM_TPU_SCHED_JOB_RETRIES``), then
  **quarantined**: its lease returns to the pool, its journal stays
  resumable for an operator, and the surviving jobs never notice —
  device eviction triggered by one job replays only that job's
  in-flight windows (the PR 4 recovery paths are already per-job).
* **Graceful drain** — :meth:`request_drain` stops admissions and
  cancels every lane; each job stops at its next window boundary with
  in-flight parts published and journaled
  (:class:`~adam_tpu.pipelines.streamed.RunCancelled` semantics), so a
  SIGTERM'd service exits 0 with every journal durable.
* **Whole-process crash recovery** — :meth:`recover` scans the run-root
  for durably written ``JOB.json`` records and resumes every
  non-terminal job from its journal, bit-identically, under the PR 6
  fingerprint/refusal rules (a changed input refuses and restarts
  clean; a quarantined job stays quarantined — auto-resuming poison
  would crash-loop the pool).

Every job runs in its own thread with its own run tracer and its own
``adam_tpu.heartbeat/7`` stream at ``<run-root>/<job>/heartbeat.ndjson``
(``adam-tpu top <run-root>`` aggregates them).  The ``sched.*`` fault
points (``sched.admit`` / ``sched.dispatch`` / ``sched.drain`` /
``sched.job_crash``, job id in the ``device`` selector slot) extend the
PR 4 fault matrix to the scheduler itself.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional, Union

from adam_tpu.parallel import device_pool as dp_mod
from adam_tpu.pipelines import streamed as streamed_mod
from adam_tpu.pipelines.checkpoint import RunJournal
from adam_tpu.serve.fairness import WeightedInterleaver
from adam_tpu.serve.job import (
    DONE,
    INTERRUPTED,
    PENDING,
    QUARANTINED,
    RESUMABLE_STATES,
    RUNNING,
    Admitted,
    Busy,
    JobRecord,
    JobSpec,
)
from adam_tpu.utils import faults
from adam_tpu.utils import incidents
from adam_tpu.utils import slo as slo_mod
from adam_tpu.utils import retry as retry_mod
from adam_tpu.utils import telemetry as tele
from adam_tpu.utils.durability import atomic_write_json
from adam_tpu.utils.retry import _env_int

log = logging.getLogger(__name__)

JOB_FILE = "JOB.json"
JOB_SCHEMA = "adam_tpu.serve_job/1"
RUN_DIR_NAME = "run"
HEARTBEAT_NAME = "heartbeat.ndjson"


def default_job_retries() -> int:
    """Quarantine policy bound: how many RESUMES a failing job gets
    before quarantine (``ADAM_TPU_SCHED_JOB_RETRIES``, default 1 — two
    attempts total; the typo-degrades-to-default tuning-var rule)."""
    return _env_int("ADAM_TPU_SCHED_JOB_RETRIES", 1)


class JobScheduler:
    """In-process async scheduler: N streamed jobs on one device pool.

    ``run_root`` is the service's durable state root — one
    subdirectory per job (``JOB.json`` + ``run/`` journal +
    ``heartbeat.ndjson``).  ``devices``/``partitioner`` configure the
    shared pool exactly like the CLI flags configure a solo run; jobs
    may pin their own ``partitioner`` in the spec.
    """

    def __init__(self, run_root: str, *, max_jobs: int = 2,
                 devices: Optional[int] = None,
                 partitioner: Optional[str] = None,
                 job_retries: Optional[int] = None,
                 batching: Optional[bool] = None,
                 batch_wait_ms: Optional[float] = None,
                 quota=None,
                 slo=None):
        from adam_tpu.serve.batching import batching_enabled
        from adam_tpu.serve.quota import QuotaManager, quota_from_env
        from adam_tpu.utils import perfledger

        self.run_root = os.path.abspath(run_root)
        os.makedirs(self.run_root, exist_ok=True)
        # arm the incident recorder on the service's durable root:
        # anomaly triggers anywhere in this process (health transition,
        # hedge, SDC mismatch, retry exhaustion, quota 429 burst) drop
        # bundles under <run-root>/incidents/ (utils/incidents.py)
        incidents.install(self.run_root)
        self.max_jobs = max(1, max_jobs)
        self.devices = devices
        self.partitioner = partitioner
        self.job_retries = (
            job_retries if job_retries is not None
            else default_job_retries()
        )
        # cross-job window batching (serve/batching.py; `--batch` /
        # ADAM_TPU_BATCH, default off): the coalescer itself is built
        # lazily with the shared pool on the first job start
        self.batching = (
            batching_enabled() if batching is None else bool(batching)
        )
        self._batch_wait_ms = batch_wait_ms
        self._coalescer = None
        # per-tenant quota enforcement (serve/quota.py; `--quota` /
        # ADAM_TPU_QUOTA, default none): accepts a ready QuotaManager,
        # a grammar string, or None (then the environment decides)
        if quota is None:
            self._quota = quota_from_env()
        elif isinstance(quota, str):
            self._quota = QuotaManager(quota) if quota.strip() else None
        else:
            self._quota = quota
        # declarative SLOs (utils/slo.py; `--slo` / ADAM_TPU_SLO,
        # default none): accepts a ready SLOEngine, a grammar string,
        # or None (then the environment decides).  The engine arms
        # module-wide with its budget file under the service root, so
        # restarts resume the error budget; the perf ledger arms on
        # the same root so every completed job books its perf keys
        # there (utils/perfledger.py).
        if slo is None:
            slo = slo_mod.slo_from_env()
        self._slo = slo_mod.install(slo, self.run_root) \
            if slo is not None else None
        perfledger.install(self.run_root)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # serializes JOB.json rewrites: a submit/recover thread and the
        # job's own state transitions may persist the same record
        # concurrently, and atomic_write_json's staging name is fixed
        # per target path
        self._persist_lock = threading.Lock()
        self._jobs: dict[str, JobRecord] = {}
        self._threads: dict[str, threading.Thread] = {}
        self._interleaver = WeightedInterleaver()
        self._draining = False
        self._closed = False
        self._pool = None
        self._pool_built = False
        # service-wide heartbeat (<run-root>/heartbeat.ndjson): samples
        # the global TRACE — tunnel bytes, retry/fault counters, HBM —
        # the pool-totals row `adam-tpu top <run-root>` renders next to
        # the per-job (job-scoped) streams
        self._service_hb = None
        # the service is an observability-on system: per-job heartbeats
        # sample the global TRACE for pool-wide counters, and concurrent
        # jobs must never flip/reset it per-run (the solo pipeline's
        # heartbeat restore semantics assume one run per process)
        self._restore_recording = tele.TRACE.recording
        tele.TRACE.recording = True
        # drain-aware retry backoff: every retry sleep in this process
        # waits on this event, so a SIGTERM drain never stalls up to
        # ADAM_TPU_RETRY_MAX_BACKOFF_S per in-flight retry — the
        # sleeping retry wakes and runs its remaining attempts with a
        # small bounded pause (failure semantics untouched: a mid-drain
        # transient still absorbs), and the job stops at its window
        # boundary under the normal drain contract
        self._drain_ev = threading.Event()
        retry_mod.set_cancel_event(self._drain_ev)

    # ---- paths ---------------------------------------------------------
    def job_dir(self, job_id: str) -> str:
        return os.path.join(self.run_root, job_id)

    def job_run_dir(self, job_id: str) -> str:
        return os.path.join(self.job_dir(job_id), RUN_DIR_NAME)

    def heartbeat_path(self, job_id: str) -> str:
        return os.path.join(self.job_dir(job_id), HEARTBEAT_NAME)

    # ---- durable job records -------------------------------------------
    def _persist(self, rec: JobRecord) -> None:
        """Durably rewrite the job's ``JOB.json`` (fsync'd atomic
        publish — the crash-recovery scan trusts these bytes)."""
        with self._lock:
            doc = {
                "schema": JOB_SCHEMA,
                "spec": rec.spec.to_doc(),
                "state": rec.state,
                "attempts": rec.attempts,
                "error": rec.error,
            }
        with self._persist_lock:
            atomic_write_json(
                os.path.join(self.job_dir(rec.spec.job_id), JOB_FILE),
                doc,
            )

    @staticmethod
    def _read_job_doc(path: str) -> Optional[dict]:
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            log.warning("job record %s is unreadable (%s); skipping",
                        path, e)
            return None
        if not isinstance(doc, dict) or doc.get("schema") != JOB_SCHEMA:
            log.warning("job record %s has schema %r (want %r); skipping",
                        path, doc.get("schema") if isinstance(doc, dict)
                        else type(doc).__name__, JOB_SCHEMA)
            return None
        return doc

    # ---- admission -----------------------------------------------------
    def _active_count_locked(self) -> int:
        return sum(
            1 for r in self._jobs.values()
            if r.state in (PENDING, RUNNING)
        )

    def _unsettled_count_locked(self) -> int:
        """Jobs whose runner thread has not fully unwound (durable
        terminal persist included) — what :meth:`wait` blocks on."""
        return sum(1 for r in self._jobs.values() if not r.settled)

    def submit(self, spec: JobSpec,
               recovered: bool = False) -> Union[Admitted, Busy]:
        """Admit one job, or refuse with a typed :class:`Busy`.

        Never blocks and never queues: a ``Busy`` caller owns the
        retry policy (the CLI front-end polls as slots free).
        ``recovered`` marks a crash-recovery resubmission — it bypasses
        the capacity bound (the slots were already granted by the
        process that died) and resumes from the journal."""
        faults.point("sched.admit", device=spec.job_id)
        spec.validate()
        with self._lock:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            if self._draining:
                tele.TRACE.count(tele.C_SCHED_REJECTED)
                return Busy(
                    "scheduler is draining; not accepting jobs",
                    kind="draining",
                )
            prior = self._jobs.get(spec.job_id)
            if prior is not None and (
                prior.state in (PENDING, RUNNING) or not prior.settled
            ):
                # `not settled` closes a narrow race: a terminal record
                # whose runner thread has not finished unwinding could
                # otherwise have its OLD thread's finally deregister
                # the resubmission's fresh fairness lane
                tele.TRACE.count(tele.C_SCHED_REJECTED)
                return Busy(
                    f"job {spec.job_id!r} is already {prior.state}",
                    kind="duplicate",
                )
            # per-tenant quota gate (serve/quota.py): an over-budget
            # tenant's FRESH submissions are refused with the typed
            # quota leg + a budget-derived Retry-After before they can
            # take a slot — other tenants are untouched.  Deliberately
            # AFTER the duplicate check (an idempotent re-PUT of a live
            # job must keep answering duplicate, never 429) and skipped
            # for resubmissions of a known job (prior is not None:
            # resuming an interrupted/quarantined journal) and for
            # crash recovery — that spend already happened, and
            # refusing the resume would strand a journal.
            if (
                self._quota is not None and not recovered
                and prior is None
            ):
                exceeded = self._quota.check(spec.tenant)
                if exceeded is not None:
                    from adam_tpu.serve.quota import rate_retry_hint

                    hint = exceeded.retry_after_s
                    if exceeded.resource == "bytes":
                        # bytes-per-grant refinement: the fairness
                        # ring's sized grants estimate how fast the
                        # service actually burns bytes — the larger
                        # (more honest) of the two hints wins
                        rh = rate_retry_hint(
                            exceeded.used - exceeded.budget,
                            self._interleaver.grant_records(64),
                        )
                        if rh is not None:
                            hint = max(hint, rh)
                    tele.TRACE.count(tele.C_SCHED_REJECTED)
                    tele.TRACE.count(tele.C_QUOTA_REJECTED)
                    # burst detection: N quota 429s inside the rolling
                    # window fire one "quota.burst" incident bundle
                    # (cooldown-limited, so at most one bundle write
                    # ever lands under this admission lock)
                    incidents.note_quota_rejected(spec.tenant)
                    return Busy(
                        exceeded.reason, kind="quota",
                        retry_after_s=hint,
                    )
            if not recovered and self._active_count_locked() >= self.max_jobs:
                tele.TRACE.count(tele.C_SCHED_REJECTED)
                return Busy(
                    f"at capacity ({self.max_jobs} job slot(s) in use); "
                    "retry when a slot frees",
                    kind="capacity",
                )
            if spec.trace_id is None:
                # direct (non-gateway) submission: mint the job's trace
                # here so every admitted job carries one; a recovered
                # spec keeps the id JOB.json round-tripped (one job =
                # one trace across SIGKILL/recovery attempts)
                spec.trace_id = tele.mint_trace_id()
            rec = JobRecord(spec, state=PENDING, recovered=recovered)
            if prior is not None:
                # re-admission of a terminal job resumes its journal
                rec.recovered = recovered or prior.state in (
                    INTERRUPTED, QUARANTINED,
                )
                rec.attempts = 0
            self._jobs[spec.job_id] = rec
        os.makedirs(self.job_dir(spec.job_id), exist_ok=True)
        self._persist(rec)
        self._interleaver.register(
            spec.job_id, tenant=spec.tenant, weight=spec.weight
        )
        self._ensure_service_heartbeat()
        t = threading.Thread(
            target=self._run_job, args=(rec,),
            name=f"adam-tpu-job:{spec.job_id}", daemon=True,
        )
        with self._lock:
            self._threads[spec.job_id] = t
        t.start()
        tele.TRACE.count(
            tele.C_SCHED_RECOVERED if recovered else tele.C_SCHED_ADMITTED
        )
        self._gauge_active()
        return Admitted(spec.job_id)

    def _gauge_active(self) -> None:
        with self._lock:
            n = self._active_count_locked()
        tele.TRACE.gauge(tele.G_SCHED_ACTIVE, n)

    def _ensure_service_heartbeat(self) -> None:
        with self._lock:
            if self._service_hb is not None:
                return
            hb = tele.Heartbeat(
                [tele.TRACE],
                os.path.join(self.run_root, HEARTBEAT_NAME),
            )
            self._service_hb = hb
        hb.start()

    # ---- the shared pool -----------------------------------------------
    def _get_pool(self):
        """Build the shared DevicePool once (None on single-device
        topologies — jobs then keep the single-chip path)."""
        with self._lock:
            if self._pool_built:
                return self._pool
            self._pool_built = True
        pool = None
        try:
            pool = dp_mod.make_pool(self.devices)
        except Exception as e:
            log.warning("shared device pool unavailable (%s); jobs run "
                        "on the single-device path", e)
        with self._lock:
            self._pool = pool
        return pool

    def _ensure_coalescer(self):
        """Build the shared cross-job coalescer once (with the shared
        pool, the WFQ interleaver and the quota manager attached).
        None once the scheduler is closed — a job thread racing
        ``close()`` must never rebuild a fresh coalescer whose
        dispatcher thread nothing would ever stop."""
        from adam_tpu.serve.batching import WindowCoalescer

        with self._lock:
            if self._closed:
                return None
            if self._coalescer is not None:
                return self._coalescer
        pool = self._get_pool()
        with self._lock:
            if self._closed:
                return None
            if self._coalescer is None:
                self._coalescer = WindowCoalescer(
                    pool=pool, wait_ms=self._batch_wait_ms,
                    interleaver=self._interleaver, quota=self._quota,
                )
            return self._coalescer

    def _job_coalesces(self, spec: JobSpec) -> bool:
        """True when this job's dispatches can actually reach the
        coalescer: the device backend (the coalescer fuses device
        dispatches only) and a non-mesh EFFECTIVE execution mode —
        resolved the same way the pipeline resolves them (spec override
        → scheduler default → the ``ADAM_TPU_*`` environment), so an
        env-pinned mesh or host-backend job never sits in the eligible
        set as a silent member."""
        try:
            from adam_tpu.parallel.partitioner import (
                resolve_execution_mode,
            )
            from adam_tpu.pipelines.bqsr import bqsr_backend

            if bqsr_backend() != "device":
                return False
            return resolve_execution_mode(
                spec.partitioner if spec.partitioner
                else self.partitioner
            ) != "mesh"
        except Exception:
            # a malformed backend/partitioner env surfaces from the
            # job's own run with proper attribution; here it just
            # means "don't register"
            return False

    def _job_pacer(self, spec: JobSpec):
        """The job's pacer: the mid-run quota throttle, then the WFQ
        turn, then the quota byte charge — every grant's window payload
        size lands on the tenant's rolling-window budget (the
        device-ledger-shaped byte leg; the coalescer charges the
        compute leg per fused dispatch).  The throttle DEFERS an
        over-budget tenant's grant (bounded sleeps until enough spend
        ages out of the rolling window, ``sched.quota.deferred``)
        instead of letting a long admitted job stream past its budget
        until the next admission-time 429; a drain or per-job cancel
        interrupts the deferral immediately and the turn that follows
        raises ``RunCancelled`` as usual."""
        from adam_tpu.serve.quota import throttle_enabled

        inner = self._interleaver.pacer(spec.job_id)
        quota = self._quota
        if quota is None:
            return inner
        tenant = spec.tenant
        job_id = spec.job_id
        throttling = throttle_enabled()

        def _stop_deferral() -> bool:
            return (
                self.draining or self._interleaver.cancelled(job_id)
            )

        def pace(phase: str, index: int, size: int = 0) -> None:
            if throttling:
                quota.throttle(tenant, should_stop=_stop_deferral)
            inner(phase, index, size)
            if size:
                quota.charge(tenant, nbytes=size)

        return pace

    # ---- the job runner -------------------------------------------------
    def _set_state(self, rec: JobRecord, state: str,
                   error: Optional[str] = None) -> None:
        with self._lock:
            rec.state = state
            if error is not None:
                rec.error = error
            self._cond.notify_all()
        self._persist(rec)

    def _run_job(self, rec: JobRecord) -> None:
        spec = rec.spec
        resume = rec.recovered
        lease = None
        coal = None
        coal_client = None
        try:
            self._set_state(rec, RUNNING)
            pool = self._get_pool()
            if pool is not None:
                lease = pool.lease(job=spec.job_id)
            if self.batching and self._job_coalesces(spec):
                # cross-job batching: register this job with the shared
                # coalescer and hand its bound client to the pipeline.
                # Jobs that can never submit tickets (mesh execution
                # mode — the mesh already fuses the device set per
                # window — or a non-device backend) are skipped
                # outright: a registered-but-silent member would force
                # every other job's group to wait out the full batching
                # delay instead of flushing early.
                coal = self._ensure_coalescer()
                if coal is not None:
                    coal_client = coal.client(
                        spec.job_id, spec.tenant, trace=spec.trace_id,
                    )
            known_snps = known_indels = None
            t0 = time.monotonic()
            while True:
                try:
                    faults.point("sched.job_crash", device=spec.job_id)
                    if (spec.known_snps or spec.known_indels) and \
                            known_snps is None and known_indels is None:
                        known_snps, known_indels = _load_known_sites(spec)
                    with tele.TRACE.span(
                        tele.SPAN_SCHED_JOB, job=spec.job_id,
                        tenant=spec.tenant, trace=spec.trace_id,
                    ):
                        stats = streamed_mod.transform_streamed(
                            spec.input, spec.output,
                            mark_duplicates=spec.mark_duplicates,
                            recalibrate=spec.recalibrate,
                            realign=spec.realign,
                            known_snps=known_snps,
                            known_indels=known_indels,
                            window_reads=spec.window_reads,
                            compression=spec.compression,
                            devices=self.devices,
                            partitioner=(
                                spec.partitioner if spec.partitioner
                                else self.partitioner
                            ),
                            progress=self.heartbeat_path(spec.job_id),
                            run_dir=self.job_run_dir(spec.job_id),
                            resume=resume,
                            pacer=self._job_pacer(spec),
                            device_pool=lease,
                            coalescer=coal_client,
                            trace=spec.trace_id,
                        )
                    with self._lock:
                        rec.stats = stats
                    self._set_state(rec, DONE, error="")
                    # SLO observation: one completed job against the
                    # armed objectives (no-op when --slo is off).
                    # Interrupted jobs are excluded — a drain is an
                    # operator action, not a service failure.
                    slo_mod.observe_job(
                        spec.tenant, time.monotonic() - t0, ok=True,
                        trace_id=spec.trace_id,
                    )
                    log.info("job %s done (%s reads, %s windows)",
                             spec.job_id, stats.get("n_reads"),
                             stats.get("windows_fresh"))
                    return
                except streamed_mod.RunCancelled:
                    # graceful drain: in-flight parts published, the
                    # journal is durable and resumable — NOT a failure
                    tele.TRACE.count(tele.C_SCHED_INTERRUPTED)
                    self._set_state(rec, INTERRUPTED)
                    log.info(
                        "job %s interrupted at a window boundary "
                        "(drain); its journal resumes it", spec.job_id,
                    )
                    return
                except Exception as e:
                    with self._lock:
                        rec.attempts += 1
                        attempts = rec.attempts
                        rec.error = f"{type(e).__name__}: {e}"
                    resume = True
                    if attempts > self.job_retries:
                        # QUARANTINE: the job stops consuming slots and
                        # devices; journal + JOB.json stay on disk for
                        # an operator resubmission.  Survivor jobs keep
                        # streaming — nothing here touches them.
                        tele.TRACE.count(tele.C_SCHED_QUARANTINED)
                        self._set_state(rec, QUARANTINED)
                        # a quarantined job is an availability bad
                        # event against the armed objectives
                        slo_mod.observe_job(
                            spec.tenant, time.monotonic() - t0,
                            ok=False, trace_id=spec.trace_id,
                        )
                        log.error(
                            "job %s QUARANTINED after %d failed "
                            "attempt(s) (last: %s); its journal stays "
                            "resumable, survivors are unaffected",
                            spec.job_id, attempts, rec.error,
                        )
                        return
                    self._persist(rec)
                    log.warning(
                        "job %s attempt %d failed (%s); resuming from "
                        "its journal (%d retr%s left)",
                        spec.job_id, attempts, rec.error,
                        self.job_retries - attempts + 1,
                        "y" if self.job_retries - attempts + 1 == 1
                        else "ies",
                    )
        finally:
            if lease is not None:
                lease.release()
            if coal is not None:
                # drop out of the coalesce-eligible set FIRST: groups
                # waiting for this job's windows flush immediately
                coal.deregister(spec.job_id)
            self._interleaver.deregister(spec.job_id)
            self._gauge_active()
            with self._lock:
                # LAST: the terminal state is already durably persisted
                # above, so a waiter unblocked by this flag can trust
                # what a crash-recovery scan would read
                rec.settled = True
                self._cond.notify_all()

    # ---- drain / wait / lifecycle --------------------------------------
    def request_drain(self) -> None:
        """Stop admissions and cancel every lane; jobs stop at their
        next window boundary with parts published and journals durable
        (idempotent, non-blocking — pair with :meth:`wait`)."""
        with self._lock:
            already = self._draining
            self._draining = True
        if already:
            return
        faults.point("sched.drain")
        log.info("drain requested: admissions closed, %d job(s) will "
                 "stop at their next window boundary",
                 len(self.active_jobs()))
        # wake every backoff-sleeping retry NOW: a drain must not wait
        # out exponential backoffs (utils/retry.set_cancel_event)
        self._drain_ev.set()
        self._interleaver.cancel()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful drain to completion: :meth:`request_drain` + wait
        for every job to reach a terminal state.  True when fully
        drained within ``timeout``."""
        self.request_drain()
        return self.wait(timeout)

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def active_jobs(self) -> list:
        with self._lock:
            return [
                r.spec.job_id for r in self._jobs.values()
                if r.state in (PENDING, RUNNING)
            ]

    def cancel(self, job_id: str) -> bool:
        """Cancel one running/pending job at its next window boundary
        (the single-job twin of :meth:`request_drain`): its pacer turn
        raises ``RunCancelled``, in-flight parts publish, the journal
        stays durable and resumable, and the job lands ``interrupted``
        — a re-submission resumes it.  False when the job is unknown
        or already terminal (nothing to cancel)."""
        with self._lock:
            rec = self._jobs.get(job_id)
            active = rec is not None and rec.state in (PENDING, RUNNING)
        if not active:
            return False
        self._interleaver.cancel(job_id)
        return True

    def grant_times(self, last: Optional[int] = None) -> list:
        """The fairness interleaver's recent grant timestamps (the
        gateway's Retry-After signal; serve/fairness.py)."""
        return self._interleaver.grant_times(last)

    def grant_records(self, last: Optional[int] = None) -> list:
        """Recent ``(time, size)`` grant records — the bytes-per-grant
        view the quota leg's Retry-After derives from."""
        return self._interleaver.grant_records(last)

    @property
    def quota(self):
        """The per-tenant QuotaManager (None when quotas are off)."""
        return self._quota

    def has_capacity(self) -> bool:
        """True when a submission would not be refused for capacity or
        draining — the polite client's pre-check, so a capacity poll
        loop doesn't inflate ``sched.jobs.rejected`` (and the
        ``sched.admit`` fault point's arrival count) with one refusal
        per poll tick."""
        with self._lock:
            return (
                not self._draining and not self._closed
                and self._active_count_locked() < self.max_jobs
            )

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until no job is pending/running (True) or ``timeout``
        elapses (False)."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        with self._lock:
            while self._unsettled_count_locked():
                remaining = (
                    deadline - time.monotonic()
                    if deadline is not None else 0.2
                )
                if deadline is not None and remaining <= 0:
                    return False
                self._cond.wait(min(0.2, max(remaining, 0.01)))
            return True

    def close(self) -> None:
        """Release process-wide hooks (restores the TRACE recording
        flag the constructor flipped, stops the service heartbeat).
        Jobs must be terminal."""
        with self._lock:
            self._closed = True
            hb = self._service_hb
            self._service_hb = None
            coal = self._coalescer
            self._coalescer = None
        if coal is not None:
            coal.stop()
        if hb is not None:
            hb.stop()
        # release the process-wide retry-cancel registration, but only
        # if it is still ours (a newer scheduler may have re-registered)
        retry_mod.clear_cancel_event(self._drain_ev)
        tele.TRACE.recording = self._restore_recording
        # disarm the incident recorder, but only if it is still armed
        # on OUR run-root (a newer scheduler may have re-armed it)
        if incidents.incidents_dir() == os.path.join(
                self.run_root, incidents.INCIDENTS_DIRNAME):
            incidents.uninstall()
        # same for the SLO engine and the perf ledger (both armed on
        # our run-root by the constructor)
        from adam_tpu.utils import perfledger

        if self._slo is not None and slo_mod.engine() is self._slo:
            slo_mod.uninstall()
        if perfledger.ledger_root() == self.run_root:
            perfledger.uninstall()

    # ---- whole-process crash recovery ----------------------------------
    def recover(self) -> list:
        """Scan the run-root and resume every incomplete job.

        Each subdirectory with a readable ``JOB.json`` in a resumable
        state (pending/running/interrupted — i.e. the previous process
        died or drained mid-job) is resubmitted with ``resume`` against
        its own journal; the PR 6 fingerprint rules guarantee the
        continuation is bit-identical or refused-and-restarted.  Done
        and quarantined jobs are re-registered for status visibility
        but not re-run.  Returns the resumed job ids."""
        resumed = []
        try:
            entries = sorted(os.listdir(self.run_root))
        except OSError as e:
            log.warning("cannot scan run root %s: %s", self.run_root, e)
            return resumed
        for name in entries:
            job_path = os.path.join(self.run_root, name, JOB_FILE)
            if not os.path.isfile(job_path):
                continue
            doc = self._read_job_doc(job_path)
            if doc is None:
                continue
            try:
                spec = JobSpec.from_doc(doc.get("spec") or {})
            except (TypeError, ValueError) as e:
                log.warning("job record %s has a malformed spec (%s); "
                            "skipping", job_path, e)
                continue
            state = doc.get("state")
            with self._lock:
                known = spec.job_id in self._jobs
            if known:
                continue
            if state not in RESUMABLE_STATES:
                # terminal: visible in status(), never re-run here
                rec = JobRecord(
                    spec, state=state if state else QUARANTINED,
                    attempts=int(doc.get("attempts") or 0),
                    error=doc.get("error"), settled=True,
                )
                with self._lock:
                    self._jobs[spec.job_id] = rec
                continue
            peek = RunJournal.peek(self.job_run_dir(spec.job_id))
            log.info(
                "recovering job %s (was %s%s)", spec.job_id, state,
                f", {peek['completed']} window(s) durable" if peek
                else ", no journal yet",
            )
            got = self.submit(spec, recovered=True)
            if isinstance(got, Admitted):
                resumed.append(spec.job_id)
            else:
                log.warning("recovery of job %s refused: %s",
                            spec.job_id, got.reason)
        return resumed

    # ---- status ---------------------------------------------------------
    def status(self) -> dict:
        """Point-in-time service view: per-job state + journal
        progress, pool lease occupancy, drain flag."""
        with self._lock:
            jobs = {
                jid: {
                    "state": r.state,
                    "tenant": r.spec.tenant,
                    "weight": r.spec.weight,
                    "attempts": r.attempts,
                    "error": r.error,
                    # the full spec rides along: the gateway's
                    # idempotent-PUT comparison and its part-fetch
                    # routes (spec["output"] is the part directory)
                    # both read it from here
                    "spec": r.spec.to_doc(),
                }
                for jid, r in self._jobs.items()
            }
            draining = self._draining
            pool = self._pool
        for jid, view in jobs.items():
            peek = RunJournal.peek(self.job_run_dir(jid))
            view["windows_durable"] = peek["completed"] if peek else 0
            view["n_windows"] = peek["n_windows"] if peek else None
        return {
            "run_root": self.run_root,
            "max_jobs": self.max_jobs,
            "draining": draining,
            "batching": self.batching,
            "quota": (
                self._quota.status() if self._quota is not None else None
            ),
            "slo": (
                self._slo.evaluate() if self._slo is not None else None
            ),
            "active_leases": (
                [lz.job for lz in pool.active_leases()]
                if pool is not None else []
            ),
            "jobs": jobs,
        }


def _load_known_sites(spec: JobSpec) -> tuple:
    """Load the spec's known-SNP/indel VCFs against the input's
    sequence dictionary (the actions.py plumbing, job-scoped)."""
    from adam_tpu.api.datasets import GenotypeDataset
    from adam_tpu.io import context

    contig_names = context.load_header(spec.input).seq_dict.names
    known = indels = None
    if spec.known_snps:
        known = GenotypeDataset.load(
            spec.known_snps, contig_names=contig_names
        ).snp_table()
    if spec.known_indels:
        indels = GenotypeDataset.load(
            spec.known_indels, contig_names=contig_names
        ).indel_table()
    return known, indels
