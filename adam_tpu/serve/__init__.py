"""Multi-job transform service: shared-pool scheduling for the
streamed flagship (docs/ROBUSTNESS.md "Fault-isolated multi-job
scheduling").

* :mod:`adam_tpu.serve.job` — the JSON-roundtrip job model and the
  typed admission results (:class:`Admitted` / :class:`Busy`).
* :mod:`adam_tpu.serve.fairness` — per-tenant weighted window
  interleaving (virtual-time fair queuing over the shared pool).
* :mod:`adam_tpu.serve.scheduler` — admission control, job quarantine,
  graceful drain and whole-process crash recovery.
* :mod:`adam_tpu.serve.batching` — continuous cross-job window
  batching: the :class:`WindowCoalescer` merges concurrent jobs'
  windows into one fused dispatch per pass (docs/SERVING.md
  "Continuous batching & quotas").
* :mod:`adam_tpu.serve.quota` — per-tenant rolling-window byte/compute
  budgets, surfaced as the gateway's typed 429 quota leg.

The thin front-ends live next door: ``adam_tpu/api/transform_service``
is the library submission seam, ``adam-tpu serve`` the CLI one.
"""

from adam_tpu.serve.batching import WindowCoalescer, batching_enabled
from adam_tpu.serve.fairness import WeightedInterleaver
from adam_tpu.serve.quota import QuotaManager
from adam_tpu.serve.job import (
    DONE,
    INTERRUPTED,
    PENDING,
    QUARANTINED,
    RUNNING,
    Admitted,
    Busy,
    JobSpec,
)
from adam_tpu.serve.scheduler import JobScheduler, default_job_retries

__all__ = [
    "Admitted",
    "Busy",
    "DONE",
    "INTERRUPTED",
    "JobScheduler",
    "JobSpec",
    "PENDING",
    "QUARANTINED",
    "QuotaManager",
    "RUNNING",
    "WeightedInterleaver",
    "WindowCoalescer",
    "batching_enabled",
    "default_job_retries",
]
