"""Continuous cross-job window batching (docs/SERVING.md "Continuous
batching & quotas").

PR 10's scheduler interleaves concurrent jobs at *window* granularity:
every job's window is its own mesh/pool dispatch, so N small jobs pay N
per-window dispatch overheads while each one under-fills the device
grid — exactly when the multi-tenant story needs the grid full.  This
module is the vLLM/Orca-style move for the streamed flagship: a
:class:`WindowCoalescer` sits between the `JobScheduler` and the
execution seam, collects ready windows from concurrent jobs (WFQ-
ordered by the fairness interleaver's tenant clocks, bounded batching
delay ``ADAM_TPU_BATCH_WAIT_MS``), and merges them into **one fused
dispatch per pass**:

* the fused grid is ONE ``[N_total, L]`` stack of per-job row blocks —
  each block is the job's own grid-quantized window (its
  :class:`~adam_tpu.parallel.device_pool.ResidentWindow` device arrays
  when the handle is alive on the target device, so coalescing does
  not re-ship ingested payloads; the host-retained ingest copy
  otherwise), concatenated *inside* the fused jit so the executable
  set stays keyed by the bucket-quantized block shapes;
* pass-B observe histograms accumulate into **per-job segments** of one
  scatter-add: each job's read-group indices offset into a disjoint
  band of the fused table, so slicing its band back out is bitwise the
  histogram its solo dispatch would have produced (integer scatter-adds
  over disjoint bins commute with concatenation);
* pass-C applies gather from one rg-concatenated table and, when packed
  columns are on, emit one flat payload whose **per-job byte ranges are
  exact** (the row-prefix pack is a prefix concatenation in row order),
  so each job's Arrow parts stay byte-identical to its solo run.

Fault contract: a fused dispatch that fails past its retry budget fails
only the tickets it carried — every affected job falls back to its own
solo dispatch path (which owns eviction/replay/host-degrade), so a
poison window quarantines its job while survivors replay from their
host ingest copies, byte-identically (``sched.batch.fallbacks`` counts
the windows that took the detour).  The ``sched.batch`` fault point
arrives once per fused dispatch; ``proc.kill device=batch`` is the
chaos harness's mid-batch kill phase.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

import numpy as np

from adam_tpu.utils import faults
from adam_tpu.utils import telemetry as tele

log = logging.getLogger(__name__)

#: Bounded batching delay (milliseconds): how long the coalescer holds
#: a group open for more jobs' windows before dispatching what it has.
DEFAULT_BATCH_WAIT_MS = 25.0

#: Backstop on a blocking ticket wait: the dispatcher failing all
#: tickets on any error makes this unreachable in practice, but a
#: wedged device RPC must surface as a fallback, not a hang.
_RESULT_TIMEOUT_S = 600.0

#: Tickets per fused dispatch, capped: the fused executable is keyed
#: by the per-ticket block-shape tuple, so unbounded group sizes would
#: grow the executable set one compile per distinct ticket COUNT (pass
#: B defers a job's whole window set at once).  8 keeps the compile
#: ledger's bounded-set contract while still fusing multiple windows
#: per job; overflow tickets simply form the next group.
MAX_GROUP_TICKETS = 8


def batch_wait_ms() -> float:
    """``ADAM_TPU_BATCH_WAIT_MS`` (default 25 ms; malformed or negative
    values warn and keep the default — ``utils/retry.env_float``, the
    shared tuning-var parser)."""
    from adam_tpu.utils.retry import env_float

    v = env_float("ADAM_TPU_BATCH_WAIT_MS", DEFAULT_BATCH_WAIT_MS)
    if v < 0:
        log.warning(
            "ADAM_TPU_BATCH_WAIT_MS=%s is negative; using default "
            "%.0fms", v, DEFAULT_BATCH_WAIT_MS,
        )
        return DEFAULT_BATCH_WAIT_MS
    return v


def batching_enabled(default: bool = False) -> bool:
    """``ADAM_TPU_BATCH`` toggle (default off — batching changes
    latency shape, so the operator opts in; ``adam-tpu serve --batch``
    sets it)."""
    from adam_tpu.utils.retry import env_toggle

    return env_toggle("ADAM_TPU_BATCH", default)


class CoalesceError(RuntimeError):
    """A ticket's fused dispatch failed (or the coalescer is stopping):
    the caller falls back to its solo dispatch path."""


class _Future:
    """Event-backed single-value future (no cancellation: the
    dispatcher resolves or fails every ticket it accepts).
    ``dataset`` carries the apply ticket's pre-recalibration dataset so
    a failed fused dispatch can re-apply solo without re-pinning the
    window anywhere else."""

    __slots__ = ("_ev", "_value", "_error", "dataset")

    def __init__(self):
        self._ev = threading.Event()
        self._value = None
        self._error = None
        self.dataset = None

    def set_result(self, value) -> None:
        self._value = value
        self._ev.set()

    def set_error(self, exc: BaseException) -> None:
        self._error = exc
        self._ev.set()

    def result(self, timeout: float = _RESULT_TIMEOUT_S):
        if not self._ev.wait(timeout):
            raise CoalesceError(
                f"fused dispatch did not resolve within {timeout:.0f}s"
            )
        if self._error is not None:
            raise self._error
        return self._value


class _Ticket:
    __slots__ = (
        "kind", "key", "job", "tenant", "window", "seq", "t_submit",
        "n", "g", "gl", "payload", "fut", "trace",
    )

    def __init__(self, kind, key, job, tenant, window, seq, n, g, gl,
                 payload, trace=None):
        self.kind = kind
        self.key = key
        self.job = job
        self.tenant = tenant
        self.window = window
        self.seq = seq
        self.trace = trace  # the submitting job's trace_id (fan-in link)
        self.t_submit = time.monotonic()
        self.n = n          # real rows
        self.g = g          # grid rows (the block's leading dim)
        self.gl = gl
        self.payload = payload
        self.fut = _Future()


# --------------------------------------------------------------------------
# Fused kernel bodies (module-level traceable functions; the jits are
# built lazily below).  Each takes per-ticket column tuples and
# concatenates INSIDE the trace, so per-job ResidentWindow arrays feed
# the fused grid without a host round-trip and the executable is keyed
# by the bucket-quantized block shapes.
# --------------------------------------------------------------------------
def _fused_markdup_body(cols):
    """cols: per-ticket (start, end, flags, ops, lens, n_ops, quals,
    lengths), each block ``[g_i(, gc/gl)]`` — one fused [N_total] run of
    the markdup reductions (per-row integer math: each row's five/score
    is independent of every other row, so block slices are bitwise the
    solo columns)."""
    import jax.numpy as jnp

    from adam_tpu.pipelines.markdup import markdup_columns_local

    cat = [jnp.concatenate(xs, axis=0) for xs in zip(*cols)]
    return markdup_columns_local(*cat)


def _fused_observe_body(cols, masks, segs, n_rg: int, lmax: int):
    """cols: per-ticket (bases, quals, lengths, flags, read_group_idx);
    masks: per-ticket (res_bits, mm_bits, read_ok) with the MD masks
    bit-packed (colpack, 8x); segs: per-ticket ``(rg_base, n_rg_i)``
    (static).  Each ticket's read-group indices resolve (null bin =
    its own ``n_rg_i - 1``) then offset by ``rg_base`` into a disjoint
    band of the fused histogram — ONE scatter-add, per-job segments
    bitwise the solo histograms."""
    import jax.numpy as jnp

    from adam_tpu.ops.colpack import unpack_mask_body
    from adam_tpu.pipelines.bqsr import observe_kernel

    parts = []
    for (bases, quals, lengths, flags, rg), \
            (res_pk, mm_pk, read_ok), (base, nri) in zip(
                cols, masks, segs):
        residue_ok = unpack_mask_body(res_pk, lmax)
        is_mm = unpack_mask_body(mm_pk, lmax)
        rg_off = (
            jnp.where(rg >= 0, rg, nri - 1).astype(jnp.int32) + base
        )
        parts.append((bases, quals, lengths, flags, rg_off,
                      residue_ok, is_mm, read_ok))
    cat = [jnp.concatenate(xs, axis=0) for xs in zip(*parts)]
    return observe_kernel.__wrapped__(*cat, n_rg, lmax)


def _fused_apply_body(cols, extras, table, segs, lmax: int,
                      pack_size: int):
    """cols: per-ticket (bases, quals, lengths, flags, read_group_idx);
    extras: per-ticket (has_qual, valid); table: the rg-concatenated
    (cycle-centered) fused table; segs as in the observe body.  One
    fused table gather; with ``pack_size`` (static, the fused grid
    area) additionally the on-device SANGER encode + row-prefix pack —
    the flat payload's per-job byte ranges are exact prefix sums, so
    slicing them back out is bitwise each job's solo packed payload."""
    import jax.numpy as jnp

    from adam_tpu.ops.colpack import pack_rows_body, sanger_body
    from adam_tpu.pipelines.bqsr import apply_table_body

    parts = []
    for (bases, quals, lengths, flags, rg), (hq, vd), (base, nri) in zip(
            cols, extras, segs):
        rg_off = (
            jnp.where(rg >= 0, rg, nri - 1).astype(jnp.int32) + base
        )
        parts.append((bases, quals, lengths, flags, rg_off, hq, vd))
    cat = [jnp.concatenate(xs, axis=0) for xs in zip(*parts)]
    new_q = apply_table_body(*cat, table, lmax)
    if not pack_size:
        return new_q
    lengths_cat, hq_cat, vd_cat = cat[2], cat[5], cat[6]
    pack_lens = jnp.where(
        vd_cat & hq_cat, lengths_cat.astype(jnp.int64), 0
    )
    return pack_rows_body(sanger_body(new_q), pack_lens, pack_size)


_FUSED_JITS: dict = {}
_FUSED_JITS_LOCK = threading.Lock()


def fused_jit(kind: str):
    """Lazily-built module-level jit for one fused body (one wrapper
    per kind, shared by warm + dispatch so both hit one executable
    cache — the markdup/observe/apply twins of ``bqsr.jit_variant``)."""
    fn = _FUSED_JITS.get(kind)
    if fn is not None:
        return fn
    with _FUSED_JITS_LOCK:
        fn = _FUSED_JITS.get(kind)
        if fn is not None:
            return fn
        import jax

        if kind == "markdup":
            fn = jax.jit(_fused_markdup_body)
        elif kind == "observe":
            fn = jax.jit(
                _fused_observe_body,
                static_argnames=("segs", "n_rg", "lmax"),
            )
        elif kind == "apply":
            fn = jax.jit(
                _fused_apply_body,
                static_argnames=("segs", "lmax", "pack_size"),
            )
        else:
            raise ValueError(f"unknown fused kind {kind!r}")
        _FUSED_JITS[kind] = fn
    return fn


def _zeros_like_tree(tree):
    """Host-zeros twin of a (possibly device-resident) arg pytree —
    the warm call's dummy payload (shapes/dtypes only matter)."""
    if isinstance(tree, (tuple, list)):
        return tuple(_zeros_like_tree(x) for x in tree)
    return np.zeros(tree.shape, tree.dtype)


class CoalescerClient:
    """One job's bound handle onto the shared coalescer — what the
    scheduler passes into ``transform_streamed(coalescer=...)`` so the
    pipeline never needs to know its own job identity."""

    def __init__(self, coalescer: "WindowCoalescer", job: str,
                 tenant: str, trace: Optional[str] = None):
        self._c = coalescer
        self.job = job
        self.tenant = tenant
        self.trace = trace

    def submit_markdup(self, window, batch, resident=None) -> _Future:
        return self._c.submit_markdup(
            self.job, self.tenant, window, batch, resident
        )

    def submit_observe(self, window, ds, known_snps=None,
                       resident=None) -> _Future:
        return self._c.submit_observe(
            self.job, self.tenant, window, ds, known_snps, resident
        )

    def submit_apply(self, window, ds, table, pack=False,
                     resident=None) -> _Future:
        return self._c.submit_apply(
            self.job, self.tenant, window, ds, table, pack, resident,
        )


class WindowCoalescer:
    """Cross-job fused-dispatch engine (module docstring).

    ``pool``: the scheduler's shared DevicePool (None on single-device
    topologies — fused dispatches then run on the default device).
    ``interleaver``: the WFQ fairness interleaver whose tenant clocks
    order tickets inside a fused grid.  ``quota``: an optional
    :class:`~adam_tpu.serve.quota.QuotaManager` charged per fused
    dispatch with each tenant's byte/compute share."""

    def __init__(self, pool=None, wait_ms: Optional[float] = None,
                 interleaver=None, quota=None, tracer=None):
        self.pool = pool
        self.wait_s = (
            batch_wait_ms() if wait_ms is None else float(wait_ms)
        ) / 1e3
        self.interleaver = interleaver
        self.quota = quota
        self.tracer = tracer if tracer is not None else tele.TRACE
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._jobs: dict = {}   # job -> tenant (the eligible set)
        self._job_traces: dict = {}  # job -> trace_id (fan-in links)
        self._pending: list = []
        self._seq = 0
        self._rr = 0            # fused-dispatch round-robin cursor
        self._stopped = False
        self._warmed: set = set()
        # fused-table placements keyed by (job-sorted (job, table
        # identity) tuple, n_cyc, device): per-job solved tables are
        # constant for a run, so the pad-center+concat+h2d happens once
        # per job-set instead of once per fused pass-C dispatch.  The
        # cached VALUES hold the table objects, so the identity ids in
        # the keys can never collide with a recycled address.
        self._table_cache: dict = {}
        self._thread = threading.Thread(
            target=self._run, name="adam-tpu-coalescer", daemon=True
        )
        self._thread.start()

    # ---- job lifecycle (scheduler-side) --------------------------------
    def client(self, job: str, tenant: str = "default",
               trace: Optional[str] = None) -> CoalescerClient:
        """Register a job as coalesce-eligible and return its bound
        client (the scheduler calls this at admission).  ``trace`` is
        the job's trace_id: every ticket the client submits carries it,
        and the fused-dispatch span links back to it (the fan-in edge
        a job-scoped trace export follows across the batch)."""
        with self._lock:
            self._jobs[job] = tenant
            if trace is not None:
                self._job_traces[job] = trace
            self._cond.notify_all()
        return CoalescerClient(self, job, tenant, trace)

    def deregister(self, job: str) -> None:
        """Drop a job from the eligible set (idempotent); groups
        waiting on its windows flush at their next check."""
        with self._lock:
            self._jobs.pop(job, None)
            self._job_traces.pop(job, None)
            self._cond.notify_all()

    def stop(self) -> None:
        """Stop the dispatcher: pending groups flush immediately, new
        submissions raise (callers fall back solo)."""
        with self._lock:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=30.0)

    # ---- ticket submission (pipeline-side, via CoalescerClient) --------
    def _submit(self, kind, key, job, tenant, window, n, g, gl,
                payload) -> _Future:
        with self._lock:
            if self._stopped:
                raise CoalesceError("coalescer is stopped")
            self._seq += 1
            t = _Ticket(kind, key, job, tenant, window, self._seq,
                        n, g, gl, payload,
                        trace=self._job_traces.get(job))
            self._pending.append(t)
            self._cond.notify_all()
        return t.fut

    def submit_markdup(self, job, tenant, window, batch,
                       resident=None) -> _Future:
        from adam_tpu.formats import schema
        from adam_tpu.formats.batch import (
            grid_cigar_cols, grid_cols, grid_rows, pad_rows_np,
        )

        b = batch.to_numpy()
        g = grid_rows(b.n_rows)
        gl = grid_cols(b.lmax)
        gc = grid_cigar_cols(
            b.cigar_ops.shape[1] if b.cigar_ops.ndim == 2 else 1
        )
        payload = {
            "b": b,
            "resident": resident,
            # the markdup-specific columns always ship (exactly the
            # solo resident dispatch's per-pass inputs)
            "fresh": (
                pad_rows_np(b.start, g, -1),
                pad_rows_np(b.end, g, -1),
                pad_rows_np(b.cigar_ops, g, schema.CIGAR_PAD, cols=gc),
                pad_rows_np(b.cigar_lens, g, 0, cols=gc),
                pad_rows_np(b.cigar_n, g, 0),
            ),
        }
        return self._submit(
            "markdup", ("markdup", gl, gc), job, tenant, window,
            b.n_rows, g, gl, payload,
        )

    def submit_observe(self, job, tenant, window, ds, known_snps=None,
                       resident=None) -> _Future:
        from adam_tpu.formats.batch import grid_cols, grid_rows, pad_rows_np
        from adam_tpu.ops.colpack import pack_mask_bits
        from adam_tpu.pipelines import bqsr as bqsr_mod

        # the host-side mask prep runs on the JOB's thread (parallel
        # across jobs); the dispatcher thread only fuses and dispatches
        b, read_ok, residue_ok, is_mm, n_rg = bqsr_mod.observe_inputs(
            ds, known_snps
        )
        g = grid_rows(b.n_rows)
        gl = grid_cols(b.lmax)
        payload = {
            "b": b,
            "resident": resident,
            "n_rg": n_rg,
            "masks": (
                pack_mask_bits(pad_rows_np(residue_ok, g, False, cols=gl)),
                pack_mask_bits(pad_rows_np(is_mm, g, False, cols=gl)),
                pad_rows_np(read_ok, g, False),
            ),
        }
        return self._submit(
            "observe", ("observe", gl), job, tenant, window,
            b.n_rows, g, gl, payload,
        )

    def submit_apply(self, job, tenant, window, ds, table,
                     pack=False, resident=None) -> _Future:
        # the table's cycle half-width is NOT threaded through: the
        # fused gather derives it from the (pad-centered, concatenated)
        # fused table's own shape, exactly like apply_table_body
        from adam_tpu.formats.batch import grid_cols, grid_rows, pad_rows_np
        from adam_tpu.ops.colpack import pack_lengths

        b = ds.batch.to_numpy()
        g = grid_rows(b.n_rows)
        gl = grid_cols(b.lmax)
        payload = {
            "ds": ds,
            "b": b,
            "resident": resident,
            "table": np.ascontiguousarray(table, np.uint8),
            "extras": (
                pad_rows_np(b.has_qual, g, False),
                pad_rows_np(b.valid, g, False),
            ),
            "pack_lens": (
                pack_lengths(b.lengths, b.valid, b.has_qual)
                if pack else None
            ),
        }
        fut = self._submit(
            "apply", ("apply", gl, bool(pack)), job, tenant, window,
            b.n_rows, g, gl, payload,
        )
        fut.dataset = ds
        return fut

    # ---- the dispatcher thread -----------------------------------------
    def _wfq_rank(self, t: _Ticket):
        """WFQ ordering inside a fused grid: the fairness interleaver's
        tenant virtual clock first (smaller clock = more underserved
        tenant = earlier rows), submission order within a tenant."""
        vt = None
        if self.interleaver is not None:
            vt = self.interleaver.tenant_clock(t.tenant)
        return (vt if vt is not None else 0.0, t.tenant, t.seq)

    def _take_group_locked(self) -> Optional[list]:
        """The oldest pending (kind, key) group once it is ripe:
        every eligible job is accounted for (in THIS group, or
        demonstrably busy with a pending ticket of a different
        bucket — a job mid-flight on another (kind, key) cannot
        contribute here before its own group resolves, so waiting for
        it only adds latency), the bounded delay expired, or the
        coalescer is stopping.  None = keep waiting.  Caller holds
        the lock."""
        if not self._pending:
            return None
        head = min(self._pending, key=lambda t: t.seq)
        grp = []
        busy_elsewhere = set()
        for t in self._pending:
            if (t.kind, t.key) == (head.kind, head.key):
                grp.append(t)
            else:
                busy_elsewhere.add(t.job)
        jobs_in = {t.job for t in grp}
        ripe = (
            self._stopped
            or (jobs_in | busy_elsewhere) >= set(self._jobs)
            or time.monotonic() - head.t_submit >= self.wait_s
        )
        if not ripe:
            return None
        if len(grp) > MAX_GROUP_TICKETS:
            # oldest first; the overflow stays pending and forms the
            # next group (already past its deadline, so no added wait)
            grp = sorted(grp, key=lambda t: t.seq)[:MAX_GROUP_TICKETS]
        drop = set(id(t) for t in grp)
        self._pending = [t for t in self._pending if id(t) not in drop]
        return grp

    def _run(self) -> None:
        while True:
            with self._lock:
                while True:
                    grp = self._take_group_locked()
                    if grp is not None:
                        break
                    if self._stopped and not self._pending:
                        return
                    if not self._pending:
                        # idle: sleep until a submit/deregister/stop
                        # notifies — no polling on a quiet service
                        self._cond.wait()
                        continue
                    # wake at the head ticket's deadline (or on a new
                    # ticket / a deregistration / stop)
                    head = min(self._pending, key=lambda t: t.seq)
                    timeout = max(
                        1e-3,
                        head.t_submit + self.wait_s - time.monotonic(),
                    )
                    self._cond.wait(min(timeout, 0.05))
            self._dispatch_group(grp)

    def _target_device(self, grp: list):
        """The fused dispatch's device: the first alive resident
        handle's pin (so coalescing consumes resident arrays in place),
        else a round-robin pool survivor, else the default device."""
        for t in grp:
            rw = t.payload.get("resident")
            if rw is not None and rw.alive and not isinstance(
                rw.device, str
            ):
                return rw.device
        if self.pool is not None:
            alive = self.pool.alive_devices()
            if alive:
                self._rr += 1
                return alive[self._rr % len(alive)]
        return None

    def _ticket_resident(self, t: _Ticket, device):
        """The ticket's usable resident handle on ``device`` (solo
        validity rules: alive, same pin, same grid), else None — the
        block then re-ships from the host ingest copy."""
        rw = t.payload.get("resident")
        if rw is not None and rw.alive and rw.device is device \
                and rw.g == t.g and rw.gl == t.gl:
            return rw
        return None

    def _resident_cols(self, t: _Ticket, device, put):
        """The five kernel columns for one ticket: the ResidentWindow
        arrays in place when usable, else the grid-padded host copy
        placed fresh (the placement itself books the re-ship in the
        h2d transfer ledger, under the ``batch`` pass bucket)."""
        from adam_tpu.formats import schema
        from adam_tpu.formats.batch import pad_rows_np

        rw = self._ticket_resident(t, device)
        if rw is not None:
            return rw.args()
        # non-resident fallback (the function name carries the
        # residency-rule exemption): the ticket's handle is dead,
        # mismatched or residency is off — the fused block re-ships
        # from the host-retained ingest copy, bitwise the same rows
        b = t.payload["b"]
        host = (
            pad_rows_np(b.bases, t.g, schema.BASE_PAD, cols=t.gl),
            pad_rows_np(b.quals, t.g, schema.QUAL_PAD, cols=t.gl),
            pad_rows_np(b.lengths, t.g, 0),
            pad_rows_np(b.flags, t.g, schema.FLAG_UNMAPPED),
            pad_rows_np(b.read_group_idx, t.g, -1),
        )
        return tuple(put(a) for a in host)

    def warm_fused_executable(self, kind, jitfn, args, statics, key,
                              device) -> None:
        """First-sight prewarm of a fused shape: run the jit on a
        zeros twin of the args under a prewarm scope, so the REAL
        dispatch records a cache hit and ``device.compile.in_window``
        stays 0 on batched runs (the coalescer's analog of the pool's
        first-sight re-prewarm)."""
        from adam_tpu.parallel.device_pool import putter, span_attrs
        from adam_tpu.utils import compile_ledger

        cache_key = (key, compile_ledger.device_cache_key(device))
        with self._lock:
            if cache_key in self._warmed:
                return
            self._warmed.add(cache_key)
        put = putter(device)

        def place(tree):
            if isinstance(tree, tuple):
                return tuple(place(x) for x in tree)
            return put(tree)

        try:
            with self.tracer.span(
                tele.SPAN_POOL_PREWARM_COMPILE, kernel=str(key[0]),
                **span_attrs(device),
            ), compile_ledger.prewarm_scope(), \
                    tele.pass_scope("prewarm"), \
                    compile_ledger.track(key, device):
                jitfn(*(place(_zeros_like_tree(a)) for a in args),
                      **statics)
        except Exception:
            with self._lock:
                self._warmed.discard(cache_key)
            log.warning(
                "fused prewarm of %s failed; the shape compiles at "
                "dispatch instead", key, exc_info=True,
            )

    def _dispatch_group(self, grp: list) -> None:
        """Fuse + dispatch one group; resolve every ticket's future
        (failures fail the whole group — each caller falls back to its
        solo path, which owns eviction/replay)."""
        grp.sort(key=self._wfq_rank)
        kind = grp[0].kind
        # the fan-in span: a fused dispatch serves MANY job traces at
        # once, so instead of claiming one it links every contributing
        # (job, window, trace) — events_for_trace / the gateway /trace
        # surface resolve these links so each job's export crosses the
        # fused-batch boundary (docs/OBSERVABILITY.md "Trace context")
        links = [
            {"job": t.job, "window": t.window, "trace": t.trace}
            for t in grp
        ]
        try:
            faults.point("sched.batch", device=kind)
            # chaos-harness kill point: one arrival per fused dispatch
            faults.point("proc.kill", device="batch")
            with self.tracer.span(
                tele.SPAN_BATCH_FUSED, kind=kind, windows=len(grp),
                links=links,
            ), tele.pass_scope("batch"):
                if kind == "markdup":
                    results, wall = self._fuse_markdup(grp)
                elif kind == "observe":
                    results, wall = self._fuse_observe(grp)
                else:
                    results, wall = self._fuse_apply(grp)
        except BaseException as e:
            self.tracer.count(tele.C_BATCH_FALLBACKS, len(grp))
            log.warning(
                "fused %s dispatch of %d window(s) failed (%s); every "
                "carried job re-dispatches solo", kind, len(grp), e,
            )
            err = CoalesceError(
                f"fused {kind} dispatch failed: {type(e).__name__}: {e}"
            )
            for t in grp:
                t.fut.set_error(err)
            return
        rows_occ = sum(t.n for t in grp)
        rows_disp = sum(t.g for t in grp)
        tr = self.tracer
        tr.count(tele.C_BATCH_DISPATCHES)
        tr.count(tele.C_BATCH_WINDOWS, len(grp))
        tr.count(tele.C_BATCH_ROWS_OCCUPIED, rows_occ)
        tr.count(tele.C_BATCH_ROWS_DISPATCHED, rows_disp)
        tr.observe(tele.H_BATCH_FILL, rows_occ / max(rows_disp, 1))
        tr.gauge(tele.G_BATCH_JOBS, len({t.job for t in grp}))
        if self.quota is not None:
            # the COMPUTE leg of the tenant's budget: each ticket's
            # rows-weighted share of the fused DISPATCH+FETCH wall —
            # the executors time exactly that region, so first-sight
            # compiles (the prewarm above) and host pad/placement prep
            # never bill against a tenant's compute budget.  The byte
            # leg is charged at the grant seam (the scheduler's pacer
            # wrapper charges every window's payload size); the fused
            # h2d books in the transfer ledger's `batch` bucket, never
            # as a second byte charge.
            for t in grp:
                self.quota.charge(
                    t.tenant, compute_s=wall * t.n / max(rows_occ, 1),
                )
        for t, res in zip(grp, results):
            t.fut.set_result(res)

    # ---- the three fused executors -------------------------------------
    def _fuse_markdup(self, grp: list):
        from adam_tpu.parallel.device_pool import putter
        from adam_tpu.utils import compile_ledger
        from adam_tpu.utils import retry as _retry
        from adam_tpu.utils.transfer import device_fetch

        device = self._target_device(grp)
        put = putter(device)
        cols = []
        for t in grp:
            start, end, ops, lens, n_ops = t.payload["fresh"]
            rw = self._ticket_resident(t, device)
            if rw is not None:
                flags = rw.get("flags")
                quals = rw.get("quals")
                lengths = rw.get("lengths")
            else:
                from adam_tpu.formats import schema
                from adam_tpu.formats.batch import pad_rows_np

                b = t.payload["b"]
                flags = put(pad_rows_np(b.flags, t.g,
                                        schema.FLAG_UNMAPPED))
                # adam-tpu: noqa[residency] reason=non-resident fallback: the ticket's handle is dead/mismatched or residency is off — the fused block re-ships from the host ingest copy
                quals = put(pad_rows_np(b.quals, t.g, schema.QUAL_PAD,
                                        cols=t.gl))
                lengths = put(pad_rows_np(b.lengths, t.g, 0))
            per = (put(start), put(end), flags, put(ops), put(lens),
                   put(n_ops), quals, lengths)
            cols.append(per)
        jitfn = fused_jit("markdup")
        key = (
            "batch.markdup",
            tuple((t.g, t.gl, grp[0].key[2]) for t in grp),
        )
        args = (tuple(cols),)
        self.warm_fused_executable(
            "markdup", jitfn, args, {}, key, device
        )

        def dispatch():
            faults.point("device.dispatch", device=device)
            return jitfn(tuple(cols))

        t_d = time.monotonic()
        with compile_ledger.track(key, device):
            five, score = _retry.retry_call(
                dispatch, site="sched.batch.dispatch"
            )
        five = device_fetch(five)
        score = device_fetch(score)
        wall = time.monotonic() - t_d
        self.tracer.count(tele.C_DEVICE_DISPATCHED)
        self.tracer.count(tele.C_DEVICE_FETCHED)
        results = []
        r0 = 0
        for t in grp:
            results.append((
                np.asarray(five[r0:r0 + t.n]),
                np.asarray(score[r0:r0 + t.n]),
            ))
            r0 += t.g
        return results, wall

    def _fuse_observe(self, grp: list):
        from adam_tpu.parallel.device_pool import putter
        from adam_tpu.utils import compile_ledger
        from adam_tpu.utils import retry as _retry
        from adam_tpu.utils.transfer import device_fetch

        device = self._target_device(grp)
        put = putter(device)
        gl = grp[0].gl
        cols = []
        masks = []
        segs = []
        base = 0
        for t in grp:
            cols.append(self._resident_cols(t, device, put))
            res_pk, mm_pk, rok = t.payload["masks"]
            masks.append((put(res_pk), put(mm_pk), put(rok)))
            segs.append((base, t.payload["n_rg"]))
            base += t.payload["n_rg"]
        n_rg_total = base
        jitfn = fused_jit("observe")
        key = (
            "batch.observe",
            tuple((t.g, t.payload["n_rg"]) for t in grp), gl,
        )
        statics = {
            "segs": tuple(segs), "n_rg": n_rg_total, "lmax": gl,
        }
        args = (tuple(cols), tuple(masks))
        self.warm_fused_executable(
            "observe", jitfn, args, statics, key, device
        )

        def dispatch():
            faults.point("device.dispatch", device=device)
            return jitfn(tuple(cols), tuple(masks), **statics)

        t_d = time.monotonic()
        with compile_ledger.track(key, device):
            total, mism = _retry.retry_call(
                dispatch, site="sched.batch.dispatch"
            )
        # ONE compact fetch for the whole group; each job's band is its
        # solo histogram, so the barrier merge stays bit-identical
        total = device_fetch(total)
        mism = device_fetch(mism)
        wall = time.monotonic() - t_d
        self.tracer.count(tele.C_DEVICE_DISPATCHED)
        self.tracer.count(tele.C_DEVICE_FETCHED)
        results = []
        for (b0, nri), t in zip(segs, grp):
            results.append((
                np.ascontiguousarray(total[b0:b0 + nri]),
                np.ascontiguousarray(mism[b0:b0 + nri]),
                gl,
            ))
        return results, wall

    def _fuse_apply(self, grp: list):
        from adam_tpu.ops.colpack import fetch_grid
        from adam_tpu.parallel.device_pool import putter
        from adam_tpu.utils import compile_ledger
        from adam_tpu.utils import retry as _retry
        from adam_tpu.utils.transfer import device_fetch

        device = self._target_device(grp)
        put = putter(device)
        gl = grp[0].gl
        pack = bool(grp[0].key[2])
        # fused table: every job's solved table centered into the
        # widest cycle axis (exactly merge_observations' centering, so
        # each job's gathers land on its own cells), concatenated on
        # the read-group axis in JOB-SORTED order — the band layout is
        # independent of the WFQ row order, so the placement cache
        # below hits across dispatches of the same job set
        job_tables = {
            j: tb for j, tb in sorted(
                {t.job: t.payload["table"] for t in grp}.items()
            )
        }
        n_cyc = max(tb.shape[2] for tb in job_tables.values())
        cache_key = (
            tuple((j, id(tb)) for j, tb in job_tables.items()),
            n_cyc, compile_ledger.device_cache_key(device),
        )
        cached = self._table_cache.get(cache_key)
        if cached is not None:
            _tables, table_dev, bands = cached
        else:
            tparts = []
            bands = {}
            base = 0
            for j, tbl in job_tables.items():
                off = (n_cyc - tbl.shape[2]) // 2
                wide = tbl
                if off:
                    wide = np.zeros(
                        (tbl.shape[0], tbl.shape[1], n_cyc,
                         tbl.shape[3]),
                        np.uint8,
                    )
                    wide[:, :, off:off + tbl.shape[2], :] = tbl
                tparts.append(wide)
                bands[j] = (base, tbl.shape[0])
                base += tbl.shape[0]
            fused_table = np.ascontiguousarray(
                np.concatenate(tparts, axis=0)
            )
            with tele.pass_scope("table"):
                table_dev = put(fused_table)
            if len(self._table_cache) >= 8:
                self._table_cache.clear()
            self._table_cache[cache_key] = (
                tuple(job_tables.values()), table_dev, bands,
            )
        segs = [bands[t.job] for t in grp]
        cols = []
        extras = []
        for t in grp:
            cols.append(self._resident_cols(t, device, put))
            hq, vd = t.payload["extras"]
            extras.append((put(hq), put(vd)))
        size = sum(t.g for t in grp) * gl if pack else 0
        jitfn = fused_jit("apply")
        key = (
            "batch.apply",
            tuple((t.g, t.payload["table"].shape[0]) for t in grp),
            gl, n_cyc, pack,
        )
        statics = {"segs": tuple(segs), "lmax": gl, "pack_size": size}
        args = (tuple(cols), tuple(extras), table_dev)
        self.warm_fused_executable(
            "apply", jitfn, args, statics, key, device
        )

        def dispatch():
            faults.point("device.dispatch", device=device)
            return jitfn(tuple(cols), tuple(extras), table_dev,
                         **statics)

        t_d = time.monotonic()
        with compile_ledger.track(key, device):
            out = _retry.retry_call(dispatch, site="sched.batch.dispatch")
        self.tracer.count(tele.C_DEVICE_DISPATCHED)
        results = []
        if pack:
            totals = [int(t.payload["pack_lens"].sum()) for t in grp]
            cut = min(size, fetch_grid(sum(totals))) if size else 0
            payload = device_fetch(out[:cut])
            self.tracer.count(tele.C_DEVICE_FETCHED)
            off = 0
            for t, total_t in zip(grp, totals):
                # the per-job packed-column payload split: the fused
                # pack's byte ranges are exact prefix sums, so this
                # slice IS the job's solo packed payload
                sl = np.ascontiguousarray(payload[off:off + total_t])
                off += total_t
                results.append((
                    t.payload["ds"], t.payload["b"],
                    ("packed", [(sl, total_t)], t.payload["pack_lens"]),
                ))
        else:
            new_q = device_fetch(out)
            self.tracer.count(tele.C_DEVICE_FETCHED)
            r0 = 0
            for t in grp:
                b = t.payload["b"]
                results.append((
                    t.payload["ds"], b,
                    np.ascontiguousarray(
                        new_q[r0:r0 + t.n, :b.lmax]
                    ),
                ))
                r0 += t.g
        return results, time.monotonic() - t_d
