"""Nested-schema flattening (util/Flattener.scala + Flatten command).

The reference flattens nested Avro records into dotted-name flat columns
so SQL engines (Impala) can query them (``Flattener.flattenSchema`` /
``flattenRecord``). The columnar port works on Arrow tables: struct
columns expand (recursively) to ``parent__child`` columns — the
reference uses ``__`` as its separator too (Flattener.scala NAME_SEPARATOR).
List columns have no flat relational form and are JSON-stringified.
"""

from __future__ import annotations

import json

import pyarrow as pa
import pyarrow.parquet as pq

SEPARATOR = "__"


def flatten_table(table: pa.Table) -> pa.Table:
    # expand struct columns one level at a time until none remain; only
    # the child columns produced by the expansion get the `__` separator
    # (literal dots in pre-existing column names are left alone), and a
    # flattened name colliding with an existing column is an error rather
    # than a silently dropped column
    while any(pa.types.is_struct(f.type) for f in table.schema):
        cols, names = [], []
        for field, col in zip(table.schema, table.columns):
            if pa.types.is_struct(field.type):
                chunked = col.combine_chunks()
                for child_field, child in zip(
                    field.type, chunked.flatten()
                ):
                    cols.append(child)
                    names.append(f"{field.name}{SEPARATOR}{child_field.name}")
            else:
                cols.append(col)
                names.append(field.name)
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(
                f"flattening collides with existing columns: {sorted(dupes)}"
            )
        table = pa.Table.from_arrays(cols, names=names)
    cols, names = [], []
    for name, col in zip(table.column_names, table.columns):
        if pa.types.is_list(col.type) or pa.types.is_large_list(col.type):
            col = pa.array(
                [None if v is None else json.dumps(v) for v in col.to_pylist()],
                pa.string(),
            )
        cols.append(col)
        names.append(name)
    return pa.Table.from_arrays(
        [pa.array(c) if not isinstance(c, (pa.Array, pa.ChunkedArray)) else c
         for c in cols],
        names=names,
    )


def flatten_parquet(in_path: str, out_path: str,
                    compression: str = "zstd") -> None:
    table = pq.read_table(in_path)
    meta = table.schema.metadata
    flat = flatten_table(table)
    if meta:
        flat = flat.replace_schema_metadata(meta)
    from adam_tpu.io.parquet import parquet_codec_kw

    pq.write_table(flat, out_path, **parquet_codec_kw(compression))
