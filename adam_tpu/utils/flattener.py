"""Nested-schema flattening (util/Flattener.scala + Flatten command).

The reference flattens nested Avro records into dotted-name flat columns
so SQL engines (Impala) can query them (``Flattener.flattenSchema`` /
``flattenRecord``). The columnar port works on Arrow tables: struct
columns expand (recursively) to ``parent__child`` columns — the
reference uses ``__`` as its separator too (Flattener.scala NAME_SEPARATOR).
List columns have no flat relational form and are JSON-stringified.
"""

from __future__ import annotations

import json

import pyarrow as pa
import pyarrow.parquet as pq

SEPARATOR = "__"


def flatten_table(table: pa.Table) -> pa.Table:
    # expand struct columns one level at a time until none remain;
    # pyarrow's Table.flatten already names children parent.child — rename
    # to the reference's `__` separator afterwards
    while any(pa.types.is_struct(f.type) for f in table.schema):
        table = table.flatten()
        table = table.rename_columns(
            [c.replace(".", SEPARATOR) for c in table.column_names]
        )
    cols, names = [], []
    for name, col in zip(table.column_names, table.columns):
        if pa.types.is_list(col.type) or pa.types.is_large_list(col.type):
            col = pa.array(
                [None if v is None else json.dumps(v) for v in col.to_pylist()],
                pa.string(),
            )
        cols.append(col)
        names.append(name)
    return pa.table(dict(zip(names, cols)))


def flatten_parquet(in_path: str, out_path: str,
                    compression: str = "snappy") -> None:
    table = pq.read_table(in_path)
    meta = table.schema.metadata
    flat = flatten_table(table)
    if meta:
        flat = flat.replace_schema_metadata(meta)
    pq.write_table(flat, out_path, compression=compression)
