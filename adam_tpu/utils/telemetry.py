"""Structured tracing + metrics: spans, counters, gauges, histograms,
flight recorder, live heartbeat.

The observability layer the reference gets from bdg-utils ``Metrics`` +
Spark's listener-decomposed stage/task timings
(``instrumentation/Timers.scala:25-81``, ``ADAMCommand.scala:56-89``),
built for the overlapped streamed pipeline: flat named timers
(:mod:`adam_tpu.utils.instrumentation`, which this module subsumes)
cannot show queue depths, per-window latency, or where the
tokenize/dispatch/fetch/encode/write overlap breaks down.

Three primitives, one lock discipline (the ``TimerRegistry`` one —
single mutex, read-modify-write only under it):

* **spans** — ``with TRACE.span("bqsr.apply.dispatch", window=i):``
  records a timestamped interval with thread and parent attribution
  into (a) a per-name aggregate (count, total ns) and (b) a bounded
  in-memory **flight recorder** (ring buffer — long runs cannot OOM;
  evictions keep the newest events and are counted).
* **counters** — monotonically accumulated ints (reads ingested, bytes
  encoded/written, device windows dispatched/fetched).
* **gauges** — sampled values with last/min/max/n (writer-pool queue
  depth at submit/drain, device dispatch in-flight).
* **histograms** — ``Tracer.observe(name, value)`` accumulates into
  fixed log-spaced buckets (:data:`HIST_BUCKETS_PER_DECADE` per decade
  — shared global edges, so per-host/per-run merges are associative),
  and every span name additionally gets an **automatic duration
  histogram** (seconds) — scalar span totals answer "how much", the
  quantiles (p50/p90/p99 in ``snapshot()``/``report()``) answer "is
  the tail why the barrier stalls" (Dean & Barroso, The Tail at
  Scale: synchronized multi-device pipelines are governed by tail
  latency, not means).

Exports: :meth:`Tracer.to_json` (the ``--metrics-json`` snapshot, whose
``timers`` section is byte-identical to the ``-print_metrics`` table)
and :meth:`Tracer.to_chrome_trace` (the ``--trace-out`` view — complete
events on per-thread tracks, loadable in chrome://tracing / Perfetto,
so the streamed overlap is visually inspectable).

Disabled-by-default cost is one branch per call site: ``span()``
returns a shared no-op context manager and ``count()``/``gauge()``
return immediately when ``recording`` is off (micro-benchmark in
docs/OBSERVABILITY.md).  The streamed pipeline records its stage spans
into a private always-on :class:`Tracer` (a handful of events per
window) and derives its ``stats`` dict from them via
:func:`streamed_stats_view`, so the dict and the span data can never
disagree; the run tracer is absorbed into the global :data:`TRACE`
when recording is on.

Every span/counter/gauge name is declared here (the ``_span``/
``_metric`` registrations below) — a **stable contract** documented in
docs/OBSERVABILITY.md and lint-enforced by
``scripts/check-telemetry-names``.
"""

from __future__ import annotations

import json
import math
import os
import re
import sys
import threading
import time
from collections import deque

# One process-wide trace epoch so timestamps from every Tracer (the
# global TRACE, streamed run tracers, absorbed events) land on a single
# comparable time axis in the Chrome-trace export.
_EPOCH_NS = time.monotonic_ns()

# --------------------------------------------------------------------------
# Name registry — the stable contract (docs/OBSERVABILITY.md)
# --------------------------------------------------------------------------
_REGISTERED_SPANS: set = set()
_REGISTERED_METRICS: set = set()


def _span(name: str) -> str:
    _REGISTERED_SPANS.add(name)
    return name


def _metric(name: str) -> str:
    _REGISTERED_METRICS.add(name)
    return name


# ---- streamed-pipeline stage spans (pipelines/streamed.py; the stats
# dict keys derive from these via streamed_stats_view) ----
SPAN_PASS_A = _span("streamed.pass_a.ingest")
SPAN_TOKENIZE = _span("streamed.tokenize")
SPAN_MD_FETCH = _span("streamed.markdup.fetch")
SPAN_RESOLVE = _span("streamed.barrier.resolve")
SPAN_SPLIT = _span("streamed.pass_b.split")
SPAN_OBSERVE = _span("streamed.observe")
SPAN_TAIL = _span("streamed.tail")
SPAN_OBS_MERGE = _span("streamed.observe.merge_fetch")
SPAN_SOLVE = _span("streamed.barrier.solve")
SPAN_PASS_C = _span("streamed.pass_c")
SPAN_APPLY_DISPATCH = _span("streamed.apply.dispatch")
SPAN_APPLY_FETCH = _span("streamed.apply.fetch")
SPAN_WRITE_WAIT = _span("streamed.write_wait")
SPAN_TOTAL = _span("streamed.total")

# ---- per-call spans with backend attribution (pipelines/bqsr.py,
# pipelines/markdup.py) ----
SPAN_BQSR_OBSERVE = _span("bqsr.observe.window")
SPAN_BQSR_APPLY_DISPATCH = _span("bqsr.apply.dispatch")
SPAN_BQSR_APPLY_FETCH = _span("bqsr.apply.fetch")
SPAN_BQSR_APPLY_HOST = _span("bqsr.apply.host")
SPAN_MD_COLUMNS = _span("markdup.columns.dispatch")
# the megakernel tier (PR 18): one fused B→C dispatch per window when
# the recalibration table is known up front; the gauges record the
# tier decision (streamed.fused_bc 1/0) and the resolved kernel
# backend (kernel.backend 0=xla 1=pallas) once per run
SPAN_FUSED_BC = _span("bqsr.fused_bc")
G_FUSED_BC = _metric("streamed.fused_bc")
G_KERNEL_BACKEND = _metric("kernel.backend")
C_FUSED_DISPATCHED = _metric("device.windows.fused")

# ---- device pool (parallel/device_pool.py): multi-chip round-robin
# dispatch + per-device compile prewarm.  Dispatch/fetch spans carry a
# ``device=<k>`` attribution (the jax device id), which (a) aggregates
# into the snapshot's ``device_spans`` section (per-chip occupancy/
# skew) and (b) mirrors onto a per-chip ``device:<k>`` track in the
# Chrome-trace export.  The prewarm records one WALL umbrella span per
# run (concurrent per-compile spans sum past wall, so the derived
# ``prewarm_s`` comes from the umbrella) plus one compile span per
# (kernel shape, device). ----
SPAN_POOL_PREWARM = _span("device.pool.prewarm")
SPAN_POOL_PREWARM_C = _span("device.pool.prewarm.pass_c")
SPAN_POOL_PREWARM_COMPILE = _span("device.pool.prewarm.compile")
# ---- resilience (utils/faults.py, utils/retry.py, the streamed
# recovery paths): one ``device.pool.replay`` span per window whose
# device work was replayed on a survivor (or the host backend) after a
# failure, with ``device=<k>`` naming the chip that FAILED. ----
SPAN_POOL_REPLAY = _span("device.pool.replay")

# ---- multi-job transform service (adam_tpu/serve): one umbrella span
# per job run attempt on the global TRACE, ``job=<id>`` + ``tenant=``
# attributed — the SLO view of how long each tenant's job actually held
# a slot, resumed attempts included. ----
SPAN_SCHED_JOB = _span("sched.job.run")

# ---- job-scoped distributed traces (docs/OBSERVABILITY.md "Trace
# context").  One span per gateway admission, ``job=`` + ``trace=``
# attributed — the root of a job's trace (submit -> fused dispatch ->
# part write).  One span per FUSED coalescer dispatch
# (serve/batching.py) whose ``links`` arg names every contributing
# ticket's {job, window, trace} — the fan-in edge that lets a per-job
# trace export cross the fused-batch boundary. ----
SPAN_GW_SUBMIT = _span("gateway.job.submit")
SPAN_BATCH_FUSED = _span("sched.batch.fused")

# ---- barrier-2 per-fetch spans (pipelines/bqsr.merge_observations):
# one per device-resident observe histogram fetched at the merge
# barrier, ``device=<k>`` + ``window=<i>`` attributed — whether the n
# fetches serialize on the host thread (the ROADMAP "observe-fetch
# serialization" item) is directly readable off these spans' start
# timestamps in a trace. ----
SPAN_OBS_FETCH = _span("device.fetch.observe")

# ---- io/parquet.py part-writer spans ----
SPAN_PART_ENCODE = _span("parquet.part.encode")
SPAN_PART_WRITE = _span("parquet.part.write")

# ---- native tokenizer/codec spans share the timer-table names
# (native/__init__.py records each dispatch as BOTH a timer row and a
# span, so the flight recorder sees the codec work on its thread) ----
from adam_tpu.utils import instrumentation as _ins  # noqa: E402

for _n in (
    _ins.TOKENIZE_INPUT, _ins.BGZF_CODEC, _ins.PARQUET_ENCODE,
    _ins.PARQUET_WRITE, _ins.SAM_ENCODE, _ins.FASTQ_ENCODE,
    _ins.OBSERVE_WALK, _ins.APPLY_WALK,
):
    _span(_n)

# ---- counters ----
C_READS_INGESTED = _metric("reads.ingested")
C_WINDOWS_INGESTED = _metric("windows.ingested")
C_DEVICE_DISPATCHED = _metric("device.windows.dispatched")
C_DEVICE_FETCHED = _metric("device.windows.fetched")
C_BYTES_ENCODED = _metric("parquet.bytes.encoded")
C_BYTES_WRITTEN = _metric("parquet.bytes.written")
C_PARTS_WRITTEN = _metric("parquet.parts.written")
# part-encode byte accounting (io/parquet._count_encode_bytes):
# bytes_in = the decoded column payload entering a part encode (batch
# matrices + sidecar string buffers, the qual matrix replaced by the
# device-packed payload when pass C shipped one), bytes_out = the
# assembled arrow table handed to the writer.  Together they make the
# packed-column encode shrink directly visible in --metrics-json
# snapshots, and `adam-tpu analyze` prints the in->out->disk ratio in
# its write-tail decomposition.
C_ENCODE_BYTES_IN = _metric("parquet.encode.bytes_in")
C_ENCODE_BYTES_OUT = _metric("parquet.encode.bytes_out")
C_CANDIDATE_ROWS = _metric("realign.candidate_rows")
C_POOL_PREWARM_COMPILES = _metric("device.pool.prewarm.compiles")
# resilience counters: injected faults (utils/faults.point), retry
# attempts actually taken (utils/retry.retry_call — 0 on a clean run),
# and devices evicted from the pool after a spent retry budget
C_FAULT_INJECTED = _metric("fault.injected")
C_RETRY_ATTEMPTS = _metric("retry.attempts")
C_DEVICE_EVICTED = _metric("device.evicted")
# durable-resume counters (pipelines/checkpoint.RunJournal +
# pipelines/streamed.py --run-dir/--resume; docs/ROBUSTNESS.md "Durable
# window-granular resume"): output windows skipped because the journal
# records their part as durably published, persisted pass-B observe
# histograms reloaded instead of recomputed, and resumes REFUSED
# (fingerprint mismatch / torn journal → clean restart, never mixed
# output).  All zero on a fresh run.
C_RESUME_WINDOWS_SKIPPED = _metric("resume.windows_skipped")
C_RESUME_HISTOGRAMS_LOADED = _metric("resume.histograms_loaded")
C_RESUME_REFUSED = _metric("resume.refused")
# mesh execution mode (--partitioner mesh; parallel/partitioner.py):
# collective dispatches actually run on the batch mesh (observe/apply/
# markdup windows), and degradations — a mesh failure that dropped the
# run back to the pool path (windows folded into a suspect accumulator
# replay through the pool/host observe, bit-identically)
C_MESH_DISPATCHED = _metric("device.mesh.dispatched")
C_MESH_DEGRADED = _metric("device.mesh.degraded")
# multi-job transform service (adam_tpu/serve; docs/ROBUSTNESS.md
# "Fault-isolated multi-job scheduling"): admissions accepted, typed
# Busy rejections (capacity / draining — never an exception, never an
# unbounded queue), jobs quarantined after a spent job-retry budget,
# jobs interrupted at a window boundary by a graceful drain, and
# incomplete jobs resumed by the whole-process crash-recovery scan.
C_SCHED_ADMITTED = _metric("sched.jobs.admitted")
C_SCHED_REJECTED = _metric("sched.jobs.rejected")
C_SCHED_QUARANTINED = _metric("sched.jobs.quarantined")
C_SCHED_INTERRUPTED = _metric("sched.jobs.interrupted")
C_SCHED_RECOVERED = _metric("sched.jobs.recovered")
# HTTP gateway (adam_tpu/gateway; docs/SERVING.md): requests served
# (every method/route, errors included), typed back-pressure responses
# actually sent (429 capacity / 503 draining-or-transient — the wire
# twin of sched.jobs.rejected), and response payload bytes that left
# the process (part-fetch chunks + event-stream lines; headers
# excluded).  The per-request wall lands in the
# ``gateway.request.seconds`` histogram below.
C_GW_REQUESTS = _metric("gateway.requests")
C_GW_BUSY = _metric("gateway.busy")
C_GW_BYTES_OUT = _metric("gateway.bytes_out")
# cross-job window batching (adam_tpu/serve/batching.py; docs/SERVING.md
# "Continuous batching & quotas"): fused device dispatches actually
# issued by the coalescer, the per-job windows they carried (windows /
# dispatches is the dispatches-saved ratio `adam-tpu analyze` prints),
# real rows occupied vs grid rows dispatched (their running ratio is
# the heartbeat's `batch_fill`), and windows that FELL BACK to their
# job's solo dispatch path (a fused-dispatch failure isolates to the
# tickets it carried; each job re-dispatches alone, byte-identically).
C_BATCH_DISPATCHES = _metric("sched.batch.dispatches")
C_BATCH_WINDOWS = _metric("sched.batch.windows")
C_BATCH_ROWS_OCCUPIED = _metric("sched.batch.rows_occupied")
C_BATCH_ROWS_DISPATCHED = _metric("sched.batch.rows_dispatched")
C_BATCH_FALLBACKS = _metric("sched.batch.fallbacks")
# per-tenant quota enforcement (adam_tpu/serve/quota.py): submissions
# refused with the typed `Busy(kind="quota")` — the gateway's 429
# quota leg, distinct from the capacity leg
C_QUOTA_REJECTED = _metric("sched.quota.rejected")
# mid-run quota throttle (serve/quota.QuotaManager.throttle): grants
# deferred at the pacer seam because the tenant's rolling window was
# over budget — the smooth edge between "admitted" and the 429 leg
C_QUOTA_DEFERRED = _metric("sched.quota.deferred")

# ---- device health / hedged dispatch / SDC audit (utils/health.py,
# docs/ROBUSTNESS.md "Device health, hedging, and SDC audit").
# Scoreboard transitions: healthy->suspect demotions, entries into
# probation (placement-excluded; includes audit quarantines),
# re-admissions after a passing known-answer probe, and probes that
# FAILED (probation -> evicted).  Hedge counters: speculative
# re-dispatches launched when an in-flight window exceeded
# ADAM_TPU_HEDGE_FACTOR x the kernel's observed p99, the subset whose
# result was actually used (won), and the subset discarded because the
# primary finished first (wasted) — fired == won + wasted.  Audit
# counters: windows sampled for dual-compute (ADAM_TPU_AUDIT_RATE) and
# bit-compare mismatches caught (each one quarantines the producing
# device and replays the window from the host copy). ----
C_HEALTH_DEMOTED = _metric("device.health.demoted")
C_HEALTH_PROBATION = _metric("device.health.probation")
C_HEALTH_READMITTED = _metric("device.health.readmitted")
C_HEALTH_PROBE_FAILED = _metric("device.health.probe_failed")
C_HEDGE_FIRED = _metric("device.hedge.fired")
C_HEDGE_WON = _metric("device.hedge.won")
C_HEDGE_WASTED = _metric("device.hedge.wasted")
C_AUDIT_SAMPLED = _metric("device.audit.sampled")
C_AUDIT_MISMATCH = _metric("device.audit.mismatch")
# one span per SDC dual-compute comparison (pipelines/streamed.py
# _audit_result), ``device=`` + ``window=`` attributed — an incident
# bundle's embedded trace shows the audit interval itself next to the
# dispatch/fetch spans of the window it checked
SPAN_AUDIT_CHECK = _span("device.audit.check")

# ---- incident recorder (utils/incidents.py; docs/OBSERVABILITY.md
# "Incident bundles"): bundles actually written (trigger-cooldowns and
# the bounded-count prune mean this can lag the trigger counters), and
# ``/metrics`` scrapes served by the gateway — the heartbeat's
# ``metrics_scrapes`` field, so `adam-tpu top` can show whether a
# scraper is actually reaching the process. ----
C_INCIDENT_RECORDED = _metric("incident.recorded")
C_GW_SCRAPES = _metric("gateway.metrics.scrapes")

# ---- SLO engine + perf sentinel (utils/slo.py, utils/perfledger.py;
# docs/OBSERVABILITY.md "SLOs and error budgets" / "The perf ledger"):
# the judgment layer.  ``slo.worst_burn`` is the worst short-window
# error-budget burn rate across armed objectives (1.0 = spending
# exactly on objective), ``slo.budget_remaining`` the smallest
# remaining budget fraction; ``slo.breaches`` counts corroborated
# fast-burn crossings (each also fires the ``slo.burn`` incident
# trigger), and ``perf.regressions`` counts direction-aware perf keys
# the ledger sentinel flagged vs its rolling median baseline. ----
C_SLO_BREACHES = _metric("slo.breaches")
C_PERF_REGRESSIONS = _metric("perf.regressions")
G_SLO_WORST_BURN = _metric("slo.worst_burn")
G_SLO_BUDGET_REMAINING = _metric("slo.budget_remaining")

# ---- gauges ----
G_POOL_DEPTH = _metric("parquet.pool.queue_depth")
# the writer pool's LIVE admission bound (parts allowed in flight):
# starts at the construction inflight_parts and grows one part at a
# time while submits measurably gate (adaptive sizing, bounded by the
# scheduling affinity) — a run whose last value exceeds its first was
# writer-bound long enough for the pool to widen itself
G_POOL_BOUND = _metric("parquet.pool.inflight_bound")
G_DEVICE_INFLIGHT = _metric("device.dispatch.in_flight")
G_OBSERVE_HIDDEN = _metric("streamed.observe_overlap_hidden")
G_POOL_DEVICES = _metric("device.pool.devices")
# 1 when the barrier-1 duplicate-resolve lexsort ran as the device sort
# of the packed summary keys (parallel/dist.device_lexsort), 0 when it
# ran host-side — `adam-tpu analyze` labels the resolve stage with it
G_RESOLVE_DEVICE_SORT = _metric("streamed.resolve.device_sort")
# live job-slot occupancy of the multi-job scheduler (adam_tpu/serve)
G_SCHED_ACTIVE = _metric("sched.jobs.active")
# distinct jobs the coalescer's LAST fused dispatch carried (the
# heartbeat's `batched_jobs` field; 1 = batching on but traffic too
# sparse to coalesce)
G_BATCH_JOBS = _metric("sched.batch.jobs")

# ---- device ledger: tunnel byte accounting (utils/transfer.py +
# parallel/device_pool.py).  Counters carry the run totals; the
# per-direction throughput histograms (bytes/second, the shared fixed
# log-spaced buckets) answer whether the link itself — not the host —
# is the wall; the snapshot's ``transfers`` section attributes
# count/bytes/seconds per device AND per pipeline pass (a/observe/
# apply/sweep/prewarm via :func:`pass_scope`). ----
C_H2D_BYTES = _metric("device.h2d.bytes")
C_D2H_BYTES = _metric("device.d2h.bytes")
H_H2D_BPS = _metric("device.h2d.bps")
H_D2H_BPS = _metric("device.d2h.bps")

# ---- device-resident windows (parallel/device_pool.ResidentWindow,
# docs/PERF.md "Device-resident windows"): each window's bases/quals
# land on device once at ingest (the ``ingest`` pass bucket in the
# transfers section) and stay resident through markdup -> observe ->
# apply.  Counters: windows placed resident / total bytes placed /
# refcounted releases after pass C / handles dropped by an eviction or
# mesh degradation (their windows re-ship from the host ingest copy).
# The gauge tracks live resident bytes — back to 0 at run end, the
# no-HBM-growth invariant tests/test_resident.py asserts. ----
C_RESIDENT_WINDOWS = _metric("device.resident.windows")
C_RESIDENT_BYTES = _metric("device.resident.bytes")
C_RESIDENT_RELEASED = _metric("device.resident.released")
C_RESIDENT_EVICTED = _metric("device.resident.evicted")
G_RESIDENT_LIVE = _metric("device.resident.live_bytes")

# ---- compile ledger (utils/compile_ledger.py wraps every streamed jit
# dispatch site): per-dispatch executable-cache hit/miss accounting
# keyed by (kernel, grid shape, device).  A miss's duration is the
# dispatch WALL of the call that compiled (trace+compile dominate it);
# misses recorded outside a prewarm scope are cold compiles that landed
# INSIDE a timed window — the direct measurement of the PERF.md
# "prewarm coverage boundary".  Entries land in the snapshot's
# ``compiles`` section; the analyzer flags the in-window subset. ----
C_COMPILE_HITS = _metric("device.compile.cache_hits")
C_COMPILE_MISSES = _metric("device.compile.cache_misses")
C_COMPILE_IN_WINDOW = _metric("device.compile.in_window")
H_COMPILE_SECONDS = _metric("device.compile.seconds")

# ---- HBM footprint (device.memory_stats(), sampled per heartbeat
# tick; per-device last/peak live in the snapshot's ``hbm`` section —
# this gauge is the cross-device total for the printed table) ----
G_HBM_IN_USE = _metric("device.hbm.bytes_in_use")

# ---- histograms (explicit observe() sites; every span name also gets
# an automatic duration histogram under its own name, in seconds) ----
H_FETCH_SECONDS = _metric("device.fetch.seconds")
H_POOL_SUBMIT_WAIT = _metric("parquet.pool.submit_wait")
# end-to-end gateway request wall (accept -> last byte written),
# streaming requests included — the service-side latency SLO view
H_GW_REQUEST_SECONDS = _metric("gateway.request.seconds")
# per-fused-dispatch grid fill (rows occupied / rows dispatched, in
# (0, 1]): the coalescer's fill/latency tradeoff rendered as a
# distribution — `adam-tpu analyze` prints its quantiles in the
# Batching section
H_BATCH_FILL = _metric("sched.batch.fill")

#: Device-only metrics: the paired-CPU bench baseline zeroes these
#: instead of omitting them so round-over-round diffs are key-stable.
DEVICE_ONLY_COUNTERS = frozenset({
    C_DEVICE_DISPATCHED, C_DEVICE_FETCHED, C_POOL_PREWARM_COMPILES,
    C_H2D_BYTES, C_D2H_BYTES,
    C_COMPILE_HITS, C_COMPILE_MISSES, C_COMPILE_IN_WINDOW,
    C_MESH_DISPATCHED, C_MESH_DEGRADED,
})
DEVICE_ONLY_GAUGES = frozenset({G_DEVICE_INFLIGHT, G_POOL_DEVICES})
DEVICE_ONLY_HISTOGRAMS = frozenset(
    {H_FETCH_SECONDS, H_H2D_BPS, H_D2H_BPS, H_COMPILE_SECONDS}
)


def registered_spans() -> frozenset:
    return frozenset(_REGISTERED_SPANS)


def registered_metrics() -> frozenset:
    return frozenset(_REGISTERED_METRICS)


def registered_names() -> frozenset:
    """Every declared span/counter/gauge name — the contract the
    ``scripts/check-telemetry-names`` lint enforces against call-site
    string literals."""
    return frozenset(_REGISTERED_SPANS | _REGISTERED_METRICS)


# --------------------------------------------------------------------------
# Histograms: fixed log-spaced buckets, shared by every histogram
# --------------------------------------------------------------------------
#: Bucket resolution: 4 buckets per decade — bucket ``i`` spans
#: ``[10^(i/4), 10^((i+1)/4))``.  The edges are GLOBAL and fixed (never
#: derived from the data), so merging two histograms is a plain
#: bucket-count sum: associative and commutative across runs, hosts and
#: absorb() calls.
HIST_BUCKETS_PER_DECADE = 4

#: Values at or below this clamp into the lowest bucket (durations are
#: nonnegative; sub-picosecond observations carry no signal).
_HIST_MIN_VALUE = 1e-12


def format_bytes(v) -> str:
    """Human-readable byte count (shared by the analyzer report and
    the ``adam-tpu top`` dashboard); ``"-"`` for non-numbers."""
    if not isinstance(v, (int, float)):
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024.0 or unit == "TiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024.0


def hist_bucket_index(value: float) -> int:
    """The fixed log-spaced bucket a value falls in."""
    v = max(float(value), _HIST_MIN_VALUE)
    return math.floor(math.log10(v) * HIST_BUCKETS_PER_DECADE)


def hist_bucket_bounds(index: int) -> tuple:
    """``[lo, hi)`` edges of bucket ``index``."""
    return (
        10.0 ** (index / HIST_BUCKETS_PER_DECADE),
        10.0 ** ((index + 1) / HIST_BUCKETS_PER_DECADE),
    )


def _new_hist() -> dict:
    return {"count": 0, "sum": 0.0, "min": None, "max": None, "buckets": {}}


def _hist_observe(h: dict, value: float) -> None:
    """Accumulate one observation (caller holds the tracer lock)."""
    v = float(value)
    h["count"] += 1
    h["sum"] += v
    if h["min"] is None or v < h["min"]:
        h["min"] = v
    if h["max"] is None or v > h["max"]:
        h["max"] = v
    idx = hist_bucket_index(v)
    b = h["buckets"]
    b[idx] = b.get(idx, 0) + 1


def _hist_quantile(h: dict, q: float) -> float | None:
    """Quantile estimate from the bucket counts: walk to the bucket
    holding rank ``q * count`` and return its geometric midpoint,
    clamped to the observed [min, max] so single-sample histograms
    report the sample, not a bucket edge."""
    if not h["count"]:
        return None
    target = q * h["count"]
    acc = 0
    # JSON round-trips turn bucket keys into strings; accept both
    items = sorted((int(k), v) for k, v in h["buckets"].items())
    for idx, n in items:
        acc += n
        if acc >= target:
            mid = 10.0 ** ((idx + 0.5) / HIST_BUCKETS_PER_DECADE)
            lo = h["min"] if h["min"] is not None else mid
            hi = h["max"] if h["max"] is not None else mid
            return min(max(mid, lo), hi)
    return h["max"]


def hist_summary(h: dict) -> dict:
    """Snapshot form of one histogram: scalars + p50/p90/p99 + the
    (string-keyed, JSON-safe) sparse bucket counts that make merges
    across snapshots possible."""
    return {
        "count": h["count"],
        "sum": h["sum"],
        "min": h["min"],
        "max": h["max"],
        "p50": _hist_quantile(h, 0.50),
        "p90": _hist_quantile(h, 0.90),
        "p99": _hist_quantile(h, 0.99),
        "buckets": {str(k): v for k, v in h["buckets"].items()},
    }


def merge_histograms(a: dict, b: dict) -> dict:
    """Merge two histograms in snapshot form (fixed global edges make
    this a plain bucket sum — associative, so per-host merge order
    cannot change the result)."""
    out = _new_hist()
    for h in (a, b):
        if not h or not h.get("count"):
            continue
        out["count"] += h["count"]
        out["sum"] += h["sum"]
        for bound, pick in (("min", min), ("max", max)):
            v = h.get(bound)
            if v is not None:
                out[bound] = v if out[bound] is None else pick(out[bound], v)
        for k, n in h.get("buckets", {}).items():
            k = int(k)
            out["buckets"][k] = out["buckets"].get(k, 0) + n
    return hist_summary(out)


# --------------------------------------------------------------------------
# Transfer pass attribution
# --------------------------------------------------------------------------
# Thread-local pipeline-pass scope: the streamed pipeline enters
# pass_scope("a"/"observe"/"apply"/"sweep") around each pass's dispatch/
# fetch sites, so the transfer ledger can attribute tunnel bytes per
# pass without threading a label through the bqsr/markdup/transfer
# APIs (the same shape as device_pool's replay_scope).
_PASS_TLS = threading.local()

#: The bucket transfers land in when no pass scope is active (library
#: calls, the monolithic pipeline, tests).
PASS_OTHER = "other"


class pass_scope:
    """Marks the current thread as inside one streamed pipeline pass
    for transfer attribution (reentrant; inner scopes shadow outer)."""

    def __init__(self, name: str):
        self._name = name

    def __enter__(self):
        stack = getattr(_PASS_TLS, "stack", None)
        if stack is None:
            stack = _PASS_TLS.stack = []
        stack.append(self._name)
        return self

    def __exit__(self, *exc):
        _PASS_TLS.stack.pop()
        return False


def current_pass() -> str | None:
    """The innermost active :class:`pass_scope` name, or None."""
    stack = getattr(_PASS_TLS, "stack", None)
    return stack[-1] if stack else None


# --------------------------------------------------------------------------
# Trace context — job-scoped distributed traces
# --------------------------------------------------------------------------
# A trace context is one hex trace_id minted at job submission (the
# gateway, the scheduler, or transform_streamed itself for solo runs),
# persisted in JOB.json so recovery replays keep the SAME id, and
# attached to every span recorded while it is in scope.  Two carriers,
# by design (the Dapper model, adapted to the in-process pool):
#
# * :class:`trace_scope` — thread-local, for code running ON a thread
#   that belongs to one job (the pass_scope shape; helper threads must
#   re-enter it explicitly, exactly like hedged_call re-enters the
#   caller's pass_scope).
# * :meth:`Tracer.set_trace` — a per-tracer default.  A streamed run
#   tracer is ALREADY job-scoped (one Tracer per transform_streamed
#   call), so stamping its default onto every event it records covers
#   worker threads without any TLS plumbing.
#
# The explicit ``trace=`` span attr wins over both — the coalescer's
# fused dispatch serves MANY traces at once and links them via its
# ``links`` arg instead of claiming any single one.
_TRACE_TLS = threading.local()


def mint_trace_id() -> str:
    """A fresh 16-hex-char trace id (crypto-random: ids minted by
    concurrent gateway submissions must never collide)."""
    import binascii

    return binascii.hexlify(os.urandom(8)).decode("ascii")


class trace_scope:
    """Marks the current thread as working for one trace (reentrant;
    inner scopes shadow outer).  ``trace_scope(None)`` is a no-op frame
    so callers can re-enter a captured-maybe-None context untested —
    the hedged_call helper-thread pattern."""

    def __init__(self, trace_id: str | None):
        self._trace = trace_id

    def __enter__(self):
        stack = getattr(_TRACE_TLS, "stack", None)
        if stack is None:
            stack = _TRACE_TLS.stack = []
        stack.append(self._trace)
        return self

    def __exit__(self, *exc):
        _TRACE_TLS.stack.pop()
        return False


def current_trace() -> str | None:
    """The innermost active :class:`trace_scope` id, or None."""
    stack = getattr(_TRACE_TLS, "stack", None)
    for tid in reversed(stack or ()):
        if tid is not None:
            return tid
    return None


# Active-trace registry: the heartbeat's ``active_traces`` field.  A
# trace activates when its job's run starts and deactivates in the
# run's finally — refcounted, because a recovery replay can briefly
# overlap the original registration.
_ACTIVE_TRACES_LOCK = threading.Lock()
_ACTIVE_TRACES: dict = {}  # trace_id -> activation count


def activate_trace(trace_id: str | None) -> None:
    if not trace_id:
        return
    with _ACTIVE_TRACES_LOCK:
        _ACTIVE_TRACES[trace_id] = _ACTIVE_TRACES.get(trace_id, 0) + 1


def deactivate_trace(trace_id: str | None) -> None:
    if not trace_id:
        return
    with _ACTIVE_TRACES_LOCK:
        n = _ACTIVE_TRACES.get(trace_id, 0) - 1
        if n <= 0:
            _ACTIVE_TRACES.pop(trace_id, None)
        else:
            _ACTIVE_TRACES[trace_id] = n


def active_traces() -> tuple:
    """The currently-active trace ids (sorted, for stable output)."""
    with _ACTIVE_TRACES_LOCK:
        return tuple(sorted(_ACTIVE_TRACES))


def event_in_trace(ev: dict, trace_id: str) -> bool:
    """True when a flight-recorder event belongs to ``trace_id`` —
    either stamped directly (``ev["trace"]``) or linked through a
    fused-dispatch fan-in edge (``args.links[*].trace``).  The one
    membership predicate the /trace export, the incident recorder and
    the tests all share."""
    if ev.get("trace") == trace_id:
        return True
    links = (ev.get("args") or {}).get("links")
    if not links:
        return False
    try:
        return any(l.get("trace") == trace_id for l in links)
    except (AttributeError, TypeError):
        return False


# --------------------------------------------------------------------------
# Prometheus name mangling — shared by gateway/metrics.py and the
# telemetry-names staticcheck rule
# --------------------------------------------------------------------------
#: Prefix every exposed series carries (`reads.ingested` ->
#: `adam_tpu_reads_ingested`).
PROMETHEUS_PREFIX = "adam_tpu_"

#: The exposition-format metric-name grammar (no leading digit).
_PROM_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")


def prometheus_name(name: str) -> str:
    """Mangle a registered metric name into its Prometheus series name
    (``.`` -> ``_``, prefixed).  Total function — validation is the
    lint's job (:mod:`adam_tpu.staticcheck.rules.telemetry_names`
    asserts every registered name mangles to a VALID, collision-free
    series name, so the gateway's render path never has to)."""
    return PROMETHEUS_PREFIX + name.replace(".", "_")


def prometheus_name_valid(mangled: str) -> bool:
    """Whether a mangled series name satisfies the Prometheus
    exposition grammar ``[a-zA-Z_:][a-zA-Z0-9_:]*``."""
    return bool(_PROM_NAME_OK.match(mangled))


#: Ring bound on retained compile-ledger entries: every entry is one
#: real XLA compile (seconds each), so a run can't plausibly exceed
#: this — it exists so a pathological shape explosion degrades to
#: truncation (counted) instead of unbounded growth.
_MAX_COMPILE_ENTRIES = 512


# --------------------------------------------------------------------------
# Span context managers
# --------------------------------------------------------------------------
class _NullSpan:
    """Shared no-op span: the disabled fast path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tr", "name", "attrs", "_t0", "_parent")

    def __init__(self, tr: "Tracer", name: str, attrs: dict):
        self._tr = tr
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        tls = self._tr._tls
        self._parent = getattr(tls, "span", None)
        tls.span = self
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        dur = time.monotonic_ns() - self._t0
        self._tr._tls.span = self._parent
        self._tr._record(
            self.name, self._t0, dur, self.attrs,
            self._parent.name if self._parent is not None else None,
        )
        return False


class Tracer:
    """Span/counter/gauge recorder with a bounded flight recorder.

    Thread-safe under one mutex (the ``TimerRegistry`` lock
    discipline); per-name aggregates live OUTSIDE the ring, so span
    totals stay exact even after the ring evicts old events.
    """

    def __init__(self, recording: bool = False, capacity: int | None = None):
        if capacity is None:
            raw = os.environ.get("ADAM_TPU_TRACE_EVENTS", "")
            try:
                capacity = int(raw)
            except ValueError:
                # the module-level TRACE constructs at import time from
                # every entry point: a malformed tuning var must degrade
                # to the default, not brick the CLI with a ValueError
                if raw:
                    import logging

                    logging.getLogger(__name__).warning(
                        "ADAM_TPU_TRACE_EVENTS=%r is not an int; using "
                        "default 65536", raw,
                    )
                capacity = 65536
        self.recording = recording
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(1, capacity))
        self._spans: dict = {}     # name -> [count, total_ns]
        self._dev_spans: dict = {} # name -> {device key -> [count, total_ns]}
        self._counters: dict = {}  # name -> int
        self._gauges: dict = {}    # name -> {last, min, max, n}
        self._hists: dict = {}     # name -> _new_hist() dict
        # device ledger: host<->device transfer accounting per
        # direction/device/pass, compile-cache entries, HBM samples
        self._xfer: dict = {}      # dir -> dev -> pass -> [n, bytes, s]
        self._compiles: list = []  # {kernel, shape, device, seconds, ...}
        self._compiles_dropped = 0
        self._hbm: dict = {}       # dev -> {last, peak, n}
        # per-tenant quota ledger (serve/quota.py feeds it): tenant ->
        # {charges, bytes, compute_s, budget_bytes, budget_compute_s}
        self._quota: dict = {}
        # device-health ledger (utils/health.py feeds it): device key ->
        # {state, score, reason, transitions} — the snapshot's `health`
        # section, rendered by `adam-tpu analyze` as "Device health"
        self._health: dict = {}
        # job-scoped trace context: the per-tracer default trace id
        # (set_trace) and the per-trace aggregate ledger the snapshot's
        # `traces` section reports: trace_id -> [events, total span ns]
        self._trace = None
        self._traces: dict = {}
        self._tls = threading.local()
        self._n_recorded = 0

    # ---- trace context ----------------------------------------------------
    def set_trace(self, trace_id: str | None) -> None:
        """Set this tracer's default trace id: every event recorded
        with no explicit ``trace=`` attr and no active
        :class:`trace_scope` is stamped with it.  The streamed run
        tracer is job-scoped, so its default covers every worker
        thread recording into it — no TLS plumbing required."""
        self._trace = trace_id

    @property
    def trace(self) -> str | None:
        """This tracer's default trace id (None when unset)."""
        return self._trace

    # ---- recording --------------------------------------------------------
    def span(self, name: str, **attrs):
        """Span context manager; a shared no-op when not recording."""
        if not self.recording:
            return _NULL_SPAN
        return _Span(self, name, attrs)

    def add_span(self, name: str, start_ns: int, dur_ns: int,
                 thread: str | None = None, **attrs) -> None:
        """Record an externally-measured interval (monotonic_ns clock)."""
        if not self.recording:
            return
        self._record(name, start_ns, dur_ns, attrs, None, thread)

    def _record(self, name, t0, dur, attrs, parent, thread=None):
        ev = {
            "name": name,
            "ts_ns": t0,
            "dur_ns": dur,
            "thread": thread or threading.current_thread().name,
        }
        if parent:
            ev["parent"] = parent
        if attrs:
            ev["args"] = dict(attrs)
        # trace attribution: explicit span attr > thread's trace_scope >
        # the tracer's own default (a streamed run tracer is job-scoped,
        # so its default covers worker threads with no TLS plumbing)
        trace = (attrs or {}).get("trace") or current_trace() or self._trace
        if trace:
            ev["trace"] = trace
        dev = (attrs or {}).get("device")
        if (
            dev is not None and (attrs or {}).get("replay")
            and name != SPAN_POOL_REPLAY
        ):
            # replayed work aggregates under ``<k>:replay``, NOT under
            # the survivor's own key: after an eviction the survivor's
            # organic occupancy and the windows it re-ran for the dead
            # chip must stay separable (the evicted device's
            # pre-eviction spans keep its original key untouched).  The
            # replay UMBRELLA is exempt: on a cascading eviction (a
            # device dies mid-replay) the nested umbrella is recorded
            # inside the outer replay_scope, but it must stay under the
            # failed chip's plain key or the analyzer would count the
            # recovery wall as busy time and miss the eviction.
            dev = f"{dev}:replay"
        with self._lock:
            self._events.append(ev)
            self._n_recorded += 1
            agg = self._spans.get(name)
            if agg is None:
                self._spans[name] = [1, dur]
            else:
                agg[0] += 1
                agg[1] += dur
            # automatic per-span-name duration histogram (seconds):
            # the scalar total says how much, the quantiles say whether
            # the tail is what the barriers wait on
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _new_hist()
            _hist_observe(h, dur / 1e9)
            if trace:
                # per-trace aggregate: survives ring eviction, merges
                # additively (absorb / merge_snapshots) — "how much
                # recorded work does trace T have" stays answerable
                # even after the events themselves age out
                tagg = self._traces.get(trace)
                if tagg is None:
                    self._traces[trace] = [1, dur]
                else:
                    tagg[0] += 1
                    tagg[1] += dur
            if dev is not None:
                # per-device aggregate: the snapshot's device_spans
                # section (chip occupancy + skew; time-sliced chips are
                # NOT symmetric, so per-device walls must be separable)
                per = self._dev_spans.setdefault(name, {})
                dagg = per.get(dev)
                if dagg is None:
                    per[dev] = [1, dur]
                else:
                    dagg[0] += 1
                    dagg[1] += dur

    def count(self, name: str, n: int = 1) -> None:
        if not self.recording:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def observe(self, name: str, value) -> None:
        """Record one value into a fixed-bucket histogram (the counter
        lock discipline: one branch when disabled, read-modify-write
        only under the mutex when recording)."""
        if not self.recording:
            return
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _new_hist()
            _hist_observe(h, value)

    def record_transfer(self, direction: str, nbytes: int, seconds: float,
                        device=None, pass_name: str | None = None) -> None:
        """Account one host<->device transfer (``direction`` is ``h2d``
        or ``d2h``): the run-total byte counter, the per-direction
        throughput histogram (bytes/second — only when the transfer
        took measurable wall, so instant memcpys don't pollute the link
        quantiles), and the per-(device, pass) attribution the
        snapshot's ``transfers`` section reports.  ``pass_name``
        defaults to the thread's active :class:`pass_scope`."""
        if not self.recording:
            return
        nbytes = int(nbytes)
        counter = C_H2D_BYTES if direction == "h2d" else C_D2H_BYTES
        hname = H_H2D_BPS if direction == "h2d" else H_D2H_BPS
        if pass_name is None:
            pass_name = current_pass() or PASS_OTHER
        dev = "default" if device is None else str(device)
        with self._lock:
            self._counters[counter] = self._counters.get(counter, 0) + nbytes
            if seconds > 1e-9 and nbytes:
                h = self._hists.get(hname)
                if h is None:
                    h = self._hists[hname] = _new_hist()
                _hist_observe(h, nbytes / seconds)
            per = self._xfer.setdefault(direction, {}).setdefault(dev, {})
            agg = per.get(pass_name)
            if agg is None:
                per[pass_name] = [1, nbytes, float(seconds)]
            else:
                agg[0] += 1
                agg[1] += nbytes
                agg[2] += float(seconds)

    def record_compile(self, kernel: str, shape, device, seconds: float,
                       in_window: bool) -> None:
        """Record one executable-cache MISS (a real trace+compile) in
        the compile ledger: the miss counter, the compile-duration
        histogram, and a (kernel, shape, device) entry — flagged
        ``in_window`` when it happened at a live dispatch site rather
        than under a prewarm scope (the cold compile then landed inside
        a timed window, the exact event the prewarm exists to prevent)."""
        if not self.recording:
            return
        entry = {
            "kernel": str(kernel),
            "shape": list(shape) if shape is not None else None,
            "device": "default" if device is None else str(device),
            "seconds": round(float(seconds), 6),
            "in_window": bool(in_window),
        }
        with self._lock:
            self._counters[C_COMPILE_MISSES] = (
                self._counters.get(C_COMPILE_MISSES, 0) + 1
            )
            if in_window:
                self._counters[C_COMPILE_IN_WINDOW] = (
                    self._counters.get(C_COMPILE_IN_WINDOW, 0) + 1
                )
            h = self._hists.get(H_COMPILE_SECONDS)
            if h is None:
                h = self._hists[H_COMPILE_SECONDS] = _new_hist()
            _hist_observe(h, seconds)
            if len(self._compiles) < _MAX_COMPILE_ENTRIES:
                self._compiles.append(entry)
            else:
                self._compiles_dropped += 1

    def record_hbm(self, device_key: str, bytes_in_use: int,
                   peak_bytes=None) -> None:
        """One HBM footprint sample for one device (the heartbeat tick
        feeds this from ``device.memory_stats()``).  ``peak`` keeps the
        max ever seen — the backend-reported peak when available, else
        the max sampled ``bytes_in_use``."""
        if not self.recording:
            return
        bytes_in_use = int(bytes_in_use)
        hi = int(peak_bytes) if peak_bytes is not None else bytes_in_use
        hi = max(hi, bytes_in_use)
        with self._lock:
            g = self._hbm.get(str(device_key))
            if g is None:
                self._hbm[str(device_key)] = {
                    "last": bytes_in_use, "peak": hi, "n": 1,
                }
            else:
                g["last"] = bytes_in_use
                if hi > g["peak"]:
                    g["peak"] = hi
                g["n"] += 1

    def record_quota(self, tenant: str, nbytes: int = 0,
                     compute_s: float = 0.0, budget_bytes=None,
                     budget_compute_s=None) -> None:
        """Account one quota charge against a tenant (serve/quota.py
        feeds this from the device ledger's h2d/d2h grant sizes and the
        per-pass compute attribution).  The snapshot's ``quota`` section
        carries the running per-tenant consumption — and the budgets,
        when the QuotaManager knows them — so ``adam-tpu analyze`` can
        render per-tenant consumption next to the batching fill."""
        if not self.recording:
            return
        with self._lock:
            q = self._quota.get(str(tenant))
            if q is None:
                q = self._quota[str(tenant)] = {
                    "charges": 0, "bytes": 0, "compute_s": 0.0,
                    "budget_bytes": None, "budget_compute_s": None,
                }
            q["charges"] += 1
            q["bytes"] += int(nbytes)
            q["compute_s"] += float(compute_s)
            if budget_bytes is not None:
                q["budget_bytes"] = int(budget_bytes)
            if budget_compute_s is not None:
                q["budget_compute_s"] = float(budget_compute_s)

    def record_health(self, device_key: str, state: str, score: float,
                      reason: str = "", transition: bool = True) -> None:
        """One device-health scoreboard update (utils/health.py feeds
        transitions and the run-end publish).  The ledger keeps the
        LAST state/score per device plus a transition count, so the
        snapshot's ``health`` section reads as "where every chip ended
        up and how often it moved".  ``transition=False`` records a
        state WITHOUT counting movement — the run-end ``publish`` of
        the board's current states, which must not inflate the count
        of transitions the run actually witnessed (a serve process
        publishes once per job)."""
        if not self.recording:
            return
        with self._lock:
            h = self._health.get(str(device_key))
            if h is None:
                # every device starts healthy, so a first LIVE record
                # that is not healthy is itself a transition; a publish
                # of a pre-existing state is not
                h = self._health[str(device_key)] = {
                    "state": state, "score": 0.0, "reason": "",
                    "transitions": (
                        1 if transition and state != "healthy" else 0
                    ),
                }
            else:
                if transition and h["state"] != state:
                    h["transitions"] += 1
                h["state"] = state
            h["score"] = round(float(score), 3)
            if reason:
                h["reason"] = str(reason)

    def gauge(self, name: str, value) -> None:
        if not self.recording:
            return
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                self._gauges[name] = {
                    "last": value, "min": value, "max": value, "n": 1,
                }
            else:
                g["last"] = value
                if value < g["min"]:
                    g["min"] = value
                if value > g["max"]:
                    g["max"] = value
                g["n"] += 1

    # ---- reading ----------------------------------------------------------
    def counters_and_gauges(self) -> tuple:
        """(counters, gauges) copies only — the heartbeat's per-beat
        accessor.  ``snapshot()`` computes histogram quantiles and
        copies every span/device aggregate; at subsecond beat intervals
        that is wasted O(names) work done under the recording mutex."""
        with self._lock:
            return (
                dict(self._counters),
                {k: dict(v) for k, v in self._gauges.items()},
            )

    def span_seconds(self) -> dict:
        """Per-name total span seconds (concurrency-safe copy)."""
        with self._lock:
            return {k: v[1] / 1e9 for k, v in self._spans.items()}

    def events(self) -> list:
        """Copy of the flight-recorder ring (oldest surviving first)."""
        with self._lock:
            return [dict(e) for e in self._events]

    def events_for_trace(self, trace_id: str) -> list:
        """The flight recorder filtered to one trace: events stamped
        with the id plus fused-dispatch events whose ``links`` name it
        (:func:`event_in_trace`) — the query the ``/jobs/<id>/trace``
        gateway surface and the incident recorder are built on."""
        with self._lock:
            return [
                dict(e) for e in self._events
                if event_in_trace(e, trace_id)
            ]

    def snapshot(self) -> dict:
        """Aggregate view (spans/counters/gauges), safe to call
        concurrently with recording.  Does NOT include the event ring —
        that is the Chrome-trace export's job."""
        with self._lock:
            return {
                "spans": {
                    k: {"count": v[0], "total_s": v[1] / 1e9}
                    for k, v in self._spans.items()
                },
                "device_spans": {
                    name: {
                        str(d): {"count": v[0], "total_s": v[1] / 1e9}
                        for d, v in per.items()
                    }
                    for name, per in self._dev_spans.items()
                },
                "counters": dict(self._counters),
                "gauges": {k: dict(v) for k, v in self._gauges.items()},
                "histograms": {
                    k: hist_summary(v) for k, v in self._hists.items()
                },
                "transfers": {
                    direction: {
                        dev: {
                            p: {
                                "count": v[0],
                                "bytes": v[1],
                                "seconds": round(v[2], 6),
                            }
                            for p, v in per.items()
                        }
                        for dev, per in by_dev.items()
                    }
                    for direction, by_dev in self._xfer.items()
                },
                "compiles": {
                    "entries": [dict(e) for e in self._compiles],
                    "dropped": self._compiles_dropped,
                },
                "hbm": {k: dict(v) for k, v in self._hbm.items()},
                "quota": {k: dict(v) for k, v in self._quota.items()},
                "health": {k: dict(v) for k, v in self._health.items()},
                "traces": {
                    k: {"events": v[0], "total_s": v[1] / 1e9}
                    for k, v in self._traces.items()
                },
                "events_recorded": self._n_recorded,
                "events_retained": len(self._events),
                "events_evicted": self._n_recorded - len(self._events),
            }

    # ---- lifecycle --------------------------------------------------------
    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._spans.clear()
            self._dev_spans.clear()
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._xfer.clear()
            self._compiles.clear()
            self._compiles_dropped = 0
            self._hbm.clear()
            self._quota.clear()
            self._health.clear()
            self._traces.clear()
            self._n_recorded = 0

    def reset_metrics(self) -> None:
        """Clear counters + gauges + histograms (and the device-ledger
        sections derived with them) only (TimerRegistry.reset delegates
        here so one reset clears the whole metrics surface)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._xfer.clear()
            self._compiles.clear()
            self._compiles_dropped = 0
            self._hbm.clear()
            self._quota.clear()
            self._health.clear()

    def absorb(self, other: "Tracer") -> None:
        """Merge another tracer's events + aggregates into this one
        (the streamed run tracer folds into the global TRACE)."""
        with other._lock:
            events = [dict(e) for e in other._events]
            spans = {k: list(v) for k, v in other._spans.items()}
            dev_spans = {
                k: {d: list(v) for d, v in per.items()}
                for k, per in other._dev_spans.items()
            }
            counters = dict(other._counters)
            gauges = {k: dict(v) for k, v in other._gauges.items()}
            hists = {
                k: {**v, "buckets": dict(v["buckets"])}
                for k, v in other._hists.items()
            }
            xfer = {
                d: {dev: {p: list(v) for p, v in per.items()}
                    for dev, per in by_dev.items()}
                for d, by_dev in other._xfer.items()
            }
            compiles = [dict(e) for e in other._compiles]
            compiles_dropped = other._compiles_dropped
            hbm = {k: dict(v) for k, v in other._hbm.items()}
            quota = {k: dict(v) for k, v in other._quota.items()}
            health = {k: dict(v) for k, v in other._health.items()}
            traces = {k: list(v) for k, v in other._traces.items()}
            n_rec = other._n_recorded
        with self._lock:
            self._events.extend(events)
            self._n_recorded += n_rec
            for k, (c, ns) in spans.items():
                agg = self._spans.get(k)
                if agg is None:
                    self._spans[k] = [c, ns]
                else:
                    agg[0] += c
                    agg[1] += ns
            for k, per in dev_spans.items():
                mine = self._dev_spans.setdefault(k, {})
                for d, (c, ns) in per.items():
                    dagg = mine.get(d)
                    if dagg is None:
                        mine[d] = [c, ns]
                    else:
                        dagg[0] += c
                        dagg[1] += ns
            for k, v in counters.items():
                self._counters[k] = self._counters.get(k, 0) + v
            for k, h in hists.items():
                mine = self._hists.get(k)
                if mine is None:
                    self._hists[k] = h
                else:
                    mine["count"] += h["count"]
                    mine["sum"] += h["sum"]
                    for bound, pick in (("min", min), ("max", max)):
                        v = h[bound]
                        if v is not None:
                            mine[bound] = (
                                v if mine[bound] is None
                                else pick(mine[bound], v)
                            )
                    for idx, n in h["buckets"].items():
                        mine["buckets"][idx] = (
                            mine["buckets"].get(idx, 0) + n
                        )
            for k, g in gauges.items():
                mine = self._gauges.get(k)
                if mine is None:
                    self._gauges[k] = dict(g)
                else:
                    mine["last"] = g["last"]
                    mine["min"] = min(mine["min"], g["min"])
                    mine["max"] = max(mine["max"], g["max"])
                    mine["n"] += g["n"]
            for d, by_dev in xfer.items():
                mdir = self._xfer.setdefault(d, {})
                for dev, per in by_dev.items():
                    mdev = mdir.setdefault(dev, {})
                    for p, (c, nb, s) in per.items():
                        agg = mdev.get(p)
                        if agg is None:
                            mdev[p] = [c, nb, s]
                        else:
                            agg[0] += c
                            agg[1] += nb
                            agg[2] += s
            room = _MAX_COMPILE_ENTRIES - len(self._compiles)
            self._compiles.extend(compiles[:room])
            self._compiles_dropped += (
                compiles_dropped + max(0, len(compiles) - room)
            )
            for k, g in hbm.items():
                mine = self._hbm.get(k)
                if mine is None:
                    self._hbm[k] = dict(g)
                else:
                    mine["last"] = g["last"]
                    mine["peak"] = max(mine["peak"], g["peak"])
                    mine["n"] += g["n"]
            for k, q in quota.items():
                mine = self._quota.get(k)
                if mine is None:
                    self._quota[k] = dict(q)
                else:
                    mine["charges"] += q["charges"]
                    mine["bytes"] += q["bytes"]
                    mine["compute_s"] += q["compute_s"]
                    for bk in ("budget_bytes", "budget_compute_s"):
                        if q.get(bk) is not None:
                            mine[bk] = q[bk]
            for k, hrow in health.items():
                mine = self._health.get(k)
                if mine is None:
                    self._health[k] = dict(hrow)
                else:
                    # the absorbed tracer's view is the newer one (run
                    # tracers fold into TRACE at run end): its last
                    # state wins, transition counts SUM and nothing
                    # else — every real transition was counted exactly
                    # once by whichever tracer witnessed it live, and
                    # run-end publishes carry transition=False, so a
                    # state difference here is a stale last-known
                    # state, not an uncounted movement
                    mine["transitions"] += hrow["transitions"]
                    mine["state"] = hrow["state"]
                    mine["score"] = hrow["score"]
                    if hrow.get("reason"):
                        mine["reason"] = hrow["reason"]
            for k, (c, ns) in traces.items():
                tagg = self._traces.get(k)
                if tagg is None:
                    self._traces[k] = [c, ns]
                else:
                    tagg[0] += c
                    tagg[1] += ns

    # ---- exports ----------------------------------------------------------
    def to_json(self, timers=None, include_events: bool = False) -> dict:
        """The ``--metrics-json`` document.  ``timers`` defaults to the
        process-wide :data:`~adam_tpu.utils.instrumentation.TIMERS`;
        its section carries the same (count, total_s) rows as the
        printed ``-print_metrics`` table, so the two cannot drift.
        ``include_events=True`` appends the flight-recorder ring (the
        dump-on-error view)."""
        if timers is None:
            timers = _ins.TIMERS
        doc = self.snapshot()
        doc["timers"] = {
            name: {"count": c, "total_s": ns / 1e9}
            for name, (c, ns) in sorted(timers.snapshot().items())
        }
        doc["meta"] = {
            "pid": os.getpid(),
            "epoch_ns": _EPOCH_NS,
            "schema": "adam_tpu.telemetry/1",
        }
        if include_events:
            doc["events"] = self.events()
        return doc

    def to_chrome_trace(self, trace_id: str | None = None) -> dict:
        """Flight recorder -> Chrome trace-event JSON (Perfetto /
        chrome://tracing).  Each recording thread gets its own track, so
        the streamed tokenize/dispatch/fetch/encode/write overlap is
        visually inspectable.  Events carrying a ``device=<k>``
        attribution (the multi-chip pool's dispatch/fetch/prewarm spans)
        are additionally mirrored onto a ``device:<k>`` track — one
        track per chip, so per-device queue occupancy and skew are
        visible next to the host threads.

        ``trace_id`` filters the export to one job's trace (stamped
        events plus fused dispatches linking it — the
        ``GET /jobs/<id>/trace`` gateway view): same shape, fewer
        events, so anything that loads the full export loads the
        per-job one."""
        evs = (
            self.events() if trace_id is None
            else self.events_for_trace(trace_id)
        )
        pid = os.getpid()
        tids: dict = {}
        out = []

        def _tid(track: str) -> int:
            if track not in tids:
                tids[track] = len(tids) + 1
                out.append({
                    "ph": "M", "pid": pid, "tid": tids[track],
                    "name": "thread_name", "args": {"name": track},
                })
            return tids[track]

        for e in evs:
            _tid(e["thread"])
        for e in evs:
            ev = {
                "ph": "X",
                "pid": pid,
                "tid": tids[e["thread"]],
                "name": e["name"],
                "cat": "adam_tpu",
                "ts": (e["ts_ns"] - _EPOCH_NS) / 1e3,  # microseconds
                "dur": e["dur_ns"] / 1e3,
            }
            args = dict(e.get("args") or {})
            if "parent" in e:
                args["parent"] = e["parent"]
            if "trace" in e:
                args["trace"] = e["trace"]
            if args:
                ev["args"] = args
            out.append(ev)
            dev = (e.get("args") or {}).get("device")
            if dev is not None:
                mirror = dict(ev)
                mirror["tid"] = _tid(f"device:{dev}")
                # explicit mirror marker: the analyzer must count each
                # interval once, and two genuinely-concurrent same-name
                # spans can coincide to the microsecond — only this
                # marker distinguishes a mirror from a twin
                mirror["cat"] = CHROME_MIRROR_CAT
                out.append(mirror)
        # carry the histogram section alongside the events (viewers
        # ignore unknown top-level keys): explicit observe() metrics
        # (device.fetch.seconds, parquet.pool.submit_wait) are not
        # spans, so a trace alone could never reproduce their
        # quantiles — and the span-duration histograms here aggregate
        # PAST the ring's retention, unlike the events.  Ring occupancy
        # rides along too: a consumer attributing wall time from the
        # events (utils/analyzer.py) must know when the oldest events
        # were evicted, or truncation reads as fabricated idle time.
        with self._lock:
            hists = {k: hist_summary(v) for k, v in self._hists.items()}
            xfer = {
                d: {
                    dev: {
                        p: {"count": v[0], "bytes": v[1],
                            "seconds": round(v[2], 6)}
                        for p, v in per.items()
                    }
                    for dev, per in by_dev.items()
                }
                for d, by_dev in self._xfer.items()
            }
            compiles = {
                "entries": [dict(e) for e in self._compiles],
                "dropped": self._compiles_dropped,
            }
            hbm = {k: dict(v) for k, v in self._hbm.items()}
            quota = {k: dict(v) for k, v in self._quota.items()}
            health = {k: dict(v) for k, v in self._health.items()}
            trace_aggs = {
                k: {"events": v[0], "total_s": v[1] / 1e9}
                for k, v in self._traces.items()
                if trace_id is None or k == trace_id
            }
            counters = dict(self._counters)
            gauges = {k: dict(v) for k, v in self._gauges.items()}
            n_rec = self._n_recorded
            n_ret = len(self._events)
        return {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "histograms": hists,
            # the device ledger rides along (viewers ignore unknown
            # top-level keys): transfers/compiles/HBM are aggregates,
            # not spans, so a trace alone could never reproduce them —
            # and the analyzer must render the same report sections
            # from either artifact kind.  Counters too: the tunnel byte
            # totals and compile hit/miss counts live there.
            "transfers": xfer,
            "compiles": compiles,
            "hbm": hbm,
            "quota": quota,
            "health": health,
            "counters": counters,
            # gauges ride along too: the analyzer labels the resolve
            # stage (device vs host sort) and the execution mode off
            # them, from either artifact kind
            "gauges": gauges,
            # per-trace aggregates (filtered when the export is):
            # a per-job export states how much recorded work its trace
            # has IN TOTAL, so a consumer can tell a complete export
            # from one whose events aged out of the ring
            "traces": trace_aggs,
            "events_recorded": n_rec,
            "events_evicted": n_rec - n_ret,
        }

    def dump_json(self, path: str, timers=None,
                  include_events: bool = False) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_json(timers, include_events=include_events),
                      fh, indent=1, default=str)

    def dump_chrome_trace(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_chrome_trace(), fh, default=str)

    def report(self) -> str:
        """Counters/gauges table printed below the timer table by
        ``-print_metrics``."""
        snap = self.snapshot()
        out = []
        if snap["counters"]:
            w = max(len(k) for k in snap["counters"])
            out += ["Counters", "========"]
            out.append(f"{'counter'.ljust(w)}  {'value':>14}")
            for k in sorted(snap["counters"]):
                out.append(f"{k.ljust(w)}  {snap['counters'][k]:>14}")
            out.append("")
        if snap["gauges"]:
            w = max(len(k) for k in snap["gauges"])
            out += ["Gauges", "======"]
            out.append(
                f"{'gauge'.ljust(w)}  {'last':>8}  {'min':>8}  {'max':>8}"
                f"  {'samples':>8}"
            )
            for k in sorted(snap["gauges"]):
                g = snap["gauges"][k]
                out.append(
                    f"{k.ljust(w)}  {g['last']:>8}  {g['min']:>8}"
                    f"  {g['max']:>8}  {g['n']:>8}"
                )
            out.append("")
        if snap.get("histograms"):
            w = max(len(k) for k in snap["histograms"])
            out += ["Histograms (seconds)", "===================="]
            out.append(
                f"{'histogram'.ljust(w)}  {'count':>8}  {'p50':>10}"
                f"  {'p90':>10}  {'p99':>10}  {'max':>10}"
            )

            def _f(v):
                return f"{v:.6f}" if v is not None else "-"

            for k in sorted(snap["histograms"]):
                h = snap["histograms"][k]
                out.append(
                    f"{k.ljust(w)}  {h['count']:>8}  {_f(h['p50']):>10}"
                    f"  {_f(h['p90']):>10}  {_f(h['p99']):>10}"
                    f"  {_f(h['max']):>10}"
                )
            out.append("")
        if not out:
            return "Counters/Gauges\n===============\n(none recorded)\n"
        return "\n".join(out)


#: Chrome-trace ``cat`` of the synthetic per-chip mirror copies
#: ``to_chrome_trace`` emits next to each device-attributed span's
#: host-thread event (utils/analyzer.py skips these when attributing).
CHROME_MIRROR_CAT = "adam_tpu.device-mirror"

#: Process-wide tracer — the ``object Timers`` analog for the
#: structured layer.  Off by default; the CLI flips it on for
#: ``-print_metrics`` / ``--metrics-json`` / ``--trace-out``.
TRACE = Tracer()


# --------------------------------------------------------------------------
# Derived views
# --------------------------------------------------------------------------
def streamed_stats_view(snap: dict) -> dict:
    """Rebuild the streamed pipeline's timing ``stats`` keys from span
    data (a :meth:`Tracer.snapshot`).  ``transform_streamed`` itself
    calls this on its run tracer — the stats dict IS this view, so the
    printed stats and the span data cannot disagree, and a test can
    recompute the view from an exported snapshot.
    """
    spans = snap.get("spans", {})

    def s(name):
        e = spans.get(name)
        return e["total_s"] if e else None

    out = {}
    for key, name in (
        ("prewarm_s", SPAN_POOL_PREWARM),
        ("ingest_pass_s", SPAN_PASS_A),  # prewarm subtracted below
        ("md_cols_fetch_s", SPAN_MD_FETCH),
        ("resolve_s", SPAN_RESOLVE),
        ("split_s", SPAN_SPLIT),
        ("observe_s", SPAN_OBSERVE),
        ("obs_merge_fetch_s", SPAN_OBS_MERGE),
        ("solve_s", SPAN_SOLVE),
        ("apply_device_dispatch_s", SPAN_APPLY_DISPATCH),
        ("apply_device_fetch_s", SPAN_APPLY_FETCH),
        ("write_wait_s", SPAN_WRITE_WAIT),
        ("total_s", SPAN_TOTAL),
    ):
        v = s(name)
        if v is not None:
            out[key] = v
    if "prewarm_s" in out and "ingest_pass_s" in out:
        # the prewarm umbrella is nested inside pass A (it fires on the
        # first ingested window): subtract it so the stage rows stay
        # disjoint and sum to the pipeline wall
        out["ingest_pass_s"] = max(
            0.0, out["ingest_pass_s"] - out["prewarm_s"]
        )
    # the pass-C re-warm (the solved table's real width) is nested
    # inside pass C: fold its wall into prewarm_s for the headline, and
    # remember it for the apply_split subtraction below — real compile
    # time must never masquerade as host encode/submit time
    prewarm_c = s(SPAN_POOL_PREWARM_C)
    if prewarm_c is not None:
        out["prewarm_s"] = out.get("prewarm_s", 0.0) + prewarm_c
    tail = s(SPAN_TAIL)
    if tail is not None:
        obs = s(SPAN_OBSERVE) or 0.0
        hidden = bool(
            snap.get("gauges", {}).get(G_OBSERVE_HIDDEN, {}).get("last", 0)
        )
        had_candidates = (
            snap.get("counters", {}).get(C_CANDIDATE_ROWS, 0) > 0
        )
        if had_candidates:
            # subtract the observe wall only when it genuinely ran
            # under the realign sweeps' device drain (streamed.py's
            # observe_overlap_hidden semantics)
            out["realign_s"] = tail - obs if hidden else tail
        else:
            out["realign_s"] = max(0.0, tail - obs)
    pass_c = s(SPAN_PASS_C)
    if pass_c is not None:
        # host share of pass C: the device dispatch/fetch walls (and
        # any pass-C re-warm compiles) are their own disjoint rows
        out["apply_split_s"] = max(
            0.0,
            pass_c
            - (s(SPAN_APPLY_DISPATCH) or 0.0)
            - (s(SPAN_APPLY_FETCH) or 0.0)
            - (prewarm_c or 0.0),
        )
    return out


def key_stable_snapshot(tr: Tracer | None = None) -> dict:
    """Snapshot with device-only counters/gauges ensured present (as
    zeros) — the bench's paired-CPU-baseline path uses this so
    round-over-round artifact diffs are key-stable."""
    snap = (tr or TRACE).snapshot()
    for name in sorted(DEVICE_ONLY_COUNTERS):
        snap["counters"].setdefault(name, 0)
    for name in sorted(DEVICE_ONLY_GAUGES):
        snap["gauges"].setdefault(
            name, {"last": 0, "min": 0, "max": 0, "n": 0}
        )
    snap.setdefault("device_spans", {})
    snap.setdefault("histograms", {})
    for name in sorted(DEVICE_ONLY_HISTOGRAMS):
        snap["histograms"].setdefault(name, hist_summary(_new_hist()))
    # device-ledger sections: empty-but-present on the CPU leg
    xfer = snap.setdefault("transfers", {})
    for direction in ("h2d", "d2h"):
        xfer.setdefault(direction, {})
    snap.setdefault("compiles", {"entries": [], "dropped": 0})
    snap.setdefault("hbm", {})
    snap.setdefault("quota", {})
    return snap


def merge_snapshots(snaps: list) -> dict:
    """Combine per-host snapshots (parallel/dist.gather_host_telemetry)
    into one report with per-host skew: for every span name, the
    min/max total wall across hosts — the Spark-listener per-executor
    skew view.  Histograms merge across hosts too (fixed global bucket
    edges make the merge a plain bucket sum, so host order is
    irrelevant) into combined p50/p90/p99 under ``histograms``.  The
    per-trace aggregates merge the same way (plain event/second sums
    per trace_id — a job whose windows executed on several hosts reads
    as one combined row), associatively, so gathering host snapshots
    in any grouping yields the same ``traces`` section.  The health
    and quota sections merge the same missing-side-tolerant way (a
    host that never tracked a device or admitted a tenant simply
    contributes nothing): health keeps per-device the WORST state
    across hosts (max transitions, min score — pessimism is the right
    default for a fleet view), quota sums per-tenant spend and keeps
    the first host's budgets (budgets are configuration, identical
    across hosts by construction).  Both keys are always present in
    the merged doc (empty dicts when no host carried the section), so
    consumers stay key-stable."""
    skew = {}
    hists: dict = {}
    traces: dict = {}
    health: dict = {}
    quota: dict = {}
    _HEALTH_RANK = {"healthy": 0, "suspect": 1, "probation": 2,
                    "evicted": 3}
    for snap in snaps:
        for name, e in snap.get("spans", {}).items():
            sk = skew.setdefault(
                name, {"min_s": e["total_s"], "max_s": e["total_s"]}
            )
            sk["min_s"] = min(sk["min_s"], e["total_s"])
            sk["max_s"] = max(sk["max_s"], e["total_s"])
        for name, h in snap.get("histograms", {}).items():
            hists[name] = merge_histograms(hists.get(name, {}), h)
        for tid, t in snap.get("traces", {}).items():
            agg = traces.setdefault(tid, {"events": 0, "total_s": 0.0})
            agg["events"] += t.get("events", 0)
            agg["total_s"] += t.get("total_s", 0.0)
        for dev, row in (snap.get("health") or {}).items():
            if not isinstance(row, dict):
                continue
            cur = health.get(dev)
            if cur is None:
                health[dev] = dict(row)
                continue
            if (_HEALTH_RANK.get(row.get("state"), 0)
                    > _HEALTH_RANK.get(cur.get("state"), 0)):
                cur["state"] = row.get("state")
                if row.get("reason"):
                    cur["reason"] = row["reason"]
            if isinstance(row.get("score"), (int, float)):
                cur["score"] = min(cur.get("score", row["score"]),
                                   row["score"])
            cur["transitions"] = (cur.get("transitions", 0)
                                  + row.get("transitions", 0))
        for tenant, row in (snap.get("quota") or {}).items():
            if not isinstance(row, dict):
                continue
            cur = quota.get(tenant)
            if cur is None:
                quota[tenant] = dict(row)
                continue
            for k in ("charges", "bytes", "compute_s"):
                cur[k] = (cur.get(k) or 0) + (row.get(k) or 0)
            for bk in ("budget_bytes", "budget_compute_s"):
                if cur.get(bk) is None and row.get(bk) is not None:
                    cur[bk] = row[bk]
    return {
        "n_hosts": len(snaps),
        "hosts": snaps,
        "span_skew": skew,
        "histograms": hists,
        "traces": traces,
        "health": health,
        "quota": quota,
    }


# --------------------------------------------------------------------------
# Live progress heartbeat
# --------------------------------------------------------------------------
#: NDJSON schema tag every heartbeat line carries.  /2 added the
#: device-ledger fields (tunnel bytes + HBM); /3 appended the
#: ``partitioner`` execution-mode field; /4 appended the cross-job
#: batching fields (``batch_fill`` + ``batched_jobs``); /5 appended
#: ``device_health`` (the per-device scoreboard states,
#: utils/health.py); /6 appended the trace/incident activity fields
#: (``active_traces``, ``metrics_scrapes``, ``last_incident``,
#: ``last_incident_age_s`` — utils/incidents.py); /7 appended the
#: judgment fields (``slo_worst_burn``, ``perf_regressions`` —
#: utils/slo.py + utils/perfledger.py) — each older version's fields
#: are a strict prefix of the next, so a consumer keying on field
#: NAMES keeps working; ``adam-tpu top`` accepts all seven.
HEARTBEAT_SCHEMA = "adam_tpu.heartbeat/7"

#: THE heartbeat line field set — a stable contract (documented in
#: docs/OBSERVABILITY.md, lint-enforced by scripts/check-telemetry-names):
#: every line carries exactly these keys, in this order, so a consumer
#: tailing the stream never needs per-line schema discovery.
HEARTBEAT_FIELDS = (
    "schema",
    "seq",
    "elapsed_s",
    "windows_ingested",
    "windows_total",
    "windows_resumed",
    "parts_written",
    "reads_ingested",
    "reads_per_s",
    "bytes_written",
    "h2d_bytes",
    "d2h_bytes",
    "hbm_bytes_in_use",
    "hbm_peak_bytes",
    "inflight",
    "inflight_per_device",
    "retries",
    "faults",
    "devices_evicted",
    "eta_s",
    "done",
    "ok",
    # /3: the streamed execution mode ("pool" | "mesh"; a mesh run that
    # degraded mid-flight flips to "pool" on its next beat) — appended
    # so the /2 fields stay a strict prefix
    "partitioner",
    # /4: cross-job window batching (serve/batching.py) — the running
    # grid fill rate (rows occupied / rows dispatched across every
    # fused dispatch so far; null when batching is off or nothing
    # coalesced yet) and the distinct-job count of the LAST fused
    # dispatch.
    "batch_fill",
    "batched_jobs",
    # /5: the device-health scoreboard's per-device states
    # ({device key: healthy|suspect|probation|evicted} from
    # utils/health.BOARD; null while no device has ever been tracked).
    "device_health",
    # /6: trace/incident activity (utils/incidents.py) — the count of
    # currently-active job traces, the count of gateway /metrics
    # scrapes served so far (a scraper-is-actually-reaching-us
    # signal for `adam-tpu top`), and the id + age of the newest
    # incident bundle recorded by THIS process (both null until one
    # fires).  Appended LAST so the /5 fields stay a strict prefix.
    "active_traces",
    "metrics_scrapes",
    "last_incident",
    "last_incident_age_s",
    # /7: the judgment layer (utils/slo.py + utils/perfledger.py) —
    # the worst short-window error-budget burn rate across armed SLO
    # objectives (null while no SLO engine is armed) and the running
    # count of perf keys the ledger sentinel flagged as regressed.
    # Appended LAST so the /6 fields stay a strict prefix.
    "slo_worst_burn",
    "perf_regressions",
)

def _health_states_for_heartbeat():
    """The /5 ``device_health`` field: the process-wide scoreboard's
    per-device states, or None while nothing has been tracked (lazy
    import — health.py imports this module at its top)."""
    try:
        from adam_tpu.utils import health as health_mod

        states = health_mod.BOARD.states()
        return states or None
    except Exception:
        return None


def _slo_for_heartbeat():
    """The /7 ``slo_worst_burn`` field: the armed SLO engine's worst
    short-window burn rate, or None while no engine is armed (lazy
    import — slo.py imports this module at its top)."""
    try:
        from adam_tpu.utils import slo as slo_mod

        burn = slo_mod.worst_burn()
        return round(burn, 3) if burn is not None else None
    except Exception:
        return None


def _incident_for_heartbeat():
    """The /6 ``last_incident`` + ``last_incident_age_s`` fields: the
    newest bundle this process recorded, as ``(id, age_s)`` — both
    None until one fires (lazy import — incidents.py imports this
    module at its top)."""
    try:
        from adam_tpu.utils import incidents as incidents_mod

        last = incidents_mod.last_incident()
        if not last:
            return None, None
        age = time.monotonic() - last["ts_monotonic"]
        return last["id"], round(max(0.0, age), 1)
    except Exception:
        return None, None


_DEFAULT_HEARTBEAT_INTERVAL_S = 2.0

#: Default size cap on a file heartbeat sink before rotation (bytes).
_DEFAULT_PROGRESS_MAX_BYTES = 64 * 1024 * 1024


def progress_max_bytes() -> int:
    """Heartbeat sink rotation cap (``ADAM_TPU_PROGRESS_MAX_BYTES``,
    default 64 MiB, ``0`` disables): when the NDJSON file passes the
    cap it rotates to ``<path>.1`` and a fresh file continues — a
    multi-hour service-style run cannot grow the sink unboundedly.
    Malformed values degrade to the default (tuning-var contract)."""
    raw = os.environ.get("ADAM_TPU_PROGRESS_MAX_BYTES", "").strip()
    if not raw:
        return _DEFAULT_PROGRESS_MAX_BYTES
    try:
        v = int(raw)
    except ValueError:
        import logging

        logging.getLogger(__name__).warning(
            "ADAM_TPU_PROGRESS_MAX_BYTES=%r is not an int; using default "
            "%d", raw, _DEFAULT_PROGRESS_MAX_BYTES,
        )
        return _DEFAULT_PROGRESS_MAX_BYTES
    return max(0, v)


def sample_hbm(devices=None) -> dict:
    """Per-device HBM footprint via ``device.memory_stats()`` —
    ``{device id: {"bytes_in_use": int, "peak_bytes_in_use": int}}``.

    Graceful everywhere: devices whose backend lacks memory stats (or
    reports none) are omitted, and a missing/unimportable jax yields
    ``{}`` — the heartbeat and analyzer render an explicit
    "unsupported" marker instead of fabricating zeros.  ``devices``
    defaults to ``jax.local_devices()`` (already initialized by any
    pipeline that has device work to measure)."""
    try:
        if devices is None:
            import jax

            devices = jax.local_devices()
    except Exception:
        return {}
    out = {}
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms or "bytes_in_use" not in ms:
            continue
        key = getattr(d, "id", None)
        key = str(key) if key is not None else str(d)
        out[key] = {
            "bytes_in_use": int(ms["bytes_in_use"]),
            "peak_bytes_in_use": int(
                ms.get("peak_bytes_in_use", ms["bytes_in_use"])
            ),
        }
    return out


def progress_sink_from_env() -> str | None:
    """Resolve ``ADAM_TPU_PROGRESS`` into a heartbeat sink: ``None``
    (unset/``0`` — the default, zero-overhead path), ``"stderr"``
    (``1``/``stderr``/``-``), or a file path to append NDJSON lines to."""
    raw = os.environ.get("ADAM_TPU_PROGRESS", "").strip()
    if not raw or raw == "0":
        return None
    if raw in ("1", "stderr", "-"):
        return "stderr"
    return raw


def progress_interval_s() -> float:
    """Heartbeat sample period (``ADAM_TPU_PROGRESS_INTERVAL_S``,
    default 2 s; malformed or nonpositive values degrade to the default
    with a warning — a tuning-var typo must not kill a pipeline)."""
    raw = os.environ.get("ADAM_TPU_PROGRESS_INTERVAL_S", "").strip()
    if not raw:
        return _DEFAULT_HEARTBEAT_INTERVAL_S
    try:
        v = float(raw)
    except ValueError:
        v = -1.0
    if v <= 0:
        import logging

        logging.getLogger(__name__).warning(
            "ADAM_TPU_PROGRESS_INTERVAL_S=%r is not a positive number; "
            "using default %.1fs", raw, _DEFAULT_HEARTBEAT_INTERVAL_S,
        )
        return _DEFAULT_HEARTBEAT_INTERVAL_S
    return v


class Heartbeat:
    """Daemon-thread progress heartbeat: one NDJSON line per sample.

    Samples the given tracers (the streamed run tracer plus the global
    :data:`TRACE` — counters are summed across them, gauges read from
    the first tracer that carries each) every ``interval_s`` seconds
    and writes one :data:`HEARTBEAT_FIELDS`-shaped JSON line to the
    sink (``"stderr"`` or a file path).  Emits immediately on
    :meth:`start` (short runs still get a line) and a final
    ``done=true`` line on :meth:`stop` (idempotent, exception-safe).

    Off is the default everywhere: when no sink is configured the
    streamed pipeline constructs no Heartbeat at all — the disabled
    cost is one ``if`` per run, the same ~zero-overhead contract the
    spans keep.  A heartbeat failure (closed sink, provider bug) is
    swallowed: progress reporting must never kill the run it reports.
    """

    def __init__(self, tracers, sink: str = "stderr",
                 interval_s: float | None = None):
        self._tracers = list(tracers)
        self._sink = sink
        self._interval = (
            progress_interval_s() if interval_s is None else interval_s
        )
        self._fh = None
        self._owns_fh = False
        self._t0 = None
        self._seq = 0
        self._total = None
        self._parts_total = None
        self._provider = None
        # HBM sampling: the device set to poll memory_stats() on each
        # beat (None = jax.local_devices() lazily); a backend that
        # yields no stats flips _hbm_supported off after the first beat
        # so an unsupported backend costs one probe, not one per tick
        self._devices = None
        self._hbm_supported = True
        self._max_bytes = progress_max_bytes()
        self._stop_ev = threading.Event()
        self._state_lock = threading.Lock()
        self._emit_lock = threading.Lock()
        self._closed = False
        self._ok = True
        self._started = False
        self._stopped = False
        self._thread = None

    # ---- producer-side knobs ------------------------------------------
    def set_total(self, n: int) -> None:
        """The ingested-window count (known at pass A's end).  Set
        once and never overwritten — ``windows_ingested / windows_total``
        must stay <= 1 for a progress consumer."""
        self._total = int(n)

    def set_parts_total(self, n: int) -> None:
        """The exact output-part count (known at pass C — residual
        windows drop, the realigned part joins): the ETA extrapolates
        ``parts_written`` against this, falling back to the window
        count until it is known."""
        self._parts_total = int(n)

    def set_provider(self, fn) -> None:
        """Register a callable returning extra field values (only keys
        in :data:`HEARTBEAT_FIELDS` are honored; the streamed pipeline
        supplies per-device in-flight depth this way)."""
        self._provider = fn

    def set_devices(self, devices) -> None:
        """The device set whose HBM footprint each beat samples
        (default: every local jax device).  The streamed pipeline
        passes its pool's devices so the per-device keys match the
        ``device=<k>`` span attribution."""
        self._devices = list(devices)

    def _sample_hbm(self) -> dict:
        """One HBM poll (graceful {} when unsupported), recorded into
        the first tracer's ``hbm`` ledger so the run snapshot carries
        the per-window peaks a tailing consumer saw live."""
        if not self._hbm_supported:
            return {}
        try:
            stats = sample_hbm(self._devices)
        except Exception:
            stats = {}
        if not stats:
            self._hbm_supported = False
            return {}
        if self._tracers:
            tr = self._tracers[0]
            total = 0
            for key, s in stats.items():
                tr.record_hbm(key, s["bytes_in_use"],
                              s["peak_bytes_in_use"])
                total += s["bytes_in_use"]
            tr.gauge(G_HBM_IN_USE, total)
        return stats

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> None:
        with self._state_lock:
            if self._started:
                return
            self._started = True
        self._t0 = time.monotonic()
        if self._sink != "stderr":
            try:
                # append, as documented: back-to-back runs pointed at
                # one log keep their history (runs delimit themselves —
                # seq restarts at 0 and the last line carries done=true).
                # Line-buffered: each line is one write()+implicit flush,
                # so a tailing consumer (`adam-tpu top`) never reads a
                # torn last line from the stdio buffer boundary.
                self._fh = open(self._sink, "a", buffering=1)
                self._owns_fh = True
            except OSError:
                import logging

                logging.getLogger(__name__).warning(
                    "cannot open progress sink %s; falling back to "
                    "stderr", self._sink, exc_info=True,
                )
                self._fh = None
        self._emit(done=False)
        self._thread = threading.Thread(
            target=self._loop, name="adam-tpu-heartbeat", daemon=True
        )
        self._thread.start()

    def stop(self, ok: bool = True) -> None:
        """Final ``done=true`` line + teardown.  ``ok=False`` marks the
        run as crashed on that line — without it a consumer tailing the
        stream would read an exception-path exit as a completed run."""
        if not ok:
            self._ok = False
        with self._state_lock:
            if not self._started or self._stopped:
                return
            self._stopped = True
        self._stop_ev.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._emit(done=True)
        if self._owns_fh and self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def _loop(self) -> None:
        while not self._stop_ev.wait(self._interval):
            self._emit(done=False)

    def _maybe_rotate(self) -> None:
        """Size-capped rotation of a file sink (caller holds the emit
        lock, so no line can be torn across the rotation): past the
        ``ADAM_TPU_PROGRESS_MAX_BYTES`` cap the current file moves to
        ``<path>.1`` (replacing any previous rotation) and a fresh file
        continues — bounded disk for service-style multi-hour runs,
        and a tailing consumer sees a normal truncate-to-zero.

        Called BEFORE each write, never after: the newest line — in
        particular the final ``done=true`` line — must always be in
        the live file, or a tailer (``adam-tpu top``) could watch a
        fresh empty file forever while the line that ends its loop
        sits in the rotation."""
        if (
            not self._max_bytes or not self._owns_fh
            or self._fh is None
        ):
            return
        try:
            if self._fh.tell() < self._max_bytes:
                return
            self._fh.close()
            os.replace(self._sink, self._sink + ".1")
            self._fh = open(self._sink, "a", buffering=1)
        except OSError:
            # rotation is hygiene, not correctness: on failure keep
            # appending to whatever handle still works
            try:
                if self._fh is None or self._fh.closed:
                    self._fh = open(self._sink, "a", buffering=1)
            except OSError:
                self._fh = None

    # ---- sampling ------------------------------------------------------
    def sample(self, done: bool = False) -> dict:
        """One heartbeat line as a dict (exactly HEARTBEAT_FIELDS)."""
        counters: dict = {}
        gauges: dict = {}
        for tr in self._tracers:
            trc, trg = tr.counters_and_gauges()
            for k, v in trc.items():
                counters[k] = counters.get(k, 0) + v
            for k, v in trg.items():
                gauges.setdefault(k, v)
        elapsed = time.monotonic() - (self._t0 or time.monotonic())
        reads = counters.get(C_READS_INGESTED, 0)
        parts = counters.get(C_PARTS_WRITTEN, 0)
        total = self._total
        parts_total = (
            self._parts_total if self._parts_total is not None else total
        )
        eta = None
        if parts_total and parts:
            eta = round(elapsed * max(0, parts_total - parts) / parts, 1)
        hbm = self._sample_hbm()
        line = {
            "schema": HEARTBEAT_SCHEMA,
            "seq": self._seq,
            "elapsed_s": round(elapsed, 3),
            "windows_ingested": counters.get(C_WINDOWS_INGESTED, 0),
            "windows_total": total,
            # resumed-vs-fresh visibility: parts_written / eta_s already
            # count only THIS process's work (the skipped windows never
            # reach the writer pool), so this is the one field a
            # consumer needs to tell a resumed completion from a fresh
            # one
            "windows_resumed": counters.get(C_RESUME_WINDOWS_SKIPPED, 0),
            "parts_written": parts,
            "reads_ingested": reads,
            "reads_per_s": (
                round(reads / elapsed, 1) if elapsed > 0 else 0.0
            ),
            "bytes_written": counters.get(C_BYTES_WRITTEN, 0),
            # tunnel byte accounting (the transfer ledger's run totals)
            "h2d_bytes": counters.get(C_H2D_BYTES, 0),
            "d2h_bytes": counters.get(C_D2H_BYTES, 0),
            # HBM footprint per device ({} + null on backends without
            # memory_stats — an explicit "unsupported" marker, never
            # fabricated zeros)
            "hbm_bytes_in_use": {
                k: v["bytes_in_use"] for k, v in hbm.items()
            },
            "hbm_peak_bytes": (
                max(v["peak_bytes_in_use"] for v in hbm.values())
                if hbm else None
            ),
            "inflight": gauges.get(G_DEVICE_INFLIGHT, {}).get("last", 0),
            "inflight_per_device": {},
            "retries": counters.get(C_RETRY_ATTEMPTS, 0),
            "faults": counters.get(C_FAULT_INJECTED, 0),
            "devices_evicted": counters.get(C_DEVICE_EVICTED, 0),
            "eta_s": eta,
            "done": done,
            "ok": self._ok,
            # overridden by the streamed provider with the live mode
            # ("pool" | "mesh"); None = the producer predates /3 fields
            "partitioner": None,
            # cross-job batching (/4): derived from the coalescer's
            # counters whenever the sampled tracers carry them (the
            # service-wide heartbeat samples the global TRACE, which
            # the coalescer records on); null otherwise
            "batch_fill": (
                round(
                    counters[C_BATCH_ROWS_OCCUPIED]
                    / counters[C_BATCH_ROWS_DISPATCHED], 4,
                )
                if counters.get(C_BATCH_ROWS_DISPATCHED) else None
            ),
            "batched_jobs": gauges.get(G_BATCH_JOBS, {}).get("last"),
            "device_health": _health_states_for_heartbeat(),
        }
        # trace/incident activity (/6): live registry + the newest
        # bundle recorded by this process (both process-wide, like the
        # health scoreboard)
        inc_id, inc_age = _incident_for_heartbeat()
        line["active_traces"] = len(active_traces())
        line["metrics_scrapes"] = counters.get(C_GW_SCRAPES, 0)
        line["last_incident"] = inc_id
        line["last_incident_age_s"] = inc_age
        # judgment layer (/7): worst burn across armed SLO objectives
        # (process-wide, like the incident recorder) + flagged perf
        # regressions
        line["slo_worst_burn"] = _slo_for_heartbeat()
        line["perf_regressions"] = counters.get(C_PERF_REGRESSIONS, 0)
        if self._provider is not None:
            try:
                for k, v in (self._provider() or {}).items():
                    if k in HEARTBEAT_FIELDS:
                        line[k] = v
            except Exception:  # provider bugs must not kill the beat
                pass
        return line

    def _emit(self, done: bool) -> None:
        # one writer at a time: without the lock, a daemon thread
        # stalled inside fh.write past stop()'s join timeout could race
        # the final done=true line — duplicate seq values, a periodic
        # line AFTER the final one, or a write to the closed handle.
        # Bounded acquire so a wedged sink makes stop() drop its final
        # line instead of hanging the pipeline on exit.
        if not self._emit_lock.acquire(timeout=5.0):
            return
        try:
            if self._closed:
                return
            if done:
                self._closed = True
            self._maybe_rotate()
            line = self.sample(done)
            self._seq += 1
            fh = self._fh if self._fh is not None else sys.stderr
            fh.write(json.dumps(line, default=str) + "\n")
            fh.flush()
        except Exception:
            # a torn sink (closed stderr under pytest, full disk) must
            # never take the pipeline down with it
            pass
        finally:
            self._emit_lock.release()
