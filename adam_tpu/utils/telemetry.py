"""Structured tracing + metrics: spans, counters, gauges, flight recorder.

The observability layer the reference gets from bdg-utils ``Metrics`` +
Spark's listener-decomposed stage/task timings
(``instrumentation/Timers.scala:25-81``, ``ADAMCommand.scala:56-89``),
built for the overlapped streamed pipeline: flat named timers
(:mod:`adam_tpu.utils.instrumentation`, which this module subsumes)
cannot show queue depths, per-window latency, or where the
tokenize/dispatch/fetch/encode/write overlap breaks down.

Three primitives, one lock discipline (the ``TimerRegistry`` one —
single mutex, read-modify-write only under it):

* **spans** — ``with TRACE.span("bqsr.apply.dispatch", window=i):``
  records a timestamped interval with thread and parent attribution
  into (a) a per-name aggregate (count, total ns) and (b) a bounded
  in-memory **flight recorder** (ring buffer — long runs cannot OOM;
  evictions keep the newest events and are counted).
* **counters** — monotonically accumulated ints (reads ingested, bytes
  encoded/written, device windows dispatched/fetched).
* **gauges** — sampled values with last/min/max/n (writer-pool queue
  depth at submit/drain, device dispatch in-flight).

Exports: :meth:`Tracer.to_json` (the ``--metrics-json`` snapshot, whose
``timers`` section is byte-identical to the ``-print_metrics`` table)
and :meth:`Tracer.to_chrome_trace` (the ``--trace-out`` view — complete
events on per-thread tracks, loadable in chrome://tracing / Perfetto,
so the streamed overlap is visually inspectable).

Disabled-by-default cost is one branch per call site: ``span()``
returns a shared no-op context manager and ``count()``/``gauge()``
return immediately when ``recording`` is off (micro-benchmark in
docs/OBSERVABILITY.md).  The streamed pipeline records its stage spans
into a private always-on :class:`Tracer` (a handful of events per
window) and derives its ``stats`` dict from them via
:func:`streamed_stats_view`, so the dict and the span data can never
disagree; the run tracer is absorbed into the global :data:`TRACE`
when recording is on.

Every span/counter/gauge name is declared here (the ``_span``/
``_metric`` registrations below) — a **stable contract** documented in
docs/OBSERVABILITY.md and lint-enforced by
``scripts/check-telemetry-names``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

# One process-wide trace epoch so timestamps from every Tracer (the
# global TRACE, streamed run tracers, absorbed events) land on a single
# comparable time axis in the Chrome-trace export.
_EPOCH_NS = time.monotonic_ns()

# --------------------------------------------------------------------------
# Name registry — the stable contract (docs/OBSERVABILITY.md)
# --------------------------------------------------------------------------
_REGISTERED_SPANS: set = set()
_REGISTERED_METRICS: set = set()


def _span(name: str) -> str:
    _REGISTERED_SPANS.add(name)
    return name


def _metric(name: str) -> str:
    _REGISTERED_METRICS.add(name)
    return name


# ---- streamed-pipeline stage spans (pipelines/streamed.py; the stats
# dict keys derive from these via streamed_stats_view) ----
SPAN_PASS_A = _span("streamed.pass_a.ingest")
SPAN_TOKENIZE = _span("streamed.tokenize")
SPAN_MD_FETCH = _span("streamed.markdup.fetch")
SPAN_RESOLVE = _span("streamed.barrier.resolve")
SPAN_SPLIT = _span("streamed.pass_b.split")
SPAN_OBSERVE = _span("streamed.observe")
SPAN_TAIL = _span("streamed.tail")
SPAN_OBS_MERGE = _span("streamed.observe.merge_fetch")
SPAN_SOLVE = _span("streamed.barrier.solve")
SPAN_PASS_C = _span("streamed.pass_c")
SPAN_APPLY_DISPATCH = _span("streamed.apply.dispatch")
SPAN_APPLY_FETCH = _span("streamed.apply.fetch")
SPAN_WRITE_WAIT = _span("streamed.write_wait")
SPAN_TOTAL = _span("streamed.total")

# ---- per-call spans with backend attribution (pipelines/bqsr.py,
# pipelines/markdup.py) ----
SPAN_BQSR_OBSERVE = _span("bqsr.observe.window")
SPAN_BQSR_APPLY_DISPATCH = _span("bqsr.apply.dispatch")
SPAN_BQSR_APPLY_FETCH = _span("bqsr.apply.fetch")
SPAN_BQSR_APPLY_HOST = _span("bqsr.apply.host")
SPAN_MD_COLUMNS = _span("markdup.columns.dispatch")

# ---- device pool (parallel/device_pool.py): multi-chip round-robin
# dispatch + per-device compile prewarm.  Dispatch/fetch spans carry a
# ``device=<k>`` attribution (the jax device id), which (a) aggregates
# into the snapshot's ``device_spans`` section (per-chip occupancy/
# skew) and (b) mirrors onto a per-chip ``device:<k>`` track in the
# Chrome-trace export.  The prewarm records one WALL umbrella span per
# run (concurrent per-compile spans sum past wall, so the derived
# ``prewarm_s`` comes from the umbrella) plus one compile span per
# (kernel shape, device). ----
SPAN_POOL_PREWARM = _span("device.pool.prewarm")
SPAN_POOL_PREWARM_C = _span("device.pool.prewarm.pass_c")
SPAN_POOL_PREWARM_COMPILE = _span("device.pool.prewarm.compile")
# ---- resilience (utils/faults.py, utils/retry.py, the streamed
# recovery paths): one ``device.pool.replay`` span per window whose
# device work was replayed on a survivor (or the host backend) after a
# failure, with ``device=<k>`` naming the chip that FAILED. ----
SPAN_POOL_REPLAY = _span("device.pool.replay")

# ---- io/parquet.py part-writer spans ----
SPAN_PART_ENCODE = _span("parquet.part.encode")
SPAN_PART_WRITE = _span("parquet.part.write")

# ---- native tokenizer/codec spans share the timer-table names
# (native/__init__.py records each dispatch as BOTH a timer row and a
# span, so the flight recorder sees the codec work on its thread) ----
from adam_tpu.utils import instrumentation as _ins  # noqa: E402

for _n in (
    _ins.TOKENIZE_INPUT, _ins.BGZF_CODEC, _ins.PARQUET_ENCODE,
    _ins.PARQUET_WRITE, _ins.SAM_ENCODE, _ins.FASTQ_ENCODE,
    _ins.OBSERVE_WALK, _ins.APPLY_WALK,
):
    _span(_n)

# ---- counters ----
C_READS_INGESTED = _metric("reads.ingested")
C_WINDOWS_INGESTED = _metric("windows.ingested")
C_DEVICE_DISPATCHED = _metric("device.windows.dispatched")
C_DEVICE_FETCHED = _metric("device.windows.fetched")
C_BYTES_ENCODED = _metric("parquet.bytes.encoded")
C_BYTES_WRITTEN = _metric("parquet.bytes.written")
C_PARTS_WRITTEN = _metric("parquet.parts.written")
C_CANDIDATE_ROWS = _metric("realign.candidate_rows")
C_POOL_PREWARM_COMPILES = _metric("device.pool.prewarm.compiles")
# resilience counters: injected faults (utils/faults.point), retry
# attempts actually taken (utils/retry.retry_call — 0 on a clean run),
# and devices evicted from the pool after a spent retry budget
C_FAULT_INJECTED = _metric("fault.injected")
C_RETRY_ATTEMPTS = _metric("retry.attempts")
C_DEVICE_EVICTED = _metric("device.evicted")

# ---- gauges ----
G_POOL_DEPTH = _metric("parquet.pool.queue_depth")
G_DEVICE_INFLIGHT = _metric("device.dispatch.in_flight")
G_OBSERVE_HIDDEN = _metric("streamed.observe_overlap_hidden")
G_POOL_DEVICES = _metric("device.pool.devices")

#: Device-only metrics: the paired-CPU bench baseline zeroes these
#: instead of omitting them so round-over-round diffs are key-stable.
DEVICE_ONLY_COUNTERS = frozenset(
    {C_DEVICE_DISPATCHED, C_DEVICE_FETCHED, C_POOL_PREWARM_COMPILES}
)
DEVICE_ONLY_GAUGES = frozenset({G_DEVICE_INFLIGHT, G_POOL_DEVICES})


def registered_spans() -> frozenset:
    return frozenset(_REGISTERED_SPANS)


def registered_metrics() -> frozenset:
    return frozenset(_REGISTERED_METRICS)


def registered_names() -> frozenset:
    """Every declared span/counter/gauge name — the contract the
    ``scripts/check-telemetry-names`` lint enforces against call-site
    string literals."""
    return frozenset(_REGISTERED_SPANS | _REGISTERED_METRICS)


# --------------------------------------------------------------------------
# Span context managers
# --------------------------------------------------------------------------
class _NullSpan:
    """Shared no-op span: the disabled fast path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tr", "name", "attrs", "_t0", "_parent")

    def __init__(self, tr: "Tracer", name: str, attrs: dict):
        self._tr = tr
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        tls = self._tr._tls
        self._parent = getattr(tls, "span", None)
        tls.span = self
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, *exc):
        dur = time.monotonic_ns() - self._t0
        self._tr._tls.span = self._parent
        self._tr._record(
            self.name, self._t0, dur, self.attrs,
            self._parent.name if self._parent is not None else None,
        )
        return False


class Tracer:
    """Span/counter/gauge recorder with a bounded flight recorder.

    Thread-safe under one mutex (the ``TimerRegistry`` lock
    discipline); per-name aggregates live OUTSIDE the ring, so span
    totals stay exact even after the ring evicts old events.
    """

    def __init__(self, recording: bool = False, capacity: int | None = None):
        if capacity is None:
            raw = os.environ.get("ADAM_TPU_TRACE_EVENTS", "")
            try:
                capacity = int(raw)
            except ValueError:
                # the module-level TRACE constructs at import time from
                # every entry point: a malformed tuning var must degrade
                # to the default, not brick the CLI with a ValueError
                if raw:
                    import logging

                    logging.getLogger(__name__).warning(
                        "ADAM_TPU_TRACE_EVENTS=%r is not an int; using "
                        "default 65536", raw,
                    )
                capacity = 65536
        self.recording = recording
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(1, capacity))
        self._spans: dict = {}     # name -> [count, total_ns]
        self._dev_spans: dict = {} # name -> {device key -> [count, total_ns]}
        self._counters: dict = {}  # name -> int
        self._gauges: dict = {}    # name -> {last, min, max, n}
        self._tls = threading.local()
        self._n_recorded = 0

    # ---- recording --------------------------------------------------------
    def span(self, name: str, **attrs):
        """Span context manager; a shared no-op when not recording."""
        if not self.recording:
            return _NULL_SPAN
        return _Span(self, name, attrs)

    def add_span(self, name: str, start_ns: int, dur_ns: int,
                 thread: str | None = None, **attrs) -> None:
        """Record an externally-measured interval (monotonic_ns clock)."""
        if not self.recording:
            return
        self._record(name, start_ns, dur_ns, attrs, None, thread)

    def _record(self, name, t0, dur, attrs, parent, thread=None):
        ev = {
            "name": name,
            "ts_ns": t0,
            "dur_ns": dur,
            "thread": thread or threading.current_thread().name,
        }
        if parent:
            ev["parent"] = parent
        if attrs:
            ev["args"] = dict(attrs)
        dev = (attrs or {}).get("device")
        with self._lock:
            self._events.append(ev)
            self._n_recorded += 1
            agg = self._spans.get(name)
            if agg is None:
                self._spans[name] = [1, dur]
            else:
                agg[0] += 1
                agg[1] += dur
            if dev is not None:
                # per-device aggregate: the snapshot's device_spans
                # section (chip occupancy + skew; time-sliced chips are
                # NOT symmetric, so per-device walls must be separable)
                per = self._dev_spans.setdefault(name, {})
                dagg = per.get(dev)
                if dagg is None:
                    per[dev] = [1, dur]
                else:
                    dagg[0] += 1
                    dagg[1] += dur

    def count(self, name: str, n: int = 1) -> None:
        if not self.recording:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value) -> None:
        if not self.recording:
            return
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                self._gauges[name] = {
                    "last": value, "min": value, "max": value, "n": 1,
                }
            else:
                g["last"] = value
                if value < g["min"]:
                    g["min"] = value
                if value > g["max"]:
                    g["max"] = value
                g["n"] += 1

    # ---- reading ----------------------------------------------------------
    def span_seconds(self) -> dict:
        """Per-name total span seconds (concurrency-safe copy)."""
        with self._lock:
            return {k: v[1] / 1e9 for k, v in self._spans.items()}

    def events(self) -> list:
        """Copy of the flight-recorder ring (oldest surviving first)."""
        with self._lock:
            return [dict(e) for e in self._events]

    def snapshot(self) -> dict:
        """Aggregate view (spans/counters/gauges), safe to call
        concurrently with recording.  Does NOT include the event ring —
        that is the Chrome-trace export's job."""
        with self._lock:
            return {
                "spans": {
                    k: {"count": v[0], "total_s": v[1] / 1e9}
                    for k, v in self._spans.items()
                },
                "device_spans": {
                    name: {
                        str(d): {"count": v[0], "total_s": v[1] / 1e9}
                        for d, v in per.items()
                    }
                    for name, per in self._dev_spans.items()
                },
                "counters": dict(self._counters),
                "gauges": {k: dict(v) for k, v in self._gauges.items()},
                "events_recorded": self._n_recorded,
                "events_retained": len(self._events),
                "events_evicted": self._n_recorded - len(self._events),
            }

    # ---- lifecycle --------------------------------------------------------
    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._spans.clear()
            self._dev_spans.clear()
            self._counters.clear()
            self._gauges.clear()
            self._n_recorded = 0

    def reset_metrics(self) -> None:
        """Clear counters + gauges only (TimerRegistry.reset delegates
        here so one reset clears the whole metrics surface)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()

    def absorb(self, other: "Tracer") -> None:
        """Merge another tracer's events + aggregates into this one
        (the streamed run tracer folds into the global TRACE)."""
        with other._lock:
            events = [dict(e) for e in other._events]
            spans = {k: list(v) for k, v in other._spans.items()}
            dev_spans = {
                k: {d: list(v) for d, v in per.items()}
                for k, per in other._dev_spans.items()
            }
            counters = dict(other._counters)
            gauges = {k: dict(v) for k, v in other._gauges.items()}
            n_rec = other._n_recorded
        with self._lock:
            self._events.extend(events)
            self._n_recorded += n_rec
            for k, (c, ns) in spans.items():
                agg = self._spans.get(k)
                if agg is None:
                    self._spans[k] = [c, ns]
                else:
                    agg[0] += c
                    agg[1] += ns
            for k, per in dev_spans.items():
                mine = self._dev_spans.setdefault(k, {})
                for d, (c, ns) in per.items():
                    dagg = mine.get(d)
                    if dagg is None:
                        mine[d] = [c, ns]
                    else:
                        dagg[0] += c
                        dagg[1] += ns
            for k, v in counters.items():
                self._counters[k] = self._counters.get(k, 0) + v
            for k, g in gauges.items():
                mine = self._gauges.get(k)
                if mine is None:
                    self._gauges[k] = dict(g)
                else:
                    mine["last"] = g["last"]
                    mine["min"] = min(mine["min"], g["min"])
                    mine["max"] = max(mine["max"], g["max"])
                    mine["n"] += g["n"]

    # ---- exports ----------------------------------------------------------
    def to_json(self, timers=None, include_events: bool = False) -> dict:
        """The ``--metrics-json`` document.  ``timers`` defaults to the
        process-wide :data:`~adam_tpu.utils.instrumentation.TIMERS`;
        its section carries the same (count, total_s) rows as the
        printed ``-print_metrics`` table, so the two cannot drift.
        ``include_events=True`` appends the flight-recorder ring (the
        dump-on-error view)."""
        if timers is None:
            timers = _ins.TIMERS
        doc = self.snapshot()
        doc["timers"] = {
            name: {"count": c, "total_s": ns / 1e9}
            for name, (c, ns) in sorted(timers.snapshot().items())
        }
        doc["meta"] = {
            "pid": os.getpid(),
            "epoch_ns": _EPOCH_NS,
            "schema": "adam_tpu.telemetry/1",
        }
        if include_events:
            doc["events"] = self.events()
        return doc

    def to_chrome_trace(self) -> dict:
        """Flight recorder -> Chrome trace-event JSON (Perfetto /
        chrome://tracing).  Each recording thread gets its own track, so
        the streamed tokenize/dispatch/fetch/encode/write overlap is
        visually inspectable.  Events carrying a ``device=<k>``
        attribution (the multi-chip pool's dispatch/fetch/prewarm spans)
        are additionally mirrored onto a ``device:<k>`` track — one
        track per chip, so per-device queue occupancy and skew are
        visible next to the host threads."""
        evs = self.events()
        pid = os.getpid()
        tids: dict = {}
        out = []

        def _tid(track: str) -> int:
            if track not in tids:
                tids[track] = len(tids) + 1
                out.append({
                    "ph": "M", "pid": pid, "tid": tids[track],
                    "name": "thread_name", "args": {"name": track},
                })
            return tids[track]

        for e in evs:
            _tid(e["thread"])
        for e in evs:
            ev = {
                "ph": "X",
                "pid": pid,
                "tid": tids[e["thread"]],
                "name": e["name"],
                "cat": "adam_tpu",
                "ts": (e["ts_ns"] - _EPOCH_NS) / 1e3,  # microseconds
                "dur": e["dur_ns"] / 1e3,
            }
            args = dict(e.get("args") or {})
            if "parent" in e:
                args["parent"] = e["parent"]
            if args:
                ev["args"] = args
            out.append(ev)
            dev = (e.get("args") or {}).get("device")
            if dev is not None:
                mirror = dict(ev)
                mirror["tid"] = _tid(f"device:{dev}")
                out.append(mirror)
        return {"traceEvents": out, "displayTimeUnit": "ms"}

    def dump_json(self, path: str, timers=None,
                  include_events: bool = False) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_json(timers, include_events=include_events),
                      fh, indent=1, default=str)

    def dump_chrome_trace(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_chrome_trace(), fh, default=str)

    def report(self) -> str:
        """Counters/gauges table printed below the timer table by
        ``-print_metrics``."""
        snap = self.snapshot()
        out = []
        if snap["counters"]:
            w = max(len(k) for k in snap["counters"])
            out += ["Counters", "========"]
            out.append(f"{'counter'.ljust(w)}  {'value':>14}")
            for k in sorted(snap["counters"]):
                out.append(f"{k.ljust(w)}  {snap['counters'][k]:>14}")
            out.append("")
        if snap["gauges"]:
            w = max(len(k) for k in snap["gauges"])
            out += ["Gauges", "======"]
            out.append(
                f"{'gauge'.ljust(w)}  {'last':>8}  {'min':>8}  {'max':>8}"
                f"  {'samples':>8}"
            )
            for k in sorted(snap["gauges"]):
                g = snap["gauges"][k]
                out.append(
                    f"{k.ljust(w)}  {g['last']:>8}  {g['min']:>8}"
                    f"  {g['max']:>8}  {g['n']:>8}"
                )
            out.append("")
        if not out:
            return "Counters/Gauges\n===============\n(none recorded)\n"
        return "\n".join(out)


#: Process-wide tracer — the ``object Timers`` analog for the
#: structured layer.  Off by default; the CLI flips it on for
#: ``-print_metrics`` / ``--metrics-json`` / ``--trace-out``.
TRACE = Tracer()


# --------------------------------------------------------------------------
# Derived views
# --------------------------------------------------------------------------
def streamed_stats_view(snap: dict) -> dict:
    """Rebuild the streamed pipeline's timing ``stats`` keys from span
    data (a :meth:`Tracer.snapshot`).  ``transform_streamed`` itself
    calls this on its run tracer — the stats dict IS this view, so the
    printed stats and the span data cannot disagree, and a test can
    recompute the view from an exported snapshot.
    """
    spans = snap.get("spans", {})

    def s(name):
        e = spans.get(name)
        return e["total_s"] if e else None

    out = {}
    for key, name in (
        ("prewarm_s", SPAN_POOL_PREWARM),
        ("ingest_pass_s", SPAN_PASS_A),  # prewarm subtracted below
        ("md_cols_fetch_s", SPAN_MD_FETCH),
        ("resolve_s", SPAN_RESOLVE),
        ("split_s", SPAN_SPLIT),
        ("observe_s", SPAN_OBSERVE),
        ("obs_merge_fetch_s", SPAN_OBS_MERGE),
        ("solve_s", SPAN_SOLVE),
        ("apply_device_dispatch_s", SPAN_APPLY_DISPATCH),
        ("apply_device_fetch_s", SPAN_APPLY_FETCH),
        ("write_wait_s", SPAN_WRITE_WAIT),
        ("total_s", SPAN_TOTAL),
    ):
        v = s(name)
        if v is not None:
            out[key] = v
    if "prewarm_s" in out and "ingest_pass_s" in out:
        # the prewarm umbrella is nested inside pass A (it fires on the
        # first ingested window): subtract it so the stage rows stay
        # disjoint and sum to the pipeline wall
        out["ingest_pass_s"] = max(
            0.0, out["ingest_pass_s"] - out["prewarm_s"]
        )
    # the pass-C re-warm (the solved table's real width) is nested
    # inside pass C: fold its wall into prewarm_s for the headline, and
    # remember it for the apply_split subtraction below — real compile
    # time must never masquerade as host encode/submit time
    prewarm_c = s(SPAN_POOL_PREWARM_C)
    if prewarm_c is not None:
        out["prewarm_s"] = out.get("prewarm_s", 0.0) + prewarm_c
    tail = s(SPAN_TAIL)
    if tail is not None:
        obs = s(SPAN_OBSERVE) or 0.0
        hidden = bool(
            snap.get("gauges", {}).get(G_OBSERVE_HIDDEN, {}).get("last", 0)
        )
        had_candidates = (
            snap.get("counters", {}).get(C_CANDIDATE_ROWS, 0) > 0
        )
        if had_candidates:
            # subtract the observe wall only when it genuinely ran
            # under the realign sweeps' device drain (streamed.py's
            # observe_overlap_hidden semantics)
            out["realign_s"] = tail - obs if hidden else tail
        else:
            out["realign_s"] = max(0.0, tail - obs)
    pass_c = s(SPAN_PASS_C)
    if pass_c is not None:
        # host share of pass C: the device dispatch/fetch walls (and
        # any pass-C re-warm compiles) are their own disjoint rows
        out["apply_split_s"] = max(
            0.0,
            pass_c
            - (s(SPAN_APPLY_DISPATCH) or 0.0)
            - (s(SPAN_APPLY_FETCH) or 0.0)
            - (prewarm_c or 0.0),
        )
    return out


def key_stable_snapshot(tr: Tracer | None = None) -> dict:
    """Snapshot with device-only counters/gauges ensured present (as
    zeros) — the bench's paired-CPU-baseline path uses this so
    round-over-round artifact diffs are key-stable."""
    snap = (tr or TRACE).snapshot()
    for name in sorted(DEVICE_ONLY_COUNTERS):
        snap["counters"].setdefault(name, 0)
    for name in sorted(DEVICE_ONLY_GAUGES):
        snap["gauges"].setdefault(
            name, {"last": 0, "min": 0, "max": 0, "n": 0}
        )
    snap.setdefault("device_spans", {})
    return snap


def merge_snapshots(snaps: list) -> dict:
    """Combine per-host snapshots (parallel/dist.gather_host_telemetry)
    into one report with per-host skew: for every span name, the
    min/max total wall across hosts — the Spark-listener per-executor
    skew view."""
    skew = {}
    for snap in snaps:
        for name, e in snap.get("spans", {}).items():
            sk = skew.setdefault(
                name, {"min_s": e["total_s"], "max_s": e["total_s"]}
            )
            sk["min_s"] = min(sk["min_s"], e["total_s"])
            sk["max_s"] = max(sk["max_s"], e["total_s"])
    return {"n_hosts": len(snaps), "hosts": snaps, "span_skew": skew}
