"""Run analyzer: attribution + critical path over telemetry artifacts.

The flight recorder (utils/telemetry.py) answers "what happened"; this
module answers the ROADMAP's measurement questions from a finished run
artifact — no re-run required:

* **Per-device wall-time attribution** — gap analysis over the
  ``device=<k>`` span tracks of a Chrome-trace export: busy (union of
  the device's dispatch/fetch/compile intervals, clamped to the run
  window), idle (wall minus busy — where chips sit between
  double-buffered windows), fetch (the ``*.fetch*`` subset) and replay
  (recovery wall: a survivor's re-run windows via the ``replay=1``
  attribution, an evicted chip's ``device.pool.replay`` umbrellas).
  Evicted devices stay in the report — their pre-eviction spans keep
  their original key (telemetry ``device_spans`` contract).
* **Barrier stall decomposition** — pass A ingest vs barrier-1 resolve
  vs barrier-2 observe-fetch/solve vs pass C and the write tail, as
  disjoint stage walls plus their fraction of the run.
* **Window-level critical path** — the Dapper-style last-finisher
  chain walked backward from the last event: at each step, the edge to
  the event that finished latest before the current one started.  The
  top-N longest edges name the spans (with their ``window=`` attrs)
  that bound the run wall — shaving anything else cannot shorten it.
* **Latency histograms** — per-span-name p50/p90/p99 (from the
  snapshot's ``histograms`` section, or rebuilt from trace events with
  the same fixed log-spaced buckets), because synchronized multi-device
  pipelines are governed by tails, not means (Dean & Barroso).

Two input shapes, one report: a ``--metrics-json`` snapshot (aggregate
mode — exact totals, no gap analysis) or a ``--trace-out`` Chrome trace
(event mode — true interval unions and the critical path).  Exposed as
``adam-tpu analyze <artifact.json>``, as ``--report PATH`` on the
streamed transform, and embedded by ``bench.py`` (the ``utilization``
key) so every bench artifact lands with attribution built in.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from adam_tpu.utils import telemetry as tele

#: Span-name fragments that classify a device-attributed event as a
#: device->host fetch (the barrier-2 / pass-C transfer side).
_FETCH_MARK = ".fetch"

#: The replay umbrella: wall a device's FAILURE caused (recorded
#: against the failed chip; the survivor's re-run work carries
#: ``replay=1`` instead).
_REPLAY_SPAN = tele.SPAN_POOL_REPLAY

#: Stage spans whose union is the whole streamed run — the barrier
#: decomposition rows, in pipeline order.
_STAGES = (
    ("pass_a_ingest", tele.SPAN_PASS_A),
    ("barrier1_resolve", tele.SPAN_RESOLVE),
    ("pass_b_split", tele.SPAN_SPLIT),
    ("observe", tele.SPAN_OBSERVE),
    ("tail_realign", tele.SPAN_TAIL),
    ("barrier2_observe_fetch", tele.SPAN_OBS_MERGE),
    ("barrier2_solve", tele.SPAN_SOLVE),
    ("pass_c_apply", tele.SPAN_PASS_C),
    ("write_tail", tele.SPAN_WRITE_WAIT),
)


def load_document(path: str) -> dict:
    """Read a telemetry artifact (snapshot or Chrome trace) from disk."""
    with open(path) as fh:
        return json.load(fh)


def document_kind(doc: dict) -> str:
    """``"trace"`` (Chrome trace-event JSON) or ``"snapshot"``
    (``--metrics-json`` / ``Tracer.snapshot()`` shape)."""
    if "traceEvents" in doc:
        return "trace"
    if "spans" in doc or "device_spans" in doc:
        return "snapshot"
    raise ValueError(
        "not a telemetry artifact: expected a Chrome trace "
        "('traceEvents') or a metrics snapshot ('spans')"
    )


# --------------------------------------------------------------------------
# Trace-event plumbing
# --------------------------------------------------------------------------
def _trace_spans(doc: dict) -> list:
    """Normalized complete events: [{name, start, end, dur, args}] in
    seconds, de-duplicated of the per-chip mirror copies (to_chrome_trace
    emits every device-attributed span twice — once on its host-thread
    track, once on its ``device:<k>`` track; attribution must count each
    interval ONCE).  Mirrors carry ``cat = CHROME_MIRROR_CAT``; traces
    from before that marker existed fall back to a timestamp-identity
    dedup restricted to device-attributed events (the only ones that
    ever had mirrors)."""
    evs = [e for e in doc.get("traceEvents", []) if e.get("ph") == "X"]
    has_marker = any(e.get("cat") == tele.CHROME_MIRROR_CAT for e in evs)
    out = []
    seen = set()
    for e in evs:
        if e.get("cat") == tele.CHROME_MIRROR_CAT:
            continue
        if (
            not has_marker
            and (e.get("args") or {}).get("device") is not None
        ):
            key = (e.get("name"), e.get("ts"), e.get("dur"), e.get("pid"))
            if key in seen:
                continue
            seen.add(key)
        start = e["ts"] / 1e6
        dur = e.get("dur", 0.0) / 1e6
        out.append({
            "name": e["name"],
            "start": start,
            "end": start + dur,
            "dur": dur,
            "args": e.get("args") or {},
        })
    out.sort(key=lambda s: (s["start"], s["end"]))
    return out


def _union_seconds(intervals: list, lo: float, hi: float) -> float:
    """Total covered wall of [start, end) intervals clamped to
    [lo, hi] — nested/overlapping spans (a dispatch under its replay
    umbrella, double-buffered fetch under pass C) must not double
    count."""
    clipped = sorted(
        (max(s, lo), min(e, hi)) for s, e in intervals if min(e, hi) > max(s, lo)
    )
    total = 0.0
    cur_s = cur_e = None
    for s, e in clipped:
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    if cur_e is not None:
        total += cur_e - cur_s
    return total


def _run_window(spans: list) -> tuple:
    """The run's [start, end] on the trace clock: the ``streamed.total``
    span when present (the pipeline wall), else the event envelope."""
    for s in spans:
        if s["name"] == tele.SPAN_TOTAL:
            return s["start"], s["end"]
    if not spans:
        return 0.0, 0.0
    return (
        min(s["start"] for s in spans),
        max(s["end"] for s in spans),
    )


# --------------------------------------------------------------------------
# Per-device attribution
# --------------------------------------------------------------------------
def _devices_from_trace(spans: list, lo: float, hi: float) -> dict:
    per: dict = {}

    def slot(key):
        return per.setdefault(str(key), {
            "busy": [], "fetch": [], "replay": [], "evicted": False,
            "n_spans": 0,
        })

    for s in spans:
        dev = s["args"].get("device")
        if dev is None:
            continue
        d = slot(dev)
        d["n_spans"] += 1
        iv = (s["start"], s["end"])
        if s["name"] == _REPLAY_SPAN:
            # the umbrella on the FAILED chip: recovery wall its death
            # caused, not work it performed
            d["replay"].append(iv)
            d["evicted"] = True
            continue
        d["busy"].append(iv)
        if s["args"].get("replay"):
            d["replay"].append(iv)
        if _FETCH_MARK in s["name"]:
            d["fetch"].append(iv)

    wall = max(hi - lo, 0.0)
    out = {}
    for dev, d in sorted(per.items()):
        busy = _union_seconds(d["busy"], lo, hi)
        out[dev] = {
            "busy_s": round(busy, 6),
            "idle_s": round(max(0.0, wall - busy), 6),
            "fetch_s": round(_union_seconds(d["fetch"], lo, hi), 6),
            "replay_s": round(_union_seconds(d["replay"], lo, hi), 6),
            "busy_frac": round(busy / wall, 4) if wall > 0 else None,
            "evicted": d["evicted"],
            "n_spans": d["n_spans"],
        }
    return out


def _devices_from_snapshot(snap: dict, wall: Optional[float]) -> dict:
    """Aggregate-mode attribution from ``device_spans``: exact totals
    (no interval union — concurrent spans on one device sum past wall
    only if the pipeline genuinely overlaps them, which the streamed
    double buffer does not within one chip).  Survivors' replayed work
    arrives under the ``<k>:replay`` keys (telemetry ``_record``) and
    folds into device ``k``'s row as ``replay_s``."""
    per: dict = {}

    def slot(key):
        return per.setdefault(str(key), {
            "busy_s": 0.0, "fetch_s": 0.0, "replay_s": 0.0,
            "evicted": False, "n_spans": 0,
        })

    for name, by_dev in (snap.get("device_spans") or {}).items():
        for dkey, agg in by_dev.items():
            dkey = str(dkey)
            total = agg["total_s"]
            if dkey.endswith(":replay"):
                d = slot(dkey[: -len(":replay")])
                d["busy_s"] += total
                d["replay_s"] += total
                d["n_spans"] += agg["count"]
                if _FETCH_MARK in name:
                    d["fetch_s"] += total
                continue
            d = slot(dkey)
            d["n_spans"] += agg["count"]
            if name == _REPLAY_SPAN:
                d["replay_s"] += total
                d["evicted"] = True
                continue
            d["busy_s"] += total
            if _FETCH_MARK in name:
                d["fetch_s"] += total

    out = {}
    for dev, d in sorted(per.items()):
        busy = d["busy_s"]
        out[dev] = {
            "busy_s": round(busy, 6),
            "idle_s": (
                round(max(0.0, wall - busy), 6) if wall is not None
                else None
            ),
            "fetch_s": round(d["fetch_s"], 6),
            "replay_s": round(d["replay_s"], 6),
            "busy_frac": (
                round(busy / wall, 4) if wall else None
            ),
            "evicted": d["evicted"],
            "n_spans": d["n_spans"],
        }
    return out


# --------------------------------------------------------------------------
# Barrier decomposition
# --------------------------------------------------------------------------
def _stage_decomposition(span_totals: dict, wall: Optional[float],
                         gauges: Optional[dict] = None) -> dict:
    out = {}
    for key, name in _STAGES:
        t = span_totals.get(name)
        if t is None:
            continue
        row = {"total_s": round(t, 6)}
        if wall:
            row["frac"] = round(t / wall, 4)
        out[key] = row
    # barrier-1 resolve: whether the duplicate-resolve lexsort ran as
    # the device sort of the packed summary keys or on the host
    g = (gauges or {}).get(tele.G_RESOLVE_DEVICE_SORT)
    if g is not None and "barrier1_resolve" in out:
        out["barrier1_resolve"]["sort"] = (
            "device" if g.get("last") else "host"
        )
    # megakernel tier (docs/PERF.md): with the fused B→C path armed,
    # per-window observe and the pass-C apply rode ONE dispatch — two
    # separate stage rows would misread as two device passes.  Render
    # them as one combined stage; the rows are disjoint and the merged
    # row is their sum, so the stage fractions still sum to the run
    # wall exactly as before.
    gf = (gauges or {}).get(tele.G_FUSED_BC)
    if gf is not None and gf.get("last") and (
        "observe" in out or "pass_c_apply" in out
    ):
        t = sum(
            out.get(k, {}).get("total_s", 0.0)
            for k in ("observe", "pass_c_apply")
        )
        row = {"total_s": round(t, 6)}
        if wall:
            row["frac"] = round(t / wall, 4)
        merged: dict = {}
        for k, v in out.items():
            if k in ("observe", "pass_c_apply"):
                merged.setdefault("fused_bc_apply", row)
            else:
                merged[k] = v
        out = merged
    return out


def _write_tail_report(counters: dict) -> dict:
    """Write-tail byte decomposition: decoded column payload entering
    the part encodes (``parquet.encode.bytes_in``), assembled arrow
    bytes handed to the writers (``parquet.encode.bytes_out``), and
    compressed bytes on disk (``parquet.bytes.written``) — with the
    encode shrink and the codec's compression ratio, so the packed-
    column path's effect on the tail is a one-line read."""
    bytes_in = counters.get(tele.C_ENCODE_BYTES_IN)
    bytes_out = counters.get(tele.C_ENCODE_BYTES_OUT)
    written = counters.get(tele.C_BYTES_WRITTEN)
    if not bytes_in and not bytes_out:
        return {}
    out = {
        "encode_bytes_in": bytes_in or 0,
        "encode_bytes_out": bytes_out or 0,
        "bytes_written": written or 0,
    }
    if bytes_in and bytes_out:
        out["encode_ratio"] = round(bytes_in / bytes_out, 3)
    if bytes_out and written:
        out["compression_ratio"] = round(bytes_out / written, 3)
    return out


def _partitioner_mode(counters: dict, devices: dict) -> Optional[str]:
    """The run's execution partitioner, derived from the ledger: mesh
    collective dispatches present -> "mesh" ("mesh->pool" when the run
    degraded mid-flight), device-attributed work without them ->
    "pool", nothing device-attributed -> None."""
    if counters.get(tele.C_MESH_DISPATCHED, 0) > 0:
        if counters.get(tele.C_MESH_DEGRADED, 0) > 0:
            return "mesh->pool"
        return "mesh"
    if counters.get(tele.C_MESH_DEGRADED, 0) > 0:
        return "mesh->pool"
    if devices:
        return "pool"
    return None


# --------------------------------------------------------------------------
# Critical path
# --------------------------------------------------------------------------
def _critical_path(spans: list, top_n: int = 5) -> dict:
    """Last-finisher chain: from the event that ends last, repeatedly
    step to the event that finished latest before the current one
    started — the chain of spans the run's end actually waited on.
    Edge weight = how much of the wall the step accounts for
    (``cur.end - pred.end``, i.e. the current span's exposed duration
    plus any scheduling gap)."""
    nodes = [s for s in spans if s["name"] != tele.SPAN_TOTAL and s["dur"] > 0]
    if not nodes:
        return {"edges": [], "length_s": 0.0, "n_nodes": 0}
    by_end = sorted(nodes, key=lambda s: s["end"])
    ends = [s["end"] for s in by_end]
    import bisect

    def label(s):
        w = s["args"].get("window")
        return f"{s['name']}[w{w}]" if w is not None else s["name"]

    cur = by_end[-1]
    chain = [cur]
    edges = []
    # bounded walk: every step moves strictly earlier, so the chain is
    # at most len(nodes) long
    for _ in range(len(nodes)):
        i = bisect.bisect_right(ends, cur["start"]) - 1
        # skip self-matches at identical timestamps
        while i >= 0 and by_end[i] is cur:
            i -= 1
        if i < 0:
            break
        pred = by_end[i]
        edges.append({
            "from": label(pred),
            "to": label(cur),
            "edge_s": round(cur["end"] - pred["end"], 6),
            "gap_s": round(max(0.0, cur["start"] - pred["end"]), 6),
        })
        cur = pred
        chain.append(cur)
    length = chain[0]["end"] - chain[-1]["start"]
    top = sorted(edges, key=lambda e: -e["edge_s"])[:top_n]
    return {
        "edges": top,
        "length_s": round(length, 6),
        "n_nodes": len(chain),
    }


# --------------------------------------------------------------------------
# Device ledger sections (transfers / compile cache / HBM)
# --------------------------------------------------------------------------
def _transfer_report(doc: dict, counters: dict) -> dict:
    """Per-device tunnel accounting from the snapshot/trace ``transfers``
    section: byte totals and mean throughput per direction, the
    per-pass byte split, and bytes-per-read (the tunnel cost of one
    read crossing the pipeline) — the ROADMAP's "chunked device_fetch
    throughput" and "barrier-2 observe-fetch share" measurements read
    straight off this."""
    xfer = doc.get("transfers") or {}
    devices: dict = {}
    totals = {"h2d": 0, "d2h": 0}
    for direction in ("h2d", "d2h"):
        for dev, per in (xfer.get(direction) or {}).items():
            d = devices.setdefault(str(dev), {})
            nbytes = sum(v["bytes"] for v in per.values())
            secs = sum(v["seconds"] for v in per.values())
            d[direction] = {
                "bytes": nbytes,
                "count": sum(v["count"] for v in per.values()),
                "seconds": round(secs, 6),
                "bytes_per_s": (
                    round(nbytes / secs) if secs > 1e-9 else None
                ),
                "by_pass": {
                    p: v["bytes"]
                    for p, v in sorted(per.items())
                },
            }
            totals[direction] += nbytes
    if not devices:
        return {}
    reads = counters.get(tele.C_READS_INGESTED) or 0
    return {
        "devices": devices,
        "h2d_bytes": totals["h2d"],
        "d2h_bytes": totals["d2h"],
        "bytes_per_read": (
            round((totals["h2d"] + totals["d2h"]) / reads, 1)
            if reads else None
        ),
    }


def _residency_report(doc: dict, counters: dict) -> dict:
    """Device-residency section (docs/PERF.md "Device-resident
    windows"): the resident-window counters, the per-pass h2d byte
    table summed across devices, and the **ingest-only verdict** — true
    when windows placed resident and the per-pass dispatch traffic
    (``observe`` + ``apply`` buckets) stayed under 25% of the one
    ``ingest`` placement, i.e. the passes genuinely dispatched against
    the handles instead of re-shipping.  Donated-signature executables
    (the resident pack2/packed-observe kernels) are split out of the
    compile entries so their prewarm coverage is visible next to the
    verdict."""
    xfer = doc.get("transfers") or {}
    per_pass: dict = {}
    for _dev, per in (xfer.get("h2d") or {}).items():
        for p, v in (per or {}).items():
            per_pass[p] = per_pass.get(p, 0) + (
                v.get("bytes", 0) if isinstance(v, dict) else 0
            )
    windows = counters.get(tele.C_RESIDENT_WINDOWS, 0)
    if not windows and "ingest" not in per_pass:
        return {}
    ingest = per_pass.get("ingest", 0)
    dispatch = per_pass.get("observe", 0) + per_pass.get("apply", 0)
    entries = (doc.get("compiles") or {}).get("entries") or []
    donated = [
        e for e in entries
        if any(k in str(e.get("kernel", ""))
               for k in ("pack2", "observe_packed"))
    ]
    return {
        "windows": windows,
        "bytes": counters.get(tele.C_RESIDENT_BYTES, 0),
        "released": counters.get(tele.C_RESIDENT_RELEASED, 0),
        "evicted": counters.get(tele.C_RESIDENT_EVICTED, 0),
        "h2d_by_pass": dict(sorted(per_pass.items())),
        "ingest_only": bool(
            windows and ingest and dispatch <= 0.25 * ingest
        ),
        "donated_compiles": {
            "count": len(donated),
            "in_window": sum(
                1 for e in donated if e.get("in_window")
            ),
        },
    }


def _compile_report(doc: dict, counters: dict) -> dict:
    """Compile-cache section: hit/miss counts plus the cold-compile
    entry list, with the ``in_window`` subset split out — every entry
    there is a shape the prewarm failed to cover, serialized inside a
    timed window (the analyzer's warning section renders them)."""
    comp = doc.get("compiles") or {}
    entries = comp.get("entries") or []
    in_window = [e for e in entries if e.get("in_window")]
    hits = counters.get(tele.C_COMPILE_HITS, 0)
    misses = counters.get(tele.C_COMPILE_MISSES, 0)
    if not entries and not hits and not misses:
        return {}
    return {
        "cache_hits": hits,
        "cache_misses": misses,
        "prewarmed": len(entries) - len(in_window),
        "in_window": in_window,
        "entries_dropped": comp.get("dropped", 0),
    }


def _hbm_report(doc: dict, devices: dict) -> dict:
    """HBM section: per-device last/peak bytes from the heartbeat's
    ``memory_stats()`` samples, or an explicit ``unsupported`` marker
    when a device-attributed run produced no samples (backend without
    memory stats, or no heartbeat ran) — never fabricated zeros."""
    hbm = doc.get("hbm") or {}
    if hbm:
        return {
            dev: {
                "bytes_in_use": v.get("last"),
                "peak_bytes": v.get("peak"),
                "samples": v.get("n", 0),
            }
            for dev, v in sorted(hbm.items())
        }
    if devices:
        return {"unsupported": True}
    return {}


# --------------------------------------------------------------------------
# Entry points
# --------------------------------------------------------------------------
def _batching_report(doc: dict, counters: dict, hists: dict) -> dict:
    """Cross-job batching section (docs/SERVING.md "Continuous
    batching & quotas"): fused-dispatch counts vs the windows they
    carried (the dispatches-saved ratio), the grid-fill distribution,
    fallback count, and per-tenant quota consumption from the
    snapshot's ``quota`` ledger.  ``{}`` when the run never coalesced
    (solo runs, batching off) — the section renders nothing."""
    dispatches = counters.get(tele.C_BATCH_DISPATCHES, 0)
    quota = doc.get("quota") or {}
    if not dispatches and not quota:
        return {}
    windows = counters.get(tele.C_BATCH_WINDOWS, 0)
    occ = counters.get(tele.C_BATCH_ROWS_OCCUPIED, 0)
    disp_rows = counters.get(tele.C_BATCH_ROWS_DISPATCHED, 0)
    return {
        "dispatches": dispatches,
        "windows": windows,
        "dispatches_saved": max(0, windows - dispatches),
        "fill": round(occ / disp_rows, 4) if disp_rows else None,
        "fallbacks": counters.get(tele.C_BATCH_FALLBACKS, 0),
        "fill_hist": (hists or {}).get(tele.H_BATCH_FILL),
        "quota_rejected": counters.get(tele.C_QUOTA_REJECTED, 0),
        "quota": quota,
    }


def _health_report(doc: dict, counters: dict) -> dict:
    """Device-health section (utils/health.py, docs/ROBUSTNESS.md
    "Device health, hedging, and SDC audit"): the per-device scoreboard
    states from the snapshot's ``health`` ledger plus the hedge/audit
    counters.  ``{}`` when the run tracked no device health and never
    hedged or audited — the section renders nothing."""
    health = doc.get("health") or {}
    keys = (
        tele.C_HEALTH_DEMOTED, tele.C_HEALTH_PROBATION,
        tele.C_HEALTH_READMITTED, tele.C_HEALTH_PROBE_FAILED,
        tele.C_HEDGE_FIRED, tele.C_HEDGE_WON, tele.C_HEDGE_WASTED,
        tele.C_AUDIT_SAMPLED, tele.C_AUDIT_MISMATCH,
    )
    if not health and not any(counters.get(k) for k in keys):
        return {}
    return {
        "devices": {k: dict(v) for k, v in sorted(health.items())},
        "demoted": counters.get(tele.C_HEALTH_DEMOTED, 0),
        "probation": counters.get(tele.C_HEALTH_PROBATION, 0),
        "readmitted": counters.get(tele.C_HEALTH_READMITTED, 0),
        "probe_failed": counters.get(tele.C_HEALTH_PROBE_FAILED, 0),
        "hedge_fired": counters.get(tele.C_HEDGE_FIRED, 0),
        "hedge_won": counters.get(tele.C_HEDGE_WON, 0),
        "hedge_wasted": counters.get(tele.C_HEDGE_WASTED, 0),
        "audit_sampled": counters.get(tele.C_AUDIT_SAMPLED, 0),
        "audit_mismatch": counters.get(tele.C_AUDIT_MISMATCH, 0),
    }


def _slo_report(slo_doc) -> dict:
    """SLO section (utils/slo.py): accepts either the live status
    document (``adam_tpu.slo/1`` — per-objective burn rates included)
    or the durable budget file (``adam_tpu.slo_budget/1`` — cumulative
    good/bad per objective; compliance and budget remaining are
    recomputed from it, burn rates are unknown post-hoc).  ``{}`` when
    the run carried no SLO."""
    if not isinstance(slo_doc, dict):
        return {}
    objectives = slo_doc.get("objectives")
    rows = []
    if isinstance(objectives, list):  # live status document
        for o in objectives:
            if isinstance(o, dict) and o.get("key"):
                rows.append({
                    "key": o["key"],
                    "compliance": o.get("compliance"),
                    "burn_short": o.get("burn_short"),
                    "burn_long": o.get("burn_long"),
                    "good": o.get("good_total"),
                    "bad": o.get("bad_total"),
                    "budget_remaining": o.get("budget_remaining"),
                })
    elif isinstance(objectives, dict):  # durable budget file
        for key, row in sorted(objectives.items()):
            if not isinstance(row, dict):
                continue
            good = int(row.get("good", 0))
            bad = int(row.get("bad", 0))
            total = good + bad
            allowed = row.get("allowed") or max(
                1.0 - float(row.get("target", 0.99)), 1e-6)
            bad_frac = (bad / total) if total else 0.0
            rows.append({
                "key": key,
                "compliance": round(1.0 - bad_frac, 6) if total else None,
                "burn_short": None,
                "burn_long": None,
                "good": good,
                "bad": bad,
                "budget_remaining": round(
                    max(0.0, 1.0 - bad_frac / allowed), 6),
            })
    if not rows:
        return {}
    return {
        "objectives": rows,
        "worst_burn": slo_doc.get("worst_burn"),
        "budget_remaining": slo_doc.get("budget_remaining"),
        "window_s": slo_doc.get("window_s"),
    }


def _perf_trend_report(entries) -> dict:
    """Perf-trend section (utils/perfledger.py): the ledger's run
    history judged entry-by-entry against the rolling median of the
    runs before it.  ``{}`` when no ledger rode along."""
    if not entries:
        return {}
    from adam_tpu.utils import perfledger

    rows = perfledger.trend(list(entries))
    flagged = sum(1 for r in rows if r["regressions"])
    return {
        "runs": rows,
        "n_runs": len(rows),
        "runs_flagged": flagged,
    }


def _hist_rows(hists: dict) -> dict:
    return {
        name: {
            "count": h.get("count", 0),
            "p50": h.get("p50"),
            "p90": h.get("p90"),
            "p99": h.get("p99"),
            "max": h.get("max"),
        }
        for name, h in sorted(hists.items())
        if h.get("count")
    }


def _hists_from_events(spans: list) -> dict:
    """Rebuild per-span-name duration histograms from trace events with
    telemetry's fixed buckets — a trace captured before the histogram
    layer existed still yields quantiles."""
    hists: dict = {}
    for s in spans:
        h = hists.setdefault(s["name"], tele._new_hist())
        tele._hist_observe(h, s["dur"])
    return {k: tele.hist_summary(v) for k, v in hists.items()}


def analyze(doc: dict) -> dict:
    """Analyze one telemetry artifact into the run report dict."""
    kind = document_kind(doc)
    if kind == "trace":
        spans = _trace_spans(doc)
        lo, hi = _run_window(spans)
        wall = max(hi - lo, 0.0)
        totals: dict = {}
        for s in spans:
            totals[s["name"]] = totals.get(s["name"], 0.0) + s["dur"]
        devices = _devices_from_trace(spans, lo, hi)
        cpath = _critical_path(spans)
        # event-rebuilt duration quantiles as the floor, overridden by
        # the exact histogram section a telemetry-written trace embeds
        # (explicit observe() metrics never appear as events, and the
        # embedded aggregates survive ring eviction)
        hists = {**_hists_from_events(spans), **(doc.get("histograms") or {})}
    else:
        span_sec = {
            k: v["total_s"] for k, v in (doc.get("spans") or {}).items()
        }
        wall = span_sec.get(tele.SPAN_TOTAL)
        totals = span_sec
        devices = _devices_from_snapshot(doc, wall)
        cpath = None  # aggregates carry no timestamps to chain
        hists = doc.get("histograms") or {}
    counters = doc.get("counters") or {}
    gauges = doc.get("gauges") or {}
    report = {
        "kind": kind,
        "events_evicted": doc.get("events_evicted", 0) or 0,
        "wall_s": round(wall, 6) if wall is not None else None,
        # execution mode ("pool" | "mesh" | "mesh->pool" for a run that
        # degraded mid-flight; None = no device-attributed work)
        "partitioner": _partitioner_mode(counters, devices),
        "devices": devices,
        "stages": _stage_decomposition(totals, wall, gauges),
        "histograms": _hist_rows(hists),
        # the device ledger (both artifact kinds embed the sections):
        # tunnel byte accounting, compile-cache hit/miss + in-window
        # cold-compile warnings, HBM footprint
        "transfers": _transfer_report(doc, counters),
        "compiles": _compile_report(doc, counters),
        # device-resident windows: per-pass h2d table + ingest-only
        # verdict + donated-executable prewarm coverage
        "residency": _residency_report(doc, counters),
        "hbm": _hbm_report(doc, devices),
        # the write-tail byte decomposition (encode in -> arrow out ->
        # parquet on disk) beside the stage walls it explains
        "write_tail": _write_tail_report(counters),
        # cross-job batching (serve/batching.py) + per-tenant quota
        # consumption (serve/quota.py)
        "batching": _batching_report(doc, counters, hists),
        # device health scoreboard + hedged dispatch + SDC audit
        # (utils/health.py)
        "health": _health_report(doc, counters),
        # incident bundles recorded beside the artifact
        # (utils/incidents.py; analyze_path folds the sibling
        # incidents/ dir's summaries into the doc)
        "incidents": list(doc.get("incidents") or []),
        # the judgment layer (utils/slo.py + utils/perfledger.py;
        # analyze_path folds the sibling SLO_BUDGET.json and
        # PERF_LEDGER.ndjson into the doc)
        "slo": _slo_report(doc.get("slo")),
        "perf_trend": _perf_trend_report(doc.get("perf_ledger")),
        "counters": {
            k: counters[k]
            for k in (
                tele.C_READS_INGESTED, tele.C_WINDOWS_INGESTED,
                tele.C_PARTS_WRITTEN, tele.C_BYTES_WRITTEN,
                tele.C_ENCODE_BYTES_IN, tele.C_ENCODE_BYTES_OUT,
                tele.C_H2D_BYTES, tele.C_D2H_BYTES,
                tele.C_COMPILE_HITS, tele.C_COMPILE_MISSES,
                tele.C_COMPILE_IN_WINDOW,
                tele.C_RETRY_ATTEMPTS, tele.C_FAULT_INJECTED,
                tele.C_DEVICE_EVICTED,
                tele.C_HEDGE_FIRED, tele.C_HEDGE_WON,
                tele.C_HEDGE_WASTED,
                tele.C_AUDIT_SAMPLED, tele.C_AUDIT_MISMATCH,
                tele.C_HEALTH_PROBATION, tele.C_HEALTH_READMITTED,
                tele.C_MESH_DISPATCHED, tele.C_MESH_DEGRADED,
                # resumed-vs-fresh window accounting (a resumed run's
                # report must say how much work the journal spared)
                tele.C_RESUME_WINDOWS_SKIPPED,
                tele.C_RESUME_HISTOGRAMS_LOADED, tele.C_RESUME_REFUSED,
            )
            if k in counters
        },
    }
    if cpath is not None:
        report["critical_path"] = cpath
    return report


def utilization_from_snapshot(snap: dict) -> dict:
    """Just the per-device utilization section from a snapshot — what
    ``bench.py`` embeds next to each artifact's telemetry key (the CPU
    baseline's empty ``device_spans``/``transfers`` yield ``{}``,
    key-stable).  ``transfers``/``compiles`` make the bench artifact
    carry tunnel utilization and prewarm-coverage evidence round over
    round, not just chip occupancy."""
    wall = (snap.get("spans") or {}).get(tele.SPAN_TOTAL, {}).get("total_s")
    counters = snap.get("counters") or {}
    return {
        "wall_s": round(wall, 6) if wall is not None else None,
        "devices": _devices_from_snapshot(snap, wall),
        "transfers": _transfer_report(snap, counters),
        "compiles": _compile_report(snap, counters),
    }


def _fmt_s(v) -> str:
    return f"{v:.3f}" if isinstance(v, (int, float)) else "-"


_fmt_bytes = tele.format_bytes


def render_report(report: dict) -> str:
    """The human-readable run report (``adam-tpu analyze`` stdout)."""
    out = []
    wall = report.get("wall_s")
    part = report.get("partitioner")
    out.append(
        f"Run report ({report['kind']} mode) — wall {_fmt_s(wall)} s"
        + (f" — partitioner {part}" if part else "")
    )
    out.append("=" * len(out[0]))
    if part == "mesh->pool":
        out.append(
            "NOTE: the mesh partitioner degraded to the pool path "
            "mid-run (device.mesh.degraded) — output stays bit-"
            "identical; attribution mixes both modes"
        )
    evicted = report.get("events_evicted")
    if evicted and report["kind"] == "trace":
        out += ["", f"WARNING: {evicted} oldest events were evicted from "
                "the flight-recorder ring before export — busy/idle "
                "attribution and the critical path undercount the early "
                "run (raise ADAM_TPU_TRACE_EVENTS or analyze the "
                "--metrics-json snapshot, whose aggregates are exact)"]
    devs = report.get("devices") or {}
    if devs:
        out += ["", "Per-device attribution"]
        hdr = (
            f"{'device':>10}  {'busy_s':>9}  {'idle_s':>9}  {'fetch_s':>9}"
            f"  {'replay_s':>9}  {'busy%':>6}  {'evicted':>7}"
        )
        out += [hdr, "-" * len(hdr)]
        for dev, d in devs.items():
            frac = d.get("busy_frac")
            out.append(
                f"{dev:>10}  {_fmt_s(d['busy_s']):>9}"
                f"  {_fmt_s(d['idle_s']):>9}  {_fmt_s(d['fetch_s']):>9}"
                f"  {_fmt_s(d['replay_s']):>9}"
                f"  {f'{frac * 100:.1f}' if frac is not None else '-':>6}"
                f"  {'yes' if d['evicted'] else 'no':>7}"
            )
    else:
        out += ["", "Per-device attribution: (no device-attributed spans "
                "— single-device or host-backend run)"]
    xfer = report.get("transfers") or {}
    if xfer:
        out += ["", "Tunnel transfers (host<->device)"]
        hdr = (
            f"{'device':>10}  {'dir':>4}  {'bytes':>10}  {'calls':>6}"
            f"  {'wall_s':>8}  {'mean B/s':>10}  per-pass bytes"
        )
        out += [hdr, "-" * len(hdr)]
        for dev, dirs in sorted(xfer["devices"].items()):
            for direction in ("h2d", "d2h"):
                d = dirs.get(direction)
                if d is None:
                    continue
                by_pass = ", ".join(
                    f"{p}={_fmt_bytes(b)}"
                    for p, b in d["by_pass"].items()
                )
                out.append(
                    f"{dev:>10}  {direction:>4}  {_fmt_bytes(d['bytes']):>10}"
                    f"  {d['count']:>6}  {_fmt_s(d['seconds']):>8}"
                    f"  {_fmt_bytes(d['bytes_per_s']):>10}  {by_pass}"
                )
        bpr = xfer.get("bytes_per_read")
        out.append(
            f"  totals: h2d {_fmt_bytes(xfer['h2d_bytes'])}, d2h "
            f"{_fmt_bytes(xfer['d2h_bytes'])}"
            + (f", {_fmt_bytes(bpr)}/read" if bpr is not None else "")
        )
    comp = report.get("compiles") or {}
    if comp:
        out += ["", "Compile cache"]
        out.append(
            f"  hits {comp['cache_hits']}, misses {comp['cache_misses']}"
            f" ({comp['prewarmed']} under prewarm,"
            f" {len(comp['in_window'])} inside timed windows)"
        )
        if comp.get("entries_dropped"):
            out.append(
                f"  ({comp['entries_dropped']} ledger entries dropped past "
                "the retention bound)"
            )
        if comp["in_window"]:
            out.append(
                "  WARNING: shapes cold-compiled INSIDE a timed window "
                "(prewarm coverage gaps — their compile wall serialized "
                "into the pipeline):"
            )
            for e in comp["in_window"]:
                shape = "x".join(str(s) for s in (e.get("shape") or []))
                out.append(
                    f"    {e['kernel']}[{shape}] on device {e['device']}"
                    f": {_fmt_s(e['seconds'])} s"
                )
    res = report.get("residency") or {}
    if res:
        out += ["", "Device residency (ingest-once H2D)"]
        out.append(
            f"  resident windows {res['windows']} "
            f"({_fmt_bytes(res['bytes'])} placed), released "
            f"{res['released']}, evicted {res['evicted']}"
        )
        by_pass = ", ".join(
            f"{p}={_fmt_bytes(b)}"
            for p, b in (res.get("h2d_by_pass") or {}).items()
        )
        if by_pass:
            out.append(f"  per-pass h2d: {by_pass}")
        out.append(
            "  verdict: h2d is ingest-only"
            if res.get("ingest_only") else
            "  verdict: h2d is NOT ingest-only — observe/apply "
            "re-shipped window payloads (residency off, handles "
            "dropped, or a regression the residency staticcheck rule "
            "should have caught)"
        )
        dc = res.get("donated_compiles") or {}
        if dc.get("count"):
            out.append(
                f"  donated-signature executables: {dc['count']} "
                f"compiled, {dc['in_window']} inside timed windows"
            )
    bat = report.get("batching") or {}
    if bat:
        out += ["", "Batching (cross-job window coalescing)"]
        if bat.get("dispatches"):
            fill = bat.get("fill")
            out.append(
                f"  {bat['windows']} window(s) in {bat['dispatches']} "
                f"fused dispatch(es) — {bat['dispatches_saved']} "
                "dispatch(es) saved vs solo"
                + (f", grid fill {fill:.0%}" if fill is not None else "")
            )
            fh = bat.get("fill_hist")
            if fh and fh.get("count"):
                out.append(
                    f"  fill distribution: p50 {_fmt_s(fh.get('p50'))}"
                    f"  p90 {_fmt_s(fh.get('p90'))}"
                    f"  min {_fmt_s(fh.get('min'))}"
                    f"  max {_fmt_s(fh.get('max'))}"
                )
            if bat.get("fallbacks"):
                out.append(
                    f"  WARNING: {bat['fallbacks']} window(s) fell back "
                    "to their solo dispatch path (fused-dispatch "
                    "failures; output stays byte-identical)"
                )
        if bat.get("quota_rejected"):
            out.append(
                f"  quota rejections: {bat['quota_rejected']} "
                "(typed 429 quota leg)"
            )
        for tenant, q in sorted((bat.get("quota") or {}).items()):
            bb = q.get("budget_bytes")
            bc = q.get("budget_compute_s")
            out.append(
                f"  tenant {tenant}: {_fmt_bytes(q.get('bytes', 0))}"
                + (f" of {_fmt_bytes(bb)}" if bb is not None else "")
                + f" bytes, {q.get('compute_s', 0.0):.3f}"
                + (f" of {bc:g}" if bc is not None else "")
                + f" s compute ({q.get('charges', 0)} charges)"
            )
    hlth = report.get("health") or {}
    if hlth:
        out += ["", "Device health (scoreboard / hedging / SDC audit)"]
        for dev, row in (hlth.get("devices") or {}).items():
            reason = row.get("reason")
            out.append(
                f"  device {dev}: {row.get('state', '?')}"
                f" (score {row.get('score', 0)},"
                f" {row.get('transitions', 0)} transition(s))"
                + (f" — {reason}" if reason else "")
            )
        out.append(
            f"  transitions: {hlth['demoted']} demoted, "
            f"{hlth['probation']} probation, "
            f"{hlth['readmitted']} readmitted, "
            f"{hlth['probe_failed']} probe-failed"
        )
        if hlth.get("hedge_fired"):
            out.append(
                f"  hedged dispatch: {hlth['hedge_fired']} fired — "
                f"{hlth['hedge_won']} won, {hlth['hedge_wasted']} "
                "wasted (first result wins; bytes identical either way)"
            )
        if hlth.get("audit_sampled"):
            out.append(
                f"  SDC audit: {hlth['audit_sampled']} window(s) "
                f"dual-computed, {hlth['audit_mismatch']} mismatch(es)"
            )
        if hlth.get("audit_mismatch"):
            out.append(
                "  WARNING: the audit caught silent data corruption — "
                "the offending device was quarantined and every "
                "mismatched window republished from the host recompute"
            )
    incidents = report.get("incidents") or []
    if incidents:
        out += ["", f"Incidents ({len(incidents)} bundle(s))"]
        for inc in incidents:
            where = [
                f"device {inc['device']}" if inc.get("device") else "",
                f"window {inc['window']}"
                if inc.get("window") is not None else "",
                f"trace {inc['trace_id']}" if inc.get("trace_id") else "",
            ]
            where_s = ", ".join(w for w in where if w)
            out.append(
                f"  {inc.get('id', '?')}: {inc.get('trigger', '?')}"
                + (f" ({where_s})" if where_s else "")
                + (f" — {inc['reason']}" if inc.get("reason") else "")
            )
    slo = report.get("slo") or {}
    if slo:
        out += ["", "SLO"]
        for o in slo.get("objectives") or []:
            comp = o.get("compliance")
            rem = o.get("budget_remaining")
            burn = o.get("burn_short")
            out.append(
                f"  {o['key']}: "
                + (f"compliance {comp:.4%}" if comp is not None
                   else "compliance n/a")
                + (f", budget remaining {rem:.1%}"
                   if rem is not None else "")
                + (f", burn {burn:.1f}x short"
                   + (f" / {o['burn_long']:.1f}x long"
                      if o.get("burn_long") is not None else "")
                   if burn is not None else "")
                + f"  ({o.get('good', 0)} good / {o.get('bad', 0)} bad)"
            )
        wb = slo.get("worst_burn")
        if wb is not None:
            out.append(f"  worst burn {wb:.1f}x, budget remaining "
                       f"{(slo.get('budget_remaining') or 0):.1%}")
    trend = report.get("perf_trend") or {}
    if trend:
        out += ["", f"Perf trend ({trend['n_runs']} run(s), "
                    f"{trend['runs_flagged']} flagged)"]
        for r in (trend.get("runs") or [])[-8:]:
            total = (f"{r['total_s']:.3f}s" if r.get("total_s")
                     is not None else "-")
            mark = (", ".join(
                f"{x['key']} {x['delta_pct']:+.1f}%"
                for x in r["regressions"])
                or "ok")
            out.append(
                f"  run {r['index']} ({r.get('run_id') or '-'}): "
                f"total {total} — {mark}"
            )
    hbm = report.get("hbm") or {}
    if hbm:
        out += ["", "HBM footprint"]
        if hbm.get("unsupported"):
            out.append(
                "  (unsupported backend: device.memory_stats() returned "
                "nothing — no HBM samples)"
            )
        else:
            for dev, d in hbm.items():
                out.append(
                    f"  device {dev}: in use {_fmt_bytes(d['bytes_in_use'])}"
                    f", peak {_fmt_bytes(d['peak_bytes'])}"
                    f" ({d['samples']} samples)"
                )
    stages = report.get("stages") or {}
    if stages:
        out += ["", "Stage / barrier decomposition"]
        w = max(len(k) for k in stages)
        for key, row in stages.items():
            frac = row.get("frac")
            pct = f"  ({frac * 100:5.1f}%)" if frac is not None else ""
            sort = row.get("sort")
            tag = f"  [{sort} sort]" if sort else ""
            out.append(
                f"  {key.ljust(w)}  {_fmt_s(row['total_s']):>9} s{pct}{tag}"
            )
        wt = report.get("write_tail") or {}
        if wt:
            enc_r = wt.get("encode_ratio")
            comp_r = wt.get("compression_ratio")
            out.append(
                "  write-tail bytes: encode in "
                f"{_fmt_bytes(wt['encode_bytes_in'])} -> arrow "
                f"{_fmt_bytes(wt['encode_bytes_out'])}"
                + (f" ({enc_r:g}x in/out)" if enc_r else "")
                + f" -> parquet {_fmt_bytes(wt['bytes_written'])}"
                + (f" ({comp_r:g}x compression)" if comp_r else "")
            )
    cpath = report.get("critical_path")
    if cpath:
        out += ["", f"Critical path (top {len(cpath['edges'])} edges of a "
                f"{cpath['n_nodes']}-node chain, {_fmt_s(cpath['length_s'])}"
                " s)"]
        for e in cpath["edges"]:
            out.append(
                f"  {e['from']} -> {e['to']}: {_fmt_s(e['edge_s'])} s"
                f" (gap {_fmt_s(e['gap_s'])} s)"
            )
    hists = report.get("histograms") or {}
    if hists:
        out += ["", "Latency histograms (seconds)"]
        w = max(len(k) for k in hists)
        hdr = (
            f"  {'name'.ljust(w)}  {'count':>7}  {'p50':>9}  {'p90':>9}"
            f"  {'p99':>9}  {'max':>9}"
        )
        out += [hdr]
        for name, h in hists.items():
            out.append(
                f"  {name.ljust(w)}  {h['count']:>7}"
                f"  {_fmt_s(h['p50']):>9}  {_fmt_s(h['p90']):>9}"
                f"  {_fmt_s(h['p99']):>9}  {_fmt_s(h['max']):>9}"
            )
    counters = report.get("counters") or {}
    if counters:
        out += ["", "Counters"]
        w = max(len(k) for k in counters)
        for k, v in sorted(counters.items()):
            out.append(f"  {k.ljust(w)}  {v}")
    return "\n".join(out)


def analyze_path(path: str) -> dict:
    """Convenience: load + analyze one artifact file.  When the
    artifact sits in (or beside) a run dir with an ``incidents/``
    subdirectory, the bundles' summaries fold into the report's
    "Incidents" section — the post-hoc view of what the anomaly
    triggers captured while the run was live.  A sibling
    ``SLO_BUDGET.json`` (utils/slo.py) and ``PERF_LEDGER.ndjson``
    (utils/perfledger.py) fold into the "SLO" and "Perf trend"
    sections the same way."""
    import json as json_mod

    from adam_tpu.utils import incidents as incidents_mod
    from adam_tpu.utils import perfledger
    from adam_tpu.utils import slo as slo_mod

    doc = load_document(path)
    found = []
    slo_doc = None
    ledger = []
    probe = os.path.dirname(os.path.abspath(path))
    for _ in range(2):  # the artifact's dir, then its parent
        if not found:
            found = incidents_mod.list_bundles(probe)
        if slo_doc is None:
            budget_path = os.path.join(probe, slo_mod.BUDGET_FILENAME)
            if os.path.isfile(budget_path):
                try:
                    with open(budget_path, encoding="utf-8") as fh:
                        slo_doc = json_mod.load(fh)
                except (OSError, ValueError):
                    slo_doc = None
        if not ledger:
            ledger = perfledger.read_ledger(probe)
        probe = os.path.dirname(probe)
    extra = {}
    if found and not doc.get("incidents"):
        extra["incidents"] = found
    if slo_doc is not None and not doc.get("slo"):
        extra["slo"] = slo_doc
    if ledger and not doc.get("perf_ledger"):
        extra["perf_ledger"] = ledger
    if extra:
        doc = dict(doc)
        doc.update(extra)
    return analyze(doc)
