"""Deterministic fault injection for the streamed multi-chip pipeline.

The reference delegates fault tolerance to Spark's RDD lineage recompute
(Zaharia et al.); the TPU build owns its own story (pipelines/checkpoint
for stage granularity, parallel/device_pool + pipelines/streamed for
window granularity).  A recovery path that is never executed is a
recovery path that does not work — this module lets tests and CI drive
the real pipeline through transient dispatch errors, permanent device
loss, and hung fetches **deterministically**, with the production build
paying one predictable-branch check per site when disabled (the same
discipline as the 163 ns disabled telemetry span).

Named fault points sit at the seams the multi-chip pipeline can
actually fail at::

    faults.point("device.dispatch", device=dev)   # before a jit dispatch
    faults.point("device.fetch")                  # before a device->host copy
    faults.point("parquet.write")                 # before a part write
    faults.point("pool.prewarm", device=dev)      # before a prewarm compile

A *fault spec* (``ADAM_TPU_FAULTS`` env var or the ``--fault-spec`` CLI
flag) arms clauses against those points.  Grammar (full reference in
docs/ROBUSTNESS.md)::

    spec    := clause (';' clause)*
    clause  := site '=' action (',' option)*
    action  := 'transient' | 'permanent' | 'delay:<seconds>' | 'kill'
             | 'corrupt'
    option  := 'every=N'    match every Nth arrival at the site
             | 'after=N'    skip the first N arrivals
             | 'times=N'    stop matching after N injections
             | 'device=K'   only arrivals attributed to device id K
             | 'pass=NAME'  only arrivals under this telemetry pass
                            scope (a / observe / apply / sweep / ...)
             | 'p=F'        match with probability F (seeded RNG)
             | 'seed=N'     RNG seed for p= (default 0)

Arrival counters are per clause, so ``every=3`` means "the 3rd, 6th,
9th ... time any call reaches this site" — reproducible run to run as
long as the call sequence is (the streamed pipeline dispatches and
fetches from a single host thread, so it is).  ``transient`` raises
:class:`TransientFault` (retryable — the retry/backoff wrappers absorb
it), ``permanent`` raises :class:`PermanentFault` (never retried — the
device-eviction path owns it), ``delay:S`` sleeps S seconds at the site
(a hung RPC; the fetch deadline watchdog turns it into a retryable
timeout), ``kill`` SIGKILLs the process itself (a host death — the
kill-and-resume chaos harness's weapon; see the ``proc.kill`` site).
``corrupt`` is the silent-data-corruption weapon (Dixit et al., "Silent
Data Corruptions at Scale"): instead of raising, it flips one
deterministically chosen bit in the *result* flowing through a
corruption-capable site (:data:`CORRUPT_POINTS` — today the
``device.fetch`` d2h boundary, via :func:`corrupt_array`), modelling a
chip that computes wrong answers without erroring; the SDC audit
(``ADAM_TPU_AUDIT_RATE``, docs/ROBUSTNESS.md "Device health, hedging,
and SDC audit") is what must catch it.  Every injection counts
``fault.injected`` on the global telemetry tracer.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time

log = logging.getLogger(__name__)

#: The named sites the pipeline exposes.  A spec naming an unknown site
#: is a hard error at install time — a typo'd site would otherwise arm
#: a clause that can never fire and silently test nothing.
KNOWN_POINTS = frozenset({
    "device.dispatch",
    "device.fetch",
    "parquet.write",
    "parquet.encode",
    "pool.prewarm",
    # host-process death (the kill-and-resume chaos harness,
    # scripts/chaos-kill-resume): the streamed pipeline arrives at this
    # site once per phase step, with the PHASE name in the ``device``
    # attribution slot — ``ingest`` (per tokenized window), ``pass_a``
    # (per window summary), ``pass_b`` (per observed window — the
    # mid-observe leg that exercises killing a run with device-resident
    # windows in flight), ``barrier2`` (before the observe merge and
    # again after the solve), ``pass_c`` (per part submit) and ``write``
    # (after each part's atomic publish) — so a clause like
    # ``proc.kill=kill,device=pass_c,after=3,times=1`` SIGKILLs the
    # process at a chosen (or ``p=F,seed=N`` randomized-but-seeded)
    # point without any cooperation from the code under test.  The
    # multi-job coalescer adds the ``batch`` phase (once per fused
    # cross-job dispatch, on the dispatcher thread) — the mid-batch
    # kill leg of the chaos matrix.
    "proc.kill",
    # multi-job transform service (adam_tpu/serve; docs/ROBUSTNESS.md
    # "Fault-isolated multi-job scheduling").  The ``device``
    # attribution slot carries the JOB ID, so a clause can target one
    # tenant's job without touching its neighbors:
    #   sched.admit      each submission's arrival at admission control
    #   sched.dispatch   each window grant the fairness interleaver
    #                    hands a job (the scheduler's hot path)
    #   sched.drain      entry into the graceful-drain sequence
    #   sched.job_crash  the top of every job run attempt — a
    #                    ``permanent`` clause keyed to one job id is the
    #                    canonical quarantine driver
    #   sched.batch      each fused cross-job dispatch the window
    #                    coalescer issues (serve/batching.py); the
    #                    ``device`` slot carries the PASS KIND
    #                    (markdup/observe/apply) — a failing clause
    #                    drives the per-job solo-fallback path
    "sched.admit",
    "sched.batch",
    "sched.dispatch",
    "sched.drain",
    "sched.job_crash",
    # HTTP gateway (adam_tpu/gateway; docs/SERVING.md).  The ``device``
    # attribution slot carries the JOB ID the request targets (or the
    # request path for non-job routes), so a clause can flake one
    # tenant's wire traffic without touching its neighbors:
    #   gateway.accept   every request's arrival at the router, before
    #                    any work — a ``transient`` clause surfaces as
    #                    a 503 with Retry-After (the client policy
    #                    absorbs it), ``permanent`` as a 500
    #   gateway.stream   each poll iteration of a live
    #                    /v1/jobs/<job>/events NDJSON stream
    #   gateway.fetch    before each chunk of part bytes a
    #                    /v1/jobs/<job>/parts/<part> response writes —
    #                    a ``kill`` clause here is the chaos harness's
    #                    gateway-dies-mid-download weapon (the client
    #                    resumes via Range)
    "gateway.accept",
    "gateway.stream",
    "gateway.fetch",
})


class FaultError(Exception):
    """Base class of injected faults (never raised itself)."""


class TransientFault(FaultError):
    """Injected retryable failure (a flaky RPC, a dropped dispatch)."""


class PermanentFault(FaultError):
    """Injected non-retryable failure (a dead chip); the retry wrappers
    re-raise it immediately and the eviction path takes over."""


#: Sites whose call path can actually flip result bits: ``corrupt``
#: clauses are only legal here (a corrupt clause on any other site
#: would arm an injection that can never fire — the same install-time
#: hard-error contract unknown sites get).  ``device.fetch`` is the one
#: data-bearing boundary every device result crosses
#: (``utils/transfer.device_fetch`` routes the fetched array through
#: :func:`corrupt_array`), so a dispatch's wrong answer and a torn
#: fetch are both expressible there.
CORRUPT_POINTS = frozenset({"device.fetch"})


class _Clause:
    __slots__ = (
        "site", "action", "delay_s", "every", "after", "times",
        "device", "pass_name", "p", "seed", "_rng", "_arrivals",
        "_fired",
    )

    def __init__(self, site: str, action: str, delay_s: float,
                 every: int | None, after: int, times: int | None,
                 device: str | None, p: float | None, seed: int,
                 pass_name: str | None = None):
        self.site = site
        self.action = action
        self.delay_s = delay_s
        self.every = every
        self.after = after
        self.times = times
        self.device = device
        self.pass_name = pass_name
        self.p = p
        self.seed = seed
        self._rng = random.Random(seed)
        self._arrivals = 0
        self._fired = 0

    def arrive(self, device, pass_name=None) -> bool:
        """Advance this clause's arrival counter and evaluate its
        predicate (called under the module lock).  Firing — and the
        ``times=`` accounting — is the caller's decision: every clause
        on a site sees every arrival, so 'the Nth time any call reaches
        this site' holds even when an earlier clause fires first."""
        if self.device is not None and str(device) != self.device:
            return False
        if self.pass_name is not None and pass_name != self.pass_name:
            return False
        self._arrivals += 1
        if self.times is not None and self._fired >= self.times:
            return False
        if self._arrivals <= self.after:
            return False
        if self.every is not None:
            return self._arrivals % self.every == 0
        if self.p is not None:
            return self._rng.random() < self.p
        return True


def _parse_clause(text: str) -> _Clause:
    head, _, opts = text.partition(",")
    site, sep, action = head.partition("=")
    site = site.strip()
    action = action.strip()
    if not sep or not site or not action:
        raise ValueError(
            f"fault clause {text!r}: expected 'site=action[,option...]'"
        )
    if site not in KNOWN_POINTS:
        raise ValueError(
            f"fault clause {text!r}: unknown fault point {site!r} "
            f"(known: {sorted(KNOWN_POINTS)})"
        )
    delay_s = 0.0
    if action.startswith("delay:"):
        try:
            delay_s = float(action[len("delay:"):])
        except ValueError:
            raise ValueError(
                f"fault clause {text!r}: delay wants a float seconds value"
            ) from None
        action = "delay"
    if action not in ("transient", "permanent", "delay", "kill",
                      "corrupt"):
        raise ValueError(
            f"fault clause {text!r}: unknown action {action!r} "
            "(expected transient | permanent | delay:<seconds> | kill "
            "| corrupt)"
        )
    if action == "corrupt" and site not in CORRUPT_POINTS:
        raise ValueError(
            f"fault clause {text!r}: 'corrupt' only fires at the "
            f"corruption-capable sites {sorted(CORRUPT_POINTS)} — a "
            "clause here would arm an injection that can never flip "
            "anything"
        )
    every = times = None
    after = 0
    device = None
    pass_name = None
    p = None
    seed = 0
    for opt in filter(None, (o.strip() for o in opts.split(","))):
        key, sep, val = opt.partition("=")
        if not sep:
            raise ValueError(f"fault clause {text!r}: bad option {opt!r}")
        try:
            if key == "every":
                every = int(val)
                if every < 1:
                    raise ValueError
            elif key == "after":
                after = int(val)
            elif key == "times":
                times = int(val)
            elif key == "device":
                device = val
            elif key == "pass":
                pass_name = val
            elif key == "p":
                p = float(val)
            elif key == "seed":
                seed = int(val)
            else:
                raise ValueError(
                    f"fault clause {text!r}: unknown option {key!r}"
                )
        except ValueError as e:
            if e.args and "fault clause" in str(e):
                raise
            raise ValueError(
                f"fault clause {text!r}: bad value for {key!r}: {val!r}"
            ) from None
    return _Clause(site, action, delay_s, every, after, times, device, p,
                   seed, pass_name)


def parse_spec(spec: str) -> list:
    """Parse a fault-spec string into clauses (validation errors raise
    ``ValueError`` with the offending clause)."""
    return [
        _parse_clause(c)
        for c in filter(None, (c.strip() for c in spec.split(";")))
    ]


# -------------------------------------------------------------------------
# Module state: ENABLED is the one branch the disabled fast path pays.
# -------------------------------------------------------------------------
ENABLED = False
_CLAUSES: list = []
_LOCK = threading.Lock()


def install(spec: str | None) -> None:
    """Arm (or, with None/empty, disarm) a fault spec process-wide.

    Arming or disarming also RESETS the device-health scoreboard
    (utils/health.py): the board's whole point is remembering real
    hardware misbehavior across runs, and signals manufactured by an
    injected spec are not that — without the reset, one test's
    injected evictions would leak probation/evicted states into every
    later run in the process.  Production never arms specs, so the
    persistent-scoreboard contract is untouched there."""
    global ENABLED, _CLAUSES
    clauses = parse_spec(spec) if spec else []
    with _LOCK:
        was = ENABLED
        _CLAUSES = clauses
        ENABLED = bool(clauses)
    if was or clauses:
        try:
            from adam_tpu.utils import health as health_mod

            health_mod.reset_board()
        except Exception:
            pass
    if clauses:
        log.warning(
            "fault injection ARMED: %d clause(s) from %r (this is a "
            "testing facility; unset ADAM_TPU_FAULTS / --fault-spec for "
            "production runs)", len(clauses), spec,
        )


def clear() -> None:
    """Disarm all fault clauses (test teardown hook)."""
    install(None)


def _current_pass():
    """The thread's active telemetry pass scope (the ``pass=NAME``
    clause selector matches against it); None outside any scope."""
    from adam_tpu.utils import telemetry as tele

    return tele.current_pass()


def point(site: str, device=None, pass_name=None) -> None:
    """A named fault point.  Disabled cost: one module-global branch.

    ``device``: the jax device (or its id) the call is attributed to,
    matched against a clause's ``device=K`` filter the same way the
    telemetry ``device=<k>`` span attribution is keyed.  ``pass_name``
    overrides the thread-local telemetry pass scope for the ``pass=``
    clause selector — call sites that arrive on helper threads (the
    fetch watchdog) capture the scope on the caller thread and thread
    it through.  ``corrupt`` clauses never fire here — they live on
    the data channel (:func:`corrupt_array`), and the two channels
    count arrivals independently so a mixed spec's ``every``/``after``
    schedules stay anchored to the arrivals each action can see.
    """
    if not ENABLED:
        return
    dev_id = getattr(device, "id", device)
    if pass_name is None:
        pass_name = _current_pass()
    fire = None
    with _LOCK:
        # every same-site clause counts the arrival (so each clause's
        # every/after schedule is anchored to REAL arrivals at the
        # site); the first whose predicate matches fires
        for clause in _CLAUSES:
            if clause.site != site or clause.action == "corrupt":
                continue
            if clause.arrive(dev_id, pass_name) and fire is None:
                fire = clause
        if fire is not None:
            fire._fired += 1
    if fire is None:
        return
    from adam_tpu.utils import telemetry as tele

    tele.TRACE.count(tele.C_FAULT_INJECTED)
    if fire.action == "delay":
        log.warning("fault injected at %s (device=%s): delay %.3fs",
                    site, dev_id, fire.delay_s)
        time.sleep(fire.delay_s)
        return
    if fire.action == "kill":
        # a real host-process death: SIGKILL to self, no cleanup, no
        # atexit — exactly what an OOM kill or a preemption delivers.
        # The durable-resume machinery (docs/ROBUSTNESS.md) is what
        # must survive this; nothing in-process is supposed to.
        import signal

        log.warning("fault injected at %s (device=%s): SIGKILL self",
                    site, dev_id)
        os.kill(os.getpid(), signal.SIGKILL)
        return  # pragma: no cover - unreachable after SIGKILL
    log.warning("fault injected at %s (device=%s): %s", site, dev_id,
                fire.action)
    if fire.action == "permanent":
        raise PermanentFault(f"injected permanent fault at {site}"
                             f" (device={dev_id})")
    raise TransientFault(f"injected transient fault at {site}"
                         f" (device={dev_id})")


def corrupt_array(site: str, arr, device=None, pass_name=None):
    """The data channel of the fault grammar: pass a just-produced
    result array through the ``corrupt`` clauses armed at ``site`` and
    return it — bit-flipped when a clause fires, untouched (the very
    same object) otherwise.  Disabled cost: one module-global branch.

    The flip is **deterministic**: the flipped bit's position derives
    from a seeded RNG per clause (``seed=N``), so a chaos run
    reproduces the exact corruption from its spec — and the SDC audit
    (docs/ROBUSTNESS.md "Device health, hedging, and SDC audit") must
    detect every one of them.  Non-numpy results (scalars, lists) pass
    through unflipped: every corruption-capable site hands numpy in
    practice, and a silent skip is exactly what a bit flip in
    un-auditable metadata must never be mistaken for.
    """
    if not ENABLED:
        return arr
    dev_id = getattr(device, "id", device)
    if pass_name is None:
        pass_name = _current_pass()
    fire = None
    with _LOCK:
        for clause in _CLAUSES:
            if clause.site != site or clause.action != "corrupt":
                continue
            if clause.arrive(dev_id, pass_name) and fire is None:
                fire = clause
        if fire is not None:
            fire._fired += 1
            # one RNG draw per injection, under the lock: the flipped
            # byte/bit sequence is a pure function of (seed, #fired)
            draw = fire._rng.random()
    if fire is None:
        return arr
    import numpy as np

    a = np.asarray(arr)
    if a.size == 0 or a.dtype == object:
        return arr
    out = np.array(a, copy=True)
    # reshape BEFORE the u8 view: a 0-d result (a scalar fetch) cannot
    # view-cast to a different itemsize, and the corrupt channel must
    # never raise — reshape(-1) of the fresh contiguous copy is a view,
    # so the flip below lands in `out`
    flat = out.reshape(-1).view(np.uint8).reshape(-1)
    pos = int(draw * flat.size * 8) % (flat.size * 8)
    flat[pos // 8] ^= np.uint8(1 << (pos % 8))
    from adam_tpu.utils import telemetry as tele

    tele.TRACE.count(tele.C_FAULT_INJECTED)
    log.warning(
        "fault injected at %s (device=%s, pass=%s): corrupt — flipped "
        "bit %d of a %d-byte result", site, dev_id, pass_name,
        pos, flat.size,
    )
    return out


# Arm from the environment at import: subprocess drivers (the CI fault
# leg, the SIGKILL crash-consistency test) configure via ADAM_TPU_FAULTS
# without touching the CLI.
if os.environ.get("ADAM_TPU_FAULTS", "").strip():
    install(os.environ["ADAM_TPU_FAULTS"])
