"""GATK/Picard interval_list reader.

Parity with ``util/IntervalListReader.scala``: the file carries a SAM
text header (@HD/@SQ lines) giving the sequence dictionary, followed by
tab-separated ``sequence start end strand name`` rows with **1-based
inclusive** coordinates. Iteration yields 0-based half-open
``(ReferenceRegion, name)`` pairs (the coordinate convention of this
framework; the reference forwards htsjdk's 1-based values unchanged).
"""

from __future__ import annotations

from adam_tpu.models.dictionaries import SequenceDictionary, SequenceRecord
from adam_tpu.models.positions import ReferenceRegion


class IntervalListReader:
    def __init__(self, path: str):
        self.path = path

    @property
    def sequence_dictionary(self) -> SequenceDictionary:
        records = []
        with open(self.path) as fh:
            for line in fh:
                if not line.startswith("@"):
                    break
                if line.startswith("@SQ"):
                    fields = dict(
                        f.split(":", 1)
                        for f in line.rstrip("\n").split("\t")[1:]
                        if ":" in f
                    )
                    records.append(
                        SequenceRecord(
                            fields["SN"], int(fields["LN"]),
                            md5=fields.get("M5"), url=fields.get("UR"),
                        )
                    )
        return SequenceDictionary(tuple(records))

    def __iter__(self):
        with open(self.path) as fh:
            for line in fh:
                if line.startswith("@") or not line.strip():
                    continue
                f = line.rstrip("\n").split("\t")
                seq, start, end = f[0], int(f[1]), int(f[2])
                name = f[4] if len(f) > 4 else ""
                yield ReferenceRegion(seq, start - 1, end), name

    def regions(self) -> list:
        return [r for r, _ in self]
