"""Device->host transfer helpers.

On a hosted/tunneled TPU the device link is the pipeline bottleneck
(measured 2-30 MB/s, high variance); fetching a large array as several
row slices on a thread pool roughly doubles sustained throughput by
keeping multiple transfer RPCs in flight. On directly-attached devices
the chunking is harmless (PCIe/DMA is far faster than any of this).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np

_MIN_CHUNK_BYTES = 8 * 1024 * 1024


def _max_threads() -> int:
    """Fetch-pool thread cap: bounded by the cores this process may
    actually run on.  The hosted environment schedules ONE core
    (``os.sched_getaffinity(0) == {0}``); the old fixed cap of 8 made
    every large fetch spin up 8 threads that competed with the
    PartWriterPool's encode threads for that single core — transfer RPCs
    release the GIL, but chunk reassembly and executor bookkeeping do
    not."""
    try:
        n = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # non-Linux fallback
        n = os.cpu_count() or 1
    return max(1, min(8, n))


_MAX_THREADS = _max_threads()


def device_fetch(x, threads: int = _MAX_THREADS) -> np.ndarray:
    """Fetch a (possibly device-resident) array to host numpy."""
    nbytes = getattr(x, "nbytes", 0)
    if nbytes < 2 * _MIN_CHUNK_BYTES or x.ndim == 0:
        return np.asarray(x)
    n = x.shape[0]
    n_chunks = min(threads, max(1, int(nbytes // _MIN_CHUNK_BYTES)), n)
    if n_chunks <= 1:
        return np.asarray(x)
    bounds = [n * i // n_chunks for i in range(n_chunks + 1)]
    slices = [x[bounds[i]: bounds[i + 1]] for i in range(n_chunks)]
    with ThreadPoolExecutor(n_chunks) as ex:
        parts = list(ex.map(np.asarray, slices))
    return np.concatenate(parts, axis=0)
