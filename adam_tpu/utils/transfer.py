"""Device->host transfer helpers.

On a hosted/tunneled TPU the device link is the pipeline bottleneck
(measured 2-30 MB/s, high variance); fetching a large array as several
row slices on a thread pool roughly doubles sustained throughput by
keeping multiple transfer RPCs in flight. On directly-attached devices
the chunking is harmless (PCIe/DMA is far faster than any of this).

Every fetch is also a **resilience boundary** (docs/ROBUSTNESS.md):

* a fault point (``device.fetch``) so the injection matrix can drive
  the recovery paths deterministically;
* a deadline watchdog (``ADAM_TPU_FETCH_TIMEOUT_S``, default 300 s,
  ``0`` disables) so a hung transfer RPC surfaces as a retryable
  :class:`~adam_tpu.utils.retry.DeadlineExceeded` instead of wedging
  the run;
* an internal retry-with-backoff for transient failures, so callers
  only ever see a fetch error after the budget is spent — at which
  point the device-eviction path (pipelines/streamed.py) takes over.

Host-resident numpy inputs short-circuit all of it: the watchdog and
retry wrap RPCs, not memcpys.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from adam_tpu.utils import faults
from adam_tpu.utils import retry as retry_mod

_MIN_CHUNK_BYTES = 8 * 1024 * 1024
_DEFAULT_FETCH_TIMEOUT_S = 300.0


def _max_threads() -> int:
    """Fetch-pool thread cap: bounded by the cores this process may
    actually run on, **floored at 2**.  The hosted environment schedules
    ONE core (``os.sched_getaffinity(0) == {0}``); the old fixed cap of
    8 made every large fetch spin up 8 threads that competed with the
    PartWriterPool's encode threads for that single core.  But the
    chunked overlap is GIL-released RPC *wait*, not CPU work — capping
    at the affinity count regressed the 1-core target to a serial fetch
    and gave back the measured ~2x (ROADMAP "re-measure chunked
    device_fetch under the affinity cap"), so the floor keeps two RPCs
    in flight regardless of affinity.

    ``ADAM_TPU_FETCH_THREADS`` overrides the floor (clamped to [1, 8])
    for the real-tunnel experiment the ``device.d2h.bps`` throughput
    histogram now makes decidable: if the histogram shows the link
    idling between chunk turnarounds at floor 2, set 4 and re-measure —
    no code change required.  The CPU-leg measurement (docs/PERF.md
    "fetch-pool I/O floor") could NOT justify raising the default: its
    fetch wall is kernel-execution wait, not link idle."""
    raw = os.environ.get("ADAM_TPU_FETCH_THREADS", "").strip()
    if raw:
        try:
            return max(1, min(8, int(raw)))
        except ValueError:
            import logging

            logging.getLogger(__name__).warning(
                "ADAM_TPU_FETCH_THREADS=%r is not an int; using the "
                "affinity-derived default", raw,
            )
    try:
        n = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # non-Linux fallback
        n = os.cpu_count() or 1
    return max(2, min(8, n))


_MAX_THREADS = _max_threads()


def _fetch_timeout_s() -> float:
    """The fetch deadline (seconds; <= 0 disables the watchdog)."""
    return retry_mod.env_float(
        "ADAM_TPU_FETCH_TIMEOUT_S", _DEFAULT_FETCH_TIMEOUT_S
    )


def _map_daemon(fn, items: list) -> list:
    """``ThreadPoolExecutor.map`` twin on daemon threads.  The chunked
    fetch runs under the deadline watchdog, which ABANDONS it on
    timeout — but concurrent.futures joins its (non-daemon) workers at
    interpreter shutdown, so a genuinely hung RPC would wedge the
    recovered process at exit.  Daemon threads cannot."""
    results = [None] * len(items)
    errs = [None] * len(items)

    def run(k, item):
        try:
            results[k] = fn(item)
        except BaseException as e:  # noqa: BLE001 — relayed below
            errs[k] = e

    threads = [
        threading.Thread(target=run, args=(k, item), daemon=True,
                         name="device-fetch-chunk")
        for k, item in enumerate(items)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for e in errs:
        if e is not None:
            raise e
    return results


def _resident_device(x):
    """The device an array lives on (None when indeterminable) — the
    fault point's ``device=K`` filter and eviction logs key on it.
    Arrays spanning SEVERAL devices (the mesh partitioner's sharded or
    replicated outputs: ``x.device`` is a Sharding, not a Device)
    return the string ``"mesh"`` — the same collective attribution
    their dispatch spans carry."""
    try:
        d = getattr(x, "device", None)
        if d is not None and not callable(d):
            if getattr(d, "id", None) is None:  # a Sharding object
                devs = getattr(x, "devices", None)
                ds = devs() if callable(devs) else set()
                if len(ds) > 1:
                    return "mesh"
                if ds:
                    return next(iter(ds))
            return d
        ds = x.devices()
        if len(ds) > 1:
            return "mesh"
        return next(iter(ds))
    except Exception:
        return None


def _fetch_chunked(x, threads: int, pass_name=None) -> np.ndarray:
    """One fetch attempt (the pre-resilience device_fetch body).  The
    fetched result passes through the fault grammar's data channel
    (``corrupt`` clauses at ``device.fetch`` — the silent-data-
    corruption injection the SDC audit must catch); the disabled cost
    is one module-global branch.  ``pass_name`` is the caller thread's
    telemetry pass scope (this body runs on the deadline watchdog
    thread, which carries none of its own)."""
    dev = _resident_device(x)
    faults.point("device.fetch", device=dev, pass_name=pass_name)
    nbytes = getattr(x, "nbytes", 0)
    if nbytes < 2 * _MIN_CHUNK_BYTES or x.ndim == 0:
        return faults.corrupt_array("device.fetch", np.asarray(x),
                                    device=dev, pass_name=pass_name)
    n = x.shape[0]
    n_chunks = min(threads, max(1, int(nbytes // _MIN_CHUNK_BYTES)), n)
    if n_chunks <= 1:
        return faults.corrupt_array("device.fetch", np.asarray(x),
                                    device=dev, pass_name=pass_name)
    bounds = [n * i // n_chunks for i in range(n_chunks + 1)]
    slices = [x[bounds[i]: bounds[i + 1]] for i in range(n_chunks)]
    parts = _map_daemon(np.asarray, slices)
    return faults.corrupt_array(
        "device.fetch", np.concatenate(parts, axis=0), device=dev,
        pass_name=pass_name,
    )


def device_fetch(x, threads: int = _MAX_THREADS,
                 deadline_s: float | None = None) -> np.ndarray:
    """Fetch a (possibly device-resident) array to host numpy.

    Device-resident inputs get the full resilience stack (deadline
    watchdog + transient retry, module docstring); host numpy inputs
    return as-is with none of it.  ``deadline_s`` overrides the
    ``ADAM_TPU_FETCH_TIMEOUT_S`` default for this call.
    """
    if isinstance(x, np.ndarray):
        return x
    timeout = _fetch_timeout_s() if deadline_s is None else deadline_s

    from adam_tpu.utils import telemetry as tele

    # the pass scope is thread-local and the attempt body runs on the
    # watchdog thread: capture it HERE so the fault grammar's pass=
    # selector sees the pipeline pass this fetch belongs to
    pass_name = tele.current_pass()

    def attempt():
        if timeout and timeout > 0:
            return retry_mod.call_with_deadline(
                lambda: _fetch_chunked(x, threads, pass_name), timeout,
                site="device.fetch",
            )
        return _fetch_chunked(x, threads, pass_name)

    def retryable(e: BaseException) -> bool:
        # the health scoreboard remembers what the retry wrappers
        # absorb: device-attributed transient failures and watchdog
        # trips feed the per-device score (utils/health.py) before the
        # backoff hides them.  Only REAL single-device attributions
        # feed it: a None (indeterminable) or "mesh" (collective)
        # source would accrue penalties on a phantom key no pool can
        # ever probe or exclude.
        ok = retry_mod.is_retryable(e)
        if ok:
            dev = _resident_device(x)
            if dev is not None and getattr(dev, "id", None) is not None:
                from adam_tpu.utils import health as health_mod

                if isinstance(e, retry_mod.DeadlineExceeded):
                    health_mod.BOARD.note_timeout(
                        dev, site="device.fetch"
                    )
                else:
                    health_mod.BOARD.note_retry(dev, site="device.fetch")
        return ok

    if not tele.TRACE.recording:
        return retry_mod.retry_call(attempt, site="device.fetch",
                                    retryable=retryable)
    # latency histogram over every device->host fetch (seconds,
    # retries included — the caller-visible latency): on a tunneled
    # link the barrier-2 and pass-C walls are governed by the fetch
    # TAIL, which the scalar span totals cannot show.  The d2h transfer
    # ledger rides the same timing: bytes + throughput attributed to
    # the resident device and the active pipeline pass (pass_scope),
    # so the analyzer can report tunnel utilization per direction.
    t0 = time.monotonic()
    out = None
    try:
        out = retry_mod.retry_call(attempt, site="device.fetch",
                                   retryable=retryable)
        return out
    finally:
        dur = time.monotonic() - t0
        tele.TRACE.observe(tele.H_FETCH_SECONDS, dur)
        if out is not None:
            dev = _resident_device(x)
            dev_id = None
            if dev is not None:
                dev_id = getattr(dev, "id", None)
                if dev_id is None:
                    dev_id = str(dev)
            tele.TRACE.record_transfer(
                "d2h", getattr(out, "nbytes", 0), dur, device=dev_id,
            )
