"""Append-only per-run perf history + the regression sentinel
(docs/OBSERVABILITY.md "The perf ledger").

``scripts/bench-diff`` can compare any TWO artifacts, but a single
pairwise diff cannot tell a noisy run from a trend.  This module keeps
the longitudinal record: on every completed run/job the bench-diff key
extractor (mirrored here so the script stays dependency-free) books
the run's direction-aware perf keys — span walls, the derived
``stages.*`` tail identities, h2d/d2h transfer totals, the
``compiles.in_window`` count, kernelbench rows when present — as one
NDJSON line in ``<run-root>/PERF_LEDGER.ndjson``.  A **sentinel** then
compares the new run against the rolling median of the previous
``ADAM_TPU_PERF_BASELINE_N`` runs (median, not mean: one straggler run
must not poison the baseline) and flags direction-aware regressions
past ``ADAM_TPU_PERF_THRESHOLD`` percent — each flagged run emits a
``perf.regression`` incident bundle, counts ``perf.regressions``, and
charges the SLO error budget (a confirmed regression spends budget
even when no individual job missed its bound).

The ledger is append-only NDJSON: concurrent appends from scheduler
job threads interleave whole lines (single ``write`` under a lock), a
torn final line from a crash is skipped on read, and the history
survives restarts for free.  ``adam-tpu perf`` renders the trend table
(``--json`` for machines) and exits 1 when the newest run regresses —
the CI leg.

The sentinel needs at least :data:`MIN_BASELINE_RUNS` prior entries
before it will flag anything: with one or two runs of history a
"regression" is indistinguishable from noise (and a resumed run's
second booking must not page anyone).
"""

from __future__ import annotations

import json
import logging
import os
import statistics
import threading
import time
from typing import Optional

from adam_tpu.utils import telemetry as tele

log = logging.getLogger(__name__)

#: Schema tag on every ledger line.
LEDGER_SCHEMA = "adam_tpu.perf_ledger/1"

#: Ledger file name under the run root.
LEDGER_FILENAME = "PERF_LEDGER.ndjson"

#: Default regression threshold, percent (``ADAM_TPU_PERF_THRESHOLD``).
DEFAULT_THRESHOLD_PCT = 25.0

#: Default rolling-baseline depth (``ADAM_TPU_PERF_BASELINE_N``).
DEFAULT_BASELINE_N = 5

#: The sentinel stays silent with fewer prior runs than this.
MIN_BASELINE_RUNS = 3

#: Walls below this (seconds / counts) are noise, not signal: a
#: 0.8 ms span doubling to 1.6 ms is scheduler jitter, not a perf
#: regression.  Keys whose baseline median sits under the floor are
#: booked but never flagged.
MIN_BASELINE_VALUE = 5e-3

_APPEND_LOCK = threading.Lock()


def perf_threshold_pct() -> float:
    """``ADAM_TPU_PERF_THRESHOLD`` (percent; malformed or nonpositive
    warns and keeps the default)."""
    from adam_tpu.utils.retry import env_float

    v = env_float("ADAM_TPU_PERF_THRESHOLD", DEFAULT_THRESHOLD_PCT)
    if v <= 0:
        log.warning("ADAM_TPU_PERF_THRESHOLD=%s is not positive; using "
                    "default %.0f%%", v, DEFAULT_THRESHOLD_PCT)
        return DEFAULT_THRESHOLD_PCT
    return v


def baseline_n() -> int:
    """``ADAM_TPU_PERF_BASELINE_N`` (rolling median depth)."""
    from adam_tpu.utils.retry import _env_int

    v = _env_int("ADAM_TPU_PERF_BASELINE_N", DEFAULT_BASELINE_N)
    if v <= 0:
        log.warning("ADAM_TPU_PERF_BASELINE_N=%s is not positive; using "
                    "default %d", v, DEFAULT_BASELINE_N)
        return DEFAULT_BASELINE_N
    return v


def booking_enabled() -> bool:
    """``ADAM_TPU_PERF_LEDGER`` (default on): whether completed runs
    book into the ledger at all."""
    from adam_tpu.utils.retry import env_toggle

    return env_toggle("ADAM_TPU_PERF_LEDGER", True)


def snapshot_keys(doc: dict) -> dict:
    """Telemetry snapshot -> ``{key: (value, direction)}`` — the
    bench-diff ``--metrics-json`` key extractor, with the sentinel's
    direction choices: span walls and the derived ``stages.*`` tail
    identities are lower-is-better, the ``compiles.in_window`` count
    is lower-is-better here (a NEW in-window cold compile between runs
    of the same input IS a prewarm-coverage regression), transfer
    totals and counters are informational (input-size dependent),
    kernelbench rows are lower-is-better except interpret mode."""
    out = {}
    for k, v in (doc.get("counters") or {}).items():
        if isinstance(v, (int, float)):
            out[f"counters.{k}"] = (float(v), None)
    spans = doc.get("spans") or {}

    def span_s(name):
        e = spans.get(name)
        t = e.get("total_s") if isinstance(e, dict) else None
        return float(t) if isinstance(t, (int, float)) else None

    for name, e in spans.items():
        t = e.get("total_s") if isinstance(e, dict) else None
        if isinstance(t, (int, float)):
            out[f"spans.{name}.total_s"] = (float(t), "lower")
    pass_c = span_s("streamed.pass_c")
    write_wait = span_s("streamed.write_wait")
    if pass_c is not None:
        apply_split = max(
            0.0,
            pass_c
            - (span_s("streamed.apply.dispatch") or 0.0)
            - (span_s("streamed.apply.fetch") or 0.0)
            - (span_s("device.pool.prewarm.pass_c") or 0.0),
        )
        out["stages.apply_split_s"] = (apply_split, "lower")
        if write_wait is not None:
            out["stages.apply_split_plus_write_wait_s"] = (
                apply_split + write_wait, "lower",
            )
    xfer = doc.get("transfers") or {}
    for direction in ("h2d", "d2h"):
        per_pass = {}
        for _dev, per in (xfer.get(direction) or {}).items():
            for p, v in (per or {}).items():
                b = v.get("bytes", 0) if isinstance(v, dict) else 0
                per_pass[p] = per_pass.get(p, 0) + b
        total = sum(b for p, b in per_pass.items() if p != "prewarm")
        if per_pass:
            out[f"transfers.{direction}.total.bytes"] = (float(total), None)
    compiles = doc.get("compiles") or {}
    entries = compiles.get("entries")
    if isinstance(entries, list):
        n_in_window = sum(
            1 for e in entries
            if isinstance(e, dict) and e.get("in_window"))
        out["compiles.in_window"] = (float(n_in_window), "lower")
    elif isinstance(compiles.get("in_window"), list):
        # bench secondary-line shape (utilization.chip.compiles)
        out["compiles.in_window"] = (
            float(len(compiles["in_window"])), "lower")
    for row in (doc.get("kernels") or {}).get("rows") or []:
        if not isinstance(row, dict) or "error" in row:
            continue
        base = (f"kernels.{row.get('kernel')}.{row.get('backend')}"
                f".g{row.get('g')}x{row.get('gl')}")
        direction = None if row.get("mode") == "interpret" else "lower"
        for key in ("mean_s", "best_s"):
            v = row.get(key)
            if isinstance(v, (int, float)):
                out[f"{base}.{key}"] = (float(v), direction)
    return out


def ledger_path(root: str) -> str:
    """Accepts a run root or the ledger file itself."""
    if os.path.basename(root) == LEDGER_FILENAME:
        return root
    return os.path.join(root, LEDGER_FILENAME)


def book(root: str, snapshot: dict, *, run_id: Optional[str] = None,
         kind: str = "run") -> dict:
    """Append one ledger entry for ``snapshot`` (a telemetry snapshot
    or an already-extracted key map) and return it."""
    if snapshot and all(isinstance(v, tuple) for v in snapshot.values()):
        keys = snapshot
    else:
        keys = snapshot_keys(snapshot or {})
    entry = {
        "schema": LEDGER_SCHEMA,
        "ts": time.time(),
        "run_id": run_id,
        "kind": kind,
        "keys": {k: [v, d] for k, (v, d) in sorted(keys.items())},
    }
    path = ledger_path(root)
    line = json.dumps(entry, sort_keys=True) + "\n"
    with _APPEND_LOCK:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(line)
            fh.flush()
    return entry


def read_ledger(root: str) -> list:
    """All well-formed entries, oldest first; a torn final line (crash
    mid-append) and foreign lines are skipped, never fatal."""
    path = ledger_path(root)
    entries = []
    try:
        with open(path, encoding="utf-8") as fh:
            for ln in fh:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    doc = json.loads(ln)
                except ValueError:
                    continue
                if (isinstance(doc, dict)
                        and doc.get("schema") == LEDGER_SCHEMA):
                    entries.append(doc)
    except OSError:
        return []
    return entries


def _entry_keys(entry: dict) -> dict:
    """Ledger entry -> {key: (value, direction)}."""
    out = {}
    for k, pair in (entry.get("keys") or {}).items():
        if (isinstance(pair, list) and len(pair) == 2
                and isinstance(pair[0], (int, float))):
            out[k] = (float(pair[0]), pair[1])
    return out


def rolling_baseline(entries: list, n: Optional[int] = None) -> dict:
    """Per-key median over the last ``n`` entries -> {key: (median,
    direction, count)}.  A key only enters the baseline when a
    majority of the sampled entries carry it (a key that appears once
    in five runs is a feature-flag artifact, not a trend)."""
    n = n if n is not None else baseline_n()
    window = entries[-n:] if n > 0 else list(entries)
    if not window:
        return {}
    per_key: dict = {}
    for e in window:
        for k, (v, d) in _entry_keys(e).items():
            per_key.setdefault(k, ([], d))[0].append(v)
    quorum = len(window) // 2 + 1
    return {
        k: (statistics.median(vals), d, len(vals))
        for k, (vals, d) in per_key.items()
        if len(vals) >= quorum
    }


def compare(entry: dict, baseline: dict,
            threshold_pct: Optional[float] = None) -> list:
    """Direction-aware regressions of ``entry`` vs ``baseline`` ->
    ``[{key, baseline, value, delta_pct}, ...]``.  Informational keys
    (direction None) and sub-noise-floor baselines never flag."""
    thr = threshold_pct if threshold_pct is not None else perf_threshold_pct()
    regressions = []
    for k, (value, direction) in sorted(_entry_keys(entry).items()):
        row = baseline.get(k)
        if row is None or direction is None:
            continue
        base, _d, _n = row
        if base < MIN_BASELINE_VALUE:
            continue
        delta = (value - base) / base * 100.0
        regressed = (delta > thr if direction == "lower"
                     else delta < -thr)
        if regressed:
            regressions.append({
                "key": k,
                "baseline": base,
                "value": value,
                "delta_pct": round(delta, 3),
                "direction": direction,
            })
    return regressions


def check_latest(root: str, *, threshold_pct: Optional[float] = None,
                 n: Optional[int] = None) -> list:
    """Regressions of the NEWEST ledger entry vs the rolling median of
    the entries before it; empty when history is too shallow
    (< :data:`MIN_BASELINE_RUNS` priors)."""
    entries = read_ledger(root)
    if len(entries) < MIN_BASELINE_RUNS + 1:
        return []
    baseline = rolling_baseline(entries[:-1], n)
    return compare(entries[-1], baseline, threshold_pct)


def sentinel(root: str, snapshot: dict, *, run_id: Optional[str] = None,
             kind: str = "run",
             threshold_pct: Optional[float] = None,
             n: Optional[int] = None) -> list:
    """Book ``snapshot`` and judge it: compare against the rolling
    median of the prior runs, and on any regression count
    ``perf.regressions``, emit a ``perf.regression`` incident bundle,
    and charge the SLO error budget.  Returns the regression list."""
    prior = read_ledger(root)
    entry = book(root, snapshot, run_id=run_id, kind=kind)
    if len(prior) < MIN_BASELINE_RUNS:
        return []
    baseline = rolling_baseline(prior, n)
    regressions = compare(entry, baseline, threshold_pct)
    if not regressions:
        return []
    tele.TRACE.count(tele.C_PERF_REGRESSIONS, len(regressions))
    worst = max(regressions, key=lambda r: abs(r["delta_pct"]))
    reason = (
        f"run {run_id or '?'}: {len(regressions)} perf key(s) regressed "
        f"past threshold; worst {worst['key']} "
        f"{worst['delta_pct']:+.1f}% vs rolling median "
        f"{worst['baseline']:.4g}"
    )
    from adam_tpu.utils import incidents

    incidents.maybe_record("perf.regression", trace_id=run_id,
                           reason=reason)
    from adam_tpu.utils import slo

    slo.note_perf_regression(len(regressions), reason=reason)
    return regressions


def trend(entries: list, *, n: Optional[int] = None,
          threshold_pct: Optional[float] = None) -> list:
    """Per-entry trend rows for ``adam-tpu perf``: each entry judged
    against the rolling median of the entries BEFORE it (the first
    :data:`MIN_BASELINE_RUNS` rows are baseline-building, never
    flagged)."""
    rows = []
    for i, e in enumerate(entries):
        keys = _entry_keys(e)
        wall = keys.get("spans.streamed.total.total_s")
        regressions = []
        if i >= MIN_BASELINE_RUNS:
            baseline = rolling_baseline(entries[:i], n)
            regressions = compare(e, baseline, threshold_pct)
        rows.append({
            "index": i,
            "ts": e.get("ts"),
            "run_id": e.get("run_id"),
            "kind": e.get("kind"),
            "n_keys": len(keys),
            "total_s": wall[0] if wall else None,
            "regressions": regressions,
        })
    return rows


# ---- module-level arm/disarm (the incident-recorder pattern) ----

_ROOT: Optional[str] = None


def install(run_root: str) -> None:
    """Arm the ledger on a service run root: completed jobs book
    there instead of their own run dirs."""
    global _ROOT
    _ROOT = os.path.abspath(run_root)


def uninstall() -> None:
    global _ROOT
    _ROOT = None


def installed() -> bool:
    return _ROOT is not None


def ledger_root() -> Optional[str]:
    return _ROOT


def _reset_for_tests() -> None:
    uninstall()
