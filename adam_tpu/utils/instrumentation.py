"""Named-timer registry and metrics report.

The tracing shape of the reference (``instrumentation/Timers.scala:25-81``
+ bdg-utils ``Metrics``): one named timer per pipeline stage / hot loop,
used as ``with TIMERS.time("Sort Reads"): ...`` wherever the reference
writes ``SortReads.time { ... }``; the CLI's ``-print_metrics`` prints
the aggregated table at command end (``ADAMCommand.scala:56-89``).

TPU additions: timers can wrap a ``jax.profiler`` trace
(:func:`device_trace`) so a stage's XLA execution shows up in xprof, and
:func:`block` synchronizes device work so wall times mean what they say.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field


@dataclass
class Timer:
    name: str
    total_ns: int = 0
    count: int = 0

    @property
    def total_s(self) -> float:
        return self.total_ns / 1e9


@dataclass
class TimerRegistry:
    timers: dict = field(default_factory=dict)
    recording: bool = False
    # Codec/write timers fire from the ingest thread and the writer pool
    # concurrently (pipelines/streamed.py); a lock keeps the
    # read-modify-write on Timer.total_ns from losing updates.
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def timer(self, name: str) -> Timer:
        if name not in self.timers:
            self.timers[name] = Timer(name)
        return self.timers[name]

    @contextlib.contextmanager
    def time(self, name: str):
        if not self.recording:
            yield
            return
        t0 = time.monotonic_ns()
        try:
            yield
        finally:
            dt = time.monotonic_ns() - t0
            with self._lock:
                t = self.timer(name)
                t.total_ns += dt
                t.count += 1

    def add(self, name: str, ns: int) -> None:
        """Accumulate an externally-measured duration under ``name``
        (for stages whose wall is computed elsewhere, e.g. the streamed
        pipeline's stats dict)."""
        if not self.recording:
            return
        with self._lock:
            t = self.timer(name)
            t.total_ns += ns
            t.count += 1

    def reset(self) -> None:
        with self._lock:
            self.timers.clear()

    def report(self) -> str:
        """Aggregated table, longest stages first (the Metrics printout)."""
        rows = sorted(self.timers.values(), key=lambda t: -t.total_ns)
        if not rows:
            return "Timings\n=======\n(no timers recorded)\n"
        w = max(len(t.name) for t in rows)
        out = ["Timings", "======="]
        out.append(f"{'timer'.ljust(w)}  {'count':>7}  {'total s':>10}")
        for t in rows:
            out.append(f"{t.name.ljust(w)}  {t.count:>7}  {t.total_s:>10.3f}")
        return "\n".join(out) + "\n"


#: Process-wide registry — the ``object Timers`` analog.
TIMERS = TimerRegistry()

# Named stages mirroring instrumentation/Timers.scala:25-81 (subset that
# maps onto this framework's stages; names kept recognizable).
LOAD_ALIGNMENTS = "Load Alignments"
SORT_READS = "Sort Reads"
MARK_DUPLICATES = "Mark Duplicates"
BQSR = "Base Quality Recalibration"
REALIGN_INDELS = "Realign Indels"
TRIM_READS = "Trim Reads"
FLAGSTAT = "Flag Stat"
COUNT_KMERS = "Count Kmers"
SAVE_OUTPUT = "Save Output"

# Codec / IO-path timers — the per-output-format timing the reference
# gets from InstrumentedOutputFormat (rdd/ADAMRDDFunctions.scala:161-164)
# and the per-stage RDD instrumentation (rdd/ADAMContext.scala:158).
# These fire inside the native tokenizer dispatch and the Parquet part
# writers, so `-print_metrics` decomposes the ingest/encode/write share
# of a command's wall time.
TOKENIZE_INPUT = "Tokenize Input (native)"
BGZF_CODEC = "BGZF Codec (native)"
PARQUET_ENCODE = "Parquet Encode"
PARQUET_WRITE = "Write ADAM Record (part file)"
SAM_ENCODE = "Write SAM/BAM Record (encode)"
FASTQ_ENCODE = "Write FASTQ Record (encode)"
OBSERVE_WALK = "BQSR Observe Walk (native)"
APPLY_WALK = "BQSR Apply Walk (native)"


@contextlib.contextmanager
def device_trace(log_dir: str):
    """jax profiler trace for a stage — the xprof face of the metrics
    system (the reference's Spark-listener task timings analog)."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


def block(x):
    """Synchronize device values so surrounding timers measure real work."""
    import jax

    return jax.block_until_ready(x)
