"""Named-timer registry and metrics report.

The tracing shape of the reference (``instrumentation/Timers.scala:25-81``
+ bdg-utils ``Metrics``): one named timer per pipeline stage / hot loop,
used as ``with TIMERS.time("Sort Reads"): ...`` wherever the reference
writes ``SortReads.time { ... }``; the CLI's ``-print_metrics`` prints
the aggregated table at command end (``ADAMCommand.scala:56-89``).

TPU additions: timers can wrap a ``jax.profiler`` trace
(:func:`device_trace`) so a stage's XLA execution shows up in xprof, and
:func:`block` synchronizes device work so wall times mean what they say.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field


@dataclass
class Timer:
    name: str
    total_ns: int = 0
    count: int = 0

    @property
    def total_s(self) -> float:
        return self.total_ns / 1e9


@dataclass
class TimerRegistry:
    timers: dict = field(default_factory=dict)
    recording: bool = False
    # Codec/write timers fire from the ingest thread and the writer pool
    # concurrently (pipelines/streamed.py); a lock keeps the
    # read-modify-write on Timer.total_ns from losing updates.
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def _timer_locked(self, name: str) -> Timer:
        # caller holds self._lock
        if name not in self.timers:
            self.timers[name] = Timer(name)
        return self.timers[name]

    def timer(self, name: str) -> Timer:
        with self._lock:
            return self._timer_locked(name)

    @contextlib.contextmanager
    def time(self, name: str):
        if not self.recording:
            yield
            return
        t0 = time.monotonic_ns()
        try:
            yield
        finally:
            dt = time.monotonic_ns() - t0
            with self._lock:
                t = self._timer_locked(name)
                t.total_ns += dt
                t.count += 1

    def add(self, name: str, ns: int) -> None:
        """Accumulate an externally-measured duration under ``name``
        (for stages whose wall is computed elsewhere, e.g. the streamed
        pipeline's stats dict)."""
        if not self.recording:
            return
        with self._lock:
            t = self._timer_locked(name)
            t.total_ns += ns
            t.count += 1

    def reset(self) -> None:
        """Clear timers; on the process-global ``TIMERS`` singleton also
        clear the structured metrics layer's counters/gauges
        (utils/telemetry.py) — one reset for the whole metrics surface,
        so a re-run never reports stale values from either.  Private
        registry instances reset only themselves: they must not wipe
        global telemetry another surface is still accumulating."""
        with self._lock:
            self.timers.clear()
        if self is globals().get("TIMERS"):
            from adam_tpu.utils import telemetry  # late: it imports us

            telemetry.TRACE.reset_metrics()

    def snapshot(self) -> dict:
        """Consistent copy ``{name: (count, total_ns)}`` taken under the
        lock — safe to call concurrently with ``time()``/``add()`` from
        writer threads (the unlocked ``report()`` iteration raced with
        timer inserts)."""
        with self._lock:
            return {t.name: (t.count, t.total_ns) for t in self.timers.values()}

    def report(self) -> str:
        """Aggregated table, longest stages first (the Metrics printout)."""
        rows = sorted(
            self.snapshot().items(), key=lambda kv: -kv[1][1]
        )
        if not rows:
            return "Timings\n=======\n(no timers recorded)\n"
        w = max(len(name) for name, _ in rows)
        out = ["Timings", "======="]
        out.append(f"{'timer'.ljust(w)}  {'count':>7}  {'total s':>10}")
        for name, (count, total_ns) in rows:
            out.append(
                f"{name.ljust(w)}  {count:>7}  {total_ns / 1e9:>10.3f}"
            )
        return "\n".join(out) + "\n"


#: Process-wide registry — the ``object Timers`` analog.
TIMERS = TimerRegistry()

# Named stages mirroring instrumentation/Timers.scala:25-81 (subset that
# maps onto this framework's stages; names kept recognizable).
LOAD_ALIGNMENTS = "Load Alignments"
SORT_READS = "Sort Reads"
MARK_DUPLICATES = "Mark Duplicates"
BQSR = "Base Quality Recalibration"
REALIGN_INDELS = "Realign Indels"
TRIM_READS = "Trim Reads"
FLAGSTAT = "Flag Stat"
COUNT_KMERS = "Count Kmers"
SAVE_OUTPUT = "Save Output"

# Codec / IO-path timers — the per-output-format timing the reference
# gets from InstrumentedOutputFormat (rdd/ADAMRDDFunctions.scala:161-164)
# and the per-stage RDD instrumentation (rdd/ADAMContext.scala:158).
# These fire inside the native tokenizer dispatch and the Parquet part
# writers, so `-print_metrics` decomposes the ingest/encode/write share
# of a command's wall time.
TOKENIZE_INPUT = "Tokenize Input (native)"
BGZF_CODEC = "BGZF Codec (native)"
PARQUET_ENCODE = "Parquet Encode"
PARQUET_WRITE = "Write ADAM Record (part file)"
SAM_ENCODE = "Write SAM/BAM Record (encode)"
FASTQ_ENCODE = "Write FASTQ Record (encode)"
OBSERVE_WALK = "BQSR Observe Walk (native)"
APPLY_WALK = "BQSR Apply Walk (native)"


# jax.profiler supports ONE active trace per process; a second
# concurrent start raises deep inside the profiler.  The flag makes
# device_trace reentrant-safe: nested/concurrent entries warn + no-op.
_DEVICE_TRACE_LOCK = threading.Lock()
_DEVICE_TRACE_ACTIVE = False


@contextlib.contextmanager
def device_trace(log_dir: str):
    """jax profiler trace for a stage — the xprof face of the metrics
    system (the reference's Spark-listener task timings analog; the CLI
    exposes it as ``--xprof-dir DIR`` around the transform pipeline).

    Reentrant-safe: when a trace is already active in this process the
    inner entry logs a warning and no-ops instead of crashing the
    profiler; degrades to a warning no-op when jax is unavailable.
    """
    global _DEVICE_TRACE_ACTIVE
    import logging

    log = logging.getLogger(__name__)
    with _DEVICE_TRACE_LOCK:
        if _DEVICE_TRACE_ACTIVE:
            already = True
        else:
            _DEVICE_TRACE_ACTIVE = True
            already = False
    if already:
        log.warning(
            "device_trace(%s): a profiler trace is already active in "
            "this process; nested trace request ignored", log_dir,
        )
        yield
        return
    try:
        try:
            import jax
        except Exception:
            log.warning(
                "device_trace(%s): jax unavailable; trace disabled", log_dir
            )
            yield
            return
        with jax.profiler.trace(log_dir):
            yield
    finally:
        with _DEVICE_TRACE_LOCK:
            _DEVICE_TRACE_ACTIVE = False


def block(x):
    """Synchronize device values so surrounding timers measure real work."""
    import jax

    return jax.block_until_ready(x)
