"""Retry-with-backoff and deadline wrappers for device RPC call sites.

The tunneled-TPU dispatch/fetch paths are remote procedure calls: they
drop, stall, and occasionally die.  Before this module, the streamed
pipeline had exactly one ``except`` on those paths (the prewarm's
warn-and-degrade) — any transient dispatch error or hung fetch killed a
whole multi-window run.  Two primitives fix that:

* :func:`retry_call` — run a callable, retrying **retryable** failures
  with exponential backoff.  Retryable means: injected
  :class:`~adam_tpu.utils.faults.TransientFault`, a
  :class:`DeadlineExceeded` fetch timeout, connection-layer ``OSError``
  subclasses, and jax's ``XlaRuntimeError`` (the shape every transient
  tunnel/RPC failure surfaces as).  Injected ``PermanentFault`` and
  everything else (a real bug would be "everything else") re-raise on
  first sight — retrying a deterministic error just triples its latency.
  Every retry counts ``retry.attempts`` on the global tracer.
* :func:`call_with_deadline` — run a callable on a watchdog thread and
  raise :class:`DeadlineExceeded` (retryable) if it exceeds a deadline,
  so a hung fetch RPC becomes a bounded, retryable timeout instead of a
  wedged run.  The abandoned thread is a daemon: it cannot block
  process exit, and its late result is discarded.

Policy knobs (all tolerantly parsed — an env typo degrades to the
default with a warning, the house rule for every ``ADAM_TPU_*`` var):

* ``ADAM_TPU_RETRY_ATTEMPTS`` — total tries per call (default 3).
* ``ADAM_TPU_RETRY_BACKOFF_S`` — first backoff sleep (default 0.05 s,
  doubling per retry).
* ``ADAM_TPU_RETRY_MAX_BACKOFF_S`` — backoff ceiling (default 2 s).
* ``ADAM_TPU_RETRY_JITTER`` — optional backoff jitter fraction
  (default 0 = off) with ``ADAM_TPU_RETRY_JITTER_SEED`` (default 0):
  each retry sleep stretches by up to this fraction, derived
  **deterministically** from (seed, site, attempt) via
  :func:`jitter_factor`.

The default backoff is jitter-free: the recovery paths must be
reproducible under the fault-injection matrix, and the call sites are
per-window (tens per run), not contended.  The jitter knob exists for
the multi-job service (``adam_tpu/serve``): N quarantine-retrying jobs
sharing one device pool would otherwise back off in lock-step and
re-collide on every retry wave.  Because the jitter is a pure function
of (seed, site, attempt) — no RNG state, no wall clock — a jittered
run is still bit-reproducible end to end: only sleep durations change,
never the retry decisions or the computed bytes.
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
from typing import Callable, Optional

from adam_tpu.utils.faults import PermanentFault, TransientFault

log = logging.getLogger(__name__)


class DeadlineExceeded(TimeoutError):
    """A watchdogged call outlived its deadline (retryable)."""


def env_float(name: str, default: float) -> float:
    """Tolerantly parsed float env var (warn + default on a typo — the
    house rule for every ``ADAM_TPU_*`` tuning var); shared with the
    transfer layer's fetch-deadline knob."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        log.warning("%s=%r is not a float; using default %s", name, raw,
                    default)
        return default


def env_toggle(name: str, default: bool) -> bool:
    """Tolerantly parsed boolean env toggle — THE shared parser for
    ``ADAM_TPU_*`` on/off knobs (packed columns, writer adaptivity, …):
    ``auto``/unset -> ``default``; ``1/on/true`` and ``0/off/false``
    force; anything else warns (naming the full accepted set) and keeps
    the default."""
    raw = os.environ.get(name, "").strip().lower()
    if raw in ("", "auto"):
        return default
    if raw in ("1", "on", "true"):
        return True
    if raw in ("0", "off", "false"):
        return False
    log.warning(
        "%s=%r is not one of (auto, 0/off/false, 1/on/true); using the "
        "default", name, raw,
    )
    return default


def _env_seed(name: str, default: int) -> int:
    """Any-int env var (seeds may legitimately be 0 or negative)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        log.warning("%s=%r is not an int; using default %s", name, raw,
                    default)
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        v = int(raw)
        return v if v >= 1 else default
    except ValueError:
        log.warning("%s=%r is not a positive int; using default %s", name,
                    raw, default)
        return default


def jitter_factor(site: str, attempt: int, *, seed: int = 0,
                  amount: float = 0.0) -> float:
    """Deterministic backoff stretch for one (site, attempt) pair.

    Returns a multiplier in ``[1, 1 + amount)`` derived from a sha256 of
    ``seed:site:attempt`` — a pure function, so a fixed seed reproduces
    the exact sleep schedule run after run (the recovery-path
    bit-reproducibility contract survives), while different sites (and
    different seeds, e.g. one per job in the multi-job service)
    decorrelate so concurrent retry waves don't re-collide in
    lock-step.  ``amount=0`` (the default) is exactly 1.0 — the
    jitter-free documented behavior."""
    if amount <= 0:
        return 1.0
    digest = hashlib.sha256(
        f"{seed}:{site}:{attempt}".encode()
    ).digest()
    unit = int.from_bytes(digest[:8], "big") / float(1 << 64)
    return 1.0 + amount * unit


# ---------------------------------------------------------------------------
# Drain-aware backoff: a process-wide cancellation event retry sleeps
# wait on.  Before this, an `adam-tpu serve` SIGTERM drain could stall
# up to ADAM_TPU_RETRY_MAX_BACKOFF_S per in-flight retry — each backoff
# was a blind time.sleep.  The multi-job scheduler registers its drain
# event here (serve/scheduler.py); when it fires, every sleeping retry
# wakes immediately and runs its REMAINING attempts with only a small
# bounded pause (_DRAIN_RETRY_PAUSE_S) between them.  Only the long
# exponential sleeps stall a drain — the attempts themselves are cheap,
# and keeping them preserves failure semantics: a one-off transient
# that arrives during a drain still absorbs (the window completes and
# the job stops cleanly at its boundary), instead of surfacing as a
# device failure that would spuriously evict a healthy chip on the
# process-wide health scoreboard (utils/health.py — mark_evicted is
# terminal).  docs/ROBUSTNESS.md "Fault-isolated multi-job scheduling".
# ---------------------------------------------------------------------------
_CANCEL_EVENT: Optional[threading.Event] = None
_CANCEL_LOCK = threading.Lock()
#: Pause between attempts once the cancel event fired: long enough for
#: a short transient to clear across the remaining attempts, bounded so
#: a drain never stalls more than attempts x this per in-flight retry.
_DRAIN_RETRY_PAUSE_S = 0.05


def set_cancel_event(event: Optional[threading.Event]) -> None:
    """Install (or, with None, remove) the process-wide retry-sleep
    cancellation event.  Idempotent; the scheduler owns its lifetime."""
    global _CANCEL_EVENT
    with _CANCEL_LOCK:
        _CANCEL_EVENT = event


def clear_cancel_event(event: Optional[threading.Event] = None) -> None:
    """Remove the installed cancellation event — but only when it is
    still ``event`` (or unconditionally with None): two schedulers in
    one process must not clear each other's registration."""
    global _CANCEL_EVENT
    with _CANCEL_LOCK:
        if event is None or _CANCEL_EVENT is event:
            _CANCEL_EVENT = None


def cancel_event() -> Optional[threading.Event]:
    with _CANCEL_LOCK:
        return _CANCEL_EVENT


class RetryPolicy:
    """Attempt/backoff tuning for one family of call sites."""

    __slots__ = ("attempts", "backoff_s", "max_backoff_s", "jitter",
                 "jitter_seed")

    def __init__(self, attempts: int = 3, backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0, jitter: float = 0.0,
                 jitter_seed: int = 0):
        self.attempts = max(1, attempts)
        self.backoff_s = max(0.0, backoff_s)
        self.max_backoff_s = max(0.0, max_backoff_s)
        self.jitter = max(0.0, jitter)
        self.jitter_seed = jitter_seed

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        return cls(
            attempts=_env_int("ADAM_TPU_RETRY_ATTEMPTS", 3),
            backoff_s=env_float("ADAM_TPU_RETRY_BACKOFF_S", 0.05),
            max_backoff_s=env_float("ADAM_TPU_RETRY_MAX_BACKOFF_S", 2.0),
            jitter=env_float("ADAM_TPU_RETRY_JITTER", 0.0),
            jitter_seed=_env_seed("ADAM_TPU_RETRY_JITTER_SEED", 0),
        )


#: XLA status prefixes that mark a *transient* runtime failure (dropped
#: tunnel, preempted RPC).  Deterministic statuses — RESOURCE_EXHAUSTED
#: (a window that OOMs on one chip OOMs on every chip), INVALID_ARGUMENT,
#: NOT_FOUND — must NOT retry: retrying them only multiplies the latency
#: of the eviction/host-fallback path that actually resolves them.
_TRANSIENT_XLA_STATUSES = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "CANCELLED", "ABORTED",
    "UNKNOWN", "INTERNAL",
)


def is_retryable(exc: BaseException) -> bool:
    """Default transient/permanent classification (module docstring)."""
    if isinstance(exc, PermanentFault):
        return False
    if isinstance(exc, (TransientFault, DeadlineExceeded, ConnectionError)):
        return True
    # jaxlib's XlaRuntimeError covers the tunnel/RPC failure surface
    # (matched by name so a CPU-only host never imports jaxlib for
    # this), but only its transient statuses — the status code leads
    # the message ("UNAVAILABLE: connection reset ...")
    if type(exc).__name__ == "XlaRuntimeError":
        msg = str(exc).lstrip()
        return msg.startswith(_TRANSIENT_XLA_STATUSES)
    return False


def retry_call(
    fn: Callable,
    *,
    site: str,
    policy: Optional[RetryPolicy] = None,
    retryable: Callable[[BaseException], bool] = is_retryable,
    cancel: Optional[threading.Event] = None,
):
    """Call ``fn()``; retry retryable failures with exponential backoff.

    Raises the last failure when the attempt budget is exhausted — the
    caller (the device-eviction path, usually) decides what a spent
    budget means.  ``site`` labels the log lines and groups nothing
    else; the ``retry.attempts`` counter is global.

    Backoff sleeps are **drain-aware**: they wait on ``cancel`` (or the
    process-wide event installed via :func:`set_cancel_event`) instead
    of sleeping blind, and a set event collapses this and every
    remaining backoff sleep to a small bounded pause — a graceful
    drain never waits out an exponential backoff.  The attempt budget
    is untouched, so a transient that would have been absorbed still
    absorbs and no spurious device failure surfaces mid-drain.
    """
    if policy is None:
        policy = RetryPolicy.from_env()
    backoff = policy.backoff_s
    attempt = 1
    while True:
        try:
            return fn()
        except BaseException as e:
            if attempt >= policy.attempts or not retryable(e):
                if attempt >= policy.attempts and retryable(e):
                    # the budget was genuinely spent on retryable
                    # failures (a permanent error on attempt 1 is NOT
                    # an incident — it never consumed the budget):
                    # snapshot the evidence before the eviction path
                    # the caller runs next churns the ring
                    from adam_tpu.utils import incidents

                    incidents.maybe_record(
                        "retry.exhausted",
                        reason="site=%s attempts=%d last=%s"
                               % (site, attempt, e),
                    )
                raise
            from adam_tpu.utils import telemetry as tele

            tele.TRACE.count(tele.C_RETRY_ATTEMPTS)
            # deterministic per-site jitter (off by default): stretches
            # the SLEEP only — attempt counts and outcomes are
            # untouched, so recovery stays bit-reproducible
            sleep_s = backoff * jitter_factor(
                site, attempt, seed=policy.jitter_seed,
                amount=policy.jitter,
            )
            log.warning(
                "%s failed (attempt %d/%d): %s — retrying in %.3fs",
                site, attempt, policy.attempts, e, sleep_s,
            )
            if sleep_s > 0:
                ev = cancel if cancel is not None else cancel_event()
                if ev is not None:
                    if ev.wait(sleep_s):
                        # a drain fired mid-wait (or was already set):
                        # keep a SMALL bounded pause between the
                        # remaining attempts — zero-delay retries would
                        # burn the whole budget in microseconds and turn
                        # a clears-in-100ms transient into a spurious
                        # device failure; attempts x 50ms can never
                        # stall the drain
                        time.sleep(min(sleep_s, _DRAIN_RETRY_PAUSE_S))
                else:
                    time.sleep(sleep_s)
            backoff = min(backoff * 2, policy.max_backoff_s)
            attempt += 1


def call_with_deadline(fn: Callable, timeout_s: float, *, site: str):
    """Run ``fn()`` on a watchdog daemon thread with a deadline.

    Returns ``fn``'s result, re-raises its exception, or raises
    :class:`DeadlineExceeded` after ``timeout_s`` — in which case the
    worker thread is abandoned (daemonized, so it can't pin process
    exit) and whatever it eventually produces is discarded.  A thread
    per call is deliberate: the deadline wraps per-window device
    fetches (tens per run), and a shared pool would let one hung RPC
    starve the watchdog for every later fetch.
    """
    if not timeout_s or timeout_s <= 0:
        return fn()
    box: list = []

    def run():
        try:
            box.append((True, fn()))
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            box.append((False, e))

    t = threading.Thread(target=run, daemon=True,
                         name=f"deadline:{site}")
    t.start()
    t.join(timeout_s)
    if not box:
        raise DeadlineExceeded(
            f"{site} exceeded its {timeout_s:.1f}s deadline (hung RPC?)"
        )
    ok, val = box[0]
    if ok:
        return val
    raise val
