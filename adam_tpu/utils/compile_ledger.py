"""Compile ledger: executable-cache hit/miss accounting at every jit
dispatch site.

The device pool's prewarm (``parallel/device_pool.py``) exists so cold
XLA compiles (20-40 s each through the tunneled compile service,
docs/PERF.md) never land inside a timed window — but until this module
existed nothing *measured* whether it succeeded.  The PERF.md "prewarm
coverage boundary" (residual-window grids, the realigned tail part,
wider merged tables) was known only by inference from suspiciously slow
windows.

This ledger makes it a first-class observable: every streamed jit
dispatch site (markdup columns, BQSR observe scatter-add, BQSR apply
table-gather, the realign sweep GEMMs) wraps its dispatch in
:func:`track`, keyed by the same ``(kernel, *grid dims)`` tuples the
prewarm entries use and the same per-device cache key
(``device_pool._device_key``) the prewarm cache uses — so the ledger's
notion of "warm" agrees with the prewarm's by construction.

* First dispatch of a (kernel, shape, device) triple in this process →
  **cache miss**: ``device.compile.cache_misses`` counts it, the
  ``device.compile.seconds`` histogram records the dispatch wall (trace
  + compile dominate a cold jit call; execution enqueues async), and an
  entry lands in the snapshot's ``compiles`` section.  A miss recorded
  *outside* a prewarm scope additionally counts
  ``device.compile.in_window`` and is flagged ``in_window=True`` — a
  cold compile that serialized inside a timed window, the exact event
  the analyzer's warning section surfaces.
* Every later dispatch of the triple → **cache hit**
  (``device.compile.cache_hits``), one set-membership check.

The seen-set is process-wide (like the prewarm cache): the bench's
warmup → timed-run pattern records the timed run's dispatches as hits,
which is precisely the claim the prewarm makes.  A dispatch that raises
(fault injection, dead chip) discards its claim so the retry re-measures.
"""

from __future__ import annotations

import threading
import time

from adam_tpu.utils import telemetry as tele

#: (kernel key, device key, kernel backend) triples whose executable
#: this process has already built — mirrors device_pool._PREWARMED,
#: which seeds it.
_SEEN: set = set()
_LOCK = threading.Lock()

_PREWARM_TLS = threading.local()


def reset() -> None:
    """Test hook: forget every compiled triple."""
    with _LOCK:
        _SEEN.clear()


class prewarm_scope:
    """Marks the current thread as compiling under a prewarm: misses
    recorded inside it are *expected* compiles, outside it they are
    in-window cold compiles (reentrant, like device_pool.replay_scope)."""

    def __enter__(self):
        _PREWARM_TLS.depth = getattr(_PREWARM_TLS, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _PREWARM_TLS.depth -= 1
        return False


def in_prewarm() -> bool:
    return getattr(_PREWARM_TLS, "depth", 0) > 0


def device_cache_key(device) -> str:
    """The per-device half of the ledger key — device_pool's
    ``_device_key`` for explicit devices, ``"default"`` for the
    single-chip default-device path (no pool → no prewarm → its first
    dispatch genuinely compiles in-window, and the ledger says so).
    Strings pass through: the mesh partitioner's collective executables
    are keyed per mesh width (``"mesh:<n>"``), not per member chip."""
    if device is None:
        return "default"
    if isinstance(device, str):
        return device
    from adam_tpu.parallel.device_pool import _device_key

    return _device_key(device)


def active_backend() -> str:
    """The kernel backend half of the ledger key.  The Pallas/XLA
    selector (``ops/kernel_backend``) swaps kernel *bodies* at trace
    time, so an XLA-warmed ``(kernel, *dims, device)`` says nothing
    about the pallas executable of the same shape — without the
    backend in the key, a backend flip's first dispatch would read as
    a cache hit while a cold compile serialized in-window.  Prewarm
    dedupe caches (device_pool._PREWARMED, the mesh prewarm) key the
    same way."""
    from adam_tpu.ops.kernel_backend import kernel_backend

    return kernel_backend()


def claim(key: tuple, device=None) -> None:
    """Assert a (kernel, shape, backend, device) triple warm without
    recording anything — the prewarm's dedupe-skip path calls this so
    the ledger seen-set re-agrees with the prewarm cache.  The two can
    diverge after a faulted run: a dispatch that RAISES gives its track
    claim back (so the retry re-measures) while the jit executable it
    built stays cached and the prewarm cache keeps the triple — without
    this re-seed, the next clean run's first dispatch of the triple
    would read as a false in-window cold compile."""
    with _LOCK:
        _SEEN.add((key, device_cache_key(device), active_backend()))


class track:
    """Context manager for one jit dispatch: times the call and records
    hit/miss against the process-wide seen-set.

    ``key`` is the prewarm-entry key tuple ``(kernel_name, *dims)``;
    ``device`` the jax device (or None for the default device).  The
    claim is taken on entry (so concurrent dispatches of one triple
    record one miss, not n) and discarded if the dispatch raises —
    a transiently-failed compile must stay a miss for the retry.
    """

    __slots__ = ("_key", "_dims", "_dev", "_cache_key", "_t0", "_miss")

    def __init__(self, key: tuple, device=None):
        self._key = key
        self._dims = tuple(key[1:])
        self._dev = device
        self._cache_key = None
        self._miss = False

    def __enter__(self):
        # membership maintenance is unconditional (a warmup run without
        # --metrics-json still warms the jit cache, and the timed run's
        # ledger must know that); only counters/entries gate on recording
        self._cache_key = (
            self._key, device_cache_key(self._dev), active_backend()
        )
        with _LOCK:
            self._miss = self._cache_key not in _SEEN
            _SEEN.add(self._cache_key)
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            # failed dispatch: nothing compiled — give the claim back
            with _LOCK:
                _SEEN.discard(self._cache_key)
            return False
        dur = time.monotonic() - self._t0
        if not self._miss:
            tele.TRACE.count(tele.C_COMPILE_HITS)
            return False
        tele.TRACE.record_compile(
            str(self._key[0]), self._dims, device_cache_key(self._dev),
            dur, in_window=not in_prewarm(),
        )
        return False
