"""``adam-tpu top`` — live terminal dashboard over a heartbeat stream.

The streamed pipeline's ``--progress PATH`` heartbeat
(utils/telemetry.Heartbeat) emits one NDJSON line per sample; this
module tails that file and renders a refreshing one-screen dashboard —
the per-job progress view the always-on-service direction needs
(ROADMAP: "the heartbeat becomes the per-job progress API").  It is a
pure *consumer*: it holds the file read-only, attaches to a run that is
already mid-flight, survives the heartbeat's size-capped rotation
(``ADAM_TPU_PROGRESS_MAX_BYTES`` — a truncate-to-zero reads as a fresh
file), tolerates a torn last line (only newline-terminated lines are
parsed; the line-buffered writer makes tears transient), accepts both
``adam_tpu.heartbeat/1``, ``/2`` and ``/3`` lines, and exits 0 when the stream
carries ``done=true`` (non-zero when that final line says ``ok=false``).

Split renderer/follower so the dashboard is unit-testable without a
terminal: :func:`render_frame` is a pure ``dict -> str`` and
:func:`follow` owns the tail-loop/TTY behavior.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

from adam_tpu.utils.telemetry import format_bytes as _fmt_bytes

#: Heartbeat schema tags this dashboard understands (missing /2 / /3
#: fields render as "-"; unknown future fields are ignored).
ACCEPTED_SCHEMAS = (
    "adam_tpu.heartbeat/1", "adam_tpu.heartbeat/2", "adam_tpu.heartbeat/3",
)

_CLEAR = "\x1b[H\x1b[2J"


def parse_heartbeat_text(text: str) -> list:
    """NDJSON text -> parsed heartbeat lines, in order.

    Only newline-terminated lines parse (the last line of a live file
    may still be mid-write — the next poll completes it); non-JSON or
    non-heartbeat lines are skipped rather than fatal, so a corrupt
    line in a multi-hour stream costs one sample, not the dashboard."""
    out = []
    for raw in text.splitlines(keepends=True):
        if not raw.endswith("\n"):
            break  # torn tail: re-read on the next poll
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
        except ValueError:
            continue
        if (
            isinstance(line, dict)
            and line.get("schema") in ACCEPTED_SCHEMAS
        ):
            out.append(line)
    return out


def _bar(frac, width: int = 24) -> str:
    if frac is None:
        return "[" + "?" * width + "]"
    frac = min(max(float(frac), 0.0), 1.0)
    n = int(round(frac * width))
    return "[" + "#" * n + "-" * (width - n) + "]"


def _fmt_s(v) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    if v >= 3600:
        return f"{int(v) // 3600}h{(int(v) % 3600) // 60:02d}m"
    if v >= 60:
        return f"{int(v) // 60}m{int(v) % 60:02d}s"
    return f"{v:.1f}s"


def render_frame(line: dict, source: str = "") -> str:
    """One dashboard frame from one heartbeat line (pure function)."""
    done = bool(line.get("done"))
    ok = line.get("ok", True)
    if not done:
        state = "RUNNING"
    else:
        state = "DONE" if ok else "FAILED"
    wt = line.get("windows_total")
    wi = line.get("windows_ingested", 0)
    frac = (wi / wt) if wt else None
    mode = line.get("partitioner")
    out = [
        f"adam-tpu top — {source or 'heartbeat'}   "
        f"{line.get('schema', '?')}  seq {line.get('seq', '-')}",
        f"state    {state:<8} elapsed {_fmt_s(line.get('elapsed_s')):<9}"
        f" eta {_fmt_s(line.get('eta_s'))}"
        + (f"   mode {mode}" if mode else ""),
        f"windows  {_bar(frac)} {wi}/{wt if wt is not None else '?'}"
        f"   resumed {line.get('windows_resumed', 0)}"
        f"   parts {line.get('parts_written', 0)}",
        f"reads    {line.get('reads_ingested', 0):,}"
        f"  ({line.get('reads_per_s', 0):,.0f} reads/s)",
        f"bytes    written {_fmt_bytes(line.get('bytes_written'))}"
        f"   h2d {_fmt_bytes(line.get('h2d_bytes'))}"
        f"   d2h {_fmt_bytes(line.get('d2h_bytes'))}",
    ]
    per_dev = line.get("inflight_per_device") or {}
    inflight = line.get("inflight", 0)
    if per_dev:
        # depth bars against the double-buffer depth of 2 per device
        devs = "  ".join(
            f"{dev}:{_bar(min(n, 2) / 2.0, 6)}{n}"
            for dev, n in sorted(per_dev.items())
        )
        out.append(f"inflight {inflight} total   {devs}")
    else:
        out.append(f"inflight {inflight} total")
    hbm = line.get("hbm_bytes_in_use")
    if hbm:
        peak = line.get("hbm_peak_bytes")
        devs = "  ".join(
            f"{dev}:{_fmt_bytes(b)}" for dev, b in sorted(hbm.items())
        )
        out.append(f"hbm      {devs}   peak {_fmt_bytes(peak)}")
    elif "hbm_bytes_in_use" in line:
        out.append("hbm      (unsupported backend — no memory stats)")
    out.append(
        f"events   retries {line.get('retries', 0)}"
        f"   faults {line.get('faults', 0)}"
        f"   evicted {line.get('devices_evicted', 0)}"
    )
    if done:
        out.append(
            "run complete — output is final" if ok else
            "RUN FAILED — the final heartbeat carries ok=false"
        )
    return "\n".join(out)


def follow(path: str, interval: float = 0.5, out=None,
           once: bool = False, clear: Optional[bool] = None,
           max_wait_s: Optional[float] = None) -> int:
    """Tail a heartbeat file and render frames until ``done=true``.

    * attaches mid-run: the first frame renders the newest line already
      in the file;
    * survives rotation: a file that shrinks (the heartbeat moved it to
      ``<path>.1`` and started fresh) re-reads from the top;
    * ``once`` renders a single frame from the newest line and exits
      (scripting/CI mode — no TTY needed);
    * ``max_wait_s`` bounds the wait for the file/new lines (None =
      wait forever, the interactive default).

    Exit codes: 0 on ``done=true, ok=true`` (or ``once``), 1 on a final
    line with ``ok=false``, 2 when the file never appeared / carried no
    heartbeat lines within the wait bound.
    """
    out = out if out is not None else sys.stdout
    if clear is None:
        clear = hasattr(out, "isatty") and out.isatty() and not once
    t0 = time.monotonic()
    last: Optional[dict] = None
    pos = 0
    buf = ""

    def expired() -> bool:
        return (
            max_wait_s is not None
            and time.monotonic() - t0 > max_wait_s
        )

    while True:
        try:
            size = os.path.getsize(path)
        except OSError:
            size = None
        if size is None:
            if once or expired():
                print(f"top: no heartbeat file at {path}",
                      file=sys.stderr)
                return 2
            time.sleep(interval)
            continue
        if size < pos:
            pos = 0  # rotated/truncated: the writer started fresh
            buf = ""
        if size > pos:
            with open(path, "rb") as fh:
                fh.seek(pos)
                chunk = fh.read()
                pos = fh.tell()
            buf += chunk.decode("utf-8", errors="replace")
            lines = parse_heartbeat_text(buf)
            # keep only the unterminated tail for the next poll
            nl = buf.rfind("\n")
            buf = buf[nl + 1:] if nl >= 0 else buf
            if lines:
                last = lines[-1]
                frame = render_frame(last, source=path)
                if clear:
                    out.write(_CLEAR)
                out.write(frame + "\n")
                if not clear:
                    out.write("\n")
                out.flush()
        if last is not None:
            if last.get("done"):
                return 0 if last.get("ok", True) else 1
            if once:
                return 0
        elif once:
            print(f"top: no heartbeat lines in {path}", file=sys.stderr)
            return 2
        if expired():
            print(
                f"top: no done=true within {max_wait_s:.0f}s "
                f"(run still live, or stream stalled)", file=sys.stderr,
            )
            return 2
        time.sleep(interval)
