"""``adam-tpu top`` — live terminal dashboard over heartbeat streams.

The streamed pipeline's ``--progress PATH`` heartbeat
(utils/telemetry.Heartbeat) emits one NDJSON line per sample; this
module tails that file and renders a refreshing one-screen dashboard —
the per-job progress view the always-on-service direction needs
(ROADMAP: "the heartbeat becomes the per-job progress API").  It is a
pure *consumer*: it holds the file read-only, attaches to a run that is
already mid-flight, survives the heartbeat's size-capped rotation
(``ADAM_TPU_PROGRESS_MAX_BYTES`` — a truncate-to-zero reads as a fresh
file), tolerates a torn last line (only newline-terminated lines are
parsed; the line-buffered writer makes tears transient), accepts every
``adam_tpu.heartbeat/1``–``/5`` line, and exits 0 when the stream
carries ``done=true`` (non-zero when that final line says ``ok=false``).

**Multi-job mode**: pointed at a *directory* (a ``adam-tpu serve``
run-root), top discovers every ``<job>/heartbeat.ndjson`` under it and
renders one aggregated dashboard — a per-job state/progress/ETA row
plus pool-wide totals.  Jobs appearing mid-watch join the board on the
next poll; finished jobs stay on it with their final state.  Job-scoped
fields (windows, parts, reads, bytes written, per-job eviction counts)
SUM across jobs; nothing process-global rides in a paced job's stream
(see ``pipelines/streamed._start_heartbeat``), so the totals never
double-count.

Split renderer/follower so the dashboard is unit-testable without a
terminal: :func:`render_frame` / :func:`render_multi_frame` are pure
``dict -> str`` and :func:`follow` / :func:`follow_root` own the
tail-loop/TTY behavior.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

from adam_tpu.utils.telemetry import format_bytes as _fmt_bytes

#: Heartbeat schema tags this dashboard understands (missing /2–/5
#: fields render as "-"; unknown future fields are ignored).
ACCEPTED_SCHEMAS = (
    "adam_tpu.heartbeat/1", "adam_tpu.heartbeat/2", "adam_tpu.heartbeat/3",
    "adam_tpu.heartbeat/4", "adam_tpu.heartbeat/5", "adam_tpu.heartbeat/6",
    "adam_tpu.heartbeat/7",
)

_CLEAR = "\x1b[H\x1b[2J"


def parse_heartbeat_text(text: str) -> list:
    """NDJSON text -> parsed heartbeat lines, in order.

    Only newline-terminated lines parse (the last line of a live file
    may still be mid-write — the next poll completes it); non-JSON or
    non-heartbeat lines are skipped rather than fatal, so a corrupt
    line in a multi-hour stream costs one sample, not the dashboard."""
    out = []
    for raw in text.splitlines(keepends=True):
        if not raw.endswith("\n"):
            break  # torn tail: re-read on the next poll
        raw = raw.strip()
        if not raw:
            continue
        try:
            line = json.loads(raw)
        except ValueError:
            continue
        if (
            isinstance(line, dict)
            and line.get("schema") in ACCEPTED_SCHEMAS
        ):
            out.append(line)
    return out


def _bar(frac, width: int = 24) -> str:
    if frac is None:
        return "[" + "?" * width + "]"
    frac = min(max(float(frac), 0.0), 1.0)
    n = int(round(frac * width))
    return "[" + "#" * n + "-" * (width - n) + "]"


def _fmt_s(v) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    if v >= 3600:
        return f"{int(v) // 3600}h{(int(v) % 3600) // 60:02d}m"
    if v >= 60:
        return f"{int(v) // 60}m{int(v) % 60:02d}s"
    return f"{v:.1f}s"


def render_frame(line: dict, source: str = "") -> str:
    """One dashboard frame from one heartbeat line (pure function)."""
    done = bool(line.get("done"))
    ok = line.get("ok", True)
    if not done:
        state = "RUNNING"
    else:
        state = "DONE" if ok else "FAILED"
    wt = line.get("windows_total")
    wi = line.get("windows_ingested", 0)
    frac = (wi / wt) if wt else None
    mode = line.get("partitioner")
    out = [
        f"adam-tpu top — {source or 'heartbeat'}   "
        f"{line.get('schema', '?')}  seq {line.get('seq', '-')}",
        f"state    {state:<8} elapsed {_fmt_s(line.get('elapsed_s')):<9}"
        f" eta {_fmt_s(line.get('eta_s'))}"
        + (f"   mode {mode}" if mode else ""),
        f"windows  {_bar(frac)} {wi}/{wt if wt is not None else '?'}"
        f"   resumed {line.get('windows_resumed', 0)}"
        f"   parts {line.get('parts_written', 0)}",
        f"reads    {line.get('reads_ingested', 0):,}"
        f"  ({line.get('reads_per_s', 0):,.0f} reads/s)",
        f"bytes    written {_fmt_bytes(line.get('bytes_written'))}"
        f"   h2d {_fmt_bytes(line.get('h2d_bytes'))}"
        f"   d2h {_fmt_bytes(line.get('d2h_bytes'))}",
    ]
    per_dev = line.get("inflight_per_device") or {}
    inflight = line.get("inflight", 0)
    if per_dev:
        # depth bars against the double-buffer depth of 2 per device
        devs = "  ".join(
            f"{dev}:{_bar(min(n, 2) / 2.0, 6)}{n}"
            for dev, n in sorted(per_dev.items())
        )
        out.append(f"inflight {inflight} total   {devs}")
    else:
        out.append(f"inflight {inflight} total")
    hbm = line.get("hbm_bytes_in_use")
    if hbm:
        peak = line.get("hbm_peak_bytes")
        devs = "  ".join(
            f"{dev}:{_fmt_bytes(b)}" for dev, b in sorted(hbm.items())
        )
        out.append(f"hbm      {devs}   peak {_fmt_bytes(peak)}")
    elif "hbm_bytes_in_use" in line:
        out.append("hbm      (unsupported backend — no memory stats)")
    fill = line.get("batch_fill")
    if fill is not None:
        # cross-job batching (/4): running grid fill + the last fused
        # dispatch's distinct-job count
        out.append(
            f"batching {_bar(fill, 12)} fill {fill:.0%}"
            f"   jobs/dispatch {line.get('batched_jobs', '-')}"
        )
    dh = line.get("device_health")
    if dh:
        # device-health scoreboard (/5): only non-healthy chips are
        # worth a cell each; an all-healthy fleet renders one word
        bad = {d: s for d, s in sorted(dh.items()) if s != "healthy"}
        if bad:
            out.append(
                "health   "
                + "  ".join(f"{d}:{s}" for d, s in bad.items())
            )
        else:
            out.append(f"health   all {len(dh)} device(s) healthy")
    if "active_traces" in line or line.get("last_incident"):
        # observability cell (/6): live trace count, /metrics scrape
        # activity, and the newest incident bundle with its age
        li = line.get("last_incident")
        out.append(
            f"observe  traces {line.get('active_traces', 0)}"
            f"   scrapes {line.get('metrics_scrapes', 0)}"
            + (
                f"   incident {li}"
                f" ({_fmt_s(line.get('last_incident_age_s'))} ago)"
                if li else "   incidents none"
            )
        )
    burn = line.get("slo_worst_burn")
    if burn is not None or line.get("perf_regressions"):
        # judgment cell (/7): worst error-budget burn across armed SLO
        # objectives + perf keys the ledger sentinel flagged
        out.append(
            "slo      "
            + (f"burn {burn:.1f}x" if burn is not None else "no slo")
            + f"   perf regressions {line.get('perf_regressions', 0)}"
        )
    out.append(
        f"events   retries {line.get('retries', 0)}"
        f"   faults {line.get('faults', 0)}"
        f"   evicted {line.get('devices_evicted', 0)}"
    )
    if done:
        out.append(
            "run complete — output is final" if ok else
            "RUN FAILED — the final heartbeat carries ok=false"
        )
    return "\n".join(out)


class _StreamTail:
    """Incremental reader for one heartbeat NDJSON file: remembers the
    byte position and the torn tail, survives rotation (shrink = reread
    from the top) and disappearance (a job dir mid-creation)."""

    def __init__(self, path: str):
        self.path = path
        self._pos = 0
        self._buf = ""
        self.last: Optional[dict] = None

    def poll(self) -> bool:
        """Read any new bytes; True when a newer complete line landed."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return False
        if size < self._pos:
            self._pos = 0  # rotated/truncated
            self._buf = ""
        if size <= self._pos:
            return False
        with open(self.path, "rb") as fh:
            fh.seek(self._pos)
            chunk = fh.read()
            self._pos = fh.tell()
        self._buf += chunk.decode("utf-8", errors="replace")
        lines = parse_heartbeat_text(self._buf)
        nl = self._buf.rfind("\n")
        self._buf = self._buf[nl + 1:] if nl >= 0 else self._buf
        if lines:
            self.last = lines[-1]
            return True
        return False


def discover_streams(root: str) -> dict:
    """Job heartbeat streams under a serve run-root:
    ``{job name: <root>/<job>/heartbeat.ndjson}`` for every job
    subdirectory that has one (the scheduler's layout).  The service's
    own pool-wide stream (``<root>/heartbeat.ndjson``) is deliberately
    not a job."""
    out = {}
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    for name in names:
        p = os.path.join(root, name, "heartbeat.ndjson")
        if os.path.isfile(p):
            out[name] = p
    return out


def _job_disk_state(root: str, job: str) -> Optional[str]:
    """The scheduler's durable per-job state (JOB.json), when present —
    it distinguishes ``interrupted``/``quarantined`` from plain failure,
    which the heartbeat alone cannot."""
    try:
        with open(os.path.join(root, job, "JOB.json")) as fh:
            doc = json.load(fh)
        if isinstance(doc, dict):
            state = doc.get("state")
            return str(state) if state else None
    except (OSError, ValueError):
        pass
    return None


def render_multi_frame(jobs: dict, root: str = "",
                       pool: Optional[dict] = None,
                       states: Optional[dict] = None) -> str:
    """One aggregated dashboard frame (pure function).

    ``jobs`` maps job name -> its newest heartbeat line; ``pool`` is
    the service stream's newest line (process-global counters: tunnel
    bytes, retries, faults), ``states`` maps job name -> the JOB.json
    state string when known.  Job-scoped numbers SUM across jobs;
    nothing global rides in a paced job's stream, so the totals cannot
    double-count."""
    states = states or {}
    rows = [
        f"adam-tpu top — multi-job {root or 'run-root'}   "
        f"{len(jobs)} job(s)",
        f"{'JOB':<16} {'STATE':<12} {'WINDOWS':<34} {'PARTS':>5} "
        f"{'READS/S':>9} {'ETA':>7}",
    ]
    tot = {"reads": 0, "bytes": 0, "parts": 0, "inflight": 0,
           "rps": 0.0, "evicted": 0, "running": 0, "done": 0,
           "failed": 0}
    hbm_per_dev: dict = {}
    for name in sorted(jobs):
        line = jobs[name]
        done = bool(line.get("done"))
        ok = line.get("ok", True)
        state = states.get(name)
        if state is None:
            state = ("RUNNING" if not done
                     else ("DONE" if ok else "FAILED"))
        else:
            state = state.upper()
        wt = line.get("windows_total")
        wi = line.get("windows_ingested", 0)
        frac = (wi / wt) if wt else None
        rows.append(
            f"{name[:16]:<16} {state[:12]:<12} "
            f"{_bar(frac)} {wi}/{wt if wt is not None else '?':<4} "
            f"{line.get('parts_written', 0):>5} "
            f"{line.get('reads_per_s', 0) or 0:>9,.0f} "
            f"{_fmt_s(line.get('eta_s')):>7}"
        )
        tot["reads"] += line.get("reads_ingested", 0) or 0
        tot["bytes"] += line.get("bytes_written", 0) or 0
        tot["parts"] += line.get("parts_written", 0) or 0
        tot["inflight"] += line.get("inflight", 0) or 0
        tot["evicted"] += line.get("devices_evicted", 0) or 0
        if not done:
            tot["running"] += 1
            tot["rps"] += line.get("reads_per_s", 0) or 0
        elif ok:
            tot["done"] += 1
        else:
            tot["failed"] += 1
        for dev, b in (line.get("hbm_bytes_in_use") or {}).items():
            if isinstance(b, (int, float)):
                hbm_per_dev[dev] = max(hbm_per_dev.get(dev, 0), b)
    rows.append(
        f"jobs     {tot['running']} running  {tot['done']} done  "
        f"{tot['failed']} stopped/failed   parts {tot['parts']}   "
        f"reads {tot['reads']:,} ({tot['rps']:,.0f}/s)"
    )
    rows.append(
        f"pool     written {_fmt_bytes(tot['bytes'])}   "
        f"inflight {tot['inflight']}   evicted {tot['evicted']}"
    )
    if hbm_per_dev:
        devs = "  ".join(
            f"{d}:{_fmt_bytes(b)}" for d, b in sorted(hbm_per_dev.items())
        )
        rows.append(f"hbm      {devs}")
    if pool:
        fill = pool.get("batch_fill")
        rows.append(
            f"global   h2d {_fmt_bytes(pool.get('h2d_bytes'))}   "
            f"d2h {_fmt_bytes(pool.get('d2h_bytes'))}   "
            f"retries {pool.get('retries', 0)}   "
            f"faults {pool.get('faults', 0)}"
            + (
                # cross-job batching fill rate (the service stream is
                # the one that carries it — the coalescer is shared)
                f"   fill {fill:.0%}"
                f" ({pool.get('batched_jobs', '-')} jobs/dispatch)"
                if fill is not None else ""
            )
        )
        if "active_traces" in pool or pool.get("last_incident"):
            li = pool.get("last_incident")
            rows.append(
                f"observe  traces {pool.get('active_traces', 0)}   "
                f"scrapes {pool.get('metrics_scrapes', 0)}"
                + (
                    f"   incident {li}"
                    f" ({_fmt_s(pool.get('last_incident_age_s'))} ago)"
                    if li else "   incidents none"
                )
            )
        burn = pool.get("slo_worst_burn")
        if burn is not None or pool.get("perf_regressions"):
            rows.append(
                "slo      "
                + (f"burn {burn:.1f}x" if burn is not None
                   else "no slo")
                + f"   perf regressions {pool.get('perf_regressions', 0)}"
            )
    if jobs and all(j.get("done") for j in jobs.values()):
        rows.append(
            "all jobs finished" if not tot["failed"] else
            f"all jobs finished — {tot['failed']} stopped or failed"
        )
    return "\n".join(rows)


def follow_root(root: str, interval: float = 0.5, out=None,
                once: bool = False, clear: Optional[bool] = None,
                max_wait_s: Optional[float] = None) -> int:
    """Aggregate every job heartbeat under a serve run-root into one
    refreshing dashboard (module doc).  Jobs appearing mid-watch join
    on the next poll; the watch ends when every discovered job stream
    carries ``done=true``.

    Exit codes mirror :func:`follow`: 0 when all jobs finished ok (or
    ``once`` with at least one line), 1 when all finished but some
    FAILED, 2 when no heartbeat lines appear within the wait bound.
    Two service-layer refinements: a job whose durable ``JOB.json``
    says ``interrupted`` is a clean graceful-drain stop, not a failure
    (its final heartbeat line carries ``ok=false``, which alone cannot
    tell a drain from a crash), and while the service's own pool
    stream is still live the watch continues — the scheduler may yet
    admit manifest jobs whose heartbeat files don't exist, so
    "every discovered stream is done" is not "the service is done"."""
    out = out if out is not None else sys.stdout
    if clear is None:
        clear = hasattr(out, "isatty") and out.isatty() and not once
    t0 = time.monotonic()
    tails: dict = {}
    service: Optional[_StreamTail] = None

    def expired() -> bool:
        return (
            max_wait_s is not None
            and time.monotonic() - t0 > max_wait_s
        )

    while True:
        for name, path in discover_streams(root).items():
            if name not in tails:
                tails[name] = _StreamTail(path)
        if service is None:
            sp = os.path.join(root, "heartbeat.ndjson")
            if os.path.isfile(sp):
                service = _StreamTail(sp)
        changed = False
        for tail in tails.values():
            changed = tail.poll() or changed
        if service is not None:
            changed = service.poll() or changed
        jobs = {n: t.last for n, t in tails.items() if t.last is not None}
        if jobs and (changed or once):
            frame = render_multi_frame(
                jobs, root=root,
                pool=service.last if service is not None else None,
                states={n: _job_disk_state(root, n) for n in jobs},
            )
            if clear:
                out.write(_CLEAR)
            out.write(frame + "\n")
            if not clear:
                out.write("\n")
            out.flush()
        if jobs:
            all_done = all(j.get("done") for j in jobs.values())
            # the service stream still live = more jobs may be coming
            # (capacity-queued manifest entries have no stream yet)
            service_live = (
                service is not None and service.last is not None
                and not service.last.get("done")
            )
            if all_done and not service_live:
                states = {n: _job_disk_state(root, n) for n in jobs}
                failed = [
                    n for n, j in jobs.items()
                    if not j.get("ok", True)
                    and states.get(n) != "interrupted"
                ]
                return 1 if failed else 0
            if once:
                return 0
        elif once:
            print(f"top: no job heartbeat lines under {root}",
                  file=sys.stderr)
            return 2
        if expired():
            print(
                f"top: jobs still live after {max_wait_s:.0f}s "
                f"(or no streams under {root})", file=sys.stderr,
            )
            return 2
        time.sleep(interval)


def follow_url(url: str, interval: float = 0.5, out=None,
               once: bool = False, clear: Optional[bool] = None,
               max_wait_s: Optional[float] = None) -> int:
    """Tail a REMOTE serve run-root through its HTTP gateway
    (``adam-tpu top --url http://host:port``): the same aggregated
    multi-job dashboard as :func:`follow_root`, fed by the gateway's
    resumable NDJSON event streams instead of local files.  Each job's
    stream is polled incrementally from a line cursor
    (``GET /v1/jobs/<job>/events?cursor=N&follow=0``), so a network
    blip or a bounced gateway costs a re-poll, not a restart; jobs
    joining mid-watch appear on the next status poll; heartbeat-file
    rotation server-side resets the cursor (re-delivery, never loss)
    exactly like a local shrink does in :func:`follow`.

    Exit codes keep the 0/1/2 contract: 0 when every job finished ok
    (a JOB.json ``interrupted`` is a clean drain stop, not a failure),
    1 when any finished failed/quarantined, 2 when no heartbeat lines
    arrive within the wait bound (or the gateway is unreachable and
    nothing terminal was seen)."""
    from adam_tpu.gateway.client import (
        TERMINAL_STATES,
        GatewayClient,
        GatewayError,
    )

    out = out if out is not None else sys.stdout
    if clear is None:
        clear = hasattr(out, "isatty") and out.isatty() and not once
    t0 = time.monotonic()
    try:
        client = GatewayClient(url)
    except ValueError as e:
        print(f"top: {e}", file=sys.stderr)
        return 2

    def expired() -> bool:
        return (
            max_wait_s is not None
            and time.monotonic() - t0 > max_wait_s
        )

    cursors: dict = {}
    last: dict = {}
    states: dict = {}

    def verdict() -> int:
        # judged over STATES, not just heartbeat lines: a job that
        # quarantined before its first heartbeat (bad input path) has
        # no line at all, and must still fail the watch
        failed = {
            n for n, s in states.items() if s == "quarantined"
        }
        failed.update(
            n for n, line in last.items()
            if (line.get("ok", True) is False
                and states.get(n) != "interrupted")
        )
        return 1 if failed else 0

    while True:
        try:
            status = client.status()
        except (GatewayError, OSError):
            # gateway gone: clean end iff everything we saw finished
            if last and all(l.get("done") for l in last.values()):
                return verdict()
            if once or expired():
                print(f"top: gateway at {url} unreachable",
                      file=sys.stderr)
                return 2
            time.sleep(interval)
            continue
        jobs_view = status.get("jobs", {})
        changed = False
        for name, view in jobs_view.items():
            states[name] = view.get("state")
            try:
                cur, lines = client.poll_events(
                    name, cursors.get(name, 0)
                )
            except (GatewayError, OSError):
                continue
            if lines:
                cursors[name] = cur
                last[name] = lines[-1]
                changed = True
        if last and (changed or once):
            frame = render_multi_frame(
                last, root=url,
                states={n: states.get(n) for n in last},
            )
            if clear:
                out.write(_CLEAR)
            out.write(frame + "\n")
            if not clear:
                out.write("\n")
            out.flush()
        all_term = bool(jobs_view) and all(
            v.get("state") in TERMINAL_STATES
            for v in jobs_view.values()
        )
        if last:
            if all_term and all(l.get("done") for l in last.values()):
                return verdict()
            if once:
                return 0
        elif all_term:
            # every job terminal yet none ever emitted a heartbeat
            # line (e.g. all quarantined before their first window):
            # the watch is over — judge on states alone
            return verdict()
        elif once:
            print(f"top: no job heartbeat lines from {url}",
                  file=sys.stderr)
            return 2
        if expired():
            print(
                f"top: jobs still live after {max_wait_s:.0f}s "
                f"(or no streams at {url})", file=sys.stderr,
            )
            return 2
        time.sleep(interval)


def follow(path: str, interval: float = 0.5, out=None,
           once: bool = False, clear: Optional[bool] = None,
           max_wait_s: Optional[float] = None) -> int:
    """Tail a heartbeat file and render frames until ``done=true``.

    * attaches mid-run: the first frame renders the newest line already
      in the file;
    * survives rotation: a file that shrinks (the heartbeat moved it to
      ``<path>.1`` and started fresh) re-reads from the top;
    * ``once`` renders a single frame from the newest line and exits
      (scripting/CI mode — no TTY needed);
    * ``max_wait_s`` bounds the wait for the file/new lines (None =
      wait forever, the interactive default).

    Exit codes: 0 on ``done=true, ok=true`` (or ``once``), 1 on a final
    line with ``ok=false``, 2 when the file never appeared / carried no
    heartbeat lines within the wait bound.
    """
    out = out if out is not None else sys.stdout
    if clear is None:
        clear = hasattr(out, "isatty") and out.isatty() and not once
    t0 = time.monotonic()
    last: Optional[dict] = None
    pos = 0
    buf = ""

    def expired() -> bool:
        return (
            max_wait_s is not None
            and time.monotonic() - t0 > max_wait_s
        )

    while True:
        try:
            size = os.path.getsize(path)
        except OSError:
            size = None
        if size is None:
            if once or expired():
                print(f"top: no heartbeat file at {path}",
                      file=sys.stderr)
                return 2
            time.sleep(interval)
            continue
        if size < pos:
            pos = 0  # rotated/truncated: the writer started fresh
            buf = ""
        if size > pos:
            with open(path, "rb") as fh:
                fh.seek(pos)
                chunk = fh.read()
                pos = fh.tell()
            buf += chunk.decode("utf-8", errors="replace")
            lines = parse_heartbeat_text(buf)
            # keep only the unterminated tail for the next poll
            nl = buf.rfind("\n")
            buf = buf[nl + 1:] if nl >= 0 else buf
            if lines:
                last = lines[-1]
                frame = render_frame(last, source=path)
                if clear:
                    out.write(_CLEAR)
                out.write(frame + "\n")
                if not clear:
                    out.write("\n")
                out.flush()
        if last is not None:
            if last.get("done"):
                return 0 if last.get("ok", True) else 1
            if once:
                return 0
        elif once:
            print(f"top: no heartbeat lines in {path}", file=sys.stderr)
            return 2
        if expired():
            print(
                f"top: no done=true within {max_wait_s:.0f}s "
                f"(run still live, or stream stalled)", file=sys.stderr,
            )
            return 2
        time.sleep(interval)
