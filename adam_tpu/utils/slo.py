"""Service-level objectives over the existing telemetry plane
(docs/OBSERVABILITY.md "SLOs and error budgets").

Everything below PR 20 *records*; this module *judges*.  A declarative
spec names per-tenant (or service-wide, ``*``) objectives over signals
the stack already measures — the ``sched.job.run`` span wall, the
non-quarantined job fraction, counter throughput — and an evaluator
turns the stream of completed jobs into compliance, error-budget
remaining, and the SRE-workbook **multi-window burn rate**: how many
times faster than "exactly on objective" the budget is being spent,
over a short and a long rolling window simultaneously, so a one-job
blip (short window only) and a slow leak (long window only) both fail
to page while a genuine fast burn (both) fires the ``slo.burn``
trigger through the incident recorder.

Grammar (``--slo`` / ``ADAM_TPU_SLO``)::

    tenantA:p99(sched.job.run)<30s;tenantB:avail>=0.999;*:avail>=0.99

Clauses split on ``;``, each ``tenant:objective[,objective...]``.
Objective forms:

``pNN(span)<BOUND``
    latency: at least NN% of the tenant's completed jobs finish the
    named span under BOUND (suffixes ``ms``/``s``/``m``; bare numbers
    are seconds).  Today the only per-job span the scheduler feeds is
    ``sched.job.run``; other names parse but observe nothing.
``avail>=FRAC``
    availability: the non-quarantined fraction of completed jobs is at
    least FRAC.
``tput(counter)>=RATE``
    throughput floor: the named counter advances at >= RATE per second
    (suffix ``/s`` optional), sampled at evaluation time.

Malformed clauses warn and are skipped — the tuning-var contract every
``ADAM_TPU_*`` knob keeps: an SLO typo must never take down serving.

Windows: the short window is ``ADAM_TPU_SLO_WINDOW_S`` (default 300 s,
the 5-minute analogue) and the long window is 12x that (the 1-hour
analogue), so scaling the knob scales both.  A fast burn fires when
the short-window burn rate is >= ``ADAM_TPU_SLO_FAST_BURN`` (default
14.4, the workbook's 2%-of-budget-in-an-hour figure) AND the
long-window burn corroborates at >= fast/2.4 (the 6x analogue).

Budget state (cumulative good/bad events per objective) persists
durably in ``<run-root>/SLO_BUDGET.json`` via
``durability.atomic_write_json``, so a scheduler restart resumes the
budget instead of silently refilling it.  The file also records each
objective's target, which makes it self-contained for
``adam-tpu analyze`` (the "SLO" section renders from the budget file
sitting next to any artifact).

Like the incident recorder this is a module-level arm/disarm seam:
``install(spec, run_root)`` / ``uninstall()``; producers call the
module functions (``observe_job``, ``note_perf_regression``) which
no-op when disarmed, so the hot path never imports policy.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from adam_tpu.utils import telemetry as tele

log = logging.getLogger(__name__)

#: Schema tag on the ``/slo`` status document and analyzer section.
SLO_SCHEMA = "adam_tpu.slo/1"

#: Schema tag on the durable budget file.
BUDGET_SCHEMA = "adam_tpu.slo_budget/1"

#: Durable budget file name under the run root.
BUDGET_FILENAME = "SLO_BUDGET.json"

#: Default short rolling window (seconds) — ``ADAM_TPU_SLO_WINDOW_S``.
#: The long window is always ``LONG_WINDOW_FACTOR`` times the short
#: one (5 m -> 1 h analogue).
DEFAULT_WINDOW_S = 300.0
LONG_WINDOW_FACTOR = 12.0

#: Default fast-burn threshold on the short window
#: (``ADAM_TPU_SLO_FAST_BURN``); the long window corroborates at
#: ``fast / FAST_LONG_RATIO``.
DEFAULT_FAST_BURN = 14.4
FAST_LONG_RATIO = 2.4

_DURATION_SUFFIX = {"ms": 1e-3, "s": 1.0, "m": 60.0}

_LATENCY_RE = re.compile(
    r"^p(?P<q>\d{1,2}(?:\.\d+)?)\((?P<name>[a-z0-9_.]+)\)"
    r"\s*<\s*(?P<bound>[0-9.]+)(?P<suffix>ms|s|m)?$")
_AVAIL_RE = re.compile(r"^avail\s*>=\s*(?P<frac>0?\.\d+|1(?:\.0+)?)$")
_TPUT_RE = re.compile(
    r"^tput\((?P<name>[a-z0-9_.]+)\)\s*>=\s*(?P<rate>[0-9.]+)(?:/s)?$")


def slo_window_s() -> float:
    """The short rolling window (``ADAM_TPU_SLO_WINDOW_S``; malformed
    or nonpositive warns and keeps the default)."""
    from adam_tpu.utils.retry import env_float

    v = env_float("ADAM_TPU_SLO_WINDOW_S", DEFAULT_WINDOW_S)
    if v <= 0:
        log.warning("ADAM_TPU_SLO_WINDOW_S=%s is not positive; using "
                    "default %.0fs", v, DEFAULT_WINDOW_S)
        return DEFAULT_WINDOW_S
    return v


def fast_burn_threshold() -> float:
    """``ADAM_TPU_SLO_FAST_BURN`` (default 14.4): the short-window
    burn rate at which ``slo.burn`` fires (long window corroborates
    at a 2.4x lower bar)."""
    from adam_tpu.utils.retry import env_float

    v = env_float("ADAM_TPU_SLO_FAST_BURN", DEFAULT_FAST_BURN)
    if v <= 0:
        log.warning("ADAM_TPU_SLO_FAST_BURN=%s is not positive; using "
                    "default %.1f", v, DEFAULT_FAST_BURN)
        return DEFAULT_FAST_BURN
    return v


@dataclass(frozen=True)
class Objective:
    """One parsed clause: a tenant scope plus a target over a signal.

    ``allowed`` is the error budget as a bad-event fraction: a p99
    latency objective allows 1% of jobs over the bound, ``avail>=
    0.999`` allows 0.1% quarantined.  Throughput floors are pass/fail
    at sample time, so their ``allowed`` is a nominal 1% too (a floor
    persistently unmet burns at 100x — loudly, as it should).
    """

    tenant: str  # "*" = service-wide
    kind: str  # "latency" | "avail" | "tput"
    name: Optional[str]  # span / counter name, None for avail
    target: float  # quantile frac (latency), avail frac, rate floor
    bound_s: Optional[float] = None  # latency bound, seconds

    @property
    def allowed(self) -> float:
        """Allowed bad-event fraction (the error budget)."""
        if self.kind == "latency":
            return max(1.0 - self.target, 1e-6)
        if self.kind == "avail":
            return max(1.0 - self.target, 1e-6)
        return 0.01

    @property
    def key(self) -> str:
        """Stable identity used in the budget file and status doc."""
        if self.kind == "latency":
            q = f"{self.target * 100:g}"
            return f"{self.tenant}:p{q}({self.name})<{self.bound_s:g}s"
        if self.kind == "avail":
            return f"{self.tenant}:avail>={self.target:g}"
        return f"{self.tenant}:tput({self.name})>={self.target:g}"

    def matches(self, tenant: Optional[str]) -> bool:
        return self.tenant == "*" or tenant == self.tenant


def parse_duration_s(text: str, suffix: Optional[str]) -> float:
    return float(text) * _DURATION_SUFFIX.get(suffix or "s", 1.0)


def parse_slo_spec(spec: str) -> list:
    """Grammar (module docstring) -> ``[Objective, ...]``.  Malformed
    clauses warn and are skipped — never raise (tuning-var contract)."""
    objectives: list = []
    for clause in (spec or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        tenant, sep, body = clause.partition(":")
        tenant = tenant.strip()
        if not sep or not tenant or not body.strip():
            log.warning("slo clause %r is not tenant:objective[,...]; "
                        "ignoring", clause)
            continue
        for item in body.split(","):
            item = item.strip().lower()
            if not item:
                continue
            m = _LATENCY_RE.match(item)
            if m:
                q = float(m.group("q")) / 100.0
                bound = parse_duration_s(m.group("bound"), m.group("suffix"))
                if 0.0 < q < 1.0 and bound > 0:
                    objectives.append(Objective(
                        tenant=tenant, kind="latency", name=m.group("name"),
                        target=q, bound_s=bound))
                    continue
            m = _AVAIL_RE.match(item)
            if m:
                frac = float(m.group("frac"))
                if 0.0 < frac <= 1.0:
                    objectives.append(Objective(
                        tenant=tenant, kind="avail", name=None, target=frac))
                    continue
            m = _TPUT_RE.match(item)
            if m:
                rate = float(m.group("rate"))
                if rate > 0:
                    objectives.append(Objective(
                        tenant=tenant, kind="tput", name=m.group("name"),
                        target=rate))
                    continue
            log.warning("slo clause %r: bad objective %r; ignoring it",
                        clause, item)
    return objectives


@dataclass
class _ObjState:
    """Mutable per-objective state: the rolling event window plus the
    durable cumulative budget counters."""

    objective: Objective
    events: deque = field(default_factory=deque)  # (t_mono, good: bool)
    good_total: int = 0  # cumulative, persisted
    bad_total: int = 0  # cumulative, persisted
    last_sample: Optional[tuple] = None  # tput: (t_mono, counter value)


class SLOEngine:
    """Evaluates parsed objectives over the job-completion stream.

    Thread-safe: jobs complete on scheduler worker threads, the
    gateway's ``/slo`` handler and the heartbeat sampler read from
    their own.  ``observe_job`` is the single write seam; it updates
    the rolling windows, persists the budget file, publishes the
    ``slo.worst_burn`` / ``slo.budget_remaining`` gauges, and fires
    ``slo.burn`` on a corroborated fast burn.
    """

    def __init__(self, objectives: list, run_root: Optional[str] = None,
                 *, window_s: Optional[float] = None,
                 fast_burn: Optional[float] = None) -> None:
        self._lock = threading.Lock()
        self._run_root = os.path.abspath(run_root) if run_root else None
        self._window_s = float(window_s) if window_s else slo_window_s()
        self._long_window_s = self._window_s * LONG_WINDOW_FACTOR
        self._fast_burn = (float(fast_burn) if fast_burn
                           else fast_burn_threshold())
        self._states = [_ObjState(objective=o) for o in objectives]
        self._load_budget()

    # ---- durable budget ----

    @property
    def budget_path(self) -> Optional[str]:
        if not self._run_root:
            return None
        return os.path.join(self._run_root, BUDGET_FILENAME)

    def _load_budget(self) -> None:
        path = self.budget_path
        if not path or not os.path.exists(path):
            return
        import json

        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
            saved = doc.get("objectives", {})
        except (OSError, ValueError) as e:
            log.warning("could not load SLO budget %s (%s); starting "
                        "fresh", path, e)
            return
        for st in self._states:
            row = saved.get(st.objective.key)
            if isinstance(row, dict):
                st.good_total = int(row.get("good", 0))
                st.bad_total = int(row.get("bad", 0))

    def _persist_budget_locked(self) -> None:
        path = self.budget_path
        if not path:
            return
        from adam_tpu.utils.durability import atomic_write_json

        doc = {
            "schema": BUDGET_SCHEMA,
            "window_s": self._window_s,
            "objectives": {
                st.objective.key: {
                    "tenant": st.objective.tenant,
                    "kind": st.objective.kind,
                    "target": st.objective.target,
                    "allowed": st.objective.allowed,
                    "good": st.good_total,
                    "bad": st.bad_total,
                }
                for st in self._states
            },
        }
        try:
            atomic_write_json(path, doc)
        except OSError as e:  # budget durability is best-effort
            log.warning("could not persist SLO budget %s: %s", path, e)

    # ---- observation ----

    def observe_job(self, tenant: Optional[str], duration_s: float,
                    ok: bool = True, *, span: str = "sched.job.run",
                    trace_id: Optional[str] = None,
                    tracer=None) -> None:
        """Book one completed job: ``ok=False`` means quarantined.
        Latency objectives over ``span`` judge ``duration_s`` against
        their bound; availability objectives judge ``ok``."""
        now = time.monotonic()
        with self._lock:
            for st in self._states:
                o = st.objective
                if not o.matches(tenant):
                    continue
                if o.kind == "latency":
                    if o.name != span:
                        continue
                    good = ok and duration_s < o.bound_s
                elif o.kind == "avail":
                    good = ok
                else:
                    continue  # tput is sampled, not event-driven
                st.events.append((now, good))
                if good:
                    st.good_total += 1
                else:
                    st.bad_total += 1
            self._evict_locked(now)
            self._persist_budget_locked()
        self._evaluate_and_alert(trace_id=trace_id, tracer=tracer)

    def note_bad_event(self, n: int = 1, *, reason: str = "") -> None:
        """Charge ``n`` bad events against every objective — the perf
        sentinel's burn charge: a confirmed perf regression spends
        error budget even when no individual job missed its bound."""
        now = time.monotonic()
        with self._lock:
            for st in self._states:
                if st.objective.kind == "tput":
                    continue
                for _ in range(max(0, int(n))):
                    st.events.append((now, False))
                    st.bad_total += 1
            self._evict_locked(now)
            self._persist_budget_locked()
        self._evaluate_and_alert(reason_prefix=reason)

    def _evict_locked(self, now: float) -> None:
        horizon = now - self._long_window_s
        for st in self._states:
            ev = st.events
            while ev and ev[0][0] < horizon:
                ev.popleft()

    # ---- evaluation ----

    @staticmethod
    def _window_frac(events: deque, since: float) -> tuple:
        """(bad fraction, event count) among events newer than
        ``since``; an empty window is compliant (0.0, 0)."""
        bad = n = 0
        for t, good in reversed(events):
            if t < since:
                break
            n += 1
            if not good:
                bad += 1
        return ((bad / n) if n else 0.0, n)

    def _eval_tput_locked(self, st: _ObjState, now: float) -> tuple:
        """Sample the counter and return (bad_frac, rate) — pass/fail
        at this instant; the first sample establishes the baseline."""
        snap = tele.TRACE.snapshot()
        value = snap.get("counters", {}).get(st.objective.name, 0)
        prev = st.last_sample
        st.last_sample = (now, value)
        if prev is None or now - prev[0] <= 0:
            return 0.0, None
        rate = (value - prev[1]) / (now - prev[0])
        good = rate >= st.objective.target
        st.events.append((now, good))
        if good:
            st.good_total += 1
        else:
            st.bad_total += 1
        return (0.0 if good else 1.0), rate

    def evaluate(self) -> dict:
        """Compliance, burn rates, and budget remaining per objective,
        plus the service-wide worst burn — the ``/slo`` document."""
        now = time.monotonic()
        rows = []
        with self._lock:
            self._evict_locked(now)
            for st in self._states:
                o = st.objective
                rate = None
                if o.kind == "tput":
                    _, rate = self._eval_tput_locked(st, now)
                bad_short, n_short = self._window_frac(
                    st.events, now - self._window_s)
                bad_long, n_long = self._window_frac(
                    st.events, now - self._long_window_s)
                burn_short = bad_short / o.allowed
                burn_long = bad_long / o.allowed
                total = st.good_total + st.bad_total
                bad_frac_total = (st.bad_total / total) if total else 0.0
                remaining = max(0.0, 1.0 - bad_frac_total / o.allowed)
                row = {
                    "key": o.key,
                    "tenant": o.tenant,
                    "kind": o.kind,
                    "name": o.name,
                    "target": o.target,
                    "allowed": o.allowed,
                    "compliance": 1.0 - bad_long,
                    "burn_short": burn_short,
                    "burn_long": burn_long,
                    "events_short": n_short,
                    "events_long": n_long,
                    "good_total": st.good_total,
                    "bad_total": st.bad_total,
                    "budget_remaining": remaining,
                    "fast_burn": (burn_short >= self._fast_burn
                                  and burn_long >= self._fast_burn
                                  / FAST_LONG_RATIO),
                }
                if o.kind == "latency":
                    row["bound_s"] = o.bound_s
                if rate is not None:
                    row["rate"] = rate
                rows.append(row)
        worst = max((r["burn_short"] for r in rows), default=0.0)
        remaining = min((r["budget_remaining"] for r in rows), default=1.0)
        return {
            "schema": SLO_SCHEMA,
            "window_s": self._window_s,
            "long_window_s": self._long_window_s,
            "fast_burn_threshold": self._fast_burn,
            "objectives": rows,
            "worst_burn": worst,
            "budget_remaining": remaining,
        }

    def _evaluate_and_alert(self, *, trace_id=None, tracer=None,
                            reason_prefix: str = "") -> None:
        status = self.evaluate()
        tele.TRACE.gauge(tele.G_SLO_WORST_BURN, status["worst_burn"])
        tele.TRACE.gauge(tele.G_SLO_BUDGET_REMAINING,
                         status["budget_remaining"])
        burning = [r for r in status["objectives"] if r["fast_burn"]]
        if not burning:
            return
        tele.TRACE.count(tele.C_SLO_BREACHES, len(burning))
        from adam_tpu.utils import incidents

        worst = max(burning, key=lambda r: r["burn_short"])
        reason = (
            f"{reason_prefix + ': ' if reason_prefix else ''}"
            f"objective {worst['key']} burning error budget at "
            f"{worst['burn_short']:.1f}x over the {self._window_s:.0f}s "
            f"window ({worst['burn_long']:.1f}x long); "
            f"{worst['budget_remaining'] * 100:.1f}% of budget remains"
        )
        incidents.maybe_record("slo.burn", trace_id=trace_id,
                               tracer=tracer, reason=reason)

    def worst_burn(self) -> float:
        """Short-window worst burn across objectives (heartbeat cell);
        reads the gauges' source of truth by re-evaluating."""
        return self.evaluate()["worst_burn"]


# ---- module-level arm/disarm (the incident-recorder pattern) ----

_ENGINE: Optional[SLOEngine] = None
_LOCK = threading.Lock()


def install(spec, run_root: Optional[str] = None, *,
            window_s: Optional[float] = None) -> Optional[SLOEngine]:
    """Arm the SLO engine.  ``spec`` is a grammar string, a parsed
    objective list, or an :class:`SLOEngine`.  A spec that parses to
    zero objectives leaves the engine disarmed (and warns — a typo'd
    spec must degrade, not raise)."""
    global _ENGINE
    if isinstance(spec, SLOEngine):
        engine = spec
    else:
        objectives = (parse_slo_spec(spec) if isinstance(spec, str)
                      else list(spec or []))
        if not objectives:
            if spec:
                log.warning("SLO spec %r parsed to no objectives; SLO "
                            "engine stays disarmed", spec)
            return None
        engine = SLOEngine(objectives, run_root, window_s=window_s)
    with _LOCK:
        _ENGINE = engine
    return engine


def uninstall() -> None:
    global _ENGINE
    with _LOCK:
        _ENGINE = None


def installed() -> bool:
    return _ENGINE is not None


def engine() -> Optional[SLOEngine]:
    return _ENGINE


def slo_from_env() -> Optional[str]:
    """``ADAM_TPU_SLO``: the spec string, or None when unset/empty."""
    spec = os.environ.get("ADAM_TPU_SLO", "").strip()
    return spec or None


def observe_job(tenant: Optional[str], duration_s: float, ok: bool = True,
                **kw) -> None:
    """Module seam for producers: books a completed job against the
    armed engine; no-op when disarmed."""
    eng = _ENGINE
    if eng is not None:
        eng.observe_job(tenant, duration_s, ok, **kw)


def note_perf_regression(n: int = 1, *, reason: str = "") -> None:
    """The perf sentinel's SLO burn charge (no-op when disarmed)."""
    eng = _ENGINE
    if eng is not None:
        eng.note_bad_event(n, reason=reason or "perf regression")


def status() -> Optional[dict]:
    """The ``/slo`` document, or None when no engine is armed."""
    eng = _ENGINE
    return eng.evaluate() if eng is not None else None


def worst_burn() -> Optional[float]:
    """Heartbeat cell: worst short-window burn, None when disarmed."""
    eng = _ENGINE
    return eng.worst_burn() if eng is not None else None


def _reset_for_tests() -> None:
    uninstall()
