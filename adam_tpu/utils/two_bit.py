"""UCSC .2bit random-access reference reader.

Parity with ``util/TwoBitFile.scala:57-152`` + ``util/ReferenceFile.scala:33``:
magic/version header (either endianness), name index, per-sequence N
blocks and mask blocks, and ``extract(region)``.

Columnar recast: the packed 2-bit payload decodes with one vectorized
shift/mask over the byte slice (the reference walks byte-at-a-time per
base), and N blocks are *applied* (bases inside an N block decode as
``N``) — the reference leaves this as a TODO and emits phantom ACGT
there.  Soft-mask blocks are exposed but not lower-cased by default,
matching reference output.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

MAGIC = 0x1A412743
#: 2-bit code -> base, in .2bit bit order (T=0, C=1, A=2, G=3)
_CODE_TO_BASE = np.frombuffer(b"TCAG", np.uint8)


class ReferenceFile:
    """Anything that can hand back reference sequence for a region
    (util/ReferenceFile.scala:33)."""

    def extract(self, contig: str, start: int, end: int) -> str:
        raise NotImplementedError


@dataclass
class TwoBitRecord:
    dna_size: int
    n_blocks: list  # [(start, end), ...)  0-based half-open
    mask_blocks: list
    dna_offset: int  # byte offset of packed DNA


class TwoBitFile(ReferenceFile):
    def __init__(self, path_or_bytes):
        if isinstance(path_or_bytes, (bytes, bytearray)):
            data = bytes(path_or_bytes)
        else:
            with open(path_or_bytes, "rb") as fh:
                data = fh.read()
        self._data = data
        if struct.unpack_from("<I", data, 0)[0] == MAGIC:
            self._end = "<"
        elif struct.unpack_from(">I", data, 0)[0] == MAGIC:
            self._end = ">"
        else:
            raise ValueError("not a .2bit file (bad magic)")
        version, count, reserved = struct.unpack_from(
            self._end + "III", data, 4
        )
        if version != 0 or reserved != 0:
            raise ValueError("unsupported .2bit version/reserved fields")
        self.records: dict[str, TwoBitRecord] = {}
        off = 16
        offsets = []
        for _ in range(count):
            name_size = data[off]
            name = data[off + 1: off + 1 + name_size].decode()
            (seq_off,) = struct.unpack_from(
                self._end + "I", data, off + 1 + name_size
            )
            offsets.append((name, seq_off))
            off += 1 + name_size + 4
        for name, seq_off in offsets:
            self.records[name] = self._read_record(seq_off)
        self._name_order = [n for n, _ in offsets]

    @property
    def num_seq(self) -> int:
        return len(self.records)

    def seq_lengths(self) -> dict[str, int]:
        return {n: r.dna_size for n, r in self.records.items()}

    def _read_record(self, off: int) -> TwoBitRecord:
        u = lambda o: struct.unpack_from(self._end + "I", self._data, o)[0]
        dna_size = u(off)
        n_count = u(off + 4)
        p = off + 8
        n_starts = [u(p + 4 * i) for i in range(n_count)]
        n_sizes = [u(p + 4 * (n_count + i)) for i in range(n_count)]
        p += 8 * n_count
        m_count = u(p)
        p += 4
        m_starts = [u(p + 4 * i) for i in range(m_count)]
        m_sizes = [u(p + 4 * (m_count + i)) for i in range(m_count)]
        p += 8 * m_count
        p += 4  # reserved
        return TwoBitRecord(
            dna_size=dna_size,
            n_blocks=[(s, s + z) for s, z in zip(n_starts, n_sizes)],
            mask_blocks=[(s, s + z) for s, z in zip(m_starts, m_sizes)],
            dna_offset=p,
        )

    def extract(self, contig: str, start: int, end: int,
                apply_masks: bool = False) -> str:
        """Sequence for [start, end) on ``contig`` (0-based half-open,
        the extract of TwoBitFile.scala:120-146 + N-block application)."""
        rec = self.records[contig]
        if start < 0 or end > rec.dna_size or end < start:
            raise ValueError(
                f"region {contig}:{start}-{end} out of bounds "
                f"(size {rec.dna_size})"
            )
        if end == start:
            return ""
        first_byte = rec.dna_offset + start // 4
        last_byte = rec.dna_offset + (end - 1) // 4 + 1
        chunk = np.frombuffer(self._data[first_byte:last_byte], np.uint8)
        # each byte holds 4 bases, most significant pair first
        shifts = np.array([6, 4, 2, 0], np.uint8)
        codes = (chunk[:, None] >> shifts[None, :]) & 0x3
        codes = codes.reshape(-1)[start % 4: start % 4 + (end - start)]
        out = _CODE_TO_BASE[codes].copy()
        for bs, be in rec.n_blocks:
            lo, hi = max(bs, start), min(be, end)
            if lo < hi:
                out[lo - start: hi - start] = ord("N")
        seq = out.tobytes().decode()
        if apply_masks:
            arr = bytearray(seq.encode())
            for bs, be in rec.mask_blocks:
                lo, hi = max(bs, start), min(be, end)
                if lo < hi:
                    arr[lo - start: hi - start] = (
                        seq[lo - start: hi - start].lower().encode()
                    )
            seq = arr.decode()
        return seq


class FragmentReferenceFile(ReferenceFile):
    """ReferenceFile over an in-memory FragmentBatch (the framework's
    native reference representation)."""

    def __init__(self, fragments, seq_dict):
        self.fragments = fragments
        self.seq_dict = seq_dict

    def extract(self, contig: str, start: int, end: int) -> str:
        idx = self.seq_dict.names.index(contig)
        return self.fragments.extract_region(idx, start, end)
