"""SAM attribute (optional tag) parsing.

Parity with ``models/Attribute.scala:50`` + ``util/AttributeUtils.scala:103``:
``TAG:TYPE:VALUE`` strings parse to typed :class:`Attribute` values, the
SAM spec types A/i/f/Z/H/B map to :class:`TagType`, and ``str()`` of an
Attribute reproduces the SAM text form.
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass
from typing import Any


class TagType(enum.Enum):
    CHARACTER = "A"
    INTEGER = "i"
    FLOAT = "f"
    STRING = "Z"
    BYTE_SEQUENCE = "H"
    NUMERIC_SEQUENCE = "B"


@dataclass(frozen=True)
class Attribute:
    tag: str
    tag_type: TagType
    value: Any

    def __str__(self) -> str:
        if self.tag_type is TagType.NUMERIC_SEQUENCE:
            # B values re-emit with their array subtype prefix
            sub, vals = self.value
            body = ",".join(str(v) for v in vals)
            return f"{self.tag}:B:{sub},{body}"
        return f"{self.tag}:{self.tag_type.value}:{self.value}"


_ATTR_RE = re.compile(r"^([^:]{2}):([AifZHB]):(.*)$")


def parse_attribute(encoded: str) -> Attribute:
    """One ``TAG:TYPE:VALUE`` token -> Attribute
    (AttributeUtils.parseAttribute, :60-67)."""
    m = _ATTR_RE.match(encoded)
    if not m:
        raise ValueError(
            f'attribute string "{encoded}" doesn\'t match format '
            f"attrTuple:type:value"
        )
    tag, type_chr, value_str = m.groups()
    tag_type = TagType(type_chr)
    if tag_type is TagType.CHARACTER:
        if len(value_str) != 1:
            raise ValueError(
                f'A-type attribute "{encoded}" must carry exactly one '
                f"character"
            )
        value: Any = value_str
    elif tag_type is TagType.INTEGER:
        value = int(value_str)
    elif tag_type is TagType.FLOAT:
        value = float(value_str)
    elif tag_type is TagType.STRING:
        value = value_str
    elif tag_type is TagType.BYTE_SEQUENCE:
        value = bytes.fromhex(value_str)
    else:  # NUMERIC_SEQUENCE: "subtype,v1,v2,..."
        parts = value_str.split(",")
        sub, items = parts[0], parts[1:]
        nums = [float(v) if "." in v else int(v) for v in items]
        value = (sub, nums)
    return Attribute(tag, tag_type, value)


def parse_attributes(tag_strings: str) -> list[Attribute]:
    """Tab-separated tag tokens -> Attributes
    (AttributeUtils.parseAttributes, :53-55)."""
    return [
        parse_attribute(tok)
        for tok in tag_strings.split("\t")
        if len(tok) > 0
    ]
