"""Validation stringency knobs.

The reference threads htsjdk's ValidationStringency through FASTQ
pairing/export paths (rdd/read/AlignmentRecordRDDFunctions.scala:386-464,
default LENIENT): STRICT raises on malformed input, LENIENT logs and
drops/continues, SILENT continues quietly.
"""

from __future__ import annotations

import enum
import logging

logger = logging.getLogger("adam_tpu.validation")


class ValidationStringency(enum.Enum):
    STRICT = "strict"
    LENIENT = "lenient"
    SILENT = "silent"

    @staticmethod
    def of(v) -> "ValidationStringency":
        if isinstance(v, ValidationStringency):
            return v
        return ValidationStringency(str(v).lower())


def handle(stringency, message: str, exc_type=ValueError) -> None:
    """STRICT: raise; LENIENT: warn; SILENT: nothing."""
    s = ValidationStringency.of(stringency)
    if s is ValidationStringency.STRICT:
        raise exc_type(message)
    if s is ValidationStringency.LENIENT:
        logger.warning(message)
