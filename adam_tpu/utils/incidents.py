"""Anomaly-triggered incident bundles — the flight recorder's escape
hatch for tail events.

The flight recorder is a bounded ring: on a long run, the evidence
around the one event an operator actually cares about — the hedge that
fired, the SDC mismatch that quarantined a chip — is silently evicted
minutes later (the Tail-at-Scale framing in utils/telemetry.py: the
interesting events are exactly the rare ones the ring loses).  This
module snapshots that evidence AT the anomaly, into a timestamped JSON
bundle under the run dir, where it survives the ring, the process, and
the operator's lunch break.

Triggers (each call site names its trigger; the set is closed and
documented in docs/OBSERVABILITY.md):

* ``health.transition`` — the device-health scoreboard moved a chip
  (utils/health.py demotion/probation/eviction/readmission).
* ``hedge.fired`` — a speculative re-dispatch launched because an
  in-flight window exceeded its latency threshold
  (parallel/device_pool.hedged_call).
* ``audit.mismatch`` — the SDC dual-compute audit caught a bit
  mismatch (pipelines/streamed._audit_result).
* ``retry.exhausted`` — a retry budget was genuinely spent on
  retryable failures (utils/retry.retry_call).
* ``quota.burst`` — a burst of per-tenant quota 429s
  (:func:`note_quota_rejected` fed from serve/scheduler.py).

A bundle carries the triggering trace (Chrome-trace JSON filtered to
the job's trace_id, fused fan-in links included), the flight-recorder
ring tail, a full metrics snapshot, and the health board — everything
the post-hoc "what happened to job J's window 12" question needs.

Lifecycle: :func:`install` arms the recorder on a run dir (the
scheduler's run root, or a solo run's ``--run-dir``); uninstalled, every
trigger is one predicate and a return — the disabled-by-default
overhead contract the spans keep.  Recording is best-effort and
swallowed: an incident bundle must never take down the run it
documents.

Knobs (tolerantly parsed, the ``ADAM_TPU_*`` house rule):

* ``ADAM_TPU_INCIDENTS`` — master toggle (default on once installed).
* ``ADAM_TPU_INCIDENT_MAX`` — bundle-count bound per incidents dir
  (default 16; oldest pruned).
* ``ADAM_TPU_INCIDENT_COOLDOWN_S`` — per-trigger cooldown (default
  30 s; a flapping chip yields one bundle per cooldown, not thousands).
* ``ADAM_TPU_INCIDENT_EVENTS`` — ring-tail cap per bundle (default
  4096 newest events).
* ``ADAM_TPU_INCIDENT_QUOTA_BURST`` / ``ADAM_TPU_INCIDENT_QUOTA_WINDOW_S``
  — the quota-429 burst threshold (default 3 rejections in 10 s).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque

from adam_tpu.utils import telemetry as tele
from adam_tpu.utils.retry import env_float, env_toggle, _env_int

log = logging.getLogger(__name__)

#: Schema tag every bundle carries.
INCIDENT_SCHEMA = "adam_tpu.incident/1"

#: The closed trigger vocabulary (docs/OBSERVABILITY.md).
TRIGGERS = (
    "health.transition",
    "hedge.fired",
    "audit.mismatch",
    "retry.exhausted",
    "quota.burst",
    "slo.burn",
    "perf.regression",
)

#: Subdirectory of the installed run dir bundles land in.
INCIDENTS_DIRNAME = "incidents"

_DEFAULT_MAX_BUNDLES = 16
_DEFAULT_COOLDOWN_S = 30.0
_DEFAULT_EVENT_CAP = 4096
_DEFAULT_QUOTA_BURST = 3
_DEFAULT_QUOTA_WINDOW_S = 10.0

_LOCK = threading.Lock()
_DIR: str | None = None         # armed incidents dir (None = disarmed)
_SEQ = 0                        # per-process bundle ordinal
_LAST_BY_TRIGGER: dict = {}     # trigger -> monotonic ts of last bundle
_LAST_INCIDENT: dict | None = None
_QUOTA_REJECTS: deque = deque() # (monotonic ts, tenant) burst window


# ---------------------------------------------------------------------------
# Lifecycle
# ---------------------------------------------------------------------------
def install(run_dir: str) -> None:
    """Arm the recorder: bundles land under ``<run_dir>/incidents/``.
    Idempotent; a second install re-points the recorder (one recorder
    per process — the scheduler's run root wins over per-job dirs
    because it installs first and jobs never re-install)."""
    global _DIR
    with _LOCK:
        _DIR = os.path.join(str(run_dir), INCIDENTS_DIRNAME)


def uninstall() -> None:
    """Disarm (tests; a drained scheduler leaves itself armed — late
    triggers during teardown still deserve evidence)."""
    global _DIR
    with _LOCK:
        _DIR = None


def installed() -> bool:
    with _LOCK:
        return _DIR is not None


def incidents_dir() -> str | None:
    """The armed incidents dir (None when disarmed)."""
    with _LOCK:
        return _DIR


def last_incident() -> dict | None:
    """Summary of the newest bundle THIS process recorded (the
    heartbeat's ``last_incident`` / ``last_incident_age_s`` fields), or
    None: ``{id, trigger, ts, ts_monotonic, path}``."""
    with _LOCK:
        return dict(_LAST_INCIDENT) if _LAST_INCIDENT else None


def _reset_for_tests() -> None:
    global _DIR, _SEQ, _LAST_INCIDENT
    with _LOCK:
        _DIR = None
        _SEQ = 0
        _LAST_BY_TRIGGER.clear()
        _LAST_INCIDENT = None
        _QUOTA_REJECTS.clear()


# ---------------------------------------------------------------------------
# Recording
# ---------------------------------------------------------------------------
def maybe_record(trigger: str, *, device=None, window=None,
                 trace_id: str | None = None, tracer=None,
                 reason: str = "") -> str | None:
    """Record one incident bundle if the recorder is armed, enabled,
    and the trigger is off cooldown; returns the bundle path (None
    when skipped).  Best-effort: any failure is logged and swallowed —
    evidence collection must never kill the run it documents.

    ``tracer`` defaults to the global :data:`~adam_tpu.utils.telemetry.TRACE`;
    call sites inside a streamed run pass their run tracer so the
    bundle's ring/trace carry the job's own spans.  ``trace_id``
    defaults to the tracer's job trace (or the thread's trace scope),
    and selects the embedded Chrome-trace view."""
    try:
        return _record(trigger, device=device, window=window,
                       trace_id=trace_id, tracer=tracer, reason=reason)
    except Exception:
        log.warning("incident bundle for %s failed", trigger,
                    exc_info=True)
        return None


def _record(trigger, *, device, window, trace_id, tracer, reason):
    global _SEQ, _LAST_INCIDENT
    now = time.monotonic()
    with _LOCK:
        dirpath = _DIR
        if dirpath is None:
            return None
        if not env_toggle("ADAM_TPU_INCIDENTS", True):
            return None
        cooldown = max(
            0.0, env_float("ADAM_TPU_INCIDENT_COOLDOWN_S",
                           _DEFAULT_COOLDOWN_S)
        )
        last = _LAST_BY_TRIGGER.get(trigger)
        if last is not None and (now - last) < cooldown:
            return None
        _LAST_BY_TRIGGER[trigger] = now
        _SEQ += 1
        seq = _SEQ
    tr = tracer if tracer is not None else tele.TRACE
    if trace_id is None:
        trace_id = tele.current_trace() or tr.trace
    bundle_id = "inc-%d-%04d-%s" % (
        int(time.time()), seq, trigger.replace(".", "-")
    )
    event_cap = _env_int("ADAM_TPU_INCIDENT_EVENTS", _DEFAULT_EVENT_CAP)
    ring = tr.events()
    bundle = {
        "schema": INCIDENT_SCHEMA,
        "id": bundle_id,
        "trigger": trigger,
        "reason": str(reason) if reason else "",
        "ts": time.time(),
        "device": None if device is None else str(device),
        "window": window,
        "trace_id": trace_id,
        # newest ring tail (the evidence the eviction would lose)
        "events": ring[-event_cap:],
        "events_dropped": max(0, len(ring) - event_cap),
        "metrics": tr.snapshot(),
        "health": _health_status(),
        # the triggering trace, as the same Chrome-trace shape the
        # gateway /trace surface serves — dispatch/fetch/audit spans of
        # the implicated window included, fan-in links intact
        "trace": (
            tr.to_chrome_trace(trace_id) if trace_id is not None else None
        ),
    }
    path = os.path.join(dirpath, bundle_id + ".json")
    from adam_tpu.utils.durability import atomic_write_json

    os.makedirs(dirpath, exist_ok=True)
    atomic_write_json(path, bundle)
    _prune(dirpath)
    tele.TRACE.count(tele.C_INCIDENT_RECORDED)
    with _LOCK:
        _LAST_INCIDENT = {
            "id": bundle_id, "trigger": trigger, "ts": bundle["ts"],
            "ts_monotonic": now, "path": path,
        }
    log.warning("incident %s recorded (%s): %s", bundle_id, trigger,
                path)
    return path


def _health_status():
    """Health-board snapshot for the bundle (lazy import; None when the
    board is empty or unimportable).  Called with NO locks held — the
    board snapshot takes the board's own lock, and a trigger fired from
    inside a board transition must already have released it
    (utils/health.py defers its incident hook past unlock)."""
    try:
        from adam_tpu.utils import health as health_mod

        return health_mod.BOARD.status() or None
    except Exception:
        return None


def _prune(dirpath: str) -> None:
    """Bounded bundle count: delete oldest beyond the cap (bundle ids
    sort chronologically — epoch seconds then per-process seq)."""
    cap = _env_int("ADAM_TPU_INCIDENT_MAX", _DEFAULT_MAX_BUNDLES)
    try:
        names = sorted(
            n for n in os.listdir(dirpath)
            if n.startswith("inc-") and n.endswith(".json")
        )
    except OSError:
        return
    for n in names[:-cap] if len(names) > cap else ():
        try:
            os.unlink(os.path.join(dirpath, n))
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Quota-burst detector
# ---------------------------------------------------------------------------
def note_quota_rejected(tenant: str) -> None:
    """Feed one quota 429 into the burst detector (serve/scheduler.py
    calls this at its ``Busy(kind="quota")`` site).  A burst —
    ``ADAM_TPU_INCIDENT_QUOTA_BURST`` rejections inside
    ``ADAM_TPU_INCIDENT_QUOTA_WINDOW_S`` — records one ``quota.burst``
    bundle (the per-trigger cooldown still applies, so a sustained
    storm yields one bundle per cooldown)."""
    if not installed():
        return
    now = time.monotonic()
    window_s = max(
        0.1, env_float("ADAM_TPU_INCIDENT_QUOTA_WINDOW_S",
                       _DEFAULT_QUOTA_WINDOW_S)
    )
    burst = _env_int("ADAM_TPU_INCIDENT_QUOTA_BURST",
                     _DEFAULT_QUOTA_BURST)
    with _LOCK:
        _QUOTA_REJECTS.append((now, str(tenant)))
        while _QUOTA_REJECTS and now - _QUOTA_REJECTS[0][0] > window_s:
            _QUOTA_REJECTS.popleft()
        n = len(_QUOTA_REJECTS)
        tenants = sorted({t for _, t in _QUOTA_REJECTS})
        fire = n >= burst
        if fire:
            _QUOTA_REJECTS.clear()
    if fire:
        maybe_record(
            "quota.burst",
            reason="%d quota rejections in %.0fs (tenants: %s)"
                   % (n, window_s, ", ".join(tenants)),
        )


# ---------------------------------------------------------------------------
# Listing — `adam-tpu incidents` and the gateway GET /incidents
# ---------------------------------------------------------------------------
def summarize_bundle(doc: dict, path: str | None = None) -> dict:
    """One bundle's list-view row (the CLI table and the gateway
    ``/incidents`` payload share it)."""
    return {
        "id": doc.get("id"),
        "trigger": doc.get("trigger"),
        "reason": doc.get("reason") or "",
        "ts": doc.get("ts"),
        "device": doc.get("device"),
        "window": doc.get("window"),
        "trace_id": doc.get("trace_id"),
        "path": path,
    }


def list_bundles(run_dir: str) -> list:
    """Bundle summaries under ``<run_dir>/incidents/`` (or ``run_dir``
    itself when it already IS an incidents dir), oldest first.
    Malformed files are skipped with a warning — a torn bundle must not
    hide its siblings."""
    import json

    dirpath = str(run_dir)
    if os.path.basename(os.path.normpath(dirpath)) != INCIDENTS_DIRNAME:
        cand = os.path.join(dirpath, INCIDENTS_DIRNAME)
        if os.path.isdir(cand):
            dirpath = cand
    try:
        names = sorted(
            n for n in os.listdir(dirpath)
            if n.startswith("inc-") and n.endswith(".json")
        )
    except OSError:
        return []
    out = []
    for n in names:
        path = os.path.join(dirpath, n)
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            log.warning("skipping malformed incident bundle %s", path)
            continue
        if doc.get("schema") != INCIDENT_SCHEMA:
            log.warning("skipping %s: unknown schema %r", path,
                        doc.get("schema"))
            continue
        out.append(summarize_bundle(doc, path))
    return out
