"""Per-device health scoreboard: healthy → suspect → probation → evicted.

The fault stack so far (faults / retry / eviction / replay) handles
devices that fail LOUDLY — exceptions retry, hung fetches time out,
dead chips evict and replay.  This module defends against devices that
misbehave QUIETLY, the two failure shapes fleet experience says are
real at scale:

* **stragglers** (Dean & Barroso, "The Tail at Scale") — a chip that
  stretches every window to p99×10 without ever erroring.  Hedged
  dispatch (``ADAM_TPU_HEDGE_FACTOR``; wired in pipelines/streamed.py)
  speculatively re-runs an overdue window on another device, and the
  scoreboard demotes the chip whose latency EWMA stays degraded.
* **silent data corruptors** (Dixit et al., "Silent Data Corruptions
  at Scale") — a chip that returns bit-flipped results that would
  otherwise publish as corrupt Parquet.  The SDC audit
  (``ADAM_TPU_AUDIT_RATE``) dual-computes a deterministic sample of
  windows on the host parity twin and bit-compares; a mismatch
  quarantines the device here and the window replays from the host
  copy, so the published part is clean.

The scoreboard is a decaying penalty score per device, fed by the
signals the pipeline already records:

=================  ======  ==========================================
signal             weight  source
=================  ======  ==========================================
retry              0.5     transient dispatch/fetch failures absorbed
                           by the backoff wrappers (utils/transfer
                           feeds the device-attributed fetch retries)
timeout            1.5     ``DeadlineExceeded`` fetch watchdog trips
latency breach     1.0     a dispatch+fetch wall above
                           ``ADAM_TPU_HEALTH_LATENCY_FACTOR`` × the
                           kernel's pooled p99 (the per-kernel
                           histogram machinery telemetry already uses),
                           or a per-(kernel, device) EWMA that stays
                           above it
audit mismatch     —       straight to **probation** (quarantine):
                           wrong bits are never a score debate
=================  ======  ==========================================

State machine (score thresholds, exponential decay with half-life
``ADAM_TPU_HEALTH_DECAY_S``):

* ``healthy`` → ``suspect`` at score ≥ ``ADAM_TPU_HEALTH_SUSPECT``
  (still placeable — an early warning, visible in the health section);
* ``suspect`` → ``probation`` at score ≥ ``ADAM_TPU_HEALTH_PROBATION``
  (or immediately via :meth:`HealthBoard.quarantine`): the device is
  **excluded from placement** (``DevicePool.alive_devices`` filters it,
  mesh construction skips it, scheduler leases never see it) but NOT
  evicted — its jit executables stay warm;
* ``probation`` → ``healthy`` after the ``ADAM_TPU_HEALTH_COOLDOWN_S``
  cooldown **and** a passing re-admission probe — a prewarmed
  known-answer dispatch (:func:`probe_known_answer`) whose result must
  come back bit-exact;
* ``probation`` → ``evicted`` when the probe fails: the chip is dead
  hardware, handed to the normal ``DevicePool.evict`` path.

Availability beats health: the filter never empties the placeable set —
when every survivor is blocked the pool serves them anyway (the audit
still keeps published bytes clean), and the LAST device is never
health-blocked.

One process-wide board (:data:`BOARD`, the ``TRACE`` pattern) spans
runs and jobs: a chip that corrupted tenant A's window must not serve
tenant B five seconds later.  All knobs follow the tolerant
``ADAM_TPU_*`` parsing contract.  Reference: docs/ROBUSTNESS.md
"Device health, hedging, and SDC audit".
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from typing import Optional

from adam_tpu.utils import telemetry as tele
from adam_tpu.utils.retry import env_float

log = logging.getLogger(__name__)

HEALTHY = "healthy"
SUSPECT = "suspect"
PROBATION = "probation"
EVICTED = "evicted"

#: Signal weights (module docstring table).
W_RETRY = 0.5
W_TIMEOUT = 1.5
W_LATENCY = 1.0

_DEF_SUSPECT = 3.0
_DEF_PROBATION = 6.0
_DEF_DECAY_S = 30.0
_DEF_COOLDOWN_S = 30.0
_DEF_LATENCY_FACTOR = 4.0
#: Pooled-histogram sample floor before latency judgments fire (a p99
#: over 3 samples is noise) and before a hedge threshold exists.
#: ``ADAM_TPU_HEDGE_MIN_SAMPLES`` overrides (short runs on slow media
#: may want a warmer trigger; the tolerant-parsing contract applies).
MIN_LATENCY_SAMPLES = 8


def min_latency_samples() -> int:
    from adam_tpu.utils.retry import _env_int

    return _env_int("ADAM_TPU_HEDGE_MIN_SAMPLES", MIN_LATENCY_SAMPLES)
#: Hedge threshold floor (seconds): never hedge on sub-noise walls
#: even when the observed p99 is tiny (virtual CPU devices fetch in
#: microseconds — factor × p99 alone would hedge every window).
_DEF_HEDGE_MIN_S = 0.05
#: EWMA smoothing for the per-(kernel, device) dispatch latency.
_EWMA_ALPHA = 0.25


def device_key(device) -> str:
    """Stable scoreboard key for a device: the ``platform:id`` form
    ``parallel/device_pool._device_key`` uses (one vocabulary across
    the prewarm cache, eviction set and this board); strings pass
    through (test fixtures, ``"mesh"``/``"default"`` attributions)."""
    if device is None:
        return "default"
    if isinstance(device, str):
        return device
    return f"{getattr(device, 'platform', '?')}:{getattr(device, 'id', id(device))}"


def hedge_factor() -> float:
    """``ADAM_TPU_HEDGE_FACTOR`` (default 0 = hedging off): hedge when
    an in-flight window's dispatch+fetch wall exceeds this multiple of
    the kernel's observed p99."""
    v = env_float("ADAM_TPU_HEDGE_FACTOR", 0.0)
    return v if v > 0 else 0.0


def audit_rate() -> float:
    """``ADAM_TPU_AUDIT_RATE`` (default 0 = audit off), clamped to
    [0, 1]: the fraction of windows deterministically sampled for
    dual-compute bit comparison."""
    v = env_float("ADAM_TPU_AUDIT_RATE", 0.0)
    return min(max(v, 0.0), 1.0)


def audit_due(window: int, rate: Optional[float] = None,
              seed: Optional[int] = None) -> bool:
    """Whether window ``window`` is audited — a pure function of
    (seed, window index), NOT of placement, arrival order or wall
    clock, so a ``--resume`` re-audits exactly the windows the killed
    run would have audited (the window plan is fingerprint-stable,
    docs/ROBUSTNESS.md "Durable window-granular resume")."""
    r = audit_rate() if rate is None else rate
    if r <= 0:
        return False
    if r >= 1:
        return True
    if seed is None:
        from adam_tpu.utils.retry import _env_seed

        seed = _env_seed("ADAM_TPU_AUDIT_SEED", 0)
    digest = hashlib.sha256(f"{seed}:{int(window)}".encode()).digest()
    unit = int.from_bytes(digest[:8], "big") / float(1 << 64)
    return unit < r


class _Device:
    __slots__ = ("score", "state", "t_score", "since", "probes",
                 "signals", "reason", "ewma")

    def __init__(self, now: float):
        self.score = 0.0
        self.state = HEALTHY
        self.t_score = now
        self.since = now
        self.probes = 0
        self.signals = {"retry": 0, "timeout": 0, "latency": 0,
                        "mismatch": 0}
        self.reason = ""
        self.ewma: dict = {}  # kernel -> EWMA seconds


class HealthBoard:
    """The per-device health scoreboard (module docstring)."""

    def __init__(self, clock=time.monotonic,
                 suspect_score: Optional[float] = None,
                 probation_score: Optional[float] = None,
                 decay_halflife_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 latency_factor: Optional[float] = None):
        self._clock = clock
        self.suspect_score = (
            suspect_score if suspect_score is not None
            else env_float("ADAM_TPU_HEALTH_SUSPECT", _DEF_SUSPECT)
        )
        self.probation_score = (
            probation_score if probation_score is not None
            else env_float("ADAM_TPU_HEALTH_PROBATION", _DEF_PROBATION)
        )
        self.decay_halflife_s = max(1e-3, (
            decay_halflife_s if decay_halflife_s is not None
            else env_float("ADAM_TPU_HEALTH_DECAY_S", _DEF_DECAY_S)
        ))
        self.cooldown_s = (
            cooldown_s if cooldown_s is not None
            else env_float("ADAM_TPU_HEALTH_COOLDOWN_S", _DEF_COOLDOWN_S)
        )
        self.latency_factor = (
            latency_factor if latency_factor is not None
            else env_float("ADAM_TPU_HEALTH_LATENCY_FACTOR",
                           _DEF_LATENCY_FACTOR)
        )
        self._lock = threading.Lock()
        self._dev: dict[str, _Device] = {}
        # state transitions staged under the lock, fired as
        # "health.transition" incident bundles AFTER release (the
        # bundle snapshots this very board via status(), which takes
        # the lock — firing inline would deadlock)
        self._pending_incidents: list = []
        # per-kernel pooled latency histogram (telemetry's fixed
        # log-spaced buckets, so the p99 math is the shared machinery)
        self._lat: dict[str, dict] = {}
        #: lock-free fast-path gate for the probe hook: the earliest
        #: monotonic time any probation device becomes probe-due
        #: (float read is GIL-atomic; inf = nothing to probe)
        self.next_probe_due = float("inf")

    # ---- internals (caller holds the lock) -----------------------------
    def _slot_locked(self, key: str) -> _Device:
        d = self._dev.get(key)
        if d is None:
            d = self._dev[key] = _Device(self._clock())
        return d

    def _decay_locked(self, d: _Device, now: float) -> None:
        dt = max(0.0, now - d.t_score)
        if dt > 0 and d.score > 0:
            d.score *= 0.5 ** (dt / self.decay_halflife_s)
            if d.score < 1e-6:
                d.score = 0.0
        d.t_score = now
        # decay can walk a suspect back to healthy; probation/evicted
        # only leave through the probe path
        if d.state == SUSPECT and d.score < 0.5 * self.suspect_score:
            d.state = HEALTHY
            d.since = now

    def _penalize_locked(self, key: str, weight: float, signal: str,
                         reason: str, tracer) -> None:
        now = self._clock()
        d = self._slot_locked(key)
        self._decay_locked(d, now)
        d.score += weight
        d.signals[signal] = d.signals.get(signal, 0) + 1
        if d.state in (PROBATION, EVICTED):
            return
        if d.score >= self.probation_score:
            self._enter_probation_locked(key, d, now, reason, tracer)
        elif d.score >= self.suspect_score and d.state == HEALTHY:
            d.state = SUSPECT
            d.since = now
            d.reason = reason
            tracer.count(tele.C_HEALTH_DEMOTED)
            tracer.record_health(key, SUSPECT, d.score, reason)
            self._pending_incidents.append((key, SUSPECT, reason, tracer))
            log.warning(
                "device %s health: healthy -> suspect (score %.1f, %s)",
                key, d.score, reason,
            )

    def _enter_probation_locked(self, key: str, d: _Device, now: float,
                                reason: str, tracer) -> None:
        d.state = PROBATION
        d.since = now
        d.reason = reason
        self.next_probe_due = min(
            self.next_probe_due, now + self.cooldown_s
        )
        tracer.count(tele.C_HEALTH_PROBATION)
        tracer.record_health(key, PROBATION, d.score, reason)
        self._pending_incidents.append((key, PROBATION, reason, tracer))
        log.error(
            "device %s health: PROBATION (score %.1f, %s) — excluded "
            "from placement; re-admission probe after %.0fs cooldown",
            key, d.score, reason, self.cooldown_s,
        )

    def _flush_incidents(self) -> None:
        """Fire the staged ``health.transition`` incident bundles.
        Called by every public feed AFTER its lock release — the bundle
        writer snapshots this board (``status()`` takes the lock) and
        must never run under it.  Best-effort like all recording."""
        with self._lock:
            if not self._pending_incidents:
                return
            pending = self._pending_incidents
            self._pending_incidents = []
        from adam_tpu.utils import incidents

        for key, state, reason, tracer in pending:
            incidents.maybe_record(
                "health.transition", device=key, tracer=tracer,
                reason=f"device {key} -> {state}: {reason}",
            )

    # ---- signal feeds --------------------------------------------------
    def note_retry(self, device, site: str = "", tracer=None) -> None:
        """A transient, retried failure attributed to ``device`` (the
        backoff wrappers absorb it; the board remembers it)."""
        with self._lock:
            self._penalize_locked(
                device_key(device), W_RETRY, "retry",
                f"retried failure at {site or 'device rpc'}",
                tracer if tracer is not None else tele.TRACE,
            )
        self._flush_incidents()

    def note_timeout(self, device, site: str = "", tracer=None) -> None:
        """A fetch-deadline watchdog trip attributed to ``device``."""
        with self._lock:
            self._penalize_locked(
                device_key(device), W_TIMEOUT, "timeout",
                f"deadline exceeded at {site or 'device.fetch'}",
                tracer if tracer is not None else tele.TRACE,
            )
        self._flush_incidents()

    def observe_latency(self, kernel: str, device, seconds: float,
                        tracer=None) -> None:
        """One window's dispatch+fetch wall on ``device`` for
        ``kernel``: feeds the pooled per-kernel histogram (the hedge
        threshold's p99) and the per-(kernel, device) EWMA; a wall — or
        an EWMA — above ``latency_factor`` × pooled p99 penalizes the
        device as a straggler."""
        s = float(seconds)
        key = device_key(device)
        with self._lock:
            h = self._lat.get(kernel)
            if h is None:
                h = self._lat[kernel] = tele._new_hist()
            d = self._slot_locked(key)
            prev = d.ewma.get(kernel)
            ew = s if prev is None else (
                _EWMA_ALPHA * s + (1 - _EWMA_ALPHA) * prev
            )
            d.ewma[kernel] = ew
            breach = None
            pool_sample = True
            if h["count"] >= min_latency_samples():
                p99 = tele._hist_quantile(h, 0.99) or 0.0
                bound = self.latency_factor * p99
                if bound > 0 and s > bound:
                    # the breached observation does NOT enter the
                    # pooled histogram: a straggler must not drag the
                    # fleet's p99 up until its own tail reads as normal
                    breach = "pooled p99"
                    pool_sample = False
                elif bound > 0 and ew > bound and (
                    prev is None or prev <= bound
                ):
                    # the EWMA crossed INTO excursion without the
                    # sample itself breaching: charge once at the
                    # crossing, never on the decay tail — one transient
                    # blip must not bill the ~log(ew/bound)/log(1-a)
                    # healthy windows it takes the average to recover
                    # (sustained stragglers keep charging through the
                    # per-sample branch above)
                    breach = "pooled p99"
                if breach is None:
                    # cross-device check: a chip slow from its FIRST
                    # window contaminates the pooled p99 it is judged
                    # against (half the warmup samples on a 2-device
                    # pool sit in its own tail), so it can never breach
                    # the pooled bound — but its peers' EWMAs it cannot
                    # touch.  A sample AND EWMA both above
                    # latency_factor x the best peer's EWMA for the
                    # same kernel is a straggler no matter what it did
                    # to the pool (single-device pools and collective
                    # attributions have no peers: no-op).
                    peer = min(
                        (
                            o.ewma[kernel]
                            for ok, o in self._dev.items()
                            if ok != key and kernel in o.ewma
                        ),
                        default=0.0,
                    )
                    rel = self.latency_factor * peer
                    if rel > 0 and s > rel and ew > rel:
                        breach = "best peer EWMA"
                        pool_sample = False
            if pool_sample:
                tele._hist_observe(h, s)
            if breach:
                self._penalize_locked(
                    key, W_LATENCY, "latency",
                    f"{kernel} wall {s * 1e3:.1f}ms above "
                    f"{self.latency_factor:g}x {breach}",
                    tracer if tracer is not None else tele.TRACE,
                )
        self._flush_incidents()

    def note_hedge_lost(self, device, kernel: str = "", tracer=None) -> None:
        """``device`` lost a hedge race: its window re-dispatched COLD
        on a peer (host re-ship + dispatch + fetch) and the peer still
        finished first.  This is the strongest straggler evidence there
        is — and the only latency signal available for a primary that
        never finished (its true wall is unknowable, only "longer than
        the whole race"; ``observe_latency`` has nothing true to
        record).  Weighted like a latency breach, so a chip slow enough
        to keep losing hedges walks to probation without ever
        erroring — hedging rescues its windows, the scoreboard retires
        the chip."""
        with self._lock:
            self._penalize_locked(
                device_key(device), W_LATENCY, "latency",
                f"lost hedge race on {kernel or 'dispatch'}",
                tracer if tracer is not None else tele.TRACE,
            )
        self._flush_incidents()

    def quarantine(self, device, reason: str = "", tracer=None) -> None:
        """Straight to probation — the SDC audit's verdict (wrong bits
        are never a score debate), also the mesh-degradation hook."""
        key = device_key(device)
        with self._lock:
            now = self._clock()
            d = self._slot_locked(key)
            d.signals["mismatch"] = d.signals.get("mismatch", 0) + 1
            if d.state in (PROBATION, EVICTED):
                return
            d.score = max(d.score, self.probation_score)
            d.t_score = now
            self._enter_probation_locked(
                key, d, now, reason or "quarantined",
                tracer if tracer is not None else tele.TRACE,
            )
        self._flush_incidents()

    def mark_evicted(self, device, tracer=None) -> None:
        """The pool evicted this chip (spent retry budget or failed
        probe): terminal state, never placeable again."""
        key = device_key(device)
        with self._lock:
            d = self._slot_locked(key)
            if d.state == EVICTED:
                return
            d.state = EVICTED
            d.since = self._clock()
            tr = tracer if tracer is not None else tele.TRACE
            tr.record_health(key, EVICTED, d.score, d.reason)
            self._pending_incidents.append(
                (key, EVICTED, d.reason or "evicted by the pool", tr)
            )
        self._flush_incidents()

    # ---- placement queries --------------------------------------------
    def state(self, device) -> str:
        with self._lock:
            d = self._dev.get(device_key(device))
            if d is None:
                return HEALTHY
            self._decay_locked(d, self._clock())
            return d.state

    def blocked(self, device) -> bool:
        """True when ``device`` must be excluded from placement
        (probation or evicted).  Cheap miss path: unknown devices are
        healthy without allocating a slot."""
        with self._lock:
            d = self._dev.get(device_key(device))
            if d is None or d.state in (HEALTHY, SUSPECT):
                return False
            return True

    def hedge_threshold(self, kernel: str) -> Optional[float]:
        """Seconds after which an in-flight ``kernel`` window should be
        hedged: ``ADAM_TPU_HEDGE_FACTOR`` × the kernel's pooled p99,
        floored at ``ADAM_TPU_HEDGE_MIN_S``.  None while hedging is off
        or fewer than :data:`MIN_LATENCY_SAMPLES` walls are pooled (a
        cold p99 is noise — never hedge on it)."""
        factor = hedge_factor()
        if factor <= 0:
            return None
        with self._lock:
            h = self._lat.get(kernel)
            if h is None or h["count"] < min_latency_samples():
                return None
            p99 = tele._hist_quantile(h, 0.99)
        if not p99:
            return None
        return max(
            factor * p99, env_float("ADAM_TPU_HEDGE_MIN_S",
                                    _DEF_HEDGE_MIN_S),
        )

    # ---- probation cooldown + re-admission probe -----------------------
    def probe_maybe_due(self) -> bool:
        """Lock-free fast-path gate for the per-window placement call:
        False when no probation device can possibly be probe-due (the
        overwhelmingly common case), so callers skip building their
        candidate set entirely.  One clock read against one
        GIL-atomic float."""
        return self._clock() >= self.next_probe_due

    def due_probes(self, candidates=None) -> list:
        """Probation device keys whose cooldown has elapsed.  Each
        returned key's cooldown restarts immediately, so a failing (or
        crashed) probe cannot hot-loop; callers run the probe and call
        :meth:`readmit` or :meth:`probe_failed`.

        ``candidates`` (devices or keys) restricts the claim to devices
        the caller can actually probe: a pool must not consume — and
        restart the cooldown of — another pool's device's due-ness,
        or a multi-pool process would postpone that device's
        re-admission forever without ever running its probe.  A
        not-claimed due device keeps its elapsed cooldown (the board
        stays probe-ready for whoever CAN reach it)."""
        now = self._clock()
        if now < self.next_probe_due:
            return []
        cand = (
            None if candidates is None
            else {device_key(c) for c in candidates}
        )
        due = []
        with self._lock:
            nxt = float("inf")
            for key, d in self._dev.items():
                if d.state != PROBATION:
                    continue
                if (cand is None or key in cand) and (
                    now - d.since >= self.cooldown_s
                ):
                    due.append(key)
                    d.since = now
                    d.probes += 1
                nxt = min(nxt, d.since + self.cooldown_s)
            self.next_probe_due = nxt
        return due

    def readmit(self, device, tracer=None) -> None:
        """A probation device passed its known-answer probe: score
        resets and it rejoins the placeable pool."""
        key = device_key(device)
        tr = tracer if tracer is not None else tele.TRACE
        with self._lock:
            d = self._dev.get(key)
            if d is None or d.state != PROBATION:
                return
            d.state = HEALTHY
            d.score = 0.0
            d.since = self._clock()
            d.t_score = d.since
            d.reason = ""
            tr.count(tele.C_HEALTH_READMITTED)
            tr.record_health(key, HEALTHY, 0.0, "probe passed")
        log.warning(
            "device %s health: re-admission probe passed — rejoining "
            "the pool", key,
        )

    def probe_failed(self, device, tracer=None) -> None:
        """The re-admission probe returned wrong bits or raised: the
        chip graduates from probation to evicted (the caller routes it
        through ``DevicePool.evict`` so replay bookkeeping engages)."""
        key = device_key(device)
        tr = tracer if tracer is not None else tele.TRACE
        with self._lock:
            d = self._slot_locked(key)
            d.state = EVICTED
            d.since = self._clock()
            tr.count(tele.C_HEALTH_PROBE_FAILED)
            tr.record_health(key, EVICTED, d.score,
                             "re-admission probe failed")
            self._pending_incidents.append(
                (key, EVICTED, "re-admission probe failed", tr)
            )
        self._flush_incidents()
        log.error(
            "device %s health: re-admission probe FAILED — evicting",
            key,
        )

    # ---- reporting -----------------------------------------------------
    def states(self) -> dict:
        """``{device key: state}`` for every tracked device (the
        heartbeat's ``device_health`` field; {} when nothing tracked)."""
        with self._lock:
            now = self._clock()
            out = {}
            for key, d in self._dev.items():
                self._decay_locked(d, now)
                out[key] = d.state
            return out

    def status(self) -> dict:
        """Full per-device view (scheduler status / debugging)."""
        with self._lock:
            now = self._clock()
            out = {}
            for key, d in sorted(self._dev.items()):
                self._decay_locked(d, now)
                out[key] = {
                    "state": d.state,
                    "score": round(d.score, 3),
                    "signals": dict(d.signals),
                    "probes": d.probes,
                    "reason": d.reason,
                }
            return out

    def publish(self, tracer) -> None:
        """Record every tracked device's current state into ``tracer``'s
        health ledger (the run-end snapshot the analyzer's "Device
        health" section renders).  ``transition=False``: publishing a
        state the board already held is not movement — only live
        transition events count, or a serve process would inflate the
        count by one per job publish."""
        for key, row in self.status().items():
            tracer.record_health(key, row["state"], row["score"],
                                 row["reason"], transition=False)

    def reset(self) -> None:
        """Test hook: forget every device and latency pool."""
        with self._lock:
            self._dev.clear()
            self._lat.clear()
            self._pending_incidents.clear()
            self.next_probe_due = float("inf")


#: The process-wide board (the ``telemetry.TRACE`` pattern): health is
#: a property of the HARDWARE, so it must span runs, jobs and tenants.
BOARD = HealthBoard()


def reset_board() -> None:
    """Test hook: clear the process-wide board."""
    BOARD.reset()


# ---------------------------------------------------------------------------
# Known-answer re-admission probe
# ---------------------------------------------------------------------------
_PROBE_JIT = None
_PROBE_ARGS = None


def probe_known_answer(device) -> bool:
    """The re-admission probe: one small **integer** matmul dispatched
    on ``device`` whose result must come back bit-exact against the
    host numpy product (int32 accumulation is exact on every backend —
    no float tolerance to hide a flipped mantissa bit behind).  The jit
    executable compiles once per process and is prewarmed by the first
    probe; the fetch rides ``transfer.device_fetch`` (deadline watchdog
    + retry), so a hung probation chip reads as a failed probe, not a
    wedged pool.  Returns False on ANY failure — a probe must never
    escalate."""
    global _PROBE_JIT, _PROBE_ARGS
    try:
        import jax
        import numpy as np

        from adam_tpu.utils.transfer import device_fetch

        if _PROBE_ARGS is None:
            rng = np.random.default_rng(0xADA)
            _PROBE_ARGS = (
                rng.integers(0, 127, size=(64, 64), dtype=np.int32),
                rng.integers(0, 127, size=(64, 64), dtype=np.int32),
            )
        a, b = _PROBE_ARGS
        expect = a.astype(np.int64) @ b.astype(np.int64)
        if _PROBE_JIT is None:
            _PROBE_JIT = jax.jit(
                lambda x, y: x.astype("int64") @ y.astype("int64")
            )
        da = jax.device_put(a, device)
        db = jax.device_put(b, device)
        got = device_fetch(_PROBE_JIT(da, db))
        return bool(np.array_equal(np.asarray(got), expect))
    except Exception as e:
        log.warning("known-answer probe failed to run: %s", e)
        return False
