"""Per-kernel microbench harness (docs/PERF.md "Megakernel tier").

The end-to-end bench (``bench.py``) measures the pipeline; nothing
measured the *kernels* — so a Pallas port or a fusion could regress one
inner loop and the signal would drown in ingest/write noise.  This
module times each registered device kernel in isolation, per kernel
backend (``ADAM_TPU_KERNEL_BACKEND``, ``ops/kernel_backend``) and per
grid bucket, with the classic simple-timeit shape: one untimed warmup
dispatch (compile), then ``iters`` timed dispatches each blocked to
completion.

The result is a stable-schema JSON document (:data:`SCHEMA`):

``{"schema": ..., "jax_backend": "cpu"|"tpu"|..., "rows": [
    {"kernel", "backend", "mode", "g", "gl", "iters",
     "mean_s", "best_s"}, ...]}``

``mode`` is ``"compiled"`` or ``"interpret"`` — Pallas rows run in
interpret mode off-TPU (bit-parity, uselessly slow: a correctness rail,
not a perf number; the smoke harness asserts the schema either way and
``bench.py`` embeds the document under the secondary line's
``"kernels"`` key so ``scripts/bench-diff`` can gate
``kernels.<kernel>.<backend>.g<g>x<gl>.mean_s`` on real hardware).

``scripts/kernel-bench`` is the CLI wrapper.
"""

from __future__ import annotations

import time

import numpy as np

SCHEMA = "adam_tpu.kernelbench/1"

#: Default grid buckets (rows, lanes) — small enough for the CPU
#: interpret rail, pow2-quantized like the streamed windows' grids.
DEFAULT_GRIDS = ((256, 128),)

KERNELS = ("observe", "pack", "apply", "fused_bc")
BACKENDS = ("xla", "pallas")


def _synth(g: int, gl: int, n_rg: int, seed: int = 7) -> dict:
    """Deterministic synthetic window at grid (g, gl) — realistic
    payload densities (the scatter/gather costs are shape-dominated,
    but all-zero masks would let an optimizer elide the interesting
    work)."""
    from adam_tpu.ops.colpack import pack_mask_bits

    rng = np.random.default_rng(seed)
    residue_ok = rng.random((g, gl)) < 0.95
    is_mm = rng.random((g, gl)) < 0.01
    return {
        "bases": rng.integers(0, 4, (g, gl), dtype=np.uint8),
        "quals": rng.integers(2, 40, (g, gl), dtype=np.uint8),
        "lengths": np.full((g,), gl, np.int32),
        "flags": np.zeros((g,), np.int32),
        "read_group_idx": rng.integers(
            0, max(n_rg - 1, 1), (g,), dtype=np.int32
        ),
        "res_bits": pack_mask_bits(residue_ok),
        "mm_bits": pack_mask_bits(is_mm),
        "read_ok": np.ones((g,), bool),
        "has_qual": np.ones((g,), bool),
        "valid": np.ones((g,), bool),
        "table": rng.integers(
            2, 40, (n_rg, 94, 2 * gl + 1, 17), dtype=np.uint8
        ),
    }


def _build(kernel: str, g: int, gl: int, n_rg: int):
    """-> zero-arg dispatch thunk for one (kernel, grid) pair, args
    pre-placed so the timed region is dispatch+execute only."""
    import jax

    from adam_tpu.pipelines.bqsr import jit_variant

    a = _synth(g, gl, n_rg)
    put = jax.device_put
    row5 = tuple(put(a[k]) for k in (
        "bases", "quals", "lengths", "flags", "read_group_idx"
    ))
    if kernel == "observe":
        args = row5 + (
            put(a["res_bits"]), put(a["mm_bits"]), put(a["read_ok"]),
        )
        return lambda: jit_variant("observe_packed", False)(
            *args, n_rg, gl
        )
    if kernel == "pack":
        from adam_tpu.ops.colpack import pack_rows_kernel

        mat = put(a["quals"])
        lens = put(a["lengths"].astype(np.int64))
        return lambda: pack_rows_kernel(mat, lens, g * gl)
    if kernel == "apply":
        args = row5 + (
            put(a["has_qual"]), put(a["valid"]), put(a["table"]),
        )
        return lambda: jit_variant("apply_pack2", False)(
            *args, gl, g * gl
        )
    if kernel == "fused_bc":
        args = row5 + (
            put(a["res_bits"]), put(a["mm_bits"]), put(a["read_ok"]),
            put(a["has_qual"]), put(a["valid"]), put(a["table"]),
        )
        return lambda: jit_variant("fused_bc", False)(
            *args, n_rg, gl, g * gl
        )
    raise ValueError(f"unknown kernel {kernel!r}")


def _timeit(thunk, iters: int) -> tuple:
    """simple-timeit: one untimed warmup (compile), then ``iters``
    dispatches each blocked to completion -> (mean_s, best_s)."""
    import jax

    jax.block_until_ready(thunk())
    walls = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(thunk())
        walls.append(time.perf_counter() - t0)
    return sum(walls) / len(walls), min(walls)


def run_kernelbench(
    grids=DEFAULT_GRIDS, iters: int = 5, n_rg: int = 3,
    kernels=KERNELS, backends=BACKENDS,
) -> dict:
    """Run the registered kernels across ``backends`` x ``grids`` ->
    the :data:`SCHEMA` document.  A backend/kernel that fails to build
    or dispatch contributes an ``"error"`` row instead of killing the
    sweep (the bench artifact must survive a broken port — that IS the
    signal)."""
    import jax

    from adam_tpu.ops.kernel_backend import backend_scope, pallas_interpret

    rows = []
    for bk in backends:
        mode = (
            "interpret" if bk == "pallas" and pallas_interpret()
            else "compiled"
        )
        with backend_scope(bk):
            for kernel in kernels:
                for g, gl in grids:
                    row = {
                        "kernel": kernel, "backend": bk, "mode": mode,
                        "g": int(g), "gl": int(gl), "iters": int(iters),
                    }
                    try:
                        mean_s, best_s = _timeit(
                            _build(kernel, g, gl, n_rg), iters
                        )
                        row["mean_s"] = mean_s
                        row["best_s"] = best_s
                    except Exception as e:  # keep the sweep alive
                        row["error"] = f"{type(e).__name__}: {e}"
                    rows.append(row)
    return {
        "schema": SCHEMA,
        "jax_backend": jax.default_backend(),
        "rows": rows,
    }
