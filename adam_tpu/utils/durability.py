"""Durable-write primitives shared by every crash-consistency seam.

The atomic-rename protocol (PR 4's writer contract: write to a temp
name, publish with ``os.replace``) guarantees readers never observe a
torn file — but rename alone is only *crash-consistent*, not *durable*:
on a power loss (or a dirtied-page-cache host death) some filesystems
may persist the rename before the file's data blocks, publishing a torn
part under the final name.  The fix is the classic three-step::

    fsync(tmp)          # the bytes are on disk before the name moves
    os.replace(tmp, dst)
    fsync(dir(dst))     # the directory entry (the rename) is on disk

Everything that publishes a durability-bearing artifact — Parquet parts
(``io/parquet.py``), checkpoint manifests and the streamed run journal
(``pipelines/checkpoint.py``) — routes through these helpers, so the
guarantee lives in one place (documented in docs/ROBUSTNESS.md).

``fsync_dir`` is best-effort: some filesystems (and all of Windows)
refuse ``open(dir)``/``fsync`` — degrading to plain atomic-rename
semantics there is correct, losing only the power-loss window.
"""

from __future__ import annotations

import json
import os


def fsync_file(path: str) -> None:
    """fsync an already-written file by path."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """Best-effort directory fsync (persists renames/creates within)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def publish_file(tmp: str, dst: str) -> None:
    """Durably publish ``tmp`` as ``dst``: fsync the data, atomically
    rename, fsync the destination directory.  After this returns the
    complete file survives a power loss; a crash at any earlier point
    leaves ``dst`` untouched (either absent or its previous version)."""
    fsync_file(tmp)
    os.replace(tmp, dst)
    fsync_dir(os.path.dirname(os.path.abspath(dst)))


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Durable whole-file write via temp + :func:`publish_file`.  The
    temp name is deterministic (``<path>.tmp``) — callers own the
    directory and serialize their own writes, so a stale temp from a
    crashed predecessor is simply overwritten."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as fh:
            fh.write(data)
        publish_file(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj) -> None:
    atomic_write_bytes(path, json.dumps(obj).encode())
