"""FASTQ ingest and export.

Covers the reference's ``converters/FastqRecordConverter.scala`` (paired /
unpaired / interleaved semantics, :27-156) and the record-boundary logic of
the Java Hadoop input formats
(``io/SingleFastqInputFormat.java``, ``io/InterleavedFastqInputFormat.java``)
— including multi-line records, where sequence/quality may wrap across
lines.  The golden ``*.fq.output`` / ``*.ifq.output`` fixtures in the
reference test tree delimit the records those input formats produce; the
splitter here reproduces the same record boundaries.
"""

from __future__ import annotations

import gzip
from typing import Iterator, Optional

from adam_tpu.formats import schema
from adam_tpu.formats.batch import ReadBatch, ReadSidecar, pack_reads
from adam_tpu.io.sam import SamHeader


def _open(path: str, mode="rt"):
    return gzip.open(path, mode) if str(path).endswith(".gz") else open(path, mode)


def _parse_one(lines: list[str], i: int) -> tuple[tuple[str, str, str], int]:
    """Parse one (possibly multi-line) record at line i -> (record, next_i).

    A record starts at '@'; sequence lines accumulate until the '+'
    separator; quality lines accumulate until their length matches the
    sequence (the rule the reference's input formats implement for
    multi-line FASTQ).
    """
    n = len(lines)
    line = lines[i].rstrip("\n")
    if not line.startswith("@"):
        raise ValueError(f"malformed FASTQ at line {i + 1}: {line[:50]!r}")
    name = line
    i += 1
    seq_parts = []
    while i < n and not lines[i].startswith("+"):
        if lines[i].startswith("@"):  # ran into the next name line: no '+'
            raise ValueError(f"FASTQ record {name!r} has no '+' separator")
        seq_parts.append(lines[i].rstrip("\n"))
        i += 1
    if i >= n:
        raise ValueError(f"FASTQ record {name!r} truncated before '+'")
    i += 1  # skip '+' line
    seq = "".join(seq_parts)
    qual_parts: list[str] = []
    qlen = 0
    while i < n and qlen < len(seq):
        q = lines[i].rstrip("\n")
        qual_parts.append(q)
        qlen += len(q)
        i += 1
    qual = "".join(qual_parts)
    if len(qual) != len(seq) or not seq:
        raise ValueError(
            f"FASTQ record {name!r}: qual length {len(qual)} != seq {len(seq)}"
        )
    return (name, seq, qual), i


def find_record_start(
    lines: list[str], interleaved: bool = False, start: int = 0
) -> int:
    """First line index where a well-formed record begins.

    This is the split-resync rule of the reference's Hadoop input formats
    (SingleFastqInputFormat.java / InterleavedFastqInputFormat.java): a
    split may open mid-record; scan forward to the next parseable record
    start — for interleaved files, to the next first-of-pair ('/1') record
    so pairs stay intact.  Returns len(lines) if none found.
    """
    for i in range(start, len(lines)):
        if not lines[i].startswith("@"):
            continue
        try:
            (name, _, _), _ = _parse_one(lines, i)
        except ValueError:
            continue
        if interleaved and not name.rstrip("\n").endswith("/1"):
            continue
        return i
    return len(lines)


def split_fastq_records(
    lines: list[str], resync: bool = False, interleaved: bool = False
) -> Iterator[tuple[str, str, str]]:
    """Yield (name_line, seq, qual) records.

    With ``resync=True``, leading junk (a partial record from a split
    boundary) is skipped instead of raising.
    """
    i = find_record_start(lines, interleaved) if resync else 0
    n = len(lines)
    while i < n:
        if not lines[i].rstrip("\n"):
            i += 1
            continue
        rec, i = _parse_one(lines, i)
        yield rec


def _strip_pair_suffix(name: str) -> tuple[str, Optional[int]]:
    """'@read/1' -> ('read', 1); no suffix -> (name, None)."""
    name = name[1:] if name.startswith("@") else name
    if len(name) > 1 and name[-2] == "/" and name[-1] in "12":
        return name[:-2], int(name[-1])
    return name, None


def read_fastq(
    path: str,
    set_first_of_pair: bool = False,
    set_second_of_pair: bool = False,
    round_rows_to: int = 1,
) -> tuple[ReadBatch, ReadSidecar, SamHeader]:
    """Unpaired FASTQ -> unmapped reads.

    ``set_first/second_of_pair`` mirror loadUnpairedFastq's flags for
    loading one mate file of a pair.
    """
    with _open(path) as fh:
        lines = fh.read().splitlines()
    records = []
    for name_line, seq, qual in split_fastq_records(lines, resync=True):
        name, _ = _strip_pair_suffix(name_line)
        flags = schema.FLAG_UNMAPPED
        if set_first_of_pair or set_second_of_pair:
            flags |= schema.FLAG_PAIRED | schema.FLAG_MATE_UNMAPPED
            flags |= (
                schema.FLAG_FIRST_OF_PAIR
                if set_first_of_pair
                else schema.FLAG_SECOND_OF_PAIR
            )
        records.append(
            dict(name=name, flags=flags, seq=seq, qual=qual, cigar="*",
                 contig_idx=-1, start=-1, mapq=255)
        )
    batch, side = pack_reads(records, round_rows_to=round_rows_to)
    return batch, side, SamHeader()


def read_interleaved_fastq(
    path: str, round_rows_to: int = 1, stringency="strict"
) -> tuple[ReadBatch, ReadSidecar, SamHeader]:
    """Interleaved paired FASTQ: records alternate mate1/mate2.

    Pairing is validated by name (after stripping /1 /2), matching
    FastqRecordConverter.convertPair's check; ``stringency`` softens the
    failure to a warning (LENIENT) or nothing (SILENT), keeping the pair.
    """
    from adam_tpu.utils.validation import handle

    with _open(path) as fh:
        lines = fh.read().splitlines()
    recs = list(split_fastq_records(lines, resync=True, interleaved=True))
    if len(recs) % 2:
        handle(
            stringency,
            f"{path}: odd number of FASTQ records in interleaved file",
        )
        recs = recs[:-1]
    records = []
    for k in range(0, len(recs), 2):
        (n1, s1, q1), (n2, s2, q2) = recs[k], recs[k + 1]
        name1, _ = _strip_pair_suffix(n1)
        name2, _ = _strip_pair_suffix(n2)
        if name1 != name2:
            handle(
                stringency,
                f"interleaved FASTQ pair mismatch: {name1!r} vs {name2!r}",
            )
        base = schema.FLAG_PAIRED | schema.FLAG_UNMAPPED | schema.FLAG_MATE_UNMAPPED
        records.append(
            dict(name=name1, flags=base | schema.FLAG_FIRST_OF_PAIR, seq=s1,
                 qual=q1, cigar="*", contig_idx=-1, start=-1, mapq=255)
        )
        records.append(
            dict(name=name2, flags=base | schema.FLAG_SECOND_OF_PAIR, seq=s2,
                 qual=q2, cigar="*", contig_idx=-1, start=-1, mapq=255)
        )
    batch, side = pack_reads(records, round_rows_to=round_rows_to)
    return batch, side, SamHeader()


# --------------------------------------------------------------------------
# Export (AlignmentRecordConverter.convertToFastq semantics: reads on the
# reverse strand are reverse-complemented back to sequencer orientation,
# names get /1 /2 suffixes when paired).
# --------------------------------------------------------------------------
def format_fastq_record(
    name: str,
    bases,
    quals,
    length: int,
    flags: int,
    add_suffix: bool = True,
) -> str:
    import numpy as np

    codes = np.asarray(bases)[:length]
    phred = np.asarray(quals)[:length]
    if flags & schema.FLAG_REVERSE:
        codes = schema.BASE_COMPLEMENT[codes][::-1]
        phred = phred[::-1]
    suffix = ""
    if add_suffix and (flags & schema.FLAG_PAIRED):
        suffix = "/1" if (flags & schema.FLAG_FIRST_OF_PAIR) else "/2"
    return (
        f"@{name}{suffix}\n"
        f"{schema.decode_bases(codes)}\n+\n{schema.decode_quals(phred)}"
    )


def write_fastq(
    path: str,
    batch: ReadBatch,
    side: ReadSidecar,
    add_suffix: bool = True,
    predicate=None,
    row_mask=None,
) -> None:
    import numpy as np

    b = batch.to_numpy()
    select = np.asarray(b.valid).copy()
    if row_mask is not None:
        select &= np.asarray(row_mask, bool)
    if predicate is not None:
        flags = np.asarray(b.flags)
        select &= np.fromiter(
            (bool(predicate(int(f))) for f in flags), bool, len(flags)
        )

    from adam_tpu import native

    nat = (
        native.fastq_encode(b, side, select, add_suffix)
        if not str(path).endswith(".gz")
        else None
    )
    if nat is not None:
        with open(path, "wb") as fh:
            fh.write(nat)
        return

    with _open(path, "wt") as fh:
        for i in np.flatnonzero(select):
            fh.write(
                format_fastq_record(
                    side.names[i], b.bases[i], b.quals[i], int(b.lengths[i]),
                    int(b.flags[i]), add_suffix,
                )
                + "\n"
            )


def write_paired_fastq(
    path1: str, path2: str, batch: ReadBatch, side: ReadSidecar,
    stringency="lenient",
) -> None:
    """Split pairs into two files (adamSaveAsPairedFastq,
    AlignmentRecordRDDFunctions.scala:386-464).

    Pairing validation follows the reference's ValidationStringency:
    read names must occur exactly twice (suffix-stripped) and no read may
    carry both first- and second-of-pair — STRICT raises with the
    reference's "don't occur exactly twice" report, LENIENT logs and
    writes only the properly paired records, SILENT just filters.
    """
    import logging

    import numpy as np

    from adam_tpu.formats.strings import StringColumn
    from adam_tpu.utils.validation import handle

    b = batch.to_numpy()
    flags = np.asarray(b.flags)
    valid = np.asarray(b.valid)
    names = StringColumn.of(side.names)
    fixed = names.to_fixed_bytes()
    # suffix-stripped grouping key (readNameHasPairedSuffix drop of /1 /2)
    keys = np.array(
        [
            k[:-2] if k.endswith((b"/1", b"/2")) else k
            for k in fixed
        ]
    )
    keys = np.where(valid, keys, b"")
    uniq, inv, counts = np.unique(keys, return_inverse=True, return_counts=True)
    n_per_read = counts[inv]
    bad = valid & (n_per_read != 2)
    if bad.any():
        bad_names = np.unique(keys[bad])[:100]
        handle(
            stringency,
            "Found %d read names that don't occur exactly twice\n\nSamples:\n\t%s"
            % (
                len(np.unique(keys[bad])),
                "\n\t".join(x.decode("utf-8", "replace") for x in bad_names),
            ),
        )
    both = (
        valid
        & ((flags & schema.FLAG_FIRST_OF_PAIR) != 0)
        & ((flags & schema.FLAG_SECOND_OF_PAIR) != 0)
    )
    if both.any():
        handle(
            stringency,
            "Read %s found with first- and second-of-pair set"
            % fixed[both.argmax()].decode("utf-8", "replace"),
        )
    paired = valid & (n_per_read == 2) & ~both
    n_first = int((paired & ((flags & schema.FLAG_FIRST_OF_PAIR) != 0)).sum())
    n_second = int((paired & ((flags & schema.FLAG_SECOND_OF_PAIR) != 0)).sum())
    logging.getLogger("adam_tpu.io.fastq").info(
        "%d/%d records are properly paired: %d firsts, %d seconds",
        int(paired.sum()), int(valid.sum()), n_first, n_second,
    )
    write_fastq(
        path1, batch, side,
        predicate=lambda f: bool(f & schema.FLAG_FIRST_OF_PAIR),
        row_mask=paired,
    )
    write_fastq(
        path2, batch, side,
        predicate=lambda f: bool(f & schema.FLAG_SECOND_OF_PAIR),
        row_mask=paired,
    )
