"""Load dispatch — the ADAMContext analog.

Format sniffing by file extension, mirroring the dispatch of
``rdd/ADAMContext.loadAlignments`` (:484-511): .sam/.bam -> SAM/BAM codec,
.ifq -> interleaved FASTQ, .fq/.fastq -> unpaired FASTQ, .fa/.fasta ->
FASTA fragments converted to unaligned reads, anything else -> Parquet.
"""

from __future__ import annotations

from typing import Optional, Sequence

from adam_tpu.api.datasets import AlignmentDataset
from adam_tpu.formats import schema
from adam_tpu.formats.batch import pack_reads
from adam_tpu.io.sam import SamHeader


def load_bam(path: str, **kw) -> AlignmentDataset:
    from adam_tpu.io import sam

    batch, side, header = sam.read_bam(path, **kw)
    return AlignmentDataset(batch, side, header)


def load_sam(path: str, **kw) -> AlignmentDataset:
    from adam_tpu.io import sam

    batch, side, header = sam.read_sam(path, **kw)
    return AlignmentDataset(batch, side, header)


def load_fastq(path: str, **kw) -> AlignmentDataset:
    from adam_tpu.io import fastq

    batch, side, header = fastq.read_fastq(path, **kw)
    return AlignmentDataset(batch, side, header)


def load_interleaved_fastq(path: str, **kw) -> AlignmentDataset:
    from adam_tpu.io import fastq

    batch, side, header = fastq.read_interleaved_fastq(path, **kw)
    return AlignmentDataset(batch, side, header)


def load_paired_fastq(path1: str, path2: str) -> AlignmentDataset:
    from adam_tpu.formats.batch import ReadBatch, ReadSidecar
    from adam_tpu.io import fastq

    b1, s1, _ = fastq.read_fastq(path1, set_first_of_pair=True)
    b2, s2, _ = fastq.read_fastq(path2, set_second_of_pair=True)
    return AlignmentDataset(
        ReadBatch.concat([b1, b2]), ReadSidecar.concat([s1, s2]), SamHeader()
    )


def load_fasta(path: str, fragment_length: int = 10_000):
    """FASTA -> (FragmentBatch, SequenceDictionary, descriptions)."""
    from adam_tpu.io import fasta

    return fasta.read_fasta(path, fragment_length)


def fragments_to_alignments(fragments, seq_dict) -> AlignmentDataset:
    """FragmentBatch -> synthetic reads dataset (the `toReads` role,
    rdd/contig/NucleotideContigFragmentRDDFunctions.scala:49, merging
    adjacent fragments per FragmentConverter.scala:100)."""
    from adam_tpu.formats.fragments import to_read_records

    records = to_read_records(fragments, seq_dict.names)
    batch, side = pack_reads(records)
    header = SamHeader(seq_dict=seq_dict)
    return AlignmentDataset(batch, side, header)


def load_fasta_reads(path: str, fragment_length: int = 10_000) -> AlignmentDataset:
    """FASTA contigs as synthetic reads (loadAlignments .fa branch,
    rdd/ADAMContext.scala:497-500: loadFasta(...).toReads)."""
    from adam_tpu.io import fasta

    fragments, seq_dict, _ = fasta.read_fasta(
        path, fragment_length=fragment_length
    )
    return fragments_to_alignments(fragments, seq_dict)


def load_parquet_alignments(
    path: str,
    projection: Optional[Sequence[str]] = None,
    predicate=None,
    **kw,
) -> AlignmentDataset:
    from adam_tpu.io import parquet

    batch, side, header = parquet.load_alignments(
        path, projection=projection, predicate=predicate, **kw
    )
    return AlignmentDataset(batch, side, header)


def load_vcf(path: str, **kw):
    """VCF -> GenotypeDataset (loadVcf, rdd/ADAMContext.scala:311-335)."""
    from adam_tpu.api.datasets import GenotypeDataset

    return GenotypeDataset.load(path, **kw)


def load_genotypes(path: str, **kw):
    """Dispatcher over genotype sources (loadGenotypes analog)."""
    return load_vcf(path, **kw)


def load_header(path: str) -> SamHeader:
    """Header-only peek (sequence dictionary / read groups) without
    materializing the reads — the role of SAMFileHeader probes in the
    reference's loaders (ADAMContext.scala:236-257)."""
    p = str(path)
    multi = _expand_multi(p)
    if multi is not None and (len(multi) > 1 or multi[0] != p):
        # directory/glob of SAM/BAM: merge the per-file header peeks
        # (still rows-free), same union rules as load_alignments_multi
        return _merge_headers([load_header(f) for f in multi])
    base = p[:-3] if p.endswith(".gz") else p
    if base.endswith(".sam"):
        from adam_tpu.io import sam

        return sam.peek_sam_header(p)
    if base.endswith(".bam"):
        from adam_tpu.io import sam

        for _, _, header in sam.iter_bam_batches(p, batch_reads=1):
            return header
        return SamHeader()
    # Parquet stores carry the header in schema metadata: read it without
    # materializing any rows (the out-of-core consumers depend on this)
    try:
        import pyarrow.parquet as _pq

        from adam_tpu.io.parquet import _header_from_meta

        parts = _parquet_parts(p)
        meta = _pq.read_schema(parts[0] if parts else p).metadata
        header = _header_from_meta(meta)
        if len(header.seq_dict.names) or len(header.read_groups):
            return header
    except Exception:
        pass
    return load_alignments(path).header


def _merge_headers(headers):
    """Union of per-source headers (loadBam's header merge,
    rdd/ADAMContext.scala:236-257): sequence dictionaries and read-group
    dictionaries merge (conflicting contig lengths raise); no hd_line —
    a sort-order claim from one source does not hold for the union."""
    from adam_tpu.io.sam import SamHeader

    sd = headers[0].seq_dict
    rgd = headers[0].read_groups
    for h in headers[1:]:
        sd = sd.merge(h.seq_dict)
        rgd = rgd.merge(h.read_groups)
    return SamHeader(seq_dict=sd, read_groups=rgd)


def _parquet_parts(path: str) -> list[str]:
    """Ordered part files of a ``.adam`` part directory ([] when the
    path is not a directory) — the one place the part-naming convention
    lives."""
    import glob as _glob
    import os as _os

    if not _os.path.isdir(path):
        return []
    return sorted(
        _glob.glob(_os.path.join(path, "part-*.parquet"))
        or _glob.glob(_os.path.join(path, "part-*"))
    )


def _expand_multi(path: str) -> Optional[list[str]]:
    """Glob patterns and directories of SAM/BAM files -> ordered file
    list (None = single-source path).  A directory whose entries are
    Parquet parts stays a single source (pyarrow reads it as one
    dataset)."""
    import glob as _glob
    import os

    p = str(path)
    if any(ch in p for ch in "*?["):
        hits = sorted(_glob.glob(p))
        return hits or None
    if os.path.isdir(p):
        entries = sorted(
            os.path.join(p, e) for e in os.listdir(p)
            if e.endswith((".sam", ".bam", ".sam.gz", ".bam.gz"))
        )
        return entries or None
    return None


def load_alignments_multi(paths: Sequence[str], **kw) -> AlignmentDataset:
    """Load several alignment files as one dataset, merging their
    headers (loadBam's header union, rdd/ADAMContext.scala:236-257:
    every file's SequenceDictionary and RecordGroupDictionary merge,
    conflicting contig lengths fail) and re-indexing each batch's
    contig/mate-contig/read-group columns into the merged dictionaries.
    """
    import numpy as np

    from adam_tpu.formats.batch import ReadBatch, ReadSidecar

    parts = [load_alignments(p, **kw) for p in paths]
    merged = _merge_headers([part.header for part in parts])
    sd = merged.seq_dict
    rgd = merged.read_groups

    def remap(idx, m):
        idx = np.asarray(idx)
        if not len(m):
            return idx.astype(np.int32)
        return np.where(
            idx >= 0, m[np.clip(idx, 0, len(m) - 1)], idx
        ).astype(np.int32)

    batches, sides = [], []
    for part in parts:
        b = part.batch.to_numpy()
        cmap = np.array(
            [sd.index(nm) for nm in part.header.seq_dict.names], np.int32
        )
        gmap = np.array(
            [rgd.index(nm) for nm in part.header.read_groups.names], np.int32
        )
        batches.append(b.replace(
            contig_idx=remap(b.contig_idx, cmap),
            mate_contig_idx=remap(b.mate_contig_idx, cmap),
            read_group_idx=remap(b.read_group_idx, gmap),
        ))
        sides.append(part.sidecar)
    return AlignmentDataset(
        ReadBatch.concat(batches),
        ReadSidecar.concat(sides),
        SamHeader(seq_dict=sd, read_groups=rgd),
    )


def iter_alignment_batches(
    path: str, batch_reads: int = 262_144, projection=None
):
    """Windowed alignment reader: yields (ReadBatch, ReadSidecar,
    SamHeader) without ever holding the whole input — the streaming
    face of :func:`load_alignments` for out-of-core consumers
    (parallel/sharded_join, parallel/host_shuffle).

    SAM/BAM inputs stream through the windowed tokenizers; ``.adam``
    part directories yield one window per part file (``projection``
    pushes column pruning into the part reads); a single Parquet file —
    or a directory/glob of SAM/BAM files, which needs the merged-header
    re-indexing of :func:`load_alignments_multi` — yields once."""
    from adam_tpu.io import sam as sam_io

    p = str(path)
    base = p[:-3] if p.endswith(".gz") else p
    if base.endswith(".sam"):
        yield from sam_io.iter_sam_batches(p, batch_reads=batch_reads)
        return
    if base.endswith(".bam"):
        yield from sam_io.iter_bam_batches(p, batch_reads=batch_reads)
        return
    from adam_tpu.io import parquet as _parquet

    kw = {"projection": projection} if projection else {}
    parts = _parquet_parts(p)
    if parts:
        for part in parts:
            yield _parquet.load_alignments(part, **kw)
        return
    multi = _expand_multi(p)
    if multi is not None:
        # SAM/BAM directory or glob: when every file shares one
        # sequence dictionary (the common same-pipeline case), stream
        # each file's windows — contig ids already agree.  Divergent
        # dictionaries need the resident multi-loader's re-indexing;
        # warn, because that materializes the whole dataset.
        headers = [load_header(f) for f in multi]
        sq0 = headers[0].seq_dict.to_sam_header_lines()
        if all(h.seq_dict.to_sam_header_lines() == sq0
               for h in headers[1:]):
            # identical sequence dictionaries: stream per file, with
            # each file's read-group ids remapped into the merged RG
            # dictionary on the fly (per-sample @RG files are the
            # common multi-BAM shape; a full resident merge just for
            # an int remap would defeat the out-of-core contract)
            import numpy as np

            merged = _merge_headers(headers)
            rgd = merged.read_groups
            for f, h in zip(multi, headers):
                gmap = np.array(
                    [rgd.index(nm) for nm in h.read_groups.names],
                    np.int32,
                )
                identity = np.array_equal(
                    gmap, np.arange(len(gmap), dtype=np.int32)
                )
                for batch, side, _h in iter_alignment_batches(
                    f, batch_reads=batch_reads, projection=projection
                ):
                    if len(gmap) and not identity:
                        rg = np.asarray(batch.read_group_idx)
                        rg = np.where(
                            rg >= 0, gmap[np.clip(rg, 0, len(gmap) - 1)],
                            rg,
                        ).astype(np.int32)
                        batch = batch.replace(read_group_idx=rg)
                    yield batch, side, merged
            return
        import logging

        logging.getLogger(__name__).warning(
            "iter_alignment_batches(%s): %d sources with differing "
            "sequence dictionaries — falling back to a resident "
            "merged load (not out-of-core)", p, len(multi),
        )
        ds = load_alignments(p)
        yield ds.batch, ds.sidecar, ds.header
        return
    yield _parquet.load_alignments(p, **kw)


def load_alignments(
    path: str, stringency: Optional[str] = None, **kw
) -> AlignmentDataset:
    """``stringency`` is forwarded to the loaders that validate pairing
    (interleaved FASTQ); other formats ignore it — callers (the CLI's
    common ``-stringency`` flag) need not know the dispatch rule.

    Glob patterns and directories of SAM/BAM files load as ONE dataset
    with merged dictionaries (:func:`load_alignments_multi`)."""
    multi = _expand_multi(path)
    if multi is not None:
        if len(multi) == 1:
            return load_alignments(multi[0], stringency=stringency, **kw)
        if stringency is not None:
            kw["stringency"] = stringency
        return load_alignments_multi(multi, **kw)
    p = str(path)
    base = p[:-3] if p.endswith(".gz") else p
    if base.endswith(".sam"):
        return load_sam(path, **kw)
    if base.endswith(".bam"):
        return load_bam(path, **kw)
    if base.endswith(".ifq"):
        if stringency is not None:
            kw["stringency"] = stringency
        return load_interleaved_fastq(path, **kw)
    if base.endswith((".fq", ".fastq")):
        return load_fastq(path, **kw)
    if base.endswith((".fa", ".fasta")):
        return load_fasta_reads(path)
    # Parquet: contig-fragment stores become synthetic reads
    # (rdd/ADAMContext.scala:501-505 `*contig.adam` branch) — sniffed by
    # schema instead of filename so renamed stores still dispatch right
    try:
        import pyarrow.parquet as _pq

        names = set(_pq.read_schema(path).names)
    except Exception:
        names = set()
    if "fragmentSequence" in names:
        from adam_tpu.io import parquet as _parquet

        fragments, seq_dict, _ = _parquet.load_fragments(path)
        return fragments_to_alignments(fragments, seq_dict)
    return load_parquet_alignments(path, **kw)
