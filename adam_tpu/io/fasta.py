"""FASTA ingest/export.

Role of ``converters/FastaConverter.scala`` (:73-185): parse description
lines on the host, fragment sequences to fixed length, emit a
:class:`FragmentBatch` + :class:`SequenceDictionary`.
"""

from __future__ import annotations

import gzip
from typing import Optional

from adam_tpu.formats import schema
from adam_tpu.formats.fragments import FragmentBatch
from adam_tpu.models.dictionaries import SequenceDictionary, SequenceRecord


def _open(path: str, mode="rt"):
    return gzip.open(path, mode) if str(path).endswith(".gz") else open(path, mode)


def parse_fasta(text: str) -> list[tuple[str, Optional[str], str]]:
    """-> [(name, description_or_None, sequence)]."""
    out = []
    name = desc = None
    seq_parts: list[str] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith(";"):
            continue
        if line.startswith(">"):
            if name is not None or seq_parts:
                out.append((name or "", desc, "".join(seq_parts)))
            headline = line[1:].strip()
            if " " in headline:
                name, desc = headline.split(" ", 1)
            else:
                name, desc = headline, None
            seq_parts = []
        else:
            seq_parts.append(line)
    if name is not None or seq_parts:
        out.append((name or "", desc, "".join(seq_parts)))
    return out


def read_fasta(
    path: str, fragment_length: int = 10_000
) -> tuple[FragmentBatch, SequenceDictionary, list[Optional[str]]]:
    with _open(path) as fh:
        entries = parse_fasta(fh.read())
    seq_dict = SequenceDictionary(
        tuple(SequenceRecord(n, len(s)) for n, _, s in entries)
    )
    fragments = FragmentBatch.from_sequences(
        [(i, s) for i, (_, _, s) in enumerate(entries)], fragment_length
    )
    descriptions = [d for _, d, _ in entries]
    return fragments, seq_dict, descriptions


def write_fasta(
    path: str,
    fragments: FragmentBatch,
    seq_dict: SequenceDictionary,
    line_width: int = 60,
) -> None:
    import numpy as np

    b = fragments.to_numpy()
    with _open(path, "wt") as fh:
        for contig_idx, rec in enumerate(seq_dict):
            rows = [
                i
                for i in range(b.n_rows)
                if b.valid[i] and int(b.contig_idx[i]) == contig_idx
            ]
            rows.sort(key=lambda i: int(b.start[i]))
            seq = "".join(
                schema.decode_bases(b.bases[i][: int(b.lengths[i])]) for i in rows
            )
            fh.write(f">{rec.name}\n")
            for off in range(0, len(seq), line_width):
                fh.write(seq[off : off + line_width] + "\n")
