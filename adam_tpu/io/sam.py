"""SAM/BAM ingest and export.

Host-side codec producing/consuming the columnar :class:`ReadBatch`.
Covers the roles of the reference's ``converters/SAMRecordConverter.scala``
(SAM record -> ADAM record, :38-130), ``converters/AlignmentRecordConverter``
(ADAM -> SAM + header build, :40-200) and the hadoop-bam/htsjdk codecs it
delegates BAM decoding to — here a self-contained BGZF + BAM binary codec
(pure Python today; the hot tokenizer moves to C++ behind ctypes without
changing this module's API).

Positions: SAM text is 1-based; everything in adam_tpu is 0-based
end-exclusive (same convention as the reference's Avro records).
"""

from __future__ import annotations

import gzip
import io as _io
import struct
import zlib
from dataclasses import dataclass, field
from typing import Iterable, Iterator, Optional

import numpy as np

from adam_tpu.formats import schema
from adam_tpu.formats.batch import ReadBatch, ReadSidecar, pack_reads
from adam_tpu.models.dictionaries import (
    RecordGroupDictionary,
    SequenceDictionary,
)


@dataclass
class SamHeader:
    seq_dict: SequenceDictionary = field(default_factory=SequenceDictionary)
    read_groups: RecordGroupDictionary = field(default_factory=RecordGroupDictionary)
    hd_line: Optional[str] = None
    program_lines: list = field(default_factory=list)
    comment_lines: list = field(default_factory=list)

    @staticmethod
    def parse(lines: Iterable[str]) -> "SamHeader":
        hd = None
        sq, rg, pg, co = [], [], [], []
        for line in lines:
            if line.startswith("@HD"):
                hd = line.rstrip("\n")
            elif line.startswith("@SQ"):
                sq.append(line)
            elif line.startswith("@RG"):
                rg.append(line)
            elif line.startswith("@PG"):
                pg.append(line.rstrip("\n"))
            elif line.startswith("@CO"):
                co.append(line.rstrip("\n"))
        return SamHeader(
            seq_dict=SequenceDictionary.from_sam_header_lines(sq),
            read_groups=RecordGroupDictionary.from_sam_header_lines(rg),
            hd_line=hd,
            program_lines=pg,
            comment_lines=co,
        )

    def to_lines(self, sort_order: Optional[str] = None) -> list[str]:
        hd = self.hd_line or "@HD\tVN:1.5"
        if sort_order is not None:
            fields = [f for f in hd.split("\t") if not f.startswith("SO:")]
            hd = "\t".join(fields + [f"SO:{sort_order}"])
        out = [hd]
        out += self.seq_dict.to_sam_header_lines()
        out += [g.to_sam_header_line() for g in self.read_groups]
        out += self.program_lines
        out += self.comment_lines
        return out


def _parse_tags(
    tag_fields: list[str],
) -> tuple[str, Optional[str], Optional[str], Optional[str]]:
    """Split raw SAM tag fields into (other_tags, md, orig_qual, rg).

    MD/OQ/RG move to dedicated columns (the reference's
    mismatchingPositions/origQual/recordGroup* record fields,
    converters/SAMRecordConverter.scala:103-130) and are re-emitted from
    those columns on export, so they are stripped from the attribute
    string here.
    """
    md = oq = rg = None
    rest = []
    for f in tag_fields:
        if f.startswith("MD:Z:"):
            md = f[5:]
        elif f.startswith("OQ:Z:"):
            oq = f[5:]
        elif f.startswith("RG:Z:") and rg is None:
            rg = f[5:]
        else:
            rest.append(f)
    return "\t".join(rest), md, oq, rg


def iter_sam_records(text_lines: Iterable[str], header: SamHeader) -> Iterator[dict]:
    """SAM body lines -> record dicts for :func:`pack_reads`."""
    sd, rgd = header.seq_dict, header.read_groups
    for line in text_lines:
        if not line or line.startswith("@"):
            continue
        f = line.rstrip("\n").split("\t")
        qname, flag, rname, pos, mapq, cigar, rnext, pnext, tlen, seq, qual = f[:11]
        flags = int(flag)
        attrs, md, oq, rg = _parse_tags(f[11:])
        rg_idx = rgd.index_or(rg) if rg is not None else -1
        if rg is not None and rg_idx < 0:
            # RG naming a group absent from the header: keep the tag in
            # attrs so round-trip preserves it (rg_idx stays -1).
            tag = f"RG:Z:{rg}"
            attrs = f"{attrs}\t{tag}" if attrs else tag
        contig_idx = sd.index_or(rname) if rname != "*" else -1
        if rnext == "=":
            mate_contig_idx = contig_idx
        elif rnext == "*":
            mate_contig_idx = -1
        else:
            mate_contig_idx = sd.index_or(rnext)
        yield dict(
            name=qname,
            flags=flags,
            contig_idx=contig_idx,
            start=int(pos) - 1 if rname != "*" and int(pos) > 0 else -1,
            mapq=int(mapq),
            cigar=cigar,
            seq=seq,
            qual=qual,
            mate_contig_idx=mate_contig_idx,
            mate_start=int(pnext) - 1 if int(pnext) > 0 else -1,
            tlen=int(tlen),
            read_group_idx=rg_idx,
            attrs=attrs,
            md=md,
            orig_qual=oq,
        )


def _columns_to_batch(
    out: dict, round_rows_to: int = 1
) -> tuple[ReadBatch, ReadSidecar]:
    """Native tokenizer columns -> (ReadBatch, ReadSidecar)."""
    from adam_tpu.formats.strings import StringColumn

    n = out["n"]
    if n == 0:
        return ReadBatch.empty(), ReadSidecar()
    batch = ReadBatch(
        bases=out["bases"],
        quals=out["quals"],
        lengths=out["lengths"],
        flags=out["flags"],
        contig_idx=out["contig_idx"],
        start=out["start"],
        end=out["end"],
        mapq=out["mapq"],
        cigar_ops=out["cigar_ops"],
        cigar_lens=out["cigar_lens"],
        cigar_n=out["cigar_n"],
        mate_contig_idx=out["mate_contig_idx"],
        mate_start=out["mate_start"],
        tlen=out["tlen"],
        read_group_idx=out["rg_idx"],
        has_qual=out["has_qual"].astype(bool),
        valid=np.ones(n, dtype=bool),
    )
    side = ReadSidecar(
        names=StringColumn(out["name_buf"], out["name_off"]),
        attrs=StringColumn(out["attr_buf"], out["attr_off"]),
        md=StringColumn(
            out["md_buf"], out["md_off"], out["md_present"].astype(bool)
        ),
        orig_quals=StringColumn(
            out["oq_buf"], out["oq_off"], out["oq_present"].astype(bool)
        ),
    )
    nrows = ((n + round_rows_to - 1) // round_rows_to) * round_rows_to
    if nrows != n:
        batch = batch.pad_rows(nrows)
        pad = nrows - n
        side = ReadSidecar.concat(
            [side, ReadSidecar(names=[""] * pad, attrs=[""] * pad,
                               md=[None] * pad, orig_quals=[None] * pad)]
        )
    return batch, side


def _split_header_lines(data: bytes) -> tuple[list[str], int]:
    """'@'-prefixed header lines + body offset of a SAM byte buffer
    (the one header scan shared by every SAM entry point)."""
    body_off = 0
    header_lines = []
    while body_off < len(data) and data[body_off : body_off + 1] == b"@":
        nl = data.find(b"\n", body_off)
        end = nl if nl >= 0 else len(data)
        line = data[body_off:end]
        if line.endswith(b"\r"):
            line = line[:-1]
        header_lines.append(line.decode("utf-8", "replace"))
        body_off = end + 1
    return header_lines, body_off


def peek_sam_header(path: str) -> SamHeader:
    """Header-only SAM read: stream lines until the first record."""
    opener = gzip.open if str(path).endswith(".gz") else open
    lines = []
    with opener(path, "rt") as fh:
        for line in fh:
            if not line.startswith("@"):
                break
            lines.append(line.rstrip("\r\n"))
    return SamHeader.parse(lines)


def iter_sam_batches(path: str, batch_reads: int = 262_144):
    """Windowed SAM reader: yields (ReadBatch, ReadSidecar, SamHeader)
    chunks of ~``batch_reads`` records each (line-exact windowing).

    The text-SAM twin of :func:`iter_bam_batches`, sized so a streamed
    transform can overlap tokenization of window i+1 with compute on
    window i (the Bam2ADAM queue design, adam-cli Bam2ADAM.scala:55-111).
    Requires the native tokenizer; whole-file :func:`read_sam` is the
    fallback.
    """
    from adam_tpu import native

    if not native.available():
        batch, side, header = read_sam(path)
        yield batch, side, header
        return
    import os as _os

    if str(path).endswith(".gz"):
        with gzip.open(path, "rb") as fh:
            data = fh.read()
        buf = np.frombuffer(data, np.uint8)
    elif _os.path.getsize(path) == 0:
        yield ReadBatch.empty(), ReadSidecar(), SamHeader()
        return
    else:
        # file-backed mapping: the input's pages stay clean/reclaimable,
        # so a WGS-scale SAM doesn't pin its whole size in RSS while the
        # windows stream through
        buf = np.memmap(path, np.uint8, mode="r")
        data = buf
    hdr_probe = bytes(buf[: 1 << 20])
    header_lines, body_off = _split_header_lines(hdr_probe)
    if body_off >= len(hdr_probe) and len(buf) > len(hdr_probe):
        # pathological >1MB header: fall back to a full scan
        hdr_probe = bytes(buf)
        header_lines, body_off = _split_header_lines(hdr_probe)
    header = SamHeader.parse(header_lines)
    # window boundaries: every batch_reads-th line start (native memchr
    # walk; the numpy fallback scans the whole buffer for newlines)
    bounds = native.line_index_strided(buf, body_off, batch_reads)
    if bounds is None:
        ends = np.flatnonzero(buf[body_off:] == 10) + body_off + 1
        starts = np.concatenate([[body_off], ends])
        if starts[-1] < len(data):  # unterminated final line
            starts = np.concatenate([starts, [len(data)]])
        bounds = starts[:: batch_reads]
        if bounds[-1] != starts[-1]:
            bounds = np.concatenate([bounds, starts[-1:]])
    if len(bounds) < 2:
        yield ReadBatch.empty(), ReadSidecar(), header
        return
    for i in range(len(bounds) - 1):
        # a u8 view, not a bytes copy — tokenize_sam reads it in place
        chunk = buf[bounds[i] : bounds[i + 1]]
        out = native.tokenize_sam(
            chunk, 0, header.seq_dict.names, header.read_groups.names
        )
        if out is None:
            raise ValueError(f"{path}: malformed SAM records in window")
        batch, side = _columns_to_batch(out, 1)
        yield batch, side, header


def read_sam(
    path: str, round_rows_to: int = 1
) -> tuple[ReadBatch, ReadSidecar, SamHeader]:
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rb") as fh:
        data = fh.read()
    # split the header prefix off without touching the body
    header_lines, body_off = _split_header_lines(data)
    header = SamHeader.parse(header_lines)

    from adam_tpu import native

    out = native.tokenize_sam(
        data, body_off, header.seq_dict.names, header.read_groups.names
    )
    if out is not None:
        batch, side = _columns_to_batch(out, round_rows_to)
        return batch, side, header

    # pure-Python fallback (same semantics)
    lines = data.decode("utf-8", "replace").splitlines()
    records = list(iter_sam_records(lines, header))
    batch, side = pack_reads(records, round_rows_to=round_rows_to)
    return batch, side, header


# --------------------------------------------------------------------------
# SAM export (AlignmentRecordConverter.convert + createSAMHeader semantics)
# --------------------------------------------------------------------------
def format_sam_records(
    batch: ReadBatch, side: ReadSidecar, header: SamHeader
) -> Iterator[str]:
    b = batch.to_numpy()
    names = header.seq_dict.names
    rg_names = header.read_groups.names
    for i in range(b.n_rows):
        if not b.valid[i]:
            continue
        L = int(b.lengths[i])
        contig = int(b.contig_idx[i])
        mate_contig = int(b.mate_contig_idx[i])
        rname = names[contig] if contig >= 0 else "*"
        if mate_contig < 0:
            rnext = "*"
        elif mate_contig == contig and rname != "*":
            rnext = "="
        else:
            rnext = names[mate_contig]
        seq = schema.decode_bases(b.bases[i], L) if L else "*"
        qual = schema.decode_quals(b.quals[i][:L]) if L and b.has_qual[i] else "*"
        cigar = schema.decode_cigar(b.cigar_ops[i], b.cigar_lens[i], int(b.cigar_n[i]))
        tags = []
        if side.attrs[i]:
            tags.append(side.attrs[i])
        if side.md[i] is not None:
            tags.append(f"MD:Z:{side.md[i]}")
        if side.orig_quals[i]:
            tags.append(f"OQ:Z:{side.orig_quals[i]}")
        rg = int(b.read_group_idx[i])
        if rg >= 0:
            tags.append(f"RG:Z:{rg_names[rg]}")
        fields = [
            side.names[i],
            str(int(b.flags[i])),
            rname,
            str(int(b.start[i]) + 1 if int(b.start[i]) >= 0 else 0),
            str(int(b.mapq[i]) if int(b.mapq[i]) >= 0 else 0),
            cigar,
            rnext,
            str(int(b.mate_start[i]) + 1 if int(b.mate_start[i]) >= 0 else 0),
            str(int(b.tlen[i])),
            seq,
            qual,
        ]
        yield "\t".join(fields + tags)


def write_sam(
    path: str,
    batch: ReadBatch,
    side: ReadSidecar,
    header: SamHeader,
    sort_order: Optional[str] = None,
) -> None:
    from adam_tpu import native

    with open(path, "wb") as fh:
        for line in header.to_lines(sort_order=sort_order):
            fh.write(line.encode("utf-8") + b"\n")
        nat = native.sam_encode(
            batch, side, header.read_groups.names, header.seq_dict.names
        )
        if nat is not None:
            fh.write(nat)
            return
        for line in format_sam_records(batch, side, header):
            fh.write(line.encode("utf-8") + b"\n")


# --------------------------------------------------------------------------
# BAM (BGZF container + binary alignment records)
# --------------------------------------------------------------------------
_BAM_SEQ_CODES = "=ACMGRSVTWYHKDBN"
_BAM_SEQ_TO_CODE = np.full(16, schema.BASE_N, dtype=np.uint8)
for _i, _c in enumerate(_BAM_SEQ_CODES):
    if _c in "ACGT":
        _BAM_SEQ_TO_CODE[_i] = "ACGT".index(_c)
_CODE_TO_BAM_SEQ = np.array([1, 2, 4, 8, 15, 0], dtype=np.uint8)  # A C G T N PAD

BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000"
)


def bgzf_decompress(data: bytes) -> bytes:
    """Decode a BGZF container (concatenated gzip members).

    Uses the native block-parallel decoder when available; plain-gzip
    fallback handles non-BGZF gzip members too.
    """
    from adam_tpu import native

    out = native.bgzf_decompress(data)
    if out is not None:
        return out
    return gzip.decompress(data)


def bgzf_compress(data: bytes, block_size: int = 0xFF00) -> bytes:
    """Encode bytes as BGZF blocks + EOF marker.

    Uses the native block-parallel encoder when available.
    """
    from adam_tpu import native

    # BSIZE is a u16 (total block size - 1), so blocks can never exceed
    # 0x10000 bytes; clamp like the native encoder does
    block_size = min(max(1, block_size), 0xFF00)
    nat = native.bgzf_compress(data, block_size=block_size)
    if nat is not None:
        return nat
    out = bytearray()
    for off in range(0, len(data), block_size):
        chunk = data[off : off + block_size]
        co = zlib.compressobj(6, zlib.DEFLATED, -15)
        comp = co.compress(chunk) + co.flush()
        bsize = len(comp) + 25 + 1  # header(12)+extra(6)+deflate+crc(4)+isize(4)
        header = struct.pack(
            "<BBBBIBBHBBHH",
            0x1F, 0x8B, 8, 4,  # magic, CM=deflate, FLG.FEXTRA
            0, 0, 0xFF,        # mtime, xfl, os
            6,                 # xlen
            ord("B"), ord("C"), 2,
            bsize - 1,
        )
        out += header + comp + struct.pack("<II", zlib.crc32(chunk), len(chunk) & 0xFFFFFFFF)
    out += BGZF_EOF
    return bytes(out)


def _parse_bam_tags(buf: bytes) -> list[str]:
    """BAM binary tags -> SAM text tag fields."""
    tags = []
    off = 0
    n = len(buf)
    while off + 3 <= n:
        tag = buf[off : off + 2].decode("ascii")
        typ = chr(buf[off + 2])
        off += 3
        if typ == "A":
            tags.append(f"{tag}:A:{chr(buf[off])}")
            off += 1
        elif typ in "cCsSiI":
            fmt, size = {"c": ("<b", 1), "C": ("<B", 1), "s": ("<h", 2),
                         "S": ("<H", 2), "i": ("<i", 4), "I": ("<I", 4)}[typ]
            (v,) = struct.unpack_from(fmt, buf, off)
            tags.append(f"{tag}:i:{v}")
            off += size
        elif typ == "f":
            (v,) = struct.unpack_from("<f", buf, off)
            tags.append(f"{tag}:f:{v:g}")
            off += 4
        elif typ in "ZH":
            end = buf.index(0, off)
            tags.append(f"{tag}:{typ}:{buf[off:end].decode('ascii')}")
            off = end + 1
        elif typ == "B":
            sub = chr(buf[off])
            (cnt,) = struct.unpack_from("<I", buf, off + 1)
            fmt, size = {"c": ("<b", 1), "C": ("<B", 1), "s": ("<h", 2),
                         "S": ("<H", 2), "i": ("<i", 4), "I": ("<I", 4),
                         "f": ("<f", 4)}[sub]
            vals = [
                struct.unpack_from(fmt, buf, off + 5 + k * size)[0]
                for k in range(cnt)
            ]
            tags.append(f"{tag}:B:{sub}," + ",".join(str(v) for v in vals))
            off += 5 + cnt * size
        else:
            raise ValueError(f"unknown BAM tag type {typ!r}")
    return tags


def _parse_bam_header_blob(raw: bytes) -> tuple[SamHeader, int]:
    """Parse the BAM preamble (magic, header text, reference list) from a
    decompressed prefix -> (header, records offset).  Raises ValueError
    when ``raw`` is too short to contain the whole preamble."""
    if raw[:4] != b"BAM\x01":
        raise ValueError("not a BAM stream")
    if len(raw) < 8:
        raise ValueError("truncated BAM preamble")
    (l_text,) = struct.unpack_from("<i", raw, 4)
    if len(raw) < 8 + l_text + 4:
        raise ValueError("truncated BAM preamble")
    text = raw[8 : 8 + l_text].decode("utf-8", "replace").rstrip("\x00")
    off = 8 + l_text
    (n_ref,) = struct.unpack_from("<i", raw, off)
    off += 4
    from adam_tpu.models.dictionaries import SequenceRecord

    recs = []
    for _ in range(n_ref):
        if len(raw) < off + 4:
            raise ValueError("truncated BAM reference list")
        (l_name,) = struct.unpack_from("<i", raw, off)
        if len(raw) < off + 4 + l_name + 4:
            raise ValueError("truncated BAM reference list")
        name = raw[off + 4 : off + 4 + l_name - 1].decode("ascii")
        (l_ref,) = struct.unpack_from("<i", raw, off + 4 + l_name)
        recs.append(SequenceRecord(name, l_ref))
        off += 4 + l_name + 4
    header = SamHeader.parse(text.splitlines())
    if len(header.seq_dict) == 0 and recs:
        header.seq_dict = SequenceDictionary(tuple(recs))
    return header, off


def iter_bam_batches(
    path: str,
    batch_reads: int = 500_000,
    window_bytes: int = 32 * 1024 * 1024,
):
    """Constant-memory streaming BAM reader.

    Yields (ReadBatch, ReadSidecar, SamHeader) chunks of roughly
    ``batch_reads`` reads (window-granular): compressed windows are read
    off disk, their
    *complete* BGZF blocks decompressed (native block-parallel codec),
    and complete BAM records tokenized, carrying both the compressed and
    decompressed tails into the next window — so a WGS-scale BAM never
    has to fit in memory (the role of hadoop-bam's splitting reader).
    Requires the native codec (raises RuntimeError without it; the
    whole-file :func:`read_bam` is the fallback path).
    """
    from adam_tpu import native

    if not native.available():
        raise RuntimeError(
            "iter_bam_batches requires the native codec; "
            "use read_bam for the pure-Python whole-file path"
        )
    with open(path, "rb") as fh:
        comp_tail = b""
        raw_tail = b""
        header = None
        records_off = 0
        pending: list[tuple] = []
        pending_reads = 0
        eof = False
        while not eof:
            chunk = fh.read(window_bytes)
            if not chunk:
                eof = True
            comp = comp_tail + chunk
            if comp:
                got = native.bgzf_decompress_partial(comp)
                if got is None:
                    raise ValueError(f"{path}: not a BGZF/BAM file")
                blob, consumed = got
                if eof and consumed < len(comp):
                    raise ValueError(f"{path}: truncated BGZF block at EOF")
                comp_tail = comp[consumed:]
                raw = raw_tail + blob
            else:
                raw = raw_tail
            if header is None:
                try:
                    header, records_off = _parse_bam_header_blob(raw)
                except ValueError:
                    if eof:
                        raise
                    raw_tail = raw
                    continue  # need more data for the preamble
                raw = raw[records_off:]
            out = native.tokenize_bam(
                raw, 0, header.read_groups.names, partial=True
            )
            if out is None:
                raise ValueError(f"{path}: malformed BAM records")
            consumed = out.pop("consumed")
            if eof and consumed < len(raw):
                raise ValueError(f"{path}: truncated BAM record at EOF")
            raw_tail = raw[consumed:]
            n = len(out["flags"])
            if n:
                pending.append(out)
                pending_reads += n
            while pending_reads >= batch_reads or (eof and pending):
                take, taken = [], 0
                while pending and taken < batch_reads:
                    take.append(pending.pop(0))
                    taken += len(take[-1]["flags"])
                batches = [_columns_to_batch(o, 1) for o in take]
                if len(batches) == 1:
                    batch, side = batches[0]
                else:
                    batch = ReadBatch.concat([b for b, _ in batches])
                    side = ReadSidecar.concat([s for _, s in batches])
                pending_reads -= taken
                yield batch, side, header
                if not eof:
                    break


def read_bam(
    path: str, round_rows_to: int = 1
) -> tuple[ReadBatch, ReadSidecar, SamHeader]:
    with open(path, "rb") as fh:
        raw = bgzf_decompress(fh.read())
    if raw[:4] != b"BAM\x01":
        raise ValueError(f"{path}: not a BAM file")
    (l_text,) = struct.unpack_from("<i", raw, 4)
    text = raw[8 : 8 + l_text].decode("utf-8", "replace").rstrip("\x00")
    off = 8 + l_text
    (n_ref,) = struct.unpack_from("<i", raw, off)
    off += 4
    ref_names = []
    for _ in range(n_ref):
        (l_name,) = struct.unpack_from("<i", raw, off)
        name = raw[off + 4 : off + 4 + l_name - 1].decode("ascii")
        off += 4 + l_name + 4
        ref_names.append(name)
    header = SamHeader.parse(text.splitlines())
    # The header text is authoritative when present; otherwise synthesize
    # the dictionary from the binary reference list (lengths unknown -> 0
    # can't happen: binary list carries l_ref; re-read it if needed).
    if len(header.seq_dict) == 0 and n_ref:
        off2 = 8 + l_text + 4
        recs = []
        from adam_tpu.models.dictionaries import SequenceRecord

        for _ in range(n_ref):
            (l_name,) = struct.unpack_from("<i", raw, off2)
            name = raw[off2 + 4 : off2 + 4 + l_name - 1].decode("ascii")
            (l_ref,) = struct.unpack_from("<i", raw, off2 + 4 + l_name)
            recs.append(SequenceRecord(name, l_ref))
            off2 += 4 + l_name + 4
        header.seq_dict = SequenceDictionary(tuple(recs))

    from adam_tpu import native

    nat = native.tokenize_bam(raw, off, header.read_groups.names)
    if nat is not None:
        batch, side = _columns_to_batch(nat, round_rows_to)
        return batch, side, header

    records = []
    n = len(raw)
    while off + 4 <= n:
        (block_size,) = struct.unpack_from("<i", raw, off)
        rec = raw[off + 4 : off + 4 + block_size]
        off += 4 + block_size
        (
            ref_id, pos, l_read_name, mapq, _bin, n_cigar, flag, l_seq,
            next_ref, next_pos, tlen,
        ) = struct.unpack_from("<iiBBHHHiiii", rec, 0)
        p = 32
        name = rec[p : p + l_read_name - 1].decode("ascii")
        p += l_read_name
        cigar_ops = np.frombuffer(rec, dtype="<u4", count=n_cigar, offset=p)
        p += 4 * n_cigar
        cigar = (
            "".join(
                f"{int(c >> 4)}{schema.CIGAR_CHARS[int(c & 0xF)]}" for c in cigar_ops
            )
            if n_cigar
            else "*"
        )
        packed = np.frombuffer(rec, dtype=np.uint8, count=(l_seq + 1) // 2, offset=p)
        p += (l_seq + 1) // 2
        nib = np.empty(2 * len(packed), dtype=np.uint8)
        nib[0::2] = packed >> 4
        nib[1::2] = packed & 0xF
        seq = schema.decode_bases(_BAM_SEQ_TO_CODE[nib[:l_seq]]) if l_seq else "*"
        qual_raw = np.frombuffer(rec, dtype=np.uint8, count=l_seq, offset=p)
        p += l_seq
        qual = (
            schema.decode_quals(qual_raw) if l_seq and not (qual_raw == 0xFF).all() else "*"
        )
        tag_fields = _parse_bam_tags(rec[p:])
        attrs, md, oq, rg = _parse_tags(tag_fields)
        rg_idx = header.read_groups.index_or(rg) if rg is not None else -1
        if rg is not None and rg_idx < 0:
            tag = f"RG:Z:{rg}"
            attrs = f"{attrs}\t{tag}" if attrs else tag
        records.append(
            dict(
                name=name,
                flags=flag,
                contig_idx=ref_id,
                start=pos if ref_id >= 0 else -1,
                mapq=mapq,
                cigar=cigar,
                seq=seq,
                qual=qual,
                mate_contig_idx=next_ref,
                mate_start=next_pos if next_ref >= 0 else -1,
                tlen=tlen,
                read_group_idx=rg_idx,
                attrs=attrs,
                md=md,
                orig_qual=oq,
            )
        )
    batch, side = pack_reads(records, round_rows_to=round_rows_to)
    return batch, side, header


def _encode_bam_tags(attrs: str, md, oq, rg_name) -> bytes:
    out = bytearray()
    fields = [f for f in attrs.split("\t") if f] if attrs else []
    if md is not None:
        fields.append(f"MD:Z:{md}")
    if oq:
        fields.append(f"OQ:Z:{oq}")
    if rg_name:
        fields.append(f"RG:Z:{rg_name}")
    for f in fields:
        tag, typ, val = f.split(":", 2)
        out += tag.encode("ascii")
        if typ == "A":
            out += b"A" + val.encode("ascii")
        elif typ == "i":
            out += b"i" + struct.pack("<i", int(val))
        elif typ == "f":
            out += b"f" + struct.pack("<f", float(val))
        elif typ in ("Z", "H"):
            out += typ.encode() + val.encode("ascii") + b"\x00"
        elif typ == "B":
            sub, rest = val[0], val.split(",")[1:]
            out += b"B" + sub.encode()
            out += struct.pack("<I", len(rest))
            fmt = {"c": "<b", "C": "<B", "s": "<h", "S": "<H",
                   "i": "<i", "I": "<I", "f": "<f"}[sub]
            conv = float if sub == "f" else int
            for v in rest:
                out += struct.pack(fmt, conv(v))
        else:
            raise ValueError(f"unknown tag type in {f!r}")
    return bytes(out)


def write_bam(
    path: str,
    batch: ReadBatch,
    side: ReadSidecar,
    header: SamHeader,
    sort_order: Optional[str] = None,
) -> None:
    text = "\n".join(header.to_lines(sort_order=sort_order)) + "\n"
    body = _io.BytesIO()
    body.write(b"BAM\x01")
    tb = text.encode("utf-8")
    body.write(struct.pack("<i", len(tb)))
    body.write(tb)
    sd = header.seq_dict
    body.write(struct.pack("<i", len(sd)))
    for r in sd:
        nb = r.name.encode("ascii") + b"\x00"
        body.write(struct.pack("<i", len(nb)))
        body.write(nb)
        body.write(struct.pack("<i", r.length))
    b = batch.to_numpy()
    rg_names = header.read_groups.names

    from adam_tpu import native

    nat = native.bam_encode(b, side, rg_names, len(sd))
    if nat is not None:
        body.write(nat)
        with open(path, "wb") as fh:
            fh.write(bgzf_compress(body.getvalue()))
        return

    for i in range(b.n_rows):
        if not b.valid[i]:
            continue
        L = int(b.lengths[i])
        name = side.names[i].encode("ascii") + b"\x00"
        ncig = int(b.cigar_n[i])
        cig = b""
        for k in range(ncig):
            cig += struct.pack(
                "<I", (int(b.cigar_lens[i, k]) << 4) | int(b.cigar_ops[i, k])
            )
        codes = b.bases[i][:L]
        nib = _CODE_TO_BAM_SEQ[np.minimum(codes, schema.BASE_PAD)]
        if L % 2:
            nib = np.concatenate([nib, [0]])
        packed = ((nib[0::2] << 4) | nib[1::2]).astype(np.uint8).tobytes()
        quals = b.quals[i][:L]
        if b.has_qual[i]:
            quals = np.where(quals == schema.QUAL_PAD, 0xFF, quals).astype(np.uint8)
        else:
            quals = np.full(L, 0xFF, np.uint8)  # BAM spec: missing qual
        rg = int(b.read_group_idx[i])
        tags = _encode_bam_tags(
            side.attrs[i], side.md[i], side.orig_quals[i],
            rg_names[rg] if rg >= 0 else None,
        )
        rec = struct.pack(
            "<iiBBHHHiiii",
            int(b.contig_idx[i]),
            int(b.start[i]) if int(b.start[i]) >= 0 else -1,
            len(name),
            int(b.mapq[i]) & 0xFF,
            0,  # bin (unused by our readers; htsjdk recomputes)
            ncig,
            int(b.flags[i]) & 0xFFFF,
            L,
            int(b.mate_contig_idx[i]),
            int(b.mate_start[i]) if int(b.mate_start[i]) >= 0 else -1,
            int(b.tlen[i]),
        )
        payload = rec + name + cig + packed + quals.tobytes() + tags
        body.write(struct.pack("<i", len(payload)))
        body.write(payload)
    with open(path, "wb") as fh:
        fh.write(bgzf_compress(body.getvalue()))
