"""Feature file parsers: GTF/GFF, BED, narrowPeak, wigFix.

Line-level parity with ``rdd/features/FeatureParser.scala``:

* GTF (:70-119): 1-based closed ranges -> 0-based half-open; attribute
  string of ``key "value";`` tokens; feature id/parent wiring per type
  (gene/transcript/exon/CDS/UTR), exon id falling back to
  ``transcriptId_exonNumber``.
* BED (:123-176): 0-based; optional name/score/strand columns; extra
  columns kept as thickStart/thickEnd/itemRgb/blockCount/blockSizes/
  blockStarts attributes.
* narrowPeak (:180-232): BED3+ with signalValue/pValue/qValue/peak
  attributes.
* wigFix -> BED (adam-cli ``Wiggle2Bed.scala:40-81``): run-length
  fixedStep declarations expanded to per-span BED rows.

Writers emit BED (the interchange format the reference's features2adam /
wigfix2bed round-trip through).
"""

from __future__ import annotations

import re
import uuid
from typing import Optional

import numpy as np

from adam_tpu.formats.features import (
    FeatureBatch,
    FeatureBatchBuilder,
    strand_code,
)

_GTF_ATTR = re.compile(r'\s*([^\s]+)\s"([^"]+)"')


def parse_gtf_attrs(attr_field: str) -> dict:
    out = {}
    for token in attr_field.split(";"):
        m = _GTF_ATTR.search(token)
        if m:
            out[m.group(1)] = m.group(2)
        elif "=" in token:  # GFF3 style key=value
            k, v = token.strip().split("=", 1)
            out[k] = v
    return out


def _gtf_line(builder: FeatureBatchBuilder, line: str) -> None:
    if line.startswith("#") or not line.strip():
        return
    f = line.rstrip("\n").split("\t")
    seqname, source, ftype, start, end, score, strand, _frame, attr = f[:9]
    attrs = parse_gtf_attrs(attr)

    # GFF3 spells transcripts 'mRNA' and wires hierarchy with ID=/Parent=;
    # normalize so downstream gene assembly (models/genes.as_genes) sees
    # one vocabulary.
    if ftype == "mRNA":
        attrs.setdefault("original_type", ftype)
        ftype = "transcript"
    gff3_id, gff3_parent = attrs.get("ID"), attrs.get("Parent")

    exon_id = attrs.get("exon_id")
    if exon_id is None and "transcript_id" in attrs and "exon_number" in attrs:
        exon_id = attrs["transcript_id"] + "_" + attrs["exon_number"]

    if ftype == "gene":
        fid, parent = attrs.get("gene_id") or gff3_id, None
    elif ftype == "transcript":
        fid = attrs.get("transcript_id") or gff3_id
        parent = attrs.get("gene_id") or gff3_parent
    elif ftype == "exon":
        fid = exon_id or gff3_id
        parent = attrs.get("transcript_id") or gff3_parent
    elif ftype in ("CDS", "UTR"):
        fid = attrs.get("id") or gff3_id
        parent = attrs.get("transcript_id") or gff3_parent
    else:
        fid, parent = attrs.get("id") or gff3_id, gff3_parent

    builder.add(
        seqname,
        int(start) - 1,  # 1-based closed -> 0-based half-open
        int(end),
        strand_code(strand),
        float(score) if score not in (".", "") else np.nan,
        feature_id=fid or "",
        feature_type=ftype,
        source=source,
        parent_ids=[parent] if parent else [],
        attributes=attrs,
    )


def _bed_like_line(builder: FeatureBatchBuilder, line: str, extras) -> None:
    """Shared BED3+ column layout; BED and narrowPeak differ only in what
    the columns past strand mean (FeatureParser.scala:123-232)."""
    f = line.rstrip("\n").split("\t")
    if len(f) < 3 or line.startswith(("#", "track", "browser")):
        return
    attrs = {k: f[6 + i] for i, k in enumerate(extras) if len(f) > 6 + i}
    builder.add(
        f[0], int(f[1]), int(f[2]),
        strand_code(f[5]) if len(f) > 5 else 0,
        float(f[4]) if len(f) > 4 and f[4] != "." else np.nan,
        feature_id=str(uuid.uuid4()),
        feature_type=f[3] if len(f) > 3 else "",
        attributes=attrs,
    )


def _bed_line(builder: FeatureBatchBuilder, line: str) -> None:
    _bed_like_line(builder, line, ["thickStart", "thickEnd", "itemRgb",
                                   "blockCount", "blockSizes", "blockStarts"])


def _narrow_peak_line(builder: FeatureBatchBuilder, line: str) -> None:
    _bed_like_line(builder, line, ["signalValue", "pValue", "qValue", "peak"])


_PARSERS = {
    "gtf": _gtf_line,
    "gff": _gtf_line,
    "gff3": _gtf_line,
    "bed": _bed_line,
    "narrowpeak": _narrow_peak_line,
}


def read_features(path: str, fmt: Optional[str] = None) -> FeatureBatch:
    """Parse a feature file; format sniffed from the extension
    (loadGTF/loadBED/loadNarrowPeak dispatch, rdd/ADAMContext.scala:358-371).
    Unknown extensions are an error — guessing a parser turns format
    mistakes into confusing mid-file crashes.
    """
    import gzip

    base = path[:-3] if path.endswith(".gz") else path
    if fmt is None:
        ext = base.rsplit(".", 1)[-1].lower()
        if ext not in _PARSERS:
            raise ValueError(
                f"cannot infer feature format from {path!r}; pass fmt= "
                f"one of {sorted(_PARSERS)}"
            )
        fmt = ext
    parse = _PARSERS[fmt.lower()]
    builder = FeatureBatchBuilder()
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        for line in fh:
            parse(builder, line)
    return builder.build()


def write_bed(path: str, feats: FeatureBatch) -> None:
    with open(path, "w") as fh:
        side = feats.sidecar
        for i in range(len(feats)):
            score = feats.score[i]
            fh.write(
                "\t".join(
                    [
                        feats.contig_names[feats.contig_idx[i]],
                        str(int(feats.start[i])),
                        str(int(feats.end[i])),
                        side.feature_type[i],
                        "." if np.isnan(score) else f"{float(score):g}",
                        {1: "+", -1: "-", 0: "."}[int(feats.strand[i])],
                    ]
                )
                + "\n"
            )


_WIG_DECL = re.compile(
    r"^fixedStep\s+chrom=(.+?)\s+start=([0-9]+)\s+step=([0-9]+)"
    r"\s*(?:$|span=([0-9]+).*$)"
)
def wigfix_to_bed_lines(lines):
    """Expand a fixedStep wiggle stream to BED rows
    (WigFix2Bed.run, adam-cli Wiggle2Bed.scala:57-81).

    Every non-blank, non-declaration line must be a numeric value
    (including scientific notation); anything else is a format error —
    silently skipping a line would desynchronize every later coordinate.
    """
    contig, current, step, span = "", 0, 0, 1
    for line in lines:
        m = _WIG_DECL.match(line)
        if m:
            contig = m.group(1)
            current = int(m.group(2)) - 1  # to BED coords
            step = int(m.group(3))
            span = int(m.group(4)) if m.group(4) else span
            continue
        s = line.strip()
        if not s or s.startswith(("#", "track", "browser")):
            continue
        float(s)  # raises ValueError on malformed data lines
        yield "\t".join([contig, str(current), str(current + span), "", s])
        current += step


def wigfix_to_bed(wig_path: str, bed_path: str) -> None:
    with open(wig_path) as fin, open(bed_path, "w") as fout:
        for row in wigfix_to_bed_lines(fin):
            fout.write(row + "\n")
