"""Zero-copy Arrow column assembly from packed (offset + data) buffers.

The encode half of the pass-C tail rebuild (ROADMAP "kill the
apply/encode/write tail"): where the device hands back an
already-packed column payload (:mod:`adam_tpu.ops.colpack` — flat
SANGER qual bytes in row order), the Arrow column is built **directly
over that memory** with ``pa.Array.from_buffers`` — no per-row
materialization, no LUT re-walk, no second copy of the fat column.
The low-cardinality name columns (contig / mateContig /
recordGroupName — the ones ``io/parquet`` already dictionary-encodes
at write time) assemble from their small-integer index arrays by
gathering the dictionary's *byte spans*, never materializing a Python
string per row.

Every builder is byte-compatible with the column the legacy path
produced (same Arrow type, same values, same validity), which is what
keeps the packed and legacy Parquet parts bit-identical —
tests/test_arrow_pack.py proves it across compressions and backends.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import pyarrow as pa

from adam_tpu.formats.strings import StringColumn, _span_gather_indices


@dataclass(frozen=True)
class PackedQuals:
    """A device-packed qual column payload: ``buf`` holds the
    concatenated in-read SANGER bytes of every row (in row order,
    zero-length rows contributing nothing) and ``lens`` the per-row
    byte counts (0 for invalid / qual-less rows).  ``buf`` is exactly
    the Arrow data buffer; offsets rebuild host-side with one cumsum —
    they never crossed the device link."""

    buf: np.ndarray   # u8[sum(lens)]
    lens: np.ndarray  # i64[N]

    def __post_init__(self):
        object.__setattr__(
            self, "buf", np.ascontiguousarray(self.buf, np.uint8)
        )
        object.__setattr__(
            self, "lens", np.asarray(self.lens, np.int64)
        )

    def offsets(self) -> np.ndarray:
        out = np.zeros(len(self.lens) + 1, np.int64)
        np.cumsum(self.lens, out=out[1:])
        return out

    def take(self, rows: np.ndarray) -> "PackedQuals":
        """Row subset.  The common case — dropping rows that carry no
        bytes (the invalid-row compaction in ``to_arrow_alignments``) —
        is free: the data stream is untouched, only the length entries
        go.  An order-preserving selection that drops byte-bearing rows
        falls back to a vectorized span gather."""
        rows = np.asarray(rows, np.int64)
        keep = np.zeros(len(self.lens), bool)
        keep[rows] = True
        in_order = bool((np.diff(rows) > 0).all()) if len(rows) > 1 else True
        if in_order and not self.lens[~keep].any():
            return PackedQuals(self.buf, self.lens[rows])
        starts = self.offsets()[:-1][rows]
        lens = self.lens[rows]
        return PackedQuals(
            self.buf[_span_gather_indices(starts, lens)], lens
        )


@dataclass(frozen=True)
class PackedColumns:
    """The pass-C packed payload pair of a device-resident window: the
    qual column AND the base column (the bases half of the packed
    tail), each a :class:`PackedQuals`-shaped (buf, lens) payload.
    ``bases`` may be None (quals-only packing, the PR 12 layout)."""

    quals: PackedQuals
    bases: "PackedQuals | None" = None

    def take(self, rows: np.ndarray) -> "PackedColumns":
        return PackedColumns(
            self.quals.take(rows),
            self.bases.take(rows) if self.bases is not None else None,
        )


def packed_qual_array(packed: PackedQuals, valid: np.ndarray) -> "pa.Array":
    """Packed qual payload -> the Arrow ``large_string`` column, built
    over the fetched buffer with zero copies (``valid`` = the rows that
    actually carry a qual; their ``lens`` are 0 and they become
    nulls — the legacy ``decoded_col`` semantics exactly)."""
    return StringColumn(
        packed.buf, packed.offsets(), np.asarray(valid, bool)
    ).to_arrow()


def packed_base_array(packed: PackedQuals) -> "pa.Array":
    """Packed base payload -> the Arrow ``sequence`` column, zero-copy
    over the fetched buffer.  Every kept row carries its sequence (the
    legacy path builds the column with an all-true validity), so the
    validity is all-valid by construction — byte-identical to the host
    LUT-walk column."""
    n = len(packed.lens)
    return StringColumn(
        packed.buf, packed.offsets(), np.ones(n, bool)
    ).to_arrow()


def index_name_array(idx: np.ndarray, names: list[str]) -> "pa.Array":
    """Dictionary-index column -> Arrow ``string`` array (nulls for
    idx < 0), assembled by gathering the dictionary's byte spans — the
    zero-materialization replacement for the legacy object-array LUT
    (``pa.array`` over N Python objects, the last per-row interpreter
    walk in the encode path).  Byte-identical output: same Arrow type
    (``pa.string()``, i32 offsets), same values, same validity."""
    idx = np.asarray(idx)
    n = len(idx)
    enc = [s.encode("utf-8") for s in names]
    dict_lens = np.array([len(b) for b in enc] + [0], np.int64)
    total_dict = int(dict_lens.sum())
    dict_buf = (
        np.frombuffer(b"".join(enc), np.uint8)
        if total_dict
        else np.zeros(0, np.uint8)
    )
    dict_off = np.zeros(len(enc) + 2, np.int64)
    np.cumsum(dict_lens, out=dict_off[1:])
    safe = np.where(idx >= 0, idx, len(enc)).astype(np.int64)
    lens = dict_lens[safe]
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum(lens, out=offsets[1:])
    total = int(offsets[-1])
    if total > np.iinfo(np.int32).max:  # i32 offset overflow: impossible
        # for window-scale batches, but never silently corrupt
        lut = np.array(names + [None], dtype=object)
        return pa.array(lut[safe], pa.string())
    buf = (
        dict_buf[_span_gather_indices(dict_off[safe], lens)]
        if total
        else np.zeros(0, np.uint8)
    )
    valid = idx >= 0
    validity = None if valid.all() else pa.array(valid).buffers()[1]
    return pa.Array.from_buffers(
        pa.string(),
        n,
        [
            validity,
            pa.py_buffer(np.ascontiguousarray(offsets.astype(np.int32))),
            pa.py_buffer(buf),
        ],
    )


def pack_matrix_host(mat: np.ndarray, lens: np.ndarray,
                     lut256: np.ndarray | None = None) -> PackedQuals:
    """Host-side packing twin (the fallback when the window applied on
    the host backend, and the bases half of the packed layout — the
    host already holds the base matrix, so shipping it d2h would buy
    nothing): native fused LUT+compact when available, else the
    vectorized numpy mask-select."""
    from adam_tpu import native
    from adam_tpu.ops.colpack import pack_rows_np

    lens = np.asarray(lens, np.int64)
    if lut256 is not None:
        nat = native.lut_compact_rows(
            np.ascontiguousarray(mat, np.uint8), lens, lut256
        )
        if nat is not None:
            return PackedQuals(nat[0], np.diff(nat[1]))
        mat = lut256[np.asarray(mat)]
    return PackedQuals(pack_rows_np(mat, lens), lens)
