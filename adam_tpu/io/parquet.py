"""Columnar (Parquet) storage of read datasets.

The role of ``rdd/ADAMRDDFunctions.adamParquetSave`` (:56-93) and
``rdd/ADAMContext.adamLoad`` (:129-167): persistent columnar storage with
**projection** (column pruning) and **predicate pushdown**.  Uses pyarrow;
the on-disk schema mirrors the reference's AlignmentRecord field names
(projections/AlignmentRecordField.scala:29-31) so files are inspectable
and semantically interchangeable.

Dictionaries ride along as file-level metadata (JSON), the role the
reference gives to sidecar Avro files / header merging.
"""

from __future__ import annotations

import json
from typing import Callable, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from adam_tpu.formats import schema
from adam_tpu.formats.batch import ReadBatch, ReadSidecar, pack_reads
from adam_tpu.io.sam import SamHeader
from adam_tpu.models.dictionaries import (
    RecordGroup,
    RecordGroupDictionary,
    SequenceDictionary,
    SequenceRecord,
)

# Full column list (the AlignmentRecordField analog).
ALIGNMENT_FIELDS = [
    "readName", "sequence", "qual", "flags", "contig", "start", "end",
    "mapq", "cigar", "mateContig", "mateAlignmentStart", "inferredInsertSize",
    "recordGroupName", "attributes", "mismatchingPositions", "origQual",
    "basesTrimmedFromStart", "basesTrimmedFromEnd",
]


def _header_meta(header: SamHeader) -> dict[bytes, bytes]:
    meta = {
        "sequences": [
            {"name": r.name, "length": r.length, "md5": r.md5, "url": r.url}
            for r in header.seq_dict
        ],
        "read_groups": [
            {"name": g.name, "sample": g.sample, "library": g.library,
             "platform": g.platform, "platform_unit": g.platform_unit}
            for g in header.read_groups
        ],
        "programs": header.program_lines,
        "comments": header.comment_lines,
        "hd": header.hd_line,
    }
    return {b"adam_tpu.header": json.dumps(meta).encode()}


def _header_from_meta(meta: Optional[dict]) -> SamHeader:
    if not meta or b"adam_tpu.header" not in meta:
        return SamHeader()
    d = json.loads(meta[b"adam_tpu.header"])
    return SamHeader(
        seq_dict=SequenceDictionary(
            tuple(
                SequenceRecord(s["name"], s["length"], md5=s.get("md5"),
                               url=s.get("url"))
                for s in d["sequences"]
            )
        ),
        read_groups=RecordGroupDictionary(
            tuple(
                RecordGroup(g["name"], sample=g.get("sample"),
                            library=g.get("library"), platform=g.get("platform"),
                            platform_unit=g.get("platform_unit"))
                for g in d["read_groups"]
            )
        ),
        hd_line=d.get("hd"),
        program_lines=d.get("programs", []),
        comment_lines=d.get("comments", []),
    )


def save_alignments(
    path: str, batch: ReadBatch, side: ReadSidecar, header: SamHeader,
    compression: str = "snappy",
) -> None:
    b = batch.to_numpy()
    rows = np.flatnonzero(np.asarray(b.valid))
    names = header.seq_dict.names
    rg_names = header.read_groups.names

    def contig_name(i):
        c = int(b.contig_idx[i])
        return names[c] if c >= 0 else None

    def mate_contig_name(i):
        c = int(b.mate_contig_idx[i])
        return names[c] if c >= 0 else None

    table = pa.table(
        {
            "readName": pa.array([side.names[i] for i in rows], pa.string()),
            "sequence": pa.array(
                [schema.decode_bases(b.bases[i], int(b.lengths[i])) for i in rows],
                pa.string(),
            ),
            "qual": pa.array(
                [
                    schema.decode_quals(b.quals[i], int(b.lengths[i]))
                    if b.has_qual[i]
                    else None
                    for i in rows
                ],
                pa.string(),
            ),
            "flags": pa.array([int(b.flags[i]) for i in rows], pa.int32()),
            "contig": pa.array([contig_name(i) for i in rows], pa.string()),
            "start": pa.array(
                [int(b.start[i]) if int(b.start[i]) >= 0 else None for i in rows],
                pa.int64(),
            ),
            "end": pa.array(
                [int(b.end[i]) if int(b.end[i]) >= 0 else None for i in rows],
                pa.int64(),
            ),
            "mapq": pa.array([int(b.mapq[i]) for i in rows], pa.int32()),
            "cigar": pa.array(
                [
                    schema.decode_cigar(
                        b.cigar_ops[i], b.cigar_lens[i], int(b.cigar_n[i])
                    )
                    for i in rows
                ],
                pa.string(),
            ),
            "mateContig": pa.array([mate_contig_name(i) for i in rows], pa.string()),
            "mateAlignmentStart": pa.array(
                [
                    int(b.mate_start[i]) if int(b.mate_start[i]) >= 0 else None
                    for i in rows
                ],
                pa.int64(),
            ),
            "inferredInsertSize": pa.array(
                [int(b.tlen[i]) for i in rows], pa.int32()
            ),
            "recordGroupName": pa.array(
                [
                    rg_names[int(b.read_group_idx[i])]
                    if int(b.read_group_idx[i]) >= 0
                    else None
                    for i in rows
                ],
                pa.string(),
            ),
            "attributes": pa.array([side.attrs[i] for i in rows], pa.string()),
            "mismatchingPositions": pa.array([side.md[i] for i in rows], pa.string()),
            "origQual": pa.array([side.orig_quals[i] for i in rows], pa.string()),
            "basesTrimmedFromStart": pa.array(
                [side.trimmed_from_start[i] for i in rows], pa.int32()
            ),
            "basesTrimmedFromEnd": pa.array(
                [side.trimmed_from_end[i] for i in rows], pa.int32()
            ),
        }
    )
    table = table.replace_schema_metadata(_header_meta(header))
    pq.write_table(table, path, compression=compression)


def load_alignments(
    path: str,
    projection: Optional[Sequence[str]] = None,
    predicate=None,
    round_rows_to: int = 1,
) -> tuple[ReadBatch, ReadSidecar, SamHeader]:
    """Load with optional column projection and pyarrow filter predicate.

    ``projection`` is a subset of ALIGNMENT_FIELDS; essential columns for
    batch building are always read.  ``predicate`` is a pyarrow
    ``filters``-style expression (pyarrow.compute expression).
    """
    cols = None
    if projection is not None:
        essential = {"sequence", "qual", "flags", "cigar", "start", "contig"}
        cols = sorted(set(projection) | essential)
    table = pq.read_table(path, columns=cols, filters=predicate)
    header = _header_from_meta(table.schema.metadata)
    sd, rgd = header.seq_dict, header.read_groups

    def col(name, default=None):
        if name in table.column_names:
            return table[name].to_pylist()
        return [default] * table.num_rows

    names_ = col("readName", "")
    seqs = col("sequence", "")
    quals = col("qual", "")
    flags = col("flags", 4)
    contigs = col("contig")
    starts = col("start")
    mapqs = col("mapq", 255)
    cigars = col("cigar", "*")
    mate_contigs = col("mateContig")
    mate_starts = col("mateAlignmentStart")
    tlens = col("inferredInsertSize", 0)
    rgs = col("recordGroupName")
    attrs = col("attributes", "")
    mds = col("mismatchingPositions")
    oqs = col("origQual")
    tfs = col("basesTrimmedFromStart", 0)
    tfe = col("basesTrimmedFromEnd", 0)

    records = [
        dict(
            name=names_[i],
            flags=flags[i] if flags[i] is not None else 4,
            contig_idx=sd.index_or(contigs[i]) if contigs[i] else -1,
            start=starts[i] if starts[i] is not None else -1,
            mapq=mapqs[i] if mapqs[i] is not None else 255,
            cigar=cigars[i] or "*",
            seq=seqs[i] or "",
            qual=quals[i] or "*",
            mate_contig_idx=sd.index_or(mate_contigs[i]) if mate_contigs[i] else -1,
            mate_start=mate_starts[i] if mate_starts[i] is not None else -1,
            tlen=tlens[i] or 0,
            read_group_idx=rgd.index_or(rgs[i]) if rgs[i] else -1,
            attrs=attrs[i] or "",
            md=mds[i],
            orig_qual=oqs[i],
            trimmed_from_start=tfs[i] or 0,
            trimmed_from_end=tfe[i] or 0,
        )
        for i in range(table.num_rows)
    ]
    batch, side = pack_reads(records, round_rows_to=round_rows_to)
    return batch, side, header
