"""Columnar (Parquet) storage of read datasets.

The role of ``rdd/ADAMRDDFunctions.adamParquetSave`` (:56-93) and
``rdd/ADAMContext.adamLoad`` (:129-167): persistent columnar storage with
**projection** (column pruning) and **predicate pushdown**.  Uses pyarrow;
the on-disk schema mirrors the reference's AlignmentRecord field names
(projections/AlignmentRecordField.scala:29-31) so files are inspectable
and semantically interchangeable.

Dictionaries ride along as file-level metadata (JSON), the role the
reference gives to sidecar Avro files / header merging.
"""

from __future__ import annotations

import json
import os
import re
import time
from collections import deque
from typing import Callable, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

# See adam_tpu/__init__: arrow's bundled mimalloc corrupts its TLS list
# under short-lived-thread churn; force the system pool even when pyarrow
# was imported (and the env default missed) before adam_tpu.
try:
    pa.set_memory_pool(pa.system_memory_pool())
except Exception:
    pass

from adam_tpu.formats import schema
from adam_tpu.formats.batch import ReadBatch, ReadSidecar, pack_reads
from adam_tpu.io.sam import SamHeader
from adam_tpu.models.dictionaries import (
    RecordGroup,
    RecordGroupDictionary,
    SequenceDictionary,
    SequenceRecord,
)

#: Staging subdirectory for in-progress part writes (crash consistency,
#: docs/ROBUSTNESS.md): every Parquet write lands under
#: ``<dir>/_temporary/`` and is PUBLISHED by an atomic ``os.replace``,
#: so readers never observe a torn file.  The ``_`` prefix matters —
#: pyarrow dataset discovery ignores underscore-prefixed entries (the
#: Hadoop ``_temporary``/``_SUCCESS`` convention), so a crash's leftover
#: staging files are invisible to every loader; the streamed pipeline
#: purges the stale dir on its next run.
TMP_DIR_NAME = "_temporary"

#: Part-file naming contract (the Spark executor ``part-r-NNNNN`` layout,
#: shared by every windowed pipeline and the streamed run journal): the
#: numeric index IS the pipeline's window index — window ``i``'s rows
#: land in ``part-r-<i:05d>.parquet``, and the streamed realigned tail
#: part takes index ``n_windows``.  The index is therefore recoverable
#: from the file name alone (:func:`part_index`), which is what lets a
#: resumed run map journaled parts back onto its window plan.
PART_NAME_FORMAT = "part-r-{:05d}.parquet"
_PART_NAME_RE = re.compile(r"^part-r-(\d{5,})\.parquet$")


def part_name(idx: int) -> str:
    """Canonical part file name for window/part index ``idx``."""
    return PART_NAME_FORMAT.format(idx)


def part_path(out_dir: str, idx: int) -> str:
    return os.path.join(out_dir, part_name(idx))


def part_index(path: str) -> Optional[int]:
    """Window/part index recovered from a part path (None when the name
    is not a canonical part file — e.g. staging or sidecar files)."""
    m = _PART_NAME_RE.match(os.path.basename(path))
    return int(m.group(1)) if m else None


def purge_stale_staging(out_dir: str) -> None:
    """Remove a previous (crashed) run's staging dir under ``out_dir``.

    Pipelines that own an output directory call this ONCE at startup,
    before any writer is live — a SIGKILL'd run leaves its torn files
    only in here, and a leftover file would keep the opportunistic
    per-write rmdir failing (ENOTEMPTY) forever.  Never call this with
    writers in flight: live claim files look identical to stale ones.
    """
    stale = os.path.join(out_dir, TMP_DIR_NAME)
    if os.path.isdir(stale):
        import logging
        import shutil

        logging.getLogger(__name__).warning(
            "removing stale staging dir %s (a previous run died "
            "mid-write)", stale,
        )
        shutil.rmtree(stale, ignore_errors=True)


def _staging_path(path: str) -> str:
    d = os.path.dirname(os.path.abspath(path))
    tmp_dir = os.path.join(d, TMP_DIR_NAME)
    try:
        # single-level mkdir, NOT makedirs: a missing parent directory
        # must stay the error it always was, not get silently created
        os.mkdir(tmp_dir)
    except FileExistsError:
        pass
    return os.path.join(tmp_dir, os.path.basename(path) + ".tmp")


def parquet_codec_kw(compression: str) -> dict:
    """Writer kwargs for a codec name — ONE place pins zstd at level 1
    (measured faster than snappy at ~45% smaller parts; pyarrow's
    current default zstd level happens to equal 1, but the pin protects
    the measured write cost against upstream default drift)."""
    kw = {"compression": compression}
    if compression == "zstd":
        kw["compression_level"] = 1
    return kw


# Full column list (the AlignmentRecordField analog).
ALIGNMENT_FIELDS = [
    "readName", "sequence", "qual", "flags", "contig", "start", "end",
    "mapq", "cigar", "mateContig", "mateAlignmentStart", "inferredInsertSize",
    "recordGroupName", "attributes", "mismatchingPositions", "origQual",
    "basesTrimmedFromStart", "basesTrimmedFromEnd",
]


def _header_meta(header: SamHeader) -> dict[bytes, bytes]:
    meta = {
        "sequences": [
            {"name": r.name, "length": r.length, "md5": r.md5, "url": r.url}
            for r in header.seq_dict
        ],
        "read_groups": [
            {"name": g.name, "sample": g.sample, "library": g.library,
             "platform": g.platform, "platform_unit": g.platform_unit}
            for g in header.read_groups
        ],
        "programs": header.program_lines,
        "comments": header.comment_lines,
        "hd": header.hd_line,
    }
    return {b"adam_tpu.header": json.dumps(meta).encode()}


def _header_from_meta(meta: Optional[dict]) -> SamHeader:
    if not meta or b"adam_tpu.header" not in meta:
        return SamHeader()
    d = json.loads(meta[b"adam_tpu.header"])
    return SamHeader(
        seq_dict=SequenceDictionary(
            tuple(
                SequenceRecord(s["name"], s["length"], md5=s.get("md5"),
                               url=s.get("url"))
                for s in d["sequences"]
            )
        ),
        read_groups=RecordGroupDictionary(
            tuple(
                RecordGroup(g["name"], sample=g.get("sample"),
                            library=g.get("library"), platform=g.get("platform"),
                            platform_unit=g.get("platform_unit"))
                for g in d["read_groups"]
            )
        ),
        hd_line=d.get("hd"),
        program_lines=d.get("programs", []),
        comment_lines=d.get("comments", []),
    )


def _matrix_string_array(mat: np.ndarray, lens: np.ndarray,
                         valid: np.ndarray) -> "pa.Array":
    """Padded ASCII byte matrix [N, W] + lengths -> arrow string column."""
    from adam_tpu.formats.strings import StringColumn

    col = StringColumn.from_matrix(
        mat, np.where(valid, lens, 0), np.ascontiguousarray(valid)
    )
    return col.to_arrow()


def _cigar_string_array(ops: np.ndarray, lens: np.ndarray,
                        n_ops: np.ndarray) -> "pa.Array":
    """Columnar CIGARs -> arrow string column ('*' when no ops): native
    threaded emitter, np.char lane passes as the fallback."""
    from adam_tpu import native
    from adam_tpu.formats.strings import StringColumn

    nat = native.cigar_strings(ops, lens, n_ops)
    if nat is not None:
        buf, offsets = nat
        return StringColumn(buf, offsets).to_arrow()

    N, C = ops.shape if ops.ndim == 2 else (len(n_ops), 0)
    if C == 0 or N == 0:
        return pa.array(np.full(N, "*", dtype=object), pa.string())
    chars = np.array(list(schema.CIGAR_CHARS) + ["?"] * 7)
    piece = np.char.add(
        lens.astype("U10"), chars[np.minimum(ops, 15)]
    )
    active = np.arange(C)[None, :] < n_ops[:, None]
    piece = np.where(active, piece, "")
    out = piece[:, 0]
    for k in range(1, C):
        out = np.char.add(out, piece[:, k])
    out = np.where(n_ops > 0, out, "*")
    return pa.array(out, pa.string())


def _index_name_array(idx: np.ndarray, names: list[str]) -> "pa.Array":
    """Small-dictionary index column -> arrow string column (None for <0):
    zero-materialization dictionary-span gather (io/arrow_pack) — same
    Arrow type and values as the old per-row object-array LUT."""
    from adam_tpu.io.arrow_pack import index_name_array

    return index_name_array(np.asarray(idx), names)


def to_arrow_alignments(
    batch: ReadBatch, side: ReadSidecar, header: SamHeader,
    packed=None,
) -> "pa.Table":
    """Columnar batch -> arrow Table in the AlignmentRecord field layout.

    This is the Spark-embedding seam (BASELINE north star): the table's
    RecordBatches can cross a py4j/mapPartitions boundary, and
    :func:`from_arrow_alignments` reconstructs the batch on the other
    side.  Header dictionaries ride along as schema metadata.

    ``packed``: an optional :class:`~adam_tpu.io.arrow_pack.PackedQuals`
    — the device-packed encode-ready qual payload from the streamed
    pass C — or a :class:`~adam_tpu.io.arrow_pack.PackedColumns`
    carrying the base column too (the resident-window bases half).
    When given, the ``qual`` (and ``sequence``) columns are built
    zero-copy over those buffers and the batch's matrices are never
    touched; output is byte-identical to the matrix path
    (tests/test_arrow_pack.py, tests/test_resident.py).
    """
    from adam_tpu.formats.strings import StringColumn
    from adam_tpu.io.arrow_pack import PackedColumns

    packed_bases = None
    if isinstance(packed, PackedColumns):
        packed_bases = packed.bases
        packed = packed.quals

    b = batch.to_numpy()
    valid = np.asarray(b.valid)
    if not valid.all():
        rows = np.flatnonzero(valid)
        # host-side gather (ReadBatch.take would bounce through the device)
        import jax

        b = jax.tree.map(lambda x: np.asarray(x)[rows], b)
        side = side.take(rows)
        if packed is not None:
            # invalid rows carry no packed bytes, so this is offsets-only
            packed = packed.take(rows)
        if packed_bases is not None:
            packed_bases = packed_bases.take(rows)
    n = b.n_rows

    def masked_int(vals, dtype):
        vals = np.asarray(vals)
        return pa.array(vals, dtype, mask=vals < 0)

    def decoded_col(mat, lut256, np_decode, valid):
        # fused native LUT + compaction; numpy LUT gather + from_matrix
        # as the fallback (same bytes)
        from adam_tpu import native

        lens = np.where(valid, np.asarray(b.lengths), 0)
        nat = native.lut_compact_rows(mat, lens, lut256)
        if nat is not None:
            return StringColumn(nat[0], nat[1], valid).to_arrow()
        return _matrix_string_array(np_decode(mat), b.lengths, valid)

    table = pa.table(
        {
            "readName": StringColumn.of(side.names).to_arrow(),
            "sequence": (
                _packed_base_col(packed_bases)
                if packed_bases is not None
                else decoded_col(
                    b.bases, schema.BASE_DECODE_LUT256,
                    lambda m: schema.BASE_DECODE_LUT[
                        np.minimum(m, schema.BASE_PAD)
                    ],
                    np.ones(n, bool),
                )
            ),
            "qual": (
                _packed_qual_col(packed, b)
                if packed is not None
                else decoded_col(
                    b.quals, schema.QUAL_SANGER_LUT256,
                    lambda m: (
                        np.minimum(m, 93) + schema.SANGER_OFFSET
                    ).astype(np.uint8),
                    np.asarray(b.has_qual),
                )
            ),
            "flags": pa.array(np.asarray(b.flags, np.int32), pa.int32()),
            "contig": _index_name_array(b.contig_idx, header.seq_dict.names),
            "start": masked_int(b.start, pa.int64()),
            "end": masked_int(b.end, pa.int64()),
            "mapq": pa.array(np.asarray(b.mapq, np.int32), pa.int32()),
            "cigar": _cigar_string_array(b.cigar_ops, b.cigar_lens, b.cigar_n),
            "mateContig": _index_name_array(
                b.mate_contig_idx, header.seq_dict.names
            ),
            "mateAlignmentStart": masked_int(b.mate_start, pa.int64()),
            "inferredInsertSize": pa.array(
                np.asarray(b.tlen, np.int32), pa.int32()
            ),
            "recordGroupName": _index_name_array(
                b.read_group_idx, header.read_groups.names
            ),
            "attributes": StringColumn.of(side.attrs).to_arrow(),
            "mismatchingPositions": StringColumn.of(side.md).to_arrow(),
            "origQual": StringColumn.of(side.orig_quals).to_arrow(),
            "basesTrimmedFromStart": pa.array(
                np.asarray(side.trimmed_from_start, np.int32), pa.int32()
            ),
            "basesTrimmedFromEnd": pa.array(
                np.asarray(side.trimmed_from_end, np.int32), pa.int32()
            ),
        }
    )
    return table.replace_schema_metadata(_header_meta(header))


def _packed_qual_col(packed, b) -> "pa.Array":
    """Device-packed payload -> the arrow qual column (zero-copy)."""
    from adam_tpu.io.arrow_pack import packed_qual_array

    return packed_qual_array(packed, np.asarray(b.has_qual))


def _packed_base_col(packed) -> "pa.Array":
    """Device-packed payload -> the arrow sequence column (zero-copy)."""
    from adam_tpu.io.arrow_pack import packed_base_array

    return packed_base_array(packed)


def _encode_bytes_in(batch, side, packed=None) -> int:
    """Decoded column-payload bytes entering a part encode — the
    [N, L]/[N, C] batch matrices plus the sidecar's flat string
    buffers (with the qual matrix replaced by the packed payload when
    the device already compacted it).  The ``parquet.encode.bytes_in``
    counter; against ``bytes_out`` (the assembled arrow table) it makes
    the packed-column encode shrink directly visible in
    ``--metrics-json`` snapshots and ``adam-tpu analyze``."""
    from adam_tpu.io.arrow_pack import PackedColumns

    packed_bases = None
    if isinstance(packed, PackedColumns):
        packed_bases = packed.bases
        packed = packed.quals
    total = 0
    for name in ("bases", "quals", "cigar_ops", "cigar_lens"):
        arr = getattr(batch, name, None)
        if name == "quals" and packed is not None:
            total += int(getattr(packed.buf, "nbytes", 0))
            continue
        if name == "bases" and packed_bases is not None:
            total += int(getattr(packed_bases.buf, "nbytes", 0))
            continue
        total += int(getattr(arr, "nbytes", 0) or 0)
    for name in ("names", "attrs", "md", "orig_quals"):
        col = getattr(side, name, None)
        buf = getattr(col, "buf", None)
        total += int(getattr(buf, "nbytes", 0) or 0)
    return total


def _count_encode_bytes(tr, batch, side, table, packed=None) -> None:
    from adam_tpu.utils import telemetry as tele

    if not tr.recording:
        return
    tr.count(tele.C_ENCODE_BYTES_IN, _encode_bytes_in(batch, side, packed))
    tr.count(tele.C_ENCODE_BYTES_OUT, int(table.nbytes))


def _write_encoded(table: "pa.Table", path: str, compression: str,
                   tracer=None) -> None:
    from adam_tpu.utils import faults
    from adam_tpu.utils import instrumentation as ins
    from adam_tpu.utils import telemetry as tele

    # part/byte counters land on ``tracer`` when given (the streamed
    # run tracer — in the multi-job service each job's heartbeat must
    # see only ITS parts, not the pool-wide total); the global TRACE
    # still gets them at end of run via the tracer absorb
    tr = tracer if tracer is not None else tele.TRACE
    tmp = _staging_path(path)
    # io-shard threads carry no trace_scope TLS, so the part-write span
    # is stamped from the run tracer's job trace explicitly — the
    # gateway /trace export must reach all the way to the part write
    span_attrs = {"path": os.path.basename(path)}
    job_trace = getattr(tracer, "trace", None)
    if job_trace:
        span_attrs["trace"] = job_trace
    with ins.TIMERS.time(ins.PARQUET_WRITE), tele.TRACE.span(
        tele.SPAN_PART_WRITE, **span_attrs
    ):
        faults.point("parquet.write")

        def write_to(tmp_path_):
            # dictionary-encode only the low-cardinality name columns:
            # letting the writer attempt dictionaries on the mostly-
            # unique readName/sequence/qual columns builds dicts it
            # then abandons (~20% of write time on a WGS-shaped part)
            pq.write_table(
                table, tmp_path_,
                use_dictionary=["contig", "mateContig", "recordGroupName"],
                **parquet_codec_kw(compression),
            )

        # claim the staging slot with an empty file FIRST: concurrent
        # writers share the staging dir (the sharded executor's thread
        # pool), and a sibling's opportunistic rmdir below can delete
        # it between our mkdir and our file create — but a non-empty
        # dir is rmdir-proof (ENOTEMPTY), so once the claim lands the
        # real write below cannot lose the race
        while True:
            try:
                with open(tmp, "wb"):
                    pass
                break
            except FileNotFoundError:
                tmp = _staging_path(path)
        try:
            write_to(tmp)
            # publish: readers either see the complete part or nothing.
            # Durable, not just atomic (docs/ROBUSTNESS.md): the bytes
            # are fsync'd before the rename and the directory entry
            # after it, so a power loss after publish cannot surface a
            # torn part under the final name — the guarantee the
            # streamed run journal's "window complete" records lean on.
            from adam_tpu.utils.durability import publish_file

            publish_file(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        # chaos-harness kill point: the part is durably published but
        # the caller (journal append, pool bookkeeping) has not run —
        # a resume must tolerate a published part the journal does not
        # know about (it rewrites the same bytes)
        faults.point("proc.kill", device="write")
    try:
        # opportunistic: drop the staging dir once it empties (fails
        # with ENOTEMPTY while sibling parts are still in flight)
        os.rmdir(os.path.dirname(tmp))
    except OSError:
        pass
    if tr.recording:
        tr.count(tele.C_PARTS_WRITTEN)
        try:
            tr.count(tele.C_BYTES_WRITTEN, os.path.getsize(path))
        except OSError:
            pass


def save_alignments(
    path: str, batch: ReadBatch, side: ReadSidecar, header: SamHeader,
    compression: str = "zstd",
) -> None:
    from adam_tpu.utils import instrumentation as ins
    from adam_tpu.utils import telemetry as tele

    with ins.TIMERS.time(ins.PARQUET_ENCODE), tele.TRACE.span(
        tele.SPAN_PART_ENCODE, rows=int(batch.n_rows)
    ):
        table = to_arrow_alignments(batch, side, header)
    if tele.TRACE.recording:
        tele.TRACE.count(tele.C_BYTES_ENCODED, int(table.nbytes))
    _count_encode_bytes(tele.TRACE, batch, side, table)
    _write_encoded(table, path, compression)


def _affinity_cap(floor: int = 1, ceil: int = 8) -> int:
    """Cores this process may actually run on, clamped to [floor, ceil]
    — the bound on every adaptive writer-pool growth decision."""
    try:
        n = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # non-Linux fallback
        n = os.cpu_count() or 1
    return max(floor, min(ceil, n))


def resolve_writer_shards(requested: Optional[int] = None) -> int:
    """Number of independent write threads (``ADAM_TPU_WRITER_SHARDS``
    override, clamped to [1, 8]): parts shard across them by part
    index, so K compress+fsync streams run concurrently — the
    per-process writer shape the multi-host ROADMAP item needs.
    Default: 2 when the affinity allows it (compression releases the
    GIL, and one flushing part must not stall the next), 1 on
    single-core hosts."""
    if requested is not None:
        return max(1, min(8, int(requested)))
    raw = os.environ.get("ADAM_TPU_WRITER_SHARDS", "").strip()
    if raw:
        try:
            return max(1, min(8, int(raw)))
        except ValueError:
            import logging

            logging.getLogger(__name__).warning(
                "ADAM_TPU_WRITER_SHARDS=%r is not an int; using the "
                "affinity-derived default", raw,
            )
    return min(2, _affinity_cap())


def writer_adaptive_enabled(default: bool = True) -> bool:
    """``ADAM_TPU_WRITER_ADAPTIVE`` toggle for the submit-gate growth
    (``0/off/false`` pins the pool at its construction bounds — the
    legacy fixed-width behavior the A/B perf gates compare against);
    parsed by the shared ``utils/retry.env_toggle`` contract."""
    from adam_tpu.utils.retry import env_toggle

    return env_toggle("ADAM_TPU_WRITER_ADAPTIVE", default)


#: A submit that waited longer than this on the gate counts as GATED —
#: the writer pool is back-pressuring the apply loop — and feeds the
#: adaptive growth decision (the same samples land in the
#: ``parquet.pool.submit_wait`` histogram).
_GATED_WAIT_S = 0.02
#: Grow when at least this many of the last ``_GATE_WINDOW`` submits
#: gated: one slow flush is noise, repeated gating is a sizing signal.
_GATE_WINDOW = 4
_GATE_TRIP = 2


class PartWriterPool:
    """Adaptive, sharded part-file writer (the streamed pipeline's pass
    C sink).

    Two stages per part: **encode** (columnar batch -> arrow table; CPU
    work, encoder threads) hands off to one of ``n_io`` **independent
    write threads** (compression + disk; releases the GIL), parts
    sharded across them by part index so one part's flush never stalls
    another's — the per-process writer shape the multi-host mesh needs.
    At most ``inflight_parts`` parts are alive inside the pool at once;
    the gate is taken in :meth:`submit` (the producer blocks) and
    released after the part's bytes hit disk, so peak memory is the
    inflight bound in decoded parts.

    **Adaptive sizing** (``adaptive=True``): when submits repeatedly
    gate — the producer measurably blocked, the signal the
    ``parquet.pool.submit_wait`` histogram records — the pool widens
    its admission bound one part at a time (letting another encoder
    thread run concurrently), bounded by the scheduling affinity: the
    pool grows only while the writer tail is the measured ceiling and
    never past the cores that could serve it.  The live bound lands in
    the ``parquet.pool.inflight_bound`` gauge.  Crash consistency is
    per part and unchanged on every width: staging write + durable
    publish (``utils/durability``), first-failure fail-fast, staging
    discarded on abort.
    """

    def __init__(self, n_encoders: int = 2, inflight_parts: int = 3,
                 compression: str = "zstd", on_published=None,
                 tracer=None, n_io: Optional[int] = None,
                 adaptive: Optional[bool] = None):
        import threading
        from concurrent.futures import ThreadPoolExecutor

        self._adaptive = (
            writer_adaptive_enabled() if adaptive is None else adaptive
        )
        n_io = resolve_writer_shards(n_io)
        # admission bound (parts alive in the pool).  Every admitted
        # part pins one DECODED window, so the adaptive cap bounds
        # memory as well as concurrency: at most one slot per
        # plausibly-useful encoder (affinity) plus one per write
        # thread, and never more than 2x the construction bound — the
        # caller sized ``inflight_parts`` to its memory budget, and
        # adaptive growth may stretch that budget, not ignore it.
        self._bound = max(1, inflight_parts)
        self._bound_cap = (
            max(
                self._bound,
                min(_affinity_cap() + n_io, 2 * self._bound),
            )
            if self._adaptive else self._bound
        )
        enc_cap = max(1, n_encoders)
        if self._adaptive:
            enc_cap = max(enc_cap, _affinity_cap())
        # ThreadPoolExecutor spawns workers lazily: idle capacity above
        # the admission bound costs nothing until growth admits work
        self._enc = ThreadPoolExecutor(enc_cap)
        # K independent single-thread write executors; part i lands on
        # shard i % K, so shard-local write order stays submission
        # order (the journal's publish hook needs no further ordering)
        self._io = [ThreadPoolExecutor(1) for _ in range(n_io)]
        # atomic round-robin fallback for non-canonical part names:
        # _io_shard runs concurrently on encoder threads
        import itertools

        self._io_rr = itertools.count()
        self._gate = threading.Semaphore(self._bound)
        self._gate_lock = threading.Lock()
        self._gated_recent: deque = deque(maxlen=_GATE_WINDOW)
        self._compression = compression
        # byte/part counters, queue-depth gauge and submit-wait samples
        # go to ``tracer`` when given (the streamed run tracer: a
        # multi-job service runs one pool per job, and each job's
        # heartbeat must count only its own parts); None keeps the
        # global-TRACE behavior for standalone use
        self._tracer = tracer
        # durable-completion hook, called as on_published(path) on the
        # write thread AFTER a part's atomic+fsync'd publish (the
        # streamed run journal records "window complete" here — by
        # contract never before the bytes are durably on disk).  A hook
        # failure is a worker failure: losing the completion record
        # would silently disable resume for that window.
        self._on_published = on_published
        self._futures: list = []
        # submit-gate depth (parts alive inside the pool), sampled into
        # the telemetry gauge at submit and at drain; the int itself is
        # maintained unconditionally (one locked increment per PART) so
        # toggling recording mid-run cannot skew the samples
        self._depth = 0
        self._depth_lock = threading.Lock()
        # first worker failure, chronologically (encode OR write): the
        # original exception object, so close() re-raises it with its
        # traceback intact and submit() can fail fast instead of
        # queueing parts behind a dead writer
        self._failed: BaseException | None = None
        self._fail_lock = threading.Lock()
        self._staging_dirs: set = set()

    def _record_failure(self, e: BaseException) -> None:
        with self._fail_lock:
            if self._failed is None:
                self._failed = e

    @property
    def failed(self) -> BaseException | None:
        """The first worker failure so far, or None (a producer can
        poll this between submits to abort a doomed run early)."""
        with self._fail_lock:
            return self._failed

    def _metric_tracer(self):
        from adam_tpu.utils import telemetry as tele

        return self._tracer if self._tracer is not None else tele.TRACE

    def _sample_depth(self, delta: int) -> None:
        from adam_tpu.utils import telemetry as tele

        # the gauge write happens INSIDE the depth lock: with K write
        # threads releasing concurrently, an outside-the-lock write
        # could publish a stale sample after a fresher one (thread A
        # reads depth 2, thread B reads 1 and writes the gauge, THEN A
        # writes 2) — the gauge would read high/stale until the next
        # sample.  Ordering the gauge with the counter makes the last
        # write always the true current depth, and the depth itself is
        # incremented before submit enqueues / decremented before the
        # gate reopens, so it can never read negative or exceed the
        # admission bound.
        tr = self._metric_tracer()
        with self._depth_lock:
            self._depth += delta
            assert self._depth >= 0, "writer-pool depth underflow"
            tr.gauge(tele.G_POOL_DEPTH, self._depth)

    def _io_shard(self, path: str):
        """The write executor for a part: sharded by part index so a
        window sequence stripes across the K write threads; non-part
        names (standalone use) round-robin."""
        idx = part_index(path)
        if idx is None:
            idx = next(self._io_rr)  # itertools.count: atomic under GIL
        return self._io[idx % len(self._io)]

    def _maybe_grow(self, gated: bool) -> None:
        """Adaptive admission: widen the gate one part when submits
        repeatedly gate (the live submit-wait signal), up to the
        affinity-derived cap.  One extra slot admits one more part —
        and with it one more concurrent encoder thread."""
        from adam_tpu.utils import telemetry as tele

        if not self._adaptive:
            return
        with self._gate_lock:
            self._gated_recent.append(gated)
            if (
                sum(self._gated_recent) < _GATE_TRIP
                or self._bound >= self._bound_cap
            ):
                return
            self._bound += 1
            self._gated_recent.clear()
            bound = self._bound
        self._gate.release()
        tr = self._metric_tracer()
        if tr.recording:
            tr.gauge(tele.G_POOL_BOUND, bound)

    @property
    def inflight_bound(self) -> int:
        """The live admission bound (grows under adaptive sizing)."""
        with self._gate_lock:
            return self._bound

    def submit(self, path: str, batch: ReadBatch, side: ReadSidecar,
               header: SamHeader, packed=None) -> None:
        from adam_tpu.utils import faults
        from adam_tpu.utils import instrumentation as ins
        from adam_tpu.utils import telemetry as tele

        # fail fast: once any worker failed there is no point queueing
        # (and gating on) further parts — surface the doomed run's first
        # error to the producer NOW, with its original context chained
        first = self.failed
        if first is not None:
            raise RuntimeError(
                "PartWriterPool worker already failed; aborting submit "
                f"of {path}"
            ) from first
        self._staging_dirs.add(
            os.path.join(os.path.dirname(os.path.abspath(path)),
                         TMP_DIR_NAME)
        )

        def release():
            # decrement BEFORE releasing the gate: a submitter unblocked
            # by the release must never observe a depth above the
            # admission bound the gauge exists to monitor
            self._sample_depth(-1)
            self._gate.release()

        def encode():
            try:
                faults.point("parquet.encode")
                # same reason as _write_encoded: encoder threads have no
                # trace_scope TLS, so stamp the job trace explicitly
                enc_attrs = {"rows": int(batch.n_rows)}
                job_trace = getattr(self._tracer, "trace", None)
                if job_trace:
                    enc_attrs["trace"] = job_trace
                with ins.TIMERS.time(ins.PARQUET_ENCODE), tele.TRACE.span(
                    tele.SPAN_PART_ENCODE, **enc_attrs
                ):
                    table = to_arrow_alignments(
                        batch, side, header, packed=packed
                    )
                tr = self._metric_tracer()
                if tr.recording:
                    tr.count(
                        tele.C_BYTES_ENCODED, int(table.nbytes)
                    )
                _count_encode_bytes(tr, batch, side, table, packed)
                return self._io_shard(path).submit(write, table)
            except BaseException as e:
                # the gate MUST release on the error path: the producer
                # may be blocked in submit() on a full gate, and an
                # un-released slot would deadlock the abort
                self._record_failure(e)
                release()
                raise

        def write(table):
            try:
                _write_encoded(table, path, self._compression,
                               tracer=self._tracer)
                if self._on_published is not None:
                    self._on_published(path)
            except BaseException as e:
                self._record_failure(e)
                raise
            finally:
                release()

        # backpressure: bound whole parts in flight.  The time the
        # producer blocks here IS the writer-pool backpressure signal —
        # a histogram (not a scalar) because one slow flush stalling a
        # single submit looks identical to chronic starvation in a
        # total, but not in the p99.  The same samples drive the
        # adaptive admission growth in _maybe_grow.
        tr = self._metric_tracer()
        rec = tr.recording
        t_gate = time.monotonic()
        self._gate.acquire()
        wait_s = time.monotonic() - t_gate
        if rec:
            tr.observe(tele.H_POOL_SUBMIT_WAIT, wait_s)
        self._maybe_grow(wait_s > _GATED_WAIT_S)
        self._sample_depth(+1)
        try:
            self._futures.append(self._enc.submit(encode))
        except BaseException:
            release()
            raise

    def _discard_staging(self) -> None:
        """Remove any unpublished staging files (abort/error path);
        published parts are untouched — the atomic-rename protocol
        means there is nothing half-written outside the staging dir."""
        for d in self._staging_dirs:
            try:
                for name in os.listdir(d):
                    if name.endswith(".tmp"):
                        try:
                            os.unlink(os.path.join(d, name))
                        except OSError:
                            pass
                os.rmdir(d)
            except OSError:
                pass

    def close(self, abort: bool = False) -> None:
        """Drain both stages; re-raise the first worker error — the
        original exception object, so its traceback survives (close is
        the producer's only window onto the worker threads' failures).
        ``abort=True``: the producer is already unwinding from its own
        error — drain, clean the staging files, and swallow nothing
        into its traceback (the caller re-raises its own)."""
        errs = []
        for f in self._futures:
            try:
                wf = f.result()
            except BaseException as e:
                errs.append(e)
                continue
            err = wf.exception()
            if err is not None:
                errs.append(err)
        self._enc.shutdown()
        for ex in self._io:
            ex.shutdown()
        first = self.failed
        if first is None and errs:
            first = errs[0]
        if abort or first is not None:
            self._discard_staging()
        if first is not None and not abort:
            raise first


def load_alignments(
    path: str,
    projection: Optional[Sequence[str]] = None,
    predicate=None,
    round_rows_to: int = 1,
) -> tuple[ReadBatch, ReadSidecar, SamHeader]:
    """Load with optional column projection and pyarrow filter predicate.

    ``projection`` is a subset of ALIGNMENT_FIELDS; essential columns for
    batch building are always read.  ``predicate`` is a pyarrow
    ``filters``-style expression (pyarrow.compute expression).
    """
    cols = None
    if projection is not None:
        essential = {"sequence", "qual", "flags", "cigar", "start", "contig"}
        cols = sorted(set(projection) | essential)
    table = pq.read_table(path, columns=cols, filters=predicate)
    return from_arrow_alignments(table, round_rows_to=round_rows_to)


def _string_column_or(table, name: str, n: int, default=None):
    from adam_tpu.formats.strings import StringColumn

    if name in table.column_names:
        return StringColumn.from_arrow(table[name])
    return StringColumn.from_list([default] * n)


def _int_col(table, name: str, n: int, default, dtype):
    import pyarrow.compute as pc

    if name not in table.column_names:
        return np.full(n, default, dtype)
    return np.asarray(
        pc.fill_null(table[name], default).combine_chunks()
    ).astype(dtype)


def _name_index_col(col, lookup) -> np.ndarray:
    """Dictionary-index a string column: unique names -> lookup() once."""
    fixed = col.to_fixed_bytes()
    uniq, inv = np.unique(fixed, return_inverse=True)
    idx = np.array(
        [lookup(u.decode("utf-8", "replace")) if u else -1 for u in uniq],
        np.int32,
    )
    out = idx[inv]
    return np.where(col.valid, out, -1).astype(np.int32)


def _codes_matrix(col, lut: np.ndarray, pad: int):
    """StringColumn -> (codes u8[N, W], lengths i32[N]) via one LUT pass.

    Fixed-length reads are the overwhelmingly common case, so two fast
    paths: all-rows-uniform-and-contiguous is a single reshape (zero
    gathers); uniform-but-sparse is one broadcasted gather.  The generic
    ragged path falls back to the span machinery.
    """
    from adam_tpu.formats.strings import (
        _span_gather_indices,
        _span_local_positions,
    )

    lens = np.where(col.valid, col.lengths(), 0)
    n = len(lens)
    w = max(1, int(lens.max()) if n else 1)
    if n and lens.sum():
        nz = np.flatnonzero(lens > 0)
        u0 = lens[nz[0]]
        uniform = (lens[nz] == u0).all()
        if uniform and len(nz) == n and int(col.offsets[-1]) == n * int(u0) \
                and int(u0) == w:
            vals = col.buf[: n * w].reshape(n, w)
            mat = lut[vals] if lut is not None else vals.copy()
            return mat, lens.astype(np.int32)
        mat = np.full((n, w), pad, np.uint8)
        if uniform:
            w0 = int(u0)
            src = (
                col.offsets[nz][:, None] + np.arange(w0, dtype=np.int64)
            ).ravel()
            vals = col.buf[src].reshape(len(nz), w0)
            mat[nz, :w0] = lut[vals] if lut is not None else vals
        else:
            src = _span_gather_indices(col.offsets[:-1], lens)
            rows = np.repeat(np.arange(n), lens)
            pos = _span_local_positions(lens)
            mat[rows, pos] = (
                lut[col.buf[src]] if lut is not None else col.buf[src]
            )
        return mat, lens.astype(np.int32)
    return np.full((n, w), pad, np.uint8), lens.astype(np.int32)


def from_arrow_alignments(
    table, round_rows_to: int = 1
) -> tuple[ReadBatch, ReadSidecar, SamHeader]:
    """Arrow Table (AlignmentRecord layout) -> columnar batch — fully
    vectorized: LUT passes for sequences/quals, native (or numpy-loop
    fallback) CIGAR column parse, dictionary-indexed name columns.  The
    inverse of :func:`to_arrow_alignments` and the import half of the
    Spark/Arrow embedding seam."""
    from adam_tpu import native
    from adam_tpu.formats.strings import StringColumn

    header = _header_from_meta(table.schema.metadata)
    sd, rgd = header.seq_dict, header.read_groups
    n = table.num_rows

    seq_col = _string_column_or(table, "sequence", n)
    qual_col = _string_column_or(table, "qual", n)
    bases, lengths = _codes_matrix(seq_col, schema.BASE_ENCODE_LUT,
                                   schema.BASE_PAD)
    lmax = bases.shape[1]
    quals_mat, qlens = _codes_matrix(qual_col, None, 0)
    has_qual = qual_col.valid & (qlens > 0) & ~(
        (qlens == 1) & (quals_mat[:, 0] == ord("*"))
    )
    quals = np.full((n, lmax), schema.QUAL_PAD, np.uint8)
    w = min(lmax, quals_mat.shape[1])
    qmask = (np.arange(w)[None, :] < qlens[:, None]) & has_qual[:, None]
    quals[:, :w][qmask] = (quals_mat[:, :w][qmask] - schema.SANGER_OFFSET)
    # reads with sequence but no qual get 0-quals over their length
    noq = ~has_qual
    inlen = np.arange(lmax)[None, :] < lengths[:, None]
    quals[noq[:, None] & inlen] = 0

    cig_col = _string_column_or(table, "cigar", n)
    cig_lens_b = np.where(cig_col.valid, cig_col.lengths(), 0)
    is_digit = (cig_col.buf >= ord("0")) & (cig_col.buf <= ord("9"))
    n_ops_cap = (
        np.add.reduceat(
            (~is_digit).astype(np.int64),
            np.minimum(cig_col.offsets[:-1], max(len(cig_col.buf) - 1, 0)),
        )
        if len(cig_col.buf) and n
        else np.zeros(n, np.int64)
    )
    # rows with empty spans get garbage from reduceat; zero them
    n_ops_cap = np.where(cig_lens_b > 0, n_ops_cap, 0)
    cmax = max(1, int(n_ops_cap.max()) if n else 1)
    offsets = cig_col.offsets.copy()
    # invalid rows: collapse their span so the parser sees empty
    if not cig_col.valid.all():
        pass  # offsets describe the buffer; invalid rows parse as-is
    nat = native.cigar_cols(cig_col.buf, offsets, cmax)
    if nat is not None:
        cigar_ops, cigar_lens, cigar_n = nat
        cigar_n = np.where(cig_col.valid, cigar_n, 0).astype(np.int32)
    else:  # pure-python fallback
        cigar_ops = np.full((n, cmax), schema.CIGAR_PAD, np.uint8)
        cigar_lens = np.zeros((n, cmax), np.int32)
        cigar_n = np.zeros(n, np.int32)
        for i in range(n):
            c = cig_col[i]
            if not c or c == "*":
                continue
            o, l, k = schema.encode_cigar(c, cmax)
            cigar_ops[i], cigar_lens[i], cigar_n[i] = o, l, k

    start = _int_col(table, "start", n, -1, np.int64)
    flags = _int_col(table, "flags", n, 4, np.int32)
    # end: prefer the stored column; else start + reference span
    if "end" in table.column_names:
        end = _int_col(table, "end", n, -1, np.int64)
    else:
        r_consume = schema.CIGAR_CONSUMES_REF[
            np.minimum(cigar_ops, 15)
        ].astype(np.int64)
        rlen = (cigar_lens * r_consume).sum(axis=1)
        end = np.where(start >= 0, start + rlen, -1)

    batch = ReadBatch(
        bases=bases,
        quals=quals,
        lengths=lengths,
        flags=flags,
        contig_idx=_name_index_col(
            _string_column_or(table, "contig", n), sd.index_or
        ),
        start=start,
        end=end,
        mapq=_int_col(table, "mapq", n, 255, np.int32),
        cigar_ops=cigar_ops,
        cigar_lens=cigar_lens,
        cigar_n=cigar_n,
        mate_contig_idx=_name_index_col(
            _string_column_or(table, "mateContig", n), sd.index_or
        ),
        mate_start=_int_col(table, "mateAlignmentStart", n, -1, np.int64),
        tlen=_int_col(table, "inferredInsertSize", n, 0, np.int32),
        read_group_idx=_name_index_col(
            _string_column_or(table, "recordGroupName", n), rgd.index_or
        ),
        has_qual=has_qual,
        valid=np.ones(n, bool),
    )
    side = ReadSidecar(
        names=_string_column_or(table, "readName", n, default=""),
        attrs=_string_column_or(table, "attributes", n, default=""),
        md=_string_column_or(table, "mismatchingPositions", n),
        orig_quals=_string_column_or(table, "origQual", n),
        trimmed_from_start=_int_col(
            table, "basesTrimmedFromStart", n, 0, np.int32
        ),
        trimmed_from_end=_int_col(table, "basesTrimmedFromEnd", n, 0, np.int32),
    )
    if round_rows_to > 1:
        g = ((n + round_rows_to - 1) // round_rows_to) * round_rows_to
        if g != n:
            batch = batch.pad_rows(g)
    return batch, side, header


# ===================================================================
# Variation storage (vcf2adam / adam2vcf round-trip target).
#
# The reference saves Genotype/Variant Avro records through the same
# adamParquetSave path; here the GenotypeDataset persists as a directory
# with two columnar tables, `variants.parquet` + `genotypes.parquet`,
# linked by genotype.variantIdx (sites-only VCFs simply have an empty
# genotype table).
# ===================================================================

def _seq_dict_meta(seq_dict) -> dict[bytes, bytes]:
    meta = [
        {"name": r.name, "length": r.length, "md5": r.md5, "url": r.url}
        for r in seq_dict
    ]
    return {b"adam_tpu.seq_dict": json.dumps(meta).encode()}


def _seq_dict_from_meta(meta) -> "SequenceDictionary":
    if not meta or b"adam_tpu.seq_dict" not in meta:
        return SequenceDictionary(())
    return SequenceDictionary(
        tuple(
            SequenceRecord(s["name"], s["length"], md5=s.get("md5"),
                           url=s.get("url"))
            for s in json.loads(meta[b"adam_tpu.seq_dict"])
        )
    )


def save_genotypes(path: str, variants, genotypes, seq_dict,
                   compression: str = "zstd",
                   typed_annotations=None) -> None:
    """``typed_annotations``: ``{adamKey: [value-or-None per variant]}``
    from formats/annotations.split_typed — stored as real typed
    ``ann_<adamKey>`` Parquet columns (the VariantAnnotationConverter
    analog), so annotation predicates push down like any other column.
    """
    import os

    from adam_tpu.formats import variants as vf

    os.makedirs(path, exist_ok=True)
    vside = variants.sidecar
    cols = {
            "contig": pa.array(
                [seq_dict.names[c] for c in variants.contig_idx], pa.string()
            ),
            "start": pa.array(variants.start.tolist(), pa.int64()),
            "end": pa.array(variants.end.tolist(), pa.int64()),
            "referenceAllele": pa.array(vside.ref_allele, pa.string()),
            "alternateAllele": pa.array(vside.alt_allele, pa.string()),
            "qual": pa.array(
                [None if np.isnan(q) else float(q) for q in variants.qual],
                pa.float64(),
            ),
            "filtersApplied": pa.array(
                variants.filters_applied.tolist(), pa.bool_()
            ),
            "filtersPassed": pa.array(variants.passing.tolist(), pa.bool_()),
            "name": pa.array(vside.names, pa.string()),
            "filters": pa.array(vside.filters, pa.list_(pa.string())),
            "annotations": pa.array(
                [json.dumps(d) for d in vside.info], pa.string()
            ),
            # row index: lets a pushed-down variant predicate select the
            # matching genotype rows without reading the full table
            "variantIdx": pa.array(
                np.arange(len(variants.start), dtype=np.int32), pa.int32()
            ),
        }
    if typed_annotations is None:
        # default: split recognized INFO keys into typed columns (the
        # loadVcf-side VariantAnnotationConverter application); pass {}
        # to disable
        from adam_tpu.formats.annotations import split_typed

        typed_annotations, leftover = split_typed(vside.info)
        if typed_annotations:
            cols["annotations"] = pa.array(
                [json.dumps(d) for d in leftover], pa.string()
            )
    if typed_annotations:
        from adam_tpu.formats.annotations import arrow_type

        for adam_key in sorted(typed_annotations):
            cols[f"ann_{adam_key}"] = pa.array(
                typed_annotations[adam_key], arrow_type(adam_key)
            )
    vt = pa.table(cols).replace_schema_metadata(_seq_dict_meta(seq_dict))
    pq.write_table(vt, os.path.join(path, "variants.parquet"),
                   **parquet_codec_kw(compression))

    gt = pa.table(
        {
            "variantIdx": pa.array(genotypes.variant_idx.tolist(), pa.int32()),
            "sampleId": pa.array(
                [genotypes.samples[s] for s in genotypes.sample_idx],
                pa.string(),
            ),
            "allele0": pa.array(genotypes.alleles[:, 0].tolist(), pa.int8()),
            "allele1": pa.array(genotypes.alleles[:, 1].tolist(), pa.int8()),
            "genotypeQuality": pa.array(genotypes.gq.tolist(), pa.int32()),
            "readDepth": pa.array(genotypes.dp.tolist(), pa.int32()),
            "referenceReadDepth": pa.array(
                genotypes.ref_depth.tolist(), pa.int32()
            ),
            "alternateReadDepth": pa.array(
                genotypes.alt_depth.tolist(), pa.int32()
            ),
            "isPhased": pa.array(genotypes.phased.tolist(), pa.bool_()),
            "genotypeLikelihoods": pa.array(
                genotypes.pl.tolist(), pa.list_(pa.int32())
            ),
            "nonReferenceLikelihoods": pa.array(
                genotypes.nonref_pl.tolist(), pa.list_(pa.int32())
            ),
            "splitFromMultiAllelic": pa.array(
                genotypes.split_from_multiallelic.tolist(), pa.bool_()
            ),
            "genotypeFilters": pa.array(
                list(genotypes.genotype_filters), pa.string()
            ),
        }
    )
    pq.write_table(gt, os.path.join(path, "genotypes.parquet"),
                   **parquet_codec_kw(compression))


def _likelihood_matrix(col, m: int, what: str) -> np.ndarray:
    """Genotype likelihood lists -> i32[m, 3], tolerating externally
    produced files whose lists are not exactly length 3 (padded with 0 /
    truncated, with a clear warning) instead of an opaque reshape error."""
    if not m:
        return np.zeros((0, 3), np.int32)
    rows = col.to_pylist()
    if all(r is not None and len(r) == 3 for r in rows):
        return np.array(rows, np.int32).reshape(m, 3)
    import logging

    logging.getLogger(__name__).warning(
        "%s: lists are not uniformly length 3; padding/truncating "
        "(bi-allelic PL layout expected)", what,
    )
    out = np.zeros((m, 3), np.int32)
    for i, r in enumerate(rows):
        if r:
            out[i, : min(3, len(r))] = r[:3]
    return out


def _pylist_or(t, name: str, n: int, default):
    """Column as pylist, or defaults when projected away."""
    if name in t.column_names:
        return t[name].to_pylist()
    return [default] * n


def load_genotypes(path: str, contig_names=None, projection=None,
                   filters=None):
    """-> (VariantBatch, GenotypeBatch, SequenceDictionary).

    ``contig_names`` optionally fixes the contig index space (e.g. from a
    BAM header), as in :func:`adam_tpu.io.vcf.read_vcf`.

    ``projection`` is a subset of VARIANT_FIELDS | GENOTYPE_FIELDS
    (formats/fields.py, mirroring GenotypeField/VariantField enums,
    projections/GenotypeField.scala): only those Parquet columns are
    read; everything else comes back as defaults.  ``filters`` is a
    pyarrow predicate over the VARIANT columns, pushed down to the
    variants read; the matching genotype rows are selected by a pushed
    ``variantIdx in ...`` predicate and re-indexed.
    """
    import os

    from adam_tpu.formats import variants as vf
    from adam_tpu.formats.fields import (
        GENOTYPE_FIELDS,
        VARIANT_FIELDS,
        validate_projection,
    )

    v_cols = g_cols = None
    if projection is not None:
        proj = set(projection)
        bad = sorted(proj - (VARIANT_FIELDS | GENOTYPE_FIELDS))
        if bad:
            raise ValueError(
                f"unknown genotype/variant projection field(s) {bad}"
            )
        v_cols = validate_projection(
            sorted(proj & VARIANT_FIELDS), VARIANT_FIELDS,
            ("contig", "start", "end", "referenceAllele",
             "alternateAllele", "variantIdx"),
            "variant",
        )
        g_cols = validate_projection(
            sorted(proj & GENOTYPE_FIELDS), GENOTYPE_FIELDS,
            ("variantIdx", "sampleId", "allele0", "allele1"),
            "genotype",
        )
    v_path = os.path.join(path, "variants.parquet")
    if v_cols is not None:
        # legacy stores predate the variantIdx row-index column
        present = set(pq.read_schema(v_path).names)
        if "annotations" in v_cols:
            # projecting the annotations field means ALL annotations,
            # including the keys the save split into typed ann_* columns
            v_cols = v_cols + sorted(
                c for c in present if c.startswith("ann_")
            )
        v_cols = [c for c in v_cols if c in present]
    vt = pq.read_table(v_path, columns=v_cols, filters=filters)
    if contig_names is not None:
        seq_dict = SequenceDictionary(
            tuple(SequenceRecord(n, 0) for n in contig_names)
        )
    else:
        seq_dict = _seq_dict_from_meta(vt.schema.metadata)
    name_idx = {n: i for i, n in enumerate(seq_dict.names)}
    contigs = vt["contig"].to_pylist()
    for c in contigs:
        if c not in name_idx:
            name_idx[c] = len(name_idx)
    names = [None] * len(name_idx)
    for n, i in name_idx.items():
        names[i] = n
    if len(names) > len(seq_dict.names):
        seq_dict = SequenceDictionary(
            tuple(
                list(seq_dict.records)
                + [SequenceRecord(n, 0) for n in names[len(seq_dict.names):]]
            )
        )

    nv = vt.num_rows
    info = [
        json.loads(s) if s else {}
        for s in _pylist_or(vt, "annotations", nv, None)
    ]
    ann_cols = [c for c in vt.column_names if c.startswith("ann_")]
    if ann_cols:
        # typed annotation columns (VariantAnnotationConverter analog)
        # merge back under their VCF keys
        from adam_tpu.formats.annotations import merge_typed

        cols = {}
        for c in ann_cols:
            vals = vt[c].to_pylist()
            if vt.schema.field(c).type == pa.float32():
                # legacy float32 store: keep the column's own precision
                # so formatting doesn't emit float64-widening noise
                # digits (2.31 -> "2.309999942779541")
                vals = [None if v is None else np.float32(v) for v in vals]
            cols[c[4:]] = vals
        info = merge_typed(cols, info)
    side = vf.VariantSidecar(
        ref_allele=vt["referenceAllele"].to_pylist(),
        alt_allele=vt["alternateAllele"].to_pylist(),
        names=_pylist_or(vt, "name", nv, None),
        filters=_pylist_or(vt, "filters", nv, None),
        info=info,
    )
    quals = [
        np.nan if q is None else q for q in _pylist_or(vt, "qual", nv, None)
    ]
    variants = vf.VariantBatch(
        contig_idx=np.array([name_idx[c] for c in contigs], np.int32),
        start=np.array(vt["start"].to_pylist(), np.int64),
        end=np.array(vt["end"].to_pylist(), np.int64),
        ref_len=np.array([len(r) for r in side.ref_allele], np.int32),
        alt_len=np.array(
            [len(a) if a else 0 for a in side.alt_allele], np.int32
        ),
        qual=np.array(quals, np.float32),
        filters_applied=np.array(
            _pylist_or(vt, "filtersApplied", nv, False), bool
        ),
        passing=np.array(_pylist_or(vt, "filtersPassed", nv, False), bool),
        sidecar=side,
    )

    g_path = os.path.join(path, "genotypes.parquet")
    g_filters = None
    remap = None
    if filters is not None:
        # surviving original variant rows: pushed down to the genotype
        # read, then genotype variant_idx re-indexes into the filtered
        # variant batch
        if "variantIdx" in vt.column_names:
            keep = np.asarray(vt["variantIdx"].combine_chunks(), np.int64)
        else:
            # legacy store without the row-index column: re-read only
            # the predicate-referenced columns with a synthesized row
            # index and evaluate the predicate in memory (identity-key
            # matching would mis-select under duplicate positions, e.g.
            # split multiallelics)
            import pyarrow.compute as pc

            expr = (
                filters if isinstance(filters, pc.Expression)
                else pq.filters_to_expression(filters)
            )
            # pyarrow has no public API for an Expression's referenced
            # fields, and guessing them from str(expr) mis-selects when a
            # column name collides with a string literal in the
            # predicate — a legacy store is rare enough to read whole
            full = pq.read_table(v_path)
            full = full.append_column(
                "__row", pa.array(np.arange(full.num_rows, dtype=np.int64))
            )
            keep = np.asarray(
                full.filter(expr)["__row"].combine_chunks(), np.int64
            )
        keep = np.sort(keep)
        import pyarrow.compute as pc

        g_filters = pc.field("variantIdx").isin(pa.array(keep))
        remap = keep
    gt = pq.read_table(g_path, columns=g_cols, filters=g_filters)
    sample_names = gt["sampleId"].to_pylist()
    samples: list = []
    sample_idx = {}
    si = []
    for s in sample_names:
        if s not in sample_idx:
            sample_idx[s] = len(samples)
            samples.append(s)
        si.append(sample_idx[s])
    m = gt.num_rows
    vidx = np.array(gt["variantIdx"].to_pylist(), np.int64)
    if remap is not None and m:
        vidx = np.searchsorted(remap, vidx)

    def _pl(name):
        if name in gt.column_names:
            return _likelihood_matrix(gt[name], m, name)
        return np.zeros((m, 3), np.int32)

    genotypes = vf.GenotypeBatch(
        variant_idx=vidx.astype(np.int32),
        sample_idx=np.array(si, np.int32),
        alleles=np.stack(
            [
                np.array(gt["allele0"].to_pylist(), np.int8),
                np.array(gt["allele1"].to_pylist(), np.int8),
            ],
            axis=1,
        ) if m else np.zeros((0, 2), np.int8),
        gq=np.clip(
            np.array(_pylist_or(gt, "genotypeQuality", m, 0), np.int32),
            0, 32767,
        ).astype(np.int16),
        dp=np.array(_pylist_or(gt, "readDepth", m, -1), np.int32),
        ref_depth=np.array(
            _pylist_or(gt, "referenceReadDepth", m, -1), np.int32
        ),
        alt_depth=np.array(
            _pylist_or(gt, "alternateReadDepth", m, -1), np.int32
        ),
        phased=np.array(_pylist_or(gt, "isPhased", m, False), bool),
        pl=_pl("genotypeLikelihoods"),
        nonref_pl=_pl("nonReferenceLikelihoods"),
        split_from_multiallelic=np.array(
            _pylist_or(gt, "splitFromMultiAllelic", m, False), bool
        ),
        samples=samples,
        genotype_filters=_pylist_or(gt, "genotypeFilters", m, None),
    )
    return variants, genotypes, seq_dict


# ===================================================================
# Feature storage (features2adam target).
# ===================================================================

def save_features(path: str, feats, compression: str = "zstd") -> None:
    side = feats.sidecar
    t = pa.table(
        {
            "contig": pa.array(
                [feats.contig_names[c] for c in feats.contig_idx], pa.string()
            ),
            "start": pa.array(feats.start.tolist(), pa.int64()),
            "end": pa.array(feats.end.tolist(), pa.int64()),
            "strand": pa.array(feats.strand.tolist(), pa.int8()),
            "score": pa.array(
                [None if np.isnan(s) else float(s) for s in feats.score],
                pa.float64(),
            ),
            "featureId": pa.array(side.feature_id, pa.string()),
            "featureType": pa.array(side.feature_type, pa.string()),
            "source": pa.array(side.source, pa.string()),
            "parentIds": pa.array(side.parent_ids, pa.list_(pa.string())),
            "attributes": pa.array(
                [json.dumps(d) for d in side.attributes], pa.string()
            ),
        }
    )
    pq.write_table(t, path, **parquet_codec_kw(compression))


def load_features(path: str, projection=None, filters=None):
    """``projection``: subset of FEATURE_FIELDS (FeatureField.scala);
    ``filters``: pyarrow predicate pushed into the Parquet read."""
    from adam_tpu.formats.features import FeatureBatch, FeatureSidecar
    from adam_tpu.formats.fields import FEATURE_FIELDS, validate_projection

    cols = validate_projection(
        projection, FEATURE_FIELDS, ("contig", "start", "end"), "feature"
    )
    t = pq.read_table(path, columns=cols, filters=filters)
    n = t.num_rows
    contigs = t["contig"].to_pylist()
    names: list = []
    idx = {}
    ci = []
    for c in contigs:
        if c not in idx:
            idx[c] = len(names)
            names.append(c)
        ci.append(idx[c])
    scores = [
        np.nan if s is None else s for s in _pylist_or(t, "score", n, None)
    ]
    return FeatureBatch(
        contig_idx=np.array(ci, np.int32),
        start=np.array(t["start"].to_pylist(), np.int64),
        end=np.array(t["end"].to_pylist(), np.int64),
        strand=np.array(_pylist_or(t, "strand", n, 0), np.int8),
        score=np.array(scores, np.float32),
        contig_names=names,
        sidecar=FeatureSidecar(
            feature_id=_pylist_or(t, "featureId", n, None),
            feature_type=_pylist_or(t, "featureType", n, None),
            source=_pylist_or(t, "source", n, None),
            parent_ids=_pylist_or(t, "parentIds", n, None),
            attributes=[
                json.loads(s) if s else {}
                for s in _pylist_or(t, "attributes", n, None)
            ],
        ),
    )


# ===================================================================
# Fragment storage (fasta2adam target).
# ===================================================================

def save_fragments(path: str, fragments, seq_dict,
                   descriptions=None, compression: str = "zstd") -> None:
    b = fragments.to_numpy()
    rows = np.flatnonzero(np.asarray(b.valid))
    # descriptions: contig_idx -> description; read_fasta hands back a
    # per-contig list, load_fragments a dict
    if isinstance(descriptions, (list, tuple)):
        descriptions = {i: d for i, d in enumerate(descriptions) if d}
    t = pa.table(
        {
            "contig": pa.array(
                [seq_dict.names[int(b.contig_idx[i])] for i in rows],
                pa.string(),
            ),
            "description": pa.array(
                [
                    (descriptions or {}).get(int(b.contig_idx[i]))
                    for i in rows
                ],
                pa.string(),
            ),
            "fragmentSequence": pa.array(
                [
                    schema.decode_bases(b.bases[i], int(b.lengths[i]))
                    for i in rows
                ],
                pa.string(),
            ),
            "fragmentStartPosition": pa.array(
                [int(b.start[i]) for i in rows], pa.int64()
            ),
            "fragmentNumber": pa.array(
                [int(b.fragment_number[i]) for i in rows], pa.int32()
            ),
            "numberOfFragmentsInContig": pa.array(
                [int(b.num_fragments[i]) for i in rows], pa.int32()
            ),
        }
    ).replace_schema_metadata(_seq_dict_meta(seq_dict))
    pq.write_table(t, path, **parquet_codec_kw(compression))


def load_fragments(path: str, projection=None, filters=None):
    """-> (FragmentBatch, SequenceDictionary, descriptions dict).

    ``projection``: subset of FRAGMENT_FIELDS
    (NucleotideContigFragmentField.scala); ``filters``: pyarrow
    predicate pushed into the Parquet read."""
    from adam_tpu.formats.fields import FRAGMENT_FIELDS, validate_projection
    from adam_tpu.formats.fragments import FragmentBatch

    cols = validate_projection(
        projection, FRAGMENT_FIELDS,
        ("contig", "fragmentSequence", "fragmentStartPosition",
         "fragmentNumber", "numberOfFragmentsInContig"),
        "fragment",
    )
    t = pq.read_table(path, columns=cols, filters=filters)
    seq_dict = _seq_dict_from_meta(t.schema.metadata)
    name_idx = {n: i for i, n in enumerate(seq_dict.names)}
    contigs = t["contig"].to_pylist()
    # tolerate contigs missing from the metadata dictionary (stripped by
    # external rewrites) by extending it, as load_genotypes does
    extra = []
    for c in contigs:
        if c not in name_idx:
            name_idx[c] = len(name_idx)
            extra.append(SequenceRecord(c, 0))
    if extra:
        seq_dict = SequenceDictionary(tuple(list(seq_dict.records) + extra))
    seqs = t["fragmentSequence"].to_pylist()
    n = t.num_rows
    fmax = max((len(s) for s in seqs), default=1)
    out = FragmentBatch(
        bases=np.full((n, fmax), schema.BASE_PAD, np.uint8),
        lengths=np.zeros(n, np.int32),
        contig_idx=np.zeros(n, np.int32),
        start=np.array(t["fragmentStartPosition"].to_pylist(), np.int64),
        fragment_number=np.array(t["fragmentNumber"].to_pylist(), np.int32),
        num_fragments=np.array(
            t["numberOfFragmentsInContig"].to_pylist(), np.int32
        ),
        valid=np.ones(n, bool),
    )
    descriptions = {}
    descs = _pylist_or(t, "description", n, None)
    for i in range(n):
        out.bases[i, : len(seqs[i])] = schema.encode_bases(seqs[i])
        out.lengths[i] = len(seqs[i])
        out.contig_idx[i] = name_idx[contigs[i]]
        if descs[i]:
            descriptions[int(out.contig_idx[i])] = descs[i]
    return out, seq_dict, descriptions
