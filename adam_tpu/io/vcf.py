"""VCF reader/writer with bi-allelic splitting.

Parity targets:

* Read: ``converters/VariantContextConverter.convert`` (:95-175) — every
  emitted site is bi-allelic; multi-allelic records are split per ALT
  allele with genotype punch-out: AD reduced to [ref, alt], PL reduced to
  the diploid (0/0, 0/alt, alt/alt) triple re-normalized to min 0,
  genotypes marked phased + splitFromMultiAllelic. The gVCF symbolic
  ``<NON_REF>`` allele maps to alt=None with likelihoods landing in
  ``nonref_pl`` (:103-120 reference-model cases).
* Write: ``rdd/variation/VariationRDDFunctions.saveAsVcf`` (:81-141) +
  the reverse conversion (VariantContextConverter.scala:298-346): samples
  collected into the header columns, 1-based coordinates restored,
  optional coordinate sort.

The reference leans on htsjdk for line codec work; here the codec is
plain Python on the host (VCF is a header-described TSV), feeding the
columnar batches of :mod:`adam_tpu.formats.variants`.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from adam_tpu.formats import variants as vf
from adam_tpu.models.dictionaries import SequenceDictionary, SequenceRecord

NON_REF = "<NON_REF>"


def _diploid_pl_indices(idx: int) -> list[int]:
    """PL indices of genotypes over alleles {0, idx} in VCF genotype
    ordering: index(j,k) = k(k+1)/2 + j for j<=k — the
    getPLIndecesOfAlleles reduction (VariantContextConverter.scala:146-151).
    """
    return [0, idx * (idx + 1) // 2, idx * (idx + 1) // 2 + idx]


def _parse_gt(gt: str):
    """GT string -> (allele ints with -1 for '.', phased flag)."""
    phased = "|" in gt
    parts = gt.replace("|", "/").split("/")
    return [(-1 if p in (".", "") else int(p)) for p in parts], phased


def _code_allele(a: int, alt_idx: int) -> int:
    if a < 0:
        return vf.ALLELE_NO_CALL
    if a == 0:
        return vf.ALLELE_REF
    if a == alt_idx:
        return vf.ALLELE_ALT
    return vf.ALLELE_OTHER_ALT


def _parse_info(s: str) -> dict:
    out = {}
    if s == ".":
        return out
    for item in s.split(";"):
        if "=" in item:
            k, v = item.split("=", 1)
            out[k] = v
        else:
            out[item] = True
    return out


def read_vcf(path: str, contig_names: Optional[list] = None):
    """Parse a VCF into (VariantBatch, GenotypeBatch, SequenceDictionary).

    ``contig_names`` optionally fixes the contig index space (e.g. from a
    BAM header); otherwise contigs come from ##contig header lines plus
    first-seen order in the records.
    """
    header_contigs: list[tuple[str, int]] = []
    samples: list[str] = []
    names = list(contig_names) if contig_names else []
    name_to_idx = {n: i for i, n in enumerate(names)}

    rows = dict(contig=[], start=[], end=[], ref_len=[], alt_len=[],
                qual=[], applied=[], passing=[])
    side = vf.VariantSidecar()
    g_rows = dict(vi=[], si=[], alleles=[], gq=[], dp=[], rd=[], ad=[],
                  phased=[], pl=[], nrpl=[], split=[], ft=[])

    def contig_id(name: str) -> int:
        if name not in name_to_idx:
            name_to_idx[name] = len(names)
            names.append(name)
        return name_to_idx[name]

    def emit_site(chrom, pos1, vid, ref, alt, qual, filt, info,
                  fmt_keys, sample_fields, alt_idx, n_alts):
        """Append one bi-allelic site (+ genotypes). alt may be None."""
        vi = len(rows["start"])
        rows["contig"].append(contig_id(chrom))
        rows["start"].append(pos1 - 1)
        # INFO END (1-based inclusive) extends gVCF reference blocks past
        # len(ref); htsjdk's getEnd honors it the same way
        end0 = pos1 - 1 + len(ref)
        if alt is None and "END" in info:
            end0 = max(end0, int(info["END"]))
        rows["end"].append(end0)
        rows["ref_len"].append(len(ref))
        rows["alt_len"].append(len(alt) if alt else 0)
        rows["qual"].append(float(qual) if qual != "." else np.nan)
        applied = filt != "."
        rows["applied"].append(applied)
        rows["passing"].append(filt in ("PASS", "."))
        side.ref_allele.append(ref)
        side.alt_allele.append(alt)
        side.names.append("" if vid == "." else vid)
        side.filters.append(
            [] if filt in (".", "PASS") else filt.split(";")
        )
        side.info.append(info)

        split = n_alts > 1
        for si, f in enumerate(sample_fields):
            vals = dict(zip(fmt_keys, f.split(":")))
            gt = vals.get("GT", ".")
            raw_alleles, phased = _parse_gt(gt)
            # pad haploid calls to a pair with no-call (ploidy<=2 support)
            while len(raw_alleles) < 2:
                raw_alleles.append(-1)
            coded = [_code_allele(a, alt_idx) for a in raw_alleles[:2]]

            ad = vals.get("AD", "")
            rd_v, ad_v = -1, -1
            if ad and ad != ".":
                # keep positions: '.' entries are missing, not removable
                parts = [
                    (int(x) if x not in (".", "") else None)
                    for x in ad.split(",")
                ]
                if parts and parts[0] is not None:
                    rd_v = parts[0]
                if alt_idx < len(parts) and parts[alt_idx] is not None:
                    ad_v = parts[alt_idx]
            pl_v = [vf.PL_MISSING] * 3
            nrpl_v = [vf.PL_MISSING] * 3
            pl = vals.get("PL", "")
            if pl and pl != ".":
                all_pls = [int(x) for x in pl.split(",")]
                if alt is None and n_alts == 1:
                    # pure reference model row (sole ALT was <NON_REF>):
                    # likelihoods describe ref vs any-nonref
                    nrpl_v = (all_pls + [vf.PL_MISSING] * 3)[:3]
                else:
                    idxs = [
                        i for i in _diploid_pl_indices(alt_idx)
                        if i < len(all_pls)
                    ]
                    sub = [all_pls[i] for i in idxs]
                    if sub:
                        m = min(sub)
                        sub = [p - m for p in sub]  # renormalize
                    pl_v = (sub + [vf.PL_MISSING] * 3)[:3]

            g_rows["vi"].append(vi)
            g_rows["si"].append(si)
            g_rows["alleles"].append(coded)
            g_rows["gq"].append(int(vals["GQ"]) if vals.get("GQ", ".") not in (".", "") else -1)
            g_rows["dp"].append(int(vals["DP"]) if vals.get("DP", ".") not in (".", "") else -1)
            g_rows["rd"].append(rd_v)
            g_rows["ad"].append(ad_v)
            g_rows["phased"].append(phased or split)
            g_rows["pl"].append(pl_v)
            g_rows["nrpl"].append(nrpl_v)
            g_rows["split"].append(split)
            g_rows["ft"].append(vals.get("FT", ""))

    with open(path) as fh:
        for line in fh:
            line = line.rstrip("\n")
            if not line:
                continue
            if line.startswith("##"):
                if line.startswith("##contig="):
                    body = line[len("##contig=<"):].rstrip(">")
                    kv = dict(
                        p.split("=", 1) for p in body.split(",") if "=" in p
                    )
                    if "ID" in kv:
                        header_contigs.append(
                            (kv["ID"], int(kv.get("length", 0)))
                        )
                continue
            if line.startswith("#CHROM"):
                cols = line.split("\t")
                samples = cols[9:]
                for n, _l in header_contigs:
                    contig_id(n)
                continue
            cols = line.split("\t")
            chrom, pos1, vid, ref, alt_s, qual, filt = cols[:7]
            info = _parse_info(cols[7]) if len(cols) > 7 else {}
            fmt_keys = cols[8].split(":") if len(cols) > 8 else []
            sample_fields = cols[9:]
            alts = alt_s.split(",") if alt_s != "." else []

            real_alts = [a for a in alts if a != NON_REF]
            if not real_alts:
                # gVCF reference block: single symbolic <NON_REF> alt
                emit_site(chrom, int(pos1), vid, ref, None, qual, filt,
                          info, fmt_keys, sample_fields, 1, 1)
            else:
                n = len(real_alts)
                for alt in real_alts:
                    emit_site(chrom, int(pos1), vid, ref, alt, qual, filt,
                              info, fmt_keys, sample_fields,
                              alts.index(alt) + 1, n)

    contig_lens = dict(header_contigs)
    seq_dict = SequenceDictionary(
        tuple(
            SequenceRecord(name=n, length=contig_lens.get(n, 0))
            for n in names
        )
    )
    variants = vf.VariantBatch(
        np.asarray(rows["contig"], np.int32),
        np.asarray(rows["start"], np.int64),
        np.asarray(rows["end"], np.int64),
        np.asarray(rows["ref_len"], np.int32),
        np.asarray(rows["alt_len"], np.int32),
        np.asarray(rows["qual"], np.float32),
        np.asarray(rows["applied"], bool),
        np.asarray(rows["passing"], bool),
        side,
    )
    genotypes = vf.GenotypeBatch(
        np.asarray(g_rows["vi"], np.int32),
        np.asarray(g_rows["si"], np.int32),
        np.asarray(g_rows["alleles"], np.int8).reshape(-1, 2),
        np.asarray(g_rows["gq"], np.int16),
        np.asarray(g_rows["dp"], np.int32),
        np.asarray(g_rows["rd"], np.int32),
        np.asarray(g_rows["ad"], np.int32),
        np.asarray(g_rows["phased"], bool),
        np.asarray(g_rows["pl"], np.int32).reshape(-1, 3),
        np.asarray(g_rows["nrpl"], np.int32).reshape(-1, 3),
        np.asarray(g_rows["split"], bool),
        samples,
        g_rows["ft"],
    )
    return variants, genotypes, seq_dict


def write_vcf(
    path: str,
    variants: vf.VariantBatch,
    genotypes: vf.GenotypeBatch,
    seq_dict: SequenceDictionary,
    sort_on_save: bool = False,
) -> None:
    """Emit VCF 4.1 (reverse conversion + saveAsVcf semantics).

    Genotype columns carry GT:AD:DP:GQ:PL (present subsets per row);
    coordinates restored to 1-based; rows optionally coordinate-sorted
    (sortOnSave, VariationRDDFunctions.scala:123-130).
    """
    names = [r.name for r in seq_dict.records]
    order = np.arange(len(variants))
    if sort_on_save:
        order = np.lexsort(
            (variants.start, variants.contig_idx)
        )

    # genotype rows grouped by variant
    by_variant: dict[int, list[int]] = {}
    for gi, vi in enumerate(genotypes.variant_idx):
        by_variant.setdefault(int(vi), []).append(gi)

    gt_sep = {True: "|", False: "/"}
    code_to_num = {vf.ALLELE_REF: "0", vf.ALLELE_ALT: "1",
                   vf.ALLELE_OTHER_ALT: ".", vf.ALLELE_NO_CALL: "."}

    with open(path, "w") as fh:
        fh.write("##fileformat=VCFv4.1\n")
        for r in seq_dict.records:
            if r.length:
                fh.write(f"##contig=<ID={r.name},length={r.length}>\n")
        fh.write(
            '##FORMAT=<ID=GT,Number=1,Type=String,Description="Genotype">\n'
            '##FORMAT=<ID=AD,Number=.,Type=Integer,Description="Allelic depths">\n'
            '##FORMAT=<ID=DP,Number=1,Type=Integer,Description="Read depth">\n'
            '##FORMAT=<ID=GQ,Number=1,Type=Integer,Description="Genotype quality">\n'
            '##FORMAT=<ID=PL,Number=G,Type=Integer,Description="Phred likelihoods">\n'
            '##FORMAT=<ID=FT,Number=1,Type=String,Description="Genotype-level filter">\n'
        )
        fh.write(
            "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"
            + ("\tFORMAT\t" + "\t".join(genotypes.samples)
               if genotypes.samples else "")
            + "\n"
        )
        for vi in order:
            vi = int(vi)
            side = variants.sidecar
            chrom = names[variants.contig_idx[vi]]
            pos1 = int(variants.start[vi]) + 1
            vid = side.names[vi] or "."
            ref = side.ref_allele[vi]
            alt = side.alt_allele[vi] or NON_REF
            q = variants.qual[vi]
            qual = "." if np.isnan(q) else f"{float(q):.2f}"
            if not variants.filters_applied[vi]:
                filt = "."
            elif variants.passing[vi]:
                filt = "PASS"
            else:
                filt = ";".join(side.filters[vi]) or "PASS"
            info_d = side.info[vi]
            info_s = (
                ";".join(
                    k if v is True else f"{k}={v}"
                    for k, v in info_d.items()
                )
                if info_d
                else "."
            )
            cols = [chrom, str(pos1), vid, ref, alt, qual, filt, info_s]
            gis = by_variant.get(vi, [])
            if genotypes.samples:
                cols.append("GT:AD:DP:GQ:PL:FT")
                per_sample = {int(genotypes.sample_idx[g]): g for g in gis}
                ref_block = side.alt_allele[vi] is None
                for si in range(len(genotypes.samples)):
                    g = per_sample.get(si)
                    if g is None:
                        cols.append("./.")
                        continue
                    sep = gt_sep[bool(genotypes.phased[g])]
                    gt = sep.join(
                        code_to_num[int(a)] for a in genotypes.alleles[g]
                    )
                    ad = (
                        f"{genotypes.ref_depth[g]},{genotypes.alt_depth[g]}"
                        if genotypes.ref_depth[g] >= 0
                        and genotypes.alt_depth[g] >= 0
                        else "."
                    )
                    dp = str(genotypes.dp[g]) if genotypes.dp[g] >= 0 else "."
                    gq = str(genotypes.gq[g]) if genotypes.gq[g] >= 0 else "."
                    # reference-model rows round-trip their likelihoods
                    # through the PL column (read_vcf routes them back to
                    # nonref_pl when ALT is <NON_REF>)
                    pls = (
                        genotypes.nonref_pl[g] if ref_block
                        else genotypes.pl[g]
                    )
                    pl = (
                        ",".join(str(int(p)) for p in pls if p != vf.PL_MISSING)
                        if pls[0] != vf.PL_MISSING
                        else "."
                    )
                    ft = genotypes.genotype_filters[g] or "."
                    cols.append(":".join([gt, ad, dp, gq, pl, ft]))
            fh.write("\t".join(cols) + "\n")
