from adam_tpu.io import sam, fastq, fasta, features, vcf
from adam_tpu.io.context import (
    load_alignments,
    load_bam,
    load_fasta,
    load_fastq,
    load_interleaved_fastq,
    load_vcf,
    load_genotypes,
)

__all__ = [
    "sam",
    "fastq",
    "fasta",
    "features",
    "vcf",
    "load_alignments",
    "load_bam",
    "load_fasta",
    "load_fastq",
    "load_interleaved_fastq",
    "load_vcf",
    "load_genotypes",
]
