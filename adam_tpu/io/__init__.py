from adam_tpu.io import sam, fastq, fasta
from adam_tpu.io.context import (
    load_alignments,
    load_bam,
    load_fasta,
    load_fastq,
    load_interleaved_fastq,
)

__all__ = [
    "sam",
    "fastq",
    "fasta",
    "load_alignments",
    "load_bam",
    "load_fasta",
    "load_fastq",
    "load_interleaved_fastq",
]
