"""User-plugin API — the ``plugins/`` package of the reference.

Parity surface:

* :class:`AdamPlugin` — the ``ADAMPlugin`` trait
  (plugins/ADAMPlugin.scala:29-48): an optional column *projection*, an
  optional row *predicate*, and a ``run`` over the loaded dataset.
  Columnar recast: the projection is a list of ALIGNMENT_FIELDS names
  (pushed down into the Parquet read), and the predicate is a
  vectorized ``ReadBatch -> bool[N]`` mask instead of a per-record
  closure.
* :class:`AccessControl` / :class:`EmptyAccessControl` —
  ``plugins/AccessControl.scala``: a site-policy predicate composed
  (AND) with the plugin's own, exactly as ``PluginExecutor`` composes
  them (adam-cli PluginExecutor.scala:98-107).
* :func:`load_plugin` — the reflective loader
  (PluginExecutor.scala:68-74), taking ``"pkg.module.ClassName"``.
"""

from __future__ import annotations

import importlib
from typing import Optional, Sequence

import numpy as np

from adam_tpu.api.datasets import AlignmentDataset


class AdamPlugin:
    """Base class for user plugins over read datasets."""

    #: Optional list of Parquet column names to project (None = all).
    projection: Optional[Sequence[str]] = None

    def predicate(self, batch) -> Optional[np.ndarray]:
        """Optional row mask ``bool[N]`` over a ReadBatch (None = keep all)."""
        return None

    def run(self, ds: AlignmentDataset, args: Sequence[str]):
        """Body of the plugin; returns any sequence of printable results."""
        raise NotImplementedError


class AccessControl:
    """Site access policy: a row mask composed with every plugin's own."""

    def predicate(self, batch) -> Optional[np.ndarray]:
        return None


class EmptyAccessControl(AccessControl):
    """The default allow-everything policy (plugins/EmptyAccessControl.scala)."""


def load_plugin(qualname: str, base=AdamPlugin):
    """Instantiate ``"pkg.module.ClassName"`` and type-check it against
    ``base`` (the loadPlugin reflection, PluginExecutor.scala:68-74)."""
    mod_name, _, cls_name = qualname.rpartition(".")
    if not mod_name:
        raise ValueError(f"plugin {qualname!r} must be a dotted path")
    cls = getattr(importlib.import_module(mod_name), cls_name)
    if not (isinstance(cls, type) and issubclass(cls, base)):
        raise TypeError(f"{qualname} is not a {base.__name__}")
    return cls()


def compose_predicates(batch, *sources) -> Optional[np.ndarray]:
    """AND the non-None predicates of plugin + access control
    (PluginExecutor.scala:98-107)."""
    mask = None
    for src in sources:
        m = src.predicate(batch)
        if m is None:
            continue
        m = np.asarray(m, bool)
        mask = m if mask is None else (mask & m)
    return mask


def execute_plugin(
    plugin: AdamPlugin,
    input_path: str,
    plugin_args: Sequence[str] = (),
    access_control: Optional[AccessControl] = None,
):
    """Load (with projection pushdown), filter, run — the PluginExecutor
    lifecycle (PluginExecutor.scala:88-119)."""
    from adam_tpu.io import context

    kw = {}
    if plugin.projection is not None and str(input_path).endswith(
        (".adam", ".parquet")
    ):
        kw["projection"] = list(plugin.projection)
    ds = context.load_alignments(str(input_path), **kw)
    ac = access_control or EmptyAccessControl()
    mask = compose_predicates(ds.batch, ac, plugin)
    if mask is not None:
        ds = ds.take_rows(np.flatnonzero(mask & np.asarray(ds.batch.valid)))
    return plugin.run(ds, list(plugin_args))
