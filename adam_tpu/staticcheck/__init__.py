"""``adam-tpu check`` — AST-based contract checker for the cross-cutting
conventions the streamed TPU pipeline's correctness rests on.

Eight PRs of device code left five *conventions* that no compiler
enforces: every device->host fetch routes through
``utils/transfer.device_fetch`` (or the PR 7 tunnel-byte ledger
under-counts), every jit dispatch is ``compile_ledger.track``-wrapped
against a prewarm entry (or ``device.compile.in_window`` lies), every
durability-bearing publish goes through ``utils/durability`` (or a
power loss can tear a part), every fault-injection site names a
``faults.KNOWN_POINTS`` member (or the chaos matrix silently tests
nothing), and shared mutable state in thread-spawning modules stays
behind its lock.  This package turns each convention into a static
rule over the Python AST, so drift is caught at review time instead of
by a runtime assertion three PRs later (docs/STATIC_ANALYSIS.md).

Entry points: ``adam-tpu check`` (CLI subcommand),
``python -m adam_tpu.staticcheck`` and ``scripts/staticcheck``.
"""

from adam_tpu.staticcheck.core import (  # noqa: F401
    EXIT_CLEAN,
    EXIT_ERROR,
    EXIT_FINDINGS,
    Finding,
    Project,
    Report,
    Rule,
    all_rules,
    register,
    run_checks,
)

__all__ = [
    "EXIT_CLEAN", "EXIT_FINDINGS", "EXIT_ERROR",
    "Finding", "Project", "Report", "Rule",
    "all_rules", "register", "run_checks",
]
