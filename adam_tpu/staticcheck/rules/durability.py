"""Rule ``durability``: durability-bearing paths publish only through
``utils/durability`` helpers.

PR 6's crash matrix (docs/ROBUSTNESS.md "Durable window-granular
resume") holds because every artifact a resume trusts — Parquet parts,
checkpoint manifests, the run journal, barrier sidecars — publishes
via ``fsync(tmp) -> os.replace -> fsync(dir)`` in ``utils/durability``.
A raw ``os.replace`` elsewhere in these files is crash-consistent but
NOT power-loss durable; a raw ``json.dump`` / write-mode ``open`` to a
final name is neither.  This rule bans the primitives in the
durability-bearing modules:

* ``os.replace`` / ``os.rename`` — use ``durability.publish_file``;
* ``json.dump(obj, fh)`` — use ``durability.atomic_write_json``;
* write-mode ``open(path, "w"/"wb"/"a"/"x")`` whose target is not
  visibly a staging name (containing ``tmp``/``temp``/``staging`` in
  an identifier or literal) — staging writes are the protocol's first
  step and stay legal, the *publish* is what must be durable;
* ``np.save``/``np.savez*`` straight to a path literal (sidecars
  serialize to bytes and go through ``atomic_write_bytes``)."""

from __future__ import annotations

import ast

from adam_tpu.staticcheck.core import Rule, register
from adam_tpu.staticcheck.rules._astutil import dotted_name

#: Files whose writes a resume/restart later trusts.
SCOPE_FILES = frozenset({
    "adam_tpu/pipelines/checkpoint.py",
    "adam_tpu/io/parquet.py",
    # the zero-copy column assembly feeds the part writer's encode
    # stage: it must never open/publish files of its own — any write
    # it grew would bypass the staging + durable-publish protocol the
    # sharded writer pool guarantees per part
    "adam_tpu/io/arrow_pack.py",
    "adam_tpu/pipelines/streamed.py",
    # the multi-job scheduler's JOB.json records gate crash recovery:
    # they must publish through utils/durability like every other
    # resume-bearing artifact
    "adam_tpu/serve/scheduler.py",
    # the cross-job coalescer and quota manager sit ON the output path
    # (fused pass-C dispatches feed the part writers) but own no
    # durable artifacts of their own — any file write they grew would
    # bypass the staging + durable-publish protocol
    "adam_tpu/serve/batching.py",
    "adam_tpu/serve/quota.py",
    # the gateway's discovery document (gateway.json) and the client's
    # verified part downloads are resume-bearing too: a fetched part
    # must publish exactly like a written one (staging name + durable
    # publish), or a crash mid-download could leave a torn final file
    "adam_tpu/gateway/server.py",
    "adam_tpu/gateway/client.py",
})

_STAGING_MARKERS = ("tmp", "temp", "staging")


def _mentions_staging(expr) -> bool:
    """The path expression visibly names a staging target: any
    identifier / attribute / string literal fragment containing a
    staging marker."""
    for node in ast.walk(expr):
        text = ""
        if isinstance(node, ast.Name):
            text = node.id
        elif isinstance(node, ast.Attribute):
            text = node.attr
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            text = node.value
        low = text.lower()
        if any(m in low for m in _STAGING_MARKERS):
            return True
    return False


def _is_pathlike(expr) -> bool:
    """A visibly path-like target: a string literal, an f-string, a
    ``+``/``%`` build, or an ``os.path.join``-style call.  A bare name
    is typically an in-memory buffer (the ``np.savez(buf, ...)`` ->
    ``atomic_write_bytes`` idiom) and stays legal."""
    if isinstance(expr, ast.Constant):
        return isinstance(expr.value, str)
    if isinstance(expr, (ast.JoinedStr, ast.BinOp)):
        return True
    if isinstance(expr, ast.Call):
        return dotted_name(expr.func).endswith("path.join")
    return False


def _open_mode(call) -> str | None:
    if len(call.args) >= 2:
        a = call.args[1]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            return str(kw.value.value)
    return None


@register
class DurabilityRule(Rule):
    name = "durability"
    summary = ("raw open(w)/os.replace/json.dump in durability-bearing "
               "paths instead of utils/durability helpers")
    contract = (
        "Parts, manifests, journal and sidecars publish through "
        "utils/durability (fsync + atomic rename + dir fsync) so the "
        "resume contract survives power loss, not just crashes "
        "(docs/ROBUSTNESS.md 'Durable window-granular resume')."
    )

    def visit(self, ctx):
        if ctx.relpath not in SCOPE_FILES:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if d in ("os.replace", "os.rename"):
                yield ctx.finding(
                    self.name, node,
                    f"raw {d} publish — use durability.publish_file "
                    "(fsync data + atomic rename + fsync dir)",
                )
            elif d == "json.dump":
                yield ctx.finding(
                    self.name, node,
                    "raw json.dump — use durability.atomic_write_json "
                    "so the document publishes atomically and durably",
                )
            elif d in ("np.save", "numpy.save", "np.savez",
                       "numpy.savez", "np.savez_compressed",
                       "numpy.savez_compressed"):
                if node.args and _is_pathlike(node.args[0]) \
                        and not _mentions_staging(node.args[0]):
                    yield ctx.finding(
                        self.name, node,
                        f"{d} straight to a final path — serialize to "
                        "bytes and publish via durability."
                        "atomic_write_bytes",
                    )
            elif d == "open" or (isinstance(node.func, ast.Name)
                                 and node.func.id == "open"):
                mode = _open_mode(node)
                if mode and any(c in mode for c in "wax"):
                    if node.args and _mentions_staging(node.args[0]):
                        continue  # staging write: protocol step 1
                    yield ctx.finding(
                        self.name, node,
                        f"write-mode open(..., {mode!r}) to a non-"
                        "staging path — write a temp name and publish "
                        "via durability.publish_file / atomic_write_*",
                    )
