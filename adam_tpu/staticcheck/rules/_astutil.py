"""Shared AST helpers for the built-in rules."""

from __future__ import annotations

import ast


def terminal_name(func) -> str:
    """The rightmost name of a call target: ``f`` for ``f(...)``,
    ``track`` for ``compile_ledger.track(...)``."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def dotted_name(node) -> str:
    """Best-effort dotted path (``jax.jit`` / ``np.asarray``); empty
    for dynamic expressions."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_expr(node) -> bool:
    """``jax.jit(...)`` / ``jit(...)`` / ``partial(jax.jit, ...)`` /
    ``functools.partial(jax.jit, ...)`` — the value side of a binding
    that produces a jit-compiled callable."""
    if not isinstance(node, ast.Call):
        return False
    d = dotted_name(node.func)
    if d in ("jax.jit", "jit"):
        return True
    if d in ("partial", "functools.partial") and node.args:
        return dotted_name(node.args[0]) in ("jax.jit", "jit")
    return False


def is_jit_decorated(fn) -> bool:
    """Function carries ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit,
    ...)`` — its body is trace-time code, its NAME is a dispatchable."""
    for dec in fn.decorator_list:
        if dotted_name(dec) in ("jax.jit", "jit"):
            return True
        if _is_jit_expr(dec):
            return True
    return False


def collect_jit_callables(tree) -> set:
    """Names in this module that are jit-compiled callables: decorated
    functions, plus any name bound to ``jax.jit(...)`` (e.g. the
    module-level ``_COLUMNS_JIT``) or to a call of a ``*_jit`` factory
    (the ``jit = get_columns_jit()`` idiom)."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if is_jit_decorated(node):
                names.add(node.name)
        elif isinstance(node, ast.Assign):
            v = node.value
            factory = (
                isinstance(v, ast.Call)
                and terminal_name(v.func).endswith("_jit")
            )
            if _is_jit_expr(v) or factory:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


#: Function-name patterns whose bodies are compile-forcing by design:
#: prewarm entry thunks (executed under DevicePool.prewarm /
#: MeshPartitioner.prewarm's own compile_ledger.track), TFLOP/s probes
#: and micro-benchmarks.  block_until_ready and direct kernel calls
#: there are the POINT, not hot-path drift.
WARMUP_FN_PATTERNS = ("warm*", "*prewarm*", "*probe*", "*bench*")


def in_warmup_function(ctx, node) -> bool:
    """``node`` sits inside a function whose (or whose ancestor's) name
    marks it as warm/probe/bench code."""
    import fnmatch

    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(fnmatch.fnmatchcase(anc.name, p)
                   for p in WARMUP_FN_PATTERNS):
                return True
    return False


def enclosing_function(ctx, node):
    """The nearest enclosing FunctionDef (None at module level)."""
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def in_with_matching(ctx, node, match) -> bool:
    """True when ``node`` sits lexically inside a ``with`` statement one
    of whose context expressions satisfies ``match(expr)``."""
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                if match(item.context_expr):
                    return True
    return False


def name_contains_lock(node) -> bool:
    """A ``with`` context expression that looks like a lock: a name or
    attribute whose terminal name contains ``lock`` (``_LOCK``,
    ``self._lock``, ``_PREWARM_LOCK``...), or a call on one
    (``lk.acquire_timeout(...)`` style)."""
    if isinstance(node, ast.Call):
        node = node.func
    term = ""
    if isinstance(node, ast.Name):
        term = node.id
    elif isinstance(node, ast.Attribute):
        term = node.attr
    return "lock" in term.lower()
