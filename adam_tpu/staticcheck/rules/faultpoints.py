"""Rule ``fault-registry``: fault-injection sites and
``faults.KNOWN_POINTS`` stay a closed, documented vocabulary.

The fault-spec grammar (``--fault-spec`` / ``ADAM_TPU_FAULTS``) can
only arm sites named in ``faults.KNOWN_POINTS`` — a typo'd site errors
at install time precisely because an unarmable clause would silently
test nothing (PR 4).  This rule closes the remaining gaps statically:

* every ``faults.point("...")`` call site in the package names a
  ``KNOWN_POINTS`` member (a site the spec grammar can't reach is dead
  injection plumbing);
* every ``KNOWN_POINTS`` member has at least one call site (a member
  with no site is a spec vocabulary entry that can never fire — the
  inverse silent-nothing);
* every member appears in docs/ROBUSTNESS.md's fault-point table (the
  docs ARE the spec author's reference — absorbed from
  scripts/check-telemetry-names' ``_fault_point_gaps``).

``KNOWN_POINTS`` is parsed statically from
``adam_tpu/utils/faults.py`` (it is a frozenset literal), so the rule
runs on fixture trees and jax-less CI images alike."""

from __future__ import annotations

import ast
import re

from adam_tpu.staticcheck.core import Finding, Rule, register
from adam_tpu.staticcheck.rules._astutil import dotted_name, terminal_name

FAULTS_MODULE = "adam_tpu/utils/faults.py"
DOC_FILE = "docs/ROBUSTNESS.md"


def parse_known_points(tree) -> tuple[set, int]:
    """The KNOWN_POINTS frozenset literal -> (members, lineno)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "KNOWN_POINTS"
                   for t in node.targets):
            continue
        v = node.value
        if isinstance(v, ast.Call) and v.args:
            v = v.args[0]
        if isinstance(v, (ast.Set, ast.Tuple, ast.List)):
            return (
                {e.value for e in v.elts
                 if isinstance(e, ast.Constant)
                 and isinstance(e.value, str)},
                node.lineno,
            )
    return set(), 0


@register
class FaultRegistryRule(Rule):
    name = "fault-registry"
    summary = ("faults.point sites vs KNOWN_POINTS vs ROBUSTNESS.md: "
               "unknown sites, unreferenced members, undocumented "
               "members")
    contract = (
        "Every injection site names a faults.KNOWN_POINTS member, "
        "every member has >=1 site and a docs/ROBUSTNESS.md entry, so "
        "the chaos matrix's vocabulary can neither drift nor rot "
        "(docs/ROBUSTNESS.md fault-spec grammar)."
    )

    def __init__(self):
        self._sites: dict[str, list] = {}  # site -> [(path, line)]

    def visit(self, ctx):
        if not ctx.relpath.startswith("adam_tpu/"):
            return
        if ctx.relpath == FAULTS_MODULE:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted_name(node.func)
            if not (d.endswith("faults.point")
                    or (terminal_name(node.func) == "point"
                        and d == "point")):
                continue
            if not node.args:
                continue
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                self._sites.setdefault(a.value, []).append(
                    (ctx.relpath, node.lineno)
                )
            else:
                yield ctx.finding(
                    self.name, node,
                    "faults.point with a non-literal site name — the "
                    "registry cross-check (and grep) cannot see it",
                )
        return

    def finalize(self, project):
        tree = project.parse_module(FAULTS_MODULE)
        if tree is None:
            return  # fixture tree without a faults module: nothing to check
        known, known_line = parse_known_points(tree)
        for site, locs in sorted(self._sites.items()):
            if site not in known:
                path, line = locs[0]
                yield Finding(
                    self.name, path, line, 0,
                    f"fault point '{site}' is not in faults."
                    "KNOWN_POINTS — no --fault-spec clause can ever "
                    "arm it",
                    "",
                )
        for member in sorted(known - set(self._sites)):
            yield Finding(
                self.name, FAULTS_MODULE, known_line, 0,
                f"KNOWN_POINTS member '{member}' has no faults.point "
                "call site — a spec naming it arms a clause that can "
                "never fire",
                "",
            )
        doc = project.read_doc(DOC_FILE)
        if doc is not None:
            for member in sorted(known):
                if not re.search(
                    rf"(?<![a-z0-9_.]){re.escape(member)}(?![a-z0-9_.])",
                    doc,
                ):
                    yield Finding(
                        self.name, FAULTS_MODULE, known_line, 0,
                        f"KNOWN_POINTS member '{member}' missing from "
                        f"{DOC_FILE}'s fault-point table — spec "
                        "authors can't discover it",
                        "",
                    )
