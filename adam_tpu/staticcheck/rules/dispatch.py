"""Rule ``dispatch-ledger``: every jit/shard_map dispatch in the
streamed path is ``compile_ledger.track``-wrapped and its kernel has a
prewarm registry entry.

PR 7's compile ledger makes "did the prewarm cover this shape?" a
first-class observable (``device.compile.in_window``), and PR 8
asserts it is **zero** on clean runs — but both hold only if every
dispatch site actually wraps itself in :func:`compile_ledger.track`
with a key the prewarm registry also builds.  PR 7 caught the
realigned-tail observe gap only at runtime; this rule catches the next
one at review time.

Three checks:

* **coverage** — a call to a jit-compiled callable (``@jax.jit``
  functions, ``jax.jit(...)`` bindings, ``*_kernel`` names, the mesh
  ``observe_window``/``apply_window``/``markdup_window``/
  ``fused_bc_window`` collectives)
  in a streamed-path module must sit inside ``with
  compile_ledger.track(...)``.  The dominant idiom nests the dispatch
  in a local ``def dispatch(): ...`` retried via ``retry_call`` inside
  the tracked block — a call is also covered when its enclosing nested
  function is *referenced* inside a tracked block of the same outer
  function.
* **prewarm cross-check** — every kernel name appearing as the first
  element of a ``track((kernel, *dims), ...)`` key tuple must appear in
  a prewarm entry key built in ``parallel/`` (the ``*_entry``/
  ``*prewarm*`` builders in ``device_pool.py``/``partitioner.py``),
  keeping the ledger's key space and the prewarm's in lockstep by
  construction.
* **pallas containment** — a ``pl.pallas_call`` anywhere in the
  package must sit inside a ``*_body``/``*_kernel``/``*_pallas``
  function: those are the surfaces the kernel-backend selector
  (``ops/kernel_backend``) branches on at trace time, so a stray
  pallas site elsewhere would dodge both the backend toggle and the
  ledger keys."""

from __future__ import annotations

import ast

from adam_tpu.staticcheck.core import Finding, Rule, register
from adam_tpu.staticcheck.rules._astutil import (
    _is_jit_expr,
    collect_jit_callables,
    enclosing_function,
    in_warmup_function,
    is_jit_decorated,
    terminal_name,
)

#: The streamed device path: the modules whose dispatches land inside
#: timed windows (ISSUE: jit/shard_map sites "in the streamed path").
SCOPE_FILES = frozenset({
    "adam_tpu/pipelines/markdup.py",
    "adam_tpu/pipelines/bqsr.py",
    "adam_tpu/pipelines/realign.py",
    "adam_tpu/pipelines/streamed.py",
    "adam_tpu/parallel/device_pool.py",
    "adam_tpu/parallel/partitioner.py",
    "adam_tpu/parallel/dist.py",
})

#: Where prewarm entry keys are built (the registry side of the
#: cross-check).
PREWARM_FILES = ("adam_tpu/parallel/device_pool.py",
                 "adam_tpu/parallel/partitioner.py")

MESH_WINDOW_METHODS = frozenset(
    {"observe_window", "apply_window", "markdup_window",
     "fused_bc_window"}
)

#: Function-name suffixes a ``pl.pallas_call`` site may live under:
#: the jit-able math (``*_body``), a dispatchable binding
#: (``*_kernel``) or the Pallas port itself (``*_pallas``).  Anywhere
#: else the call escapes the backend selector (ops/kernel_backend) and
#: the ledger/prewarm machinery that keys on it.
PALLAS_HOST_SUFFIXES = ("_body", "_kernel", "_pallas")


def _is_track_call(expr) -> bool:
    return (isinstance(expr, ast.Call)
            and terminal_name(expr.func) == "track")


def _kernel_of_track(call) -> str | None:
    """The kernel-name literal of a ``track((kernel, *dims), dev)``."""
    if call.args and isinstance(call.args[0], ast.Tuple):
        elts = call.args[0].elts
        if elts and isinstance(elts[0], ast.Constant) and isinstance(
            elts[0].value, str
        ):
            return elts[0].value
    return None


@register
class DispatchLedgerRule(Rule):
    name = "dispatch-ledger"
    summary = ("streamed jit/shard_map dispatches not wrapped in "
               "compile_ledger.track, or tracked kernels with no "
               "prewarm registry entry")
    contract = (
        "Every streamed-path jit dispatch wraps in compile_ledger."
        "track keyed identically to a prewarm entry, so device.compile"
        ".in_window == 0 is a compile-time property (docs/PERF.md "
        "'prewarm coverage boundary', tests/test_mesh.py)."
    )

    def __init__(self):
        self._tracked: dict[str, tuple] = {}  # kernel -> (path, line)
        self._prewarmed: set[str] = set()

    def visit(self, ctx):
        # collect both sides of the cross-check (package code only —
        # tests exercise the ledger with synthetic kernel keys)
        if ctx.relpath.startswith("adam_tpu/"):
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call) and _is_track_call(node):
                    k = _kernel_of_track(node)
                    if k is not None and k not in self._tracked:
                        self._tracked[k] = (ctx.relpath, node.lineno)
        if ctx.relpath in PREWARM_FILES:
            self._collect_prewarm_kernels(ctx.tree)
        # pallas containment (package-wide): a pallas_call outside a
        # *_body/*_kernel/*_pallas function is a dispatch surface the
        # backend selector and the ledger cannot key on
        if ctx.relpath.startswith("adam_tpu/"):
            yield from self._check_pallas_sites(ctx)
        if ctx.relpath not in SCOPE_FILES:
            return
        dispatchables = collect_jit_callables(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_jit_expr(node):
                continue  # jax.jit(...) builds a callable, no dispatch
            if self._in_traced_code(ctx, node):
                continue  # inside a @jax.jit body: trace-time call
            if in_warmup_function(ctx, node):
                # prewarm entry thunks run under the pool/mesh
                # prewarm's own track; probe/bench dispatches are
                # deliberately outside any window
                continue
            func = node.func
            name = terminal_name(func)
            if isinstance(func, ast.Call) and terminal_name(
                func.func
            ).endswith("_jit"):
                # factory()(...) — dispatch via a *_jit factory result
                name = terminal_name(func.func) + "()"
            elif (name in dispatchables
                  or name in MESH_WINDOW_METHODS
                  or name.endswith("_kernel")):
                outer = ctx.parents.get(node)
                if isinstance(outer, ast.Call) and outer.func is node:
                    continue  # bare factory: the outer call is flagged
            else:
                continue
            if self._covered(ctx, node):
                continue
            yield ctx.finding(
                self.name, node,
                f"jit dispatch '{name}' outside compile_ledger.track — "
                "the compile ledger (and the in_window == 0 invariant) "
                "cannot see this site",
            )

    def _check_pallas_sites(self, ctx):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and terminal_name(node.func) == "pallas_call"):
                continue
            fn = enclosing_function(ctx, node)
            if fn is not None and fn.name.endswith(PALLAS_HOST_SUFFIXES):
                continue
            where = fn.name if fn is not None else "module scope"
            yield ctx.finding(
                self.name, node,
                f"pallas_call in '{where}' — Pallas call sites must "
                "live inside a *_body/*_kernel/*_pallas function so the "
                "kernel-backend selector and the compile ledger key on "
                "them (ops/kernel_backend.py)",
            )

    @staticmethod
    def _in_traced_code(ctx, node) -> bool:
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and is_jit_decorated(anc):
                return True
        return False

    # ---- coverage -------------------------------------------------------
    def _covered(self, ctx, call) -> bool:
        for anc in ctx.ancestors(call):
            if isinstance(anc, (ast.With, ast.AsyncWith)):
                for item in anc.items:
                    if _is_track_call(item.context_expr):
                        return True
        # nested-def idiom: def dispatch(): <call> ... with track(...):
        #   retry_call(dispatch, ...)
        fn = enclosing_function(ctx, call)
        while fn is not None:
            outer = enclosing_function(ctx, fn)
            if outer is None:
                return False
            if self._referenced_under_track(outer, fn.name):
                return True
            fn = outer
        return False

    @staticmethod
    def _referenced_under_track(outer_fn, name: str) -> bool:
        for node in ast.walk(outer_fn):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(_is_track_call(i.context_expr) for i in node.items):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and sub.id == name and \
                        isinstance(sub.ctx, ast.Load):
                    return True
        return False

    # ---- prewarm registry side ------------------------------------------
    def _collect_prewarm_kernels(self, tree) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            fname = node.name.lower()
            if "entry" not in fname and "prewarm" not in fname:
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Tuple) and sub.elts:
                    first = sub.elts[0]
                    if isinstance(first, ast.Constant) and isinstance(
                        first.value, str
                    ) and "." in first.value:
                        self._prewarmed.add(first.value)

    def finalize(self, project):
        for kernel, (path, line) in sorted(self._tracked.items()):
            if kernel not in self._prewarmed:
                yield Finding(
                    self.name, path, line, 0,
                    f"kernel '{kernel}' is ledger-tracked but no "
                    "prewarm registry entry builds this key "
                    "(parallel/device_pool.py / partitioner.py) — its "
                    "first dispatch cold-compiles inside a timed "
                    "window",
                    "",
                )
