"""Rule ``telemetry-contract``: telemetry names and heartbeat fields
are declared and documented.

Absorbs ``scripts/check-telemetry-names`` (PR 2's grep lint) as a
first-class staticcheck rule, AST-based instead of regex-based:

* every string-literal name at a tracer call site
  (``TRACE/tr/tracer .span/.count/.gauge/.observe/.add_span``) must be
  declared in the ``adam_tpu/utils/telemetry.py`` registry — a renamed
  or ad-hoc metric can't silently fork the contract;
* every dotted registry name must appear in docs/OBSERVABILITY.md's
  name contract (whole-token match, so a prefix can't ride on a longer
  documented name);
* every ``telemetry.HEARTBEAT_FIELDS`` member must appear in
  docs/OBSERVABILITY.md's heartbeat schema.

The declared-name set comes from a static parse of the registry module
(``_span("...")``/``_metric("...")`` literal registrations and the
``HEARTBEAT_FIELDS`` tuple); when the tree under check IS this repo,
the imported registry is merged in as well, covering the handful of
names registered through ``instrumentation`` constants in a loop.  The
fault-point docs check that also lived in the old script now belongs
to the ``fault-registry`` rule."""

from __future__ import annotations

import ast
import os
import re

from adam_tpu.staticcheck.core import Finding, Rule, register
from adam_tpu.staticcheck.rules._astutil import terminal_name

REGISTRY_MODULE = "adam_tpu/utils/telemetry.py"
DOC_FILE = "docs/OBSERVABILITY.md"

#: Prometheus mangling contract (gateway/metrics.py mirrors
#: utils/telemetry.prometheus_name/prometheus_name_valid; kept as
#: literals here so the rule lints foreign trees without importing
#: them — tests pin the two in sync).
PROMETHEUS_PREFIX = "adam_tpu_"
_PROM_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")

_TRACER_RECEIVERS = frozenset({"TRACE", "tr", "tracer"})
_TRACER_METHODS = frozenset({"span", "count", "gauge", "observe",
                             "add_span"})


def parse_registry(tree) -> tuple[set, tuple]:
    """Static view of the registry: literal ``_span``/``_metric``
    registrations + the HEARTBEAT_FIELDS literal tuple."""
    declared: set[str] = set()
    heartbeat: tuple = ()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and terminal_name(node.func) in (
            "_span", "_metric"
        ):
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                declared.add(node.args[0].value)
        elif isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id == "HEARTBEAT_FIELDS"
                   for t in node.targets):
                v = node.value
                if isinstance(v, (ast.Tuple, ast.List)):
                    heartbeat = tuple(
                        e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    )
    return declared, heartbeat


def _whole_token(name: str, doc: str, charset: str = "a-z0-9_.") -> bool:
    return bool(re.search(
        rf"(?<![{charset}]){re.escape(name)}(?![{charset}])", doc
    ))


@register
class TelemetryContractRule(Rule):
    name = "telemetry-contract"
    summary = ("undeclared telemetry names at tracer call sites; "
               "registry names / heartbeat fields missing from "
               "OBSERVABILITY.md")
    contract = (
        "Span/counter/gauge/histogram names used at call sites are "
        "declared in utils/telemetry.py and documented in docs/"
        "OBSERVABILITY.md, as are the heartbeat NDJSON fields — the "
        "stable consumer contract (docs/OBSERVABILITY.md)."
    )

    def __init__(self, declared=None, heartbeat_fields=None):
        # injectable for fixture tests; resolved lazily otherwise
        self._declared = set(declared) if declared is not None else None
        self._heartbeat = (tuple(heartbeat_fields)
                           if heartbeat_fields is not None else None)
        self._sites: list = []  # (name, relpath, line, col, snippet)

    def visit(self, ctx):
        if ctx.relpath == REGISTRY_MODULE:
            return ()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _TRACER_METHODS):
                continue
            recv = terminal_name(f.value)
            if recv not in _TRACER_RECEIVERS:
                continue
            if node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                self._sites.append((
                    node.args[0].value, ctx.relpath, node.lineno,
                    node.col_offset, ctx.line_text(node.lineno),
                ))
        return ()

    def _resolve_registry(self, project) -> tuple[set, tuple]:
        if self._declared is not None:
            return self._declared, self._heartbeat or ()
        tree = project.parse_module(REGISTRY_MODULE)
        if tree is None:
            return set(), ()
        declared, heartbeat = parse_registry(tree)
        # checking this very repo: merge the imported registry, which
        # also holds the loop-registered instrumentation timer names
        try:
            import adam_tpu.utils.telemetry as tele

            pkg_file = os.path.abspath(tele.__file__)
            if pkg_file == os.path.abspath(
                os.path.join(project.root, REGISTRY_MODULE)
            ):
                declared |= set(tele.registered_names())
                heartbeat = tuple(tele.HEARTBEAT_FIELDS)
        except Exception:
            pass
        return declared, heartbeat

    def finalize(self, project):
        declared, heartbeat = self._resolve_registry(project)
        if not declared:
            return  # no registry in this tree: nothing to lint against
        for name, path, line, col, snippet in self._sites:
            if name not in declared:
                yield Finding(
                    self.name, path, line, col,
                    f"undeclared telemetry name {name!r} — declare it "
                    "in adam_tpu/utils/telemetry.py (and docs/"
                    "OBSERVABILITY.md) or use a declared one",
                    snippet,
                )
        doc = project.read_doc(DOC_FILE)
        if doc is None:
            return
        for name in sorted(declared):
            if re.fullmatch(r"[a-z0-9_.]+", name) and "." in name and \
                    not _whole_token(name, doc):
                yield Finding(
                    self.name, REGISTRY_MODULE, 1, 0,
                    f"registry name '{name}' missing from {DOC_FILE}'s "
                    "name contract",
                    "",
                )
        for fld in heartbeat:
            if not _whole_token(fld, doc, charset="a-zA-Z0-9_"):
                yield Finding(
                    self.name, REGISTRY_MODULE, 1, 0,
                    f"heartbeat field '{fld}' missing from {DOC_FILE}'s "
                    "heartbeat schema",
                    "",
                )
        # Prometheus exposition contract (gateway GET /metrics): every
        # dotted contract name must mangle ('.' -> '_' under the
        # adam_tpu_ prefix) to a VALID metric name, and no two distinct
        # names may collide once mangled — a collision would silently
        # merge two series in every scraper.  Display-style
        # instrumentation timer names (spaces/parens) sit outside the
        # dotted contract; the renderer sanitizes them instead.
        mangled: dict = {}
        for name in sorted(declared):
            if not (re.fullmatch(r"[a-z0-9_.]+", name) and "." in name):
                continue
            prom = PROMETHEUS_PREFIX + name.replace(".", "_")
            if not _PROM_NAME_RE.fullmatch(prom):
                yield Finding(
                    self.name, REGISTRY_MODULE, 1, 0,
                    f"registry name '{name}' mangles to '{prom}', not a "
                    "valid Prometheus metric name",
                    "",
                )
            prior = mangled.get(prom)
            if prior is not None:
                yield Finding(
                    self.name, REGISTRY_MODULE, 1, 0,
                    f"registry names '{prior}' and '{name}' collide as "
                    f"Prometheus metric '{prom}' — every scraper would "
                    "merge their series",
                    "",
                )
            else:
                mangled[prom] = name
