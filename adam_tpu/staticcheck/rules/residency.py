"""Rule ``residency``: window bases/quals place host->device at ingest
only.

The device-resident-windows contract (docs/PERF.md "Device-resident
windows"): a streamed window's ``bases``/``quals`` matrices cross the
tunnel ONCE, when the window is tokenized
(``device_pool.make_resident_window`` / ``partitioner.
mesh_resident_window`` under ``pass_scope("ingest")``), and the
markdup/observe/apply passes dispatch against the
:class:`~adam_tpu.parallel.device_pool.ResidentWindow` handle.  A new
``putter``/``DevicePool.put``/``put_rows`` placement of those matrices
inside a dispatch path silently re-ships the fattest arrays in the
pipeline every pass — exactly the regression this rule exists to stop
(the guardrail the ROADMAP's "Device-resident windows end-to-end" item
names).

Detection: inside the streamed dispatch surface
(``pipelines/{bqsr,markdup,streamed}.py``,
``parallel/{device_pool,partitioner}.py``), a call whose argument
expression reads a ``.bases`` or ``.quals`` attribute is flagged when
the call target is a placer (a name bound from ``putter(...)``,
``put``/``put_rows``/``put_replicated``/``device_put``) **or a
``pad_rows_np`` grid pad** — padding the fat window matrices is what a
device ship looks like on this surface, whether the placement happens
in the same expression or via a tuple handed to a mesh collective.
Functions whose name (or any
enclosing function's name) matches ``*resident*``/``*ingest*`` — the
sanctioned placement sites — or the warm/prewarm/probe/bench patterns
are exempt.  The legacy non-resident fallbacks (residency off, a dead
handle, an eviction replay re-shipping from the host ingest copy) stay
in the code on purpose and carry ``noqa[residency]`` suppressions with
reasons, per the usual suppression contract."""

from __future__ import annotations

import ast
import fnmatch

from adam_tpu.staticcheck.core import Rule, register
from adam_tpu.staticcheck.rules._astutil import (
    WARMUP_FN_PATTERNS,
    terminal_name,
)

#: The streamed flagship's dispatch surface — the scope the residency
#: contract covers.  The non-streamed distributed paths (parallel/
#: dist.py, sharded.py) predate residency and stay out, like the
#: dispatch-ledger rule's baseline treatment of them.
SCOPE_FILES = (
    "adam_tpu/pipelines/bqsr.py",
    "adam_tpu/pipelines/markdup.py",
    "adam_tpu/pipelines/streamed.py",
    "adam_tpu/parallel/device_pool.py",
    "adam_tpu/parallel/partitioner.py",
    # the cross-job coalescer dispatches fused grids built from
    # ResidentWindow slices; its non-resident re-ship fallbacks must
    # stay visibly fallbacks (serve/batching.py)
    "adam_tpu/serve/batching.py",
)

#: Call targets that place host arrays on device — plus the grid pad
#: that precedes every such ship on this surface (the pad is flagged
#: even when the placement happens downstream via a tuple argument).
PLACER_NAMES = frozenset({
    "put", "put_rows", "put_replicated", "device_put", "pad_rows_np",
})

#: Function-name patterns exempt from the rule: the sanctioned ingest
#: placement builders, and warm/prewarm/probe/bench bodies (dummy
#: placements are the point there).
EXEMPT_FN_PATTERNS = ("*resident*", "*ingest*") + WARMUP_FN_PATTERNS

#: The window matrices the ingest-once contract covers.
_RESIDENT_ATTRS = frozenset({"bases", "quals"})


def _reads_resident_attr(node) -> str | None:
    """The first ``.bases``/``.quals`` attribute read inside ``node``
    (None when it reads neither)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _RESIDENT_ATTRS:
            return sub.attr
    return None


def _fn_exempt(name: str) -> bool:
    return any(fnmatch.fnmatchcase(name, p) for p in EXEMPT_FN_PATTERNS)


@register
class ResidencyRule(Rule):
    name = "residency"
    summary = ("window bases/quals host->device placement outside the "
               "ingest-resident path (the passes must dispatch against "
               "the ResidentWindow handle)")
    contract = (
        "A streamed window's bases/quals matrices place on device once, "
        "at ingest (ResidentWindow under pass_scope('ingest')); markdup/"
        "observe/apply dispatch against the handle.  Re-placements in "
        "the dispatch paths are fallbacks and must carry a justified "
        "noqa[residency] (docs/PERF.md 'Device-resident windows')."
    )

    def visit(self, ctx):
        if ctx.relpath not in SCOPE_FILES:
            return
        # names bound from putter(...) are placers too (_put = putter(d))
        placers = set(PLACER_NAMES)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if terminal_name(node.value.func) == "putter":
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            placers.add(t.id)
        yield from self._walk(ctx, ctx.tree.body, placers, exempt=False)

    def _walk(self, ctx, stmts, placers, exempt: bool):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._walk(
                    ctx, stmt.body, placers,
                    exempt or _fn_exempt(stmt.name),
                )
                continue
            if isinstance(stmt, ast.ClassDef):
                yield from self._walk(ctx, stmt.body, placers, exempt)
                continue
            if exempt:
                # exemption is lexical: everything under a sanctioned
                # function (nested defs included) is placement-side
                yield from self._walk_children(ctx, stmt, placers)
                continue
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                tname = terminal_name(sub.func)
                if tname not in placers or not sub.args:
                    continue
                attr = _reads_resident_attr(sub.args[0])
                if attr is None:
                    continue
                yield ctx.finding(
                    self.name, sub,
                    f"host->device placement of window .{attr} outside "
                    "the ingest-resident path — dispatch against the "
                    "ResidentWindow handle, or justify the fallback "
                    "with noqa[residency] (docs/PERF.md "
                    "'Device-resident windows')",
                )

    def _walk_children(self, ctx, stmt, placers):
        """Recurse into defs nested under an exempt statement so their
        bodies inherit the exemption (nothing is flagged there)."""
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                yield from self._walk(ctx, sub.body, placers, True)
            else:
                yield from self._walk_children(ctx, sub, placers)
