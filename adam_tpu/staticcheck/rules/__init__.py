"""Built-in rules — importing this package registers them all."""

from adam_tpu.staticcheck.rules import (  # noqa: F401
    dispatch,
    durability,
    faultpoints,
    hostsync,
    locks,
    residency,
    telemetry_names,
)
