"""Rule ``lock-discipline``: shared mutable state stays behind its
lock.

The streamed pipeline spawns threads in half a dozen modules (writer
pool, heartbeat, fetch chunks, prewarm workers, deadline watchdogs);
their shared state is guarded by convention — ``TimerRegistry`` takes
``self._lock`` around every ``Timer`` read-modify-write precisely
because codec timers fire from the ingest thread and the writer pool
concurrently.  Two checks encode that convention:

* **module globals** — in a module that spawns threads
  (``threading.Thread`` / ``ThreadPoolExecutor`` textually present),
  rebinding a ``global``-declared name or mutating a module-level
  container (``.add``/``.append``/``.update``/``[...]=``/``del``)
  outside a ``with <lock>`` block is a finding.  Lock recognition is
  by name: any context manager whose terminal name contains ``lock``.
* **locked classes** — in ANY class that owns a lock attribute
  (``self._lock = threading.Lock()`` or a dataclass
  ``field(default_factory=threading.Lock)``), methods that mutate the
  instance's container attributes outside ``with self.<lock>`` are
  findings.  The ``*_locked`` naming convention is honored both ways:
  a method named ``*_locked`` asserts "caller holds the lock" and is
  exempt inside, but *calling* one outside a ``with``-lock block is a
  finding — the convention is only as good as its call sites."""

from __future__ import annotations

import ast

from adam_tpu.staticcheck.core import Rule, register
from adam_tpu.staticcheck.rules._astutil import (
    dotted_name,
    in_with_matching,
    name_contains_lock,
    terminal_name,
)

_MUTATORS = frozenset({
    "add", "append", "appendleft", "extend", "update", "clear",
    "discard", "remove", "pop", "popleft", "insert", "setdefault",
})

_THREAD_SPAWNERS = ("threading.Thread", "Thread", "ThreadPoolExecutor",
                    "concurrent.futures.ThreadPoolExecutor")

_CONTAINER_FACTORIES = ("dict", "list", "set", "deque", "defaultdict",
                        "OrderedDict", "Counter")


def _spawns_threads(tree) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            if dotted_name(node.func) in _THREAD_SPAWNERS:
                return True
    return False


def _module_containers(tree) -> set:
    """Module-level names bound to container literals/constructors."""
    out: set[str] = set()
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        v = stmt.value
        is_container = isinstance(v, (ast.Dict, ast.List, ast.Set)) or (
            isinstance(v, ast.Call)
            and terminal_name(v.func) in _CONTAINER_FACTORIES
        )
        if is_container:
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _under_lock(ctx, node) -> bool:
    return in_with_matching(ctx, node, name_contains_lock)


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    summary = ("shared-state mutation outside its lock in "
               "thread-spawning modules and lock-owning classes")
    contract = (
        "Module globals in thread-spawning modules and container "
        "attributes of lock-owning classes (TimerRegistry, Tracer, "
        "the prewarm/compile-ledger seen-sets) mutate only under "
        "their lock; *_locked methods are callable only under it."
    )

    def visit(self, ctx):
        if not ctx.relpath.startswith("adam_tpu/"):
            return
        if _spawns_threads(ctx.tree):
            yield from self._check_module_globals(ctx)
        yield from self._check_locked_classes(ctx)

    # ---- module-global discipline --------------------------------------
    def _check_module_globals(self, ctx):
        containers = _module_containers(ctx.tree)
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            declared: set[str] = set()
            for stmt in fn.body:
                if isinstance(stmt, ast.Global):
                    declared.update(stmt.names)
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if (isinstance(t, ast.Name) and t.id in declared
                                and not _under_lock(ctx, node)):
                            yield ctx.finding(
                                self.name, node,
                                f"rebinding module global '{t.id}' "
                                "outside a lock in a thread-spawning "
                                "module",
                            )
                        elif (isinstance(t, ast.Subscript)
                              and isinstance(t.value, ast.Name)
                              and t.value.id in containers
                              and not _under_lock(ctx, node)):
                            yield ctx.finding(
                                self.name, node,
                                f"item assignment on module container "
                                f"'{t.value.id}' outside a lock in a "
                                "thread-spawning module",
                            )
                elif isinstance(node, ast.Call):
                    f = node.func
                    if (isinstance(f, ast.Attribute)
                            and f.attr in _MUTATORS
                            and isinstance(f.value, ast.Name)
                            and f.value.id in containers
                            and not _under_lock(ctx, node)):
                        yield ctx.finding(
                            self.name, node,
                            f"mutation '{f.value.id}.{f.attr}()' of a "
                            "module container outside a lock in a "
                            "thread-spawning module",
                        )

    # ---- lock-owning class discipline ----------------------------------
    def _check_locked_classes(self, ctx):
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            lock_attrs = self._lock_attrs(cls)
            if not lock_attrs:
                continue
            shared = self._container_attrs(cls)
            for method in cls.body:
                if not isinstance(
                    method, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                exempt = (
                    method.name.endswith("_locked")
                    or method.name in ("__init__", "__post_init__")
                )
                for node in ast.walk(method):
                    if isinstance(node, ast.Call):
                        f = node.func
                        # calling a *_locked helper asserts the caller
                        # holds the lock — verify it lexically does
                        if (isinstance(f, ast.Attribute)
                                and f.attr.endswith("_locked")
                                and isinstance(f.value, ast.Name)
                                and f.value.id == "self"
                                and not method.name.endswith("_locked")
                                and not _under_lock(ctx, node)):
                            yield ctx.finding(
                                self.name, node,
                                f"call to self.{f.attr}() outside a "
                                "with-lock block — *_locked methods "
                                "assert the caller holds the lock",
                            )
                            continue
                        if exempt:
                            continue
                        if (isinstance(f, ast.Attribute)
                                and f.attr in _MUTATORS
                                and self._is_self_attr(f.value, shared)
                                and not _under_lock(ctx, node)):
                            yield ctx.finding(
                                self.name, node,
                                f"mutation 'self.{f.value.attr}."
                                f"{f.attr}()' outside 'with self."
                                f"{sorted(lock_attrs)[0]}' in a "
                                "lock-owning class",
                            )
                    elif isinstance(node, (ast.Assign, ast.AugAssign)) \
                            and not exempt:
                        targets = (
                            node.targets if isinstance(node, ast.Assign)
                            else [node.target]
                        )
                        for t in targets:
                            if (isinstance(t, ast.Subscript)
                                    and self._is_self_attr(t.value, shared)
                                    and not _under_lock(ctx, node)):
                                yield ctx.finding(
                                    self.name, node,
                                    f"item assignment on 'self."
                                    f"{t.value.attr}' outside 'with "
                                    f"self.{sorted(lock_attrs)[0]}' in "
                                    "a lock-owning class",
                                )

    @staticmethod
    def _is_self_attr(node, shared) -> bool:
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in shared)

    @staticmethod
    def _lock_attrs(cls) -> set:
        """Attributes holding a lock: assigned ``threading.Lock()`` /
        ``RLock()`` in __init__, or a dataclass field whose
        default_factory is a Lock."""
        out: set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                d = dotted_name(node.value.func)
                if d.endswith(("Lock", "RLock")):
                    for t in node.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"):
                            out.add(t.attr)
            if isinstance(node, ast.AnnAssign) and isinstance(
                node.value, ast.Call
            ):
                # dataclass: x: threading.Lock = field(default_factory=...)
                if terminal_name(node.value.func) == "field":
                    for kw in node.value.keywords:
                        if kw.arg == "default_factory" and dotted_name(
                            kw.value
                        ).endswith(("Lock", "RLock")):
                            if isinstance(node.target, ast.Name):
                                out.add(node.target.id)
        return out

    @staticmethod
    def _container_attrs(cls) -> set:
        """Instance attributes initialized as containers (assigned in
        __init__/__post_init__ or dataclass container fields)."""
        out: set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                v = node.value
                is_container = isinstance(
                    v, (ast.Dict, ast.List, ast.Set)
                ) or (isinstance(v, ast.Call)
                      and terminal_name(v.func) in _CONTAINER_FACTORIES)
                if not is_container:
                    continue
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        out.add(t.attr)
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.value, ast.Call
            ):
                if terminal_name(node.value.func) == "field":
                    for kw in node.value.keywords:
                        if kw.arg == "default_factory" and terminal_name(
                            kw.value
                        ) in _CONTAINER_FACTORIES:
                            if isinstance(node.target, ast.Name):
                                out.add(node.target.id)
        return out
