"""Rule ``host-sync``: hot-path device->host synchronization must route
through ``utils/transfer.device_fetch``.

The PR 7 device ledger (``device.d2h.bytes``/``device.d2h.bps`` and the
per-pass ``transfers`` attribution) is complete only **by convention**:
every fetch of a device-resident array crosses in
``transfer.device_fetch``, which also carries the fetch deadline
watchdog, the transient retry and the ``device.fetch`` fault point
(docs/ROBUSTNESS.md).  A stray ``np.asarray(device_value)`` in the hot
path is an unledgered, unwatched, unretryable d2h RPC — exactly the
drift this rule kills.

Detection is a per-function forward taint pass: values produced by
jit-compiled callables (``@jax.jit`` functions, ``jax.jit(...)``
bindings, ``*_kernel``/``*_jit`` names, the mesh window methods, a
``putter(...)``-made placer) are *device-tainted*; taint follows
assignment, tuple unpacking, indexing, attribute access and method
calls; ``device_fetch`` launders it.  Applying ``np.asarray`` /
``np.array`` / ``np.ascontiguousarray`` / ``float`` / ``int`` /
``bool`` / ``.item()`` / ``.tolist()`` to a tainted value — or calling
``jax.device_get`` / ``.block_until_ready()`` at all — inside
``pipelines/``, ``parallel/`` or ``ops/`` is a finding.  An
``isinstance(x, np.ndarray)`` test narrows ``x`` to host inside the
guarded branch (the standard host-short-circuit idiom)."""

from __future__ import annotations

import ast
import fnmatch

from adam_tpu.staticcheck.core import Rule, register
from adam_tpu.staticcheck.rules._astutil import (
    WARMUP_FN_PATTERNS,
    collect_jit_callables,
    dotted_name,
    is_jit_decorated,
    terminal_name,
)


def _is_warmup_fn(fn) -> bool:
    """warm/prewarm/probe/bench functions force compiles and sync on
    purpose — their body is not hot-path code (the pool/mesh prewarm
    executes these thunks under its own span/track umbrella)."""
    return any(fnmatch.fnmatchcase(fn.name, p) for p in WARMUP_FN_PATTERNS)

SCOPE_PREFIXES = ("adam_tpu/pipelines/", "adam_tpu/parallel/",
                  "adam_tpu/ops/", "adam_tpu/serve/",
                  "adam_tpu/gateway/")

#: Callable-name patterns whose results are device-resident (or may
#: be): kernels, jit factories, the mesh per-window collectives, the
#: backend-polymorphic observe.  fnmatch'd against the call's terminal
#: name, so cross-module ``bqsr_mod._observe_device(...)`` matches too.
DEVICE_CALL_PATTERNS = (
    "*_kernel",
    "*_jit",
    "_observe_device",
    "observe_window",
    "apply_window",
    "markdup_window",
    "device_lexsort",
    "*_columns_dispatch",
    "device_put",
    "put_replicated",
)

#: Calls that launder taint: the result is host-resident numpy.
SANITIZERS = ("device_fetch",)

_NP_SINKS = {
    "np.asarray", "numpy.asarray",
    "np.array", "numpy.array",
    "np.ascontiguousarray", "numpy.ascontiguousarray",
}
_BUILTIN_SINKS = {"float", "int", "bool"}
_METHOD_SINKS = {"item", "tolist"}


def _matches_device_call(name: str) -> bool:
    return any(fnmatch.fnmatchcase(name, p) for p in DEVICE_CALL_PATTERNS)


@register
class HostSyncRule(Rule):
    name = "host-sync"
    summary = ("hot-path d2h sync (np.asarray/.item()/float()/"
               "block_until_ready on device values) outside "
               "transfer.device_fetch")
    contract = (
        "Every device->host fetch in pipelines/, parallel/ and ops/ "
        "routes through utils/transfer.device_fetch so the tunnel-byte "
        "ledger, fetch watchdog, retry and fault point stay complete "
        "by construction (docs/PERF.md 'Device ledger measurements', "
        "docs/ROBUSTNESS.md)."
    )

    def visit(self, ctx):
        if not ctx.relpath.startswith(SCOPE_PREFIXES):
            return
        jit_names = collect_jit_callables(ctx.tree)
        # names bound from putter(...) place arrays on device
        placers: set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if terminal_name(node.value.func) in ("putter",):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            placers.add(t.id)
        self._jit_names = jit_names
        self._placers = placers

        findings: list = []
        # one walk from the module body: _walk_block recurses into
        # every function/class it encounters exactly once (including
        # defs nested in module-level if/try), skipping jit-decorated
        # bodies (trace-time code where jnp ops are the point, not a
        # sync) and warm/probe functions
        self._walk_block(ctx, ctx.tree.body, set(), findings)
        yield from findings

    # ---- helpers --------------------------------------------------------
    def _is_device_call(self, call: ast.Call, tainted) -> bool:
        func = call.func
        name = terminal_name(func)
        if name in SANITIZERS:
            return False
        if name in self._jit_names or name in self._placers:
            return True
        if _matches_device_call(name):
            return True
        d = dotted_name(func)
        if d.startswith(("jnp.", "jax.numpy.")):
            return True
        # method on a tainted value stays tainted (t.astype(...), t.sum())
        if isinstance(func, ast.Attribute) and self._tainted(
            func.value, tainted
        ):
            return True
        return False

    def _tainted(self, expr, tainted) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Call):
            if terminal_name(expr.func) in SANITIZERS:
                return False
            return self._is_device_call(expr, tainted)
        if isinstance(expr, ast.Attribute):
            # array metadata is host-resident even on device arrays
            if expr.attr in ("shape", "ndim", "dtype", "size", "nbytes"):
                return False
            return self._tainted(expr.value, tainted)
        if isinstance(expr, (ast.Subscript, ast.Starred)):
            return self._tainted(expr.value, tainted)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return any(self._tainted(e, tainted) for e in expr.elts)
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._tainted(expr.elt, tainted)
        if isinstance(expr, ast.BinOp):
            return (self._tainted(expr.left, tainted)
                    or self._tainted(expr.right, tainted))
        if isinstance(expr, ast.UnaryOp):
            return self._tainted(expr.operand, tainted)
        if isinstance(expr, ast.IfExp):
            return (self._tainted(expr.body, tainted)
                    or self._tainted(expr.orelse, tainted))
        if isinstance(expr, ast.NamedExpr):
            return self._tainted(expr.value, tainted)
        return False

    def _assign_names(self, target, value_tainted: bool, tainted) -> None:
        if isinstance(target, ast.Name):
            if value_tainted:
                tainted.add(target.id)
            else:
                tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_names(elt, value_tainted, tainted)
        elif isinstance(target, ast.Starred):
            self._assign_names(target.value, value_tainted, tainted)

    def _check_exprs(self, ctx, node, tainted, findings) -> None:
        """Scan every Call inside ``node`` for sink applications, and
        record NamedExpr bindings."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.NamedExpr) and isinstance(
                sub.target, ast.Name
            ):
                self._assign_names(
                    sub.target, self._tainted(sub.value, tainted), tainted
                )
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            d = dotted_name(func)
            name = terminal_name(func)
            args_tainted = any(
                self._tainted(a, tainted) for a in sub.args
            )
            if d in _NP_SINKS and args_tainted:
                findings.append(ctx.finding(
                    self.name, sub,
                    f"{d}() on a device value — route the fetch "
                    "through transfer.device_fetch (ledger + watchdog "
                    "+ retry)",
                ))
            elif (isinstance(func, ast.Name)
                  and func.id in _BUILTIN_SINKS
                  and len(sub.args) == 1 and args_tainted):
                findings.append(ctx.finding(
                    self.name, sub,
                    f"{func.id}() on a device value forces a blocking "
                    "d2h sync — fetch through transfer.device_fetch "
                    "first",
                ))
            elif (isinstance(func, ast.Attribute)
                  and func.attr in _METHOD_SINKS
                  and self._tainted(func.value, tainted)):
                findings.append(ctx.finding(
                    self.name, sub,
                    f".{func.attr}() on a device value forces a "
                    "blocking d2h sync — fetch through "
                    "transfer.device_fetch first",
                ))
            elif d == "jax.device_get":
                findings.append(ctx.finding(
                    self.name, sub,
                    "jax.device_get bypasses transfer.device_fetch "
                    "(unledgered, unwatched d2h)",
                ))
            elif (isinstance(func, ast.Attribute)
                  and func.attr == "block_until_ready") or (
                      d == "jax.block_until_ready"):
                findings.append(ctx.finding(
                    self.name, sub,
                    "block_until_ready in the hot path stalls the "
                    "dispatch pipeline — fetch through "
                    "transfer.device_fetch or keep the value lazy",
                ))

    def _walk_block(self, ctx, stmts, tainted, findings) -> None:
        """Forward walk over a statement block, threading the tainted
        name set through assignments and branch structure."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not is_jit_decorated(stmt) and not _is_warmup_fn(stmt):
                    # closure sees the taint state at its definition point
                    self._walk_block(ctx, stmt.body, set(tainted), findings)
                continue
            if isinstance(stmt, ast.ClassDef):
                for item in stmt.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and not is_jit_decorated(item) \
                            and not _is_warmup_fn(item):
                        self._walk_block(ctx, item.body, set(), findings)
                continue
            if isinstance(stmt, ast.Assign):
                self._check_exprs(ctx, stmt.value, tainted, findings)
                vt = self._tainted(stmt.value, tainted)
                for t in stmt.targets:
                    self._assign_names(t, vt, tainted)
                continue
            if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._check_exprs(ctx, stmt.value, tainted, findings)
                self._assign_names(
                    stmt.target, self._tainted(stmt.value, tainted), tainted
                )
                continue
            if isinstance(stmt, ast.AugAssign):
                self._check_exprs(ctx, stmt.value, tainted, findings)
                if self._tainted(stmt.value, tainted):
                    self._assign_names(stmt.target, True, tainted)
                continue
            if isinstance(stmt, ast.If):
                self._check_exprs(ctx, stmt.test, tainted, findings)
                narrowed = set(tainted)
                for n in _isinstance_ndarray_names(stmt.test):
                    narrowed.discard(n)
                else_taint = set(tainted)
                self._walk_block(ctx, stmt.body, narrowed, findings)
                self._walk_block(ctx, stmt.orelse, else_taint, findings)
                # conservative join: anything tainted in either branch
                tainted |= narrowed | else_taint
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._check_exprs(ctx, stmt.iter, tainted, findings)
                self._assign_names(
                    stmt.target, self._tainted(stmt.iter, tainted), tainted
                )
                self._walk_block(ctx, stmt.body, tainted, findings)
                self._walk_block(ctx, stmt.orelse, tainted, findings)
                continue
            if isinstance(stmt, ast.While):
                self._check_exprs(ctx, stmt.test, tainted, findings)
                self._walk_block(ctx, stmt.body, tainted, findings)
                self._walk_block(ctx, stmt.orelse, tainted, findings)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._check_exprs(
                        ctx, item.context_expr, tainted, findings
                    )
                    if item.optional_vars is not None:
                        self._assign_names(
                            item.optional_vars,
                            self._tainted(item.context_expr, tainted),
                            tainted,
                        )
                self._walk_block(ctx, stmt.body, tainted, findings)
                continue
            if isinstance(stmt, ast.Try):
                self._walk_block(ctx, stmt.body, tainted, findings)
                for h in stmt.handlers:
                    self._walk_block(ctx, h.body, set(tainted), findings)
                self._walk_block(ctx, stmt.orelse, tainted, findings)
                self._walk_block(ctx, stmt.finalbody, tainted, findings)
                continue
            # leaf statements: Expr, Return, Raise, Assert, Delete...
            self._check_exprs(ctx, stmt, tainted, findings)


def _isinstance_ndarray_names(test) -> set:
    """Names proven host-resident by an ``isinstance(x, np.ndarray)``
    test (possibly inside an ``and``)."""
    names: set[str] = set()
    nodes = [test]
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        nodes = list(test.values)
    for n in nodes:
        if (isinstance(n, ast.Call)
                and terminal_name(n.func) == "isinstance"
                and len(n.args) == 2
                and isinstance(n.args[0], ast.Name)
                and dotted_name(n.args[1]) in
                ("np.ndarray", "numpy.ndarray")):
            names.add(n.args[0].id)
    return names
