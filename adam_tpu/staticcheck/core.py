"""Checker engine: rule registry, suppressions, baseline, runner.

The engine is deliberately dependency-free (stdlib ``ast`` only) so
``adam-tpu check`` runs in CI images without jax/numpy and costs one
parse per file.  Rules are plugins: anything exposing the
:class:`Rule` interface can be registered — the built-ins live in
``adam_tpu/staticcheck/rules/`` and third-party rules load via
``--plugin dotted.module`` (the module either calls
:func:`register` at import or exposes a module-level ``RULES``
iterable).

Three layers decide what a finding means:

* **suppressions** — ``# adam-tpu: noqa[rule-a,rule-b] reason=...`` on
  the flagged line (or a comment-only line directly above it) silences
  a finding *in place*; the reason is mandatory, because a suppression
  without one is exactly the undocumented drift the checker exists to
  kill (a reason-less directive is itself reported, rule
  ``suppression``).
* **baseline** — a committed JSON file (default
  ``.staticcheck-baseline.json``) of triaged pre-existing findings,
  each with a justification.  Baselined findings don't fail the run;
  entries with an empty reason or entries whose finding no longer
  exists (stale) do, so the baseline can only shrink or stay honest.
* **new findings** — anything else fails the run (exit 1).

Exit codes are deterministic so CI can gate: 0 clean, 1 findings (new,
unjustified-baseline or reason-less suppression), 2 usage/internal
error.  ``--json`` emits schema ``adam_tpu.staticcheck/1``.
"""

from __future__ import annotations

import ast
import hashlib
import importlib
import json
import os
import re
from dataclasses import dataclass, field

SCHEMA = "adam_tpu.staticcheck/1"
BASELINE_SCHEMA = "adam_tpu.staticcheck_baseline/1"
DEFAULT_BASELINE = ".staticcheck-baseline.json"

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_ERROR = 2

#: Scan surface (mirrors scripts/check-telemetry-names): the package,
#: the test tree, the tooling, and the bench driver.
SCAN_ROOTS = ("adam_tpu", "tests", "tools", "scripts")
SCAN_FILES = ("bench.py",)

_SUPPRESS_RE = re.compile(
    r"#\s*adam-tpu:\s*noqa\[([A-Za-z0-9_*,\- ]+)\]"
    r"(?:\s+reason=(.*?))?\s*$"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    message: str
    snippet: str = ""  # stripped source line — the fingerprint anchor

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"


class Rule:
    """Plugin interface.  Subclasses set ``name`` (the suppression /
    ``--rules`` token), ``summary`` (one line for ``--list-rules``)
    and ``contract`` (the convention being enforced, rendered in
    docs/STATIC_ANALYSIS.md terms), then implement :meth:`visit` for
    per-file checks and optionally :meth:`finalize` for cross-file
    checks run after every file has been visited."""

    name: str = ""
    summary: str = ""
    contract: str = ""

    def visit(self, ctx: "FileContext"):
        return ()

    def finalize(self, project: "Project"):
        return ()


class FileContext:
    """One parsed source file handed to every rule's :meth:`Rule.visit`
    — parse once, share the tree and the parent map."""

    def __init__(self, root: str, path: str):
        self.root = root
        self.path = path
        self.relpath = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as fh:
            self.source = fh.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.relpath)
        self._parents: dict | None = None

    # parent links let rules walk from a call site out to an enclosing
    # ``with`` / ``def`` without a full custom visitor per rule
    @property
    def parents(self) -> dict:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def ancestors(self, node):
        p = self.parents.get(node)
        while p is not None:
            yield p
            p = self.parents.get(p)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node, message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        return Finding(rule, self.relpath, line, col, message,
                       self.line_text(line))


class Project:
    """Cross-file state shared with :meth:`Rule.finalize`."""

    def __init__(self, root: str):
        self.root = root
        self.files: list[str] = []  # relpaths visited

    def read_doc(self, relpath: str) -> str | None:
        """A docs file's text, or None when absent (fixture trees) —
        doc-side contract checks degrade to skipped, like the
        scripts/check-telemetry-names behavior they absorbed."""
        try:
            with open(os.path.join(self.root, relpath),
                      encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None

    def parse_module(self, relpath: str):
        """Parse one in-repo module to an AST (None when absent)."""
        try:
            with open(os.path.join(self.root, relpath),
                      encoding="utf-8") as fh:
                return ast.parse(fh.read(), filename=relpath)
        except (OSError, SyntaxError):
            return None


# -------------------------------------------------------------------------
# Rule registry (the plugin API)
# -------------------------------------------------------------------------
_REGISTRY: dict[str, type] = {}


def register(rule_cls: type) -> type:
    """Register a Rule class (usable as a decorator).  Re-registering a
    name replaces the previous rule — that's how a plugin can override
    a built-in."""
    if not getattr(rule_cls, "name", ""):
        raise ValueError(f"rule {rule_cls!r} has no name")
    _REGISTRY[rule_cls.name] = rule_cls
    return rule_cls


def all_rules() -> dict[str, type]:
    _load_builtins()
    return dict(_REGISTRY)


_BUILTINS_LOADED = False


def _load_builtins() -> None:
    global _BUILTINS_LOADED
    if not _BUILTINS_LOADED:
        importlib.import_module("adam_tpu.staticcheck.rules")
        _BUILTINS_LOADED = True


def load_plugins(specs) -> None:
    """Import plugin modules: each either registers rules at import
    time via :func:`register` or exposes ``RULES`` (iterable of Rule
    classes).  Also honors ``ADAM_TPU_CHECK_PLUGINS`` (colon-separated
    dotted module paths)."""
    for spec in specs:
        mod = importlib.import_module(spec)
        for rule_cls in getattr(mod, "RULES", ()):
            register(rule_cls)


# -------------------------------------------------------------------------
# Suppressions
# -------------------------------------------------------------------------
@dataclass
class Suppression:
    line: int
    rules: frozenset
    reason: str
    used: bool = False


def scan_suppressions(lines) -> list[Suppression]:
    out = []
    for i, text in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = frozenset(
            r.strip() for r in m.group(1).split(",") if r.strip()
        )
        reason = (m.group(2) or "").strip()
        out.append(Suppression(i, rules, reason))
    return out


def _suppression_for(finding: Finding, by_line: dict, lines) -> Suppression | None:
    """The directive covering ``finding``: same line, or a comment-only
    line directly above (for lines too long to carry the directive)."""
    for ln in (finding.line, finding.line - 1):
        sup = by_line.get(ln)
        if sup is None:
            continue
        if ln != finding.line:
            text = lines[ln - 1].lstrip() if 0 < ln <= len(lines) else ""
            if not text.startswith("#"):
                continue  # code line above — its directive is its own
        if finding.rule in sup.rules or "*" in sup.rules:
            return sup
    return None


# -------------------------------------------------------------------------
# Baseline
# -------------------------------------------------------------------------
def load_baseline(path: str) -> dict:
    """fingerprint -> entry dict.  A missing file is an empty baseline;
    a torn/invalid one is a hard error (exit 2) — CI must not pass on
    a baseline it couldn't read."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("schema") != BASELINE_SCHEMA:
        raise ValueError(
            f"{path}: unknown baseline schema {doc.get('schema')!r} "
            f"(expected {BASELINE_SCHEMA})"
        )
    return {e["fingerprint"]: e for e in doc.get("entries", [])}


def write_baseline(path: str, entries: list) -> None:
    doc = {
        "schema": BASELINE_SCHEMA,
        "entries": sorted(
            entries, key=lambda e: (e["path"], e["rule"], e["line"])
        ),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")


def fingerprint(finding: Finding, occurrence: int) -> str:
    """Stable identity for baseline matching: rule + file + the flagged
    line's text + the occurrence index among identical (rule, file,
    text) findings.  Line NUMBERS are deliberately excluded so edits
    elsewhere in the file don't churn the baseline; editing the flagged
    line itself retires the entry (it must be re-triaged)."""
    # finalize()-produced findings carry no source line; anchor those
    # on the message instead, or same-file same-rule findings would be
    # distinguished only by sort order (fixing one would silently
    # re-map its baseline entry onto a different finding)
    anchor = finding.snippet or finding.message
    basis = "|".join(
        (finding.rule, finding.path, anchor, str(occurrence))
    )
    return hashlib.sha256(basis.encode()).hexdigest()[:16]


# -------------------------------------------------------------------------
# Runner
# -------------------------------------------------------------------------
def iter_source_files(root: str):
    for sub in SCAN_ROOTS:
        top = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(
                d for d in dirnames
                if d not in {"__pycache__", ".git", "_build"}
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)
    for fn in SCAN_FILES:
        p = os.path.join(root, fn)
        if os.path.exists(p):
            yield p


@dataclass
class Report:
    root: str
    rules: list
    entries: list = field(default_factory=list)  # dicts, see to_json
    files_scanned: int = 0
    parse_errors: list = field(default_factory=list)

    @property
    def new_findings(self) -> list:
        return [e for e in self.entries if e["status"] == "new"]

    @property
    def ok(self) -> bool:
        return not self.new_findings and not self.parse_errors

    @property
    def exit_code(self) -> int:
        return EXIT_CLEAN if self.ok else EXIT_FINDINGS

    def counts(self) -> dict:
        c = {"new": 0, "baselined": 0, "suppressed": 0}
        for e in self.entries:
            c[e["status"]] = c.get(e["status"], 0) + 1
        c["files"] = self.files_scanned
        return c

    def to_json(self) -> dict:
        return {
            "schema": SCHEMA,
            "root": self.root,
            "rules": list(self.rules),
            "counts": self.counts(),
            "findings": list(self.entries),
            "parse_errors": list(self.parse_errors),
            "ok": self.ok,
        }

    def render(self) -> str:
        out = []
        order = {"new": 0, "baselined": 1, "suppressed": 2}
        for e in sorted(
            self.entries,
            key=lambda e: (order[e["status"]], e["path"], e["line"]),
        ):
            if e["status"] == "suppressed":
                continue  # silenced in place; only the count prints
            tag = "" if e["status"] == "new" else " [baselined]"
            out.append(
                f"{e['path']}:{e['line']}:{e['col']}: "
                f"[{e['rule']}]{tag} {e['message']}"
            )
            if e.get("snippet"):
                out.append(f"    {e['snippet']}")
        for err in self.parse_errors:
            out.append(f"PARSE ERROR: {err}")
        c = self.counts()
        out.append(
            f"adam-tpu check: {c['new']} finding(s), "
            f"{c['baselined']} baselined, {c['suppressed']} suppressed "
            f"({c['files']} files, rules: {', '.join(self.rules)})"
        )
        out.append("OK" if self.ok else "FAIL")
        return "\n".join(out)


def run_checks(
    root: str,
    rule_names=None,
    plugins=(),
    baseline_path: str | None = None,
    update_baseline: bool = False,
    files=None,
) -> Report:
    """Run the checker over ``root``.  ``rule_names`` restricts the
    rule set (None = all registered); ``files`` restricts the scanned
    files (absolute paths; None = the standard scan surface)."""
    _load_builtins()
    env_plugins = [
        p for p in os.environ.get("ADAM_TPU_CHECK_PLUGINS", "").split(":")
        if p
    ]
    load_plugins(list(plugins) + env_plugins)

    registry = dict(_REGISTRY)
    if rule_names is not None:
        unknown = sorted(set(rule_names) - set(registry))
        if unknown:
            raise ValueError(
                f"unknown rule(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(registry))})"
            )
        registry = {n: registry[n] for n in rule_names}
    rules = [cls() for _, cls in sorted(registry.items())]

    root = os.path.abspath(root)
    project = Project(root)
    report = Report(root=root, rules=[r.name for r in rules])

    raw: list[Finding] = []
    suppressions: dict[str, tuple] = {}  # relpath -> (by_line, lines)
    paths = list(files) if files is not None else list(iter_source_files(root))
    for path in paths:
        try:
            ctx = FileContext(root, path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            report.parse_errors.append(f"{path}: {e}")
            continue
        report.files_scanned += 1
        project.files.append(ctx.relpath)
        sups = scan_suppressions(ctx.lines)
        suppressions[ctx.relpath] = ({s.line: s for s in sups}, ctx.lines)
        for rule in rules:
            raw.extend(rule.visit(ctx) or ())
    for rule in rules:
        raw.extend(rule.finalize(project) or ())

    # reason-less suppressions are findings in their own right
    for relpath, (by_line, _lines) in sorted(suppressions.items()):
        for sup in by_line.values():
            if not sup.reason:
                raw.append(Finding(
                    "suppression", relpath, sup.line, 0,
                    "suppression without a reason= justification "
                    "(# adam-tpu: noqa[rule] reason=...)",
                    _lines[sup.line - 1].strip()
                    if 0 < sup.line <= len(_lines) else "",
                ))

    baseline_file = (
        baseline_path
        if baseline_path is not None
        else os.path.join(root, DEFAULT_BASELINE)
    )
    baseline = load_baseline(baseline_file) if baseline_file else {}

    occ: dict[tuple, int] = {}
    matched_fps = set()
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.col, f.rule)):
        key = (f.rule, f.path, f.snippet)
        k = occ.get(key, 0)
        occ[key] = k + 1
        fp = fingerprint(f, k)
        by_line, lines = suppressions.get(f.path, ({}, []))
        sup = _suppression_for(f, by_line, lines)
        if sup is not None and sup.reason and f.rule != "suppression":
            sup.used = True
            # a suppressed finding still EXISTS: its baseline entry (if
            # any) is matched, not stale
            if fp in baseline:
                matched_fps.add(fp)
            status, reason = "suppressed", sup.reason
        elif fp in baseline:
            matched_fps.add(fp)
            reason = baseline[fp].get("reason", "")
            status = "baselined" if reason else "new"
            if not reason:
                f = Finding(
                    f.rule, f.path, f.line, f.col,
                    f.message + " [baselined without justification — "
                    "add a reason to the baseline entry]", f.snippet,
                )
        else:
            status, reason = "new", ""
        report.entries.append({
            "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
            "message": f.message, "snippet": f.snippet,
            "fingerprint": fp, "status": status, "reason": reason,
        })

    # unused suppressions: a directive whose finding no longer fires is
    # the noqa twin of a stale baseline entry — report it so exemption
    # debt shrinks too.  Only judged when every rule it names ran (a
    # --rules subset must not condemn directives for the other rules).
    active = {r.name for r in rules}
    for relpath, (by_line, _lines) in sorted(suppressions.items()):
        for sup in by_line.values():
            if (sup.reason and not sup.used and "*" not in sup.rules
                    and sup.rules <= active):
                report.entries.append({
                    "rule": "suppression", "path": relpath,
                    "line": sup.line, "col": 0,
                    "message": (
                        "unused suppression — no finding of "
                        f"[{', '.join(sorted(sup.rules))}] fires here; "
                        "remove the directive"
                    ),
                    "snippet": _lines[sup.line - 1].strip()
                    if 0 < sup.line <= len(_lines) else "",
                    "fingerprint": "", "status": "new", "reason": "",
                })

    # stale baseline entries: the finding they excuse no longer exists
    # — fail so the baseline shrinks with the debt it records.  Only
    # entries belonging to an ACTIVE rule can be judged stale (a
    # --rules subset run must not condemn the other rules' entries).
    for fp, entry in sorted(baseline.items()):
        if entry.get("rule") not in active:
            continue
        if fp not in matched_fps:
            report.entries.append({
                "rule": "baseline", "path": entry.get("path", "?"),
                "line": int(entry.get("line", 0)), "col": 0,
                "message": (
                    f"stale baseline entry {fp} "
                    f"[{entry.get('rule', '?')}]: finding no longer "
                    "exists — remove it from the baseline"
                ),
                "snippet": entry.get("snippet", ""),
                "fingerprint": fp, "status": "new", "reason": "",
            })

    if update_baseline and baseline_file:
        # entries of rules not in this run carry over untouched (a
        # --rules subset update must not drop the others' triage)
        entries = [
            e for e in baseline.values()
            if e.get("rule") not in active
        ]
        for e in report.entries:
            # meta findings (stale-baseline, suppression hygiene) are
            # fixed in place, never baselined — and suppressed findings
            # already carry their justification at the site
            if (e["rule"] in ("baseline", "suppression")
                    or not e["fingerprint"]
                    or e["status"] == "suppressed"):
                continue
            old = baseline.get(e["fingerprint"], {})
            entries.append({
                "fingerprint": e["fingerprint"], "rule": e["rule"],
                "path": e["path"], "line": e["line"],
                "snippet": e["snippet"],
                "reason": old.get("reason", e.get("reason", "")),
            })
        write_baseline(baseline_file, entries)

    return report
