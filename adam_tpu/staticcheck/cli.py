"""Argument surface shared by ``adam-tpu check``, ``python -m
adam_tpu.staticcheck`` and ``scripts/staticcheck``."""

from __future__ import annotations

import argparse
import json
import os
import sys

from adam_tpu.staticcheck import core


def configure(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--root", default=None, metavar="DIR",
        help="repository root to check (default: auto-detected from "
        "this package's location)",
    )
    parser.add_argument(
        "--rules", default=None, metavar="R1,R2",
        help="comma-separated rule subset (default: all registered); "
        "see --list-rules",
    )
    parser.add_argument(
        "--plugin", action="append", default=[], metavar="MODULE",
        help="import a plugin module registering extra rules (may "
        "repeat; also honored from ADAM_TPU_CHECK_PLUGINS)",
    )
    parser.add_argument(
        "--baseline", default=None, metavar="PATH",
        help="baseline file of triaged findings (default: "
        f"<root>/{core.DEFAULT_BASELINE})",
    )
    parser.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline from the current findings, "
        "preserving existing justifications; new entries still fail "
        "until a reason= is added by hand",
    )
    parser.add_argument(
        "--json", dest="json_out", default=None, metavar="PATH",
        help="write the machine-readable report (schema "
        f"{core.SCHEMA}) to PATH, '-' for stdout",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the registered rules and their contracts, then exit",
    )
    parser.add_argument(
        "--quiet", action="store_true",
        help="suppress the human-readable report (exit code / --json "
        "only)",
    )


def detect_root() -> str:
    """The repo root: the directory holding the ``adam_tpu`` package."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(here)


def run(args) -> int:
    if args.list_rules:
        env_plugins = [
            p for p in os.environ.get(
                "ADAM_TPU_CHECK_PLUGINS", ""
            ).split(":") if p
        ]
        try:
            core.load_plugins(list(args.plugin) + env_plugins)
        except ImportError as e:
            print(f"adam-tpu check: {e}", file=sys.stderr)
            return core.EXIT_ERROR
        for name, cls in sorted(core.all_rules().items()):
            print(f"{name}: {cls.summary}")
            if cls.contract:
                print(f"    contract: {cls.contract}")
        return core.EXIT_CLEAN
    root = os.path.abspath(args.root) if args.root else detect_root()
    rule_names = (
        [r.strip() for r in args.rules.split(",") if r.strip()]
        if args.rules else None
    )
    try:
        report = core.run_checks(
            root,
            rule_names=rule_names,
            plugins=args.plugin,
            baseline_path=args.baseline,
            update_baseline=args.update_baseline,
        )
    except (ValueError, ImportError, OSError) as e:
        print(f"adam-tpu check: {e}", file=sys.stderr)
        return core.EXIT_ERROR
    if args.json_out:
        doc = json.dumps(report.to_json(), indent=1, sort_keys=True)
        if args.json_out == "-":
            print(doc)
        else:
            with open(args.json_out, "w", encoding="utf-8") as fh:
                fh.write(doc + "\n")
    if not args.quiet and args.json_out != "-":
        print(report.render())
    return report.exit_code


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="adam-tpu check",
        description="AST-based contract checker (docs/STATIC_ANALYSIS.md)",
    )
    configure(parser)
    return run(parser.parse_args(argv))
