"""``python -m adam_tpu.staticcheck`` — the scripts/staticcheck face."""

import sys

from adam_tpu.staticcheck.cli import main

if __name__ == "__main__":
    sys.exit(main())
