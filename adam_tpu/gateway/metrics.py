"""Prometheus text exposition for the gateway ``GET /metrics`` surface.

Pure rendering: :func:`render_prometheus` turns one
:meth:`~adam_tpu.utils.telemetry.Tracer.snapshot` into exposition-format
text (version 0.0.4 — the format every Prometheus-compatible scraper
speaks), with no HTTP, no tracer access, and no state, so a test can
assert on the text without a server.

Naming: every registered telemetry name mangles via
:func:`~adam_tpu.utils.telemetry.prometheus_name` (``.`` -> ``_``,
``adam_tpu_`` prefix).  Validity and collision-freedom of the mangled
set are the telemetry-names lint's job
(staticcheck/rules/telemetry_names.py), enforced at check time — this
renderer assumes them.

Sections rendered, in order: counters (as ``counter``), gauge last
values (as ``gauge``), histograms (cumulative ``_bucket{le=...}`` +
``_sum`` + ``_count`` rows from the fixed log-spaced buckets), the
per-tenant quota ledger (``tenant=`` labelled), the per-device health
board (``device=`` labelled state/score/transitions), and the live
job-trace gauge.  Budget rows appear only for tenants whose budgets
the QuotaManager knows — absent is absent, never a fabricated zero.
"""

from __future__ import annotations

import re

from adam_tpu.utils import telemetry as tele

#: Content type the gateway serves the rendered body under.
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _metric_name(name: str) -> str:
    """Mangle one telemetry name for exposition.  Dotted contract
    names mangle cleanly (the lint guarantees it); the display-style
    instrumentation timer names ("BGZF Codec (native)") additionally
    sanitize every non-name character to ``_`` so the exposition stays
    parseable whatever lands in a snapshot."""
    m = tele.prometheus_name(name)
    if not tele.prometheus_name_valid(m):
        m = re.sub(r"[^a-zA-Z0-9_:]", "_", m)
        if not re.match(r"[a-zA-Z_:]", m):
            m = "_" + m
    return m


def _fmt(v) -> str:
    """One sample value: ints verbatim, floats via repr (full
    precision; Prometheus parses scientific notation)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    try:
        return repr(float(v))
    except (TypeError, ValueError):
        return "0"


def _label_value(v) -> str:
    """Escape one label value per the exposition grammar."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _labels(**kv) -> str:
    inner = ",".join(
        '%s="%s"' % (k, _label_value(v)) for k, v in kv.items()
    )
    return "{%s}" % inner if inner else ""


def render_prometheus(snap: dict, slo_status: dict | None = None) -> str:
    """One snapshot -> exposition-format text (trailing newline
    included, as the format requires).  ``slo_status`` is the armed
    SLO engine's :func:`adam_tpu.utils.slo.status` document; when
    given, per-objective burn/compliance/budget gauges render with an
    ``objective=`` label (the service-wide worst-burn and
    budget-remaining gauges already flow through the plain gauges
    section — they are registered telemetry names)."""
    out: list = []

    def head(name: str, kind: str, help_text: str) -> None:
        out.append("# HELP %s %s" % (name, help_text))
        out.append("# TYPE %s %s" % (name, kind))

    for name in sorted(snap.get("counters", {})):
        m = _metric_name(name)
        head(m, "counter", "adam_tpu counter %s" % name)
        out.append("%s %s" % (m, _fmt(snap["counters"][name])))

    for name in sorted(snap.get("gauges", {})):
        m = _metric_name(name)
        head(m, "gauge", "adam_tpu gauge %s (last sampled value)" % name)
        out.append("%s %s" % (m, _fmt(snap["gauges"][name]["last"])))

    for name in sorted(snap.get("histograms", {})):
        h = snap["histograms"][name]
        m = _metric_name(name)
        head(m, "histogram", "adam_tpu histogram %s" % name)
        # cumulative buckets over the fixed log-spaced edges: each
        # sparse bucket's UPPER edge becomes its le label, so two
        # scrapes of a growing histogram stay monotonically consistent
        # per edge (the edges are global constants, never data-derived)
        acc = 0
        items = sorted(
            (int(k), v) for k, v in (h.get("buckets") or {}).items()
        )
        for idx, n in items:
            acc += n
            le = tele.hist_bucket_bounds(idx)[1]
            out.append(
                "%s_bucket%s %d" % (m, _labels(le="%.6g" % le), acc)
            )
        out.append("%s_bucket%s %d" % (m, _labels(le="+Inf"), h["count"]))
        out.append("%s_sum %s" % (m, _fmt(h["sum"])))
        out.append("%s_count %d" % (m, h["count"]))

    quota = snap.get("quota") or {}
    if quota:
        rows = [
            ("adam_tpu_tenant_quota_charges", "counter", "charges",
             "quota charges accounted per tenant"),
            ("adam_tpu_tenant_quota_bytes", "counter", "bytes",
             "quota bytes consumed per tenant"),
            ("adam_tpu_tenant_quota_compute_seconds", "counter",
             "compute_s", "quota compute-seconds consumed per tenant"),
        ]
        for m, kind, key, help_text in rows:
            head(m, kind, help_text)
            for tenant in sorted(quota):
                out.append(
                    "%s%s %s" % (m, _labels(tenant=tenant),
                                 _fmt(quota[tenant].get(key, 0)))
                )
        for m, key, help_text in (
            ("adam_tpu_tenant_quota_budget_bytes", "budget_bytes",
             "per-tenant byte budget (absent when unknown)"),
            ("adam_tpu_tenant_quota_budget_compute_seconds",
             "budget_compute_s",
             "per-tenant compute-second budget (absent when unknown)"),
        ):
            budgeted = [
                t for t in sorted(quota)
                if quota[t].get(key) is not None
            ]
            if not budgeted:
                continue
            head(m, "gauge", help_text)
            for tenant in budgeted:
                out.append(
                    "%s%s %s" % (m, _labels(tenant=tenant),
                                 _fmt(quota[tenant][key]))
                )

    health = snap.get("health") or {}
    if health:
        head("adam_tpu_device_health_state", "gauge",
             "1 for each device's current health-board state")
        for dev in sorted(health):
            out.append(
                "adam_tpu_device_health_state%s 1"
                % _labels(device=dev, state=health[dev].get("state", ""))
            )
        head("adam_tpu_device_health_score", "gauge",
             "device health score (0 healthy, higher worse)")
        for dev in sorted(health):
            out.append(
                "adam_tpu_device_health_score%s %s"
                % (_labels(device=dev),
                   _fmt(health[dev].get("score", 0.0)))
            )
        head("adam_tpu_device_health_transitions", "counter",
             "health-board state transitions witnessed per device")
        for dev in sorted(health):
            out.append(
                "adam_tpu_device_health_transitions%s %s"
                % (_labels(device=dev),
                   _fmt(health[dev].get("transitions", 0)))
            )

    for row_name, key, help_text in (
        ("adam_tpu_slo_burn_short", "burn_short",
         "error-budget burn rate over the short window per objective"),
        ("adam_tpu_slo_burn_long", "burn_long",
         "error-budget burn rate over the long window per objective"),
        ("adam_tpu_slo_compliance", "compliance",
         "long-window compliance fraction per objective"),
        ("adam_tpu_slo_objective_budget_remaining", "budget_remaining",
         "error-budget fraction remaining per objective"),
    ):
        objectives = (slo_status or {}).get("objectives") or []
        if not objectives:
            break
        head(row_name, "gauge", help_text)
        for o in objectives:
            out.append(
                "%s%s %s" % (
                    row_name,
                    _labels(objective=o.get("key", ""),
                            tenant=o.get("tenant", "")),
                    _fmt(o.get(key, 0.0)),
                )
            )

    head("adam_tpu_traces_active", "gauge",
         "job traces currently active in this process")
    out.append("adam_tpu_traces_active %d" % len(tele.active_traces()))
    head("adam_tpu_traces_recorded", "gauge",
         "distinct job traces with recorded events in the snapshot")
    out.append(
        "adam_tpu_traces_recorded %d" % len(snap.get("traces") or {})
    )

    return "\n".join(out) + "\n"
