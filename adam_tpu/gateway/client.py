"""``GatewayClient`` — the typed stdlib client for the adam-tpu
gateway (docs/SERVING.md).

Three behaviors make it a *service* client rather than a URL fetcher:

* **Back-pressure honoring** — 429/503 raise :class:`GatewayBusy`
  carrying the server's ``Retry-After``; :meth:`submit_with_retry`
  sleeps the LARGER of that hint and the local
  :class:`~adam_tpu.utils.retry.RetryPolicy` backoff (with the PR 10
  seeded per-site jitter), so a fleet of refused clients decorrelates
  instead of re-colliding on the server's hint tick.
* **Resumable event following** — :meth:`events` streams the job's
  NDJSON heartbeat and, on any connection loss or stall, reconnects
  *from its line cursor* — the tailer's position lives client-side,
  so a bounced gateway or a flaky link costs a reconnect, not a
  restart of the stream.
* **Byte-exact resumable fetch** — :meth:`fetch_part` downloads into
  a ``.fetch-tmp`` staging file, resumes a partial download with
  ``Range: bytes=<have>-``, verifies the assembled bytes against the
  server's whole-part sha256 (restarting clean once on a mismatch —
  a stale partial must produce a re-download, never a corrupt part),
  and publishes via the durability helpers — the network twin of the
  PR 6 resume contract: SIGKILL the client mid-download, rerun, get
  identical bytes.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import socket
import time
from http.client import HTTPConnection, HTTPException
from typing import Iterator, Optional
from urllib.parse import quote, urlsplit

from adam_tpu.gateway import protocol
from adam_tpu.utils.durability import fsync_dir, publish_file
from adam_tpu.utils.retry import RetryPolicy, jitter_factor

log = logging.getLogger(__name__)

#: Terminal job states (mirrors serve.job.TERMINAL_STATES; duplicated
#: string-side so the client never imports the scheduler stack).
TERMINAL_STATES = frozenset({"done", "quarantined", "interrupted"})


class GatewayError(Exception):
    """Non-2xx gateway response (or a broken protocol invariant)."""

    def __init__(self, message: str, status: int = 0,
                 kind: str = "error",
                 retry_after: Optional[int] = None):
        super().__init__(message)
        self.status = status
        self.kind = kind
        self.retry_after = retry_after


class GatewayBusy(GatewayError):
    """Typed back-pressure: 429 (capacity) / 503 (draining or
    transiently unhealthy), with the server's Retry-After hint."""


def _raise_for(status: int, headers, body: bytes) -> None:
    kind, message, retry_after = "error", "", None
    try:
        doc = json.loads(body.decode("utf-8"))
        kind = doc.get("kind", kind)
        message = doc.get("error", "")
        retry_after = doc.get("retry_after_s")
    except (ValueError, UnicodeDecodeError):
        message = body.decode("utf-8", errors="replace")[:200]
    if retry_after is None:
        ra = headers.get("Retry-After") if headers is not None else None
        if ra is not None:
            try:
                retry_after = int(ra)
            except ValueError:
                pass
    cls = GatewayBusy if status in (429, 503) else GatewayError
    raise cls(
        f"gateway answered {status} ({kind}): {message}",
        status=status, kind=kind, retry_after=retry_after,
    )


class GatewayClient:
    """Typed client for one gateway URL (``http://host:port``)."""

    def __init__(self, url: str, timeout_s: float = 30.0):
        split = urlsplit(url if "//" in url else f"http://{url}")
        if split.scheme not in ("", "http"):
            raise ValueError(
                f"gateway URL {url!r}: only http:// is supported"
            )
        if not split.hostname or not split.port:
            raise ValueError(
                f"gateway URL {url!r} needs host and port "
                "(http://host:port)"
            )
        self.host = split.hostname
        self.port = split.port
        self.timeout_s = timeout_s

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ---- transport -----------------------------------------------------
    def _connect(self, timeout: Optional[float] = None) -> HTTPConnection:
        return HTTPConnection(
            self.host, self.port,
            timeout=self.timeout_s if timeout is None else timeout,
        )

    def _request_json(self, method: str, path: str,
                      doc: Optional[dict] = None,
                      headers: Optional[dict] = None) -> dict:
        body = (json.dumps(doc).encode("utf-8")
                if doc is not None else None)
        hdrs = dict(headers or {})
        if body is not None:
            hdrs["Content-Type"] = "application/json"
        conn = self._connect()
        try:
            conn.request(method, path, body=body, headers=hdrs)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 400:
                _raise_for(resp.status, resp.headers, data)
            try:
                return json.loads(data.decode("utf-8"))
            except (ValueError, UnicodeDecodeError) as e:
                raise GatewayError(
                    f"gateway returned non-JSON for {method} {path}: {e}"
                ) from None
        finally:
            conn.close()

    @staticmethod
    def _job_path(job: str, *rest: str) -> str:
        segs = [protocol.JOBS_PREFIX, quote(job, safe="")]
        segs += [quote(r, safe="") for r in rest]
        return "/".join(segs)

    # ---- submission ----------------------------------------------------
    def submit(self, job_id: str, doc: dict) -> dict:
        """One idempotency-keyed ``PUT /v1/jobs/<job_id>``.  Raises
        :class:`GatewayBusy` on 429/503 (carrying Retry-After) and
        :class:`GatewayError` on everything else non-2xx; a duplicate
        re-PUT of an identical spec is a SUCCESS (the response carries
        ``duplicate: true`` and the job's current state)."""
        return self._request_json("PUT", self._job_path(job_id), doc=doc)

    def submit_with_retry(self, job_id: str, doc: dict, *,
                          policy: Optional[RetryPolicy] = None,
                          deadline_s: Optional[float] = None,
                          sleep=time.sleep) -> dict:
        """Submit, honoring typed back-pressure until admitted.

        429/503 wait ``max(server Retry-After, local backoff *
        seeded jitter)`` — the server's hint is a floor, never a
        synchronization tick — bounded only by ``deadline_s``.
        Transport failures (connection refused/reset, timeouts: the
        gateway may be mid-restart) retry on the policy's attempt
        budget.  Raises the last :class:`GatewayBusy`/transport error
        when the deadline or budget runs out."""
        policy = policy or RetryPolicy.from_env()
        deadline = (
            time.monotonic() + deadline_s if deadline_s is not None
            else None
        )
        backoff = max(policy.backoff_s, 0.001)
        attempt = 1
        transport_failures = 0
        while True:
            try:
                return self.submit(job_id, doc)
            except GatewayBusy as e:
                last = e
                wait_s = max(
                    float(e.retry_after or 0),
                    backoff * jitter_factor(
                        "gateway.submit", attempt,
                        seed=policy.jitter_seed, amount=policy.jitter,
                    ),
                )
                transport_failures = 0
            except (ConnectionError, socket.timeout, HTTPException,
                    OSError) as e:
                last = e
                transport_failures += 1
                if transport_failures >= policy.attempts:
                    raise
                wait_s = backoff * jitter_factor(
                    "gateway.submit", attempt,
                    seed=policy.jitter_seed, amount=policy.jitter,
                )
                log.warning(
                    "gateway submit transport failure (%s); retrying "
                    "in %.2fs", e, wait_s,
                )
            if deadline is not None and \
                    time.monotonic() + wait_s > deadline:
                raise last
            sleep(wait_s)
            backoff = min(backoff * 2, policy.max_backoff_s)
            attempt += 1

    # ---- status / cancel -----------------------------------------------
    def status(self, job: Optional[str] = None) -> dict:
        if job is None:
            return self._request_json("GET", protocol.JOBS_PREFIX)
        return self._request_json("GET", self._job_path(job))

    def cancel(self, job: str) -> dict:
        return self._request_json("DELETE", self._job_path(job))

    def wait(self, job: str, deadline_s: Optional[float] = None,
             poll_s: float = 0.5) -> dict:
        """Poll until the job reaches a terminal state; returns its
        final status view (raises :class:`GatewayError` past the
        deadline)."""
        deadline = (
            time.monotonic() + deadline_s if deadline_s is not None
            else None
        )
        while True:
            view = self.status(job)
            if view.get("state") in TERMINAL_STATES:
                return view
            if deadline is not None and time.monotonic() >= deadline:
                raise GatewayError(
                    f"job {job!r} still {view.get('state')!r} after "
                    f"{deadline_s:.1f}s"
                )
            time.sleep(poll_s)

    # ---- observability surfaces ----------------------------------------
    def metrics(self) -> str:
        """``GET /metrics``: the Prometheus text exposition body,
        verbatim (it is NOT JSON — scrapers and the smoke test parse
        the exposition format directly)."""
        conn = self._connect()
        try:
            conn.request("GET", protocol.METRICS_PATH)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 400:
                _raise_for(resp.status, resp.headers, data)
            return data.decode("utf-8", errors="replace")
        finally:
            conn.close()

    def job_trace(self, job: str) -> dict:
        """``GET /v1/jobs/<job>/trace``: the job's Chrome-trace JSON
        (``traceEvents`` + ledger sections + ``trace_id``), spanning
        submit -> fused dispatch -> part write via fan-in links."""
        return self._request_json("GET", self._job_path(job, "trace"))

    def incidents(self) -> dict:
        """``GET /incidents``: incident-bundle summaries under the
        service's run root (oldest first)."""
        return self._request_json("GET", protocol.INCIDENTS_PATH)

    def slo(self) -> dict:
        """``GET /slo``: the service's SLO compliance document —
        ``enabled`` plus, when an engine is armed, per-objective
        compliance, burn rates, and error-budget remaining."""
        return self._request_json("GET", protocol.SLO_PATH)

    # ---- event streaming -----------------------------------------------
    def poll_events(self, job: str, cursor: int = 0) -> tuple:
        """One non-following poll: ``(next_cursor, lines)`` of every
        complete heartbeat line past ``cursor`` (``adam-tpu top
        --url``'s building block).  The stream's control lines
        (:data:`protocol.EVENTS_CTRL_SCHEMA`) re-anchor the cursor, so
        a server-side rotation reset moves ours instead of silently
        diverging (a diverged cursor would re-download the whole file
        on every poll forever)."""
        conn = self._connect()
        try:
            conn.request(
                "GET",
                self._job_path(job, "events")
                + f"?cursor={int(cursor)}&follow=0",
            )
            resp = conn.getresponse()
            if resp.status >= 400:
                _raise_for(resp.status, resp.headers, resp.read())
            lines = []
            cursor = int(cursor)
            for raw in resp.read().splitlines():
                if not raw.strip():
                    continue
                try:
                    line = json.loads(raw)
                except ValueError:
                    cursor += 1  # the server counted it; so must we
                    continue
                if isinstance(line, dict) and \
                        line.get("schema") == protocol.EVENTS_CTRL_SCHEMA:
                    cursor = int(line.get("cursor", cursor))
                    continue
                cursor += 1
                lines.append(line)
            return cursor, lines
        finally:
            conn.close()

    def events(self, job: str, cursor: int = 0, *,
               reconnect_s: float = 0.5,
               max_reconnects: int = 60,
               stall_timeout_s: float = 60.0) -> Iterator[tuple]:
        """Follow the job's heartbeat stream, yielding
        ``(cursor, line)`` with ``cursor`` = lines consumed so far —
        the resume token.  The stream ends after a ``done=true`` line.
        Connection losses and stalls reconnect FROM THE CURSOR (the
        resumable-stream contract); ``max_reconnects`` consecutive
        failures without a single new line raise the last error."""
        cursor = int(cursor)
        idle_failures = 0
        while True:
            got_line = False
            conn = self._connect(timeout=stall_timeout_s)
            try:
                conn.request(
                    "GET",
                    self._job_path(job, "events")
                    + f"?cursor={cursor}&follow=1",
                )
                resp = conn.getresponse()
                if resp.status >= 400:
                    _raise_for(resp.status, resp.headers, resp.read())
                while True:
                    raw = resp.readline()
                    if not raw:
                        break  # stream closed (gateway drain/restart)
                    raw = raw.strip()
                    if not raw:
                        continue
                    try:
                        line = json.loads(raw)
                    except ValueError:
                        cursor += 1  # count it: the server did
                        continue
                    if isinstance(line, dict) and line.get("schema") \
                            == protocol.EVENTS_CTRL_SCHEMA:
                        # stream-start echo or a mid-stream rotation
                        # reset: re-anchor so the NEXT reconnect
                        # resumes at the position the server means
                        cursor = int(line.get("cursor", cursor))
                        continue
                    cursor += 1
                    got_line = True
                    idle_failures = 0
                    yield cursor, line
                    if line.get("done"):
                        return
            except GatewayError:
                raise
            except (ConnectionError, socket.timeout, HTTPException,
                    OSError) as e:
                if not got_line:
                    idle_failures += 1
                    if idle_failures >= max_reconnects:
                        raise GatewayError(
                            f"event stream for {job!r} unreachable "
                            f"after {idle_failures} reconnects: {e}"
                        ) from e
                log.debug("event stream dropped (%s); resuming at "
                          "cursor %d", e, cursor)
            finally:
                conn.close()
            time.sleep(reconnect_s)

    # ---- resumable part fetch ------------------------------------------
    def list_parts(self, job: str) -> dict:
        return self._request_json("GET", self._job_path(job, "parts"))

    def _part_meta(self, job: str, name: str) -> tuple:
        """(sha256, size) of a part without transferring it: a
        1-byte ranged GET — every part response carries both headers."""
        conn = self._connect()
        try:
            conn.request("GET", self._job_path(job, "parts", name),
                         headers={"Range": "bytes=0-0"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status >= 400:
                _raise_for(resp.status, resp.headers, data)
            return (
                resp.headers.get(protocol.HDR_PART_SHA256, ""),
                int(resp.headers.get(protocol.HDR_PART_SIZE, "-1")),
            )
        finally:
            conn.close()

    #: Download attempts per part: transport aborts RESUME the partial
    #: (progress is monotone), so the bound only caps pathological
    #: corruption/flap loops.
    _FETCH_ATTEMPTS = 3

    def fetch_part(self, job: str, name: str, dest_dir: str) -> str:
        """Download one part byte-exactly into ``dest_dir``.

        Resumable: an existing ``<name>.fetch-tmp`` staging file (a
        previous attempt SIGKILLed mid-download, or a mid-body
        transport abort — the gateway dying mid-response included)
        resumes with ``Range: bytes=<have>-``; a partial that already
        holds the WHOLE part (killed between the last byte and the
        publish) verifies and publishes without re-transfer.  The
        assembled file must match the server's whole-part sha256 and
        size — a mismatch discards the partial and restarts clean;
        corrupt bytes are never published.  The verified file
        publishes durably (fsync + atomic rename) under its final
        name; an existing final file that already matches the
        server's sha is kept untouched."""
        os.makedirs(dest_dir, exist_ok=True)
        fsync_dir(dest_dir)
        final = os.path.join(dest_dir, name)
        tmp = final + ".fetch-tmp"
        path = self._job_path(job, "parts", name)
        note = "no attempt made"
        for _attempt in range(self._FETCH_ATTEMPTS):
            start = (
                os.path.getsize(tmp) if os.path.isfile(tmp) else 0
            )
            headers = {"Range": f"bytes={start}-"} if start else {}
            sha, total = "", -1
            conn = self._connect()
            try:
                conn.request("GET", path, headers=headers)
                resp = conn.getresponse()
                if resp.status == 416:
                    resp.read()
                    m = re.match(
                        r"bytes \*/(\d+)$",
                        resp.headers.get("Content-Range", ""),
                    )
                    if m and start == int(m.group(1)):
                        # the partial is exactly part-sized: a client
                        # killed between its last byte and the publish
                        # — verify and publish with zero re-transfer
                        sha, total = self._part_meta(job, name)
                        if start == total and sha and \
                                _sha256_file(tmp) == sha:
                            publish_file(tmp, final)
                            return final
                    # genuinely stale partial: restart clean
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    note = "stale partial discarded"
                    continue
                if resp.status >= 400:
                    _raise_for(resp.status, resp.headers, resp.read())
                sha = resp.headers.get(protocol.HDR_PART_SHA256, "")
                total = int(
                    resp.headers.get(protocol.HDR_PART_SIZE, "-1")
                )
                if os.path.isfile(final) and sha and \
                        _sha256_file(final) == sha:
                    return final  # already fetched and verified
                if resp.status == 200 and start:
                    start = 0  # server ignored the range: rewrite
                with open(tmp, "ab" if start else "wb") as fh:
                    while True:
                        chunk = resp.read(protocol.FETCH_CHUNK_BYTES)
                        if not chunk:
                            break
                        fh.write(chunk)
            except (ConnectionError, socket.timeout, HTTPException,
                    OSError) as e:
                # transport abort, possibly mid-body (a bounced or
                # fault-killed gateway): KEEP the partial — the next
                # attempt resumes it from its new length
                log.warning("part %s/%s transfer interrupted (%s); "
                            "resuming from the partial", job, name, e)
                note = f"transport: {e}"
                time.sleep(0.2)
                continue
            finally:
                conn.close()
            got = os.path.getsize(tmp) if os.path.isfile(tmp) else 0
            if total >= 0 and got == total and \
                    (not sha or _sha256_file(tmp) == sha):
                publish_file(tmp, final)
                return final
            if total < 0 or got < total:
                # silent truncation (server closed cleanly early):
                # progress is preserved, resume on the next attempt
                note = f"short read ({got} of {total} bytes)"
                continue
            # full length but wrong bytes: corrupt — never publish,
            # restart from scratch
            log.warning("part %s/%s failed sha256 verification; "
                        "restarting clean", job, name)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            note = "sha256 mismatch discarded"
        raise GatewayError(
            f"part {name!r} of job {job!r} did not verify within "
            f"{self._FETCH_ATTEMPTS} attempts (last: {note}); "
            "refusing to publish unverified bytes"
        )

    def fetch(self, job: str, dest_dir: str) -> dict:
        """Fetch every published part of ``job`` into ``dest_dir``;
        returns ``{name: local path}``, each byte-verified."""
        listing = self.list_parts(job)
        out = {}
        for part in listing.get("parts", []):
            out[part["name"]] = self.fetch_part(
                job, part["name"], dest_dir
            )
        return out


def resolve_url(text: str) -> str:
    """CLI convenience: ``text`` is either a gateway URL
    (``http://host:port`` / ``host:port``) or a serve RUN-ROOT
    directory, in which case the address comes from the
    ``gateway.json`` discovery document the server durably publishes
    on bind."""
    if os.path.isdir(text):
        path = os.path.join(text, "gateway.json")
        try:
            with open(path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as e:
            raise ValueError(
                f"{text} is a directory but {path} is unreadable ({e}); "
                "is an 'adam-tpu serve --listen' running on this root?"
            ) from None
        url = doc.get("url") if isinstance(doc, dict) else None
        if not url:
            raise ValueError(f"{path} carries no gateway url")
        return url
    return text


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()
