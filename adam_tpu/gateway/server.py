"""``GatewayServer`` — the threaded HTTP front over
:class:`~adam_tpu.api.transform_service.TransformService`.

Dependency-free by design (stdlib ``http.server`` + threads): the
gateway is a thin wire adapter, and every hard property it advertises
is one the scheduler already proves in-process — admission stays
bounded because ``JobScheduler.submit`` is, drain stays graceful
because ``RunCancelled`` is, resume stays byte-exact because parts
publish atomically.  What the gateway ADDS is the protocol surface
(docs/SERVING.md):

* **Idempotency-keyed submission** — ``PUT /v1/jobs/<job>`` with a
  JobSpec-document body.  The job id in the path is the idempotency
  key: re-PUTting an identical document returns the job's current
  state (200) whether the first attempt's response was lost to the
  network or the whole gateway restarted in between (``recover()``
  re-registers every durably recorded job); a conflicting document
  under a taken id is 409, never a silent overwrite.
* **Typed back-pressure** — scheduler ``Busy(capacity)`` maps to 429,
  ``Busy(draining)`` (and a gateway that stopped accepting ahead of a
  drain) to 503; both carry ``Retry-After`` derived from the WFQ
  grant cadence (gateway/protocol.retry_after_s), so clients back off
  at the pace the pool is actually draining windows.
* **Resumable event streaming** — ``GET /v1/jobs/<job>/events`` tails
  the job's ``adam_tpu.heartbeat/7`` NDJSON stream as a chunked
  response, resumable from a line ``cursor`` (a tailer that
  reconnects re-requests from its last count; a heartbeat-file
  rotation resets the cursor, exactly like ``adam-tpu top``'s
  shrink-means-fresh rule).  Torn trailing lines are never shipped.
* **Resumable part fetch** — ``GET /v1/jobs/<job>/parts/<part>``
  honors ``Range`` and stamps every response with the whole-part
  sha256 + size, so a client SIGKILLed mid-download resumes byte-exact
  and verifies the assembly (the network twin of the PR 6 resume
  contract).
* **Observability surfaces** (docs/OBSERVABILITY.md) — submission
  mints the job's trace context (``trace_id`` echoed in the 201 and
  persisted via JOB.json); ``GET /metrics`` serves Prometheus text
  exposition off the live tracer snapshot; ``GET /v1/jobs/<job>/trace``
  serves the job's Chrome-trace view across the fused-batch boundary;
  ``GET /incidents`` lists the run root's incident bundles.

Full citizenship in the cross-cutting subsystems: ``gateway.accept``/
``gateway.stream``/``gateway.fetch`` fault points (a ``transient``
clause at accept surfaces as a 503 the client policy absorbs; a
``kill`` at fetch is the chaos harness's mid-download gateway death),
``gateway.requests``/``gateway.busy``/``gateway.bytes_out`` counters +
the ``gateway.request.seconds`` histogram, and SIGTERM drain ordering
owned by the CLI: stop accepting -> 503 -> scheduler drain -> settled
-> exit 0.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from adam_tpu.gateway import protocol
from adam_tpu.serve.job import JobSpec, Admitted, Busy
from adam_tpu.serve.job import _JOB_ID_RE as JOB_ID_RE
from adam_tpu.utils import faults
from adam_tpu.utils import telemetry as tele
from adam_tpu.utils.durability import atomic_write_json
from adam_tpu.utils.faults import PermanentFault, TransientFault

log = logging.getLogger(__name__)

#: How often a following event stream re-polls the heartbeat file.
_STREAM_POLL_S = 0.2

GATEWAY_JSON = "gateway.json"


class _HTTPError(Exception):
    """Internal routing error -> one JSON error response."""

    def __init__(self, status: int, kind: str, message: str,
                 retry_after: Optional[int] = None,
                 headers: Optional[dict] = None):
        super().__init__(message)
        self.status = status
        self.kind = kind
        self.message = message
        self.retry_after = retry_after
        self.headers = dict(headers or {})


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.1: persistent connections + chunked responses for the
    # event stream (1.0 has no chunked encoding at all)
    protocol_version = "HTTP/1.1"
    server_version = "adam-tpu-gateway/1"

    # ---- plumbing ------------------------------------------------------
    @property
    def gw(self) -> "GatewayServer":
        return self.server.gateway  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # stderr-per-request is noise
        log.debug("gateway %s: " + fmt, self.client_address[0], *args)

    def do_GET(self):
        self._dispatch("GET")

    def do_PUT(self):
        self._dispatch("PUT")

    def do_DELETE(self):
        self._dispatch("DELETE")

    def end_headers(self):
        # once headers are on the wire, an error can no longer become
        # a JSON error response — _dispatch aborts the connection
        # instead of corrupting the framed body with a second status
        # line (the client resumes via Range / its line cursor)
        self._sent_headers = True
        super().end_headers()

    def _dispatch(self, method: str) -> None:
        t0 = time.monotonic()
        self._sent_headers = False
        split = urlsplit(self.path)
        segs = [s for s in split.path.split("/") if s]
        query = parse_qs(split.query)
        # fault attribution: the job id when the route names one, else
        # the raw path — a clause can target one tenant's wire traffic
        target = segs[2] if len(segs) > 2 else split.path
        try:
            try:
                faults.point("gateway.accept", device=target)
                self._route(method, segs, query)
            except _HTTPError:
                raise
            except protocol.RangeError:
                raise  # _serve_part re-raises with the size attached
            except TransientFault as e:
                # injected wire flake: surface as retryable 503 so the
                # client-side policy (Retry-After + backoff) absorbs it
                raise _HTTPError(
                    503, "transient", str(e),
                    retry_after=protocol.RETRY_AFTER_MIN_S,
                ) from e
            except PermanentFault as e:
                raise _HTTPError(500, "permanent", str(e)) from e
            except (BrokenPipeError, ConnectionResetError):
                raise
            except Exception as e:  # noqa: BLE001 — wire boundary
                log.exception("gateway: unhandled error on %s %s",
                              method, self.path)
                raise _HTTPError(
                    500, "internal", f"{type(e).__name__}: {e}"
                ) from e
        except _HTTPError as e:
            if self._sent_headers:
                # mid-body failure (an injected gateway.fetch/stream
                # fault, a part unreadable under us): the response is
                # already framed, so ABORT — the client sees a short
                # read and resumes via Range / its cursor, instead of
                # parsing an interleaved error document as part bytes
                log.warning("gateway: aborting in-flight response "
                            "(%s %s): %s", method, self.path, e.message)
                self.close_connection = True
            else:
                try:
                    self._send_error(e)
                except (BrokenPipeError, ConnectionResetError):
                    pass
        except (BrokenPipeError, ConnectionResetError):
            # the client went away mid-response; its retry will resume
            pass
        finally:
            tele.TRACE.count(tele.C_GW_REQUESTS)
            tele.TRACE.observe(
                tele.H_GW_REQUEST_SECONDS, time.monotonic() - t0
            )

    # ---- routing -------------------------------------------------------
    def _route(self, method: str, segs: list, query: dict) -> None:
        if segs == ["metrics"]:
            if method != "GET":
                raise _HTTPError(405, "method", f"{method} on /metrics")
            self._metrics()
            return
        if segs == ["incidents"]:
            if method != "GET":
                raise _HTTPError(405, "method",
                                 f"{method} on /incidents")
            self._incidents()
            return
        if segs == ["slo"]:
            if method != "GET":
                raise _HTTPError(405, "method", f"{method} on /slo")
            self._slo()
            return
        if segs[:2] != ["v1", "jobs"]:
            raise _HTTPError(
                404, "not_found",
                f"unknown route {self.path!r} (the surface is "
                f"{protocol.JOBS_PREFIX}[/<job>[/events|/trace|/parts"
                "[/<part>]]], /metrics, /incidents and /slo; "
                "docs/SERVING.md)",
            )
        rest = segs[2:]
        if not rest:
            if method != "GET":
                raise _HTTPError(405, "method", f"{method} on /v1/jobs")
            self._send_json(200, self.gw.service.status())
            return
        job = rest[0]
        if not JOB_ID_RE.match(job):
            raise _HTTPError(
                400, "bad_job_id",
                f"job id {job!r} must match {JOB_ID_RE.pattern}",
            )
        if len(rest) == 1:
            if method == "PUT":
                self._submit(job)
            elif method == "GET":
                self._send_json(200, self._job_view(job))
            elif method == "DELETE":
                self._cancel(job)
            else:
                raise _HTTPError(405, "method", f"{method} on a job")
            return
        if method != "GET":
            raise _HTTPError(405, "method",
                             f"{method} on {'/'.join(rest[1:])}")
        if rest[1] == "events" and len(rest) == 2:
            self._stream_events(job, query)
        elif rest[1] == "trace" and len(rest) == 2:
            self._job_trace(job)
        elif rest[1] == "parts" and len(rest) == 2:
            self._list_parts(job)
        elif rest[1] == "parts" and len(rest) == 3:
            self._serve_part(job, rest[2])
        else:
            raise _HTTPError(404, "not_found",
                             f"unknown job route {self.path!r}")

    # ---- submission (idempotency-keyed) --------------------------------
    def _submit(self, job: str) -> None:
        body = self._read_body()
        try:
            doc = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise _HTTPError(
                400, "bad_manifest", f"manifest body is not JSON: {e}"
            ) from None
        if not isinstance(doc, dict):
            raise _HTTPError(
                400, "bad_manifest",
                "manifest body must be one JSON job object",
            )
        if doc.get("job_id") not in (None, job):
            raise _HTTPError(
                400, "bad_manifest",
                f"body job_id {doc['job_id']!r} contradicts the path "
                f"job id {job!r} (the path is the idempotency key)",
            )
        doc = dict(doc, job_id=job)
        unknown = set(doc) - set(JobSpec.__dataclass_fields__)
        if unknown:
            raise _HTTPError(
                400, "bad_manifest",
                f"unknown manifest field(s) {sorted(unknown)}",
            )
        try:
            spec = JobSpec.from_doc(doc)
        except (TypeError, ValueError) as e:
            raise _HTTPError(400, "bad_manifest", str(e)) from None
        if self._idempotent_reply(job, spec):
            return
        if not self.gw.accepting:
            # drain ordering step 1 (docs/SERVING.md): the gateway
            # stops accepting BEFORE the scheduler drains, so a
            # submission racing a SIGTERM still gets the typed 503
            self._send_busy(
                Busy("gateway is draining; not accepting jobs",
                     kind="draining"),
            )
            return
        # trace context is minted HERE (docs/OBSERVABILITY.md): the
        # gateway is the job's entry point, so its submit span is the
        # trace root; the id persists via JOB.json (spec round-trip)
        # and is echoed below so the client can correlate
        if spec.trace_id is None:
            spec.trace_id = tele.mint_trace_id()
        with tele.TRACE.span(tele.SPAN_GW_SUBMIT, job=job,
                             tenant=spec.tenant, trace=spec.trace_id):
            got = self.gw.service.submit(spec)
        if isinstance(got, Admitted):
            self._send_json(201, {
                "job_id": job,
                "state": "pending",
                "trace_id": spec.trace_id,
            })
            return
        if got.kind == "duplicate":
            # lost a submit race with another client retry: answer
            # idempotently off the now-registered record
            if self._idempotent_reply(job, spec):
                return
            raise _HTTPError(
                409, "conflict",
                f"job {job!r} is registered but its record is not "
                "readable yet; retry",
                retry_after=protocol.RETRY_AFTER_MIN_S,
            )
        self._send_busy(got)

    def _idempotent_reply(self, job: str, spec: JobSpec) -> bool:
        """200 when ``job`` is already tracked with an IDENTICAL spec
        (a duplicate-safe client retry — across gateway restarts too,
        because ``recover()`` re-registers every durable JOB.json);
        409 on a different spec under the same id.  False when the job
        is unknown (a genuinely new submission) — or interrupted/
        quarantined: those terminal states are the ones a deliberate
        re-PUT RESUMES (the cancel verb promises exactly that), so
        they fall through to ``submit``, which re-admits against the
        job's journal."""
        view = self.gw.service.status()["jobs"].get(job)
        if view is None:
            return False
        stored = dict(view.get("spec") or {})
        incoming = spec.to_doc()
        if incoming.get("trace_id") is None:
            # the gateway minted the stored trace_id — a client retry
            # that never saw the first response cannot echo it, so an
            # absent incoming trace_id matches any stored one (an
            # EXPLICIT mismatched trace_id is still a conflict)
            stored.pop("trace_id", None)
            incoming.pop("trace_id", None)
        if stored == incoming:
            if view["state"] in ("interrupted", "quarantined"):
                # deliberate re-PUT resume: keep the job's ORIGINAL
                # trace — one job is one trace however many attempts
                if spec.trace_id is None:
                    spec.trace_id = (
                        (view.get("spec") or {}).get("trace_id")
                    )
                return False
            self._send_json(200, {
                "job_id": job,
                "state": view["state"],
                "duplicate": True,
                "trace_id": (view.get("spec") or {}).get("trace_id"),
            })
            return True
        raise _HTTPError(
            409, "conflict",
            f"job id {job!r} is taken by a different spec "
            "(idempotent re-PUT requires an identical manifest)",
        )

    def _send_busy(self, busy: Busy) -> None:
        status = protocol.BUSY_HTTP_STATUS.get(busy.kind, 429)
        # the quota leg carries its own budget-derived hint (when the
        # tenant's rolling window frees enough spend, serve/quota.py)
        # — it OVERRIDES the grant-cadence estimate, which describes
        # slot turnover, not budget refill
        retry = getattr(busy, "retry_after_s", None)
        if retry is None:
            retry = protocol.retry_after_s(
                self.gw.service.scheduler.grant_times(),
                now=protocol.now_monotonic(),
            )
        tele.TRACE.count(tele.C_GW_BUSY)
        self._send_json(
            status,
            protocol.error_doc(status, busy.kind, busy.reason,
                               retry_after=retry),
            headers={"Retry-After": str(retry)},
        )

    # ---- observability surfaces ----------------------------------------
    def _metrics(self) -> None:
        """``GET /metrics``: Prometheus text exposition rendered from
        the live global tracer snapshot.  The scrape counter bumps
        BEFORE the snapshot, so a scraper always sees its own scrape
        counted — two consecutive scrapes read strictly increasing
        ``adam_tpu_gateway_metrics_scrapes`` (the smoke test's
        monotonicity probe)."""
        from adam_tpu.gateway import metrics as metrics_mod
        from adam_tpu.utils import slo as slo_mod

        tele.TRACE.count(tele.C_GW_SCRAPES)
        body = metrics_mod.render_prometheus(
            tele.TRACE.snapshot(), slo_status=slo_mod.status()
        ).encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type",
                         metrics_mod.PROMETHEUS_CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        tele.TRACE.count(tele.C_GW_BYTES_OUT, len(body))

    def _incidents(self) -> None:
        """``GET /incidents``: bundle summaries under the scheduler's
        run root (the dir utils/incidents.py is armed on in serve
        mode), oldest first."""
        from adam_tpu.utils import incidents as incidents_mod

        rows = incidents_mod.list_bundles(
            self.gw.service.scheduler.run_root
        )
        self._send_json(200, {
            "schema": protocol.INCIDENTS_SCHEMA,
            "incidents": rows,
        })

    def _slo(self) -> None:
        """``GET /slo``: the armed SLO engine's compliance document —
        per-objective compliance, short/long-window burn rates, and
        error-budget remaining (utils/slo.py).  Always 200: a service
        running without ``--slo`` answers ``enabled: false`` so a
        fleet prober needs no per-service configuration to ask."""
        from adam_tpu.utils import slo as slo_mod

        status = slo_mod.status()
        doc = {
            "schema": protocol.SLO_STATUS_SCHEMA,
            "enabled": status is not None,
        }
        if status is not None:
            doc["slo"] = status
        self._send_json(200, doc)

    def _job_trace(self, job: str) -> None:
        """``GET /v1/jobs/<job>/trace``: the job's trace as Chrome
        trace-event JSON — events stamped with its trace_id plus fused
        coalescer dispatches whose fan-in ``links`` name it, so the
        view crosses the fused-batch boundary (submit -> fused
        dispatch -> part write)."""
        view = self.gw.service.status()["jobs"].get(job)
        if view is None:
            raise _HTTPError(404, "not_found", f"no job {job!r}")
        trace_id = (view.get("spec") or {}).get("trace_id")
        if not trace_id:
            raise _HTTPError(
                404, "not_found",
                f"job {job!r} carries no trace context (submitted "
                "before tracing existed?)",
            )
        doc = tele.TRACE.to_chrome_trace(trace_id)
        doc["job_id"] = job
        doc["trace_id"] = trace_id
        self._send_json(200, doc)

    # ---- status / cancel -----------------------------------------------
    def _job_view(self, job: str) -> dict:
        view = self.gw.service.status()["jobs"].get(job)
        if view is None:
            raise _HTTPError(404, "not_found", f"no job {job!r}")
        return dict(view, job_id=job)

    def _cancel(self, job: str) -> None:
        view = self.gw.service.status()["jobs"].get(job)
        if view is None:
            raise _HTTPError(404, "not_found", f"no job {job!r}")
        if self.gw.service.cancel(job):
            self._send_json(202, {"job_id": job, "cancelling": True})
            return
        raise _HTTPError(
            409, "conflict",
            f"job {job!r} is already {view['state']}; nothing to cancel",
        )

    # ---- event streaming -----------------------------------------------
    def _stream_events(self, job: str, query: dict) -> None:
        path = self.gw.service.scheduler.heartbeat_path(job)
        known = job in self.gw.service.status()["jobs"]
        if not known and not os.path.isfile(path):
            raise _HTTPError(404, "not_found", f"no job {job!r}")
        try:
            cursor = max(0, int(query.get("cursor", ["0"])[0]))
        except ValueError:
            raise _HTTPError(
                400, "bad_cursor",
                f"cursor {query['cursor'][0]!r} is not an integer",
            ) from None
        follow = query.get("follow", ["1"])[0] != "0"
        self.send_response(200)
        self.send_header("Content-Type", protocol.NDJSON_MIME)
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header(protocol.HDR_EVENT_CURSOR, str(cursor))
        self.end_headers()
        # declare the effective start position in-stream: it is the
        # only channel that can also announce a mid-stream reset
        # (rotation), so the client's cursor never silently diverges
        self._write_ctrl(cursor)
        pos = 0
        buf = ""
        seen = 0  # complete lines observed in the current file
        done = False
        while True:
            faults.point("gateway.stream", device=job)
            try:
                size = os.path.getsize(path)
            except OSError:
                size = None
            if size is not None:
                if size < pos:
                    # heartbeat rotation (ADAM_TPU_PROGRESS_MAX_BYTES):
                    # the file restarted — so does the line cursor,
                    # the same shrink-means-fresh rule `adam-tpu top`
                    # applies to its local tail
                    pos, buf, seen, cursor = 0, "", 0, 0
                    self._write_ctrl(0)
                if size > pos:
                    with open(path, "rb") as fh:
                        fh.seek(pos)
                        chunk = fh.read()
                        pos = fh.tell()
                    buf += chunk.decode("utf-8", errors="replace")
                    while True:
                        nl = buf.find("\n")
                        if nl < 0:
                            break  # torn tail: never shipped
                        line, buf = buf[:nl + 1], buf[nl + 1:]
                        seen += 1
                        if seen <= cursor:
                            continue
                        self._write_chunk(line.encode("utf-8"))
                        try:
                            if json.loads(line).get("done"):
                                done = True
                        except ValueError:
                            pass
            if not follow and size is not None and seen < cursor:
                # the heartbeat rotated between two non-follow polls:
                # the file now holds fewer lines than the client's
                # cursor.  Re-deliver from the top, announcing the
                # reset so the client re-anchors its cursor —
                # starving the poller forever would be worse
                pos, buf, seen, cursor = 0, "", 0, 0
                self._write_ctrl(0)
                continue
            if done or (not follow) or self.gw.stopping:
                break
            time.sleep(_STREAM_POLL_S)
        self._write_chunk(b"")  # terminal chunk

    # ---- part listing / fetch ------------------------------------------
    def _parts_dir(self, job: str) -> tuple:
        """(output dir, status view) — one status() pass serves both
        the routing and the response's state field."""
        view = self.gw.service.status()["jobs"].get(job)
        if view is None or not view.get("spec"):
            raise _HTTPError(404, "not_found", f"no job {job!r}")
        return os.path.abspath(view["spec"]["output"]), view

    def _list_parts(self, job: str) -> None:
        out_dir, view = self._parts_dir(job)
        parts = []
        try:
            names = sorted(os.listdir(out_dir))
        except OSError:
            names = []  # nothing published yet
        for name in names:
            if not protocol.part_name_ok(name):
                continue
            path = os.path.join(out_dir, name)
            if not os.path.isfile(path):
                continue
            parts.append({
                "name": name,
                "bytes": os.path.getsize(path),
                "sha256": self.gw.part_sha256(path),
            })
        self._send_json(200, {
            "job_id": job,
            "state": view["state"],
            "parts": parts,
        })

    def _serve_part(self, job: str, name: str) -> None:
        if not protocol.part_name_ok(name):
            raise _HTTPError(
                404, "not_found",
                f"{name!r} is not a servable part name",
            )
        out_dir, _view = self._parts_dir(job)
        path = os.path.join(out_dir, name)
        # belt and braces on top of the name regex: the resolved path
        # must stay inside the job's output directory
        if os.path.dirname(os.path.abspath(path)) != out_dir or \
                not os.path.isfile(path):
            raise _HTTPError(404, "not_found",
                             f"job {job!r} has no part {name!r}")
        size = os.path.getsize(path)
        try:
            rng = protocol.parse_range(self.headers.get("Range"), size)
        except protocol.RangeError as e:
            raise _HTTPError(
                416, "bad_range", str(e),
                headers={"Content-Range": f"bytes */{size}"},
            ) from None
        start, end = rng if rng is not None else (0, size - 1)
        n = max(0, end - start + 1)
        sha = self.gw.part_sha256(path)
        self.send_response(206 if rng is not None else 200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(n))
        self.send_header("Accept-Ranges", "bytes")
        self.send_header(protocol.HDR_PART_SHA256, sha)
        self.send_header(protocol.HDR_PART_SIZE, str(size))
        if rng is not None:
            self.send_header("Content-Range",
                             f"bytes {start}-{end}/{size}")
        self.end_headers()
        with open(path, "rb") as fh:
            fh.seek(start)
            left = n
            while left > 0:
                faults.point("gateway.fetch", device=job)
                chunk = fh.read(min(protocol.FETCH_CHUNK_BYTES, left))
                if not chunk:
                    break  # truncated underneath us; client sha check
                self.wfile.write(chunk)
                tele.TRACE.count(tele.C_GW_BYTES_OUT, len(chunk))
                left -= len(chunk)

    # ---- response/body primitives --------------------------------------
    def _write_ctrl(self, cursor: int) -> None:
        self._write_chunk(
            (json.dumps(protocol.events_ctrl_line(cursor)) + "\n")
            .encode("utf-8")
        )

    def _write_chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):X}\r\n".encode("ascii"))
        self.wfile.write(data)
        self.wfile.write(b"\r\n")
        self.wfile.flush()
        if data:
            tele.TRACE.count(tele.C_GW_BYTES_OUT, len(data))

    def _send_json(self, status: int, doc: dict,
                   headers: Optional[dict] = None) -> None:
        body = (json.dumps(doc, default=str) + "\n").encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_error(self, e: _HTTPError) -> None:
        headers = dict(e.headers)
        if e.retry_after is not None:
            headers.setdefault("Retry-After", str(e.retry_after))
        # error paths may leave the request body unread (the 413 cap
        # refuses BEFORE reading it): answering on the keep-alive
        # connection would let the unread bytes parse as the next
        # request line, so every error response closes the connection
        headers["Connection"] = "close"
        self.close_connection = True
        self._send_json(
            e.status,
            protocol.error_doc(e.status, e.kind, e.message,
                               retry_after=e.retry_after),
            headers=headers,
        )

    def _read_body(self) -> bytes:
        """Read the request body: Content-Length or chunked, capped at
        :data:`protocol.MAX_MANIFEST_BYTES` (413 past it, 400 on a
        truncated/malformed body — the fuzz surface)."""
        if self.headers.get("Transfer-Encoding", "").lower() == "chunked":
            return self._read_chunked_body()
        raw_len = self.headers.get("Content-Length")
        if raw_len is None:
            raise _HTTPError(411, "length_required",
                             "Content-Length (or chunked) required")
        try:
            length = int(raw_len)
        except ValueError:
            raise _HTTPError(
                400, "bad_manifest",
                f"Content-Length {raw_len!r} is not an integer",
            ) from None
        if length < 0:
            raise _HTTPError(400, "bad_manifest",
                             "negative Content-Length")
        if length > protocol.MAX_MANIFEST_BYTES:
            raise _HTTPError(
                413, "too_large",
                f"manifest body of {length} bytes exceeds the "
                f"{protocol.MAX_MANIFEST_BYTES}-byte cap",
            )
        body = self.rfile.read(length)
        if len(body) != length:
            raise _HTTPError(
                400, "bad_manifest",
                f"truncated body: got {len(body)} of {length} bytes",
            )
        return body

    def _read_chunked_body(self) -> bytes:
        out = b""
        while True:
            size_line = self.rfile.readline(32)
            if not size_line.endswith(b"\r\n"):
                raise _HTTPError(400, "bad_manifest",
                                 "truncated chunked body (size line)")
            try:
                size = int(size_line.strip().split(b";")[0], 16)
            except ValueError:
                raise _HTTPError(
                    400, "bad_manifest",
                    f"bad chunk size line {size_line!r}",
                ) from None
            if size == 0:
                # swallow any trailers up to the final blank line
                while True:
                    t = self.rfile.readline(1024)
                    if t in (b"\r\n", b"\n", b""):
                        break
                return out
            if len(out) + size > protocol.MAX_MANIFEST_BYTES:
                raise _HTTPError(
                    413, "too_large",
                    "chunked manifest body exceeds the "
                    f"{protocol.MAX_MANIFEST_BYTES}-byte cap",
                )
            chunk = self.rfile.read(size)
            if len(chunk) != size:
                raise _HTTPError(
                    400, "bad_manifest",
                    f"truncated chunk: got {len(chunk)} of {size} bytes",
                )
            out += chunk
            crlf = self.rfile.read(2)
            if crlf != b"\r\n":
                raise _HTTPError(400, "bad_manifest",
                                 "chunk missing its trailing CRLF")


class GatewayServer:
    """One HTTP listener over one :class:`TransformService`.

    Lifecycle: :meth:`start` binds and publishes the discovery
    document (``<run-root>/gateway.json``, durably — a restarted
    client finds the address where a crashed gateway's clients did);
    :meth:`stop_accepting` flips submissions to 503 (drain step 1);
    :meth:`close` ends event streams and joins the listener.  The
    service itself is NOT owned: the CLI drains and closes it after
    the gateway stops accepting (docs/SERVING.md drain ordering).
    """

    def __init__(self, service, host: str = "127.0.0.1", port: int = 0):
        self.service = service
        self._host = host
        self._port = port
        self._lock = threading.Lock()
        self._accepting = True
        self._stop_ev = threading.Event()
        self._sha_cache: dict = {}  # (path, size, mtime_ns) -> hex sha
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle -----------------------------------------------------
    def start(self) -> tuple:
        """Bind, publish ``gateway.json``, serve on a daemon thread;
        returns the bound ``(host, port)`` (port 0 resolves here)."""
        httpd = ThreadingHTTPServer((self._host, self._port), _Handler)
        httpd.daemon_threads = True
        httpd.gateway = self  # type: ignore[attr-defined]
        with self._lock:
            self._httpd = httpd
            self._host, self._port = httpd.server_address[:2]
        atomic_write_json(
            os.path.join(self.service.scheduler.run_root, GATEWAY_JSON),
            {
                "schema": protocol.GATEWAY_SCHEMA,
                "url": self.url,
                "host": self._host,
                "port": self._port,
                "pid": os.getpid(),
            },
        )
        t = threading.Thread(
            target=httpd.serve_forever, name="adam-tpu-gateway",
            daemon=True,
        )
        with self._lock:
            self._thread = t
        t.start()
        log.info("gateway listening on %s", self.url)
        return self._host, self._port

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self._port}"

    @property
    def accepting(self) -> bool:
        with self._lock:
            return self._accepting

    @property
    def stopping(self) -> bool:
        return self._stop_ev.is_set()

    def stop_accepting(self) -> None:
        """Drain step 1: every subsequent submission answers 503
        (draining) while live event streams and part fetches keep
        flowing — clients finish their downloads, new work bounces."""
        with self._lock:
            self._accepting = False

    def close(self) -> None:
        """Stop the listener: ends follow-mode event streams, joins
        the serve thread, releases the socket (idempotent)."""
        self._stop_ev.set()
        with self._lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=10)

    # ---- shared helpers ------------------------------------------------
    def part_sha256(self, path: str) -> str:
        """Whole-part sha256, cached by (path, size, mtime): parts are
        immutable once published (atomic rename), so the cache only
        ever re-hashes a name the writer re-published."""
        st = os.stat(path)
        key = (path, st.st_size, st.st_mtime_ns)
        with self._lock:
            hit = self._sha_cache.get(key)
        if hit is not None:
            return hit
        h = hashlib.sha256()
        with open(path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        sha = h.hexdigest()
        with self._lock:
            if len(self._sha_cache) > 4096:
                self._sha_cache.clear()
            self._sha_cache[key] = sha
        return sha
