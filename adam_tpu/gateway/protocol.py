"""Wire vocabulary shared by the gateway server and client
(docs/SERVING.md is the operator-facing reference).

Everything both sides must agree on lives here so neither can drift:
route prefixes, body/size limits, the custom header names, HTTP Range
parsing, the error-document schema, and the Retry-After derivation
from the scheduler's WFQ grant cadence.  The module is deliberately
transport-only — it imports nothing from the serve/ scheduler, so the
client stays importable on machines that never run a service.
"""

from __future__ import annotations

import re
import time
from typing import Optional

#: Discovery document the server durably writes at
#: ``<run-root>/gateway.json`` on bind (scripts and `adam-tpu submit`
#: read the URL from it when the operator used ``--listen host:0``).
GATEWAY_SCHEMA = "adam_tpu.gateway/1"

#: JSON body every non-2xx response carries.
ERROR_SCHEMA = "adam_tpu.gateway_error/1"

#: Route prefix; the full surface is documented in docs/SERVING.md:
#:   PUT    /v1/jobs/<job>                submit (idempotency-keyed;
#:                                        mints + echoes trace_id)
#:   GET    /v1/jobs                      service status
#:   GET    /v1/jobs/<job>                job status
#:   DELETE /v1/jobs/<job>                cancel at a window boundary
#:   GET    /v1/jobs/<job>/events         NDJSON heartbeat stream
#:   GET    /v1/jobs/<job>/trace          Chrome-trace JSON of the
#:                                        job's trace (fan-in links
#:                                        across fused batches)
#:   GET    /v1/jobs/<job>/parts          part listing (name/bytes/sha)
#:   GET    /v1/jobs/<job>/parts/<part>   part bytes (Range-resumable)
#:   GET    /metrics                      Prometheus text exposition
#:   GET    /incidents                    incident-bundle summaries
#:   GET    /slo                          SLO compliance + error-budget
#:                                        burn (utils/slo.py)
JOBS_PREFIX = "/v1/jobs"

#: Top-level observability routes (docs/OBSERVABILITY.md).
METRICS_PATH = "/metrics"
INCIDENTS_PATH = "/incidents"
SLO_PATH = "/slo"

#: JSON body of ``GET /incidents`` (``incidents`` holds
#: utils/incidents.summarize_bundle rows, oldest first).
INCIDENTS_SCHEMA = "adam_tpu.incidents/1"

#: JSON body of ``GET /slo``: ``enabled`` plus, when an engine is
#: armed, the utils/slo.py status document (per-objective compliance,
#: short/long burn rates, budget remaining).
SLO_STATUS_SCHEMA = "adam_tpu.slo_status/1"

#: Submission-manifest body cap: a JobSpec document is a few hundred
#: bytes; anything past this is a client bug or an attack, refused
#: with 413 before the body is read into memory.
MAX_MANIFEST_BYTES = 1 << 20

#: Part-fetch response chunk size (one ``gateway.fetch`` fault-point
#: arrival and one ``gateway.bytes_out`` increment per chunk).
FETCH_CHUNK_BYTES = 64 * 1024

#: Whole-part sha256 (lowercase hex), present on every part response —
#: full and ranged alike, always the digest of the ENTIRE part — so a
#: client that assembled a part across any number of resumed Range
#: fetches can verify the final bytes against one stable value.
HDR_PART_SHA256 = "X-Adam-Part-Sha256"

#: Total part size in bytes (rides every part response next to the
#: sha, so a ranged client knows when assembly is complete).
HDR_PART_SIZE = "X-Adam-Part-Size"

#: Line cursor an event-stream response STARTS at; the client's next
#: cursor is this plus the number of NDJSON lines it received.
HDR_EVENT_CURSOR = "X-Adam-Event-Cursor"

NDJSON_MIME = "application/x-ndjson"

#: Control line the event stream interleaves with the verbatim
#: heartbeat lines: ``{"schema": <this>, "cursor": N}`` declares that
#: the NEXT heartbeat line is line N of the current file.  One is sent
#: at stream start (echoing the effective start position) and another
#: whenever the server resets to 0 (heartbeat rotation, or a poll
#: cursor that overshoots the rotated file) — without it the client's
#: cursor would silently diverge after a rotation: polls would
#: re-download the whole file forever and follow-mode reconnects would
#: skip real lines.  Control lines are not heartbeat lines: consumers
#: keying on the heartbeat schema ignore them for free.
EVENTS_CTRL_SCHEMA = "adam_tpu.gateway_events/1"


def events_ctrl_line(cursor: int) -> dict:
    return {"schema": EVENTS_CTRL_SCHEMA, "cursor": int(cursor)}

#: Typed back-pressure mapping (docs/SERVING.md): the scheduler's
#: ``Busy.kind`` to the HTTP status the gateway answers with.  429 is
#: "come back later, the refusal is about YOU" — either ``capacity``
#: (slots full; Retry-After from the WFQ grant cadence) or ``quota``
#: (the tenant spent its rolling-window budget; Retry-After is
#: budget-derived, carried on the Busy itself) — clients branch on the
#: error document's ``kind``.  503 is "going away (drain) or
#: transiently unhealthy".  All carry Retry-After.
BUSY_HTTP_STATUS = {"capacity": 429, "quota": 429, "draining": 503}

#: Part names the gateway will serve: the ``part-r-NNNNN.parquet``
#: writer contract (io/parquet.py) plus the realigned-tail part —
#: conservatively, any ``part-``-prefixed simple filename.  No path
#: separators, no dotfiles, nothing outside the output directory.
_PART_NAME_RE = re.compile(r"^part-[A-Za-z0-9][A-Za-z0-9._-]*$")

_RANGE_RE = re.compile(r"^bytes=(\d*)-(\d*)$")


def part_name_ok(name: str) -> bool:
    return bool(_PART_NAME_RE.match(name or "")) and ".." not in name


def parse_listen(text: str) -> tuple[str, int]:
    """``HOST:PORT`` -> (host, port); port 0 asks the OS for a free
    one (the bound address is then published in ``gateway.json``)."""
    host, sep, port = (text or "").rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"--listen wants HOST:PORT (got {text!r}); use 127.0.0.1:0 "
            "for an OS-assigned port"
        )
    try:
        p = int(port)
    except ValueError:
        raise ValueError(
            f"--listen port {port!r} is not an integer"
        ) from None
    if not 0 <= p <= 65535:
        raise ValueError(f"--listen port {p} out of range 0..65535")
    return host, p


class RangeError(ValueError):
    """Unsatisfiable/malformed Range header (HTTP 416)."""


def parse_range(header: Optional[str], size: int) -> Optional[tuple]:
    """``Range: bytes=start-[end]`` -> inclusive ``(start, end)``.

    None means "no range: serve the whole part".  Suffix ranges
    (``bytes=-N``, last N bytes) are supported for completeness; a
    start at or past the part size — the resumed-download client whose
    partial file somehow outgrew the part — raises :class:`RangeError`
    so the server answers 416 with the real size and the client can
    restart clean instead of assembling garbage.  Multipart ranges are
    refused (one resuming client needs exactly one open-ended range).
    """
    if not header:
        return None
    m = _RANGE_RE.match(header.strip())
    if not m:
        raise RangeError(
            f"unsupported Range {header!r} (want bytes=start-[end])"
        )
    start_s, end_s = m.groups()
    if not start_s and not end_s:
        raise RangeError(f"empty Range {header!r}")
    if not start_s:  # suffix: last N bytes
        n = int(end_s)
        if n <= 0:
            raise RangeError(f"zero-length suffix Range {header!r}")
        return max(0, size - n), size - 1
    start = int(start_s)
    end = int(end_s) if end_s else size - 1
    if start >= size or end < start:
        raise RangeError(
            f"Range {header!r} unsatisfiable for a {size}-byte part"
        )
    return start, min(end, size - 1)


#: Retry-After bounds (seconds): never tell a client "now" (it just
#: lost a capacity race; hammering doesn't free slots) and never park
#: it past half a minute (slots turn over at job granularity; the
#: client re-probes cheaply).
RETRY_AFTER_MIN_S = 1
RETRY_AFTER_MAX_S = 30
_RETRY_AFTER_DEFAULT_S = 2

#: How many window grants a freed slot is assumed to trail the current
#: cadence by: a refused submission waits roughly one in-flight job's
#: worth of recent window throughput, not one window.
_GRANT_BATCH = 8


def retry_after_s(grant_times: list, now: Optional[float] = None) -> int:
    """Derive the Retry-After hint from the WFQ grant history.

    The fairness interleaver stamps every window grant
    (serve/fairness.WeightedInterleaver.grant_times); the median
    inter-grant gap over the recent ring is the service's live window
    cadence.  A capacity-refused client is told to come back after
    ``_GRANT_BATCH`` windows' worth of that cadence — if windows are
    draining fast, retries come fast; if the pool is grinding, clients
    back off instead of dogpiling — clamped to
    [:data:`RETRY_AFTER_MIN_S`, :data:`RETRY_AFTER_MAX_S`].  With
    fewer than 2 grants (cold service, stalled pool) the conservative
    default applies.  ``now`` widens the newest gap so a service that
    stopped granting (wedged pool) decays toward the max instead of
    advertising its last healthy cadence forever.
    """
    times = sorted(grant_times or [])[-64:]
    if len(times) < 2:
        return _RETRY_AFTER_DEFAULT_S
    gaps = sorted(b - a for a, b in zip(times, times[1:]))
    cadence = gaps[len(gaps) // 2]
    if now is not None:
        # a pool that stopped granting is slower than its history
        # says: the time since the newest grant overrides the median
        # once it exceeds it, decaying the hint toward the cap
        cadence = max(cadence, now - times[-1])
    est = cadence * _GRANT_BATCH
    return int(min(RETRY_AFTER_MAX_S, max(RETRY_AFTER_MIN_S, round(est))))


def error_doc(status: int, kind: str, message: str,
              retry_after: Optional[int] = None) -> dict:
    """The JSON body of every non-2xx response (stable shape: clients
    branch on ``kind``, humans read ``error``)."""
    doc = {
        "schema": ERROR_SCHEMA,
        "status": int(status),
        "kind": kind,
        "error": message,
    }
    if retry_after is not None:
        doc["retry_after_s"] = int(retry_after)
    return doc


def now_monotonic() -> float:
    """Seam for tests to pin the Retry-After clock."""
    return time.monotonic()
