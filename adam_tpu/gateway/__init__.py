"""HTTP gateway: the network front over the multi-job transform
service (docs/SERVING.md).

PR 10 built the hard service parts — shared-pool scheduling, WFQ
fairness, quarantine, drain, crash recovery — behind the in-process
:class:`~adam_tpu.api.transform_service.TransformService` seam; this
package puts a wire protocol over exactly that seam, dependency-free
(stdlib ``http.server`` + threads, the repo's no-new-deps discipline):

* :mod:`adam_tpu.gateway.protocol` — the shared wire vocabulary:
  routes, limits, header names, Range parsing, the Retry-After
  derivation from the WFQ grant cadence, error-document shape.
* :mod:`adam_tpu.gateway.server` — :class:`GatewayServer`, a threaded
  HTTP front: idempotency-keyed ``PUT /v1/jobs/<job>`` submission,
  typed back-pressure (``Busy(capacity)`` -> 429, ``Busy(draining)``
  -> 503, both with Retry-After), chunked NDJSON heartbeat streaming
  resumable from a line cursor, and Range-resumable part fetch with
  whole-part sha256 integrity.
* :mod:`adam_tpu.gateway.client` — :class:`GatewayClient`, the typed
  stdlib client: submission with Retry-After-honoring backoff
  (utils/retry.RetryPolicy + seeded jitter), event-stream following
  that reconnects at its cursor, and byte-exact resumable downloads
  (the network twin of the PR 6 resume contract).

The CLI verbs (``adam-tpu serve --listen`` / ``submit`` / ``status`` /
``fetch`` / ``cancel`` and ``adam-tpu top --url``) are thin fronts
over these two classes.
"""

from adam_tpu.gateway.client import GatewayBusy, GatewayClient, GatewayError
from adam_tpu.gateway.protocol import parse_listen, retry_after_s
from adam_tpu.gateway.server import GatewayServer

__all__ = [
    "GatewayBusy",
    "GatewayClient",
    "GatewayError",
    "GatewayServer",
    "parse_listen",
    "retry_after_s",
]
