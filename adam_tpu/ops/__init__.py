from adam_tpu.ops import cigar, flagstat, intervals, kmer, mdtag, phred, smith_waterman

__all__ = ["cigar", "flagstat", "intervals", "kmer", "mdtag", "phred", "smith_waterman"]
