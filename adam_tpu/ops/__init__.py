from adam_tpu.ops import cigar, flagstat, kmer, mdtag, phred, smith_waterman

__all__ = ["cigar", "flagstat", "kmer", "mdtag", "phred", "smith_waterman"]
