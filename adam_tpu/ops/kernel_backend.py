"""Kernel backend selection — the ``ADAM_TPU_KERNEL_BACKEND`` knob.

PR 18 adds Pallas ports of the two memory-bound inner loops (the
observe scatter-add and the row-prefix pack scatter).  Both live behind
this selector: ``xla`` (the default) keeps the original ``.at[].add``/
``.at[].set`` bodies — the bit-parity reference — while ``pallas``
swaps in the hand-written TPU kernels at *trace* time.  The switch is
read inside the traceable bodies, so every jit cache that can hold a
traced body must key on :func:`kernel_backend` (``bqsr.jit_variant``,
the mesh jit registry, the compile ledger and the prewarm dedupe all
do — see the PR 18 compile-ledger key fix).

Resolution precedence follows the repo's tuning-var contract
(``utils/retry``-style warn-and-default):

* an explicit ``override`` argument wins and must be valid — a typo in
  *code* is a bug, so it raises;
* else ``ADAM_TPU_KERNEL_BACKEND`` (``xla``/``pallas``; ``auto`` and
  unset mean ``xla``) — an unrecognized *environment* value warns once
  and falls back to ``xla`` rather than killing a long run;
* a :func:`backend_scope` context override (used by the microbench
  harness and the parity tests) sits between the two: stronger than
  the environment, weaker than an explicit argument.

Off-TPU (CPU tests, interpret mode) the Pallas kernels run with
``interpret=True`` so the parity matrix stays hermetic — see
:func:`pallas_interpret`.
"""

from __future__ import annotations

import contextlib
import os
import threading
import warnings

KERNEL_BACKENDS = ("xla", "pallas")

_ENV_VAR = "ADAM_TPU_KERNEL_BACKEND"

_lock = threading.Lock()
_warned: set = set()

# backend_scope() override — process-wide, not thread-local, because
# the device pool's dispatch executors must see the same backend as
# the submitting thread (a per-thread override would let one window
# trace pallas while its prewarm traced xla).
_OVERRIDE: list = []


def kernel_backend(override: str | None = None) -> str:
    """Resolve the active kernel backend (``"xla"`` or ``"pallas"``)."""
    if override is not None:
        if override not in KERNEL_BACKENDS:
            raise ValueError(
                f"unknown kernel backend {override!r}; expected one of "
                f"{KERNEL_BACKENDS}"
            )
        return override
    if _OVERRIDE:
        return _OVERRIDE[-1]
    raw = os.environ.get(_ENV_VAR, "").strip().lower()
    if raw in ("", "auto", "xla"):
        return "xla"
    if raw in KERNEL_BACKENDS:
        return raw
    with _lock:
        if raw not in _warned:
            _warned.add(raw)
            warnings.warn(
                f"{_ENV_VAR}={raw!r} is not one of {KERNEL_BACKENDS}; "
                "using 'xla'",
                RuntimeWarning,
                stacklevel=2,
            )
    return "xla"


@contextlib.contextmanager
def backend_scope(backend: str):
    """Temporarily force the kernel backend (parity tests, kernelbench).

    Process-wide; nesting stacks.  The traceable bodies read
    :func:`kernel_backend` at trace time and every jit cache keys on
    it, so flipping the scope retraces rather than reusing a stale
    executable."""
    if backend not in KERNEL_BACKENDS:
        raise ValueError(
            f"unknown kernel backend {backend!r}; expected one of "
            f"{KERNEL_BACKENDS}"
        )
    _OVERRIDE.append(backend)
    try:
        yield backend
    finally:
        _OVERRIDE.pop()


def pallas_interpret() -> bool:
    """True when Pallas must run in interpret mode (no TPU attached).

    CPU test runs (``JAX_PLATFORMS=cpu``) have no Mosaic compiler, so
    the Pallas kernels execute through the interpreter — bit-parity
    with the compiled path, just slow.  The kernelbench rows carry
    ``mode: interpret`` so nobody reads interpreter timings as chip
    numbers."""
    try:
        import jax

        return jax.default_backend() != "tpu"
    except Exception:  # pragma: no cover - jax always importable here
        return True
