"""Fixed-depth DNA "prefix trie" over packed k-mer keys.

API parity with ``algorithms/prefixtrie/DNAPrefixTrie.scala:22-210``:
uniform-length ACGT keys mapping to values, with ``contains/get/
get_or_else/get_if_exists``, wildcard ``search`` ('N'/'*' match any
base), ``prefix_search`` and ``suffix_search``; keys containing
ambiguous bases are dropped at build, mixed key lengths and empty input
are errors.

Array-hardware recast: instead of a 4-ary pointer trie, keys live as a
**sorted 2-bit-packed integer array** plus a parallel value list —
lookups are binary searches, a prefix is a contiguous key range
(searchsorted pair), and wildcard/suffix queries are vectorized
mask-compare sweeps. Same asymptotics as trie walks for DNA alphabets,
but the whole structure is two flat arrays that can ship to device or
broadcast across a mesh.
"""

from __future__ import annotations

import numpy as np

_CODE = {"A": 0, "C": 1, "G": 2, "T": 3}
_BASE = "ACGT"


def _pack(key: str) -> int | None:
    """2 bits per base, first base most significant. None if ambiguous."""
    v = 0
    for ch in key:
        code = _CODE.get(ch)
        if code is None:
            if ch in ("N", "*"):
                return None
            raise ValueError(f"illegal character {ch!r} in key {key!r}")
        v = (v << 2) | code
    return v


class DNAPrefixTrie:
    def __init__(self, init: dict):
        assert len(init) > 0, "Cannot build empty prefix trie."
        lengths = {len(k) for k in init}
        assert len(lengths) == 1, "all keys must have equal length"
        self.depth = lengths.pop()
        if self.depth > 31:
            # 2 bits/base in a signed 64-bit key; 31 bases = 62 bits
            raise ValueError(
                f"key length {self.depth} exceeds the 31-base packed-key "
                f"limit"
            )
        keys, values = [], []
        for k, v in init.items():
            packed = _pack(k)  # raises on illegal chars
            if packed is None:
                continue  # ambiguous bases are silently dropped
            keys.append(packed)
            values.append(v)
        order = np.argsort(np.asarray(keys, np.int64), kind="stable")
        self._keys = np.asarray(keys, np.int64)[order] if keys else np.zeros(0, np.int64)
        self._values = [values[i] for i in order]

    # ------------------------------------------------------------ basics
    @property
    def size(self) -> int:
        return len(self._keys)

    def __len__(self) -> int:
        return self.size

    def _index_of(self, key: str) -> int:
        if len(key) != self.depth:
            return -1
        packed = _pack(key)
        if packed is None:
            return -1
        i = int(np.searchsorted(self._keys, packed))
        if i < len(self._keys) and self._keys[i] == packed:
            return i
        return -1

    def contains(self, key: str) -> bool:
        if any(c in ("N", "*") for c in key):
            return len(self.search(key)) > 0
        return self._index_of(key) >= 0

    def get(self, key: str):
        i = self._index_of(key)
        if i < 0:
            raise KeyError(key)
        return self._values[i]

    def get_or_else(self, key: str, default):
        i = self._index_of(key)
        return self._values[i] if i >= 0 else default

    def get_if_exists(self, key: str):
        i = self._index_of(key)
        return self._values[i] if i >= 0 else None

    def _unpack(self, packed: int) -> str:
        return "".join(
            _BASE[(packed >> (2 * (self.depth - 1 - i))) & 0x3]
            for i in range(self.depth)
        )

    # ----------------------------------------------------------- queries
    def search(self, key: str) -> dict:
        """Wildcard query: 'N'/'*' positions match any base
        (DNAPrefixTrie.search)."""
        if len(key) != self.depth:
            return {}
        mask = 0
        want = 0
        for ch in key:
            mask <<= 2
            want <<= 2
            if ch in ("N", "*"):
                continue
            code = _CODE.get(ch)
            if code is None:
                raise ValueError(f"illegal character {ch!r} in key {key!r}")
            mask |= 0x3
            want |= code
        hits = np.flatnonzero((self._keys & mask) == want)
        return {self._unpack(int(self._keys[i])): self._values[i] for i in hits}

    def find(self, key: str) -> dict:
        return self.search(key)

    def prefix_search(self, prefix: str) -> dict:
        """All keys beginning with ``prefix`` — one contiguous packed-key
        range (DNAPrefixTrie.prefixSearch)."""
        if len(prefix) > self.depth:
            return {}
        packed = _pack(prefix)
        if packed is None:
            # wildcards inside the prefix: pad with wildcards and search
            return self.search(prefix + "*" * (self.depth - len(prefix)))
        rest = self.depth - len(prefix)
        lo = packed << (2 * rest)
        hi = (packed + 1) << (2 * rest)
        i0 = int(np.searchsorted(self._keys, lo, "left"))
        i1 = int(np.searchsorted(self._keys, hi, "left"))
        return {
            self._unpack(int(self._keys[i])): self._values[i]
            for i in range(i0, i1)
        }

    def suffix_search(self, suffix: str) -> dict:
        """All keys ending with ``suffix`` — masked compare on the low
        bits (DNAPrefixTrie.suffixSearch)."""
        if len(suffix) > self.depth:
            return {}
        packed = _pack(suffix)
        if packed is None:
            return self.search("*" * (self.depth - len(suffix)) + suffix)
        mask = (1 << (2 * len(suffix))) - 1
        hits = np.flatnonzero((self._keys & mask) == packed)
        return {self._unpack(int(self._keys[i])): self._values[i] for i in hits}
