"""Vectorized interval primitives — the engine under region joins/coverage.

The reference implements interval logic with per-element scans and binary
searches inside Spark closures (``rdd/BroadcastRegionJoin.scala:169-301``,
``rdd/ShuffleRegionJoin.scala:223-290``, ``rdd/Coverage.scala:55-190``).
Here intervals are columnar arrays ``(contig: i32[N], start: i64[N],
end: i64[N])`` and every operation is a sort + scan + searchsorted over
whole arrays — the same shape of computation runs on host numpy for
driver-side index building and under ``jit`` for device-side kernels
(``jnp.searchsorted`` / ``associative_scan``).

Cross-contig totality uses the packed key of
:mod:`adam_tpu.models.positions` so one flat sorted array covers the whole
genome (contig index dominates the position bits).
"""

from __future__ import annotations

import numpy as np

from adam_tpu.models.positions import pack_position_key


def sort_intervals(contig, start, end):
    """Permutation sorting intervals by (contig, start, end)."""
    contig = np.asarray(contig)
    start = np.asarray(start)
    end = np.asarray(end)
    return np.lexsort((end, start, contig))


def merge_intervals(contig, start, end, adjacent: bool = True):
    """Union of intervals: the ``NonoverlappingRegions.mergeRegions`` /
    ``Coverage.collapseAdjacent`` core (BroadcastRegionJoin.scala:191-211,
    Coverage.scala:133-166) as one sort + running-max scan.

    With ``adjacent=True``, regions that touch end-to-start are collapsed
    too ("overlaps || isAdjacent", the alternation invariant the broadcast
    join relies on).

    Returns ``(m_contig, m_start, m_end, group_of_input)`` where
    ``group_of_input[i]`` is the merged-group id of input interval ``i``
    (in *input* order). Merged groups are disjoint, non-adjacent, and
    sorted by (contig, start).
    """
    contig = np.asarray(contig, np.int64)
    start = np.asarray(start, np.int64)
    end = np.asarray(end, np.int64)
    n = len(start)
    if n == 0:
        z = np.zeros(0, np.int64)
        return z, z, z, z
    perm = sort_intervals(contig, start, end)
    c, s, e = contig[perm], start[perm], end[perm]
    # running max of (contig, end) packed keys: packing makes the scan
    # reset naturally at contig changes (contig bits dominate), so one
    # flat cummax covers the whole genome
    e_keys = pack_position_key(c, e)
    s_keys = pack_position_key(c, s)
    cummax_e = np.maximum.accumulate(e_keys)
    prev_reach = np.concatenate([[np.iinfo(np.int64).min], cummax_e[:-1]])
    # new group starts where a gap opens; adjacency (start == reach)
    # bridges groups when adjacent=True
    boundary = s_keys > prev_reach if adjacent else s_keys >= prev_reach
    group_sorted = np.cumsum(boundary) - 1
    n_groups = group_sorted[-1] + 1
    m_contig = c[boundary]
    m_start = s[boundary]
    m_end = np.zeros(n_groups, np.int64)
    np.maximum.at(m_end, group_sorted, e)
    group_of_input = np.empty(n, np.int64)
    group_of_input[perm] = group_sorted
    return m_contig, m_start, m_end, group_of_input


def overlap_group_ranges(m_contig, m_start, m_end, q_contig, q_start, q_end):
    """For each query interval, the contiguous range ``[lo, hi)`` of merged
    (disjoint, sorted) groups it overlaps.

    This is the vectorized replacement for the reference's
    ``binaryPointSearch`` walk (BroadcastRegionJoin.scala:213-227): because
    merged groups are disjoint and sorted, overlap candidacy is a
    contiguous id range recoverable with two ``searchsorted`` calls over
    packed (contig, pos) keys.
    """
    end_keys = pack_position_key(m_contig, m_end)
    start_keys = pack_position_key(m_contig, m_start)
    q_start_keys = pack_position_key(np.asarray(q_contig), np.asarray(q_start))
    q_end_keys = pack_position_key(np.asarray(q_contig), np.asarray(q_end))
    # first group with (contig, end) > (contig, q_start)
    lo = np.searchsorted(end_keys, q_start_keys, side="right")
    # first group with (contig, start) >= (contig, q_end)
    hi = np.searchsorted(start_keys, q_end_keys, side="left")
    return lo, np.maximum(hi, lo)


def expand_ranges(lo, hi):
    """Flatten per-query ``[lo, hi)`` ranges into (query_idx, group_id)
    pairs — the vectorized version of the reference's per-record flatMap
    over overlapped bins (ShuffleRegionJoin.scala:86-98)."""
    lo = np.asarray(lo, np.int64)
    hi = np.asarray(hi, np.int64)
    counts = hi - lo
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    query_idx = np.repeat(np.arange(len(lo)), counts)
    # within-query offset: arange minus each query's starting cumsum
    offsets = np.arange(total) - np.repeat(
        np.concatenate([[0], np.cumsum(counts)[:-1]]), counts
    )
    group_id = lo[query_idx] + offsets
    return query_idx, group_id


def point_depth(contig, start, end, q_contig, q_pos):
    """Number of intervals covering each query point:
    count(start <= p) - count(end <= p) over packed keys, fully
    vectorized (the counting core of the ``depth`` command,
    adam-cli CalculateDepth.scala:41)."""
    skeys = np.sort(pack_position_key(np.asarray(contig), np.asarray(start)))
    ekeys = np.sort(pack_position_key(np.asarray(contig), np.asarray(end)))
    q = pack_position_key(np.asarray(q_contig), np.asarray(q_pos))
    return np.searchsorted(skeys, q, side="right") - np.searchsorted(
        ekeys, q, side="right"
    )


def overlap_join(l_contig, l_start, l_end, r_contig, r_start, r_end):
    """All (i, j) with left interval i overlapping right interval j.

    Algorithm: merge the left side into disjoint groups; each left belongs
    to exactly one group, each right overlaps a contiguous group range;
    expand right ranges, group lefts by group id, emit the per-group cross
    product, filter by actual overlap. Every step is a whole-array op —
    no per-record closure, mirroring how the work maps onto a TPU shard.
    """
    l_contig = np.asarray(l_contig, np.int64)
    l_start = np.asarray(l_start, np.int64)
    l_end = np.asarray(l_end, np.int64)
    r_contig = np.asarray(r_contig, np.int64)
    r_start = np.asarray(r_start, np.int64)
    r_end = np.asarray(r_end, np.int64)
    if len(l_start) == 0 or len(r_start) == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    m_c, m_s, m_e, l_group = merge_intervals(l_contig, l_start, l_end)
    lo, hi = overlap_group_ranges(m_c, m_s, m_e, r_contig, r_start, r_end)
    rj, rg = expand_ranges(lo, hi)  # right j participates in group rg
    if len(rj) == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)

    # group lefts: order lefts by group, record group offsets
    l_order = np.argsort(l_group, kind="stable")
    l_group_sorted = l_group[l_order]
    n_groups = len(m_s)
    group_starts = np.searchsorted(l_group_sorted, np.arange(n_groups))
    group_ends = np.searchsorted(l_group_sorted, np.arange(n_groups), "right")

    # per (right, group) pair: cross with all lefts in that group
    pair_lo = group_starts[rg]
    pair_hi = group_ends[rg]
    rep_r, slot = expand_ranges(pair_lo, pair_hi)
    li = l_order[slot]
    ri = rj[rep_r]
    keep = (l_end[li] > r_start[ri]) & (r_end[ri] > l_start[li])
    return li[keep], ri[keep]
