"""Batched Smith-Waterman local alignment.

Semantics match ``algorithms/smithwaterman/`` in the reference:
constant-gap scoring with the exact move-priority and tie-breaking of
``SmithWatermanGapScoringFromFn.buildScoringMatrix``
(B if m>=d && m>=in && m>0, else J if d>=in && d>0, else I if in>0,
else terminate) and ``SmithWaterman.maxCoordinates`` (on score ties the
*later* row/column wins, because the reference's fold keeps the right
operand on equality), and the same trackback emission
(B -> M/M, J -> I in x / D in y, I -> D in x / I in y).

TPU formulation: the O(|x|·|y|) matrix fill runs as an anti-diagonal
wavefront — each step updates a whole diagonal vector-wide, the pair
dimension is batched, and the matrices are *kept in diagonal layout*
``[B, D, lx+1]`` (``matrix[i, j] == diag[i + j, i]``) so no device-side
gather/transpose is ever paid.  Two interchangeable fills:

* :func:`_sw_fill_pallas` — Pallas TPU kernel: x/y codes and the two
  rolling diagonals live in VMEM, the y lane is read through a dynamic
  lane slice of the reversed-padded sequence, one fused VPU step per
  diagonal (the GCUPS path of BASELINE.md).
* :func:`_sw_fill_scan` — ``lax.scan`` fallback for CPU/interpret.

Trackback is O(|x|+|y|) per pair on the host, reading the diagonal
move matrix directly.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# move codes in the device move matrix
MOVE_T = 0  # terminate
MOVE_B = 1  # both (diagonal)
MOVE_J = 2  # consume x only
MOVE_I = 3  # consume y only

_LANE = 128


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# ------------------------------------------------------------- scan fill


@partial(jax.jit, static_argnames=("lx", "ly"))
def _sw_fill_scan(
    x_codes, x_len, y_codes, y_len, w_match, w_mismatch, w_insert, w_delete,
    lx: int, ly: int,
):
    """Diagonal-layout fill via lax.scan.

    Returns (scores [B, D, lx+1] f32, moves [B, D, lx+1] u8) with
    ``matrix[b, i, j] = out[b, i + j, i]``.
    """
    B = x_codes.shape[0]
    D = lx + ly + 1
    ii = jnp.arange(lx + 1)
    # f32 compute to match the Pallas kernel bit-for-bit (and the TPU VPU)
    w_match = jnp.float32(w_match)
    w_mismatch = jnp.float32(w_mismatch)
    w_insert = jnp.float32(w_insert)
    w_delete = jnp.float32(w_delete)

    def step(carry, d):
        d1, d2 = carry  # diagonals d-1 and d-2, each [B, lx+1] indexed by i
        jj = d - ii
        valid = (
            (ii >= 1)
            & (jj >= 1)
            & (ii[None, :] <= x_len[:, None])
            & (jj[None, :] <= y_len[:, None])
        )
        xc = x_codes[:, jnp.clip(ii - 1, 0, lx - 1)]
        yc = y_codes[:, jnp.clip(jj - 1, 0, ly - 1)]  # jj is batch-invariant
        sub = jnp.where(xc == yc, w_match, w_mismatch)

        def shift_i(v):  # v[i-1] with 0 at i=0
            return jnp.pad(v[:, :-1], ((0, 0), (1, 0)))

        m = shift_i(d2) + sub
        dd = shift_i(d1) + w_delete
        inn = d1 + w_insert

        take_b = (m >= dd) & (m >= inn) & (m > 0.0)
        take_j = ~take_b & (dd >= inn) & (dd > 0.0)
        take_i = ~take_b & ~take_j & (inn > 0.0)
        score = jnp.where(
            take_b, m, jnp.where(take_j, dd, jnp.where(take_i, inn, 0.0))
        )
        move = jnp.where(
            take_b,
            MOVE_B,
            jnp.where(take_j, MOVE_J, jnp.where(take_i, MOVE_I, MOVE_T)),
        ).astype(jnp.uint8)
        score = jnp.where(valid, score, 0.0)
        move = jnp.where(valid, move, MOVE_T)
        return (score, d1), (score, move)

    (_, _), (diag_scores, diag_moves) = jax.lax.scan(
        step,
        (
            jnp.zeros((B, lx + 1), jnp.float32),
            jnp.zeros((B, lx + 1), jnp.float32),
        ),
        jnp.arange(D),
    )
    # [D, B, L] -> [B, D, L]
    return (
        jnp.moveaxis(diag_scores, 0, 1).astype(jnp.float32),
        jnp.moveaxis(diag_moves, 0, 1),
    )


# ----------------------------------------------------------- pallas fill


def _per_lane_best(scores, x_len, y_len):
    """Per-lane (matrix row) running max over diagonals, ties -> last d.

    -> (best_sc f32[B, L] with -inf outside the valid region,
        best_d i32[B, L] diagonal index of the winning cell).
    Reducing over lanes with ties -> last lane reproduces the reference's
    maxCoordinates lexicographic-(i, j)-max rule (the right-biased fold
    in SmithWaterman.maxCoordinates, SmithWaterman.scala:50-83).
    """
    B, D, L = scores.shape
    ii = jnp.arange(L)[None, None, :]
    dd = jnp.arange(D)[None, :, None]
    jj = dd - ii
    valid = (
        (ii <= x_len[:, None, None])
        & (jj >= 0)
        & (jj <= y_len[:, None, None])
    )
    masked = jnp.where(valid, scores, -jnp.inf)
    amax_rev = jnp.argmax(masked[:, ::-1, :], axis=1)  # first max = last d
    best_d = (D - 1 - amax_rev).astype(jnp.int32)
    best_sc = jnp.max(masked, axis=1).astype(jnp.float32)
    return best_sc, best_d


@partial(jax.jit, static_argnames=("lx", "ly"))
def _sw_fill_scan_best(
    x_codes, x_len, y_codes, y_len, w_match, w_mismatch, w_insert, w_delete,
    lx: int, ly: int,
):
    """Scan fill + per-lane best, fused under one jit so the full f32
    score matrix never leaves the device."""
    scores, moves = _sw_fill_scan.__wrapped__(
        x_codes, x_len, y_codes, y_len,
        w_match, w_mismatch, w_insert, w_delete, lx, ly,
    )
    best_sc, best_d = _per_lane_best(scores, x_len, y_len)
    return moves, best_sc, best_d


def _sw_kernel(x_ref, ydiag_ref, xlen_ref, ylen_ref, move_ref,
               best_sc_ref, best_d_ref,
               d1_ref, d2_ref, *, lx: int, ly: int, L: int,
               w_match: float, w_mismatch: float, w_insert: float,
               w_delete: float):
    """One grid-less call fills all D diagonals of one TB-row batch tile.

    Mosaic constraints shape this kernel (all verified against the real
    TPU compile service):

    * No Pallas *grid* is used: this toolchain fails to legalize grids
      whose block index maps revisit a block (any spec that ignores a
      grid dimension), which a diagonal-in-grid layout would need for x
      and y.  Instead the diagonal loop is a ``fori_loop`` and the
      (D, TB, L) arrays are indexed on the *untiled* leading dimension,
      which lowers fine.
    * No unaligned dynamic lane slice — and a per-step ``pltpu.roll``
      measured ~0.3 ms/step — so the y lane windows for every diagonal
      are pre-gathered in XLA into ``ydiag[d, :, i] = y[d - 1 - i]``
      (i8) and the kernel just reads ``ydiag_ref[d]``.
    """
    TB = x_ref.shape[0]
    D = lx + ly + 1
    # all in-kernel scalars are pinned to i32/f32: under jax_enable_x64 a
    # bare Python literal becomes an i64/f64 constant, and Mosaic's
    # convert-element-type lowering recurses forever on 64-bit casts
    ii = jax.lax.broadcasted_iota(jnp.int32, (TB, L), 1)
    one = jnp.int32(1)
    zf = jnp.float32(0.0)
    wm = jnp.float32(w_match)
    wx = jnp.float32(w_mismatch)
    wi = jnp.float32(w_insert)
    wd = jnp.float32(w_delete)
    mv_b, mv_j, mv_i, mv_t = (
        jnp.int32(MOVE_B), jnp.int32(MOVE_J), jnp.int32(MOVE_I), jnp.int32(MOVE_T),
    )
    zero = jnp.int32(0)
    ninf = jnp.float32(-jnp.inf)
    xlen = xlen_ref[:]  # [TB, 1]
    ylen = ylen_ref[:]
    # xc: lane i holds x[i-1] (static shift; lane 0 and lanes past lx are
    # junk — masked by `valid`, and the -2 pad can never equal ydiag's -1).
    # Codes live as i32: i8 vectors carry (32, 128) tiling whose compare
    # masks Mosaic cannot relayout against the f32 selects.
    xc = jnp.pad(x_ref[:], ((0, 0), (1, L - 1 - lx)),
                 constant_values=jnp.int32(-2))
    d1_ref[:] = jnp.zeros((TB, L), jnp.float32)
    d2_ref[:] = jnp.zeros((TB, L), jnp.float32)
    best_sc_ref[:] = jnp.full((TB, L), ninf, jnp.float32)
    best_d_ref[:] = jnp.zeros((TB, L), jnp.int32)

    def body(d, c):
        jj = d - ii
        valid = (ii >= one) & (jj >= one) & (ii <= xlen) & (jj <= ylen)
        yc = ydiag_ref[d, :, :]
        sub = jnp.where(xc == yc, wm, wx)
        d1 = d1_ref[:]
        d2 = d2_ref[:]
        m = jnp.pad(d2[:, : L - 1], ((0, 0), (1, 0))) + sub
        dd = jnp.pad(d1[:, : L - 1], ((0, 0), (1, 0))) + wd
        inn = d1 + wi
        take_b = (m >= dd) & (m >= inn) & (m > zf)
        take_j = ~take_b & (dd >= inn) & (dd > zf)
        take_i = ~take_b & ~take_j & (inn > zf)
        score = jnp.where(
            take_b, m, jnp.where(take_j, dd, jnp.where(take_i, inn, zf))
        )
        score = jnp.where(valid, score, zf)
        move = jnp.where(
            take_b, mv_b, jnp.where(take_j, mv_j, jnp.where(take_i, mv_i, mv_t))
        )
        move = jnp.where(valid, move, mv_t)
        move_ref[d, :, :] = move.astype(jnp.int8)
        # running per-lane max over the valid region (incl. the zero
        # borders i==0 / j==0); ties -> later diagonal (larger j)
        in_region = (ii <= xlen) & (jj >= zero) & (jj <= ylen)
        cur = jnp.where(in_region, score, ninf)
        upd = cur >= best_sc_ref[:]
        best_sc_ref[:] = jnp.where(upd, cur, best_sc_ref[:])
        best_d_ref[:] = jnp.where(upd, d, best_d_ref[:])
        d2_ref[:] = d1
        d1_ref[:] = score
        return c

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(D), body, jnp.int32(0))


@partial(
    jax.jit,
    static_argnames=(
        "lx", "ly", "w_match", "w_mismatch", "w_insert", "w_delete",
        "interpret",
    ),
)
def _sw_fill_pallas(
    x_codes, x_len, y_codes, y_len, lx: int, ly: int,
    w_match: float, w_mismatch: float, w_insert: float, w_delete: float,
    interpret: bool = False,
):
    """Pallas wavefront fill.

    -> (moves u8[B, D, lx+1], best_sc f32[B, lx+1], best_d i32[B, lx+1]),
    matching :func:`_sw_fill_scan_best` bit-for-bit.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B = x_codes.shape[0]
    D = lx + ly + 1
    L = _round_up(lx + 1, _LANE)
    # tile so the (D, TB, L) i8 move matrix + pre-gathered i32 y
    # diagonals fit comfortably in VMEM (~16MB/core); scores are never
    # materialized — the kernel tracks the per-lane running max instead
    TB = max(1, min(B, (2 * 1024 * 1024) // (D * L)))
    TB = _round_up(TB, 32)  # (32, 128) i8-tile-divisible batch tile
    Bp = _round_up(B, TB)

    x = jnp.full((Bp, lx), -2, jnp.int32).at[:B].set(x_codes.astype(jnp.int32))
    # ydiag[b, d, i] = y[b, d - 1 - i] (-1 outside the read): the
    # per-diagonal y lane windows, gathered once in XLA so the kernel
    # never needs an unaligned dynamic lane slice (or a per-step roll)
    ypad = jnp.full((Bp, lx + ly + L), -1, jnp.int32)
    ypad = ypad.at[:B, lx: lx + ly].set(y_codes[:, ::-1].astype(jnp.int32))
    widx = (lx + ly - jnp.arange(D))[:, None] + jnp.arange(L)[None, :]
    ydiag = ypad[:, widx]  # [Bp, D, L]
    xl = jnp.zeros((Bp, 1), jnp.int32).at[:B, 0].set(x_len.astype(jnp.int32))
    yl = jnp.zeros((Bp, 1), jnp.int32).at[:B, 0].set(y_len.astype(jnp.int32))

    kernel = functools.partial(
        _sw_kernel, lx=lx, ly=ly, L=L,
        w_match=w_match, w_mismatch=w_mismatch,
        w_insert=w_insert, w_delete=w_delete,
    )
    fill = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((D, TB, L), jnp.int8),
            jax.ShapeDtypeStruct((TB, L), jnp.float32),
            jax.ShapeDtypeStruct((TB, L), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((TB, L), jnp.float32),
            pltpu.VMEM((TB, L), jnp.float32),
        ],
        interpret=interpret,
    )

    nt = Bp // TB
    if nt == 1:
        m, bs, bd = fill(
            x, jnp.transpose(ydiag, (1, 0, 2)), xl, yl
        )  # [D, TB, L], [TB, L] x2
        moves = jnp.transpose(m, (1, 0, 2))
    else:
        # one compiled kernel, sequential over batch tiles
        m, bs, bd = jax.lax.map(
            lambda t: fill(*t),
            (
                x.reshape(nt, TB, lx),
                jnp.transpose(
                    ydiag.reshape(nt, TB, D, L), (0, 2, 1, 3)
                ),
                xl.reshape(nt, TB, 1),
                yl.reshape(nt, TB, 1),
            ),
        )  # [nt, D, TB, L], [nt, TB, L] x2
        moves = jnp.transpose(m, (0, 2, 1, 3)).reshape(Bp, D, L)
        bs = bs.reshape(Bp, L)
        bd = bd.reshape(Bp, L)
    return (
        moves[:B, :, : lx + 1].astype(jnp.uint8),
        bs[:B, : lx + 1],
        bd[:B, : lx + 1],
    )


# ----------------------------------------------------- score-only fills
#
# The GCUPS path (BASELINE metric 2).  Alignment *scores* need neither
# the [B, D, L] move matrix nor per-lane argmax bookkeeping — the row
# recurrence carries two [B, L] vectors and a running max.  The same-row
# delete chain H[i] = max(tmp[i], H[i-1] + wd) is solved by log2(L)
# doubling steps of static lane shifts (striped SW's prefix-max with
# linear decay), which both XLA and Mosaic vectorize cleanly — no
# per-diagonal y gathers, no unaligned dynamic lane slices.


@partial(jax.jit, static_argnames=("lx", "ly"))
def _sw_score_scan(
    x_codes, x_len, y_codes, y_len, w_match, w_mismatch, w_insert, w_delete,
    lx: int, ly: int,
):
    """Best local-alignment score per pair -> f32[B] (value-parity with
    :func:`_sw_fill_scan_best`'s best_sc max; i32/f32 throughout — i64
    vector ops are emulated on TPU)."""
    B = x_codes.shape[0]
    L = lx + 1
    wm = jnp.float32(w_match)
    wx = jnp.float32(w_mismatch)
    wi = jnp.float32(w_insert)
    wd = jnp.float32(w_delete)
    ii = jnp.arange(1, L, dtype=jnp.int32)  # lane i holds matrix row i
    in_x = ii[None, :] <= x_len.astype(jnp.int32)[:, None]
    xc = x_codes.astype(jnp.int32)  # lane i-1 holds x[i-1]
    yT = y_codes.astype(jnp.int32).T  # [ly, B]: scalar row per step

    shifts = []
    s = 1
    while s < L - 1:
        shifts.append(s)
        s *= 2

    def step(carry, args):
        # h_prev [B, lx+1]: lane i = matrix row i of the previous column
        h_prev, best = carry
        yj, jok = args  # y code [B], j <= y_len mask [B]
        sub = jnp.where(xc == yj[:, None], wm, wx)  # [B, lx], lane k = x[k]
        m = h_prev[:, :-1] + sub       # row i reads h_prev[i-1]
        inn = h_prev[:, 1:] + wi       # row i reads h_prev[i]
        tmp = jnp.maximum(jnp.maximum(m, inn), 0.0)
        # same-row delete chain H[i] = max(tmp[i], H[i-1] + wd) via
        # doubling (decay wd per lane step); the row-0 boundary (value 0)
        # never wins because tmp >= 0 > k*wd
        h = tmp
        for s in shifts:
            h = jnp.maximum(
                h,
                jnp.pad(h[:, :-s], ((0, 0), (s, 0)), constant_values=-jnp.inf)
                + jnp.float32(s) * wd,
            )
        h = jnp.where(in_x & jok[:, None], h, 0.0)
        # keep best as a [B, lx] accumulator — one elementwise max per
        # step instead of a per-step lane reduction; reduce once at end
        best = jnp.maximum(best, h)
        hfull = jnp.pad(h, ((0, 0), (1, 0)))  # prepend boundary row 0
        return (hfull, best), None

    h0 = jnp.zeros((B, L), jnp.float32)
    jok = (
        jnp.arange(1, ly + 1, dtype=jnp.int32)[:, None]
        <= y_len.astype(jnp.int32)[None, :]
    )
    (_, best2d), _ = jax.lax.scan(
        step, (h0, jnp.zeros((B, L - 1), jnp.float32)), (yT, jok)
    )
    return best2d.max(axis=1)


_SW_DTYPES = {"f32": jnp.float32, "bf16": jnp.bfloat16, "i16": jnp.int16,
              "i32": jnp.int32}


def _sw_score_kernel(x_ref, y_ref, xmask_ref, ymask_ref, best_ref,
                     h_ref, *, lx: int, ly: int, L: int,
                     w_match: float, w_mismatch: float, w_insert: float,
                     w_delete: float, dtype_name: str = "f32"):
    """Mosaic kernel body for one batch tile, transposed layout.

    Arrays are [L, TB] — read position in SUBLANES, batch pair in LANES —
    so the per-step y row reads as a clean [1, TB] dynamic slice off the
    leading dimension and broadcasts against the [L, TB] state for free
    (a [TB, 1]-shaped slice tiles its size-1 minor dim out to 128 lanes
    in VMEM: 128x memory for nothing).  State (rolling column + running
    best) lives in VMEM; the same-row delete chain resolves with log2(L)
    static sublane shifts.

    ``dtype_name`` picks the compute element type: "f32" is the exact
    path for ADAM's fractional default weights
    (SmithWatermanConstantGapScoring.scala:20-43); "i16"/"i32" require
    integral weights (scores stay exact integers — the narrow-type
    lane-throughput experiment); "bf16" is measurement-only (integers
    above 256 round)."""
    from jax.experimental import pallas as pl

    dt = _SW_DTYPES[dtype_name]
    integral = dtype_name in ("i16", "i32")
    wm = dt(w_match)
    wx = dt(w_mismatch)
    wi = dt(w_insert)
    zf = dt(0)
    # pad value for the delete-chain shifts: never wins (h >= 0); for the
    # int types it sits far enough above the type min that adding s*wd
    # cannot wrap (the wrapper guards |w|*L)
    ninf = dt(-16384) if integral else dt(-jnp.inf)
    xc = x_ref[:]  # [L, TB] i32, sublane i = x[i] (-2 padding)
    xmask = xmask_ref[:]  # [L, TB] 1/0 in dt: row i+1 <= x_len
    h_ref[:] = jnp.zeros_like(h_ref)
    best_ref[:] = jnp.zeros_like(best_ref)

    shifts = []
    s = 1
    while s < L:
        shifts.append(s)
        s *= 2

    def body(j, c):
        h_prev = h_ref[:]  # sublane i holds H[row i+1] of previous column
        yj = y_ref[pl.ds(j, 1), :]  # [1, TB] i32
        jok = ymask_ref[pl.ds(j, 1), :]  # [1, TB] f32 1/0
        sub = jnp.where(xc == yj, wm, wx)
        hp_shift = jnp.pad(h_prev[: L - 1, :], ((1, 0), (0, 0)))
        m = hp_shift + sub
        inn = h_prev + wi
        tmp = jnp.maximum(jnp.maximum(m, inn), zf)
        h = tmp
        for s in shifts:
            # python-level product, cast once: bf16/f32 stay in their
            # own type (a f32 scalar would promote the whole chain)
            decay = dt(s * w_delete) if integral else dt(
                np.float32(s) * np.float32(w_delete)
            )
            h = jnp.maximum(
                h,
                jnp.pad(h[: L - s, :], ((s, 0), (0, 0)),
                        constant_values=ninf) + decay,
            )
        h = jnp.maximum(h, zf)
        h = h * xmask * jok
        h_ref[:] = h
        best_ref[:] = jnp.maximum(best_ref[:], h)
        return c

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(ly), body, jnp.int32(0))


def _i16_safe(lx: int, ly: int, w_match: float, w_mismatch: float,
              w_insert: float, w_delete: float) -> bool:
    """Whether the i16 score kernel cannot overflow for these shapes and
    (integral) weights.  Two hazards: score magnitudes themselves, and
    the delete chain's decay constants, whose shift distance scales with
    the 128-lane-padded L (not lx) — the -16384 pad plus the largest
    s*w_delete must stay above int16 min."""
    if not all(
        float(w).is_integer()
        for w in (w_match, w_mismatch, w_insert, w_delete)
    ):
        return False
    wmax = max(abs(w_match), abs(w_mismatch), abs(w_insert), abs(w_delete))
    L = _round_up(lx, _LANE)
    return (max(lx, ly) + 1) * wmax < 16000 and L * abs(w_delete) < 16000


@partial(
    jax.jit,
    static_argnames=(
        "lx", "ly", "w_match", "w_mismatch", "w_insert", "w_delete",
        "interpret", "dtype_name",
    ),
)
def _sw_score_pallas(
    x_codes, x_len, y_codes, y_len, lx: int, ly: int,
    w_match: float, w_mismatch: float, w_insert: float, w_delete: float,
    interpret: bool = False, dtype_name: str = "f32",
):
    """Pallas striped score fill -> f32[B] best scores."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    dt = _SW_DTYPES[dtype_name]
    if dtype_name in ("i16", "i32"):
        for w in (w_match, w_mismatch, w_insert, w_delete):
            if not float(w).is_integer():
                raise ValueError(
                    f"integer SW dtype {dtype_name} needs integral "
                    f"weights, got {w}"
                )
        if dtype_name == "i16" and not _i16_safe(
            lx, ly, w_match, w_mismatch, w_insert, w_delete
        ):
            raise ValueError(
                "i16 SW overflow risk for these weights/lengths "
                f"(lx={lx}, ly={ly}) — use f32 or i32"
            )
    B = x_codes.shape[0]
    L = _round_up(lx, _LANE)
    TB = max(_LANE, min(_round_up(B, _LANE), 1024))
    Bp = _round_up(B, TB)

    # transposed layout (see kernel docstring): [L, Bp] with batch in
    # lanes; sublane i holds x[i] (the kernel's row i+1); -2 never
    # matches y codes
    x = jnp.full((L, Bp), -2, jnp.int32).at[:lx, :B].set(
        x_codes.astype(jnp.int32).T
    )
    xmask = (
        jnp.arange(1, L + 1, dtype=jnp.int32)[:, None]
        <= jnp.zeros((1, Bp), jnp.int32).at[0, :B].set(
            x_len.astype(jnp.int32)
        )
    ).astype(dt)
    yT = jnp.full((ly, Bp), -1, jnp.int32).at[:, :B].set(
        y_codes.astype(jnp.int32).T
    )
    ymask = (
        jnp.arange(1, ly + 1, dtype=jnp.int32)[:, None]
        <= jnp.zeros((1, Bp), jnp.int32).at[0, :B].set(
            y_len.astype(jnp.int32)
        )
    ).astype(dt)

    kernel = functools.partial(
        _sw_score_kernel, lx=lx, ly=ly, L=L,
        w_match=w_match, w_mismatch=w_mismatch,
        w_insert=w_insert, w_delete=w_delete, dtype_name=dtype_name,
    )
    nt = Bp // TB
    # one pallas_call with a grid over batch (lane) tiles — each grid
    # step owns a distinct output block, the Mosaic-legal grid shape:
    # the runtime pipelines tile i+1's HBM->VMEM copies under tile i's
    # compute, and the whole batch is a single dispatch through the
    # device tunnel instead of nt sequential kernel launches
    fill = pl.pallas_call(
        kernel,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec((L, TB), lambda i: (0, i)),
            pl.BlockSpec((ly, TB), lambda i: (0, i)),
            pl.BlockSpec((L, TB), lambda i: (0, i)),
            pl.BlockSpec((ly, TB), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((L, TB), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((L, Bp), dt),
        scratch_shapes=[pltpu.VMEM((L, TB), dt)],
        interpret=interpret,
    )
    # under jax_enable_x64 the grid machinery traces i64 indices, which
    # Mosaic fails to legalize ("func.return (i32, i64)"); every dtype in
    # this kernel is explicit, so tracing the call with x64 off is
    # semantics-preserving
    if jax.config.jax_enable_x64:
        with jax.enable_x64(False):
            best = fill(x, yT, xmask, ymask)
    else:
        best = fill(x, yT, xmask, ymask)
    return best.max(axis=0)[:B].astype(jnp.float32)


def sw_best_scores(
    x_codes, x_len, y_codes, y_len,
    w_match: float = 1.0, w_mismatch: float = -0.333,
    w_insert: float = -0.5, w_delete: float = -0.5,
    backend: str | None = None,
):
    """Best local-alignment score per pair (no trackback) -> f32[B]."""
    lx = int(np.shape(x_codes)[1])
    ly = int(np.shape(y_codes)[1])
    be = backend or os.environ.get("ADAM_TPU_SW_BACKEND", "scan")
    if be in ("pallas", "pallas_i16"):
        # "pallas" always runs the f32 kernel (exact for ADAM's
        # fractional defaults, and the only variant this environment's
        # Mosaic reliably compiles — see _use_pallas); "pallas_i16" is
        # the explicit opt-in narrow kernel for integral weight sets
        if be == "pallas_i16" and not _i16_safe(
            lx, ly, w_match, w_mismatch, w_insert, w_delete
        ):
            raise ValueError(
                "pallas_i16 backend needs integral weights within the "
                f"i16 overflow bound (lx={lx}, ly={ly}, weights="
                f"{(w_match, w_mismatch, w_insert, w_delete)})"
            )
        return _sw_score_pallas(
            jnp.asarray(x_codes), jnp.asarray(x_len), jnp.asarray(y_codes),
            jnp.asarray(y_len), lx, ly,
            float(w_match), float(w_mismatch), float(w_insert),
            float(w_delete),
            dtype_name="i16" if be == "pallas_i16" else "f32",
        )
    return _sw_score_scan(
        jnp.asarray(x_codes), jnp.asarray(x_len), jnp.asarray(y_codes),
        jnp.asarray(y_len), w_match, w_mismatch, w_insert, w_delete, lx, ly,
    )


def benchmark_gcups(
    B: int = 8192, lx: int = 127, ly: int = 127, reps: int = 6,
    backend: str | None = None, trials: int = 3,
) -> float:
    """Measured score-only fill throughput in GCUPS (giga cell updates
    per second), the standard Smith-Waterman metric (scores, no
    trackback — matching how SW search tools report GCUPS).

    Defeats the axon client's result memoization and per-dispatch
    latency the same way bench.py's kernels do: the repetition loop runs
    on device inside one jit with a data dependency chained between
    fills (each rep's x is perturbed by a value derived from the
    previous best scores), and the final scalar is fetched once.

    The shared bench chip is time-sliced: identical runs vary ~10x
    (measured 0.57 -> 5.10 GCUPS back-to-back), so the result is the
    best of ``trials`` timed runs — sustained capability between
    throttle windows, with the methodology recorded here.
    """
    import time

    rng = np.random.default_rng(0)
    xc = jnp.asarray(rng.integers(0, 4, (B, lx)), jnp.int32)
    yc = jnp.asarray(rng.integers(0, 4, (B, ly)), jnp.int32)
    xl = jnp.full((B,), lx, jnp.int32)
    yl = jnp.full((B,), ly, jnp.int32)
    if backend == "pallas_i16":
        # the integer-scoring scheme SW search tools bench with
        args = (2.0, -1.0, -1.0, -1.0)
    else:
        args = (1.0, -0.333, -0.5, -0.5)

    @jax.jit
    def bench(xc0):
        def body(i, carry):
            x, acc = carry
            best = sw_best_scores(x, xl, yc, yl, *args, backend=backend)
            # data dependency: perturb x by a (always-zero) value derived
            # from this rep's result, so reps can't be collapsed/memoized
            x = x + (best[0:1, None] % 1).astype(x.dtype)
            return (x, acc + best.sum())

        return jax.lax.fori_loop(0, reps, body, (xc0, jnp.float32(0)))[1]

    acc = bench(xc)
    jax.block_until_ready(acc)  # compile + warm
    best_dt = float("inf")
    for t in range(max(1, trials)):
        t0 = time.perf_counter()
        acc = bench(xc + jnp.int32(t) - jnp.int32(t))
        float(acc)  # full sync
        best_dt = min(best_dt, (time.perf_counter() - t0) / reps)
    return B * lx * ly / best_dt / 1e9


def _use_pallas() -> bool:
    """Whether to run the hand-written Pallas *trackback* fill.

    Default is the lax.scan fill on every backend for the
    moves-producing path: it materializes the [B, D, L] move matrix the
    host trackback needs, and XLA pipelines that fine.

    GCUPS measurement note (the one measured truth, superseding earlier
    conflicting claims): the **score-only** striped fills above are the
    benchmark path — :func:`benchmark_gcups` measured on the shared
    v5e bench chip (2026-07-30, chained-rep on-device loop, best of 3):
    pallas (transposed [L, TB] grid kernel, single dispatch) 5.4-8.8
    GCUPS ~= scan 5.5-9.2 at B=8192/127x127 across throttle windows.

    Why this is a *VPU op-count* bound, not a lazy-kernel artifact: each
    cell update costs ~20 vector ops (3 max/2 add for the m/i/0 floor,
    plus the log2(L)=7-step doubling delete chain at 2 ops each — the
    chain is the irreducible cost of striped SW; Farrar's lazy-F
    shortcut is data-dependent control flow Mosaic/XLA can't vectorize).
    The recurrence is max/add, so the MXU cannot help (the realign sweep
    was reformulated onto the MXU in round 4 precisely because it had
    *no* such dependency — 9 GFLOP/s -> matmul rates; SW does not admit
    that).

    Narrow-type evidence (round 5, closing VERDICT r4 item 3): the
    hoped-for 2x from 16-bit lanes is unreachable on this toolchain —
    minimal-kernel bisect on the real chip shows Mosaic compiles 16-bit
    elementwise/scratch ops but its compile helper CRASHES (subprocess
    exit 1) on 16-bit sublane pad/shift, select, and dynamic-slice, the
    exact ops the striped kernel is made of; and the i32 integral-weight
    variant (which does compile; dtype_name="i32") measured 4.3-4.7
    GCUPS vs f32's 9.9 in the same windows — integer vector max/select
    run *slower* than f32, not 2x faster.  The i16 kernel is kept
    behind backend="pallas_i16" (bit-exact for integral weights,
    interpret-verified) for toolchains whose Mosaic accepts 16-bit
    vectors.

    Corrected derivation (replacing the optimistic 10-25 band): with
    mask multiplies and boundary pads the kernel spends ~25 vector
    ops/cell, and the probe-paired measurements put the effective VPU
    rate near ~2.2-2.8 Tera vector-op/s, i.e. ~90-110 full-chip GCUPS;
    slice-normalized measurements (BENCH `sw.windows`) sit at 104-124,
    matching.  At the 5-9%% slices the probes record, that predicts
    5-10 GCUPS raw — measured 5.5-9.9.  Raw GCUPS above ~12 requires a
    granted slice above ~11%%, which the scheduler rarely gives.  bench.py emits per-window (gcups,
    probe_tflops) pairs plus slice-normalized GCUPS so the tracking is
    recorded, not asserted.  Earlier numbers — "154 GCUPS" (commit
    6129bde, an axon-memoization artifact), "12.4 scan / 0.9 pallas" (a
    moves-path measurement), "~127 GCUPS full-chip bound" (asserted
    without the op-count derivation), and the driver's 0.03 (BENCH_r02)
    — are obsolete.
    """
    return os.environ.get("ADAM_TPU_SW_BACKEND", "scan") == "pallas"


_warned_pallas_fallback = False


def sw_fill(x_codes, x_len, y_codes, y_len, w_match, w_mismatch, w_insert,
            w_delete, lx: int, ly: int):
    """Diagonal-layout fill, Pallas on accelerators, scan elsewhere.

    -> (moves u8[B, D, lx+1], best_sc f32[B, lx+1], best_d i32[B, lx+1]).

    A Pallas failure falls back to the scan fill with a warn-once log
    (never silently), so a TPU-side kernel regression is observable;
    force a backend with ADAM_TPU_SW_BACKEND={pallas,scan}.
    """
    if _use_pallas():
        try:
            return _sw_fill_pallas(
                jnp.asarray(x_codes), jnp.asarray(x_len),
                jnp.asarray(y_codes), jnp.asarray(y_len), lx, ly,
                float(w_match), float(w_mismatch), float(w_insert),
                float(w_delete),
            )
        except Exception as e:  # pragma: no cover - driver/kernel capability
            if os.environ.get("ADAM_TPU_SW_BACKEND") == "pallas":
                raise  # explicitly requested: never mask a kernel failure
            global _warned_pallas_fallback
            if not _warned_pallas_fallback:
                _warned_pallas_fallback = True
                import logging

                logging.getLogger(__name__).warning(
                    "Pallas Smith-Waterman kernel failed (%s: %s); "
                    "falling back to the lax.scan fill for this process",
                    type(e).__name__, e,
                )
    return _sw_fill_scan_best(
        jnp.asarray(x_codes), jnp.asarray(x_len), jnp.asarray(y_codes),
        jnp.asarray(y_len), w_match, w_mismatch, w_insert, w_delete, lx, ly,
    )


# ------------------------------------------------------------ trackback


@dataclass(frozen=True)
class SWAlignment:
    cigar_x: str
    cigar_y: str
    x_start: int
    y_start: int
    x_end: int  # exclusive end of the aligned span in x
    y_end: int
    score: float


def _max_coordinates(
    best_sc: np.ndarray, best_d: np.ndarray, x_len: int
) -> tuple[int, int, float]:
    """Reference tie rule from the per-lane best arrays: the global max
    with the LAST row i winning ties, then the LAST column j
    (maxCoordinates' right-biased fold; the per-lane max already kept
    the largest diagonal = largest j within each row)."""
    lanes = best_sc[: x_len + 1]
    best = lanes.max()
    i = int(np.flatnonzero(lanes == best).max())
    j = int(best_d[i]) - i
    return i, j, float(best)


def _rnn_to_cigar(ops: list[str]) -> str:
    """Reversed unit-length op list -> run-length CIGAR string."""
    if not ops:
        return ""
    out = []
    last, run = ops[0], 1
    for c in ops[1:]:
        if c == last:
            run += 1
        else:
            out.append(f"{run}{last}")
            last, run = c, 1
    out.append(f"{run}{last}")
    return "".join(reversed(out))


def _trackback(
    diag_moves: np.ndarray, best_sc: np.ndarray, best_d: np.ndarray,
    x_len: int,
) -> SWAlignment:
    i, j, score = _max_coordinates(best_sc, best_d, x_len)
    end_i, end_j = i, j
    cx: list[str] = []
    cy: list[str] = []
    while diag_moves[i + j, i] != MOVE_T:
        mv = diag_moves[i + j, i]
        if mv == MOVE_B:
            cx.append("M")
            cy.append("M")
            i -= 1
            j -= 1
        elif mv == MOVE_J:
            cx.append("I")
            cy.append("D")
            i -= 1
        else:
            cx.append("D")
            cy.append("I")
            j -= 1
    return SWAlignment(
        cigar_x=_rnn_to_cigar(cx),
        cigar_y=_rnn_to_cigar(cy),
        x_start=i,
        y_start=j,
        x_end=end_i,
        y_end=end_j,
        score=score,
    )


def smith_waterman_batch(
    x_codes,
    x_len,
    y_codes,
    y_len,
    w_match: float = 1.0,
    w_mismatch: float = -0.333,
    w_insert: float = -0.5,
    w_delete: float = -0.5,
) -> list[SWAlignment]:
    """Align each x[i] against y[i]; device fill + host trackback."""
    x_codes = jnp.asarray(x_codes)
    y_codes = jnp.asarray(y_codes)
    moves, best_sc, best_d = sw_fill(
        x_codes, jnp.asarray(x_len), y_codes, jnp.asarray(y_len),
        w_match, w_mismatch, w_insert, w_delete,
        int(x_codes.shape[1]), int(y_codes.shape[1]),
    )
    moves = np.asarray(moves)
    best_sc = np.asarray(best_sc)
    best_d = np.asarray(best_d)
    xl = np.asarray(x_len)
    return [
        _trackback(moves[b], best_sc[b], best_d[b], int(xl[b]))
        for b in range(x_codes.shape[0])
    ]


def smith_waterman(
    x: str,
    y: str,
    w_match: float = 1.0,
    w_mismatch: float = -0.333,
    w_insert: float = -0.5,
    w_delete: float = -0.5,
) -> SWAlignment:
    """Single-pair convenience wrapper (strings in, CIGARs out)."""
    from adam_tpu.formats.schema import encode_bases

    xc = encode_bases(x)[None, :]
    yc = encode_bases(y)[None, :]
    return smith_waterman_batch(
        xc, np.array([len(x)]), yc, np.array([len(y)]),
        w_match, w_mismatch, w_insert, w_delete,
    )[0]
