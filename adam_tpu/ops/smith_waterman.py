"""Batched Smith-Waterman local alignment.

Semantics match ``algorithms/smithwaterman/`` in the reference:
constant-gap scoring with the exact move-priority and tie-breaking of
``SmithWatermanGapScoringFromFn.buildScoringMatrix``
(B if m>=d && m>=in && m>0, else J if d>=in && d>0, else I if in>0,
else terminate) and ``SmithWaterman.maxCoordinates`` (on score ties the
*later* row/column wins, because the reference's fold keeps the right
operand on equality), and the same trackback emission
(B -> M/M, J -> I in x / D in y, I -> D in x / I in y).

TPU formulation: the O(|x|·|y|) matrix fill runs as a ``lax.scan`` over
anti-diagonals — each step updates a whole diagonal vector-wide, and the
pair dimension is ``vmap``-batched, so the chip fills thousands of
matrices concurrently (the per-read-per-consensus sweep of indel
realignment).  Trackback is O(|x|+|y|) per pair on the host, reading the
device-produced move matrix.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

# move codes in the device move matrix
MOVE_T = 0  # terminate
MOVE_B = 1  # both (diagonal)
MOVE_J = 2  # consume x only
MOVE_I = 3  # consume y only


@partial(jax.jit, static_argnames=("lx", "ly"))
def _sw_fill_diagonals(
    x_codes, x_len, y_codes, y_len, w_match, w_mismatch, w_insert, w_delete,
    lx: int, ly: int,
):
    """Fill scoring/move matrices for a batch of pairs.

    x_codes: [B, lx] u8, y_codes: [B, ly] u8 (base codes; equality is the
    match test, so N==N matches — same as the reference's char equality).
    Returns (scores [B, lx+1, ly+1] f32, moves [B, lx+1, ly+1] u8).
    """
    B = x_codes.shape[0]
    D = lx + ly + 1  # number of anti-diagonals of the (lx+1)x(ly+1) matrix
    ii = jnp.arange(lx + 1)

    def step(carry, d):
        d1, d2 = carry  # diagonals d-1 and d-2, each [B, lx+1] indexed by i
        jj = d - ii  # column index per lane
        valid = (
            (ii >= 1)
            & (jj >= 1)
            & (ii[None, :] <= x_len[:, None])
            & (jj[None, :] <= y_len[:, None])
        )
        xc = x_codes[:, jnp.clip(ii - 1, 0, lx - 1)]
        yc = y_codes[:, jnp.clip(jj - 1, 0, ly - 1)]  # jj is batch-invariant
        sub = jnp.where(xc == yc, w_match, w_mismatch)

        def shift_i(v):  # v[i-1] with 0 at i=0
            return jnp.pad(v[:, :-1], ((0, 0), (1, 0)))

        m = shift_i(d2) + sub
        dd = shift_i(d1) + w_delete
        inn = d1 + w_insert

        take_b = (m >= dd) & (m >= inn) & (m > 0.0)
        take_j = ~take_b & (dd >= inn) & (dd > 0.0)
        take_i = ~take_b & ~take_j & (inn > 0.0)
        score = jnp.where(
            take_b, m, jnp.where(take_j, dd, jnp.where(take_i, inn, 0.0))
        )
        move = jnp.where(
            take_b,
            MOVE_B,
            jnp.where(take_j, MOVE_J, jnp.where(take_i, MOVE_I, MOVE_T)),
        ).astype(jnp.uint8)
        score = jnp.where(valid, score, 0.0)
        move = jnp.where(valid, move, MOVE_T)
        return (score, d1), (score, move)

    (_, _), (diag_scores, diag_moves) = jax.lax.scan(
        step,
        (jnp.zeros((B, lx + 1)), jnp.zeros((B, lx + 1))),
        jnp.arange(D),
    )
    # diag_scores: [D, B, lx+1]; matrix[b, i, j] = diag[i+j, b, i]
    jj = jnp.arange(ly + 1)
    dmat = ii[:, None] + jj[None, :]  # [lx+1, ly+1]
    scores = diag_scores[dmat, :, ii[:, None]]  # [lx+1, ly+1, B]
    moves = diag_moves[dmat, :, ii[:, None]]
    return (
        jnp.moveaxis(scores, -1, 0).astype(jnp.float32),
        jnp.moveaxis(moves, -1, 0),
    )


@dataclass(frozen=True)
class SWAlignment:
    cigar_x: str
    cigar_y: str
    x_start: int
    y_start: int
    x_end: int  # exclusive end of the aligned span in x
    y_end: int
    score: float


def _max_coordinates(score: np.ndarray, x_len: int, y_len: int) -> tuple[int, int]:
    """Reference tie rule: per-row pick the LAST max column, then across
    rows pick the LAST row achieving the global max."""
    sub = score[: x_len + 1, : y_len + 1]
    flipped = sub[:, ::-1]
    row_arg = sub.shape[1] - 1 - np.argmax(flipped, axis=1)
    row_max = sub[np.arange(sub.shape[0]), row_arg]
    i = sub.shape[0] - 1 - int(np.argmax(row_max[::-1]))
    return i, int(row_arg[i])


def _rnn_to_cigar(ops: list[str]) -> str:
    """Reversed unit-length op list -> run-length CIGAR string."""
    if not ops:
        return ""
    out = []
    last, run = ops[0], 1
    for c in ops[1:]:
        if c == last:
            run += 1
        else:
            out.append(f"{run}{last}")
            last, run = c, 1
    out.append(f"{run}{last}")
    return "".join(reversed(out))


def _trackback(
    moves: np.ndarray, score: np.ndarray, x_len: int, y_len: int
) -> SWAlignment:
    i, j = _max_coordinates(score, x_len, y_len)
    end_i, end_j = i, j
    cx: list[str] = []
    cy: list[str] = []
    while moves[i, j] != MOVE_T:
        mv = moves[i, j]
        if mv == MOVE_B:
            cx.append("M")
            cy.append("M")
            i -= 1
            j -= 1
        elif mv == MOVE_J:
            cx.append("I")
            cy.append("D")
            i -= 1
        else:
            cx.append("D")
            cy.append("I")
            j -= 1
    return SWAlignment(
        cigar_x=_rnn_to_cigar(cx),
        cigar_y=_rnn_to_cigar(cy),
        x_start=i,
        y_start=j,
        x_end=end_i,
        y_end=end_j,
        score=float(score[end_i, end_j]),
    )


def smith_waterman_batch(
    x_codes,
    x_len,
    y_codes,
    y_len,
    w_match: float = 1.0,
    w_mismatch: float = -0.333,
    w_insert: float = -0.5,
    w_delete: float = -0.5,
) -> list[SWAlignment]:
    """Align each x[i] against y[i]; device fill + host trackback."""
    x_codes = jnp.asarray(x_codes)
    y_codes = jnp.asarray(y_codes)
    scores, moves = _sw_fill_diagonals(
        x_codes,
        jnp.asarray(x_len),
        y_codes,
        jnp.asarray(y_len),
        w_match, w_mismatch, w_insert, w_delete,
        int(x_codes.shape[1]),
        int(y_codes.shape[1]),
    )
    scores = np.asarray(scores)
    moves = np.asarray(moves)
    xl = np.asarray(x_len)
    yl = np.asarray(y_len)
    return [
        _trackback(moves[b], scores[b], int(xl[b]), int(yl[b]))
        for b in range(x_codes.shape[0])
    ]


def smith_waterman(
    x: str,
    y: str,
    w_match: float = 1.0,
    w_mismatch: float = -0.333,
    w_insert: float = -0.5,
    w_delete: float = -0.5,
) -> SWAlignment:
    """Single-pair convenience wrapper (strings in, CIGARs out)."""
    from adam_tpu.formats.schema import encode_bases

    xc = encode_bases(x)[None, :]
    yc = encode_bases(y)[None, :]
    return smith_waterman_batch(
        xc, np.array([len(x)]), yc, np.array([len(y)]),
        w_match, w_mismatch, w_insert, w_delete,
    )[0]
