"""Batched CIGAR walks on device.

The reference walks htsjdk Cigar objects per read on the JVM
(``rich/RichAlignmentRecord.scala``: referenceLengthFromCigar :41-57,
unclippedStart/End :110-121, fivePrimePosition :124-126, per-base
referencePositions :200-229).  Here every walk is a masked reduction over
the ``[N, C]`` cigar columns, so one XLA fusion covers the whole batch.
"""

from __future__ import annotations

import jax.numpy as jnp

from adam_tpu.formats import schema


def _op_table(table):
    return jnp.asarray(table)


def _valid_mask(cigar_ops, cigar_n):
    C = cigar_ops.shape[-1]
    return jnp.arange(C) < cigar_n[..., None]


def reference_length(cigar_ops, cigar_lens, cigar_n):
    """Reference bases consumed by each read's CIGAR (M/D/N/=/X)."""
    consumes = _op_table(schema.CIGAR_CONSUMES_REF)[cigar_ops]
    v = _valid_mask(cigar_ops, cigar_n)
    return jnp.sum(cigar_lens * consumes * v, axis=-1).astype(jnp.int64)


def query_length(cigar_ops, cigar_lens, cigar_n):
    """Query bases consumed (M/I/S/=/X)."""
    consumes = _op_table(schema.CIGAR_CONSUMES_QUERY)[cigar_ops]
    v = _valid_mask(cigar_ops, cigar_n)
    return jnp.sum(cigar_lens * consumes * v, axis=-1).astype(jnp.int32)


def _is_clip(cigar_ops):
    return (cigar_ops == schema.CIGAR_S) | (cigar_ops == schema.CIGAR_H)


def leading_clip(cigar_ops, cigar_lens, cigar_n):
    """Total clipped (S+H) length at the start of each read."""
    v = _valid_mask(cigar_ops, cigar_n)
    clip = _is_clip(cigar_ops) & v
    run = jnp.cumprod(clip.astype(jnp.int32), axis=-1)  # 1 while still clipping
    return jnp.sum(cigar_lens * run, axis=-1).astype(jnp.int64)


def trailing_clip(cigar_ops, cigar_lens, cigar_n):
    """Total clipped (S+H) length at the end of each read.

    Padding lanes (beyond cigar_n) must not break the trailing run, so the
    run predicate is clip-or-pad, and only real clip lanes contribute."""
    v = _valid_mask(cigar_ops, cigar_n)
    clip = _is_clip(cigar_ops) & v
    run_pred = (clip | ~v).astype(jnp.int32)
    run = jnp.flip(jnp.cumprod(jnp.flip(run_pred, axis=-1), axis=-1), axis=-1)
    return jnp.sum(cigar_lens * clip * run, axis=-1).astype(jnp.int64)


def unclipped_start(start, cigar_ops, cigar_lens, cigar_n):
    """start - leading clips (RichAlignmentRecord.unclippedStart)."""
    return start - leading_clip(cigar_ops, cigar_lens, cigar_n)


def unclipped_end(end, cigar_ops, cigar_lens, cigar_n):
    """end + trailing clips.  ``end`` is 0-based exclusive, and so is the
    reference's unclippedEnd (it folds clip lengths onto the exclusive
    ``getEnd``, rich/RichAlignmentRecord.scala:110-114) — no -1 anywhere."""
    return end + trailing_clip(cigar_ops, cigar_lens, cigar_n)


def five_prime_position(start, end, flags, cigar_ops, cigar_lens, cigar_n):
    """5' reference position with clipping (fivePrimePosition semantics,
    rich/RichAlignmentRecord.scala:124-126): the *exclusive* unclipped end
    for reverse-strand reads — the reference uses `end` directly, which is
    0-based exclusive — and the unclipped start otherwise.

    Duplicate marking keys on this (ReferencePositionPair via
    RichAlignmentRecord.fivePrimeReferencePosition); the key also carries
    strand, so forward/reverse positions never collide."""
    rev = (flags & schema.FLAG_REVERSE) != 0
    us = unclipped_start(start, cigar_ops, cigar_lens, cigar_n)
    ue = unclipped_end(end, cigar_ops, cigar_lens, cigar_n)
    return jnp.where(rev, ue, us)


def first_real_op(cigar_ops, cigar_n):
    """Code of the first non-clip op, CIGAR_PAD if none."""
    C = cigar_ops.shape[-1]
    v = _valid_mask(cigar_ops, cigar_n)
    real = v & ~_is_clip(cigar_ops)
    idx = jnp.argmax(real, axis=-1)
    any_real = jnp.any(real, axis=-1)
    got = jnp.take_along_axis(cigar_ops, idx[..., None], axis=-1)[..., 0]
    return jnp.where(any_real, got, schema.CIGAR_PAD)


def reference_positions(cigar_ops, cigar_lens, cigar_n, start, lmax):
    """Per-base reference position for each read -> i64[N, lmax].

    -1 for bases that don't map to the reference (insertions, soft clips)
    and for padding lanes — the role of
    RichAlignmentRecord.referencePositions (:200-229).

    Implemented as a scan-free gather: for each cigar op we know the query
    span [q0, q1) and the reference offset at q0; a base at query index j
    inside an M/=/X op maps to start + refoff + (j - q0).
    """
    consumes_q = _op_table(schema.CIGAR_CONSUMES_QUERY)[cigar_ops]
    consumes_r = _op_table(schema.CIGAR_CONSUMES_REF)[cigar_ops]
    v = _valid_mask(cigar_ops, cigar_n).astype(jnp.int64)
    qlen = cigar_lens * consumes_q * v  # query span per op
    rlen = cigar_lens * consumes_r * v
    q0 = jnp.cumsum(qlen, axis=-1) - qlen  # query offset at op start
    r0 = jnp.cumsum(rlen, axis=-1) - rlen  # ref offset at op start
    aligned = (consumes_q * consumes_r * v).astype(bool)  # M/=/X

    j = jnp.arange(lmax)[None, None, :]  # [1, 1, L]
    in_op = (j >= q0[..., None]) & (j < (q0 + qlen)[..., None]) & aligned[..., None]
    pos = start[..., None, None] + r0[..., None] + (j - q0[..., None])
    out = jnp.sum(jnp.where(in_op, pos, 0), axis=-2)
    hit = jnp.any(in_op, axis=-2)
    return jnp.where(hit, out, -1)
