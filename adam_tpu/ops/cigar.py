"""Batched CIGAR walks on device.

The reference walks htsjdk Cigar objects per read on the JVM
(``rich/RichAlignmentRecord.scala``: referenceLengthFromCigar :41-57,
unclippedStart/End :110-121, fivePrimePosition :124-126, per-base
referencePositions :200-229).  Here every walk is a masked reduction over
the ``[N, C]`` cigar columns, so one XLA fusion covers the whole batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from adam_tpu.formats import schema


def _op_table(table):
    return jnp.asarray(table)


def _valid_mask(cigar_ops, cigar_n):
    C = cigar_ops.shape[-1]
    return jnp.arange(C) < cigar_n[..., None]


def reference_length(cigar_ops, cigar_lens, cigar_n):
    """Reference bases consumed by each read's CIGAR (M/D/N/=/X)."""
    consumes = _op_table(schema.CIGAR_CONSUMES_REF)[cigar_ops]
    v = _valid_mask(cigar_ops, cigar_n)
    return jnp.sum(cigar_lens * consumes * v, axis=-1).astype(jnp.int64)


def query_length(cigar_ops, cigar_lens, cigar_n):
    """Query bases consumed (M/I/S/=/X)."""
    consumes = _op_table(schema.CIGAR_CONSUMES_QUERY)[cigar_ops]
    v = _valid_mask(cigar_ops, cigar_n)
    return jnp.sum(cigar_lens * consumes * v, axis=-1).astype(jnp.int32)


def _is_clip(cigar_ops):
    return (cigar_ops == schema.CIGAR_S) | (cigar_ops == schema.CIGAR_H)


def leading_clip(cigar_ops, cigar_lens, cigar_n):
    """Total clipped (S+H) length at the start of each read."""
    v = _valid_mask(cigar_ops, cigar_n)
    clip = _is_clip(cigar_ops) & v
    run = jnp.cumprod(clip.astype(jnp.int32), axis=-1)  # 1 while still clipping
    return jnp.sum(cigar_lens * run, axis=-1).astype(jnp.int64)


def trailing_clip(cigar_ops, cigar_lens, cigar_n):
    """Total clipped (S+H) length at the end of each read.

    Padding lanes (beyond cigar_n) must not break the trailing run, so the
    run predicate is clip-or-pad, and only real clip lanes contribute."""
    v = _valid_mask(cigar_ops, cigar_n)
    clip = _is_clip(cigar_ops) & v
    run_pred = (clip | ~v).astype(jnp.int32)
    run = jnp.flip(jnp.cumprod(jnp.flip(run_pred, axis=-1), axis=-1), axis=-1)
    return jnp.sum(cigar_lens * clip * run, axis=-1).astype(jnp.int64)


def unclipped_start(start, cigar_ops, cigar_lens, cigar_n):
    """start - leading clips (RichAlignmentRecord.unclippedStart)."""
    return start - leading_clip(cigar_ops, cigar_lens, cigar_n)


def unclipped_end(end, cigar_ops, cigar_lens, cigar_n):
    """end + trailing clips.  ``end`` is 0-based exclusive, and so is the
    reference's unclippedEnd (it folds clip lengths onto the exclusive
    ``getEnd``, rich/RichAlignmentRecord.scala:110-114) — no -1 anywhere."""
    return end + trailing_clip(cigar_ops, cigar_lens, cigar_n)


def five_prime_position(start, end, flags, cigar_ops, cigar_lens, cigar_n):
    """5' reference position with clipping (fivePrimePosition semantics,
    rich/RichAlignmentRecord.scala:124-126): the *exclusive* unclipped end
    for reverse-strand reads — the reference uses `end` directly, which is
    0-based exclusive — and the unclipped start otherwise.

    Duplicate marking keys on this (ReferencePositionPair via
    RichAlignmentRecord.fivePrimeReferencePosition); the key also carries
    strand, so forward/reverse positions never collide."""
    rev = (flags & schema.FLAG_REVERSE) != 0
    us = unclipped_start(start, cigar_ops, cigar_lens, cigar_n)
    ue = unclipped_end(end, cigar_ops, cigar_lens, cigar_n)
    return jnp.where(rev, ue, us)


def five_prime_position_np(start, end, flags, cigar_ops, cigar_lens, cigar_n):
    """Host (numpy) twin of :func:`five_prime_position` -> i64[N].

    Pipelines whose only device work would be this walk plus a couple of
    reductions (duplicate marking's key prep) run it host-side: on a
    tunneled chip the fetch of even small outputs costs more than the
    whole computation.
    """
    import numpy as np

    ops = np.asarray(cigar_ops)
    lens = np.asarray(cigar_lens).astype(np.int64)
    n_ops = np.asarray(cigar_n)
    N, C = ops.shape if ops.ndim == 2 else (len(n_ops), 0)
    if C == 0:
        return np.asarray(start).copy()
    v = np.arange(C)[None, :] < n_ops[:, None]
    clip = ((ops == schema.CIGAR_S) | (ops == schema.CIGAR_H)) & v
    lead_run = np.cumprod(clip.astype(np.int64), axis=1)
    lead = (lens * lead_run).sum(axis=1)
    run_pred = (clip | ~v).astype(np.int64)
    trail_run = np.cumprod(run_pred[:, ::-1], axis=1)[:, ::-1]
    trail = (lens * clip * trail_run).sum(axis=1)
    rev = (np.asarray(flags) & schema.FLAG_REVERSE) != 0
    return np.where(rev, np.asarray(end) + trail, np.asarray(start) - lead)


def first_real_op(cigar_ops, cigar_n):
    """Code of the first non-clip op, CIGAR_PAD if none."""
    C = cigar_ops.shape[-1]
    v = _valid_mask(cigar_ops, cigar_n)
    real = v & ~_is_clip(cigar_ops)
    idx = jnp.argmax(real, axis=-1)
    any_real = jnp.any(real, axis=-1)
    got = jnp.take_along_axis(cigar_ops, idx[..., None], axis=-1)[..., 0]
    return jnp.where(any_real, got, schema.CIGAR_PAD)


def reference_positions_np(cigar_ops, cigar_lens, cigar_n, start, lmax):
    """Host (numpy) twin of :func:`reference_positions` -> i64[N, lmax].

    Pipelines that need per-base reference positions as a *host-side*
    filter input (e.g. BQSR's known-SNP masking) use this to avoid
    round-tripping an int64 [N, L] array through the device — on a
    tunneled TPU that fetch alone costs more than the whole pass.
    Delegates to the threaded native CIGAR walk when available.
    """
    import numpy as np

    from adam_tpu import native

    nat = native.ref_positions(cigar_ops, cigar_lens, cigar_n, start, lmax)
    if nat is not None:
        return nat

    ops = np.asarray(cigar_ops)
    lens = np.asarray(cigar_lens).astype(np.int64)
    n_ops = np.asarray(cigar_n)
    start = np.asarray(start)
    N, C = ops.shape
    if C == 0:
        return np.full((N, lmax), -1, np.int64)
    v = (np.arange(C)[None, :] < n_ops[:, None]).astype(np.int64)
    consumes_q = schema.CIGAR_CONSUMES_QUERY[np.minimum(ops, 15)].astype(np.int64)
    consumes_r = schema.CIGAR_CONSUMES_REF[np.minimum(ops, 15)].astype(np.int64)
    qlen = lens * consumes_q * v
    rlen = lens * consumes_r * v
    q_end = np.cumsum(qlen, axis=1)
    q0 = q_end - qlen
    r0 = np.cumsum(rlen, axis=1) - rlen
    aligned = (consumes_q * consumes_r * v).astype(bool)

    j = np.arange(lmax, dtype=np.int64)
    # first op whose query span ends after j: vectorized binary search
    # (side='right') over the non-decreasing q_end lanes, [N, L] working set
    lo = np.zeros((N, lmax), np.int64)
    hi = np.full((N, lmax), C, np.int64)
    while (lo < hi).any():
        mid = (lo + hi) // 2
        ge = np.take_along_axis(q_end, np.minimum(mid, C - 1), axis=1) <= j[None, :]
        adv = lo < hi
        lo = np.where(adv & ge, mid + 1, lo)
        hi = np.where(adv & ~ge, mid, hi)
    op_idx = lo
    in_read = op_idx < C
    op_clip = np.minimum(op_idx, C - 1)
    hit = np.take_along_axis(aligned, op_clip, axis=1) & in_read
    pos = (
        start[:, None]
        + np.take_along_axis(r0, op_clip, axis=1)
        + (j[None, :] - np.take_along_axis(q0, op_clip, axis=1))
    )
    return np.where(hit, pos, -1)


def reference_positions(cigar_ops, cigar_lens, cigar_n, start, lmax):
    """Per-base reference position for each read -> i64[N, lmax].

    -1 for bases that don't map to the reference (insertions, soft clips)
    and for padding lanes — the role of
    RichAlignmentRecord.referencePositions (:200-229).

    Implemented as a per-base binary search over the cigar's cumulative
    query spans (searchsorted over the [C] lane axis), so the working set
    stays [N, L] — no [N, C, L] blow-up, and the fusion compiles in
    milliseconds even under x64.
    """
    consumes_q = _op_table(schema.CIGAR_CONSUMES_QUERY)[cigar_ops]
    consumes_r = _op_table(schema.CIGAR_CONSUMES_REF)[cigar_ops]
    v = _valid_mask(cigar_ops, cigar_n).astype(jnp.int64)
    qlen = cigar_lens * consumes_q * v  # query span per op
    rlen = cigar_lens * consumes_r * v
    q_end = jnp.cumsum(qlen, axis=-1)  # query offset at op end
    q0 = q_end - qlen
    r0 = jnp.cumsum(rlen, axis=-1) - rlen  # ref offset at op start
    aligned = (consumes_q * consumes_r * v).astype(bool)  # M/=/X

    j = jnp.arange(lmax, dtype=q_end.dtype)  # [L]
    # first op whose query span ends after j (ops with qlen==0 share q_end
    # with their predecessor, so side='right' skips them)
    op_idx = jax.vmap(lambda qe: jnp.searchsorted(qe, j, side="right"))(q_end)
    C = cigar_ops.shape[-1]
    in_read = op_idx < C
    op_idx = jnp.minimum(op_idx, C - 1)
    hit = jnp.take_along_axis(aligned, op_idx, axis=-1) & in_read
    pos = (
        start[..., None]
        + jnp.take_along_axis(r0, op_idx, axis=-1)
        + (j[None, :] - jnp.take_along_axis(q0, op_idx, axis=-1))
    )
    return jnp.where(hit, pos, -1)
