"""samtools-flagstat metrics as one fused mask-reduction pass.

Matches the metric definitions of ``rdd/read/FlagStat.scala:24-119``
(FlagStatMetrics / DuplicateMetrics, split by vendor-quality flag).  The
reference computes a per-record metrics object then tree-aggregates; here
each metric is a masked ``sum`` over the batch — a single XLA reduction
kernel — and the cross-device combine is a ``psum`` (see
adam_tpu.parallel).
"""

from __future__ import annotations

from dataclasses import dataclass, fields

import jax
import jax.numpy as jnp

from adam_tpu.formats import schema
from adam_tpu.formats.batch import ReadBatch


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class DuplicateMetrics:
    total: jnp.ndarray
    both_mapped: jnp.ndarray
    only_read_mapped: jnp.ndarray
    cross_chromosome: jnp.ndarray

    def __add__(self, other):
        return jax.tree.map(lambda a, b: a + b, self, other)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class FlagStatMetrics:
    total: jnp.ndarray
    duplicates_primary: DuplicateMetrics
    duplicates_secondary: DuplicateMetrics
    mapped: jnp.ndarray
    paired_in_sequencing: jnp.ndarray
    read1: jnp.ndarray
    read2: jnp.ndarray
    properly_paired: jnp.ndarray
    with_self_and_mate_mapped: jnp.ndarray
    singleton: jnp.ndarray
    with_mate_mapped_to_diff_chromosome: jnp.ndarray
    with_mate_mapped_to_diff_chromosome_mapq5: jnp.ndarray

    def __add__(self, other):
        return jax.tree.map(lambda a, b: a + b, self, other)

    def to_ints(self) -> "FlagStatMetrics":
        return jax.tree.map(int, self)


def _metrics_for(b: ReadBatch, select) -> FlagStatMetrics:
    """Mask-reduce metrics over rows where ``select`` holds."""
    flags = b.flags

    def has(bit):
        return (flags & bit) != 0

    mapped = ~has(schema.FLAG_UNMAPPED)
    mate_mapped = ~has(schema.FLAG_MATE_UNMAPPED)
    paired = has(schema.FLAG_PAIRED)
    primary = ~has(schema.FLAG_SECONDARY)
    dup = has(schema.FLAG_DUPLICATE)
    # isSameContig(contig, mateContig): name equality, null==null included
    # (util/Util.scala:24-30) — index equality reproduces it (-1 == -1).
    same_contig = b.contig_idx == b.mate_contig_idx
    diff_chrom = paired & mapped & mate_mapped & ~same_contig

    def count(mask):
        return jnp.sum((mask & select).astype(jnp.int64))

    def dup_metrics(which):
        m = dup & which
        return DuplicateMetrics(
            total=count(m),
            both_mapped=count(m & mapped & mate_mapped),
            only_read_mapped=count(m & mapped & ~mate_mapped),
            cross_chromosome=count(m & ~same_contig),
        )

    return FlagStatMetrics(
        total=count(jnp.ones_like(mapped)),
        duplicates_primary=dup_metrics(primary),
        duplicates_secondary=dup_metrics(~primary),
        mapped=count(mapped),
        paired_in_sequencing=count(paired),
        read1=count(paired & has(schema.FLAG_FIRST_OF_PAIR)),
        read2=count(paired & has(schema.FLAG_SECOND_OF_PAIR)),
        properly_paired=count(paired & has(schema.FLAG_PROPER_PAIR)),
        with_self_and_mate_mapped=count(paired & mapped & mate_mapped),
        singleton=count(paired & mapped & ~mate_mapped),
        with_mate_mapped_to_diff_chromosome=count(diff_chrom),
        with_mate_mapped_to_diff_chromosome_mapq5=count(diff_chrom & (b.mapq >= 5)),
    )


@jax.jit
def flagstat_device(b: ReadBatch) -> tuple[FlagStatMetrics, FlagStatMetrics]:
    """-> (failed_vendor_quality, passed_vendor_quality) metric structs."""
    failed = ((b.flags & schema.FLAG_FAILED_QC) != 0) & b.valid
    passed = ((b.flags & schema.FLAG_FAILED_QC) == 0) & b.valid
    return _metrics_for(b, failed), _metrics_for(b, passed)


def flagstat(b: ReadBatch) -> tuple[FlagStatMetrics, FlagStatMetrics]:
    failed, passed = flagstat_device(b.to_device())
    return failed.to_ints(), passed.to_ints()


def format_flagstat(failed: FlagStatMetrics, passed: FlagStatMetrics) -> str:
    """samtools-flagstat-style text report, matching the reference CLI's
    format string (adam-cli FlagStat.scala:70-112): all percentages are
    over `total`, and a zero denominator prints 0.00%."""
    def pct(num, den):
        return f"{100.0 * num / den:.2f}%" if den else "0.00%"

    p, f = passed, failed
    lines = [
        f"{p.total} + {f.total} in total (QC-passed reads + QC-failed reads)",
        f"{p.duplicates_primary.total} + {f.duplicates_primary.total} primary duplicates",
        f"{p.duplicates_primary.both_mapped} + {f.duplicates_primary.both_mapped} "
        "primary duplicates - both read and mate mapped",
        f"{p.duplicates_primary.only_read_mapped} + {f.duplicates_primary.only_read_mapped} "
        "primary duplicates - only read mapped",
        f"{p.duplicates_primary.cross_chromosome} + {f.duplicates_primary.cross_chromosome} "
        "primary duplicates - cross chromosome",
        f"{p.duplicates_secondary.total} + {f.duplicates_secondary.total} secondary duplicates",
        f"{p.duplicates_secondary.both_mapped} + {f.duplicates_secondary.both_mapped} "
        "secondary duplicates - both read and mate mapped",
        f"{p.duplicates_secondary.only_read_mapped} + {f.duplicates_secondary.only_read_mapped} "
        "secondary duplicates - only read mapped",
        f"{p.duplicates_secondary.cross_chromosome} + {f.duplicates_secondary.cross_chromosome} "
        "secondary duplicates - cross chromosome",
        f"{p.mapped} + {f.mapped} mapped ({pct(p.mapped, p.total)}:{pct(f.mapped, f.total)})",
        f"{p.paired_in_sequencing} + {f.paired_in_sequencing} paired in sequencing",
        f"{p.read1} + {f.read1} read1",
        f"{p.read2} + {f.read2} read2",
        f"{p.properly_paired} + {f.properly_paired} properly paired "
        f"({pct(p.properly_paired, p.total)}:{pct(f.properly_paired, f.total)})",
        f"{p.with_self_and_mate_mapped} + {f.with_self_and_mate_mapped} "
        "with itself and mate mapped",
        f"{p.singleton} + {f.singleton} singletons "
        f"({pct(p.singleton, p.total)}:{pct(f.singleton, f.total)})",
        f"{p.with_mate_mapped_to_diff_chromosome} + "
        f"{f.with_mate_mapped_to_diff_chromosome} with mate mapped to a different chr",
        f"{p.with_mate_mapped_to_diff_chromosome_mapq5} + "
        f"{f.with_mate_mapped_to_diff_chromosome_mapq5} "
        "with mate mapped to a different chr (mapQ>=5)",
    ]
    return "\n".join(lines)
