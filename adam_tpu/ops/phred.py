"""Phred quality <-> probability conversions.

Device analog of ``util/PhredUtils.scala:22-40``: the 256-entry lookup
tables become constant arrays gathered on device; conversions back to
phred use the same round(-10*log10(p)) rule.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Constant tables (f64 so Q40+ stays exact; gathers are cheap).
PHRED_TO_ERROR = 10.0 ** (-np.arange(256) / 10.0)
PHRED_TO_SUCCESS = 1.0 - PHRED_TO_ERROR


def phred_to_error_probability(phred):
    """phred (int array) -> error probability."""
    return jnp.asarray(PHRED_TO_ERROR)[jnp.clip(phred, 0, 255)]


def phred_to_success_probability(phred):
    return jnp.asarray(PHRED_TO_SUCCESS)[jnp.clip(phred, 0, 255)]


def error_probability_to_phred(p):
    """error probability -> phred, rounded like the reference:
    Scala math.round = floor(x + 0.5), not banker's rounding."""
    return jnp.floor(-10.0 * jnp.log10(p) + 0.5).astype(jnp.int32)


def success_probability_to_phred(p):
    return error_probability_to_phred(1.0 - p)
