"""On-device column packing — [N, L] matrices -> flat Arrow-layout buffers.

The streamed pipeline's pass C used to fetch each window's recalibrated
quals as a dense ``u8[N, L]`` matrix and re-walk it on the host into the
Arrow string layout (one flat byte buffer + offsets).  The device
already knows every row's true length, so the kernel here does the
compaction *before* the bytes cross the link: scatter each row's
in-read prefix at its exclusive-cumsum offset, ship ``packed[:total]``
— the exact column payload, padding lanes never cross d2h — and hand
the host a buffer that IS the Arrow data buffer (io/arrow_pack.py wraps
it zero-copy).  Offsets never cross at all: the host holds the same
lengths and rebuilds them with one cumsum.

The same shrink-the-d2h move as PR 8's barrier-2 mesh psum, applied to
the pass-C apply fetch (the ROADMAP "kill the apply/encode/write tail"
item): on trimmed/short-read libraries — adapter-trimmed short-insert
runs, small-RNA reads at a fraction of the instrument read length —
``sum(lengths)`` is several times smaller than ``N*L``, and the ledger's
pass-C ``device.d2h.bytes`` entry shrinks by the same factor.

``pack_rows_body`` is a plain traceable function so mesh ``shard_map``
bodies can fuse it after the apply gather (each shard packs its own row
block; the host concatenates shard payloads in shard order, which is
row order).  ``pack_rows_np`` is the bit-parity host twin used by the
fallback paths and the differential tests.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from adam_tpu.formats import schema


def pack_lengths(lengths, valid, has_qual=None) -> np.ndarray:
    """Per-row packed byte counts for a qual/base column: the true read
    length for rows that carry the column, 0 for padding/invalid rows
    (and, when ``has_qual`` is given, for rows whose qual was ``'*'`` —
    those rows are NULL in the Arrow column and contribute no bytes)."""
    lens = np.where(np.asarray(valid), np.asarray(lengths), 0)
    if has_qual is not None:
        lens = np.where(np.asarray(has_qual), lens, 0)
    return lens.astype(np.int64)


def pack_rows_body(mat, lens, size: int):
    """Traceable pack: scatter row prefixes ``mat[i, :lens[i]]`` at
    exclusive-cumsum offsets into a flat ``[size]`` buffer.

    ``size`` must be static and >= ``sum(lens)``; callers use the
    window's dense grid area (``g * gl``) so the jit cache sees no new
    shapes — the *fetch* is what shrinks (``packed[:total]``), not the
    device allocation, which aliases the matrix footprint it replaces.
    Padding positions scatter to index ``size`` and drop.

    Backend-selected at trace time (``ops/kernel_backend``): the XLA
    scatter below is the bit-parity reference; ``pallas`` swaps in
    :func:`pack_rows_pallas`.  Every jit that can hold this body keys
    its cache on the backend, so flipping the env retraces.
    """
    from adam_tpu.ops.kernel_backend import kernel_backend

    if kernel_backend() == "pallas":
        return pack_rows_pallas(mat, lens, size)
    n, w = mat.shape
    lens = lens.astype(jnp.int64)
    offsets = jnp.cumsum(lens) - lens  # exclusive row starts
    col = jnp.arange(w, dtype=jnp.int64)[None, :]
    in_row = col < lens[:, None]
    idx = jnp.where(in_row, offsets[:, None] + col, size)
    return (
        jnp.zeros(size, mat.dtype)
        .at[idx.ravel()]
        .set(mat.ravel(), mode="drop")
    )


def _pack_block_kernel(mat_ref, lens_ref, offs_ref, out_ref):
    """One pallas grid step: scatter one row block's prefixes into the
    flat VMEM payload (revisited across steps; zeroed at step 0 so
    bucket-tail padding matches the XLA path's ``jnp.zeros`` base)."""
    import jax as _jax
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _zero():
        out_ref[...] = jnp.zeros_like(out_ref)

    br, w = mat_ref.shape

    def row_body(r, carry):
        ln = lens_ref[r, 0]
        off = offs_ref[r, 0]

        def col_body(j, carry):
            @pl.when(j < ln)
            def _store():
                out_ref[off + j] = mat_ref[r, j]

            return carry

        return _jax.lax.fori_loop(0, w, col_body, carry)

    _jax.lax.fori_loop(0, br, row_body, 0)


def pack_rows_pallas(mat, lens, size: int):
    """Pallas twin of the XLA row-prefix pack scatter: the grid
    pipeline double-buffers each row block's DMA while the previous
    block scatters into the flat payload held in VMEM.  Row offsets
    (exclusive cumsum) stay an XLA prefix-sum — only the memory-bound
    scatter loop is hand-scheduled.  Bitwise identical to the XLA
    body: same values at the same offsets, zeros elsewhere."""
    from jax.experimental import pallas as pl

    from adam_tpu.ops.kernel_backend import pallas_interpret
    from adam_tpu.ops.pallas_observe import _block_rows

    n, w = mat.shape
    if n == 0 or w == 0 or size == 0:
        return jnp.zeros(size, mat.dtype)
    lens32 = lens.astype(jnp.int32).reshape(n, 1)
    offs32 = (jnp.cumsum(lens.astype(jnp.int32))
              - lens.astype(jnp.int32)).reshape(n, 1)
    br = _block_rows(n)
    return pl.pallas_call(
        _pack_block_kernel,
        out_shape=jax.ShapeDtypeStruct((size,), mat.dtype),
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, w), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((size,), lambda i: (0,)),
        interpret=pallas_interpret(),
    )(mat, lens32, offs32)


#: Per-backend jits for the standalone pack entry — the body branches
#: on the backend at trace time, so a single module-level ``jax.jit``
#: would pin whichever backend traced first.
_PACK_JITS: dict = {}


def pack_rows_kernel(mat, lens, size: int):
    """Jit entry point over :func:`pack_rows_body` (standalone packing
    of an already-resident matrix; the apply path fuses the body into
    its own kernel instead — one dispatch, no intermediate).  Resolves
    the active kernel backend and jits per backend."""
    from adam_tpu.ops.kernel_backend import kernel_backend

    be = kernel_backend()
    fn = _PACK_JITS.get(be)
    if fn is None:
        fn = _PACK_JITS.setdefault(
            be, partial(jax.jit, static_argnames=("size",))(pack_rows_body)
        )
    return fn(mat, lens, size)


def pack_rows_np(mat: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Host twin of :func:`pack_rows_body` (exact total, no padding):
    one boolean mask-select in row-major order — concatenated row
    prefixes, bitwise the device scatter's first ``sum(lens)`` bytes."""
    mat = np.ascontiguousarray(mat)
    lens = np.asarray(lens, np.int64)
    n, w = mat.shape if mat.ndim == 2 else (len(lens), 0)
    if n == 0 or w == 0:
        return np.zeros(0, mat.dtype)
    mask = np.arange(w)[None, :] < lens[:, None]
    return mat[mask]


def sanger_body(quals):
    """Traceable SANGER (phred+33) encode of a qual matrix — the device
    twin of ``schema.QUAL_SANGER_LUT256`` (min(q, 93) + 33), so packed
    qual buffers come home already ASCII, ready to BE the Arrow column
    data."""
    return (
        jnp.minimum(quals.astype(jnp.int32), 93) + schema.SANGER_OFFSET
    ).astype(jnp.uint8)


def base_decode_body(bases):
    """Traceable base decode of a code matrix — the device twin of
    ``schema.BASE_DECODE_LUT256`` (code -> ACGTN. ASCII), so packed
    base buffers come home ready to BE the Arrow ``sequence`` column
    data (the bases half of the packed tail: with the window resident
    on device, decoding there costs one tiny gather instead of a host
    LUT walk per part)."""
    return jnp.asarray(schema.BASE_DECODE_LUT256)[bases.astype(jnp.uint8)]


def pack_mask_bits(mask: np.ndarray) -> np.ndarray:
    """Bit-pack a host boolean [N, L] mask along its lane axis ->
    u8[N, ceil(L/8)] (``np.packbits`` big-endian layout).

    The resident-window observe dispatch ships its per-pass masks
    (residue_ok / is_mismatch — the only per-residue inputs that are
    genuinely host-derived, from the MD-tag walk) packed 8x, so the
    observe pass's h2d ledger entry stays ~0 next to the one ingest
    placement.  :func:`unpack_mask_body` is the device-side inverse."""
    return np.packbits(np.asarray(mask, bool), axis=1)


def unpack_mask_body(packed, n_cols: int):
    """Traceable inverse of :func:`pack_mask_bits`: u8[N, ceil(L/8)] ->
    bool[N, n_cols] (``n_cols`` static; trailing pad bits drop)."""
    shifts = (7 - jnp.arange(8, dtype=jnp.uint8))[None, None, :]
    bits = (packed[:, :, None] >> shifts) & jnp.uint8(1)
    n = packed.shape[0]
    return bits.reshape(n, -1)[:, :n_cols].astype(bool)


def fetch_grid(nbytes: int, floor: int = 4096) -> int:
    """Quantize a packed-payload byte count up to a coarse fetch
    bucket: the next multiple of 1/16th of its power-of-two scale
    (over-fetch < 6.25%), floored at 4 KiB.

    The d2h fetch is a device-side slice, and every distinct slice
    size is a distinct XLA program — per-window exact sizes would
    compile once per window (the same mid-run-compile trap the row
    grid quantization in ``formats/batch.grid_rows`` exists to avoid).
    Bucketing collapses a run's slice sizes to a handful of shapes;
    the host trims the tail bytes after the fetch."""
    n = max(int(nbytes), 1)
    q = max(floor, 1 << max(0, n.bit_length() - 4))
    return -(-n // q) * q


def packed_columns_enabled(default: bool = True) -> bool:
    """Resolve the ``ADAM_TPU_PACKED_COLS`` toggle for the pass-C
    packed-column fetch: ``auto``/unset -> ``default`` (on wherever the
    device apply runs), ``1/on/true`` and ``0/off/false`` force; a typo
    warns and keeps the default (``utils/retry.env_toggle``, the shared
    tuning-var contract)."""
    from adam_tpu.utils.retry import env_toggle

    return env_toggle("ADAM_TPU_PACKED_COLS", default)
