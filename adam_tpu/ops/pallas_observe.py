"""Pallas port of the BQSR observe scatter-add.

The observe pass is memory-bound: per residue it reads one i32
covariate key plus two *bits* (residue-ok / is-mismatch, shipped
bit-packed by the resident-window dispatch) and bumps two histogram
counters.  The XLA lowering materializes the unpacked boolean masks
and runs a generic scatter; the Pallas kernel here instead streams the
bit-packed masks straight out of HBM — the grid pipeline double-buffers
each row block's DMA while the previous block accumulates — unpacks
bits in-register, and accumulates the (total, mism) histogram in VMEM,
which is revisited across grid steps and only written back once.

Bit-parity contract: given the same i32 keys and masks this produces
exactly the histograms of ``bqsr.observe_kernel``'s scatter-add (i32
accumulation, cast to i64 by the caller).  The selector in
``ops/kernel_backend.py`` keeps XLA the default; off-TPU the kernel
runs with ``interpret=True`` so the parity tests stay hermetic on CPU.

Keys are precomputed by the caller (``bqsr.observe_packed_body``'s
pallas branch) because the covariate math — cycles, dinucs, read-group
fold — is compute-light and fuses fine under XLA; only the
scatter-add inner loop is worth hand-scheduling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from adam_tpu.ops.kernel_backend import pallas_interpret


def _block_rows(n: int) -> int:
    """Largest row-block size in {8, 4, 2, 1} dividing ``n`` — pallas
    grid blocks must tile the row axis exactly (the grid quantization
    in ``formats/batch.grid_rows`` makes 8 the common case)."""
    for br in (8, 4, 2, 1):
        if n % br == 0:
            return br
    return 1


def _hist_block_kernel(keys_ref, res_ref, mm_ref, rdok_ref,
                       total_ref, mism_ref):
    """One grid step: accumulate one row block into the VMEM histogram.

    ``total_ref``/``mism_ref`` map the full histogram every step
    (revisited output block): zeroed at step 0, accumulated across
    steps, flushed once at the end."""
    @pl.when(pl.program_id(0) == 0)
    def _zero():
        total_ref[...] = jnp.zeros_like(total_ref)
        mism_ref[...] = jnp.zeros_like(mism_ref)

    br, lmax = keys_ref.shape

    def row_body(r, carry):
        rd = rdok_ref[r, 0]

        def col_body(j, carry):
            byte_r = res_ref[r, j // 8].astype(jnp.int32)
            byte_m = mm_ref[r, j // 8].astype(jnp.int32)
            shift = 7 - (j % 8)
            res_bit = (byte_r >> shift) & 1
            mm_bit = (byte_m >> shift) & 1
            inc = (res_bit != 0) & (rd != 0)
            k = keys_ref[r, j]

            @pl.when(inc)
            def _bump_total():
                total_ref[k] = total_ref[k] + 1

            @pl.when(inc & (mm_bit != 0))
            def _bump_mism():
                mism_ref[k] = mism_ref[k] + 1

            return carry

        return jax.lax.fori_loop(0, lmax, col_body, carry)

    jax.lax.fori_loop(0, br, row_body, 0)


def observe_hist_pallas(flat_key, res_bits, mm_bits, read_ok,
                        size: int):
    """(total, mism) i32[size] histograms over bit-packed masks.

    ``flat_key``: i32[N, L] fused covariate keys (always in-range —
    the covariate math bounds every factor; excluded residues are
    simply never added).  ``res_bits``/``mm_bits``: u8[N, ceil(L/8)]
    from ``colpack.pack_mask_bits``.  ``read_ok``: bool[N].
    """
    n, lmax = flat_key.shape
    if n == 0 or lmax == 0:
        z = jnp.zeros(size, jnp.int32)
        return z, z
    br = _block_rows(n)
    lb = res_bits.shape[1]
    rdok = read_ok.astype(jnp.int32).reshape(n, 1)
    return pl.pallas_call(
        _hist_block_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((size,), jnp.int32),
            jax.ShapeDtypeStruct((size,), jnp.int32),
        ),
        grid=(n // br,),
        in_specs=[
            pl.BlockSpec((br, lmax), lambda i: (i, 0)),
            pl.BlockSpec((br, lb), lambda i: (i, 0)),
            pl.BlockSpec((br, lb), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((size,), lambda i: (0,)),
            pl.BlockSpec((size,), lambda i: (0,)),
        ),
        interpret=pallas_interpret(),
    )(flat_key, res_bits, mm_bits, rdok)
