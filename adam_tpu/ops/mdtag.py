"""MD ("mismatchingPositions") tag engine.

Host-side implementation of the reference's ``util/MdTag.scala``: parse
(:47-109), regeneration from a (read, reference, cigar) alignment
(:255-304), ``moveAlignment`` after realignment (:148-244), reference
reconstruction ``getReference`` (:410-458) and the canonical ``toString``
FSM (:466-532).  Equality = (start, canonical string), as in the
reference.

The device-facing entry point is :func:`batch_md_arrays`, which turns a
batch's MD strings into per-base columns (is-mismatch mask + reference
base codes) that BQSR and realignment kernels consume.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import numpy as np

from adam_tpu.formats import schema

_DIGITS = re.compile(r"[0-9]+")
# Full IUPAC ambiguity alphabet, as the reference's basesPattern accepts
# (util/MdTag.scala digitPattern/basesPattern definitions).
_BASES = re.compile(r"[AGCTNUKMRSWBVHDXY]+")


def parse_cigar(cigar: str) -> list[tuple[int, str]]:
    """'4M2D3M' -> [(4,'M'), (2,'D'), (3,'M')]; '*' -> []."""
    if not cigar or cigar == "*":
        return []
    out = []
    num = 0
    for ch in cigar:
        if ch.isdigit():
            num = num * 10 + ord(ch) - 48
        else:
            out.append((num, ch))
            num = 0
    return out


@dataclass
class MdTag:
    start: int
    matches: list = field(default_factory=list)  # [(start, end)) ref ranges
    mismatches: dict = field(default_factory=dict)  # ref pos -> ref base
    deletions: dict = field(default_factory=dict)  # ref pos -> ref base

    # ----------------------------------------------------------- constructors
    @staticmethod
    def parse(md: str, reference_start: int) -> "MdTag":
        """Parse an MD string at a given alignment start."""
        tag = MdTag(reference_start)
        if md is None or md == "0" or md == "":
            return tag
        s = md.upper()
        offset = 0
        pos = reference_start

        def read_matches():
            nonlocal offset, pos
            m = _DIGITS.match(s, offset)
            if not m:
                raise ValueError(f"malformed MD tag {md!r} at offset {offset}")
            length = int(m.group())
            if length > 0:
                tag.matches.append((pos, pos + length))
            offset = m.end()
            pos += length

        read_matches()
        while offset < len(s):
            if s[offset] == "^":
                offset += 1
                m = _BASES.match(s, offset)
                if not m:
                    raise ValueError(f"malformed MD deletion in {md!r}")
                for base in m.group():
                    tag.deletions[pos] = base
                    pos += 1
                offset = m.end()
            else:
                m = _BASES.match(s, offset)
                if not m:
                    raise ValueError(f"malformed MD mismatch in {md!r}")
                for base in m.group():
                    tag.mismatches[pos] = base
                    pos += 1
                offset = m.end()
            read_matches()
        return tag

    @staticmethod
    def from_alignment(
        read: str, reference: str, cigar: str, start: int
    ) -> "MdTag":
        """Generate the MD tag of aligning ``read`` against ``reference``
        (reference string starting at the alignment start)."""
        match_count = 0
        del_count = 0
        out = ""
        read_pos = 0
        ref_pos = 0
        for length, op in parse_cigar(cigar):
            if op in "M=X":
                for _ in range(length):
                    if read[read_pos] == reference[ref_pos]:
                        match_count += 1
                    else:
                        out += str(match_count) + reference[ref_pos]
                        match_count = 0
                    read_pos += 1
                    ref_pos += 1
                    del_count = 0
            elif op == "D":
                for _ in range(length):
                    if del_count == 0:
                        out += str(match_count) + "^"
                    out += reference[ref_pos]
                    match_count = 0
                    del_count += 1
                    ref_pos += 1
            elif op in "ISHP":
                if op in "IS":
                    read_pos += length
            else:
                raise ValueError(f"cannot handle CIGAR op {op} in MD generation")
        out += str(match_count)
        return MdTag.parse(out, start)

    @staticmethod
    def move_alignment(
        reference: str,
        sequence: str,
        new_cigar: str,
        read_start: int,
    ) -> "MdTag":
        """Recompute the tag for a new alignment of ``sequence`` against
        ``reference`` (string beginning at ``read_start``)."""
        tag = MdTag(read_start)
        ref_pos = 0
        read_pos = 0
        for length, op in parse_cigar(new_cigar):
            if op == "M":
                range_start = 0
                in_match = False
                for _ in range(length):
                    if reference[ref_pos] == sequence[read_pos]:
                        if not in_match:
                            range_start = ref_pos
                            in_match = True
                    else:
                        if in_match:
                            tag.matches.append(
                                (range_start + read_start, ref_pos + read_start)
                            )
                            in_match = False
                        tag.mismatches[ref_pos + read_start] = reference[ref_pos]
                    read_pos += 1
                    ref_pos += 1
                if in_match:
                    tag.matches.append(
                        (range_start + read_start, ref_pos + read_start)
                    )
            elif op == "D":
                for _ in range(length):
                    tag.deletions[ref_pos + read_start] = reference[ref_pos]
                    ref_pos += 1
            elif op in "ISHP":
                if op in "IS":
                    read_pos += length
            else:
                raise ValueError(f"cannot handle CIGAR op {op}")
        return tag

    # --------------------------------------------------------------- queries
    def is_match(self, pos: int) -> bool:
        return any(s <= pos < e for s, e in self.matches)

    def mismatched_base(self, pos: int):
        return self.mismatches.get(pos)

    def deleted_base(self, pos: int):
        return self.deletions.get(pos)

    def end(self) -> int:
        """Largest reference position covered (inclusive)."""
        candidates = [e - 1 for _, e in self.matches]
        candidates += list(self.mismatches)
        candidates += list(self.deletions)
        return max(candidates) if candidates else self.start

    def get_reference(self, read_sequence: str, cigar: str) -> str:
        """Reconstruct the reference over the aligned span from the read."""
        ref_pos = self.start
        read_pos = 0
        out = []
        for length, op in parse_cigar(cigar):
            if op in "M=X":
                for _ in range(length):
                    base = self.mismatches.get(ref_pos)
                    out.append(base if base else read_sequence[read_pos])
                    read_pos += 1
                    ref_pos += 1
            elif op == "D":
                for _ in range(length):
                    base = self.deletions.get(ref_pos)
                    if base is None:
                        raise ValueError(
                            f"no deleted base recorded at ref pos {ref_pos}"
                        )
                    out.append(base)
                    ref_pos += 1
            elif op in "IS":
                read_pos += length
            elif op in "HP":
                pass
            else:
                raise ValueError(f"cannot handle CIGAR op {op}")
        return "".join(out)

    # ------------------------------------------------------------- emission
    def to_string(self) -> str:
        if not self.matches and not self.mismatches and not self.deletions:
            return "0"
        out = []
        last_was_match = False
        last_was_deletion = False
        match_run = 0
        for i in range(self.start, self.end() + 1):
            if self.is_match(i):
                match_run = match_run + 1 if last_was_match else 1
                last_was_match = True
                last_was_deletion = False
            elif i in self.deletions:
                if not last_was_deletion:
                    out.append(str(match_run) if last_was_match else "0")
                    out.append("^")
                    last_was_match = False
                    last_was_deletion = True
                out.append(self.deletions[i])
            else:
                out.append(str(match_run) if last_was_match else "0")
                out.append(self.mismatches[i])
                last_was_match = False
                last_was_deletion = False
        out.append(str(match_run) if last_was_match else "0")
        return "".join(out)

    __str__ = to_string

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, MdTag)
            and self.start == other.start
            and self.to_string() == other.to_string()
        )


def batch_md_arrays(batch, sidecar) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-base MD-derived columns for a batch.

    Returns (is_mismatch bool[N, L], ref_codes u8[N, L], has_md bool[N]):
    for each *read* position of an aligned base, whether it mismatches the
    reference and the reference base code there (= read base on match, MD
    base on mismatch).  Insertions/soft-clips get ref code BASE_PAD and
    is_mismatch False — the per-residue view BQSR's covariates consume
    (DecadentRead.Residue semantics, rich/DecadentRead.scala:77-116).
    """
    b = batch.to_numpy()
    N, L = b.bases.shape
    is_mm = np.zeros((N, L), dtype=bool)
    ref_codes = np.full((N, L), schema.BASE_PAD, dtype=np.uint8)
    has_md = np.zeros(N, dtype=bool)
    for i in range(N):
        if not b.valid[i]:
            continue
        md = sidecar.md[i]
        if md is None:
            continue
        has_md[i] = True
        tag = MdTag.parse(md, int(b.start[i]))
        cigar = schema.decode_cigar(
            b.cigar_ops[i], b.cigar_lens[i], int(b.cigar_n[i])
        )
        read_pos = 0
        ref_pos = int(b.start[i])
        for length, op in parse_cigar(cigar):
            if op in "M=X":
                for _ in range(length):
                    base = tag.mismatches.get(ref_pos)
                    if base is not None:
                        is_mm[i, read_pos] = True
                        ref_codes[i, read_pos] = schema.BASE_ENCODE_LUT[ord(base)]
                    else:
                        ref_codes[i, read_pos] = b.bases[i, read_pos]
                    read_pos += 1
                    ref_pos += 1
            elif op in "DN":
                ref_pos += length
            elif op in "IS":
                read_pos += length
    return is_mm, ref_codes, has_md
