"""MD ("mismatchingPositions") tag engine.

Host-side implementation of the reference's ``util/MdTag.scala``: parse
(:47-109), regeneration from a (read, reference, cigar) alignment
(:255-304), ``moveAlignment`` after realignment (:148-244), reference
reconstruction ``getReference`` (:410-458) and the canonical ``toString``
FSM (:466-532).  Equality = (start, canonical string), as in the
reference.

The device-facing entry point is :func:`batch_md_arrays`, which turns a
batch's MD strings into per-base columns (is-mismatch mask + reference
base codes) that BQSR and realignment kernels consume.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import numpy as np

from adam_tpu.formats import schema

_DIGITS = re.compile(r"[0-9]+")
# Full IUPAC ambiguity alphabet, as the reference's basesPattern accepts
# (util/MdTag.scala digitPattern/basesPattern definitions).
_BASES = re.compile(r"[AGCTNUKMRSWBVHDXY]+")


def parse_cigar(cigar: str) -> list[tuple[int, str]]:
    """'4M2D3M' -> [(4,'M'), (2,'D'), (3,'M')]; '*' -> []."""
    if not cigar or cigar == "*":
        return []
    out = []
    num = 0
    for ch in cigar:
        if ch.isdigit():
            num = num * 10 + ord(ch) - 48
        else:
            out.append((num, ch))
            num = 0
    return out


@dataclass
class MdTag:
    start: int
    matches: list = field(default_factory=list)  # [(start, end)) ref ranges
    mismatches: dict = field(default_factory=dict)  # ref pos -> ref base
    deletions: dict = field(default_factory=dict)  # ref pos -> ref base

    # ----------------------------------------------------------- constructors
    @staticmethod
    def parse(md: str, reference_start: int) -> "MdTag":
        """Parse an MD string at a given alignment start."""
        tag = MdTag(reference_start)
        if md is None or md == "0" or md == "":
            return tag
        s = md.upper()
        offset = 0
        pos = reference_start

        def read_matches():
            nonlocal offset, pos
            m = _DIGITS.match(s, offset)
            if not m:
                raise ValueError(f"malformed MD tag {md!r} at offset {offset}")
            length = int(m.group())
            if length > 0:
                tag.matches.append((pos, pos + length))
            offset = m.end()
            pos += length

        read_matches()
        while offset < len(s):
            if s[offset] == "^":
                offset += 1
                m = _BASES.match(s, offset)
                if not m:
                    raise ValueError(f"malformed MD deletion in {md!r}")
                for base in m.group():
                    tag.deletions[pos] = base
                    pos += 1
                offset = m.end()
            else:
                m = _BASES.match(s, offset)
                if not m:
                    raise ValueError(f"malformed MD mismatch in {md!r}")
                for base in m.group():
                    tag.mismatches[pos] = base
                    pos += 1
                offset = m.end()
            read_matches()
        return tag

    @staticmethod
    def from_alignment(
        read: str, reference: str, cigar: str, start: int
    ) -> "MdTag":
        """Generate the MD tag of aligning ``read`` against ``reference``
        (reference string starting at the alignment start)."""
        match_count = 0
        del_count = 0
        out = ""
        read_pos = 0
        ref_pos = 0
        for length, op in parse_cigar(cigar):
            if op in "M=X":
                for _ in range(length):
                    if read[read_pos] == reference[ref_pos]:
                        match_count += 1
                    else:
                        out += str(match_count) + reference[ref_pos]
                        match_count = 0
                    read_pos += 1
                    ref_pos += 1
                    del_count = 0
            elif op == "D":
                for _ in range(length):
                    if del_count == 0:
                        out += str(match_count) + "^"
                    out += reference[ref_pos]
                    match_count = 0
                    del_count += 1
                    ref_pos += 1
            elif op in "ISHP":
                if op in "IS":
                    read_pos += length
            else:
                raise ValueError(f"cannot handle CIGAR op {op} in MD generation")
        out += str(match_count)
        return MdTag.parse(out, start)

    @staticmethod
    def move_alignment(
        reference: str,
        sequence: str,
        new_cigar: str,
        read_start: int,
    ) -> "MdTag":
        """Recompute the tag for a new alignment of ``sequence`` against
        ``reference`` (string beginning at ``read_start``)."""
        tag = MdTag(read_start)
        ref_pos = 0
        read_pos = 0
        for length, op in parse_cigar(new_cigar):
            if op == "M":
                rseg = reference[ref_pos : ref_pos + length]
                sseg = sequence[read_pos : read_pos + length]
                if len(rseg) < length or len(sseg) < length:
                    raise IndexError("string index out of range")
                if rseg == sseg:  # whole-segment match, the common case
                    tag.matches.append(
                        (ref_pos + read_start, ref_pos + length + read_start)
                    )
                else:
                    # byte-compare the segment once; match runs are the
                    # gaps between mismatch positions
                    a = np.frombuffer(rseg.encode("ascii"), np.uint8)
                    bb = np.frombuffer(sseg.encode("ascii"), np.uint8)
                    mm = np.flatnonzero(a != bb)
                    for j in mm:
                        tag.mismatches[ref_pos + int(j) + read_start] = rseg[int(j)]
                    prev = -1
                    for j in [int(x) for x in mm] + [length]:
                        if j > prev + 1:
                            tag.matches.append(
                                (ref_pos + prev + 1 + read_start,
                                 ref_pos + j + read_start)
                            )
                        prev = j
                read_pos += length
                ref_pos += length
            elif op == "D":
                dseg = reference[ref_pos : ref_pos + length]
                if len(dseg) < length:
                    raise IndexError("string index out of range")
                for j, ch in enumerate(dseg):
                    tag.deletions[ref_pos + j + read_start] = ch
                ref_pos += length
            elif op in "ISHP":
                if op in "IS":
                    read_pos += length
            else:
                raise ValueError(f"cannot handle CIGAR op {op}")
        return tag

    # --------------------------------------------------------------- queries
    def is_match(self, pos: int) -> bool:
        return any(s <= pos < e for s, e in self.matches)

    def mismatched_base(self, pos: int):
        return self.mismatches.get(pos)

    def deleted_base(self, pos: int):
        return self.deletions.get(pos)

    def end(self) -> int:
        """Largest reference position covered (inclusive)."""
        candidates = [e - 1 for _, e in self.matches]
        candidates += list(self.mismatches)
        candidates += list(self.deletions)
        return max(candidates) if candidates else self.start

    def get_reference(self, read_sequence: str, cigar) -> str:
        """Reconstruct the reference over the aligned span from the read.

        ``cigar`` may be a string or an already-parsed ``[(len, op)]``
        list.  M/=/X segments are emitted as one slice patched at the
        (few) recorded mismatch positions rather than a per-base loop."""
        ref_pos = self.start
        read_pos = 0
        out = []
        elems = parse_cigar(cigar) if isinstance(cigar, str) else cigar
        for length, op in elems:
            if op in "M=X":
                seg = read_sequence[read_pos : read_pos + length]
                if len(seg) < length:
                    # corrupt alignment: the CIGAR span overruns the
                    # read; fail loudly (move_alignment does the same)
                    # instead of emitting a silently truncated reference
                    raise IndexError(
                        f"CIGAR {op}-segment of length {length} overruns "
                        f"read of length {len(read_sequence)} at read "
                        f"position {read_pos}"
                    )
                if self.mismatches:
                    patches = [
                        (p - ref_pos, base)
                        for p, base in self.mismatches.items()
                        if ref_pos <= p < ref_pos + length and base
                    ]
                    if patches:
                        lseg = list(seg)
                        for off, base in patches:
                            lseg[off] = base
                        seg = "".join(lseg)
                out.append(seg)
                read_pos += length
                ref_pos += length
            elif op == "D":
                for _ in range(length):
                    base = self.deletions.get(ref_pos)
                    if base is None:
                        raise ValueError(
                            f"no deleted base recorded at ref pos {ref_pos}"
                        )
                    out.append(base)
                    ref_pos += 1
            elif op in "IS":
                read_pos += length
            elif op in "HP":
                pass
            else:
                raise ValueError(f"cannot handle CIGAR op {op}")
        return "".join(out)

    # ------------------------------------------------------------- emission
    def to_string(self) -> str:
        """Event-walk emission: O(mismatches + deletions), not
        O(span x match-intervals) — positions between events are match
        run length by construction."""
        if not self.matches and not self.mismatches and not self.deletions:
            return "0"
        start, end = self.start, self.end()
        events = sorted(
            [(p, False, b) for p, b in self.mismatches.items()]
            + [(p, True, b) for p, b in self.deletions.items()]
        )
        out = []
        prev_end = start  # next unemitted reference position
        last_was_deletion = False
        for p, is_del, base in events:
            run = p - prev_end
            if is_del:
                if run > 0 or not last_was_deletion:
                    out.append(str(run))
                    out.append("^")
                out.append(base)
                last_was_deletion = True
            else:
                out.append(str(run))
                out.append(base)
                last_was_deletion = False
            prev_end = p + 1
        out.append(str(end + 1 - prev_end))
        return "".join(out)

    __str__ = to_string

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, MdTag)
            and self.start == other.start
            and self.to_string() == other.to_string()
        )


def tokenize_md_column(md_column) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized MD tokenizer over a whole StringColumn of MD tags.

    Returns per-mismatch flat arrays ``(row, ref_off, base_byte)``:
    the batch row of each mismatch, its 0-based reference offset from the
    alignment start, and the reference base (ASCII byte) recorded in the
    MD tag.  Deletion bases (after ``^``) advance the reference offset but
    are not emitted.  Pure numpy — no per-read Python.
    """
    buf = md_column.buf
    offsets = md_column.offsets
    if len(buf) == 0:
        z = np.zeros(0, np.int64)
        return z, z, z.astype(np.uint8)

    is_digit = (buf >= 48) & (buf <= 57)
    is_caret = buf == 94  # '^'
    is_letter = ~is_digit & ~is_caret

    # Only strings containing letters can contribute mismatches; strings
    # that are a plain match count (the common case) are skipped entirely.
    lpos_all = np.flatnonzero(is_letter)
    if len(lpos_all) == 0:
        z = np.zeros(0, np.int64)
        return z, z, z.astype(np.uint8)
    letter_rows = np.unique(
        np.searchsorted(offsets, lpos_all, side="right") - 1
    )
    row_keep = np.zeros(len(offsets) - 1, dtype=bool)
    row_keep[letter_rows] = True

    # ---- number runs (split at string boundaries: tags end with a run) --
    prev_digit = np.zeros(len(buf), dtype=bool)
    prev_digit[1:] = is_digit[:-1]
    run_start = is_digit & ~prev_digit
    starts = offsets[:-1][offsets[:-1] < len(buf)]
    boundary = np.zeros(len(buf), dtype=bool)
    boundary[starts] = True
    run_start |= is_digit & boundary
    # drop bytes of letter-free strings from all token machinery
    byte_keep = np.repeat(row_keep, np.diff(offsets))
    is_digit &= byte_keep
    run_start &= byte_keep

    run_id = np.cumsum(run_start) - 1  # id per byte (valid at digit bytes)
    dpos = np.flatnonzero(is_digit)
    drun = run_id[dpos]
    n_runs = int(run_start.sum())
    run_len = np.bincount(drun, minlength=n_runs)
    run_pos = np.flatnonzero(run_start)  # first byte of each run, in order
    local = dpos - run_pos[drun]
    expo = run_len[drun] - 1 - local
    run_val = np.bincount(
        drun, weights=(buf[dpos] - 48).astype(np.float64) * 10.0 ** expo,
        minlength=n_runs,
    ).astype(np.int64)

    # ---- letters: mismatch vs deletion state ---------------------------
    lpos = np.flatnonzero(is_letter)
    nonletter_idx = np.where(~is_letter, np.arange(len(buf)), -1)
    # force a state reset at string starts so '^' never leaks across tags
    nonletter_idx[starts] = np.maximum(nonletter_idx[starts], starts)
    prev_nonletter = np.maximum.accumulate(nonletter_idx)
    pn = prev_nonletter[lpos]
    is_del = (pn >= 0) & (buf[np.maximum(pn, 0)] == 94)

    # ---- merge tokens in byte order, accumulate reference advance ------
    tok_pos = np.concatenate([run_pos, lpos])
    tok_adv = np.concatenate([run_val, np.ones(len(lpos), np.int64)])
    tok_is_mm = np.concatenate(
        [np.zeros(len(run_pos), bool), ~is_del]
    )
    order = np.argsort(tok_pos, kind="stable")
    tok_pos = tok_pos[order]
    tok_adv = tok_adv[order]
    tok_is_mm = tok_is_mm[order]

    tok_row = np.searchsorted(offsets, tok_pos, side="right") - 1
    csum = np.cumsum(tok_adv)
    ref_off_excl = csum - tok_adv
    # subtract each row's base (exclusive cumsum at its first token)
    n_rows = len(offsets) - 1
    first_tok = np.searchsorted(tok_row, np.arange(n_rows), side="left")
    has_tok = first_tok < len(tok_row)
    base = np.zeros(n_rows, np.int64)
    base[has_tok] = ref_off_excl[np.minimum(first_tok[has_tok], len(tok_row) - 1)]
    ref_off = ref_off_excl - base[tok_row]

    mm = tok_is_mm
    return tok_row[mm], ref_off[mm], buf[tok_pos[mm]]


def batch_md_arrays(
    batch, sidecar, need_ref_codes: bool = True
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-base MD-derived columns for a batch — vectorized.

    Returns (is_mismatch bool[N, L], ref_codes u8[N, L], has_md bool[N]):
    for each *read* position of an aligned base, whether it mismatches the
    reference and the reference base code there (= read base on match, MD
    base on mismatch).  Insertions/soft-clips get ref code BASE_PAD and
    is_mismatch False — the per-residue view BQSR's covariates consume
    (DecadentRead.Residue semantics, rich/DecadentRead.scala:77-116).

    Implementation: one vectorized MD tokenize over the whole column
    (:func:`tokenize_md_column`), then a cumulative-CIGAR coordinate map
    from reference offsets to read positions — no per-read loops (the
    design stance of SURVEY §7: MD-derived masks computed at ingest
    speed, not per call).
    """
    from adam_tpu.formats.strings import StringColumn

    b = batch.to_numpy()
    N, L = b.bases.shape
    if N == 0 or b.cigar_ops.shape[1] == 0:
        ref = np.full((N, L), schema.BASE_PAD, np.uint8) if need_ref_codes else None
        return np.zeros((N, L), bool), ref, np.zeros(N, bool)
    md_col = StringColumn.of(sidecar.md)
    valid = np.asarray(b.valid)
    has_md = md_col.valid[:N] & valid if len(md_col) >= N else np.zeros(N, bool)

    ops = np.asarray(b.cigar_ops)
    lens = np.asarray(b.cigar_lens).astype(np.int64)
    C = ops.shape[1]
    q_consume = schema.CIGAR_CONSUMES_QUERY[np.minimum(ops, 15)].astype(np.int64)
    r_consume = schema.CIGAR_CONSUMES_REF[np.minimum(ops, 15)].astype(np.int64)
    read_adv = lens * q_consume
    ref_adv = lens * r_consume
    cum_read_incl = np.cumsum(read_adv, axis=1)
    cum_ref_incl = np.cumsum(ref_adv, axis=1)
    cum_read_excl = cum_read_incl - read_adv
    cum_ref_excl = cum_ref_incl - ref_adv

    both = (q_consume > 0) & (r_consume > 0)
    ref_codes = None
    if need_ref_codes:
        # aligned-position mask per read position (inside M/=/X ops).
        # Fast path: a single M/=/X op spanning the read (the dominant
        # shape) is pos < length; only the remaining rows walk their ops.
        pos = np.arange(L, dtype=np.int64)
        cigar_n = np.asarray(b.cigar_n)
        simple = (cigar_n == 1) & both[:, 0]
        lengths = np.asarray(b.lengths).astype(np.int64)
        aligned = simple[:, None] & (pos[None, :] < lengths[:, None])
        complex_rows = np.flatnonzero(~simple & (cigar_n > 0))
        if len(complex_rows):
            max_ops = int(cigar_n[complex_rows].max())
            for j in range(min(C, max_ops)):
                rows = complex_rows[both[complex_rows, j]]
                if len(rows) == 0:
                    continue
                lo = cum_read_excl[rows, j][:, None]
                hi = (cum_read_excl[rows, j] + read_adv[rows, j])[:, None]
                aligned[rows] |= (pos[None, :] >= lo) & (pos[None, :] < hi)
        ref_codes = np.where(
            aligned & has_md[:, None], np.asarray(b.bases),
            np.uint8(schema.BASE_PAD),
        ).astype(np.uint8)
    is_mm = np.zeros((N, L), dtype=bool)

    rows, ref_off, base_bytes = tokenize_md_column(md_col)
    keep = has_md[rows] if len(rows) else np.zeros(0, bool)
    rows, ref_off, base_bytes = rows[keep], ref_off[keep], base_bytes[keep]
    if len(rows):
        # op containing each mismatch's reference offset
        j = (cum_ref_incl[rows] <= ref_off[:, None]).sum(axis=1)
        j = np.minimum(j, C - 1)
        in_m = both[rows, j]
        read_pos = cum_read_excl[rows, j] + (ref_off - cum_ref_excl[rows, j])
        ok = in_m & (read_pos >= 0) & (read_pos < L)
        r_, p_ = rows[ok], read_pos[ok]
        is_mm[r_, p_] = True
        if ref_codes is not None:
            ref_codes[r_, p_] = schema.BASE_ENCODE_LUT[base_bytes[ok]]
    return is_mm, ref_codes, has_md


def batch_md_arrays_reference(
    batch, sidecar
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-read oracle implementation of :func:`batch_md_arrays` (slow;
    kept for differential testing of the vectorized path)."""
    b = batch.to_numpy()
    N, L = b.bases.shape
    is_mm = np.zeros((N, L), dtype=bool)
    ref_codes = np.full((N, L), schema.BASE_PAD, dtype=np.uint8)
    has_md = np.zeros(N, dtype=bool)
    for i in range(N):
        if not b.valid[i]:
            continue
        md = sidecar.md[i]
        if md is None:
            continue
        has_md[i] = True
        tag = MdTag.parse(md, int(b.start[i]))
        cigar = schema.decode_cigar(
            b.cigar_ops[i], b.cigar_lens[i], int(b.cigar_n[i])
        )
        read_pos = 0
        ref_pos = int(b.start[i])
        for length, op in parse_cigar(cigar):
            if op in "M=X":
                for _ in range(length):
                    base = tag.mismatches.get(ref_pos)
                    if base is not None:
                        is_mm[i, read_pos] = True
                        ref_codes[i, read_pos] = schema.BASE_ENCODE_LUT[ord(base)]
                    else:
                        ref_codes[i, read_pos] = b.bases[i, read_pos]
                    read_pos += 1
                    ref_pos += 1
            elif op in "DN":
                ref_pos += length
            elif op in "IS":
                read_pos += length
    return is_mm, ref_codes, has_md
