"""k-mer and q-mer counting kernels.

The reference counts k-mers with ``sliding(k)`` + ``reduceByKey``
(rdd/read/AlignmentRecordRDDFunctions.scala:218-226) and quality-weighted
q-mers (Quake-style) in ``correction/ErrorCorrection.scala:43-80``.

TPU formulation: every window of every read is packed into a single
integer key — 3 bits per base so N is representable, k <= 21 fits an i64
— extracted with one gather per window offset (an [N, W, k] gather XLA
vectorizes), then counted by sort + run-length on device.  The cross-chip
combine is a hash-sharded all-to-all (adam_tpu.parallel.kmers).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from adam_tpu.formats import schema
from adam_tpu.formats.batch import ReadBatch
from adam_tpu.ops.phred import phred_to_success_probability
from adam_tpu.utils.transfer import device_fetch

MAX_PACKED_K = 21  # 3 bits/base in a signed i64


@partial(jax.jit, static_argnames=("k",))
def extract_kmers(bases, lengths, valid, k: int):
    """-> (packed i64[N, W], window_valid bool[N, W]) with W = L - k + 1.

    A window is valid when fully inside the read and the row is valid.
    N bases participate (code 4) — matching the reference, which counts
    k-mer *strings* and therefore keeps N-containing k-mers distinct.
    """
    n, L = bases.shape
    W = max(L - k + 1, 1)
    if k > MAX_PACKED_K:
        raise ValueError(f"k={k} exceeds packed maximum {MAX_PACKED_K}")
    offs = jnp.arange(W)[:, None] + jnp.arange(k)[None, :]  # [W, k]
    windows = bases[:, offs].astype(jnp.int64)  # [N, W, k]
    shifts = jnp.arange(k - 1, -1, -1, dtype=jnp.int64) * 3
    packed = jnp.sum(windows << shifts, axis=-1)
    win_valid = (jnp.arange(W)[None, :] + k <= lengths[:, None]) & valid[:, None]
    return packed, win_valid


def pack_kmer_string(s: str) -> int:
    v = 0
    for ch in s:
        v = (v << 3) | int(schema.BASE_ENCODE_LUT[ord(ch)])
    return v


def unpack_kmer(packed: int, k: int) -> str:
    chars = []
    for i in range(k):
        chars.append("ACGTN"[(packed >> (3 * (k - 1 - i))) & 0x7])
    return "".join(chars)


@partial(jax.jit, static_argnames=("k",))
def device_kmer_histogram(bases, lengths, valid, k: int):
    """Sort-based local count: -> (sorted_kmers i64[M], counts i32[M], is_head bool[M]).

    Invalid windows pack to sentinel -1 and sort first; ``is_head`` marks
    the first row of each run of equal keys (excluding the sentinel), so
    (sorted_kmers[is_head], counts[is_head]) is the unique histogram.
    """
    packed, win_valid = extract_kmers(bases, lengths, valid, k)
    flat = jnp.where(win_valid, packed, jnp.int64(-1)).ravel()
    s = jnp.sort(flat)
    is_new = jnp.concatenate([jnp.array([True]), s[1:] != s[:-1]])
    is_head = is_new & (s >= 0)
    # run lengths via segment ids
    seg = jnp.cumsum(is_new) - 1
    counts = jax.ops.segment_sum(
        jnp.ones_like(s, jnp.int32), seg, num_segments=s.shape[0]
    )
    run_counts = counts[seg]  # broadcast back; only head rows meaningful
    return s, run_counts, is_head


def histogram_to_dict(bases, lengths, valid, k: int) -> dict[str, int]:
    """Run the device histogram over any padded base array set and
    decode the unique (kmer string -> count) table."""
    import jax.numpy as jnp

    s, run_counts, is_head = device_kmer_histogram(
        jnp.asarray(bases), jnp.asarray(lengths), jnp.asarray(valid), k
    )
    s, run_counts, is_head = (
        device_fetch(s), device_fetch(run_counts), device_fetch(is_head),
    )
    return {
        unpack_kmer(int(key), k): int(v)
        for key, v in zip(s[is_head], run_counts[is_head])
    }


def count_kmers(batch: ReadBatch, k: int) -> dict[str, int]:
    """Exact k-mer counts over all reads (sequence strings, N included)."""
    if batch.n_rows == 0:
        return {}
    b = batch.to_device()
    return histogram_to_dict(b.bases, b.lengths, b.valid, k)


@partial(jax.jit, static_argnames=("k",))
def device_qmer_weights(bases, quals, lengths, valid, k: int):
    """-> (packed i64[N*W], weight f64[N*W]) with weight = prod of base
    success probabilities (Quake q-mer weight, ErrorCorrection.scala:59-80);
    invalid windows have weight 0 and key -1."""
    packed, win_valid = extract_kmers(bases, lengths, valid, k)
    n, L = bases.shape
    W = packed.shape[1]
    succ = phred_to_success_probability(quals)
    offs = jnp.arange(W)[:, None] + jnp.arange(k)[None, :]
    wins = succ[:, offs]  # [N, W, k]
    weights = jnp.prod(wins, axis=-1)
    flat_keys = jnp.where(win_valid, packed, jnp.int64(-1)).ravel()
    flat_w = jnp.where(win_valid, weights, 0.0).ravel()
    return flat_keys, flat_w


def count_qmers(batch: ReadBatch, k: int) -> dict[str, float]:
    if batch.n_rows == 0:
        return {}
    b = batch.to_device()
    keys, weights = device_qmer_weights(b.bases, b.quals, b.lengths, b.valid, k)
    keys, weights = device_fetch(keys), device_fetch(weights)
    order = np.argsort(keys, kind="stable")
    keys, weights = keys[order], weights[order]
    uniq, start_idx = np.unique(keys, return_index=True)
    sums = np.add.reduceat(weights, start_idx)
    return {
        unpack_kmer(int(key), k): float(w)
        for key, w in zip(uniq, sums)
        if key >= 0
    }
