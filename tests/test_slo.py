"""SLO engine: spec grammar, error-budget burn, durable budget,
slo.burn firing (utils/slo.py, docs/OBSERVABILITY.md "SLOs and error
budgets").

The contract under test: a declarative ``--slo`` spec parses
forgivingly (malformed clauses warn and skip — the tuning-var
contract), completed jobs book good/bad events per matching
objective, the multi-window burn rate fires ``slo.burn`` only when
BOTH rolling windows corroborate, and the cumulative budget survives
an engine restart through ``SLO_BUDGET.json``.
"""

import json
import os

import pytest

from adam_tpu.utils import incidents
from adam_tpu.utils import slo
from adam_tpu.utils import telemetry as tele

TID = "cd" * 8


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """Fresh engine + recorder per test; incident cooldown off."""
    slo._reset_for_tests()
    incidents._reset_for_tests()
    monkeypatch.setenv("ADAM_TPU_INCIDENT_COOLDOWN_S", "0")
    yield
    slo._reset_for_tests()
    incidents._reset_for_tests()


# ---------------------------------------------------------------------------
# grammar
# ---------------------------------------------------------------------------
def test_parse_grammar_all_forms():
    objs = slo.parse_slo_spec(
        "tenantA:p99(sched.job.run)<30s;"
        "tenantB:avail>=0.999,p50(sched.job.run)<500ms;"
        "*:tput(reads.ingested)>=1000/s")
    kinds = [(o.tenant, o.kind) for o in objs]
    assert kinds == [("tenantA", "latency"), ("tenantB", "avail"),
                     ("tenantB", "latency"), ("*", "tput")]
    lat = objs[0]
    assert lat.name == "sched.job.run"
    assert lat.target == pytest.approx(0.99)
    assert lat.bound_s == pytest.approx(30.0)
    assert lat.allowed == pytest.approx(0.01)
    assert objs[2].bound_s == pytest.approx(0.5)  # ms suffix
    assert objs[3].target == pytest.approx(1000.0)


def test_parse_duration_suffixes():
    objs = slo.parse_slo_spec("t:p90(x.y)<2m;t:p90(x.y)<2;t:p90(x.y)<2s")
    assert [o.bound_s for o in objs] == [120.0, 2.0, 2.0]


def test_parse_malformed_clauses_warn_and_skip(caplog):
    with caplog.at_level("WARNING"):
        objs = slo.parse_slo_spec(
            "good:avail>=0.99;"
            "nocolon;"            # missing tenant separator
            "t:p200(x)<1s;"       # quantile out of range
            "t:avail>=1.5;"       # fraction out of range
            "t:garbage(x)")
    assert len(objs) == 1 and objs[0].tenant == "good"
    assert any("ignoring" in r.message for r in caplog.records)


def test_parse_empty_spec_is_empty():
    assert slo.parse_slo_spec("") == []
    assert slo.parse_slo_spec(";;") == []


def test_objective_key_roundtrips_through_parse():
    objs = slo.parse_slo_spec("t:p99(sched.job.run)<30s;*:avail>=0.99")
    reparsed = slo.parse_slo_spec(";".join(o.key for o in objs))
    assert [o.key for o in reparsed] == [o.key for o in objs]


def test_objective_matches_tenant_scope():
    wide, narrow = slo.parse_slo_spec("*:avail>=0.9;t1:avail>=0.9")
    assert wide.matches("anyone") and wide.matches(None)
    assert narrow.matches("t1") and not narrow.matches("t2")


# ---------------------------------------------------------------------------
# engine evaluation
# ---------------------------------------------------------------------------
def test_engine_books_and_burns(tmp_path):
    eng = slo.SLOEngine(
        slo.parse_slo_spec("t:p99(sched.job.run)<1s"),
        str(tmp_path), window_s=60.0)
    for _ in range(3):
        eng.observe_job("t", 0.1, ok=True)
    status = eng.evaluate()
    row = status["objectives"][0]
    assert row["compliance"] == pytest.approx(1.0)
    assert row["burn_short"] == 0.0 and not row["fast_burn"]
    assert status["worst_burn"] == 0.0

    eng.observe_job("t", 5.0, ok=True)  # over the 1s bound = bad
    row = eng.evaluate()["objectives"][0]
    assert row["bad_total"] == 1 and row["good_total"] == 3
    # 1 bad / 4 events = 25% bad over a 1% budget -> 25x burn
    assert row["burn_short"] == pytest.approx(25.0)
    assert row["fast_burn"]  # both windows hold the same events here


def test_engine_ignores_other_tenants_and_spans(tmp_path):
    eng = slo.SLOEngine(
        slo.parse_slo_spec("t1:p99(sched.job.run)<1s"), str(tmp_path))
    eng.observe_job("t2", 99.0, ok=False)        # other tenant
    eng.observe_job("t1", 99.0, span="other.span")  # other span
    row = eng.evaluate()["objectives"][0]
    assert row["good_total"] == 0 and row["bad_total"] == 0


def test_avail_objective_judges_ok_flag(tmp_path):
    eng = slo.SLOEngine(
        slo.parse_slo_spec("*:avail>=0.99"), str(tmp_path))
    eng.observe_job("t", 0.1, ok=True)
    eng.observe_job("t", 0.1, ok=False)  # quarantined
    row = eng.evaluate()["objectives"][0]
    assert row["good_total"] == 1 and row["bad_total"] == 1
    assert row["burn_short"] == pytest.approx(50.0)


def test_budget_persists_and_resumes(tmp_path):
    spec = "t:avail>=0.99"
    eng = slo.SLOEngine(slo.parse_slo_spec(spec), str(tmp_path))
    eng.observe_job("t", 0.1, ok=True)
    eng.observe_job("t", 0.1, ok=False)
    path = os.path.join(str(tmp_path), slo.BUDGET_FILENAME)
    doc = json.load(open(path))
    assert doc["schema"] == slo.BUDGET_SCHEMA
    key = "t:avail>=0.99"
    assert doc["objectives"][key] == pytest.approx(
        {"tenant": "t", "kind": "avail", "target": 0.99,
         "allowed": 0.01, "good": 1, "bad": 1}, abs=1e-9)

    # a restart resumes the cumulative budget (not the rolling window)
    eng2 = slo.SLOEngine(slo.parse_slo_spec(spec), str(tmp_path))
    row = eng2.evaluate()["objectives"][0]
    assert row["good_total"] == 1 and row["bad_total"] == 1
    assert row["budget_remaining"] == 0.0  # 50% bad over a 1% budget
    assert row["burn_short"] == 0.0  # but the live window starts empty


def test_corrupt_budget_file_starts_fresh(tmp_path, caplog):
    (tmp_path / slo.BUDGET_FILENAME).write_text("{not json")
    with caplog.at_level("WARNING"):
        eng = slo.SLOEngine(
            slo.parse_slo_spec("t:avail>=0.9"), str(tmp_path))
    row = eng.evaluate()["objectives"][0]
    assert row["good_total"] == 0 and row["bad_total"] == 0


def test_fast_burn_fires_slo_burn_incident(tmp_path):
    incidents.install(str(tmp_path))
    eng = slo.SLOEngine(
        slo.parse_slo_spec("t:p99(sched.job.run)<0.01s"),
        str(tmp_path), window_s=60.0)
    slo.install(eng)
    for _ in range(3):
        slo.observe_job("t", 5.0, ok=True, trace_id=TID)  # all miss
    found = incidents.list_bundles(str(tmp_path))
    assert any(b["trigger"] == "slo.burn" for b in found)
    burn = [b for b in found if b["trigger"] == "slo.burn"][0]
    assert burn["trace_id"] == TID
    assert "burning error budget" in burn["reason"]


def test_note_bad_event_charges_budget(tmp_path):
    eng = slo.SLOEngine(
        slo.parse_slo_spec("t:avail>=0.99;*:tput(reads.ingested)>=1"),
        str(tmp_path))
    eng.note_bad_event(2, reason="perf regression")
    rows = {r["kind"]: r for r in eng.evaluate()["objectives"]}
    assert rows["avail"]["bad_total"] == 2
    # the charge itself never touches tput (sampled, not event-driven):
    # its only bookings come from its own rate samples
    assert rows["tput"]["good_total"] + rows["tput"]["bad_total"] <= 1


def test_tput_floor_flags_stalled_counter(tmp_path):
    eng = slo.SLOEngine(
        slo.parse_slo_spec("*:tput(reads.ingested)>=1000"), str(tmp_path))
    eng.evaluate()  # first sample establishes the baseline, books nothing
    row = eng.evaluate()["objectives"][0]  # counter never advanced
    assert row["bad_total"] >= 1
    assert row.get("rate") == pytest.approx(0.0)


def test_gauges_published_on_evaluation(tmp_path):
    was = tele.TRACE.recording
    tele.TRACE.recording = True
    try:
        eng = slo.SLOEngine(
            slo.parse_slo_spec("t:avail>=0.99"), str(tmp_path),
            window_s=60.0)
        slo.install(eng)
        slo.observe_job("t", 0.1, ok=False)
        gauges = tele.TRACE.snapshot()["gauges"]
        assert gauges[tele.G_SLO_WORST_BURN]["last"] == \
            pytest.approx(100.0)
        assert gauges[tele.G_SLO_BUDGET_REMAINING]["last"] == 0.0
    finally:
        tele.TRACE.recording = was
        tele.TRACE.reset()


# ---------------------------------------------------------------------------
# module arm/disarm seam
# ---------------------------------------------------------------------------
def test_disarmed_module_functions_noop(tmp_path):
    assert not slo.installed()
    slo.observe_job("t", 1.0)  # must not raise
    slo.note_perf_regression(1, reason="x")
    assert slo.status() is None
    assert slo.worst_burn() is None


def test_install_empty_spec_stays_disarmed(caplog):
    with caplog.at_level("WARNING"):
        assert slo.install("nonsense-spec") is None
    assert not slo.installed()
    assert slo.install("") is None  # silent: no spec at all
    assert slo.install(None) is None


def test_install_from_spec_string_and_env(tmp_path, monkeypatch):
    eng = slo.install("t:avail>=0.9", str(tmp_path))
    assert eng is not None and slo.installed()
    assert slo.engine() is eng
    slo.uninstall()
    monkeypatch.setenv("ADAM_TPU_SLO", "t:avail>=0.9")
    assert slo.slo_from_env() == "t:avail>=0.9"
    monkeypatch.delenv("ADAM_TPU_SLO")
    assert slo.slo_from_env() is None


def test_status_document_shape(tmp_path):
    slo.install("t:p99(sched.job.run)<30s", str(tmp_path))
    slo.observe_job("t", 1.0, ok=True)
    doc = slo.status()
    assert doc["schema"] == slo.SLO_SCHEMA
    assert doc["long_window_s"] == pytest.approx(
        doc["window_s"] * slo.LONG_WINDOW_FACTOR)
    row = doc["objectives"][0]
    for field in ("key", "tenant", "kind", "compliance", "burn_short",
                  "burn_long", "budget_remaining", "fast_burn",
                  "bound_s"):
        assert field in row


def test_window_knob_validation(monkeypatch, caplog):
    monkeypatch.setenv("ADAM_TPU_SLO_WINDOW_S", "-5")
    with caplog.at_level("WARNING"):
        assert slo.slo_window_s() == slo.DEFAULT_WINDOW_S
    monkeypatch.setenv("ADAM_TPU_SLO_FAST_BURN", "bogus")
    assert slo.fast_burn_threshold() == slo.DEFAULT_FAST_BURN
