"""Mesh execution partitioner (--partitioner mesh): SPMD parity,
on-device psum merge, degrade-to-pool fault matrix, the device resolve
lexsort, long-tail re-prewarm, and sweep fan-out pacing.

The 8 virtual CPU devices (tests/conftest.py) stand in for a multi-chip
topology: the streamed flagship under ``--partitioner mesh`` must be
**bit-identical** to the pool path and the host backends on 1, 2 and 8
devices — the mesh only changes WHERE work runs (sharded collectives
instead of per-window round-robin) and WHAT crosses the link at
barrier 2 (one psum-merged table instead of per-window copies), never
what is computed.  PR 4's eviction/replay matrix is the degrade
contract: a mesh failure mid-run must fall back to the pool path with
byte-identical output.
"""

import hashlib
import os
import sys

import numpy as np
import pytest

from adam_tpu.parallel import device_pool as dp
from adam_tpu.parallel import partitioner as part_mod
from adam_tpu.utils import telemetry as tele

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "tools")
)


def _sha_parts(d):
    return {
        f: hashlib.sha256(
            open(os.path.join(d, f), "rb").read()
        ).hexdigest()
        for f in os.listdir(d) if f.startswith("part-")
    }


# ---------------------------------------------------------------------------
# Execution-mode resolution
# ---------------------------------------------------------------------------
def test_resolve_execution_mode(monkeypatch):
    monkeypatch.delenv("ADAM_TPU_PARTITIONER", raising=False)
    assert part_mod.resolve_execution_mode() == "pool"
    assert part_mod.resolve_execution_mode("mesh") == "mesh"
    monkeypatch.setenv("ADAM_TPU_PARTITIONER", "mesh")
    assert part_mod.resolve_execution_mode() == "mesh"
    # explicit arg beats env; malformed env degrades (warn + pool),
    # malformed arg is a hard error (the CLI flag contract)
    assert part_mod.resolve_execution_mode("pool") == "pool"
    monkeypatch.setenv("ADAM_TPU_PARTITIONER", "bogus")
    assert part_mod.resolve_execution_mode() == "pool"
    with pytest.raises(ValueError, match="partitioner"):
        part_mod.resolve_execution_mode("bogus")


# ---------------------------------------------------------------------------
# psum-merge associativity: on-device accumulation == window-order merge
# ---------------------------------------------------------------------------
def test_mesh_accumulator_matches_window_order_merge():
    """The mesh accumulates (total, mism) in dispatch order on device;
    the pool merges host-side in window order with centered gl padding.
    Integer adds are exact, so ANY accumulation grouping must equal the
    window-order merge bitwise — including mixed grid widths."""
    import jax

    from adam_tpu.pipelines.bqsr import merge_observations

    rng = np.random.default_rng(7)
    n_rg = 3
    parts = []
    for gl in (32, 64, 32, 64, 32):
        shape = (n_rg, 94, 2 * gl + 1, 17)
        parts.append((
            rng.integers(0, 1 << 40, shape).astype(np.int64),
            rng.integers(0, 1 << 40, shape).astype(np.int64),
            gl,
        ))
    ref_t, ref_m, ref_gl = merge_observations([p for p in parts])

    part = part_mod.MeshPartitioner(jax.devices()[:2])
    order = [4, 1, 3, 0, 2]  # arbitrary accumulation order
    for k in order:
        t, m, gl = parts[k]
        part.accumulate(jax.numpy.asarray(t), jax.numpy.asarray(m), gl)
    fetched = part.fetch_accumulated(tele.Tracer(recording=False))
    got_t, got_m, got_gl = merge_observations(
        [(np.asarray(t), np.asarray(m), g) for t, m, g in fetched]
    )
    assert got_gl == ref_gl
    np.testing.assert_array_equal(got_t, ref_t)
    np.testing.assert_array_equal(got_m, ref_m)
    assert not part.has_accumulated()  # fetch clears


# ---------------------------------------------------------------------------
# Device lexsort: bitwise np.lexsort, ties included
# ---------------------------------------------------------------------------
def test_device_lexsort_bit_parity():
    from adam_tpu.parallel.dist import device_lexsort

    rng = np.random.default_rng(11)
    for n in (1, 3, 97, 4096, 5000):
        # heavy ties (small ranges) exercise the stability contract
        ks = tuple(
            rng.integers(-4, 4, n).astype(np.int64) for _ in range(5)
        )
        np.testing.assert_array_equal(device_lexsort(ks), np.lexsort(ks))
        # full-range keys (the unmapped-hash words)
        lo, hi = np.iinfo(np.int64).min // 2, np.iinfo(np.int64).max // 2
        ks2 = tuple(rng.integers(lo, hi, n) for _ in range(3))
        np.testing.assert_array_equal(
            device_lexsort(ks2), np.lexsort(ks2)
        )


def test_resolve_duplicates_device_sort_parity():
    """resolve_duplicates with the device sort of the packed summary
    keys marks exactly the rows the host lexsort marks."""
    from adam_tpu.formats import schema
    from adam_tpu.pipelines.markdup import resolve_duplicates

    rng = np.random.default_rng(5)
    n = 2000
    flags = np.where(
        rng.random(n) < 0.1, schema.FLAG_UNMAPPED, 0
    ).astype(np.int32)
    names = np.array(
        [f"r{rng.integers(0, 700)}".encode() for _ in range(n)], "S12"
    )
    s = dict(
        flags=flags,
        valid=rng.random(n) < 0.98,
        score=rng.integers(0, 3000, n).astype(np.int32),
        row_key=np.stack([
            np.where((flags & schema.FLAG_UNMAPPED) == 0, 1, 2),
            rng.integers(0, 3, n),
            rng.integers(0, 1000, n),
            rng.integers(0, 2, n),
        ], axis=1).astype(np.int64),
        rg_idx=rng.integers(-1, 2, n).astype(np.int64),
        lib_per_row=rng.integers(-1, 2, n).astype(np.int64),
        name_bytes=names,
    )
    host = resolve_duplicates(s)
    dev = resolve_duplicates(s, sort_device="default")
    np.testing.assert_array_equal(host, dev)
    assert host.any()  # a real workload, not a vacuous equality


# ---------------------------------------------------------------------------
# Streamed parity: mesh vs pool vs host on 1/2/8 virtual devices
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def mesh_runs(tmp_path_factory):
    """One streamed run per (mode, device count) over the same input
    (ragged last window + realign tail, so the long-tail prewarm paths
    execute), each with its telemetry snapshot captured."""
    from make_wgs_sam import make_wgs

    from adam_tpu.pipelines.streamed import transform_streamed

    d = tmp_path_factory.mktemp("mesh_parity")
    path = str(d / "in.sam")
    # 4500 reads / window 2048 -> grids 2048, 2048, 1024: the residual
    # window exercises the re-prewarm; indels produce a realign tail
    make_wgs(path, 4500, 100, n_contigs=2, contig_len=30_000,
             indel_every=700, snp_every=400)
    runs = {}
    legs = [
        ("host", None, None),
        ("pool2", "pool", 2),
        ("mesh1", "mesh", 1),
        ("mesh2", "mesh", 2),
        ("mesh8", "mesh", 8),
    ]
    for label, mode, n in legs:
        out = str(d / f"out.{label}.adam")
        csv = str(d / f"obs.{label}.csv")
        if mode is not None:
            os.environ["ADAM_TPU_BQSR_BACKEND"] = "device"
        tele.TRACE.reset()
        tele.TRACE.recording = True
        try:
            stats = transform_streamed(
                path, out, window_reads=2048, devices=n,
                partitioner=mode, dump_observations=csv,
            )
            snap = tele.TRACE.snapshot()
        finally:
            tele.TRACE.recording = False
            os.environ.pop("ADAM_TPU_BQSR_BACKEND", None)
        runs[label] = (out, csv, stats, snap)
    return runs


def test_mesh_parts_bit_identical_across_modes(mesh_runs):
    ref = _sha_parts(mesh_runs["host"][0])
    assert ref
    for label in ("pool2", "mesh1", "mesh2", "mesh8"):
        assert _sha_parts(mesh_runs[label][0]) == ref, label


def test_mesh_observe_table_identical(mesh_runs):
    """The merged observation table (the recalibration source of
    truth): the on-device psum + accumulator path cannot drift from
    the host window-order merge."""
    ref = open(mesh_runs["host"][1]).read()
    assert len(ref.splitlines()) > 1
    for label in ("pool2", "mesh1", "mesh2", "mesh8"):
        assert open(mesh_runs[label][1]).read() == ref, label


def test_mesh_actually_ran_collectives(mesh_runs):
    for label in ("mesh1", "mesh2", "mesh8"):
        _out, _csv, stats, snap = mesh_runs[label]
        assert stats["partitioner"] == "mesh", label
        assert snap["counters"].get(tele.C_MESH_DISPATCHED, 0) > 0, label
        assert snap["counters"].get(tele.C_MESH_DEGRADED, 0) == 0, label
    assert mesh_runs["pool2"][3]["counters"].get(
        tele.C_MESH_DISPATCHED, 0
    ) == 0


def test_mesh_barrier2_fetches_one_table_not_per_window(mesh_runs):
    """THE tentpole claim, measured off the device ledger: the mesh
    leg's observe-pass d2h bytes must undercut the pool leg's by at
    least the window count's worth of per-window tables."""
    def observe_d2h(snap):
        total = 0
        for _dev, per in (snap.get("transfers", {}).get("d2h") or {}).items():
            e = per.get("observe")
            if e:
                total += e["bytes"]
        return total

    pool_b = observe_d2h(mesh_runs["pool2"][3])
    mesh_b = observe_d2h(mesh_runs["mesh2"][3])
    assert pool_b > 0 and mesh_b > 0
    # 3 windows + realigned tail fetch per-window on the pool leg; the
    # mesh fetches one merged pair per distinct grid width (2 here)
    assert mesh_b * 2 <= pool_b, (pool_b, mesh_b)


def test_clean_run_has_no_in_window_compiles(mesh_runs):
    """Long-tail re-prewarm: the residual-window grid and the
    realigned-tail observe must compile under a prewarm scope, leaving
    the `device.compile.in_window` warning list empty."""
    for label in ("pool2", "mesh2", "mesh8"):
        snap = mesh_runs[label][3]
        in_win = [
            e for e in snap.get("compiles", {}).get("entries", [])
            if e.get("in_window")
        ]
        assert snap["counters"].get(tele.C_COMPILE_IN_WINDOW, 0) == 0, (
            label, in_win,
        )


def test_mesh_resolve_used_device_sort(mesh_runs):
    snap = mesh_runs["mesh2"][3]
    g = snap["gauges"].get(tele.G_RESOLVE_DEVICE_SORT)
    assert g and g["last"] == 1
    # and the host leg kept the host sort
    g_host = mesh_runs["host"][3]["gauges"].get(tele.G_RESOLVE_DEVICE_SORT)
    assert g_host is None or g_host["last"] == 0


def test_analyzer_reports_mesh_mode(mesh_runs):
    from adam_tpu.utils import analyzer

    snap = mesh_runs["mesh2"][3]
    report = analyzer.analyze(snap)
    assert report["partitioner"] == "mesh"
    assert report["stages"]["barrier1_resolve"]["sort"] == "device"
    text = analyzer.render_report(report)
    assert "partitioner mesh" in text and "[device sort]" in text
    report_pool = analyzer.analyze(mesh_runs["pool2"][3])
    assert report_pool["partitioner"] == "pool"


# ---------------------------------------------------------------------------
# Fault matrix under --partitioner mesh (the PR 4 contract)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("spec,expect_degrade", [
    # transient faults: absorbed by the retry wrappers, mesh stays up
    ("device.dispatch=transient,every=3", False),
    # permanent faults mid-run: the mesh degrades to the pool, the pool
    # evicts through to the host backend — output identical throughout
    ("device.dispatch=permanent,after=6", True),
])
def test_mesh_fault_matrix_degrades_bit_identically(
    mesh_runs, tmp_path, spec, expect_degrade, monkeypatch
):
    from adam_tpu.pipelines.streamed import transform_streamed
    from adam_tpu.utils import faults

    ref = _sha_parts(mesh_runs["host"][0])
    src = mesh_runs["host"][0].replace("out.host.adam", "in.sam")
    out = str(tmp_path / "faulted.adam")
    monkeypatch.setenv("ADAM_TPU_BQSR_BACKEND", "device")
    monkeypatch.setenv("ADAM_TPU_RETRY_BACKOFF_S", "0.001")
    faults.install(spec)
    tele.TRACE.reset()
    tele.TRACE.recording = True
    try:
        stats = transform_streamed(
            src, out, window_reads=2048, devices=2, partitioner="mesh"
        )
        snap = tele.TRACE.snapshot()
    finally:
        tele.TRACE.recording = False
        faults.clear()
    assert _sha_parts(out) == ref
    assert snap["counters"].get(tele.C_FAULT_INJECTED, 0) > 0
    degraded = snap["counters"].get(tele.C_MESH_DEGRADED, 0)
    if expect_degrade:
        assert degraded == 1 and stats["partitioner"] == "pool"
    else:
        assert degraded == 0 and stats["partitioner"] == "mesh"


# ---------------------------------------------------------------------------
# Sweep fan-out pacing
# ---------------------------------------------------------------------------
def test_sweep_schedule_deficit_round_robin():
    devs = ["a", "b"]
    # 3:1 weights -> 3 of every 4 chunks land on the fast device
    sched = dp.SweepSchedule(devs, weights=[3.0, 1.0])
    got = [sched.next_device() for _ in range(8)]
    assert got.count("a") == 6 and got.count("b") == 2
    # equal weights degrade to plain round-robin
    sched = dp.SweepSchedule(devs, weights=[1.0, 1.0])
    got = [sched.next_device() for _ in range(4)]
    assert got == ["a", "b", "a", "b"]


def test_sweep_weights_env_override(monkeypatch):
    import jax

    devs = jax.devices()[:3]
    monkeypatch.setenv("ADAM_TPU_SWEEP_TFLOPS", "2.0,1.0")
    w = dp.sweep_weights(devs)
    assert w[0] == 2.0 and w[1] == 1.0 and w[2] == 1.5  # padded w/ mean
    monkeypatch.setenv("ADAM_TPU_SWEEP_TFLOPS", "bogus")
    assert dp.sweep_weights(devs) == [1.0] * 3
    monkeypatch.delenv("ADAM_TPU_SWEEP_TFLOPS")
    # virtual CPU devices are symmetric: no probe, equal weights
    assert dp.sweep_weights(devs) == [1.0] * 3


def test_realign_sweep_fans_out_bit_identically():
    """realign_indels with sweep_devices fanned over 4 virtual chips
    returns exactly the single-device result (placement never changes
    the sweep values)."""
    import jax

    from make_wgs_sam import make_wgs

    from adam_tpu.io import context
    from adam_tpu.pipelines.realign import realign_indels

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "in.sam")
        make_wgs(path, 1500, 100, n_contigs=1, contig_len=20_000,
                 indel_every=600, snp_every=300)
        ds = context.load_alignments(path)
        one = realign_indels(ds)
        fan = realign_indels(ds, sweep_devices=list(jax.devices()[:4]))
    b1, b2 = one.batch.to_numpy(), fan.batch.to_numpy()
    for f in ("start", "end", "mapq", "cigar_ops", "cigar_lens",
              "cigar_n", "flags"):
        np.testing.assert_array_equal(
            np.asarray(getattr(b1, f)), np.asarray(getattr(b2, f)), f
        )
    assert list(one.sidecar.md) == list(fan.sidecar.md)


# ---------------------------------------------------------------------------
# Heartbeat surfaces the mode
# ---------------------------------------------------------------------------
def test_heartbeat_carries_partitioner_field(tmp_path, monkeypatch):
    from make_wgs_sam import make_wgs

    import json

    from adam_tpu.pipelines.streamed import transform_streamed

    path = str(tmp_path / "in.sam")
    make_wgs(path, 1200, 100, n_contigs=1, contig_len=20_000)
    hb_path = str(tmp_path / "hb.ndjson")
    monkeypatch.setenv("ADAM_TPU_BQSR_BACKEND", "device")
    monkeypatch.setenv("ADAM_TPU_PROGRESS_INTERVAL_S", "0.1")
    transform_streamed(
        path, str(tmp_path / "out.adam"), window_reads=1024, devices=2,
        partitioner="mesh", progress=hb_path,
    )
    lines = [json.loads(l) for l in open(hb_path)]
    assert lines
    for l in lines:
        assert tuple(l.keys()) == tele.HEARTBEAT_FIELDS
        # the immediate first beat fires before the pipeline resolves
        # its mode (provider not yet registered): None there, the live
        # mode on every later line
        assert l["partitioner"] in (None, "mesh")
    assert lines[-1]["partitioner"] == "mesh"
    assert lines[-1]["done"] is True and lines[-1]["ok"] is True
    # adam-tpu top renders the mode
    from adam_tpu.utils.top import render_frame

    frame = render_frame(lines[-1])
    assert "mode mesh" in frame
